"""XF4xx config cross-check: every `cfg.<section>.<key>` read resolves
to a config.py default, and every default is read somewhere.

The config tree is the repo's only schema (one dataclass tree,
docs/README): a misspelled key in code raises `AttributeError` only
when that code path finally runs, and a default nobody reads is dead
weight that reads as a tunable. Both are mechanical to check:

- XF401 unknown-config-key: an attribute chain rooted at a Config
  value (a typo like `cfg.train.lag_every`), or a dotted `--set`
  override string in Python or a smoke script, that does not resolve
  in the config.py tree. (This docstring spells the example WITHOUT
  the `=value` suffix so the pass's own string scanner stays quiet.)
- XF402 dead-config-key: a leaf default no Python module, test, or
  shell script references (attribute read or dotted string). Only
  reported on full-tree runs — a partial lint would report everything
  dead.

Resolution is type-light but annotation-aware: parameters/attributes
annotated with a section class (`cfg: Config`, `serve: ServeConfig`)
resolve into that subtree; `x = cfg.serve`-style aliases follow; names
literally called `cfg`/`config` (and `self.cfg`/`self._cfg`/
`self.config`) are assumed to be the root Config. Dotted strings only
count in config-shaped contexts — `override()`/`from_overrides()` dict
keys and `section.key=value` assignment strings — so registry counter
names like `data.rows` never false-positive.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Optional

from xflow_tpu.analysis import astutil
from xflow_tpu.analysis.core import Finding, Module, Project, register_pass

RULES = ("XF401", "XF402")

CFG_ROOT_NAMES = {"cfg", "config", "base_cfg", "base"}
CFG_ROOT_ATTRS = {"self.cfg", "self._cfg", "self.config", "self._config"}
OVERRIDE_CALLS = {"override", "from_overrides", "config.override",
                  "config.from_overrides"}

# `section.key=value` tokens (Python strings and shell text)
ASSIGN_RE = re.compile(
    r"(?<![\w./-])([a-z_]+)\.([a-z0-9_]+(?:\.[a-z0-9_]+)*)="
)


class ConfigTree:
    """The schema parsed from config.py's dataclass AST — never
    imported/executed, so linting works without the package's deps."""

    def __init__(self, sections: dict, root_extra: set, class_to_path: dict):
        self.sections = sections  # nested dicts; leaves -> lineno
        self.root_extra = root_extra  # Config-level properties/methods
        self.class_to_path = class_to_path  # "ServeConfig" -> ("serve",)

    @classmethod
    def parse(cls, config_path: str) -> Optional["ConfigTree"]:
        if not os.path.exists(config_path):
            return None
        with open(config_path) as f:
            try:
                tree = ast.parse(f.read(), filename=config_path)
            except SyntaxError:
                return None
        classes: dict = {}
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                classes[node.name] = node
        if "Config" not in classes:
            return None

        def fields_of(cnode: ast.ClassDef) -> dict:
            out = {}
            for item in cnode.body:
                if isinstance(item, ast.AnnAssign) and isinstance(
                        item.target, ast.Name):
                    ann = item.annotation
                    ann_name = astutil.dotted(ann) or astutil.const_str(ann)
                    out[item.target.id] = (ann_name, item.lineno)
            return out

        def extras_of(cnode: ast.ClassDef) -> set:
            return {item.name for item in cnode.body
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}

        class_to_path: dict = {"Config": ()}

        def build(cnode: ast.ClassDef, path: tuple) -> dict:
            sub = {}
            for name, (ann, lineno) in fields_of(cnode).items():
                if ann in classes:
                    class_to_path.setdefault(ann, path + (name,))
                    sub[name] = build(classes[ann], path + (name,))
                else:
                    sub[name] = lineno
            return sub

        sections = build(classes["Config"], ())
        return cls(sections, extras_of(classes["Config"]), class_to_path)

    def resolve(self, chain: tuple) -> tuple:
        """Walk `chain` from the root. -> (status, depth) where status
        is 'ok' (resolves to leaf/section, possibly with trailing
        non-config attrs past a leaf), or 'bad' at chain[depth]."""
        node = self.sections
        for i, part in enumerate(chain):
            if isinstance(node, dict):
                if part in node:
                    node = node[part]
                    continue
                if i == 0 and part in self.root_extra:
                    return ("ok", i)
                return ("bad", i)
            # past a leaf: `.split(...)`-style trailing attrs are fine
            return ("ok", i)
        return ("ok", len(chain))

    def resolve_from(self, base: tuple, chain: tuple) -> tuple:
        return self.resolve(tuple(base) + tuple(chain))

    def leaves(self) -> list:
        out = []

        def walk(node: dict, path: tuple) -> None:
            for name, child in sorted(node.items()):
                if isinstance(child, dict):
                    walk(child, path + (name,))
                else:
                    out.append((path + (name,), child))

        walk(self.sections, ())
        return out

    def mark_used(self, used: set, chain: tuple) -> None:
        """Record the leaf a resolved chain touches (prefix-resolved)."""
        node = self.sections
        path: tuple = ()
        for part in chain:
            if isinstance(node, dict) and part in node:
                node = node[part]
                path = path + (part,)
            else:
                break
        if not isinstance(node, dict) and path:
            used.add(path)


def _usage_modules(project: Project) -> list:
    """Modules to scan for key USAGE: the lint set plus tests/ (a key
    only tests read is not dead)."""
    mods = list(project.modules)
    have = {m.path for m in mods}
    tests_dir = os.path.join(project.root, "tests")
    if project.full_tree and os.path.isdir(tests_dir):
        for dirpath, dirnames, filenames in os.walk(tests_dir):
            # fixtures are deliberate nonsense — a valid key read there
            # must not keep a dead default alive
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "fixtures")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    fp = os.path.join(dirpath, fn)
                    if fp not in have:
                        rel = os.path.relpath(fp, project.root)
                        with open(fp, encoding="utf-8",
                                  errors="replace") as f:
                            mods.append(Module(fp, rel, f.read()))
    return mods


def _attr_chains(mod: Module, tree: ConfigTree):
    """Yields (chain-after-root, base-path, lineno) for reads rooted at
    a recognized Config value."""
    if mod.tree is None:
        return

    def class_path(ann_name: Optional[str]) -> Optional[tuple]:
        if not ann_name:
            return None
        m = re.search(r"\b([A-Z]\w*Config|Config)\b", ann_name)
        if m and m.group(1) in tree.class_to_path:
            return tree.class_to_path[m.group(1)]
        return None

    # phase 1 — annotations: param/attr annotated with a section class
    ann_roots: dict = {}    # bare name -> base path
    alias_roots: dict = {}  # name or self-attr chain -> base path
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs
                      + ([args.vararg] if args.vararg else [])
                      + ([args.kwarg] if args.kwarg else [])):
                ann = a.annotation
                ann_name = (astutil.dotted(ann) or astutil.const_str(ann)
                            if ann is not None else None)
                base = class_path(ann_name)
                if base is not None:
                    ann_roots[a.arg] = base
        elif isinstance(node, ast.AnnAssign) and node.annotation is not None:
            ann_name = (astutil.dotted(node.annotation)
                        or astutil.const_str(node.annotation))
            tgt = astutil.dotted(node.target)
            base = class_path(ann_name)
            if base is not None and tgt:
                alias_roots[tgt] = base

    def _section_path(src: str) -> Optional[tuple]:
        """Base path a source expression denotes, if it is a SECTION
        (not a leaf): `cfg.serve`, an annotated name, an alias."""
        parts = src.split(".")
        for cut in range(len(parts), 0, -1):
            prefix = ".".join(parts[:cut])
            rest = parts[cut:]
            base = alias_roots.get(prefix)
            if base is None and cut == 1:
                base = ann_roots.get(parts[0])
            if base is None:
                root, rest2 = _split_root(parts)
                if root is None or cut != len(parts):
                    continue
                base, rest = root, rest2
            node2 = tree.sections
            for p in tuple(base) + tuple(rest):
                if isinstance(node2, dict) and p in node2:
                    node2 = node2[p]
                else:
                    return None
            return tuple(base) + tuple(rest) if isinstance(node2, dict) \
                else None
        return None

    # phase 2 — aliases: x = cfg.serve / self._scfg = serve_cfg, incl.
    # tuple unpacking; two sweeps so chained aliases settle
    for _sweep in range(2):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            pairs = []
            if len(node.targets) == 1 and isinstance(
                    node.targets[0], (ast.Tuple, ast.List)) and isinstance(
                    node.value, (ast.Tuple, ast.List)):
                pairs = list(zip(node.targets[0].elts, node.value.elts))
            elif len(node.targets) == 1:
                pairs = [(node.targets[0], node.value)]
            for tgt_node, val_node in pairs:
                src = astutil.dotted(val_node)
                tgt = astutil.dotted(tgt_node)
                if not src or not tgt:
                    continue
                base = _section_path(src)
                if base is not None:
                    alias_roots[tgt] = base

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Attribute) or not isinstance(
                node.ctx, ast.Load):
            continue
        chain = astutil.dotted(node)
        if chain is None:
            continue
        parts = chain.split(".")
        # longest-prefix alias/annotation match
        base = None
        rest: list = []
        for cut in range(len(parts), 0, -1):
            prefix = ".".join(parts[:cut])
            if prefix in alias_roots:
                base, rest = alias_roots[prefix], parts[cut:]
                break
            if cut == 1 and parts[0] in ann_roots:
                base, rest = ann_roots[parts[0]], parts[1:]
                break
        if base is None:
            root, rest2 = _split_root(parts)
            if root is None:
                continue
            base, rest = root, rest2
        if not rest:
            continue
        # only report on the FULL chain (avoid double hits on inner
        # Attribute nodes of one chain): yield only maximal chains
        yield tuple(rest), tuple(base), node.lineno, node


def _split_root(parts: list) -> tuple:
    if parts[0] in CFG_ROOT_NAMES:
        return (), parts[1:]
    if len(parts) >= 2 and ".".join(parts[:2]) in CFG_ROOT_ATTRS:
        return (), parts[2:]
    return None, []


@register_pass("config-cross-check", RULES, scope="project")
def run(project: Project) -> list:
    tree = ConfigTree.parse(project.config_path)
    if tree is None:
        return []
    findings: list = []
    used: set = set()
    scan = _usage_modules(project)
    lintable = {m.relpath for m in project.modules}
    for mod in scan:
        if mod.tree is None:
            continue
        chains = list(_attr_chains(mod, tree))
        # drop chains that are sub-chains of a longer reported chain
        inner: set = set()
        for _rest, _base, _ln, node in chains:
            sub = node.value
            while isinstance(sub, ast.Attribute):
                inner.add(id(sub))
                sub = sub.value
        for rest, base, lineno, node in chains:
            if id(node) in inner:
                continue
            status, depth = tree.resolve_from(base, rest)
            full = tuple(base) + tuple(rest)
            if status == "ok":
                tree.mark_used(used, full)
            elif mod.relpath in lintable:
                bad = ".".join(full[: depth + 1])
                findings.append(Finding(
                    rule="XF401", path=mod.relpath, line=lineno,
                    message=f"config read `{'.'.join(('cfg',) + full)}` "
                            f"does not resolve: `{bad}` is not in the "
                            "config.py tree",
                    hint="fix the key, or add the field (with a default "
                         "and a comment) to xflow_tpu/config.py",
                ))
        # dotted strings: override()/from_overrides() dict keys
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                cn = astutil.call_name(node)
                if cn in OVERRIDE_CALLS:
                    for d in ast.walk(node):
                        if isinstance(d, ast.Dict):
                            for k in d.keys:
                                s = astutil.const_str(k) if k else None
                                if s and re.fullmatch(r"[a-z_][\w.]*", s):
                                    _check_dotted(findings, tree, used, mod,
                                                  k.lineno, s,
                                                  report=mod.relpath
                                                  in lintable)
            s = astutil.const_str(node) if isinstance(node, ast.Constant) \
                else None
            if s:
                for m in ASSIGN_RE.finditer(s):
                    dotted = f"{m.group(1)}.{m.group(2)}"
                    if m.group(1) in tree.sections:
                        _check_dotted(findings, tree, used, mod,
                                      node.lineno, dotted,
                                      report=mod.relpath in lintable)
    # shell scripts: --set section.key=value tokens (comment lines are
    # prose — a note about a renamed key must not fail the gate)
    for script in project.shell_scripts:
        for i, line in enumerate(script.lines, 1):
            if line.lstrip().startswith("#"):
                continue
            for m in ASSIGN_RE.finditer(line):
                if m.group(1) not in tree.sections:
                    continue
                dotted = f"{m.group(1)}.{m.group(2)}"
                chain = tuple(dotted.split("."))
                status, depth = tree.resolve(chain)
                if status == "ok":
                    tree.mark_used(used, chain)
                else:
                    bad = ".".join(chain[: depth + 1])
                    findings.append(Finding(
                        rule="XF401", path=script.relpath, line=i,
                        message=f"config override `{dotted}=` does not "
                                f"resolve: `{bad}` is not in the config.py "
                                "tree",
                        hint="fix the key, or add the field to "
                             "xflow_tpu/config.py",
                    ))
    # dead keys: full-tree runs only
    if project.full_tree:
        config_rel = os.path.relpath(project.config_path, project.root)
        for path, lineno in tree.leaves():
            if path not in used:
                findings.append(Finding(
                    rule="XF402", path=config_rel.replace(os.sep, "/"),
                    line=lineno,
                    message=f"config default `{'.'.join(path)}` is never "
                            "read by any module, test, or smoke script "
                            "(dead key)",
                    hint="delete the field, or wire the code that should "
                         "be reading it",
                ))
    return findings


def _check_dotted(findings, tree, used, mod, lineno, dotted, report) -> None:
    chain = tuple(dotted.split("."))
    if chain[0] not in tree.sections:
        return  # not config-shaped ("data.rows" counter names etc. never
        # reach here for override() keys; assignment strings pre-filter)
    status, depth = tree.resolve(chain)
    if status == "ok":
        tree.mark_used(used, chain)
    elif report:
        bad = ".".join(chain[: depth + 1])
        findings.append(Finding(
            rule="XF401", path=mod.relpath, line=lineno,
            message=f"config override `{dotted}` does not resolve: "
                    f"`{bad}` is not in the config.py tree",
            hint="fix the key, or add the field to xflow_tpu/config.py",
        ))
