"""XF7xx sharding contracts: extract and cross-check the engine
builders' partition/donation/scope contracts.

The ROADMAP's unlock item — collapsing the four step builders into one
rule-driven engine — is blocked on exactly what no tool could see:
the builders' sharding contracts (mesh axes, PartitionSpecs, donation,
trace scopes) drift silently. PR 7 had to wire CompileRecorder into
all four separately, and XF204 exists because of that drift. This pass
makes the contracts machine-readable and machine-checked:

- **Extraction** (`extract_contracts`): per engine builder
  (`ENGINE_MODULES`), a normalized record — mesh axes referenced by
  every PartitionSpec and collective, `in_shardings`/`out_shardings`
  and `donate_argnums` per jit program (program names resolved through
  `recorder.wrap`), shard_map in/out specs, per-table-leaf sharding
  declarations, and `jax.named_scope` coverage — emitted as the
  byte-stable `tools/engine_contracts.json` artifact
  (`tools/xflowlint.py --write-contracts` / `--check-contracts`,
  drift = exit 4, distinct from finding growth). The contract matrix
  is the acceptance oracle the future unified builder must reproduce:
  its riskiest step becomes a diff against a checked-in artifact.

- **XF701 undeclared-mesh-axis**: a PartitionSpec referencing an axis
  name not declared by the project mesh (parallel/mesh.py
  DATA_AXIS/TABLE_AXIS) nor by a Mesh(...) constructed in the same
  module. A misspelled axis fails deep inside GSPMD partitioning at
  run time; here it fails in lint.

- **XF702 donated-buffer-read**: flow-sensitive (analysis/dataflow.py)
  — a value whose buffer was handed to a jitted call with
  `donate_argnums` is read again afterwards (including the next
  iteration of a loop that forgot to rebind). Donated buffers are
  invalidated by execution; the read works on CPU test runs and
  corrupts or crashes on TPU.

- **XF703 undonated-state**: a jit of a train step (first parameter
  `state`, the TrainState carrying tables + optimizer state) without
  `donate_argnums` including it. The state is the dominant HBM
  resident; without donation the update holds TWO copies live — the
  PR 7 memory_analysis bug class (docs/PERF.md "HBM residency").

- **XF704 cross-engine-drift**: (a) a builder missing a trace scope
  every other builder covers (the gather/loss/grad/optimizer xprof
  vocabulary, docs/OBSERVABILITY.md) — scope drift is how per-stage
  attribution silently goes blind on one engine; (b) one builder
  declaring two different shardings for the same table leaf across its
  programs (a train step and its sibling eval/opt-state declaration
  disagreeing is exactly the desync XF204's recorder catches only at
  run time).
"""

from __future__ import annotations

import ast
import os
from dataclasses import replace
from typing import Optional

from xflow_tpu.analysis import astutil, dataflow
from xflow_tpu.analysis.core import Finding, Project, register_pass
from xflow_tpu.analysis.passes.recompile import _static_spec

RULES = ("XF701", "XF702", "XF703", "XF704")

ENGINE_MODULES = (
    "xflow_tpu/train/step.py",
    "xflow_tpu/parallel/train_step.py",
    "xflow_tpu/parallel/sorted_sharded.py",
    "xflow_tpu/parallel/sorted_fullshard.py",
)
SHARED_STEP_MODULE = "xflow_tpu/train/step.py"
MESH_MODULE = "xflow_tpu/parallel/mesh.py"
ARTIFACT_REL = "tools/engine_contracts.json"

SPEC_CTORS = {"P", "PartitionSpec", "jax.sharding.PartitionSpec"}
NS_CTORS = {"NamedSharding", "jax.sharding.NamedSharding"}
JIT_CALLS = {"jax.jit", "jit", "pjit", "jax.pjit"}
MESH_CTORS = {"Mesh", "jax.sharding.Mesh", "jax.make_mesh"}
COLLECTIVES = {
    "jax.lax.psum", "lax.psum", "jax.lax.pmean", "lax.pmean",
    "jax.lax.psum_scatter", "lax.psum_scatter",
    "jax.lax.all_to_all", "lax.all_to_all",
    "jax.lax.all_gather", "lax.all_gather",
    "jax.lax.axis_index", "lax.axis_index",
}
# delegation calls that inherit the shared single-device step's scopes
SHARED_STEP_BUILDERS = {"make_train_step", "make_eval_step"}
DEFAULT_AXES = ("data", "table")
STATE_PARAM = "state"


# --------------------------------------------------------- axis declarations


def _axis_decls_from_tree(tree) -> tuple:
    """(axis names, {CONST_NAME: value}) declared by one module: string
    constants assigned at module level plus Mesh(...)/make_mesh axis
    tuples."""
    axes: set = set()
    consts: dict = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                      ast.Constant) \
                and isinstance(node.value.value, str):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    consts[tgt.id] = node.value.value
                    if tgt.id.endswith("_AXIS"):
                        axes.add(node.value.value)
    aliases = astutil.import_aliases(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        cn = astutil.canonical(astutil.call_name(node), aliases)
        if cn not in MESH_CTORS:
            continue
        cands = list(node.args) + [kw.value for kw in node.keywords
                                   if kw.arg == "axis_names"]
        for arg in cands:
            if isinstance(arg, (ast.Tuple, ast.List)):
                names = []
                for el in arg.elts:
                    s = astutil.const_str(el)
                    if s is None and isinstance(el, ast.Name):
                        s = consts.get(el.id)
                    if s is None:
                        names = []
                        break
                    names.append(s)
                axes.update(names)
    return axes, consts


def mesh_decls(project: Project) -> tuple:
    """Project-level declared axes + axis-constant map, anchored at
    parallel/mesh.py (falls back to the canonical ('data', 'table')
    mesh when linting a scratch tree without it)."""
    tree = None
    for mod in project.modules:
        if mod.relpath == MESH_MODULE and mod.tree is not None:
            tree = mod.tree
            break
    if tree is None:
        path = os.path.join(project.root, *MESH_MODULE.split("/"))
        if os.path.exists(path):
            try:
                with open(path, encoding="utf-8", errors="replace") as f:
                    tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                tree = None
    if tree is None:
        return set(DEFAULT_AXES), {"DATA_AXIS": "data",
                                   "TABLE_AXIS": "table"}
    axes, consts = _axis_decls_from_tree(tree)
    if not axes:
        axes = set(DEFAULT_AXES)
    return axes, consts


# ------------------------------------------------------------------ renderer


class _Renderer:
    """Deterministic, machine-stable rendering of sharding expressions:
    axis constants resolve to their strings, names bound to spec
    constructors resolve through the module-wide alias map, everything
    else renders structurally. No line numbers, no absolute paths —
    the artifact must be byte-stable and the messages baselinable."""

    MAX_DEPTH = 6
    MAX_LEN = 120

    def __init__(self, consts: dict, aliases: dict):
        self.consts = dict(consts)
        self.aliases = aliases
        self.alias_specs: dict = {}

    def seed_alias_specs(self, tree) -> None:
        """name -> rendered spec for every `x = P(...)` / `x =
        NamedSharding(...)` assignment anywhere in the module; a name
        bound to two different specs renders bare (ambiguous)."""
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            cn = astutil.canonical(astutil.call_name(node.value),
                                   self.aliases)
            if cn not in SPEC_CTORS | NS_CTORS:
                continue
            rendered = self.render(node.value)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    prev = self.alias_specs.get(tgt.id)
                    if prev is not None and prev != rendered:
                        self.alias_specs[tgt.id] = None  # ambiguous
                    elif prev is None and tgt.id not in self.alias_specs:
                        self.alias_specs[tgt.id] = rendered

    def render(self, node, env: Optional[dict] = None, depth: int = 0) -> str:
        r = self.render_raw(node, env, depth)
        return r if len(r) <= self.MAX_LEN else r[: self.MAX_LEN - 3] + "..."

    def render_raw(self, node, env, depth) -> str:
        if depth > self.MAX_DEPTH:
            return "..."
        if isinstance(node, ast.Constant):
            return repr(node.value)
        if isinstance(node, ast.Name):
            if env is not None:
                v = env.get(node.id)
                if v is not None and v.spec:
                    return v.spec
            alias = self.alias_specs.get(node.id)
            if alias:
                return alias
            if node.id in self.consts:
                return repr(self.consts[node.id])
            return node.id
        if isinstance(node, ast.Attribute):
            return astutil.dotted(node) or (
                self.render_raw(node.value, env, depth + 1) + "." + node.attr)
        if isinstance(node, ast.Tuple):
            inner = ", ".join(self.render_raw(e, env, depth + 1)
                              for e in node.elts)
            return f"({inner},)" if len(node.elts) == 1 else f"({inner})"
        if isinstance(node, ast.List):
            return "[" + ", ".join(self.render_raw(e, env, depth + 1)
                                   for e in node.elts) + "]"
        if isinstance(node, ast.Dict):
            parts = []
            for k, v in zip(node.keys, node.values):
                ks = self.render_raw(k, env, depth + 1) if k is not None \
                    else "**"
                parts.append(f"{ks}: {self.render_raw(v, env, depth + 1)}")
            return "{" + ", ".join(parts) + "}"
        if isinstance(node, ast.DictComp):
            return (f"{{{self.render_raw(node.key, env, depth + 1)}: "
                    f"{self.render_raw(node.value, env, depth + 1)} "
                    "for ...}")
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return f"[{self.render_raw(node.elt, env, depth + 1)} for ...]"
        if isinstance(node, ast.Starred):
            return "*" + self.render_raw(node.value, env, depth + 1)
        if isinstance(node, ast.Subscript):
            return (self.render_raw(node.value, env, depth + 1) + "["
                    + self.render_raw(node.slice, env, depth + 1) + "]")
        if isinstance(node, ast.Call):
            cn = astutil.canonical(astutil.call_name(node), self.aliases)
            if cn in NS_CTORS:
                # drop the mesh argument: the SPEC is the contract
                spec_arg = node.args[1] if len(node.args) > 1 else (
                    node.args[0] if node.args else None)
                inner = self.render_raw(spec_arg, env, depth + 1) \
                    if spec_arg is not None else ""
                return f"NamedSharding({inner})"
            if cn in SPEC_CTORS:
                parts = [self.render_raw(a, env, depth + 1)
                         for a in node.args]
                return "P(" + ", ".join(parts) + ")"
            label = astutil.call_name(node) or "<call>"
            args = [self.render_raw(a, env, depth + 1) for a in node.args]
            args += [f"{kw.arg}={self.render_raw(kw.value, env, depth + 1)}"
                     for kw in node.keywords if kw.arg]
            return f"{label}({', '.join(args)})"
        if isinstance(node, ast.IfExp):
            return (self.render_raw(node.body, env, depth + 1) + " if ... "
                    "else " + self.render_raw(node.orelse, env, depth + 1))
        try:
            s = ast.unparse(node)
        except Exception:  # pragma: no cover
            s = "<expr>"
        return s


# ------------------------------------------------------- per-module analysis


class _ContractHooks(dataflow.Hooks):
    """Dataflow hooks: jit-record capture + recorder.wrap program
    naming + donated-buffer tracking (XF702)."""

    propagate_returns = True

    def __init__(self, mod, renderer: _Renderer):
        self.mod = mod
        self.renderer = renderer
        self.jits: dict = {}  # id(jit Call) -> record
        self.jit_order: list = []
        self.findings: list = []
        self._flagged: set = set()

    def _program_name(self, node) -> Optional[str]:
        s = astutil.const_str(node)
        if s is not None:
            return s
        if isinstance(node, ast.JoinedStr):
            parts = []
            for v in node.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                elif isinstance(v, ast.FormattedValue):
                    try:
                        parts.append("{" + ast.unparse(v.value) + "}")
                    except Exception:  # pragma: no cover
                        parts.append("{}")
            return "".join(parts)
        return None

    def at_call(self, node, callee, argvals, kwvals, env, df, fval):
        rend = self.renderer
        if callee in JIT_CALLS:
            nums, names = _static_spec(node)
            donate: list = []
            for kw in node.keywords:
                if kw.arg in ("donate_argnums", "donate_argnames"):
                    v = kw.value
                    items = v.elts if isinstance(v, (ast.Tuple, ast.List)) \
                        else [v]
                    for it in items:
                        if isinstance(it, ast.Constant):
                            donate.append(it.value)
            fn_txt = rend.render(node.args[0], env) if node.args else "<fn>"
            rec = {
                "function": fn_txt,
                "fn_ref": argvals[0].ref if argvals else None,
                "donate_argnums": donate,
                "static_argnums": nums,
                "static_argnames": names,
                "in_shardings": None,
                "out_shardings": None,
                "line": node.lineno,
                "name": None,
            }
            for kw in node.keywords:
                if kw.arg in ("in_shardings", "out_shardings"):
                    rec[kw.arg] = rend.render(kw.value, env)
            if id(node) not in self.jits:
                self.jit_order.append(id(node))
            self.jits[id(node)] = rec
            return dataflow.AbsVal(ref=("jit", id(node)), origin=node.lineno)
        if isinstance(node.func, ast.Attribute) and node.func.attr == "wrap" \
                and len(node.args) >= 2:
            nm = self._program_name(node.args[0])
            target = argvals[1]
            if nm is not None and target.ref is not None \
                    and target.ref[0] == "jit":
                rec = self.jits.get(target.ref[1])
                if rec is not None and rec["name"] is None:
                    rec["name"] = nm
            return target  # wrap returns the wrapped callable unchanged
        if fval.ref is not None and fval.ref[0] == "jit":
            # invoking a locally-jitted program: donate its buffers
            rec = self.jits.get(fval.ref[1])
            for idx in (rec or {}).get("donate_argnums", ()):
                if isinstance(idx, int) and idx < len(node.args):
                    d = astutil.dotted(node.args[idx])
                    if d is not None:
                        cur = env.get(d, dataflow.BOTTOM)
                        env[d] = replace(
                            cur, tags=cur.tags | {"donated"},
                            origin=node.lineno)
            return dataflow.AbsVal(tags=frozenset({"device"}), fresh=True,
                                   origin=node.lineno)
        if callee in SPEC_CTORS | NS_CTORS:
            return dataflow.AbsVal(spec=rend.render(node, env))
        # module-local call: let the engine propagate its return value
        if fval.ref is not None and fval.ref[0] == "def":
            return None
        if callee is not None:
            simple = callee.split(".")[-1]
            if callee in (simple, f"self.{simple}", f"cls.{simple}") \
                    and astutil.resolve_scoped(simple, df.current_qn,
                                               df.by_name):
                return None
        # opaque call: keep textual provenance so `ssh = state_shardings(
        # state, mesh)` renders meaningfully inside a jit contract
        return dataflow.AbsVal(spec=rend.render(node, env))

    def at_load(self, node, name, val, env, df):
        if name is None:
            # un-dotted attribute fallthrough: the base Name load
            # already reported the donated read, with a readable name
            return
        if val.tagged("donated"):
            key = (node.lineno, name)
            if key in self._flagged:
                return
            self._flagged.add(key)
            self.findings.append(Finding(
                rule="XF702", path=self.mod.relpath, line=node.lineno,
                message=(
                    f"`{name}` read after its buffer was donated to a "
                    "jitted call (donate_argnums) — donated buffers are "
                    "invalidated by execution; works on CPU, corrupts "
                    "on TPU"
                ),
                hint="rebind the name to the call's result (state = "
                     "step(state, ...)) or drop the donation",
            ))


def _first_param(fn_node) -> Optional[str]:
    args = fn_node.args
    pos = args.posonlyargs + args.args
    return pos[0].arg if pos else None


def _p_axis_entries(arg, consts: dict):
    """Axis names referenced by one PartitionSpec argument."""
    nodes = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) else [arg]
    for el in nodes:
        s = astutil.const_str(el)
        if s is None and isinstance(el, ast.Name):
            s = consts.get(el.id)
        if s is not None:
            yield s, el


def _flatten_leaf_specs(dict_node, renderer, prefix, out: dict) -> None:
    for k, v in zip(dict_node.keys, dict_node.values):
        key = astutil.const_str(k) if k is not None else None
        if key is None:
            continue
        path = f"{prefix}{key}"
        if isinstance(v, ast.Dict):
            _flatten_leaf_specs(v, renderer, path + ".", out)
            continue
        rendered = renderer.render(v)
        if "P(" in rendered or "NamedSharding(" in rendered:
            out.setdefault(path, set()).add(rendered)


class _ModuleContract:
    """Everything extracted from one module: findings + contract data."""

    def __init__(self, mod, project_axes: set, project_consts: dict):
        self.mod = mod
        tree = mod.tree
        aliases = astutil.import_aliases(tree)
        local_axes, local_consts = _axis_decls_from_tree(tree)
        self.consts = dict(project_consts)
        self.consts.update(local_consts)
        self.declared = set(project_axes) | local_axes
        self.renderer = _Renderer(self.consts, aliases)
        self.renderer.seed_alias_specs(tree)
        self.findings: list = []
        self.axes_referenced: set = set()
        self.scopes: set = set()
        self.scope_lines: list = []
        self.leaf_specs: dict = {}
        self.shard_map_specs: dict = {}
        self.calls_shared_builder = False

        # ---- flow-sensitive sweep: jit records, wrap names, XF702
        hooks = _ContractHooks(mod, self.renderer)
        dataflow.Dataflow(mod, hooks).run_all()
        self.jits = [hooks.jits[i] for i in hooks.jit_order]
        self.findings.extend(hooks.findings)

        # ---- syntactic sweeps
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            cn = astutil.canonical(astutil.call_name(node), aliases)
            if cn in SPEC_CTORS:
                for axis, el in _p_axis_entries_all(node, self.consts):
                    self.axes_referenced.add(axis)
                    if axis not in self.declared:
                        self.findings.append(Finding(
                            rule="XF701", path=mod.relpath,
                            line=el.lineno,
                            message=(
                                f"PartitionSpec references axis {axis!r}, "
                                "not a declared mesh axis "
                                f"({', '.join(sorted(self.declared))}) — "
                                "fails inside GSPMD partitioning at run "
                                "time"
                            ),
                            hint="use the canonical axis constants "
                                 "(parallel/mesh.py DATA_AXIS/TABLE_AXIS)",
                        ))
            elif cn in COLLECTIVES:
                for arg in list(node.args)[1:2] + [
                        kw.value for kw in node.keywords
                        if kw.arg == "axis_name"]:
                    for axis, _el in _p_axis_entries(arg, self.consts):
                        self.axes_referenced.add(axis)
            elif cn is not None and cn.endswith("named_scope") and node.args:
                s = astutil.const_str(node.args[0])
                if s is not None:
                    self.scopes.add(s)
                    self.scope_lines.append(node.lineno)
            elif cn is not None and cn.split(".")[-1] in SHARED_STEP_BUILDERS:
                origin = aliases.get(cn.split(".")[-1], "")
                if origin.startswith("xflow_tpu.train.step."):
                    self.calls_shared_builder = True

        # per-table-leaf shardings from dict literals (incl. TrainState(...));
        # nested dicts flatten through their parent's key path only
        nested: set = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Dict):
                for v in node.values:
                    if isinstance(v, ast.Dict):
                        nested.add(id(v))
        for node in ast.walk(tree):
            if isinstance(node, ast.Dict) and id(node) not in nested:
                _flatten_leaf_specs(node, self.renderer, "", self.leaf_specs)

        # shard_map decorator / call specs
        parents = None
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            cn = astutil.canonical(astutil.call_name(node), aliases)
            if cn is None or cn.split(".")[-1] not in (
                    "shard_map", "smap"):
                continue
            specs = {}
            for kw in node.keywords:
                if kw.arg in ("in_specs", "out_specs"):
                    specs[kw.arg] = self.renderer.render(kw.value)
            if specs:
                if parents is None:  # built once, only when needed
                    parents = astutil.parent_map(tree)
                owner = astutil.enclosing(
                    node, parents,
                    (ast.FunctionDef, ast.AsyncFunctionDef))
                name = owner.name if owner is not None else "<module>"
                self.shard_map_specs.setdefault(name, {}).update(specs)

        # ---- XF703: jit of a train step without state donation
        by_qn = {qn: n for qn, n, _c in astutil.func_defs(tree)}
        for rec in self.jits:
            ref = rec.get("fn_ref")
            if ref is None or ref[0] != "def":
                continue
            fn_node = by_qn.get(ref[1])
            if fn_node is None or _first_param(fn_node) != STATE_PARAM:
                continue
            if 0 not in rec["donate_argnums"] \
                    and STATE_PARAM not in rec["donate_argnums"]:
                self.findings.append(self._xf703(rec["line"]))
        # decorator form
        for qn, fn_node, _cls in astutil.func_defs(tree):
            if _first_param(fn_node) != STATE_PARAM:
                continue
            for dec in fn_node.decorator_list:
                # the jit family ONLY (shard_map/grad/vmap wrappers have
                # no donation contract): @jax.jit, @jax.jit(...), or
                # @partial(jax.jit, ...)
                name = astutil.canonical(astutil.dotted(dec), aliases)
                is_jit = name in JIT_CALLS
                if not is_jit and isinstance(dec, ast.Call):
                    cn = astutil.canonical(astutil.call_name(dec), aliases)
                    if cn in JIT_CALLS:
                        is_jit = True
                    elif cn in ("functools.partial", "partial") and dec.args:
                        is_jit = astutil.canonical(
                            astutil.dotted(dec.args[0]), aliases) in JIT_CALLS
                if not is_jit:
                    continue
                donated = isinstance(dec, ast.Call) and any(
                    kw.arg in ("donate_argnums", "donate_argnames")
                    for kw in dec.keywords)
                if not donated:
                    self.findings.append(self._xf703(fn_node.lineno))
                break

    def _xf703(self, line: int) -> Finding:
        return Finding(
            rule="XF703", path=self.mod.relpath, line=line,
            message=(
                "train-step jit takes the TrainState (tables + optimizer "
                "state) without donate_argnums — the update keeps TWO "
                "copies of the dominant HBM resident live (double-HBM "
                "residency, docs/PERF.md)"
            ),
            hint="donate the state: jax.jit(step, donate_argnums=(0,))",
        )

    def contract(self) -> dict:
        programs: dict = {}
        unnamed = 0
        for rec in self.jits:
            name = rec["name"]
            if name is None:
                unnamed += 1
                name = f"unnamed:{rec['function']}:{unnamed}"
            if name in programs:
                # two jits wrapped under one recorder name must BOTH
                # stay visible to the drift gate — never shadow one
                n = 2
                while f"{name}#{n}" in programs:
                    n += 1
                name = f"{name}#{n}"
            programs[name] = {
                "function": rec["function"],
                "donate_argnums": sorted(
                    x for x in rec["donate_argnums"]
                    if isinstance(x, int)),
                "static_argnums": sorted(rec["static_argnums"]),
                "static_argnames": sorted(rec["static_argnames"]),
                "in_shardings": rec["in_shardings"],
                "out_shardings": rec["out_shardings"],
            }
        return {
            "axes_referenced": sorted(self.axes_referenced),
            "scopes": sorted(self.scopes),
            "programs": programs,
            "leaf_specs": {k: sorted(v)
                           for k, v in sorted(self.leaf_specs.items())},
            "shard_map_specs": {k: dict(sorted(v.items()))
                                for k, v in
                                sorted(self.shard_map_specs.items())},
        }


def _p_axis_entries_all(call: ast.Call, consts: dict):
    for arg in call.args:
        yield from _p_axis_entries(arg, consts)


# --------------------------------------------------------------- entry points


def _analyze(project: Project) -> tuple:
    """-> (findings, {relpath: _ModuleContract for engine modules})."""
    axes, consts = mesh_decls(project)
    findings: list = []
    engines: dict = {}
    for mod in project.modules:
        if mod.tree is None:
            continue
        # cheap pre-filter: modules with no sharding/jit surface skip the
        # flow-sensitive sweep entirely
        if not any(tok in mod.source for tok in (
                "PartitionSpec", "NamedSharding", "jax.jit", "pjit",
                "named_scope", "shard_map", "donate_argnums")):
            continue
        mc = _ModuleContract(mod, axes, consts)
        findings.extend(mc.findings)
        if mod.relpath in ENGINE_MODULES:
            engines[mod.relpath] = mc

    # ---- XF704(a): scope drift across engine builders. The comparison
    # ROSTER is always the full builder set — builders a partial scan
    # (--changed, a subtree) left out load from disk for comparison
    # only, so a partial scan's verdicts match the full tree's (findings
    # fire solely on SCANNED modules; like mesh_decls' axes anchor)
    roster: dict = dict(engines)
    from xflow_tpu.analysis.core import Module, _read

    for rel in ENGINE_MODULES:
        if not engines:
            break  # no scanned builder -> nothing XF704 could fire on
        if rel in roster:
            continue
        path = os.path.join(project.root, *rel.split("/"))
        if not os.path.exists(path):
            continue
        m = Module(path, rel, _read(path))
        if m.tree is not None:
            roster[rel] = _ModuleContract(m, axes, consts)
    if len(roster) >= 2:
        shared = roster.get(SHARED_STEP_MODULE)
        effective: dict = {}
        for rel, mc in roster.items():
            if rel != SHARED_STEP_MODULE and mc.calls_shared_builder \
                    and shared is None:
                # delegating builder whose delegate is unreadable: its
                # effective scope set is unknowable — never guess a drift
                effective[rel] = None
                continue
            eff = set(mc.scopes)
            if rel != SHARED_STEP_MODULE and mc.calls_shared_builder:
                eff |= shared.scopes
            effective[rel] = eff
        for rel, mc in sorted(roster.items()):
            if rel not in engines or effective[rel] is None:
                continue  # unscanned roster members are comparison-only
            others = [effective[r] for r in roster
                      if r != rel and effective[r] is not None]
            if not others:
                continue
            everywhere_else = set.intersection(*others)
            for scope in sorted(everywhere_else - effective[rel]):
                line = min(mc.scope_lines) if mc.scope_lines else 1
                findings.append(Finding(
                    rule="XF704", path=rel, line=line,
                    message=(
                        f"engine builder is missing trace scope "
                        f"{scope!r} that every other engine builder "
                        "covers — per-stage xprof attribution goes "
                        "blind on this engine (contract matrix, "
                        "tools/engine_contracts.json)"
                    ),
                    hint=f"add `with jax.named_scope({scope!r}):` around "
                         "the corresponding stage, or regenerate the "
                         "contract matrix if the vocabulary changed",
                ))
    # ---- XF704(b): intra-builder table-leaf spec disagreement
    for rel, mc in sorted(engines.items()):
        for path, specs in sorted(mc.leaf_specs.items()):
            if len(specs) > 1:
                findings.append(Finding(
                    rule="XF704", path=rel, line=1,
                    message=(
                        f"table leaf {path!r} is declared with "
                        f"{len(specs)} different shardings within one "
                        f"builder: {sorted(specs)} — its programs will "
                        "disagree about where the table lives"
                    ),
                    hint="hoist the sharding into one shared declaration",
                ))
    return findings, engines


def extract_contracts(project: Project) -> dict:
    """The engine-contract matrix (tools/engine_contracts.json): the
    machine-readable acceptance oracle for the ROADMAP's unified-builder
    refactor. Deterministic function of the sources — byte-stable."""
    _findings, engines = _analyze(project)
    axes, _consts = mesh_decls(project)
    return {
        "_comment": (
            "Engine sharding-contract matrix, extracted by xflowlint's "
            "XF7xx pass (analysis/passes/sharding_contract.py). "
            "Regenerate with `python tools/xflowlint.py "
            "--write-contracts`; CI fails with exit 4 on drift "
            "(tools/smoke_lint.sh). The future unified step builder "
            "must reproduce this matrix (ROADMAP: one engine, "
            "rule-driven sharding)."
        ),
        "declared_mesh_axes": sorted(axes),
        "engines": {rel: mc.contract()
                    for rel, mc in sorted(engines.items())},
    }


def render_artifact(contracts: dict) -> str:
    import json

    return json.dumps(contracts, indent=2, sort_keys=True) + "\n"


@register_pass("sharding-contract", RULES, scope="project")
def run(project: Project) -> list:
    findings, _engines = _analyze(project)
    return findings
