"""XF11x host-sync taint: device-origin values blocking the hot path.

docs/PERF.md's measured roofline names host/device synchronization as
one of the two remaining perf levers, and the repo's answer is the
one-step-behind discipline (telemetry.StepTimer / HealthMonitor,
docs/OBSERVABILITY.md): a step's metrics are only read AFTER the next
step's async dispatch, so the blocking read hides under device time
instead of stalling it. These rules are that discipline's static
complement, built on the flow-sensitive dataflow engine
(analysis/dataflow.py):

- XF110 explicit-host-sync: a device-origin value (the result of a jit
  program call, `jax.device_put`, or a locally-jitted callable) flows
  into a blocking host conversion — `float()`/`int()`/`np.asarray()`/
  `.item()`/`.tolist()`/`.block_until_ready()`/`print`/`str.format`/
  f-string interpolation — inside a hot loop, in the SAME iteration
  that dispatched it (the value is still "fresh": no newer dispatch
  has been issued to hide the block under).
- XF111 implicit-host-sync: the same fresh device value driving a host
  branch (`if`/`while`/ternary/`assert` test, `bool()`) or being
  iterated — the sneakier form with no conversion call to grep for.

Scope — the three hot paths, by qualified function name: the trainer's
fit loop (`*._fit`), the input-pipeline prefetch producer
(`prefetch`), and the serve device worker (`*._worker_loop`), plus
their nested closures; and only sync sites inside a loop that
DISPATCHES device work. Blocking between dispatches stalls the
pipeline; a read-only loop (the post-fit occupancy sweep) performs
mandatory one-time syncs and is exempt by construction.

Exemption by construction, not suppression: the DELIBERATE one-behind
reads never match, in three structural ways. (1) Freshness: a source
call ages every device value in the environment, so a value staged
last iteration and read after this iteration's dispatch is stale — the
exact shape of the discipline. (2) The sanctioned blocking reads live
in telemetry.py (StepTimer._finish_pending, HealthMonitor.collect),
outside the scoped functions. (3) A closure reading staged metrics
through a free variable (the trainer's `check_pending`) sees it as
BOTTOM — crossing the staging seam is what makes the read legal, and
it is also what makes it invisible to the intraprocedural engine.

Motivating fix (this PR): the fit loop's log block read
`float(m["loss"])` on the step it had JUST dispatched, stalling the
device once per train.log_every steps; the record is now staged and
written one step behind (train/trainer.py emit_pending_record).
"""

from __future__ import annotations

import ast
import fnmatch
from dataclasses import replace

from xflow_tpu.analysis import astutil, dataflow
from xflow_tpu.analysis.core import Finding, Project, register_pass

RULES = ("XF110", "XF111")

# the hot-path functions, by qualname pattern (nested closures included)
HOT_QUALNAMES = (
    "*._fit", "*._fit.*",
    "*._fit_tail", "*._fit_tail.*",
    "*._worker_loop", "*._worker_loop.*",
    "prefetch", "prefetch.*",
)

# callables whose results live on device: jit-program products bound as
# attributes by the step builders (make_train_step / make_sharded_* /
# make_predict_fn) — the names the trainer and serve tier call them by
SOURCE_ATTRS = {"train_step", "eval_step", "predict_step", "_predict_step",
                "step_fn"}
SOURCE_CALLS = {"jax.device_put", "jax.jit", "jit", "pjit", "jax.pjit"}
JIT_CTORS = {"jax.jit", "jit", "pjit", "jax.pjit"}

# blocking host conversions (XF110). len() stays out on purpose: a jax
# array's length is shape metadata, no device read
SINK_CALLS = {
    "float", "int", "bool", "str", "print",
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "np.float32", "numpy.float32", "np.float64", "numpy.float64",
    "jax.device_get",
}
SINK_METHODS = {"item", "tolist", "block_until_ready"}


def _short(node) -> str:
    try:
        s = ast.unparse(node)
    except Exception:  # pragma: no cover — unparse covers all exprs
        s = "<expr>"
    return s if len(s) <= 48 else s[:45] + "..."


def _is_dispatch_call(node: ast.Call, aliases: dict,
                      jitted_names: set) -> bool:
    """Syntactic: does this call enqueue device work? (source-attr step
    calls, device_put, an immediately-invoked jit, a locally-jitted
    name)."""
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in SOURCE_ATTRS:
        return True
    if isinstance(node.func, ast.Name):
        if node.func.id in SOURCE_ATTRS or node.func.id in jitted_names:
            return True
    cn = astutil.canonical(astutil.call_name(node), aliases)
    if cn in SOURCE_CALLS and cn not in JIT_CTORS:
        return True
    if isinstance(node.func, ast.Call):  # jax.jit(f)(x)
        inner = astutil.canonical(astutil.call_name(node.func), aliases)
        return inner in JIT_CTORS
    return False


def _dispatching_loops(tree, aliases: dict) -> set:
    """ids of loop nodes whose body issues a device dispatch. Only such
    loops can have a sync BUBBLE: blocking between dispatches stalls
    the pipeline, while a loop that only READS (a post-run epilogue
    sweep) performs mandatory one-time syncs — exempt by construction."""
    jitted_names: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if astutil.canonical(astutil.call_name(node.value),
                                 aliases) in JIT_CTORS:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        jitted_names.add(tgt.id)
    out: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            continue
        for sub in astutil.walk_scope(node):
            if isinstance(sub, ast.Call) and _is_dispatch_call(
                    sub, aliases, jitted_names):
                out.add(id(node))
                break
    return out


class _Hooks(dataflow.Hooks):
    propagate_returns = True

    def __init__(self, mod, parents, dispatch_loops):
        self.mod = mod
        self.parents = parents
        self.dispatch_loops = dispatch_loops
        self.findings: list = []

    # ------------------------------------------------------------ helpers
    def _in_scope(self, df) -> bool:
        qn = df.current_qn
        return bool(qn) and any(fnmatch.fnmatch(qn, p)
                                for p in HOT_QUALNAMES)

    def _hot(self, node, df) -> bool:
        """Inside a hot function AND inside a loop that dispatches
        device work — only there can a blocking read be a bubble."""
        if not self._in_scope(df):
            return False
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)) \
                    and id(cur) in self.dispatch_loops:
                return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
            cur = self.parents.get(cur)
        return False

    def _fresh_device(self, val) -> bool:
        return val.tagged("device") and val.fresh

    def _age(self, env: dict) -> None:
        """A new device dispatch: every older device value's blocking
        read now hides under it (fresh -> stale), containers included."""
        for k, v in list(env.items()):
            env[k] = self._aged(v)

    def _aged(self, v):
        if v.elems is not None:
            v = replace(v, elems=tuple(self._aged(e) for e in v.elems))
        if v.fresh:
            v = replace(v, fresh=False)
        return v

    def _flag(self, rule: str, node, how: str, expr_node) -> None:
        self.findings.append(Finding(
            rule=rule, path=self.mod.relpath, line=node.lineno,
            message=(
                f"{how} `{_short(expr_node)}` blocks on a device value "
                "dispatched THIS iteration of the hot loop — a host/"
                "device sync bubble (the one-step-behind discipline, "
                "docs/OBSERVABILITY.md)"
            ),
            hint="stage the value and read it AFTER the next step's "
                 "async dispatch (telemetry.StepTimer pattern), or move "
                 "the read out of the loop",
        ))

    # -------------------------------------------------------------- hooks
    def at_call(self, node, callee, argvals, kwvals, env, df, fval):
        # -- sources: device dispatch ages the env, result is fresh
        is_source = False
        if callee in SOURCE_CALLS:
            is_source = True
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in SOURCE_ATTRS:
            is_source = True
        elif isinstance(node.func, ast.Name) and node.func.id in SOURCE_ATTRS:
            is_source = True
        elif fval.ref is not None and fval.ref[0] == "jit":
            is_source = True  # a name bound from jax.jit(...), invoked
        if is_source:
            if callee in JIT_CTORS:
                # jax.jit(f) CONSTRUCTS a callable — no device dispatch
                # happens, so nothing ages; invoking the returned ref
                # later is the source
                return dataflow.AbsVal(ref=("jit", id(node)),
                                       origin=node.lineno)
            self._age(env)
            return dataflow.AbsVal(tags=frozenset({"device"}), fresh=True,
                                   origin=node.lineno)
        # -- sinks: explicit blocking conversions (XF110)
        if callee in SINK_CALLS and self._hot(node, df):
            for av, anode in zip(argvals, node.args):
                if self._fresh_device(av):
                    self._flag("XF110", node,
                               f"blocking host sync `{callee}(...)` on",
                               anode)
                    break
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in SINK_METHODS and self._fresh_device(fval) \
                    and self._hot(node, df):
                self._flag("XF110", node,
                           f"blocking host sync `.{node.func.attr}()` on",
                           node.func.value)
            elif node.func.attr == "format" and self._hot(node, df):
                for av, anode in zip(argvals, node.args):
                    if self._fresh_device(av):
                        self._flag("XF110", node, "string formatting of",
                                   anode)
                        break
        return None

    def at_branch(self, node, val, env, df):
        if self._fresh_device(val) and self._hot(node, df):
            self._flag("XF111", node, "host branch condition on", node)

    def at_iter(self, node, val, env, df):
        if self._fresh_device(val) and self._hot(node, df):
            self._flag("XF111", node, "host iteration over", node)

    def at_format(self, node, val, env, df):
        if self._fresh_device(val) and self._hot(node, df):
            self._flag("XF110", node, "f-string interpolation of",
                       node.value)


@register_pass("host-sync", RULES)
def run(project: Project) -> list:
    findings: list = []
    for mod in project.modules:
        if mod.tree is None:
            continue
        defs = astutil.func_defs(mod.tree)
        if not any(fnmatch.fnmatch(qn, p) for qn, _n, _c in defs
                   for p in HOT_QUALNAMES):
            continue
        parents = astutil.parent_map(mod.tree)
        aliases = astutil.import_aliases(mod.tree)
        hooks = _Hooks(mod, parents, _dispatching_loops(mod.tree, aliases))
        dataflow.Dataflow(mod, hooks).run_all()
        findings.extend(hooks.findings)
    return findings
