"""XF5xx JSONL-schema drift: record literals vs docs/OBSERVABILITY.md.

Every stream in this repo flows through the stamped JsonlAppender and
is documented as a schema table in docs/OBSERVABILITY.md; the runtime
gate (`metrics_report --check`) can only complain AFTER a run produced
a drifted stream. This pass fails the same drift in lint: it parses
the doc's tables into {kind -> allowed keys} and checks every record
dict literal the code ships against them.

Doc parsing: a `##`/`###` heading (or a table-introducing paragraph
line) containing `kind="X"` binds the following markdown tables to
kind X; the first table of the "Metrics JSONL schema" section is the
provenance stamp (keys legal on every kind). Key cells may list
several backticked names (`` `a`, `b` ``).

Code side, a dict literal is a record when:
- it contains a literal `"kind"` key, or
- it `**`-merges a binding known to hold one (`{**self._kind, ...}`
  where `self._kind = {"kind": "serve"}`), or
- it is the argument of `.append(...)` on a name/attr bound to a
  `JsonlAppender(..., stamp={... "kind": "X"})` — the heartbeat/
  watchdog pattern, where the kind lives in the stamp.

Findings:
- XF501 undocumented-record-key: a literal key the kind's tables (or
  the stamp table) do not list.
- XF502 unknown-record-kind: a `kind` value with no doc section.

Dynamic keys (`**extra`, computed keys) are out of scope by design —
the pass checks what it can prove, `--check` still guards the rest.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Optional

from xflow_tpu.analysis import astutil
from xflow_tpu.analysis.core import Finding, Project, register_pass

RULES = ("XF501", "XF502")

KIND_RE = re.compile(r'kind="([a-z_]+)"')
KEY_CELL_RE = re.compile(r"`([A-Za-z_][\w.]*)`")


def parse_schema_doc(path: str) -> Optional[tuple]:
    """-> ({kind: set(keys)}, stamp_keys) or None if the doc is absent."""
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    kinds: dict = {}
    stamp: set = set()
    current: list = []  # kinds the next table binds to
    stamp_next = False
    in_metrics_section = False
    in_fence = False
    i = 0
    while i < len(lines):
        line = lines[i]
        # fenced code blocks are examples, not schema: a `# comment`
        # line inside ``` must not read as a heading that clears the
        # current kind binding, and a fenced table is not a schema
        if line.lstrip().startswith("```"):
            in_fence = not in_fence
            i += 1
            continue
        if in_fence:
            i += 1
            continue
        if line.startswith("#"):
            in_metrics_section = "Metrics JSONL schema" in line
            found = KIND_RE.findall(line)
            current = found
            stamp_next = in_metrics_section
        elif KIND_RE.search(line) and not line.strip().startswith("|"):
            # a paragraph line naming kinds re-binds subsequent tables
            # (e.g. 'Heartbeat records (kind="heartbeat"):')
            found = KIND_RE.findall(line)
            if found:
                current = found
        if line.strip().startswith("|") and "---" not in line:
            # a table block: consume it
            keys: set = set()
            j = i
            while j < len(lines) and lines[j].strip().startswith("|"):
                row = lines[j]
                j += 1
                if re.match(r"^\s*\|[\s:|-]*$", row):
                    continue  # separator
                first_cell = row.split("|")[1] if row.count("|") >= 2 else ""
                for m in KEY_CELL_RE.finditer(first_cell):
                    name = m.group(1)
                    if "." not in name:  # skip `hbm.*`-style globs
                        keys.add(name)
            keys.discard("field")  # header row
            if stamp_next:
                stamp |= keys
                stamp_next = False
            else:
                for k in current:
                    kinds.setdefault(k, set()).update(keys)
            i = j
            continue
        i += 1
    for k in kinds:
        kinds[k] |= {"kind"}
    return kinds, stamp | {"kind", "event"}


def _kind_bindings(tree: ast.AST) -> tuple:
    """(dict-bindings, appender-bindings): dotted target -> kind, for
    `X = {"kind": "serve"}` and `X = JsonlAppender(..., stamp={...})`."""
    dict_kinds: dict = {}
    app_kinds: dict = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = astutil.dotted(node.targets[0])
        if not tgt:
            continue
        val = node.value
        if isinstance(val, ast.Dict):
            k = _literal_kind(val)
            if k:
                dict_kinds[tgt] = k
        elif isinstance(val, ast.Call):
            cn = astutil.call_name(val) or ""
            if cn.split(".")[-1] == "JsonlAppender":
                for kw in val.keywords:
                    if kw.arg == "stamp" and isinstance(kw.value, ast.Dict):
                        k = _literal_kind(kw.value)
                        if k:
                            app_kinds[tgt] = k
    return dict_kinds, app_kinds


def _literal_kind(d: ast.Dict) -> Optional[str]:
    for k, v in zip(d.keys, d.values):
        if k is not None and astutil.const_str(k) == "kind":
            return astutil.const_str(v)
    return None


def _dict_info(d: ast.Dict, dict_kinds: dict) -> tuple:
    """(kind or None, literal keys, dynamic) for a dict literal,
    resolving one level of `**`-merge against known bindings."""
    kind = _literal_kind(d)
    keys: set = set()
    dynamic = False
    for k, v in zip(d.keys, d.values):
        if k is None:  # **merge
            name = astutil.dotted(v)
            merged = dict_kinds.get(name) if name else None
            if merged:
                kind = kind or merged
                keys.add("kind")
            else:
                dynamic = True
            continue
        s = astutil.const_str(k)
        if s is None:
            dynamic = True
        else:
            keys.add(s)
    return kind, keys, dynamic


@register_pass("schema-drift", RULES)
def run(project: Project) -> list:
    parsed = parse_schema_doc(project.schema_doc_path)
    if parsed is None:
        return []
    kinds, stamp = parsed
    findings: list = []
    for mod in project.modules:
        if mod.tree is None:
            continue
        dict_kinds, app_kinds = _kind_bindings(mod.tree)
        checked: set = set()
        # records appended to a kind-stamped appender
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "append" and node.args
                    and isinstance(node.args[0], ast.Dict)):
                owner = astutil.dotted(node.func.value)
                akind = app_kinds.get(owner) if owner else None
                d = node.args[0]
                kind, keys, _dyn = _dict_info(d, dict_kinds)
                kind = kind or akind
                if kind is not None:
                    checked.add(id(d))
                    _check(findings, mod, d, kind, keys, kinds, stamp)
        # any other dict literal that states its kind
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Dict) and id(node) not in checked:
                kind, keys, _dyn = _dict_info(node, dict_kinds)
                if kind is None or "kind" not in keys:
                    continue
                _check(findings, mod, node, kind, keys, kinds, stamp)
    return findings


def _check(findings, mod, d, kind, keys, kinds, stamp) -> None:
    if kind not in kinds:
        findings.append(Finding(
            rule="XF502", path=mod.relpath, line=d.lineno,
            message=f'record kind "{kind}" has no schema section in '
                    "docs/OBSERVABILITY.md",
            hint="add a schema table (a heading or intro line containing "
                 f'kind="{kind}") before shipping records of this kind',
        ))
        return
    allowed = kinds[kind] | stamp
    for key in sorted(keys):
        if key not in allowed:
            findings.append(Finding(
                rule="XF501", path=mod.relpath, line=d.lineno,
                message=f'key `{key}` on a kind="{kind}" record is not in '
                        "the docs/OBSERVABILITY.md schema tables",
                hint="document the field in the kind's table (or fix the "
                     "drifted key) — metrics_report --check gates the "
                     "same schema at runtime",
            ))
