"""XF301 thread-safety lockset: unlocked cross-thread attribute writes.

PR 8 paid for this the hard way: `JsonlAppender` was written as a
single-threaded sink, the serving-fleet router became its first
multi-threaded caller, and two handler threads could interleave one
JSONL line (the fix added the internal append lock). The bug class is
mechanical: a class whose methods run on more than one thread mutates
`self.<attr>` somewhere without holding the object's lock.

Per class the pass:
- finds thread entrypoints: methods passed as `target=` to
  `threading.Thread` / `threading.Timer` (each its own thread), plus
  the external region — public methods (and everything they call)
  that outside callers invoke on their own threads;
- only classes that actually SPAWN a thread (or subclass a
  threading-server base) are analyzed — a single-threaded helper may
  mutate freely;
- builds the per-class `self.method()` call graph and assigns every
  method the set of threads it can run on;
- flags `self.<attr> = ...` / `self.<attr> += ...` stores (outside
  `__init__`, which happens-before any thread start) that are not
  lexically under `with self.<lock-family>` when the attribute is
  touched from >= 2 distinct threads.

A lock is any `with self.<name>:` / `with self.<name>.<ctx>` where
`<name>` contains "lock", "cv", "cond", or "mutex" — the repo's
`self._lock`-family convention (docs/STATIC_ANALYSIS.md). The pass is
intra-class by design: an unlocked SHARED OBJECT (the pre-PR 8
appender itself) is caught when ITS class runs handlers on several
threads; the fixture corpus pins exactly that reproduction.
"""

from __future__ import annotations

import ast
from typing import Optional

from xflow_tpu.analysis import astutil
from xflow_tpu.analysis.core import Finding, Project, register_pass

RULE = "XF301"

THREAD_SPAWNS = {"threading.Thread", "Thread", "threading.Timer", "Timer"}
# (import aliases canonicalize `import threading as _th` before lookup)
# subclassing one of these makes methods run on server-managed threads
THREADED_BASES = {
    "ThreadingHTTPServer", "ThreadingMixIn", "ThreadingTCPServer",
    "ThreadingUnixStreamServer", "BaseHTTPRequestHandler",
}
LOCK_TOKENS = ("lock", "cv", "cond", "mutex")
# construction-time methods: writes there happen-before thread start
EXEMPT_METHODS = {"__init__", "__new__", "__post_init__"}


def _lockish(name: str) -> bool:
    low = name.lower()
    return any(tok in low for tok in LOCK_TOKENS)


def _under_lock(node: ast.AST, parents: dict) -> bool:
    """Lexically inside `with self.<lock-family>[...]:`?"""
    cur = parents.get(node)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        if isinstance(cur, ast.With):
            for item in cur.items:
                name = astutil.dotted(item.context_expr)
                if name is None and isinstance(item.context_expr, ast.Call):
                    name = astutil.call_name(item.context_expr)
                if name and any(_lockish(part) for part in name.split(".")):
                    return True
        cur = parents.get(cur)
    return False


def _thread_targets(cls: ast.ClassDef, aliases: dict) -> list:
    """[(method name, spawn lineno)] for Thread/Timer targets that are
    `self.<m>` in this class."""
    out = []
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        if astutil.canonical(astutil.call_name(node),
                             aliases) not in THREAD_SPAWNS:
            continue
        target: Optional[ast.AST] = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None and len(node.args) >= 2:
            target = node.args[1]  # Timer(interval, function)
        name = astutil.dotted(target) if target is not None else None
        if name and name.startswith("self."):
            out.append((name.split(".", 1)[1], node.lineno))
    return out


def _methods(cls: ast.ClassDef) -> dict:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _call_graph(methods: dict) -> dict:
    graph: dict = {}
    for name, node in methods.items():
        callees = set()
        for sub in astutil.walk_scope(node):
            if isinstance(sub, ast.Call):
                cn = astutil.call_name(sub)
                if cn and cn.startswith("self."):
                    m = cn.split(".", 1)[1]
                    if "." not in m and m in methods:
                        callees.add(m)
        graph[name] = callees
    return graph


def _reach(seeds: set, graph: dict) -> set:
    seen = set(seeds)
    stack = list(seeds)
    while stack:
        for nxt in graph.get(stack.pop(), ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return seen


def _self_attr_accesses(node: ast.AST, parents: dict):
    """Yields (attr, lineno, is_write, locked) for self.<attr> uses."""
    for sub in astutil.walk_scope(node):
        if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (sub.targets if isinstance(sub, ast.Assign)
                       else [sub.target])
            for tgt in targets:
                for leaf in ast.walk(tgt):
                    if (isinstance(leaf, ast.Attribute)
                            and isinstance(leaf.value, ast.Name)
                            and leaf.value.id == "self"):
                        yield (leaf.attr, leaf.lineno, True,
                               _under_lock(sub, parents))
        elif (isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "self"
                and isinstance(sub.ctx, ast.Load)):
            yield (sub.attr, sub.lineno, False, _under_lock(sub, parents))


@register_pass("lockset", (RULE,))
def run(project: Project) -> list:
    findings = []
    for mod in project.modules:
        if mod.tree is None:
            continue
        parents = astutil.parent_map(mod.tree)
        aliases = astutil.import_aliases(mod.tree)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(
                    _check_class(node, mod.relpath, parents, aliases))
    return findings


def _check_class(cls: ast.ClassDef, relpath: str, parents: dict,
                 aliases: dict) -> list:
    methods = _methods(cls)
    if not methods:
        return []
    targets = _thread_targets(cls, aliases)
    threaded_base = any(
        (astutil.dotted(b) or "").split(".")[-1] in THREADED_BASES
        for b in cls.bases)
    if not targets and not threaded_base:
        return []
    graph = _call_graph(methods)
    # thread regions: one per spawn target; the external region is every
    # non-exempt method an outside caller can enter (public API and the
    # private helpers it reaches) — handler-base subclasses run do_*/
    # handle* on server threads, which the external region models too.
    regions: dict = {}
    for i, (tgt, _ln) in enumerate(sorted(set(targets))):
        if tgt in methods:
            regions[f"thread:{tgt}"] = _reach({tgt}, graph)
    target_names = {t for t, _ln in targets}
    # the external region seeds from PUBLIC methods only: a private
    # helper (`_flush`) that only the spawned thread ever calls must
    # not read as caller-thread-reachable — it still joins the region
    # transitively when a public method actually calls it
    external_seeds = {
        name for name in methods
        if name not in target_names and not name.startswith("_")
    }
    regions["external"] = _reach(external_seeds, graph)

    # thread-id sets per method
    ids: dict = {name: set() for name in methods}
    for rid, members in regions.items():
        for m in members:
            ids[m].add(rid)

    # attribute access census
    write_sites: dict = {}  # attr -> [(line, locked, method)]
    touch_ids: dict = {}    # attr -> set of region ids touching it
    for name, node in methods.items():
        if name in EXEMPT_METHODS:
            continue
        mids = ids.get(name) or set()
        if not mids:
            continue  # unreachable helper; no thread can be attributed
        for attr, line, is_write, locked in _self_attr_accesses(node, parents):
            if _lockish(attr):
                continue  # the lock object itself
            touch_ids.setdefault(attr, set()).update(mids)
            if is_write:
                write_sites.setdefault(attr, []).append((line, locked, name))

    findings = []
    for attr, sites in sorted(write_sites.items()):
        if len(touch_ids.get(attr, ())) < 2:
            continue  # single-thread attribute
        unlocked = [(ln, m) for ln, locked, m in sites if not locked]
        for line, meth in sorted(unlocked):
            findings.append(Finding(
                rule=RULE, path=relpath, line=line,
                message=f"`self.{attr}` written without holding a lock in "
                        f"`{cls.name}.{meth}`, but the attribute is "
                        "reachable from multiple threads "
                        f"({', '.join(sorted(touch_ids[attr]))})",
                hint="guard the write (and its paired reads) with `with "
                     "self._lock:` — the PR 8 JsonlAppender interleave is "
                     "this exact bug class (docs/ROBUSTNESS.md)",
            ))
    return findings
