"""Flow-sensitive intraprocedural forward dataflow over stdlib `ast`.

The syntactic passes (PR 10) answer "does this pattern appear"; the
rules this engine powers need "what is this VALUE at this program
point" — is it device-origin, has its buffer been donated, does it
still vary with the enclosing loop, which PartitionSpec does it carry.
Nothing under analysis is imported or executed (same contract as
core.py): abstract values propagate through assignments, tuple
unpacking, attribute chains, calls resolved via the scope-aware
astutil graph, and loop bodies iterated to a (capped) fixpoint with
join = may-union.

Abstract value (`AbsVal`):
- `tags`    may-facts: "device" (produced by a jit program / device_put),
            "donated" (its buffer was handed to a donating call),
            "loopvar" (varies per iteration of a tracked loop).
- `fresh`   device-origin AND no later device dispatch has been issued
            on this path. A blocking read of a *fresh* value stalls the
            host behind the step just dispatched; a *stale* one hides
            under the newer dispatch's device time — this bit is the
            one-step-behind StepTimer discipline, stated as dataflow
            (hostsync pass). Every source call ages the whole
            environment (fresh -> stale) before producing its own
            fresh result.
- `spec`    a rendered sharding/PartitionSpec expression, for the
            contract-extraction pass (mesh-axis sets ride inside it).
- `ref`     an opaque identity token, e.g. ("def", qualname) for a
            module-local function object or ("jit", node-id) for the
            result of a jax.jit call — lets passes link a wrapped /
            invoked name back to its producing site.
- `loops`   ids of the loop nodes a "loopvar" fact came from, so a
            consumer can ask "does THIS call site sit inside the loop
            that binds the value" (the XF202 retrofit: a loop variable
            read after its loop is one value, not one-per-iteration).
- `elems`   element values for tuples/lists of known shape, so
            `state, m = step(state, batch)` taints both names.

Soundness posture: under-approximate on purpose. Unknown calls return
BOTTOM (host, untainted); closures see their free variables as BOTTOM
(a value staged into an enclosing scope and read back in a nested
function has, by construction, crossed the one-behind seam); `global`
state is not modeled. Rules built on this engine therefore miss some
true positives but do not invent false ones — the property the empty-
baseline CI gate depends on.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, replace
from typing import Optional

from xflow_tpu.analysis import astutil


@dataclass(frozen=True)
class AbsVal:
    """One abstract value (see module docstring for field semantics)."""

    tags: frozenset = frozenset()
    fresh: bool = False
    spec: Optional[str] = None
    ref: Optional[tuple] = None
    loops: frozenset = frozenset()
    elems: Optional[tuple] = None
    origin: Optional[int] = None

    def tagged(self, *tags) -> bool:
        return any(t in self.tags for t in tags)


BOTTOM = AbsVal()


def join(a: AbsVal, b: AbsVal) -> AbsVal:
    """May-union of two values (path join)."""
    if a == b:
        return a
    elems = None
    if a.elems is not None and b.elems is not None \
            and len(a.elems) == len(b.elems):
        elems = tuple(join(x, y) for x, y in zip(a.elems, b.elems))
    origins = [o for o in (a.origin, b.origin) if o is not None]
    return AbsVal(
        tags=a.tags | b.tags,
        fresh=a.fresh or b.fresh,
        spec=a.spec if a.spec == b.spec else None,
        ref=a.ref if a.ref == b.ref else None,
        loops=a.loops | b.loops,
        elems=elems,
        origin=min(origins) if origins else None,
    )


def join_env(a: dict, b: dict) -> dict:
    out = dict(a)
    for k, v in b.items():
        cur = out.get(k)
        out[k] = v if cur is None else join(cur, v)
    return out


def propagated(val: AbsVal, origin: Optional[int] = None) -> AbsVal:
    """The value seen through an attribute/subscript/element access:
    taint facts carry, identity facts (spec/ref/elems) do not."""
    return AbsVal(tags=val.tags, fresh=val.fresh, loops=val.loops,
                  origin=val.origin if val.origin is not None else origin)


class Hooks:
    """Override points for a pass built on the engine. Every hook is
    optional; `at_call` returning a non-None AbsVal short-circuits the
    default call handling (local-return propagation)."""

    # analyze module-local callees to propagate their return values
    propagate_returns = False

    def at_call(self, node, callee, argvals, kwvals, env, df, fval):
        return None

    def at_branch(self, node, val, env, df):  # if/while/ternary tests
        pass

    def at_iter(self, node, val, env, df):  # for-loop / comprehension iter
        pass

    def at_format(self, node, val, env, df):  # f-string interpolation
        pass

    def at_load(self, node, name, val, env, df):  # every Name/attr load
        pass

    def at_dict(self, node, keyvals, env, df):
        """Dict literal: keyvals = [(constant key or None, AbsVal)].
        May return an AbsVal override (e.g. to attach a ref)."""
        return None


class Dataflow:
    """Forward abstract interpreter for one module. `run_all()` analyzes
    the module body and every function definition (each in isolation —
    intraprocedural; parameters and free variables start at BOTTOM)."""

    MAX_LOOP_PASSES = 3
    MAX_CALL_DEPTH = 4

    def __init__(self, module, hooks: Hooks):
        self.module = module
        self.hooks = hooks
        self.tree = module.tree
        self.aliases = astutil.import_aliases(self.tree)
        self.defs = astutil.func_defs(self.tree)
        self.by_qn = {qn: node for qn, node, _cls in self.defs}
        self.by_name = astutil.defs_by_name(self.defs)
        self.current_qn = ""
        self._ret_cache: dict = {}
        self._ret_stack: set = set()
        self._depth = 0

    # ------------------------------------------------------------ drivers
    def run_all(self) -> None:
        ret: list = []
        env: dict = {}
        self.current_qn = ""
        self.exec_stmts(self.tree.body, env, ret)
        for qn, node, _cls in self.defs:
            self.run_function(qn, node)

    def run_function(self, qn: str, node, seed: Optional[dict] = None) -> AbsVal:
        """Analyze one function; returns the join of its return values."""
        prev = self.current_qn
        self.current_qn = qn
        env: dict = {}
        args = node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            env[a.arg] = (seed or {}).get(a.arg, BOTTOM)
        if args.vararg:
            env[args.vararg.arg] = BOTTOM
        if args.kwarg:
            env[args.kwarg.arg] = BOTTOM
        ret: list = []
        self.exec_stmts(node.body, env, ret)
        self.current_qn = prev
        if not ret:
            return BOTTOM
        # fold WITHOUT a BOTTOM seed: a single return path keeps its
        # identity facts (ref/spec) — join only erases what genuinely
        # differs between paths
        out = ret[0]
        for v in ret[1:]:
            out = join(out, v)
        return out

    # --------------------------------------------------------- statements
    def exec_stmts(self, stmts, env: dict, ret: list) -> None:
        for st in stmts:
            self.exec_stmt(st, env, ret)

    def exec_stmt(self, st, env: dict, ret: list) -> None:
        if isinstance(st, ast.Assign):
            val = self.eval(st.value, env)
            for tgt in st.targets:
                self.assign(tgt, val, env)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self.assign(st.target, self.eval(st.value, env), env)
        elif isinstance(st, ast.AugAssign):
            old = self.eval(st.target, env)
            val = join(old, self.eval(st.value, env))
            self.assign(st.target, val, env)
        elif isinstance(st, ast.Expr):
            self.eval(st.value, env)
        elif isinstance(st, ast.Return):
            ret.append(self.eval(st.value, env) if st.value else BOTTOM)
        elif isinstance(st, ast.If):
            tv = self.eval(st.test, env)
            self.hooks.at_branch(st.test, tv, env, self)
            e1, e2 = dict(env), dict(env)
            self.exec_stmts(st.body, e1, ret)
            self.exec_stmts(st.orelse, e2, ret)
            env.clear()
            env.update(join_env(e1, e2))
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            itv = self.eval(st.iter, env)
            self.hooks.at_iter(st.iter, itv, env, self)
            loopval = AbsVal(
                tags=itv.tags | {"loopvar"}, fresh=itv.fresh,
                loops=itv.loops | {id(st)}, origin=st.lineno,
            )
            self._loop(st, env, ret, bind=lambda e: self.assign(
                st.target, loopval, e))
            self.exec_stmts(st.orelse, env, ret)
        elif isinstance(st, ast.While):
            def test_hook(e, _st=st):
                tv = self.eval(_st.test, e)
                self.hooks.at_branch(_st.test, tv, e, self)

            self._loop(st, env, ret, bind=test_hook)
            self.exec_stmts(st.orelse, env, ret)
        elif isinstance(st, ast.Try):
            pre = dict(env)
            self.exec_stmts(st.body, env, ret)
            merged = join_env(pre, env)
            # outs[0] must be a COPY: with zero handlers `acc` would
            # alias `env`, and the final clear()+update(acc) would wipe
            # every binding a try/finally body made
            outs = [dict(env)]
            for h in st.handlers:
                henv = dict(merged)
                if h.name:
                    henv[h.name] = BOTTOM
                self.exec_stmts(h.body, henv, ret)
                outs.append(henv)
            self.exec_stmts(st.orelse, outs[0], ret)
            acc = outs[0]
            for o in outs[1:]:
                acc = join_env(acc, o)
            self.exec_stmts(st.finalbody, acc, ret)
            env.clear()
            env.update(acc)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                v = self.eval(item.context_expr, env)
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, v, env)
            self.exec_stmts(st.body, env, ret)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in st.decorator_list:
                self.eval(dec, env)
            child_qn = f"{self.current_qn}.{st.name}" if self.current_qn \
                else st.name
            env[st.name] = AbsVal(ref=("def", child_qn), origin=st.lineno)
        elif isinstance(st, ast.ClassDef):
            env[st.name] = BOTTOM
        elif isinstance(st, (ast.Raise, ast.Assert)):
            if isinstance(st, ast.Assert):
                tv = self.eval(st.test, env)
                self.hooks.at_branch(st.test, tv, env, self)
            elif st.exc is not None:
                self.eval(st.exc, env)
        elif isinstance(st, ast.Delete):
            for tgt in st.targets:
                d = astutil.dotted(tgt)
                if d is not None:
                    env.pop(d, None)
        # Import/Global/Nonlocal/Pass/Break/Continue: no value flow
        # (break/continue are approximated by the loop join)

    def _loop(self, st, env: dict, ret: list, bind) -> None:
        """Fixpoint over a loop body: env_in = join(env_before,
        env_after_body), capped at MAX_LOOP_PASSES iterations."""
        state = dict(env)
        for _ in range(self.MAX_LOOP_PASSES):
            body_env = dict(state)
            bind(body_env)
            self.exec_stmts(st.body, body_env, ret)
            nxt = join_env(state, body_env)
            if nxt == state:
                break
            state = nxt
        env.clear()
        env.update(state)

    # -------------------------------------------------------- assignment
    def assign(self, tgt, val: AbsVal, env: dict) -> None:
        if isinstance(tgt, ast.Name):
            env[tgt.id] = val
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            n = len(tgt.elts)
            star_free = not any(isinstance(e, ast.Starred) for e in tgt.elts)
            if val.elems is not None and len(val.elems) == n and star_free:
                for e, v in zip(tgt.elts, val.elems):
                    self.assign(e, v, env)
            else:
                each = propagated(val)
                for e in tgt.elts:
                    self.assign(e, each, env)
        elif isinstance(tgt, ast.Starred):
            self.assign(tgt.value, propagated(val), env)
        elif isinstance(tgt, ast.Attribute):
            d = astutil.dotted(tgt)
            if d is not None:
                env[d] = val
        elif isinstance(tgt, ast.Subscript):
            d = astutil.dotted(tgt.value)
            if d is not None:
                # weak update: the container keeps its other elements
                cur = env.get(d, BOTTOM)
                env[d] = join(cur, propagated(val))

    # -------------------------------------------------------- expressions
    def eval(self, node, env: dict) -> AbsVal:
        if node is None or isinstance(node, ast.Constant):
            return BOTTOM
        if isinstance(node, ast.Name):
            val = env.get(node.id)
            if val is None:
                val = self._def_ref(node.id)
            self.hooks.at_load(node, node.id, val, env, self)
            return val
        if isinstance(node, ast.Attribute):
            d = astutil.dotted(node)
            if d is not None and d in env:
                val = env[d]
                self.hooks.at_load(node, d, val, env, self)
                return val
            base = self.eval(node.value, env)
            val = propagated(base, origin=node.lineno)
            self.hooks.at_load(node, d, val, env, self)
            return val
        if isinstance(node, ast.Subscript):
            base = self.eval(node.value, env)
            self.eval(node.slice, env)
            return propagated(base, origin=node.lineno)
        if isinstance(node, ast.Call):
            return self.eval_call(node, env)
        if isinstance(node, (ast.Tuple, ast.List)):
            elems = tuple(self.eval(e, env) for e in node.elts)
            out = BOTTOM
            for e in elems:
                out = join(out, propagated(e))
            return replace(out, elems=elems)
        if isinstance(node, ast.Set):
            out = BOTTOM
            for e in node.elts:
                out = join(out, propagated(self.eval(e, env)))
            return out
        if isinstance(node, ast.Dict):
            keyvals = []
            out = BOTTOM
            for k, v in zip(node.keys, node.values):
                if k is not None:
                    self.eval(k, env)
                vv = self.eval(v, env)
                out = join(out, propagated(vv))
                key = k.value if isinstance(k, ast.Constant) else None
                keyvals.append((key, vv))
            override = self.hooks.at_dict(node, keyvals, env, self)
            return override if override is not None else out
        if isinstance(node, (ast.BinOp, ast.BoolOp, ast.Compare)):
            vals = []
            if isinstance(node, ast.BinOp):
                vals = [self.eval(node.left, env), self.eval(node.right, env)]
            elif isinstance(node, ast.BoolOp):
                vals = [self.eval(v, env) for v in node.values]
            else:
                vals = [self.eval(node.left, env)] + [
                    self.eval(c, env) for c in node.comparators]
            out = BOTTOM
            for v in vals:
                out = join(out, propagated(v, origin=node.lineno))
            return out
        if isinstance(node, ast.UnaryOp):
            return propagated(self.eval(node.operand, env), node.lineno)
        if isinstance(node, ast.IfExp):
            tv = self.eval(node.test, env)
            self.hooks.at_branch(node.test, tv, env, self)
            return join(self.eval(node.body, env),
                        self.eval(node.orelse, env))
        if isinstance(node, ast.JoinedStr):
            for part in node.values:
                if isinstance(part, ast.FormattedValue):
                    v = self.eval(part.value, env)
                    self.hooks.at_format(part, v, env, self)
            return BOTTOM
        if isinstance(node, ast.FormattedValue):
            v = self.eval(node.value, env)
            self.hooks.at_format(node, v, env, self)
            return BOTTOM
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            # comprehension scope: bindings live in a COPY of the env
            # (python gives comprehensions their own scope — the target
            # must neither leak out nor clobber an outer binding), and
            # the target varies per iteration exactly like a for-loop
            # target, tagged with the comprehension node as its binding
            # loop (the XF202 enclosure check accepts comprehensions)
            cenv = dict(env)
            for gen in node.generators:
                itv = self.eval(gen.iter, cenv)
                self.hooks.at_iter(gen.iter, itv, cenv, self)
                loopval = AbsVal(
                    tags=itv.tags | {"loopvar"}, fresh=itv.fresh,
                    loops=itv.loops | {id(node)}, origin=node.lineno,
                )
                self.assign(gen.target, loopval, cenv)
                for cond in gen.ifs:
                    self.eval(cond, cenv)
            if isinstance(node, ast.DictComp):
                self.eval(node.key, cenv)
                return propagated(self.eval(node.value, cenv), node.lineno)
            return propagated(self.eval(node.elt, cenv), node.lineno)
        if isinstance(node, ast.NamedExpr):
            val = self.eval(node.value, env)
            self.assign(node.target, val, env)
            return val
        if isinstance(node, ast.Starred):
            return self.eval(node.value, env)
        if isinstance(node, ast.Slice):
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    self.eval(part, env)
            return BOTTOM
        if isinstance(node, (ast.Await, ast.YieldFrom)):
            return self.eval(node.value, env)
        if isinstance(node, ast.Yield):
            if node.value is not None:
                self.eval(node.value, env)
            return BOTTOM
        if isinstance(node, ast.Lambda):
            return BOTTOM  # opaque; traced-lambda rules are syntactic
        return BOTTOM

    def eval_call(self, node: ast.Call, env: dict) -> AbsVal:
        fval = self.eval(node.func, env)
        argvals = [self.eval(a, env) for a in node.args]
        kwvals = {kw.arg: self.eval(kw.value, env) for kw in node.keywords}
        callee = astutil.canonical(astutil.call_name(node), self.aliases)
        res = self.hooks.at_call(node, callee, argvals, kwvals, env, self,
                                 fval)
        if res is not None:
            return res
        if self.hooks.propagate_returns:
            rv = self._local_return(callee, fval)
            if rv is not None:
                return propagated(rv, origin=node.lineno) if rv.ref is None \
                    else rv
        if fval.tags or fval.fresh:
            # a method call on a tainted object (x.sum(), x.reshape())
            # yields a tainted result — the callee rides the value
            return propagated(fval, origin=node.lineno)
        return BOTTOM

    # ----------------------------------------------- local-call resolution
    def _def_ref(self, name: str) -> AbsVal:
        """A bare Name that resolves (scope-aware) to exactly one
        visible function definition becomes a function reference
        (flow-sensitive bindings in env take precedence)."""
        if name not in self.by_name:
            return BOTTOM
        qns = astutil.resolve_scoped(name, self.current_qn, self.by_name)
        if len(qns) == 1:
            return AbsVal(ref=("def", qns[0]))
        return BOTTOM

    def _local_return(self, callee, fval: AbsVal) -> Optional[AbsVal]:
        """Join of return values of a module-local callee, analyzed in
        isolation (params at BOTTOM) and memoized. None = not local."""
        qns: list = []
        if fval.ref is not None and fval.ref[0] == "def":
            qns = [fval.ref[1]]
        elif callee is not None:
            simple = callee.split(".")[-1]
            if callee in (simple, f"self.{simple}", f"cls.{simple}"):
                qns = astutil.resolve_scoped(simple, self.current_qn,
                                             self.by_name)
        qns = [qn for qn in qns if qn in self.by_qn]
        if not qns or self._depth >= self.MAX_CALL_DEPTH:
            return None
        out = None
        for qn in qns:
            if qn in self._ret_stack:
                continue  # recursion: contribute nothing
            if qn not in self._ret_cache:
                self._ret_stack.add(qn)
                self._depth += 1
                try:
                    self._ret_cache[qn] = self.run_function(
                        qn, self.by_qn[qn])
                finally:
                    self._depth -= 1
                    self._ret_stack.discard(qn)
            rv = self._ret_cache[qn]
            out = rv if out is None else join(out, rv)
        return out
