"""xflowlint: project-native static analysis for xflow-tpu.

The reference xflow shipped zero correctness tooling — races and
protocol drift were found by crashing in production (PAPER.md, the
hand-rolled multithreaded workers). This repo has nine PRs of
invariants that are cheap to state and expensive to re-discover at
runtime: jit bodies must be pure (PR 2's perf_counter rule), every
program compiles exactly once per signature (PR 7's CompileRecorder
contract), cross-thread attributes are touched under a lock (the PR 8
JsonlAppender interleave), every `cfg.section.key` read resolves to a
config.py default, and every record flowing into the stamped JSONL
appender matches the schema tables in docs/OBSERVABILITY.md.

`xflow_tpu/analysis/` enforces those mechanically, from the AST alone
(stdlib `ast`; no new dependencies, nothing is imported or
executed), so `tools/smoke_lint.sh` can gate them in CI before the
unified-engine churn the ROADMAP plans. See docs/STATIC_ANALYSIS.md
for the rule catalog and the suppression/baseline workflow.

Layout:
- core.py      — Finding model, suppression parsing, baseline files,
                 the Project/Module source graph every pass shares
- passes/      — one module per rule family (jit purity, recompile
                 hazards, thread-safety lockset, config cross-check,
                 JSONL schema drift, shell strict-mode)
- tools/xflowlint.py — the CLI (repo-wide lint, --baseline gating)
"""

from xflow_tpu.analysis.core import (  # noqa: F401
    Baseline,
    Finding,
    Module,
    Project,
    run_passes,
)
