"""xflowlint: project-native static analysis for xflow-tpu.

The reference xflow shipped zero correctness tooling — races and
protocol drift were found by crashing in production (PAPER.md, the
hand-rolled multithreaded workers). This repo has nine PRs of
invariants that are cheap to state and expensive to re-discover at
runtime: jit bodies must be pure (PR 2's perf_counter rule), every
program compiles exactly once per signature (PR 7's CompileRecorder
contract), cross-thread attributes are touched under a lock (the PR 8
JsonlAppender interleave), every `cfg.section.key` read resolves to a
config.py default, and every record flowing into the stamped JSONL
appender matches the schema tables in docs/OBSERVABILITY.md.

`xflow_tpu/analysis/` enforces those mechanically in two tiers: the
AST tier works from stdlib `ast` alone (no new dependencies, nothing
imported or executed — lints without jax, on scratch copies), and the
IR tier (ir.py) deliberately lowers the engine builders' jitted
programs to jaxprs in a pinned CPU subprocess — trace-only, no
execution — for the semantic rules (XF8xx) and the fusion-worklist /
contracts-v2 artifacts the AST cannot state. `tools/smoke_lint.sh`
gates both in CI before the unified-engine churn the ROADMAP plans.
See docs/STATIC_ANALYSIS.md for the rule catalog, the tier contract,
and the suppression/baseline workflow.

Layout:
- core.py      — Finding model, suppression parsing, baseline files,
                 the Project/Module source graph every pass shares
- dataflow.py  — the flow-sensitive abstract interpreter
- ir.py        — the IR-tier extractor (subprocess; jaxpr facts)
- passes/      — one module per rule family (jit purity, recompile
                 hazards, thread-safety lockset, config cross-check,
                 JSONL schema drift, shell strict-mode, sharding
                 contracts, host-sync taint, IR rules)
- tools/xflowlint.py — the CLI (repo-wide lint, --baseline gating,
                 artifact modes)
"""

from xflow_tpu.analysis.core import (  # noqa: F401
    Baseline,
    Finding,
    Module,
    Project,
    run_passes,
)
