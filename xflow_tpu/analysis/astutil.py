"""Small AST helpers shared by the xflowlint passes."""

from __future__ import annotations

import ast
from typing import Iterator, Optional


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None. `self.x.y`
    renders as 'self.x.y'; calls/subscripts break the chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Attribute):  # unreachable, kept for clarity
        return None
    else:
        return None
    return ".".join(reversed(parts))


def call_name(call: ast.Call) -> Optional[str]:
    """Dotted name of a call's callee ('jax.jit', 'print', ...)."""
    return dotted(call.func)


def walk_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Like ast.walk over a function body, but does NOT descend into
    nested function/class definitions (they are separate scopes the
    call-graph handles explicitly)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(child))


def parent_map(tree: ast.AST) -> dict:
    """child node -> parent node, for lexical-context questions."""
    parents: dict = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def enclosing(node: ast.AST, parents: dict, kinds: tuple) -> Optional[ast.AST]:
    """Nearest ancestor of one of `kinds` (or None)."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = parents.get(cur)
    return None


def in_loop(node: ast.AST, parents: dict, stop_at: tuple = ()) -> bool:
    """Whether `node` sits inside a for/while body, without crossing a
    function boundary (a loop in an outer function does not count)."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda) + stop_at):
            return False
        cur = parents.get(cur)
    return False


def import_aliases(tree: ast.AST) -> dict:
    """local name -> canonical dotted origin, from import statements:
    `import numpy as np` -> {np: numpy}; `import jax.numpy as jnp` ->
    {jnp: jax.numpy}; `from time import perf_counter as pc` ->
    {pc: time.perf_counter}. Lets rule tables match canonical names
    (`time.perf_counter`) whatever the module imported them as."""
    amap: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                amap[local] = a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                amap[a.asname or a.name] = f"{node.module}.{a.name}"
    return amap


def canonical(name: Optional[str], aliases: dict) -> Optional[str]:
    """Rewrite a dotted name's first component through the import-alias
    map ('np.random.seed' -> 'numpy.random.seed')."""
    if not name:
        return name
    head, _, rest = name.partition(".")
    origin = aliases.get(head)
    if origin is None or origin == head:
        return name
    return f"{origin}.{rest}" if rest else origin


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def defs_by_name(defs: list) -> dict:
    """simple name -> [qualnames] over a func_defs() list."""
    by_name: dict = {}
    for qn, _node, _cls in defs:
        by_name.setdefault(qn.split(".")[-1], []).append(qn)
    return by_name


def resolve_scoped(simple: str, caller_qn: str, by_name: dict) -> list:
    """Scope-aware name resolution: among same-named definitions, pick
    the ones whose defining scope is an ancestor of the caller's scope,
    preferring the innermost (two `def one(...)` in different functions
    must never cross-link — that is how a host helper would get marked
    jit-reachable). Falls back to every candidate for `self.x` refs."""
    cands = by_name.get(simple, [])
    if len(cands) <= 1:
        return list(cands)
    visible = []
    for c in cands:
        scope = c.rsplit(".", 1)[0] if "." in c else ""
        if scope == "" or caller_qn == scope or caller_qn.startswith(
                scope + "."):
            visible.append((len(scope.split(".")) if scope else 0, c))
    if not visible:
        return list(cands)
    best = max(d for d, _c in visible)
    return [c for d, c in visible if d == best]


def scope_sites(tree: ast.AST, defs: list):
    """Yields (caller qualname, node) for every node, attributed to its
    innermost enclosing function ('' = module level)."""
    covered: dict = {}
    for qn, node, _cls in defs:
        for sub in walk_scope(node):
            covered.setdefault(id(sub), (qn, sub))
    # module-level statements (not inside any def)
    seen_ids = set(covered)
    for node in ast.walk(tree):
        if id(node) not in seen_ids and not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            covered.setdefault(id(node), ("", node))
    return covered.values()


def local_call_graph(defs: list) -> dict:
    """qualname -> set of callee qualnames (module-local, scope-aware:
    a call binds to the innermost visible same-named definition)."""
    by_name = defs_by_name(defs)
    graph: dict = {}
    for qn, node, _cls in defs:
        callees: set = set()
        for sub in walk_scope(node):
            if not isinstance(sub, ast.Call):
                continue
            cn = call_name(sub)
            if cn is None:
                continue
            simple = cn.split(".")[-1]
            if cn == simple or cn == f"self.{simple}" or cn == f"cls.{simple}":
                callees.update(resolve_scoped(simple, qn, by_name))
        graph[qn] = callees
    return graph


def reachable(roots: set, graph: dict) -> set:
    seen = set(roots)
    stack = list(roots)
    while stack:
        cur = stack.pop()
        for nxt in graph.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return seen


def func_defs(tree: ast.AST) -> list:
    """Every (qualname, node, class_name) function/method in a module.
    Qualnames use '.' ('Cls.method', 'outer.inner')."""
    out = []

    def visit(node: ast.AST, prefix: str, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                out.append((qn, child, cls))
                visit(child, qn + ".", cls)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.", child.name)
            else:
                visit(child, prefix, cls)

    visit(tree, "", None)
    return out
