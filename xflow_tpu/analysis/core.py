"""Shared analyzer core: source model, finding model, suppressions,
baseline files, and the pass registry/driver.

Everything works from text + `ast` — the analyzer never imports the
code under analysis (a lint run must not depend on jax being
importable, and must be able to lint a scratch copy of a module
without executing it).

Suppressions (docs/STATIC_ANALYSIS.md):
- `# xflowlint: disable=XF101` on the offending line silences the
  named rule(s) (comma-separated) for that line only;
- `# xflowlint: disable-file=XF201` anywhere in a file silences the
  rule(s) for the whole file (use for tools where a rule's premise —
  e.g. "jit compiles more than once" — is the point of the file).

Baseline (`tools/xflowlint_baseline.json`): legacy findings are
recorded as (rule, path, message) entries with a human reason, so the
CI gate fails on *growth* (a new finding) and on *staleness* (a fixed
finding whose entry was not removed) rather than on existence. Line
numbers are deliberately not part of the fingerprint — an unrelated
edit above a baselined finding must not break the gate.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import json
import os
import re
from dataclasses import dataclass
from typing import Callable, Iterable, Optional

SUPPRESS_RE = re.compile(
    r"#\s*xflowlint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,\- ]+)"
)


@dataclass(frozen=True)
class Finding:
    """One diagnostic: rule id, location, message, and a fix hint."""

    rule: str
    path: str  # repo-relative, '/'-separated (stable across machines)
    line: int
    message: str
    hint: str = ""
    severity: str = "error"

    def fingerprint(self) -> tuple:
        """Baseline identity: line numbers excluded on purpose."""
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        sev = "" if self.severity == "error" else f" [{self.severity}]"
        out = f"{self.path}:{self.line}: {self.rule}{sev}: {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


class _SuppressionTable:
    """Shared `# xflowlint: disable[-file]=` semantics — ONE parser and
    ONE `suppressed()` so Python and shell sources cannot drift (the
    `all` wildcard behaves identically in both)."""

    def _parse_suppressions(self) -> None:
        self.line_suppress: dict[int, set] = {}
        self.file_suppress: set = set()
        for i, line in enumerate(self.lines, 1):
            m = SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
            if m.group(1) == "disable-file":
                self.file_suppress |= rules
            else:
                self.line_suppress.setdefault(i, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppress or "all" in self.file_suppress:
            return True
        at = self.line_suppress.get(line, ())
        return rule in at or "all" in at


class Module(_SuppressionTable):
    """One parsed Python source file plus its suppression table."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.syntax_error: Optional[str] = None
        try:
            self.tree = ast.parse(source, filename=relpath)
        except SyntaxError as e:  # surfaced as its own finding (XF001)
            self.syntax_error = f"{e.msg} (line {e.lineno})"
        self._parse_suppressions()


class ShellScript(_SuppressionTable):
    """One shell script (config cross-check + strict-mode pass input)."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self._parse_suppressions()


DEFAULT_PY_GLOBS = (
    "xflow_tpu/**/*.py",
    "tools/*.py",
    "bench.py",
    "conftest.py",
)
DEFAULT_SH_GLOBS = ("tools/*.sh",)
EXCLUDE_DIRS = ("__pycache__", ".git", ".pytest_cache", "tests/fixtures")


class Project:
    """The source set one lint run sees, with the repo-root anchors the
    cross-checking passes need (config.py, docs/OBSERVABILITY.md)."""

    def __init__(self, root: str, modules: list, shell_scripts: list,
                 full_tree: bool = True):
        self.root = root
        self.modules: list[Module] = modules
        self.shell_scripts: list[ShellScript] = shell_scripts
        # dead-key analysis (XF402) is only sound when the whole tree
        # was scanned — a partial lint would report every key dead
        self.full_tree = full_tree
        self.config_path = os.path.join(root, "xflow_tpu", "config.py")
        self.schema_doc_path = os.path.join(root, "docs", "OBSERVABILITY.md")

    @classmethod
    def load(cls, root: str, paths: Optional[Iterable[str]] = None) -> "Project":
        """Load the default source set under `root`, or an explicit
        file/dir list (relative to cwd or absolute)."""
        root = os.path.abspath(root)
        py_files: list[str] = []
        sh_files: list[str] = []
        full_tree = not paths
        if paths:
            for p in paths:
                p = os.path.abspath(p)
                if os.path.isdir(p):
                    for dirpath, dirnames, filenames in os.walk(p):
                        dirnames[:] = [d for d in dirnames
                                       if d not in ("__pycache__", ".git")]
                        for fn in sorted(filenames):
                            fp = os.path.join(dirpath, fn)
                            if fn.endswith(".py"):
                                py_files.append(fp)
                            elif fn.endswith(".sh"):
                                sh_files.append(fp)
                elif p.endswith(".sh"):
                    sh_files.append(p)
                else:
                    py_files.append(p)
        else:
            for pat in DEFAULT_PY_GLOBS:
                py_files.extend(_glob_under(root, pat))
            for pat in DEFAULT_SH_GLOBS:
                sh_files.extend(_glob_under(root, pat))
        modules = []
        for fp in sorted(set(py_files)):
            rel = _rel_to(fp, root)
            modules.append(Module(fp, rel, _read(fp)))
        scripts = []
        for fp in sorted(set(sh_files)):
            rel = _rel_to(fp, root)
            scripts.append(ShellScript(fp, rel, _read(fp)))
        return cls(root, modules, scripts, full_tree=full_tree)


def _read(path: str) -> str:
    with open(path, encoding="utf-8", errors="replace") as f:
        return f.read()


def _rel_to(path: str, root: str) -> str:
    try:
        rel = os.path.relpath(path, root)
    except ValueError:
        rel = path
    return path if rel.startswith("..") else rel


def _glob_under(root: str, pattern: str) -> list:
    """`**`-aware glob rooted at `root`, skipping EXCLUDE_DIRS."""
    out = []
    if "**" in pattern:
        head = pattern.split("**", 1)[0].rstrip("/")
        base = os.path.join(root, head) if head else root
        tail = pattern.split("**", 1)[1].lstrip("/")
        for dirpath, dirnames, filenames in os.walk(base):
            rel_dir = os.path.relpath(dirpath, root).replace(os.sep, "/")
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            if any(x in rel_dir.split("/") for x in ("__pycache__",)):
                continue
            for fn in filenames:
                rel = (rel_dir + "/" + fn) if rel_dir != "." else fn
                if fnmatch.fnmatch(fn, tail) or fnmatch.fnmatch(rel, pattern):
                    out.append(os.path.join(dirpath, fn))
    else:
        import glob as _glob

        out = _glob.glob(os.path.join(root, pattern))
    return [p for p in out if not _excluded(p)]


def _excluded(path: str) -> bool:
    """EXCLUDE_DIRS entries match path components ('__pycache__') or
    '/'-joined sub-paths ('tests/fixtures')."""
    norm = path.replace(os.sep, "/")
    comps = norm.split("/")
    for x in EXCLUDE_DIRS:
        if "/" in x:
            if f"/{x}/" in f"/{norm}/":
                return True
        elif x in comps:
            return True
    return False


# ---------------------------------------------------------------- baseline


@dataclass
class BaselineEntry:
    rule: str
    path: str
    message: str
    reason: str = ""


class Baseline:
    """Checked-in legacy findings: the gate fails on growth (new
    finding) and staleness (entry whose finding no longer fires)."""

    def __init__(self, entries: Optional[list] = None):
        self.entries: list[BaselineEntry] = list(entries or [])

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path) as f:
            data = json.load(f)
        entries = [
            BaselineEntry(
                rule=e["rule"], path=e["path"], message=e["message"],
                reason=e.get("reason", ""),
            )
            for e in data.get("entries", [])
        ]
        return cls(entries)

    def save(self, path: str) -> None:
        data = {
            "comment": (
                "xflowlint baseline: legacy findings accepted with a "
                "reason. The CI gate fails on NEW findings and on STALE "
                "entries (fixed findings must be removed from here). "
                "Regenerate with tools/xflowlint.py --write-baseline "
                "after auditing every entry."
            ),
            "entries": [dataclasses.asdict(e) for e in sorted(
                self.entries, key=lambda e: (e.path, e.rule, e.message))],
        }
        with open(path, "w") as f:
            json.dump(data, f, indent=2, sort_keys=False)
            f.write("\n")

    def split(self, findings: list, only_rules: Optional[set] = None,
              only_paths: Optional[set] = None) -> tuple:
        """-> (new_findings, baselined_findings, stale_entries).

        `only_rules` scopes the STALENESS check to entries of the rules
        that actually ran — a `--rules XF301` run must not report an
        XF401 entry stale just because the config pass was skipped.
        `only_paths` scopes it the same way to the files that were
        actually scanned (a `--changed` run must not report an entry in
        an untouched file stale)."""
        fps = {}
        for f in findings:
            fps.setdefault((f.rule, f.path, f.message), []).append(f)
        known = {(e.rule, e.path, e.message) for e in self.entries}
        new = [f for f in findings
               if (f.rule, f.path, f.message) not in known]
        base = [f for f in findings
                if (f.rule, f.path, f.message) in known]
        stale = [e for e in self.entries
                 if (e.rule, e.path, e.message) not in fps
                 and (only_rules is None or e.rule in only_rules)
                 and (only_paths is None or e.path in only_paths)]
        return new, base, stale


# ------------------------------------------------------------ pass driver

# rules whose analysis only runs on FULL-tree scans (a partial scan
# cannot fire them, so a partial scan must not call their baseline
# entries stale either — the --changed pre-commit path)
FULL_TREE_RULES = ("XF402",)

# rules produced by the IR tier (analysis/ir.py): like FULL_TREE_RULES,
# a run that did not include the tier must not call their baseline
# entries stale
IR_RULES = ("XF801", "XF802", "XF803", "XF804")

# populated by xflow_tpu.analysis.passes at import; maps pass name ->
# (runner, rule ids, scope) so the CLI can list and select. scope
# "module" = findings derive from one file at a time (parallelizable
# across a worker pool); "project" = needs the whole source set at
# once (cross-module comparisons, dead-key analysis); "ir" = the
# jaxpr tier (analysis/ir.py) — runs in-process only when the caller
# opts into the "ir" tier, never in the worker pool.
PASS_REGISTRY: dict[str, tuple] = {}


def register_pass(name: str, rules: tuple, scope: str = "module") -> Callable:
    assert scope in ("module", "project", "ir"), scope

    def deco(fn: Callable) -> Callable:
        PASS_REGISTRY[name] = (fn, rules, scope)
        return fn

    return deco


def _run_selected(project: Project, pass_names, only_rules: Optional[set],
                  with_syntax: bool) -> list:
    """Raw findings (no suppression/dedup) from the named passes."""
    findings: list[Finding] = []
    if with_syntax:
        for mod in project.modules:
            if mod.syntax_error is None:
                continue
            # XF001 honors --rules like any other rule
            if only_rules is not None and "XF001" not in only_rules:
                continue
            findings.append(Finding(
                rule="XF001", path=mod.relpath, line=1,
                message=f"syntax error: {mod.syntax_error}",
                hint="xflowlint needs parseable sources to analyze",
            ))
    for name in sorted(pass_names):
        runner, rules, _scope = PASS_REGISTRY[name]
        if only_rules is not None and not (set(rules) & only_rules):
            continue
        for f in runner(project):
            if only_rules is not None and f.rule not in only_rules:
                continue
            findings.append(f)
    return findings


def _mp_worker(payload) -> list:
    """Pool worker: lint one chunk of files with the module-scope
    passes. Receives plain paths (ASTs don't pickle; re-parsing a chunk
    is cheap) and returns raw findings."""
    root, paths, pass_names, only = payload
    import xflow_tpu.analysis.passes  # noqa: F401  (registers passes)

    sub = Project.load(root, paths)
    return _run_selected(sub, pass_names,
                         set(only) if only is not None else None,
                         with_syntax=True)


def _run_parallel(project: Project, only_rules: Optional[set],
                  jobs: int, extra_passes: list) -> list:
    """Module-scope passes fan out over a fork pool (one chunk of files
    per worker); project-scope passes (plus any opted-in IR-tier
    passes) run in-process on the full tree. Output is merged raw
    findings — identical to the serial path after the shared
    suppress/dedup/sort."""
    import multiprocessing

    module_passes = [n for n, (_f, _r, s) in PASS_REGISTRY.items()
                     if s == "module"]
    project_passes = [n for n, (_f, _r, s) in PASS_REGISTRY.items()
                      if s == "project"] + extra_passes
    paths = [m.path for m in project.modules] \
        + [s.path for s in project.shell_scripts]
    chunks = [c for c in (paths[i::jobs] for i in range(jobs)) if c]
    only = sorted(only_rules) if only_rules is not None else None
    payloads = [(project.root, c, module_passes, only) for c in chunks]
    ctx = multiprocessing.get_context("fork")
    with ctx.Pool(processes=len(chunks)) as pool:
        # dispatch the workers FIRST, then run the project-scope passes
        # while they execute: wall-clock is max(project, module), not
        # the sum
        async_result = pool.map_async(_mp_worker, payloads)
        findings = _run_selected(project, project_passes, only_rules,
                                 with_syntax=False)
        for chunk_findings in async_result.get():
            findings.extend(chunk_findings)
    return findings


def run_passes(project: Project, only_rules: Optional[set] = None,
               jobs: int = 1, tiers: tuple = ("ast",)) -> list:
    """Run every registered pass of the selected `tiers`, apply
    suppressions, return findings sorted by (path, line, rule).
    Unparseable files yield XF001. `jobs` > 1 fans the per-module
    passes out over a process pool (same findings, same order — the
    pre-commit speed path); any pool failure falls back to the serial
    sweep. `tiers` defaults to the AST tier only; adding "ir" also
    runs the jaxpr-tier passes (scope="ir", always in-process)."""
    import xflow_tpu.analysis.passes  # noqa: F401  (registers passes)

    selected = {n for n, (_f, _r, s) in PASS_REGISTRY.items()
                if s in ("module", "project") and "ast" in tiers
                or s == "ir" and "ir" in tiers}
    ir_passes = [n for n in selected
                 if PASS_REGISTRY[n][2] == "ir"]
    raw: list[Finding]
    if jobs > 1 and len(project.modules) + len(project.shell_scripts) > 1 \
            and "ast" in tiers:
        try:
            raw = _run_parallel(project, only_rules, jobs, ir_passes)
        except Exception:  # pragma: no cover — pool/platform failure
            raw = _run_selected(project, selected, only_rules,
                                with_syntax=True)
    else:
        raw = _run_selected(project, selected, only_rules,
                            with_syntax="ast" in tiers)
    sources = {m.relpath: m for m in project.modules}
    sources.update({s.relpath: s for s in project.shell_scripts})
    findings = []
    for f in raw:
        src = sources.get(f.path)
        if src is not None and src.suppressed(f.rule, f.line):
            continue
        findings.append(f)
    # dedup: two passes (or one fixpoint sweep visiting a loop body
    # twice) must not double-report one defect
    seen: set = set()
    unique = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule,
                                             f.message)):
        key = (f.rule, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique
