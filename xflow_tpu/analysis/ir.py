"""The IR tier: jaxpr-grounded semantic extraction for xflowlint.

The AST tier (core.py + passes/) deliberately never imports the code
under analysis. This module is the OTHER tier, with the opposite
contract stated just as strictly: it imports the engine modules under
``JAX_PLATFORMS=cpu`` and lowers each step builder's jitted programs to
jaxprs on abstract ``jax.ShapeDtypeStruct`` inputs derived from the
config schema — **no execution, no TPU, trace-only** (tracing and
``.lower()`` build the IR; nothing is compiled for or dispatched to a
device, and ``cost_analysis`` runs client-side on the lowered-but-not-
compiled module).

It is designed to run in a SUBPROCESS (``python -m
xflow_tpu.analysis.ir --root R``) so that

- the jax environment is pinned (CPU platform, a forced 8-device host
  platform so the ('data','table') = (4,2) mesh programs lower the
  same way on every machine — the worklist artifact must be
  byte-stable),
- a scratch tree under ``--root`` is imported INSTEAD of the installed
  package (PYTHONPATH isolation), and
- an unimportable tree or a jax-less machine degrades to a clean
  "unavailable" verdict (exit 5) the AST tier can report and continue
  past — scratch-copy AST-only linting keeps working.

What it extracts, per program in ``PROGRAMS`` (the four engine
builders' train/eval/predict programs across the model variants the
ROADMAP's kernel arc targets):

- op histogram, gather/scatter counts, dtype census, and flop/byte
  estimates (``lowered.cost_analysis()``) — the **contracts v2**
  section of ``tools/engine_contracts.json``;
- gather → elementwise-chain → scatter-add subgraphs over table-sized
  operands, with shapes/dtypes/byte estimates and source anchors —
  the **fusion worklist** (``tools/fusion_worklist.json``), i.e. the
  Pallas kernel arc's machine-checked target list (XF801);
- widening ``convert_element_type`` ops over large operands (XF802);
- ``scan`` carries returned unchanged and stacked scan outputs no
  consumer reads (XF803);
- the lowered signature facts (donation per argument, sharding
  annotations present) the XF804 AST/IR cross-check compares against
  the AST tier's extracted contracts.

The jitted programs are captured through the builders' own
``recorder`` seam (telemetry.CompileRecorder): a capturing recorder
whose ``wrap(name, fn)`` raises, so the lazily-jitting builders
(GSPMD, fullshard) surrender their jit object at the wrap site without
the call ever executing.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

# primitives whose operand-0 is a table being read / written sparsely
GATHER_PRIMS = ("gather",)
SCATTER_PRIMS = ("scatter", "scatter-add", "scatter-mul", "scatter-max",
                 "scatter-min")
# a chain participant must touch at least this many elements to count
# as "the table" (filters per-row/per-batch scatters out of XF801)
MIN_TABLE_ELEMS = 1 << 16
# XF802 only cares about big operands (a scalar upcast is free)
MIN_CONVERT_ELEMS = 1 << 16
WIDENING = {("bfloat16", "float32"), ("float16", "float32"),
            ("bfloat16", "float64"), ("float16", "float64")}

# elementwise / selection primitives: a chain's "update math" between
# the gather and the scatter (FTRL/SGD are exactly these)
ELEMENTWISE_PRIMS = frozenset({
    "add", "add_any", "sub", "mul", "div", "neg", "abs", "sign", "sqrt",
    "rsqrt", "exp", "log", "log1p", "logistic", "tanh", "pow",
    "integer_pow", "max", "min", "select_n", "and", "or", "xor", "not",
    "eq", "ne", "lt", "le", "gt", "ge", "is_finite",
    "convert_element_type", "copy", "square",
})

# the program matrix: every entry lowers one recorder-named jit program
# of one engine builder under one config variant. Keys are
# "<recorder name>[<variant>]" — recorder names repeat across configs
# ("train_step" serves both the LR and FM variants), the bracket makes
# them unique and greppable.
PROGRAMS = (
    # key, engine module (repo-relative), builder, config overrides, batch
    ("train_step[lr]", "xflow_tpu/train/step.py", "single_train",
     {"model.name": "lr"}, "rowmajor"),
    ("predict[lr]", "xflow_tpu/train/step.py", "single_eval",
     {"model.name": "lr"}, "rowmajor"),
    ("train_step[fm]", "xflow_tpu/train/step.py", "single_train",
     {"model.name": "fm"}, "rowmajor"),
    # the kernel arc's marquee target: the sorted fused path (on CPU the
    # scatter+FTRL fusion falls back to gather/scatter + elementwise XLA
    # ops — exactly the chain the Pallas kernel replaces)
    ("train_step[fm.sorted]", "xflow_tpu/train/step.py", "single_train",
     {"model.name": "fm"}, "sorted_flat"),
    ("train_step.gspmd[lr]", "xflow_tpu/parallel/train_step.py",
     "gspmd_train", {"model.name": "lr"}, "rowmajor"),
    ("predict.gspmd[lr]", "xflow_tpu/parallel/train_step.py",
     "gspmd_eval", {"model.name": "lr"}, "rowmajor"),
    ("train_step.replicated[fm]", "xflow_tpu/parallel/sorted_sharded.py",
     "sorted_sharded_train", {"model.name": "fm"}, "sorted_stacked"),
    ("train_step.fullshard.fm[fm]",
     "xflow_tpu/parallel/sorted_fullshard.py", "fullshard_train",
     {"model.name": "fm"}, "fullshard"),
    ("predict.fullshard.fm[fm]",
     "xflow_tpu/parallel/sorted_fullshard.py", "fullshard_eval",
     {"model.name": "fm"}, "fullshard"),
)

# mesh shape every sharded program lowers against (forced host devices)
MESH_DATA, MESH_TABLE = 4, 2
FORCED_DEVICES = MESH_DATA * MESH_TABLE

EXIT_UNAVAILABLE = 5


class _Captured(Exception):
    """Raised by the capturing recorder at the wrap site: carries the
    jit object out of a lazily-jitting builder without executing it."""

    def __init__(self, name, fn):
        super().__init__(name)
        self.name, self.fn = name, fn


class _CapturingRecorder:
    def wrap(self, name, fn):
        raise _Captured(name, fn)


def _capture(thunk):
    """Run a builder (or its call seam) until recorder.wrap fires."""
    try:
        thunk()
    except _Captured as c:
        return c.name, c.fn
    raise RuntimeError("builder returned without reaching recorder.wrap")


# ------------------------------------------------------ abstract inputs


def _abstract_state(model, opt, cfg):
    """ShapeDtypeStruct TrainState via eval_shape — the real init
    traced abstractly, nothing allocated."""
    import jax

    from xflow_tpu.train.state import init_state

    return jax.eval_shape(lambda: init_state(model, opt, cfg))


def _with_shardings(tree, shardings):
    import jax

    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        tree, shardings)


def _rowmajor_batch(cfg, mesh=None):
    import jax
    import jax.numpy as jnp

    sds = jax.ShapeDtypeStruct
    B, F = cfg.data.batch_size, cfg.data.max_nnz
    sh = {}
    if mesh is not None:
        from xflow_tpu.parallel.mesh import batch_sharding

        sh = batch_sharding(mesh)
    mk = lambda k, shape, dt: sds(shape, dt, sharding=sh.get(k))
    return {
        "slots": mk("slots", (B, F), jnp.int32),
        "fields": mk("fields", (B, F), jnp.int32),
        "mask": mk("mask", (B, F), jnp.float32),
        "labels": mk("labels", (B,), jnp.float32),
        "row_mask": mk("row_mask", (B,), jnp.float32),
    }


def _sorted_flat_batch(cfg):
    """Single-device flat sorted plan (ops/sorted_table plan shapes)."""
    import jax
    import jax.numpy as jnp

    from xflow_tpu.ops.sorted_table import CHUNK, WINDOW

    sds = jax.ShapeDtypeStruct
    B, F = cfg.data.batch_size, cfg.data.max_nnz
    npad = (B * F // CHUNK + 2) * CHUNK
    n_win = cfg.num_slots // WINDOW
    return {
        "sorted_slots": sds((npad,), jnp.int32),
        "sorted_row": sds((npad,), jnp.int32),
        "sorted_mask": sds((npad,), jnp.float32),
        "win_off": sds((n_win + 1,), jnp.int32),
        "labels": sds((B,), jnp.float32),
        "row_mask": sds((B,), jnp.float32),
    }


def _sorted_stacked_batch(cfg, mesh):
    """Stacked per-data-shard plans [D, Np_l] (sorted_sharded path)."""
    import jax
    import jax.numpy as jnp

    from xflow_tpu.ops.sorted_table import CHUNK, WINDOW
    from xflow_tpu.parallel.mesh import DATA_AXIS, batch_sharding

    sds = jax.ShapeDtypeStruct
    sh = batch_sharding(mesh)
    B, F = cfg.data.batch_size, cfg.data.max_nnz
    D = mesh.shape[DATA_AXIS]
    rows = B // D
    npad = (rows * F // CHUNK + 2) * CHUNK
    n_win = cfg.num_slots // WINDOW
    mk = lambda k, shape, dt: sds(shape, dt, sharding=sh[k])
    return {
        "sorted_slots": mk("sorted_slots", (D, npad), jnp.int32),
        "sorted_row": mk("sorted_row", (D, npad), jnp.int32),
        "sorted_mask": mk("sorted_mask", (D, npad), jnp.float32),
        "win_off": mk("win_off", (D, n_win + 1), jnp.int32),
        "labels": mk("labels", (B,), jnp.float32),
        "row_mask": mk("row_mask", (B,), jnp.float32),
    }


def _fullshard_batch(cfg, mesh):
    import jax
    import jax.numpy as jnp

    from xflow_tpu.ops.sorted_table import WINDOW
    from xflow_tpu.parallel.mesh import (
        DATA_AXIS, TABLE_AXIS, batch_sharding,
    )
    from xflow_tpu.parallel.sorted_fullshard import fullshard_capacity

    sds = jax.ShapeDtypeStruct
    sh = batch_sharding(mesh)
    B = cfg.data.batch_size
    D, T = mesh.shape[DATA_AXIS], mesh.shape[TABLE_AXIS]
    cap = fullshard_capacity(cfg, mesh)
    wpo = (cfg.num_slots // WINDOW) // (D * T)
    mk = lambda k, shape, dt: sds(shape, dt, sharding=sh[k])
    return {
        "fs_slots": mk("fs_slots", (D, T, D, cap), jnp.int32),
        "fs_row": mk("fs_row", (D, T, D, cap), jnp.int32),
        "fs_mask": mk("fs_mask", (D, T, D, cap), jnp.float32),
        "fs_off": mk("fs_off", (D, T, D, wpo + 1), jnp.int32),
        "labels": mk("labels", (B,), jnp.float32),
        "row_mask": mk("row_mask", (B,), jnp.float32),
    }


# -------------------------------------------------------- program build


def _build_program(key, engine, builder, overrides, batch_kind):
    """-> (recorder name, jit object, (arg pytrees...), cfg)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from xflow_tpu.config import Config, override
    from xflow_tpu.models import get_model
    from xflow_tpu.optim import get_optimizer

    needs_mesh = builder not in ("single_train", "single_eval")
    ov = dict(overrides)
    if needs_mesh:
        ov.update({"mesh.data": MESH_DATA, "mesh.table": MESH_TABLE})
    cfg = override(Config(), **ov)
    model = get_model(cfg.model.name)
    opt = get_optimizer(cfg.optim.name)
    state = _abstract_state(model, opt, cfg)
    cap = _CapturingRecorder()

    if builder == "single_train":
        from xflow_tpu.train.step import make_train_step

        name, fn = _capture(lambda: make_train_step(
            model, opt, cfg, jit=True, recorder=cap))
        batch = _rowmajor_batch(cfg) if batch_kind == "rowmajor" \
            else _sorted_flat_batch(cfg)
        return name, fn, (state, batch), cfg
    if builder == "single_eval":
        from xflow_tpu.train.step import make_eval_step

        name, fn = _capture(lambda: make_eval_step(
            model, cfg, jit=True, recorder=cap))
        return name, fn, (state.tables, _rowmajor_batch(cfg)), cfg

    from xflow_tpu.parallel.mesh import make_mesh, state_shardings

    mesh = make_mesh(cfg)
    if builder == "gspmd_train":
        from xflow_tpu.parallel.train_step import make_sharded_train_step

        st = _with_shardings(state, state_shardings(state, mesh))
        batch = _rowmajor_batch(cfg, mesh)
        call = make_sharded_train_step(model, opt, cfg, mesh, recorder=cap)
        name, fn = _capture(lambda: call(st, batch))
        return name, fn, (st, batch), cfg
    if builder == "gspmd_eval":
        from xflow_tpu.parallel.train_step import make_sharded_eval_step

        st = _with_shardings(state, state_shardings(state, mesh))
        batch = _rowmajor_batch(cfg, mesh)
        call = make_sharded_eval_step(model, cfg, mesh, recorder=cap)
        name, fn = _capture(lambda: call(st.tables, batch))
        return name, fn, (st.tables, batch), cfg
    if builder == "sorted_sharded_train":
        from xflow_tpu.parallel.mesh import TABLE_AXIS
        from xflow_tpu.parallel.sorted_sharded import (
            make_sorted_sharded_train_step,
        )

        tsh = NamedSharding(mesh, P(TABLE_AXIS, None))
        rep = NamedSharding(mesh, P())
        st = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=tsh if getattr(x, "ndim", 0) >= 1 else rep),
            state)
        batch = _sorted_stacked_batch(cfg, mesh)
        name, fn = _capture(lambda: make_sorted_sharded_train_step(
            opt, cfg, mesh, recorder=cap))
        return name, fn, (st, batch), cfg
    if builder == "fullshard_train":
        from xflow_tpu.parallel.sorted_fullshard import (
            make_fullshard_train_step,
        )

        st = _with_shardings(state, state_shardings(state, mesh))
        batch = _fullshard_batch(cfg, mesh)
        call = make_fullshard_train_step(opt, cfg, mesh, recorder=cap)
        name, fn = _capture(lambda: call(st, batch))
        keys = ("fs_slots", "fs_row", "fs_mask", "fs_off", "labels",
                "row_mask")
        return name, fn, (st, {k: batch[k] for k in keys}), cfg
    if builder == "fullshard_eval":
        from xflow_tpu.parallel.sorted_fullshard import (
            make_fullshard_eval_step,
        )

        st = _with_shardings(state, state_shardings(state, mesh))
        batch = _fullshard_batch(cfg, mesh)
        call = make_fullshard_eval_step(cfg, mesh, recorder=cap)
        name, fn = _capture(lambda: call(st.tables, batch))
        keys = ("fs_slots", "fs_row", "fs_mask", "fs_off", "labels")
        return name, fn, (st.tables, {k: batch[k] for k in keys}), cfg
    raise ValueError(f"unknown builder kind {builder!r}")


# -------------------------------------------------------- jaxpr analysis


def _iter_eqns(jaxpr):
    """Every eqn in a jaxpr, recursing into sub-jaxpr params (pjit,
    scan, shard_map, custom_jvp, ...). Params hold either ClosedJaxprs
    (with a .jaxpr) or plain Jaxprs (with .eqns directly) — shard_map
    passes the latter."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            vs = v if isinstance(v, (list, tuple)) else [v]
            for x in vs:
                if hasattr(x, "eqns"):
                    yield from _iter_eqns(x)
                elif hasattr(getattr(x, "jaxpr", None), "eqns"):
                    yield from _iter_eqns(x.jaxpr)


def _src_frames(eqn, root):
    """Repo-relative (file, line) frames of an eqn's traceback,
    innermost first, excluding the analysis tier itself."""
    out = []
    tb = getattr(eqn.source_info, "traceback", None)
    if tb is None:
        return out
    prefix = os.path.abspath(root) + os.sep
    for fr in tb.frames:
        fn = fr.file_name
        if not fn.startswith(prefix):
            continue
        rel = fn[len(prefix):].replace(os.sep, "/")
        if rel.startswith("xflow_tpu/analysis/") or rel.startswith("tools/"):
            continue
        out.append((rel, fr.line_num))
    return out


def _anchor(frames, engine):
    """Innermost frame inside the program's engine module, else the
    innermost repo frame — the file:line a finding points at."""
    for rel, line in frames:
        if rel == engine:
            return [rel, line]
    return list(frames[0]) if frames else [engine, 1]


def _aval(var):
    return getattr(var, "aval", None)


def _nelems(aval):
    n = 1
    for d in aval.shape:
        n *= int(d)
    return n


def analyze_jaxpr(jaxpr, root, engine, table_names):
    """Semantic facts of one traced program's jaxpr.

    `table_names`: {shape tuple -> leaf name} from the abstract state,
    to label chains with the table they stream."""
    histogram: dict = {}
    dtype_census: dict = {}
    gathers: list = []
    scatters: list = []
    converts: list = []
    scans: list = []
    table_sweeps: dict = {}  # shape -> elementwise-eqn count at shape
    for eqn in _iter_eqns(jaxpr):
        name = eqn.primitive.name
        histogram[name] = histogram.get(name, 0) + 1
        for v in eqn.outvars:
            av = _aval(v)
            if av is not None and hasattr(av, "dtype"):
                dt = str(av.dtype)
                dtype_census[dt] = dtype_census.get(dt, 0) + 1
        if name in GATHER_PRIMS or name in SCATTER_PRIMS:
            op_av = _aval(eqn.invars[0]) if eqn.invars else None
            if op_av is None or _nelems(op_av) < MIN_TABLE_ELEMS:
                continue
            idx_av = _aval(eqn.invars[1]) if len(eqn.invars) > 1 else None
            # gather/scatter indices are [..., index_depth]: the
            # occurrence count is every dim but the trailing one
            occ = 0
            if idx_av is not None and idx_av.shape:
                occ = _nelems(idx_av) // max(int(idx_av.shape[-1]), 1)
            rec = {
                "shape": [int(d) for d in op_av.shape],
                "dtype": str(op_av.dtype),
                "occ": occ,
                "src": _anchor(_src_frames(eqn, root), engine),
            }
            (gathers if name in GATHER_PRIMS else scatters).append(rec)
        elif name == "convert_element_type":
            in_av = _aval(eqn.invars[0]) if eqn.invars else None
            out_av = _aval(eqn.outvars[0]) if eqn.outvars else None
            if in_av is None or out_av is None:
                continue
            pair = (str(getattr(in_av, "dtype", "")),
                    str(getattr(out_av, "dtype", "")))
            if pair in WIDENING and _nelems(in_av) >= MIN_CONVERT_ELEMS:
                converts.append({
                    "from": pair[0], "to": pair[1],
                    "shape": [int(d) for d in in_av.shape],
                    "elems": _nelems(in_av),
                    "src": _anchor(_src_frames(eqn, root), engine),
                })
        elif name == "scan":
            scans.append(_analyze_scan(eqn, root, engine))
        if name in ELEMENTWISE_PRIMS:
            for v in eqn.outvars:
                av = _aval(v)
                if av is not None and _nelems(av) >= MIN_TABLE_ELEMS:
                    shp = tuple(int(d) for d in av.shape)
                    table_sweeps[shp] = table_sweeps.get(shp, 0) + 1
    chains = _chains(gathers, scatters, table_sweeps, table_names)
    scans = [s for s in scans if s["dead_outputs"] or s["identity_carries"]]
    return {
        "op_histogram": dict(sorted(histogram.items())),
        "dtype_census": dict(sorted(dtype_census.items())),
        "gathers": len(gathers),
        "scatters": len(scatters),
        "chains": chains,
        "converts": converts,
        "scans": scans,
    }


def _analyze_scan(eqn, root, engine):
    """Dead stacked outputs (DropVar pasts the carry) + carry leaves the
    body returns unchanged (the leaf rides every iteration for
    nothing)."""
    num_carry = int(eqn.params.get("num_carry", 0))
    num_consts = int(eqn.params.get("num_consts", 0))
    dead = []
    for i, v in enumerate(eqn.outvars[num_carry:]):
        if type(v).__name__ == "DropVar":
            dead.append(i)
    identity = []
    body = eqn.params.get("jaxpr")
    if body is not None:
        j = body.jaxpr
        carried_in = j.invars[num_consts:num_consts + num_carry]
        for i, (vin, vout) in enumerate(zip(carried_in,
                                            j.outvars[:num_carry])):
            if vin is vout:
                identity.append(i)
    return {
        "dead_outputs": dead,
        "identity_carries": identity,
        "length": int(eqn.params.get("length", 0) or 0),
        "src": _anchor(_src_frames(eqn, root), engine),
    }


def _chains(gathers, scatters, table_sweeps, table_names):
    """Group gather/scatter records into per-(shape, dtype) chains —
    the gather → elementwise → scatter-add subgraphs the fusion
    worklist records. A chain needs at least one scatter (a forward-
    only gather is not an update path)."""
    by_key: dict = {}
    for kind, recs in (("gather", gathers), ("scatter", scatters)):
        for r in recs:
            key = (tuple(r["shape"]), r["dtype"])
            ent = by_key.setdefault(key, {"gather": [], "scatter": []})
            ent[kind].append(r)
    chains = []
    for (shape, dtype), ent in sorted(by_key.items()):
        if not ent["scatter"]:
            continue
        table = table_names.get(tuple(shape))
        sweep_shape = tuple(shape)
        if table is None:
            # shard_map bodies see PER-SHARD table shapes: match a state
            # leaf with the same trailing dims whose slot dim this shape
            # divides (the worklist entry reports the shard shape — the
            # per-device kernel target). The optimizer sweep runs on
            # the FULL table outside the shard_map body, so the chain's
            # elementwise ops are counted at the matched full shape.
            for full_shape, name in sorted(table_names.items()):
                if (len(full_shape) == len(shape)
                        and full_shape[1:] == tuple(shape[1:])
                        and shape[0] and full_shape[0] % shape[0] == 0):
                    table = f"{name}/shard"
                    sweep_shape = full_shape
                    break
        itemsize = 2 if dtype in ("bfloat16", "float16") else 4
        nbytes = lambda shp: itemsize * int(math.prod(shp))
        table_bytes = nbytes(shape)
        occ = max([r["occ"] for r in ent["gather"] + ent["scatter"]] or [0])
        row_bytes = table_bytes // shape[0] if shape else itemsize
        sweeps = table_sweeps.get(tuple(shape), 0) \
            or table_sweeps.get(sweep_shape, 0)
        n_g, n_s = len(ent["gather"]), len(ent["scatter"])
        chains.append({
            "table": table or "?",
            "table_shape": list(shape),
            "table_dtype": dtype,
            "table_bytes": table_bytes,
            "occurrences": occ,
            "gathers": n_g,
            "scatters": n_s,
            "elementwise_table_ops": sweeps,
            # rough HBM traffic of the unfused chain: each gather/
            # scatter moves ~occ stored rows, each table-wide
            # elementwise op re-streams the (full) table once
            "est_bytes_per_step": (n_g + n_s) * occ * row_bytes
            + sweeps * nbytes(sweep_shape),
            "gather_at": ent["gather"][0]["src"] if ent["gather"] else None,
            "scatter_at": ent["scatter"][0]["src"],
        })
    return chains


# ------------------------------------------------------------ extraction


def extract_program(key, engine, builder, overrides, batch_kind, root):
    name, fn, args, cfg = _build_program(key, engine, builder, overrides,
                                         batch_kind)
    traced = fn.trace(*args)
    facts = analyze_jaxpr(traced.jaxpr.jaxpr, root, engine,
                          _table_names(args[0]))
    lowered = traced.lower()
    cost = None
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if ca:
            cost = {
                "flops": float(ca.get("flops", 0.0)),
                "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
            }
    except Exception:
        cost = None
    donated = sorted(
        i for i, arg in enumerate(args)
        if _all_donated(traced, i, len(args)))
    mlir_text = lowered.as_text()
    has_shardings = "mhlo.sharding" in mlir_text \
        or "sdy.sharding" in mlir_text
    facts.update({
        "engine": engine,
        "recorder_name": name,
        "config": dict(sorted(overrides.items())),
        "batch": batch_kind,
        "donated_args": donated,
        "has_sharding_annotations": bool(has_shardings),
        "cost": cost,
    })
    return facts


def _table_names(state_like):
    """{leaf shape -> table name} for chain labeling."""
    tables = getattr(state_like, "tables", state_like)
    out = {}
    if isinstance(tables, dict):
        for name, leaf in sorted(tables.items()):
            out[tuple(int(d) for d in leaf.shape)] = name
    return out


def _all_donated(traced, idx, n_args):
    """Whether every leaf of top-level positional arg `idx` is donated
    in the lowered signature (args_info is the ground truth — the
    Traced.donate_argnums attribute does not report user argnums)."""
    import jax

    infos = traced.args_info
    if isinstance(infos, tuple) and len(infos) == 2 \
            and isinstance(infos[1], dict):
        infos = infos[0]  # ((args...), kwargs) → positional args
    leaves = jax.tree.leaves(infos[idx]) if idx < len(infos) else []
    return bool(leaves) and all(getattr(a, "donated", False)
                                for a in leaves)


def extract_all(root):
    """Lower and analyze every program in PROGRAMS. Returns the facts
    dict (deterministic given a fixed jax version and device count)."""
    import jax

    programs: dict = {}
    errors: list = []
    for key, engine, builder, overrides, batch_kind in PROGRAMS:
        try:
            programs[key] = extract_program(key, engine, builder,
                                            overrides, batch_kind, root)
        except Exception as e:  # one broken builder must not hide the rest
            errors.append({"program": key, "error": f"{type(e).__name__}: {e}"})
    return {
        "ok": True,
        "jax_version": jax.__version__,
        "device_count": len(jax.devices()),
        "mesh": [MESH_DATA, MESH_TABLE],
        "programs": programs,
        "errors": errors,
    }


# ------------------------------------------------------------------ CLI


def _pin_env():
    """Pin the jax environment BEFORE jax import: CPU platform, forced
    8-device host platform (deterministic mesh programs everywhere)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={FORCED_DEVICES}")
    os.environ["XLA_FLAGS"] = " ".join(flags)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="xflow-ir", description=__doc__)
    ap.add_argument("--root", default=os.getcwd(),
                    help="tree whose engine modules to import and lower")
    ap.add_argument("--probe", action="store_true",
                    help="only report availability (jax importable, "
                         "tree importable)")
    args = ap.parse_args(argv)
    root = os.path.abspath(args.root)
    _pin_env()
    # the --root tree, not any installed copy, must win the import
    sys.path.insert(0, root)
    for m in [m for m in sys.modules
              if m == "xflow_tpu" or m.startswith("xflow_tpu.")]:
        if m.startswith("xflow_tpu.analysis") or m == "xflow_tpu":
            continue
        del sys.modules[m]
    try:
        import jax
    except Exception as e:
        print(json.dumps({"ok": False,
                          "reason": f"jax unavailable: {type(e).__name__}"}))
        return EXIT_UNAVAILABLE
    # ambient site config can pin another platform OVER the env var
    # (the axon images); the config API wins when set before the first
    # device use, so pin CPU both ways
    for key, val in (("jax_platforms", "cpu"),
                     ("jax_num_cpu_devices", FORCED_DEVICES)):
        try:
            jax.config.update(key, val)
        except Exception:  # older jax without the knob: XLA_FLAGS holds
            pass
    try:
        import xflow_tpu.train.step as _step
    except Exception as e:
        print(json.dumps({
            "ok": False,
            "reason": f"tree not importable from {root}: "
                      f"{type(e).__name__}: {e}"}))
        return EXIT_UNAVAILABLE
    got = os.path.realpath(getattr(_step, "__file__", "") or "")
    if not got.startswith(os.path.realpath(root) + os.sep):
        # a partial scratch tree (no package __init__) silently resolves
        # to the installed copy — lowering THAT would attribute the
        # wrong tree's semantics to this root
        print(json.dumps({
            "ok": False,
            "reason": f"tree under {root} is not an importable package "
                      f"(import resolved to {got})"}))
        return EXIT_UNAVAILABLE
    if args.probe:
        print(json.dumps({"ok": True}))
        return 0
    facts = extract_all(root)
    print(json.dumps(facts, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
