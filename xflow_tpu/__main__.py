import sys

from xflow_tpu.launch.cli import main

if __name__ == "__main__":
    sys.exit(main())
