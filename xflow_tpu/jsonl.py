"""Lazy append-mode JSONL sink, shared by the metrics stream
(train/trainer.py MetricsLogger) and the bad-record quarantine
(data/libffm.py QuarantineWriter) so the lifecycle mechanics live once.

Lifecycle: the file opens on the FIRST record (creating the parent
directory — a path inside a not-yet-existing run dir must not crash the
construction), every record is flushed (a crash loses nothing already
appended), and `close()` flushes, closes, and returns the sink to its
lazy state — a later append transparently reopens in append mode
instead of writing to a closed handle. An empty path disables the sink
entirely (every call is a no-op)."""

from __future__ import annotations

import json
import os


class JsonlAppender:
    def __init__(self, path: str = ""):
        self._path = path
        self._f = None

    def append(self, record: dict) -> None:
        if not self._path:
            return
        if self._f is None:
            parent = os.path.dirname(self._path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._f = open(self._path, "a")
        self._f.write(json.dumps(record) + "\n")
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
