"""Lazy append-mode JSONL sink + truncation-tolerant reader, shared by
the metrics stream (train/trainer.py MetricsLogger) and the bad-record
quarantine (data/libffm.py QuarantineWriter) so the lifecycle and
stamping mechanics live once.

Lifecycle: the file opens on the FIRST record (creating the parent
directory — a path inside a not-yet-existing run dir must not crash the
construction), every record is flushed (a crash loses nothing already
appended), and `close()` flushes, closes, and returns the sink to its
lazy state — a later append transparently reopens in append mode
instead of writing to a closed handle. An empty path disables the sink
entirely (every call is a no-op).

Stamping: every record is prefixed with `ts` (wall-clock seconds —
correlation only; durations use time.perf_counter), `rank`, and
`run_id` (xflow_tpu/telemetry.py), so per-rank metrics and quarantine
streams from one run are joinable and a report tool can group them
without side-channel knowledge. Callers that know their identity pass
`stamp=`; sinks constructed deep in the data layer resolve it lazily at
the first append (by then the launcher env / distributed init has
settled).

Rotation: `max_bytes > 0` caps the live file — an append that would
push past the cap first rolls the file to a single `<path>.1` sibling
(overwriting the previous roll) and reopens fresh, all under the same
append lock, so a long-running serving fleet's span/metrics streams
are bounded at ~2x max_bytes instead of growing with uptime. Readers
fold transparently: `read_jsonl(path)` reads `<path>.1` first (the
older records) then `path`, so file order — and every order-sensitive
gate metrics_report runs — survives the roll.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Optional


class JsonlAppender:
    def __init__(self, path: str = "", stamp: Optional[dict] = None,
                 max_bytes: int = 0):
        self._path = path
        self._f = None
        # size-capped rotation (0 = unbounded, the historical
        # behavior): the roll happens inside append() under the lock
        self._max_bytes = max(int(max_bytes), 0)
        self._size = None  # bytes in the live file; resolved at open
        # appends are serialized: the serving-fleet router writes one
        # sink from request-handler threads, hedge legs, and the
        # health loop at once, and an unlocked TextIOWrapper.write can
        # interleave two records into one damaged line
        self._lock = threading.Lock()
        self._static = stamp
        # an explicit stamp may already carry `replica`; None still
        # resolves lazily (fleet replicas export XFLOW_REPLICA)
        self._replica_resolved = bool(stamp) and "replica" in stamp
        # likewise `slice` (multi-slice runs export XFLOW_SLICE)
        self._slice_resolved = bool(stamp) and "slice" in stamp

    def _stamp(self) -> dict:
        if self._static is None:
            from xflow_tpu.telemetry import resolve_rank, resolve_run_id

            self._static = {"rank": resolve_rank(), "run_id": resolve_run_id()}
        if "gen" not in self._static:
            # restart generation (elastic recovery, docs/OBSERVABILITY.md
            # "Restart generations"): resolved lazily like rank/run_id so
            # callers that pass an explicit stamp still get it, and a
            # supervisor-exported XFLOW_RESTART_GEN has settled by the
            # first append
            from xflow_tpu.telemetry import resolve_restart_gen

            self._static = {**self._static, "gen": resolve_restart_gen()}
        if "world" not in self._static:
            # the generation's world size (degraded-mode supervision,
            # docs/ROBUSTNESS.md): a shrunk relaunch stamps its NEW rank
            # count so report tools can tell a retired rank from a dead
            # one — resolved lazily like gen, after the launcher env
            # (XFLOW_NUM_PROCESSES) has settled
            from xflow_tpu.telemetry import resolve_world_size

            self._static = {**self._static, "world": resolve_world_size()}
        if not self._replica_resolved:
            # serving-fleet identity (docs/SERVING.md "Fleet"): replica
            # index + port, resolved lazily like gen/world. Only fleet
            # replicas export XFLOW_REPLICA, so solo runs' records are
            # byte-identical to before — absent keys, not nulls.
            from xflow_tpu.telemetry import resolve_replica, resolve_replica_port

            self._replica_resolved = True
            rep = resolve_replica()
            if rep is not None:
                extra = {"replica": rep}
                port = resolve_replica_port()
                if port is not None:
                    extra["port"] = port
                self._static = {**self._static, **extra}
        if not self._slice_resolved:
            # multi-slice identity (docs/DISTRIBUTED.md "Multi-slice
            # bounded staleness"): the slice index, resolved lazily like
            # replica. Only launch-multislice children export
            # XFLOW_SLICE, so everyone else's records stay
            # byte-identical — absent keys, not nulls.
            from xflow_tpu.telemetry import resolve_slice

            self._slice_resolved = True
            sl = resolve_slice()
            if sl is not None:
                self._static = {**self._static, "slice": sl}
        return self._static

    @property
    def enabled(self) -> bool:
        """Whether appends go anywhere ('' path = disabled sink) — lets
        callers skip work that only feeds this sink (span buffering)."""
        return bool(self._path)

    def _open_locked(self) -> None:
        parent = os.path.dirname(self._path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(self._path, "a")
        self._size = self._f.tell()  # append mode: at end of file

    def append(self, record: dict) -> None:
        if not self._path:
            return
        with self._lock:
            if self._f is None:
                self._open_locked()
            rec = {"ts": round(time.time(), 6), **self._stamp(), **record}
            line = json.dumps(rec) + "\n"
            if (
                self._max_bytes > 0
                and self._size > 0
                and self._size + len(line) > self._max_bytes
            ):
                # roll: the live file becomes <path>.1 (replacing the
                # previous roll — two files bound the footprint) and a
                # fresh live file opens; still under the append lock,
                # so concurrent appenders never interleave mid-roll
                self._f.close()
                try:
                    os.replace(self._path, self._path + ".1")
                except OSError:
                    pass  # rotation is best-effort; appending must not die
                self._open_locked()
            self._f.write(line)
            self._f.flush()
            self._size += len(line)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


def read_jsonl_counted(path: str, warn: bool = True,
                       fold_rotated: bool = True) -> tuple[list, int]:
    """(records, skipped) from a JSONL file, tolerating damage.

    A crash mid-append leaves a partial last line (the appender flushes
    per record, but the record itself can be cut); a reader that raises
    on it makes every post-crash report useless. Unparseable lines —
    final or not — are skipped and counted, with one stderr warning per
    file, never an exception.

    Rotation fold (`fold_rotated`, default on): when the appender's
    size cap rolled older records into `<path>.1`, they are read FIRST
    so the combined list keeps file order — callers see one logical
    stream, not a rotation artifact. Reading the `.1` sibling
    explicitly does not re-fold (no double reads)."""
    if (
        fold_rotated
        and not path.endswith(".1")
        and os.path.exists(path + ".1")
    ):
        records, skipped = read_jsonl_counted(path + ".1", warn=warn,
                                              fold_rotated=False)
        live, live_skipped = read_jsonl_counted(path, warn=warn,
                                                fold_rotated=False)
        return records + live, skipped + live_skipped
    records: list = []
    skipped = 0
    first_bad = 0
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                first_bad = first_bad or i
                continue
            if not isinstance(rec, dict):
                skipped += 1
                first_bad = first_bad or i
                continue
            records.append(rec)
    if skipped and warn:
        print(
            f"xflow: warning: {path}: skipped {skipped} unparseable JSONL "
            f"line(s) (first at line {first_bad}; truncated append or "
            "corruption)",
            file=sys.stderr,
        )
    return records, skipped


def read_jsonl(path: str, warn: bool = True, fold_rotated: bool = True) -> list:
    """Truncation-tolerant JSONL read (see read_jsonl_counted)."""
    return read_jsonl_counted(path, warn=warn, fold_rotated=fold_rotated)[0]
