from xflow_tpu.ops.sorted_table import (  # noqa: F401
    SortedPlan,
    plan_sorted_batch,
    table_gather_sorted,
)
