"""Sorted-window table engine: the TPU-native replacement for random
gather/scatter on giant embedding tables.

Why: the FM/MVM step is dominated by XLA's scatter-add of per-occurrence
gradient rows into the [S, 1+k] table — random HBM access at ~100 ns per
row (measured: 216 ms of a 280 ms step at 2M occurrences, and XLA does
not exploit sorted indices; docs/PERF.md). Sequential window streams +
MXU one-hot matmuls avoid table-scale random access entirely; the only
random access left is into [B, k]-sized (cache-resident) row aggregates.

Design (reference analog: the per-minibatch key sort + dedup the worker
does before Pull, `/root/reference/src/model/lr/lr_worker.cc:150-165` —
here the sort becomes the *device layout*):

- the HOST (parser / pipeline) emits each batch's occurrences in
  slot-sorted order: `sorted_slots [Np]`, `sorted_row [Np]`,
  `sorted_mask [Np]`, plus `win_off [S/W + 1]` — each W-slot table
  window's first occurrence position in the sorted order.
- `table_gather_sorted` (custom_vjp) returns per-occurrence table rows
  TRANSPOSED: `occ_t [K8, Np]` (K8 = K rounded up to the 8-sublane
  tile). The transposed layout is load-bearing twice over: elementwise
  work on [Np, 11] wastes ~11x lane bandwidth on TPU, and Mosaic
  rejects DMA slices whose minor dim is not 128-aligned — [K8, C]
  column slices of a [K8, Np] array satisfy both.
- its VJP consumes the cotangent in the same [K8, Np] layout and
  scatters with one [W, K] block write per window (MXU-accumulated).

Chunks are CHUNK-aligned (Mosaic requires aligned DMA offsets), so a
window's chunk range may include occurrences of neighboring windows;
the in-window test masks them in compute (scatter) or blends them back
from the existing output (gather) — no explicit tail masking needed.

Two implementations with identical semantics:
- Pallas TPU kernels (grid over windows; MXU does the heavy lifting);
- an XLA reference used on CPU (tests) and as the oracle.
"""

from __future__ import annotations

import os
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

WINDOW = 2048  # table slots per grid step
CHUNK = 512  # sorted occurrences per inner iteration (DMA granularity)


def _k8(k: int) -> int:
    return max(8, ((k + 7) // 8) * 8)


PACK = 8  # slots per packed table row (see pack_table)


def pack_table(t):
    """[S, K] logical table -> [S/PACK, PACK*K] packed storage (a pure
    reshape: slot s lives at [s // PACK, (s % PACK)*K : (s % PACK+1)*K]).

    WHY: TPU HBM buffers are (8, 128)-tiled, so a [S, 11] f32 array is
    stored [S, 128] — 11.6× its logical bytes (at 2^24 slots the FM FTRL
    state alone is 3 × 8 GB and cannot fit a v5e chip) — and every
    elementwise optimizer pass runs at 11/128 lane efficiency
    (docs/PERF.md microbench: the FTRL update was the dominant FM step
    cost for exactly this reason). Packed, the minor dim is PACK*K
    (88 for the fused FM table): 1.45× padding instead of 11.6×, and
    the FTRL update runs 88/128 of peak.

    Consumers detect the layout FROM THE SHAPE (`pack_of`), so
    hand-built logical tables keep working everywhere."""
    S, K = t.shape
    assert S % PACK == 0, (S, PACK)
    return t.reshape(S // PACK, PACK * K)


def unpack_table(t_packed, K: int):
    """Inverse of pack_table. On a TPU DEVICE this materializes the
    11.6×-padded logical buffer — call on host arrays (free reshape) or
    small tables only."""
    Sp, PK = t_packed.shape
    assert PK % K == 0, (PK, K)
    return t_packed.reshape(Sp * (PK // K), K)


def compact_plan_wire(arrays: dict, rows_bound: int, fields_bound: int = 0) -> dict:
    """Shrink the per-batch plan arrays' host->device wire format:
    row ids to uint16, fields to uint8, the 0/1 mask to uint8 —
    14.2 -> 8.2 MB per 64k x 18 batch (plus ~3.5 MB on the MVM segment
    path's fields), ~45% less PCIe (or tunnel) traffic per step. The
    jitted forwards upcast on device (`wire_rows` / `wire_mask`), where
    the cast fuses for free.

    Every decision here is made from CONFIG-DERIVED BOUNDS (`rows_bound`
    = rows per sub-batch/shard, `fields_bound` = model.num_fields), NOT
    from the data: in multi-process SPMD each rank compacts its own
    batch, the dtypes are baked into the jitted collective program, and
    a value-dependent choice could differ across ranks and desync the
    all_to_all sequences. The mask is guaranteed 0/1 by the data
    pipeline (parser/pad contract); a fractional mask from a custom
    caller is a bug and raises loudly rather than silently changing the
    wire format."""
    out = dict(arrays)
    if rows_bound <= (1 << 16):
        for key in ("sorted_row", "fs_row"):
            if key in out and np.asarray(out[key]).dtype == np.int32:
                out[key] = np.asarray(out[key]).astype(np.uint16)
    if 0 < fields_bound <= (1 << 8):
        for key in ("sorted_fields", "fs_fields"):
            if key in out and np.asarray(out[key]).dtype == np.int32:
                out[key] = np.asarray(out[key]).astype(np.uint8)
    for key in ("sorted_mask", "fs_mask"):
        if key in out:
            m = np.asarray(out[key])
            if m.dtype == np.float32:
                u8 = m.astype(np.uint8)
                if not (m == u8).all():
                    raise ValueError(
                        f"{key} carries non-0/1 values: the mask is a presence "
                        "mask by the batch-schema contract (data/schema.py); "
                        "fractional values here are a pipeline bug"
                    )
                out[key] = u8
    return out


def wire_rows(sorted_row):
    """Device-side upcast of a possibly-compacted row-id array."""
    return sorted_row.astype(jnp.int32)


def wire_mask(sorted_mask):
    """Device-side upcast of a possibly-compacted mask array."""
    return sorted_mask.astype(jnp.float32)


def dedup_slots(slots: np.ndarray, cap: int):
    """Host-side batch dedup for the ROW-MAJOR paths (reference analog:
    the per-minibatch unique-key Pull, `lr_worker.cc:150-165`):
    returns (unique_slots [cap] padded with the last unique, inverse
    [B, F] int32) or None when the batch has more than `cap` uniques
    (the caller ships row-major and the step's direct-gather variant
    runs — jit shapes must be static, so capacity is fixed).

    The win is on SKEWED data and on a sharded mesh: the table gather
    moves `cap` rows instead of B·F (cross-chip gather/scatter volume
    shrinks by U/(B·F)); uniform batches at bench shapes have U ≈ 0.76
    B·F and are not worth the host sort (docs/PERF.md lever 4)."""
    flat = np.asarray(slots, np.int32).ravel()
    u, inv = np.unique(flat, return_inverse=True)
    if u.size > cap or u.size == 0:
        return None
    pad = np.full(cap - u.size, u[-1], np.int32)
    return (
        np.concatenate([u.astype(np.int32), pad]),
        inv.astype(np.int32).reshape(np.asarray(slots).shape),
    )


def batch_rows(table, batch: dict, K: int):
    """Per-occurrence LOGICAL table rows for a row-major batch: the
    deduped two-level gather when the host attached (unique_slots,
    inverse), else the direct gather. Layout-blind (`table_rows`)."""
    if "unique_slots" in batch:
        return table_rows(table, batch["unique_slots"], K)[batch["inverse"]]
    return table_rows(table, batch["slots"], K)


# packed-row gather intermediate cap (bytes). The packed gather
# materializes [chunk, pack*K] full packed rows before the sub-row
# select; at FFM's K=73 a 64k×18 batch would make that ~3 GB in one
# piece (the round-5 OOM at the 64k row-major shape). Chunking the
# occurrence axis caps it; 256 MB keeps the per-chunk gather large
# enough to stay on XLA's fast row-gather path. (A single 2-D
# lax.gather with a (row, sub-row·K) start index avoids the
# intermediate entirely but lowers to a ~2.5 µs/row scalar path on
# TPU — measured 140× slower.)
_PACKED_GATHER_CHUNK_BYTES = 256 * 1024 * 1024


def table_rows(table, slots, K: int):
    """Logical rows ``table[slots]`` from EITHER storage layout — the
    row-major paths' (GSPMD step, mesh eval, non-sorted forwards)
    layout-blind gather. Packed: a full-packed-row gather of
    [..., pack*K] plus an elementwise 0/1 sub-row select (never a
    matmul, so no MXU operand rounding — see `_sub_select`), chunked
    over the occurrence axis so the packed-row intermediate stays
    under _PACKED_GATHER_CHUNK_BYTES."""
    pack = pack_of(table, K)
    if pack == 1:
        return table[slots]
    flat = slots.reshape(-1)
    n = flat.shape[0]
    chunk_rows = max(1, _PACKED_GATHER_CHUNK_BYTES // (pack * K * 4))
    nch = -(-n // chunk_rows)
    if nch <= 1:
        rows = table[flat // pack]
        out = _sub_select(rows, flat % pack, pack, K)
    else:
        pad = nch * chunk_rows - n
        padded = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])

        def one(chunk):
            rows = table[chunk // pack]
            return _sub_select(rows, chunk % pack, pack, K)

        out = jax.lax.map(one, padded.reshape(nch, chunk_rows)).reshape(
            nch * chunk_rows, K
        )[:n]
    return out.reshape(*slots.shape, K)


def pack_of(table, K: int) -> int:
    """Storage layout of `table` given its LOGICAL row width K: 1 =
    logical [S, K], PACK = packed [S/PACK, PACK*K]. Raises on anything
    else (a shape mismatch here means a config/table disagreement)."""
    if table.ndim != 2 or table.shape[1] == K:
        return 1
    if table.shape[1] == PACK * K:
        return PACK
    raise ValueError(
        f"table shape {table.shape} is neither logical [S, {K}] nor "
        f"packed [S/{PACK}, {PACK * K}]"
    )


class SortedPlan(NamedTuple):
    """Host-computed sorted layout of one batch's feature occurrences.

    Arrays are padded to a CHUNK multiple plus one spare chunk so
    aligned [start, start+CHUNK) reads never leave bounds; pad slots are
    `num_slots - 1` (the LAST window) with mask/row 0, so every padded
    position is owned — and therefore written — by some window: the
    gather output has no uninitialized columns (a pad column holds row
    `num_slots-1`'s values; consumers must multiply by `sorted_mask`),
    and the scatter receives a zero cotangent there (mask zeroes it).
    """

    sorted_slots: np.ndarray  # int32 [Np]
    sorted_row: np.ndarray  # int32 [Np]
    sorted_mask: np.ndarray  # float32 [Np]
    win_off: np.ndarray  # int32 [S/WINDOW + 1]
    sorted_fields: Optional[np.ndarray] = None  # int32 [Np] (MVM; pad 0)


def padded_len(n: int) -> int:
    return (n // CHUNK + 2) * CHUNK


_NATIVE_PLAN = None  # tri-state: None = untried, False = unavailable, else fn
_PLAN_POOL = None  # one fixed-size executor per process, created once
_PLAN_POOL_LOCK = __import__("threading").Lock()


def _plan_pool(workers: int):
    """Shared planning thread pool, sized once to the host's cores and
    NEVER shut down: a resize-by-replacement would race concurrent
    Trainers' in-flight map() futures against the old pool's shutdown
    (advisor r2). Oversubscription is impossible (cores is the useful
    ceiling regardless of any caller's num_sub); `workers` only matters
    the first call, as a floor for tiny-cpu_count() hosts."""
    global _PLAN_POOL
    with _PLAN_POOL_LOCK:
        if _PLAN_POOL is None:
            from concurrent.futures import ThreadPoolExecutor

            size = max(workers, min(os.cpu_count() or 1, 16))
            _PLAN_POOL = ThreadPoolExecutor(max_workers=size)
        return _PLAN_POOL


def _native_planner():
    """xf_plan_sorted via ctypes (native/parser.cc): a stable O(n) radix
    sort replacing np.argsort's ~150 ms/2M-occurrence comparison sort —
    the host would otherwise wall the sorted-engine step times. Falls
    back to numpy when the toolchain is missing. XFLOW_NO_NATIVE_PLAN=1
    forces the numpy path (used by the parity tests)."""
    global _NATIVE_PLAN
    if _NATIVE_PLAN is None:
        if os.environ.get("XFLOW_NO_NATIVE_PLAN"):
            _NATIVE_PLAN = False
        else:
            try:
                from xflow_tpu.data.native import native_plan_sorted

                _NATIVE_PLAN = native_plan_sorted
            except Exception:
                _NATIVE_PLAN = False
    return _NATIVE_PLAN


def plan_sorted_batch(
    slots: np.ndarray,
    mask: np.ndarray,
    num_slots: int,
    fields: Optional[np.ndarray] = None,
    wire: bool = False,
) -> SortedPlan:
    """Sort a [B, F] batch's occurrences by table slot (host side).

    Masked occurrences keep their (meaningless) slot — their mask rides
    along and zeroes both the forward contribution and the gradient.
    `fields` (MVM) rides through the same permutation when given.
    Uses the C radix-sort builder when built (bit-identical to the numpy
    path — both sorts are stable; parity-tested). `wire=True` asks the
    C builder to emit the compact wire dtypes (uint16 rows, uint8
    mask/fields — compact_plan_wire's format) DIRECTLY, skipping the
    int32/f32 intermediate and its astype passes; the caller must have
    checked the CONFIG bounds (rows per sub-batch ≤ 2^16, fields <
    2^8) — compact_plan_wire stays the single place those rules live,
    and it passes already-compact arrays through untouched. Without the
    native builder `wire` is ignored (the numpy path emits int32 and
    compaction happens downstream as before).
    """
    native = _native_planner()
    if native and num_slots % WINDOW == 0:
        # no try/except: the numpy fallback exists for a MISSING toolchain
        # (handled once at load in _native_planner); a runtime failure in a
        # successfully-built planner is a bug and must raise, not silently
        # re-run the 4x-slower argsort on every batch
        if wire:
            from xflow_tpu.data.native import native_plan_sorted_wire

            ss, row, m, f, off = native_plan_sorted_wire(
                np.ascontiguousarray(slots, np.int32),
                mask, fields, num_slots, WINDOW,
                padded_len(slots.size),
            )
            return SortedPlan(ss, row, m, off, f)
        ss, row, m, f, off = native(
            np.ascontiguousarray(slots, np.int32),
            mask, fields, num_slots, WINDOW,
            padded_len(slots.size),
        )
        return SortedPlan(ss, row, m, off, f)
    flat_slots = np.ascontiguousarray(slots, np.int32).ravel()
    flat_mask = np.ascontiguousarray(mask, np.float32).ravel()
    if flat_slots.size and (
        int(flat_slots.min()) < 0 or int(flat_slots.max()) >= num_slots
    ):
        # same loud-failure contract as the native planner: an out-of-range
        # slot would sort past the last window and be silently dropped
        raise ValueError(
            f"slot out of range [0, {num_slots}): "
            f"min={int(flat_slots.min())} max={int(flat_slots.max())}"
        )
    n = flat_slots.shape[0]
    np_len = padded_len(n)
    order = np.argsort(flat_slots, kind="stable").astype(np.int32)
    pad = np_len - n
    ss = np.concatenate([flat_slots[order], np.full(pad, num_slots - 1, np.int32)])
    # pads sort at (or past) the real occurrences of slot num_slots-1, so
    # the full padded array is sorted and the last window's range covers
    # every padded position — nothing is left unwritten by the kernels
    win_off = np.searchsorted(ss, np.arange(0, num_slots + 1, WINDOW)).astype(np.int32)
    sorted_fields = None
    if fields is not None:
        flat_fields = np.ascontiguousarray(fields, np.int32).ravel()
        sorted_fields = np.concatenate([flat_fields[order], np.zeros(pad, np.int32)])
    return SortedPlan(
        sorted_slots=ss,
        sorted_row=np.concatenate([(order // slots.shape[1]).astype(np.int32),
                                   np.zeros(pad, np.int32)]),
        sorted_mask=np.concatenate([flat_mask[order], np.zeros(pad, np.float32)]),
        win_off=win_off,
        sorted_fields=sorted_fields,
    )


def map_host_parallel(fn, n: int) -> list:
    """Run fn(0..n-1) on the shared planning pool when the C planner is
    built (it releases the GIL during the sort, so plans parallelize
    across host cores); the numpy fallback holds the GIL through
    argsort, where threads would only add churn. Order-preserving."""
    workers = min(n, os.cpu_count() or 1)
    if workers > 1 and _native_planner():
        return list(_plan_pool(workers).map(fn, range(n)))
    return [fn(i) for i in range(n)]


def plan_sorted_stacked(
    slots: np.ndarray,
    mask: np.ndarray,
    num_slots: int,
    fields: Optional[np.ndarray] = None,
    num_sub: int = 1,
    always_stack: bool = False,
    wire: bool = False,
) -> SortedPlan:
    """Per-sub-batch sorted plans, stacked on a leading [NS] axis.

    Splits the [B, F] batch into `num_sub` row-contiguous sub-batches and
    plans each independently (row ids are LOCAL to the sub-batch). The
    device step maps over the NS axis, so per-row aggregates are sized
    [B/NS, ...] — small enough to stay cache-resident for models whose
    row-side state is large (MVM's [B·nf, k]); XLA accumulates the table
    gradient across sub-batches. `B % num_sub == 0` is required (the
    planner's callers pick a divisor). `num_sub=1` returns FLAT arrays
    unless `always_stack` (the sharded engine wants [1, Np] at D=1).
    """
    B = slots.shape[0]
    if num_sub <= 1:
        p = plan_sorted_batch(slots, mask, num_slots, fields=fields, wire=wire)
        if not always_stack:
            return p
        return SortedPlan(
            sorted_slots=p.sorted_slots[None],
            sorted_row=p.sorted_row[None],
            sorted_mask=p.sorted_mask[None],
            win_off=p.win_off[None],
            sorted_fields=None if p.sorted_fields is None else p.sorted_fields[None],
        )
    if B % num_sub:
        raise ValueError(f"batch {B} not divisible by num_sub {num_sub}")
    bs = B // num_sub

    def one(i):
        return plan_sorted_batch(
            slots[i * bs : (i + 1) * bs],
            mask[i * bs : (i + 1) * bs],
            num_slots,
            fields=None if fields is None else fields[i * bs : (i + 1) * bs],
            wire=wire,
        )

    if num_slots % WINDOW == 0:
        plans = map_host_parallel(one, num_sub)
    else:
        plans = [one(i) for i in range(num_sub)]
    return SortedPlan(
        sorted_slots=np.stack([p.sorted_slots for p in plans]),
        sorted_row=np.stack([p.sorted_row for p in plans]),
        sorted_mask=np.stack([p.sorted_mask for p in plans]),
        win_off=np.stack([p.win_off for p in plans]),
        sorted_fields=(
            np.stack([p.sorted_fields for p in plans]) if fields is not None else None
        ),
    )


def sorted_gather_map(table, batch: dict, row_keys: tuple, batch_rows: int,
                      row_fn, K: int, bf16: bool):
    """Gather the table ONCE, then map the row side over sub-batches.

    `row_fn(occ_t [K8, Np], *row_arrays, rows)` computes logits for one
    sub-batch from its raw gathered rows. Flat plans use the
    single-stream gather; stacked plans ([NS, Np_sub],
    `plan_sorted_stacked`) run ONE `table_gather_sorted_multi` over the
    concatenated streams — window-major, so the table (and its gradient
    blocks in the VJP) crosses HBM exactly once per step instead of
    once per sub-batch. Before this, NS=4 sub-batching re-read the
    whole table 4× each direction — the dominant cost of the MVM
    segment path (docs/PERF.md 3a).
    """
    pack = pack_of(table, K)
    ss, wo = batch["sorted_slots"], batch["win_off"]
    arrs = tuple(batch[k] for k in row_keys)
    if ss.ndim == 1:
        occ_t = table_gather_sorted(table, ss, wo, bf16, pack)
        return row_fn(occ_t, *arrs, batch_rows)
    ns, np_sub = ss.shape
    rows = batch_rows // ns
    occ_all = table_gather_sorted_multi(table, ss.reshape(-1), wo, bf16, pack)
    occ_ns = occ_all.reshape(occ_all.shape[0], ns, np_sub).transpose(1, 0, 2)
    logits = jax.lax.map(
        lambda a: row_fn(a[0], *a[1:], rows), (occ_ns, *arrs)
    )  # [NS, rows]
    return logits.reshape(batch_rows)


def auto_sub_batches(batch_size: int, row_state_bytes_per_row: int,
                     target_bytes: int = 1 << 24) -> int:
    """Smallest power-of-two NS (dividing batch_size) that keeps the
    per-sub-batch row-side state under `target_bytes`; capped so
    sub-batches keep >= 1024 rows. 16 MiB measured best on v5e for MVM
    at B=64k/nf=18/k=10 (NS=4 → 396k ex/s; NS=1 252k, NS=16 210k —
    smaller sub-batches pay window fragmentation in the table kernels,
    larger ones fall out of cache on the row side; docs/PERF.md)."""
    ns = 1
    while (
        batch_size % (ns * 2) == 0
        and batch_size // (ns * 2) >= 1024
        and (batch_size // ns) * row_state_bytes_per_row > target_bytes
    ):
        ns *= 2
    return ns


def resolve_sub_batches(cfg) -> int:
    """NS for the sorted layout (cfg.data.sorted_sub_batches; 0 = auto).

    Auto keeps MVM's *segment-path* per-sub-batch [B/NS·nf, k+1] row
    aggregate under 16 MiB (the measured v5e sweet spot — docs/PERF.md).
    FM's [B, 24] is already small, so NS=1 — and so is the MVM
    exclusive-fields product path's (models/mvm.py), which is the
    expected path whenever `model.mvm_exclusive` != off; a stray
    duplicate-field batch then runs the segment path at NS=1 (correct,
    just not cache-tuned — routing NS per batch would retrace the step).
    """
    ns = cfg.data.sorted_sub_batches
    B = cfg.data.batch_size
    if ns > 0:
        if B % ns:
            raise ValueError(
                f"data.sorted_sub_batches={ns} must divide batch_size={B}"
            )
        return ns
    if cfg.model.name == "mvm" and cfg.model.mvm_exclusive == "off":
        per_row = cfg.model.num_fields * (cfg.model.v_dim + 1) * 4
        return auto_sub_batches(B, per_row)
    if cfg.model.name == "ffm":
        # FFM's per-(row, field) aggregate is [B/NS·nf, nf·k+2]
        per_row = cfg.model.num_fields * (cfg.model.num_fields * cfg.model.v_dim + 2) * 4
        return auto_sub_batches(B, per_row)
    return 1


# ------------------------------------------------------------------ XLA path

def _sub_select(rows, sub, pack: int, K: int):
    """[..., pack*K] packed rows -> [..., K] logical rows selected by
    `sub` in [0, pack). Elementwise multiply-sum on 0/1 masks — NEVER a
    matmul, so no MXU operand rounding can touch the values."""
    sel = (sub[..., None] == jnp.arange(pack)).astype(rows.dtype)  # [..., pack]
    grouped = rows.reshape(*rows.shape[:-1], pack, K)
    return (grouped * sel[..., None]).sum(axis=-2)


def _gather_xla(table, sorted_slots, win_off, pack: int = 1):
    Sp, W = table.shape
    S, K = Sp * pack, W // pack
    safe = jnp.minimum(sorted_slots, S - 1)
    if pack == 1:
        occ = jnp.where((sorted_slots < S)[:, None], table[safe], 0.0)  # [Np, K]
    else:
        rows = jnp.where(
            (sorted_slots < S)[:, None], table[safe // pack], 0.0
        )  # [Np, pack*K]
        occ = _sub_select(rows, safe % pack, pack, K)
    out = jnp.zeros((_k8(K), sorted_slots.shape[0]), table.dtype)
    return jax.lax.dynamic_update_slice(out, occ.T, (0, 0))


def _scatter_xla(d_occ_t, sorted_slots, win_off, num_slots, k: int, pack: int = 1):
    safe = jnp.minimum(sorted_slots, num_slots - 1)
    d = jnp.where((sorted_slots < num_slots)[None, :], d_occ_t[:k], 0.0)
    if pack == 1:
        return jax.ops.segment_sum(d.T, safe, num_segments=num_slots)
    sub = safe % pack
    sel = (sub[:, None] == jnp.arange(pack)).astype(d.dtype)  # [Np, pack]
    d_exp = (d.T[:, None, :] * sel[:, :, None]).reshape(-1, pack * k)
    return jax.ops.segment_sum(d_exp, safe // pack, num_segments=num_slots // pack)


# --------------------------------------------------------------- Pallas path

def _dot_f32(a, onehot_f32, dims, bf16: bool):
    """MXU contraction of `a` against a 0/1 matrix, f32-accurate by default.

    `bf16=False` (default): splits `a` into three bf16 terms (hi/mid/lo,
    8 mantissa bits each — together the full f32 mantissa) and runs
    three DEFAULT-precision bf16 matmuls, since the other operand is
    EXACTLY representable in bf16 (one-hot 0/1). Where an output element
    selects a single column (the gather), (hi+mid)+lo reconstructs the
    f32 value BIT-exactly; where it sums several columns (the scatter,
    duplicate slots in a chunk), each column's contribution is exact and
    only the f32 summation ORDER differs from a direct accumulation —
    the same ≤1-ulp-per-add reorder class as any parallel reduction.
    Cost: 3 bf16 MXU passes — about half of Precision.HIGHEST (which
    decomposes BOTH operands), Mosaic's only other non-DEFAULT option.

    `bf16=True` (cfg.data.sorted_bf16): one rounded pass — values carry
    8 mantissa bits, the standard bf16-training trade. The flag is
    threaded as a static argument (never a global) so each jitted step
    keeps the precision of the config it was built with.

    The three exact terms run as ONE stacked MXU pass: hi/mid/lo
    concatenated along `a`'s free axis ([W, 3K] x [W, C] instead of
    three [W, K] x [W, C]), then the three output blocks summed in the
    same (hi+mid)+lo order — bit-identical results, and the skinny
    free dim (K=11 of 128 MXU rows) wastes 3x less of the systolic
    array per window (measured ~1.5x faster gather/scatter kernels than
    three separate passes)."""
    oh = onehot_f32.astype(jnp.bfloat16)
    if bf16:
        return jax.lax.dot_general(
            a.astype(jnp.bfloat16), oh, dims, preferred_element_type=jnp.float32
        )
    hi = a.astype(jnp.bfloat16)
    rem = a - hi.astype(jnp.float32)
    mid = rem.astype(jnp.bfloat16)
    lo = (rem - mid.astype(jnp.float32)).astype(jnp.bfloat16)
    free = 1 - dims[0][0][0]  # a's non-contracted axis (2-D, one contract dim)
    a3 = jnp.concatenate([hi, mid, lo], axis=free)
    out = jax.lax.dot_general(a3, oh, dims, preferred_element_type=jnp.float32)
    # lhs free dims lead the result: blocks stack along result axis 0
    k = a.shape[free]
    return (out[:k] + out[k : 2 * k]) + out[2 * k :]

def _windowed_select(table_block, rel, pack: int, bf16: bool):
    """One window's per-occurrence table rows via the one-hot MXU
    contraction, in logical ([W, K] block, pack=1) or packed
    ([W/pack, pack*K] block) layout. Packed does the one-hot over
    PACKED rows (pack× narrower iota/compare and pack× shorter MXU
    contraction) and then selects the sub-row with `pack` STATIC
    slice-multiply-adds — 0/1 masks, elementwise, exact. Returns
    occ [K, C]."""
    if pack == 1:
        onehot = (
            jax.lax.broadcasted_iota(jnp.int32, (table_block.shape[0], rel.shape[1]), 0)
            == rel
        ).astype(jnp.float32)
        return _dot_f32(table_block, onehot, (((0,), (0,)), ((), ())), bf16)
    Wp = table_block.shape[0]
    K = table_block.shape[1] // pack
    rel_p = rel // pack  # floor semantics also for out-of-window negatives
    onehot_p = (
        jax.lax.broadcasted_iota(jnp.int32, (Wp, rel.shape[1]), 0) == rel_p
    ).astype(jnp.float32)
    occ_p = _dot_f32(table_block, onehot_p, (((0,), (0,)), ((), ())), bf16)  # [pack*K, C]
    sub = rel - rel_p * pack  # [1, C]; out-of-window chunks have no onehot hit
    occ = occ_p[0:K, :] * (sub == 0)
    for p in range(1, pack):
        occ = occ + occ_p[p * K : (p + 1) * K, :] * (sub == p)
    return occ


def _gather_span(slots_ref, out_ref, table_ref, slc, old, sem_s, sem_d, sem_o,
                 base, start, end, bf16, pack):
    """NB-deep pipelined windowed gather of ONE occurrence span [start,
    end) against the table block at `base` (NB = the scratch buffer
    count, `PIPE_NB`): the chunk chain is DMA-LATENCY bound, not
    bandwidth bound (~460 MB of traffic measured ~18 ms serialized =
    ~4 us/chunk of waits), so inputs for chunk c+NB-1 prefetch during
    compute of c and the output copy of c drains while later chunks
    run. Buffer sel = c % NB; `old[sel]` is both the blend source and
    the out staging, so its input copy for c+NB-1 waits the out copy of
    c-1 (same buffer). The epilogue drains the min(n, NB) out copies
    still in flight (one per buffer); spans run sequentially (grid
    steps / the multi kernel's buffer loop), so the next span (whose
    aligned chunk range can overlap this one's) never races these
    writes. Shared by the single-stream and multi-buffer gather
    kernels — a fix here fixes both."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    NB = old.shape[0]  # pipeline depth = scratch buffer count
    K = table_ref.shape[1] // pack
    astart = (start // CHUNK) * CHUNK  # aligned down: extras self-mask
    n_chunks = pl.cdiv(end - astart, CHUNK)

    def in_copies(c):
        sel = c % NB
        o = astart + c * CHUNK
        return (
            pltpu.make_async_copy(
                slots_ref.at[:, pl.ds(o, CHUNK)], slc.at[sel], sem_s.at[sel]
            ),
            pltpu.make_async_copy(
                out_ref.at[:, pl.ds(o, CHUNK)], old.at[sel], sem_d.at[sel]
            ),
        )

    def out_copy(c):
        sel = c % NB
        o = astart + c * CHUNK
        return pltpu.make_async_copy(
            old.at[sel], out_ref.at[:, pl.ds(o, CHUNK)], sem_o.at[sel]
        )

    def start_in(c):
        cs, co = in_copies(c)
        cs.start()
        co.start()

    for i in range(NB - 1):
        @pl.when(n_chunks > i)
        def _(i=i):
            start_in(i)

    def chunk_step(c, carry):
        sel = c % NB
        cs, co = in_copies(c)
        cs.wait()
        rel = slc[sel][0:1, :] - base  # [1, C]
        # f32-accurate selection via the stacked 3-term bf16 contraction
        # (_dot_f32): the MXU's default bf16 pass would round every
        # gathered table value to 8 mantissa bits (caught by an on-device
        # parity check vs the XLA gather, ~2^-8 rel error — CPU tests are
        # f32-exact and cannot see it)
        occ = _windowed_select(table_ref[:, :], rel, pack, bf16)  # [K, C]
        co.wait()
        in_win = (rel >= 0) & (rel < WINDOW)  # [1, C]
        # blend: positions whose slot is outside this window belong to a
        # neighboring window's (or buffer's) chunks — keep what is there.
        # No concat when K is already sublane-aligned: Mosaic rejects the
        # zero-row pad array (K=96/128/... would fail to compile)
        if old.shape[1] > K:
            pad = jnp.zeros((old.shape[1] - K, CHUNK), jnp.float32)
            occ = jnp.concatenate([occ, pad], axis=0)
        old[sel] = jnp.where(in_win, occ, old[sel])
        out_copy(c).start()

        @pl.when(c + NB - 1 < n_chunks)
        def _():
            # old[(c+NB-1)%NB] was the out staging of chunk c-1: drain
            # that copy before overwriting the buffer
            @pl.when(c >= 1)
            def _():
                out_copy(c - 1).wait()

            start_in(c + NB - 1)

        return carry

    jax.lax.fori_loop(0, n_chunks, chunk_step, 0)

    # drain every out copy not waited in-loop: iteration c waits out(c-1)
    # only while prefetching (c+NB-1 < n), so the last min(n, NB) outs
    # (one per buffer) are still in flight here — an unwaited DMA would
    # leave its semaphore signaled and corrupt the next span
    for i in range(NB, 0, -1):
        @pl.when(n_chunks > i - 1)
        def _(i=i):
            out_copy(n_chunks - i).wait()


def _gather_kernel(off_ref, slots_ref, table_ref, out_ref, slc, old, sem_s, sem_d,
                   sem_o, *, bf16, n_tw, pack):
    """Single-stream windowed gather: grid step t owns logical window
    t % n_tw (identity when the stream covers the table once)."""
    from jax.experimental import pallas as pl

    t = pl.program_id(0)
    _gather_span(
        slots_ref, out_ref, table_ref, slc, old, sem_s, sem_d, sem_o,
        (t % n_tw) * WINDOW, off_ref[t], off_ref[t + 1], bf16, pack,
    )


def _gather_kernel_multi(off_ref, slots_ref, table_ref, out_ref, slc, old, sem_s,
                         sem_d, sem_o, *, bf16, nbuf, cap, pack):
    """Windowed gather over `nbuf` concatenated per-source buffers,
    WINDOW-MAJOR: grid step j owns table window j and walks every
    buffer's matching span, so each table block is DMA'd into VMEM
    exactly ONCE per call instead of once per buffer — the source-major
    order read the whole table nbuf times (nbuf = D source shards in
    the fullshard engine, NS sub-batches on one device; measured 2×+ on
    the MVM segment path at NS=4). `off_ref` is [nbuf, wpo+1]
    buffer-local window offsets, the `_scatter_kernel_multi` contract."""
    from jax.experimental import pallas as pl

    j = pl.program_id(0)

    def buf_step(i, carry):
        _gather_span(
            slots_ref, out_ref, table_ref, slc, old, sem_s, sem_d, sem_o,
            j * WINDOW, i * cap + off_ref[i, j], i * cap + off_ref[i, j + 1],
            bf16, pack,
        )
        return carry

    jax.lax.fori_loop(0, nbuf, buf_step, 0)


PIPE_NB = 6  # gather chunk-chain pipeline depth (buffers); the chain is
# DMA-latency bound (_gather_span), so deeper prefetch hides more of the
# per-chunk wait — 6 measured best vs 3 on v5e at bench shapes; VMEM cost
# is NB × (K8+1) × CHUNK × 4 B: ~110 KB at K8=8, ~210 KB for the fused FM
# row (K8=16), ~1 MB for FFM's K8=80 — all small next to the table block


def _gather_pallas(table, sorted_slots, win_off, bf16=False, pack=1):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    Sp, Kp = table.shape
    K = Kp // pack
    K8 = _k8(K)
    n_tw = Sp * pack // WINDOW
    # grid = logical windows = len(win_off)-1; a multiple of n_tw when the
    # occurrence stream is D concatenated buffers over the same table
    n_win = win_off.shape[0] - 1
    n = sorted_slots.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_win,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # slots [1, Np]
            pl.BlockSpec((WINDOW // pack, Kp), lambda t, off: (t % n_tw, 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),  # occ_t [K8, Np]
        scratch_shapes=[
            pltpu.VMEM((PIPE_NB, 1, CHUNK), jnp.int32),  # slc, pipelined
            pltpu.VMEM((PIPE_NB, K8, CHUNK), jnp.float32),  # old/staging
            pltpu.SemaphoreType.DMA((PIPE_NB,)),
            pltpu.SemaphoreType.DMA((PIPE_NB,)),
            pltpu.SemaphoreType.DMA((PIPE_NB,)),
        ],
    )
    return pl.pallas_call(
        partial(_gather_kernel, bf16=bf16, n_tw=n_tw, pack=pack),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((K8, n), jnp.float32),
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )(win_off, sorted_slots.reshape(1, n), table)


def _gather_pallas_multi(table, sorted_slots, loc_off, cap, bf16=False, pack=1):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    Sp, Kp = table.shape
    K = Kp // pack
    K8 = _k8(K)
    n_win = Sp * pack // WINDOW
    nbuf, wpo1 = loc_off.shape
    n = sorted_slots.shape[0]
    assert wpo1 == n_win + 1, (loc_off.shape, n_win)
    assert cap % CHUNK == 0 and nbuf * cap == n, (nbuf, cap, n)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_win,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # slots [1, Np]
            pl.BlockSpec((WINDOW // pack, Kp), lambda t, off: (t, 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),  # occ_t [K8, Np]
        scratch_shapes=[
            pltpu.VMEM((PIPE_NB, 1, CHUNK), jnp.int32),  # slc, pipelined
            pltpu.VMEM((PIPE_NB, K8, CHUNK), jnp.float32),  # old/staging
            pltpu.SemaphoreType.DMA((PIPE_NB,)),
            pltpu.SemaphoreType.DMA((PIPE_NB,)),
            pltpu.SemaphoreType.DMA((PIPE_NB,)),
        ],
    )
    return pl.pallas_call(
        partial(_gather_kernel_multi, bf16=bf16, nbuf=nbuf, cap=cap, pack=pack),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((K8, n), jnp.float32),
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )(loc_off, sorted_slots.reshape(1, n), table)


def _scatter_span(slots_ref, d_ref, slc, dch, sem_s, sem_d, base, start, end,
                  acc_t, bf16, pack=1, k=None):
    """Accumulate one occurrence span's contribution to the window at
    `base` into acc_t ([K8, W] logical, [pack*K, W/pack] packed) — the
    precision-critical DMA + one-hot + `_dot_f32` sequence shared by
    the single-stream and multi-buffer scatter kernels (a fix here
    fixes both). Packed expands the [K, C] cotangent chunk to
    [pack*K, C] with `pack` static 0/1-masked block copies (exact) and
    contracts against the PACKED one-hot — pack× fewer MXU MACs per
    chunk. NB-deep pipelined (NB = scratch buffer count, `PIPE_NB`):
    chunk c+NB-1's inputs prefetch during compute of c (the chain is
    DMA-latency bound, like the gather's)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    astart = (start // CHUNK) * CHUNK
    n_chunks = pl.cdiv(end - astart, CHUNK)

    NB = dch.shape[0]  # pipeline depth = scratch buffer count

    def in_copies(c):
        sel = c % NB
        o = astart + c * CHUNK
        return (
            pltpu.make_async_copy(
                slots_ref.at[:, pl.ds(o, CHUNK)], slc.at[sel], sem_s.at[sel]
            ),
            pltpu.make_async_copy(
                d_ref.at[:, pl.ds(o, CHUNK)], dch.at[sel], sem_d.at[sel]
            ),
        )

    def start_in(c):
        cs, cd = in_copies(c)
        cs.start()
        cd.start()

    for i in range(NB - 1):
        @pl.when(n_chunks > i)
        def _(i=i):
            start_in(i)

    def chunk_step(c, acc):
        sel = c % NB
        cs, cd = in_copies(c)
        cs.wait()
        cd.wait()

        @pl.when(c + NB - 1 < n_chunks)
        def _():
            start_in(c + NB - 1)

        rel = slc[sel][0:1, :] - base  # [1, C]; out-of-window: no lane
        if pack == 1:
            onehot = (
                jax.lax.broadcasted_iota(jnp.int32, (WINDOW, CHUNK), 0) == rel
            ).astype(jnp.float32)  # [W, C]
            # [K8, C] x [W, C] contracting C -> [K8, W]
            # f32-accurate for the same reason as the gather; duplicate
            # slots in a chunk make this a SUM, so vs XLA's scatter only
            # the f32 accumulation order differs (<= 1 ulp/add, _dot_f32)
            return acc + _dot_f32(dch[sel], onehot, (((1,), (1,)), ((), ())), bf16)
        rel_p = rel // pack
        onehot_p = (
            jax.lax.broadcasted_iota(jnp.int32, (WINDOW // pack, CHUNK), 0) == rel_p
        ).astype(jnp.float32)  # [W/pack, C]
        sub = rel - rel_p * pack
        d_exp = jnp.concatenate(
            [dch[sel][0:k, :] * (sub == p) for p in range(pack)], axis=0
        )  # [pack*K, C]
        return acc + _dot_f32(d_exp, onehot_p, (((1,), (1,)), ((), ())), bf16)

    return jax.lax.fori_loop(0, n_chunks, chunk_step, acc_t)


def _scatter_kernel(off_ref, slots_ref, d_ref, out_ref, slc, dch, sem_s, sem_d,
                    *, bf16, pack):
    from jax.experimental import pallas as pl

    t = pl.program_id(0)
    K8 = d_ref.shape[0]
    K = out_ref.shape[1] // pack
    rows = pack * K if pack > 1 else K8
    acc_t = jnp.zeros((rows, WINDOW // pack), jnp.float32)
    acc_t = _scatter_span(
        slots_ref, d_ref, slc, dch, sem_s, sem_d,
        t * WINDOW, off_ref[t], off_ref[t + 1], acc_t, bf16, pack, K,
    )
    out_ref[:, :] = (acc_t if pack > 1 else acc_t[0:K, :]).T  # [W/pack, pack*K]


def _scatter_pallas(d_occ_t, sorted_slots, win_off, num_slots, k: int, bf16=False,
                    pack=1):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    K8, n = d_occ_t.shape
    n_win = num_slots // WINDOW
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_win,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # slots [1, Np]
            pl.BlockSpec(memory_space=pl.ANY),  # d [K8, Np]
        ],
        out_specs=pl.BlockSpec((WINDOW // pack, pack * k), lambda t, off: (t, 0)),
        scratch_shapes=[
            pltpu.VMEM((PIPE_NB, 1, CHUNK), jnp.int32),
            pltpu.VMEM((PIPE_NB, K8, CHUNK), jnp.float32),
            pltpu.SemaphoreType.DMA((PIPE_NB,)),
            pltpu.SemaphoreType.DMA((PIPE_NB,)),
        ],
    )
    return pl.pallas_call(
        partial(_scatter_kernel, bf16=bf16, pack=pack),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_slots // pack, pack * k), jnp.float32),
    )(win_off, sorted_slots.reshape(1, n), d_occ_t)


def _scatter_kernel_multi(off_ref, slots_ref, d_ref, out_ref, slc, dch, sem_s, sem_d,
                          *, bf16, nbuf, cap, pack):
    """Windowed scatter over `nbuf` concatenated per-source buffers.

    The fully-sharded engine's cotangent stream is nbuf buffers of `cap`
    positions each, all targeting the SAME local table shard; grid step j
    owns table window j and accumulates the matching span of every
    buffer before one [W, K] block write — each output block is visited
    exactly once, so no cross-step revisit semantics are needed.
    `off_ref` is [nbuf, wpo+1] buffer-local window offsets with
    off_ref[i, wpo] extended to `cap` (pads ride in the last window)."""
    from jax.experimental import pallas as pl

    j = pl.program_id(0)
    K8 = d_ref.shape[0]
    K = out_ref.shape[1] // pack

    def buf_step(i, acc_t):
        # aligned-down reads stay >= i*cap (cap % CHUNK == 0)
        return _scatter_span(
            slots_ref, d_ref, slc, dch, sem_s, sem_d,
            j * WINDOW, i * cap + off_ref[i, j], i * cap + off_ref[i, j + 1],
            acc_t, bf16, pack, K,
        )

    rows = pack * K if pack > 1 else K8
    acc_t = jnp.zeros((rows, WINDOW // pack), jnp.float32)
    acc_t = jax.lax.fori_loop(0, nbuf, buf_step, acc_t)
    out_ref[:, :] = (acc_t if pack > 1 else acc_t[0:K, :]).T


def _scatter_pallas_multi(d_occ_t, sorted_slots, loc_off, num_slots, k, cap,
                          bf16=False, pack=1):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    K8, n = d_occ_t.shape
    nbuf, wpo1 = loc_off.shape
    n_win = num_slots // WINDOW
    assert wpo1 == n_win + 1, (loc_off.shape, n_win)
    assert cap % CHUNK == 0 and nbuf * cap == n, (nbuf, cap, n)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_win,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # slots [1, Np]
            pl.BlockSpec(memory_space=pl.ANY),  # d [K8, Np]
        ],
        out_specs=pl.BlockSpec((WINDOW // pack, pack * k), lambda t, off: (t, 0)),
        scratch_shapes=[
            pltpu.VMEM((PIPE_NB, 1, CHUNK), jnp.int32),
            pltpu.VMEM((PIPE_NB, K8, CHUNK), jnp.float32),
            pltpu.SemaphoreType.DMA((PIPE_NB,)),
            pltpu.SemaphoreType.DMA((PIPE_NB,)),
        ],
    )
    return pl.pallas_call(
        partial(_scatter_kernel_multi, bf16=bf16, nbuf=nbuf, cap=cap, pack=pack),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_slots // pack, pack * k), jnp.float32),
    )(loc_off, sorted_slots.reshape(1, n), d_occ_t)


def _scatter_ftrl_kernel(off_ref, slots_ref, d_ref, w_ref, n_ref, z_ref,
                         w_out, n_out, z_out, slc, dch, sem_s, sem_d,
                         *, bf16, pack, alpha, beta, lambda1, lambda2):
    """Fused windowed scatter-add + FTRL-proximal window update: grid
    step t accumulates window t's complete gradient block (every chunk
    of its span — the block's gradient is FINAL at the write point, so
    applying the optimizer here is exact) and writes the UPDATED
    (w, n, z) blocks instead of the gradient. The gradient never
    exists in HBM, and the separate dense optimizer sweep — O(S) per
    step regardless of batch (docs/PERF.md lever 5b) — disappears into
    this already-streaming pass. FTRL math is optim/ftrl._update_one
    verbatim (incl. the lazy-init parity guard)."""
    from jax.experimental import pallas as pl

    from xflow_tpu.optim.ftrl import _update_one

    t = pl.program_id(0)
    K8 = d_ref.shape[0]
    K = w_out.shape[1] // pack
    rows = pack * K if pack > 1 else K8
    acc_t = jnp.zeros((rows, WINDOW // pack), jnp.float32)
    acc_t = _scatter_span(
        slots_ref, d_ref, slc, dch, sem_s, sem_d,
        t * WINDOW, off_ref[t], off_ref[t + 1], acc_t, bf16, pack, K,
    )
    g = (acc_t if pack > 1 else acc_t[0:K, :]).T  # [W/pack, pack*K]
    w_new, n_new, z_new = _update_one(
        w_ref[:, :], n_ref[:, :], z_ref[:, :], g, alpha, beta, lambda1, lambda2
    )
    w_out[:, :] = w_new
    n_out[:, :] = n_new
    z_out[:, :] = z_new


def _scatter_ftrl_pallas(d_occ_t, sorted_slots, win_off, w, n, z, k, hp,
                         bf16=False, pack=1):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    K8, n_occ = d_occ_t.shape
    num_slots = w.shape[0] * pack
    n_win = num_slots // WINDOW
    state_block = pl.BlockSpec((WINDOW // pack, pack * k), lambda t, off: (t, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_win,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # slots [1, Np]
            pl.BlockSpec(memory_space=pl.ANY),  # d [K8, Np]
            state_block, state_block, state_block,  # w, n, z windows
        ],
        out_specs=(state_block, state_block, state_block),
        scratch_shapes=[
            pltpu.VMEM((PIPE_NB, 1, CHUNK), jnp.int32),
            pltpu.VMEM((PIPE_NB, K8, CHUNK), jnp.float32),
            pltpu.SemaphoreType.DMA((PIPE_NB,)),
            pltpu.SemaphoreType.DMA((PIPE_NB,)),
        ],
    )
    shape = jax.ShapeDtypeStruct((num_slots // pack, pack * k), jnp.float32)
    return pl.pallas_call(
        partial(
            _scatter_ftrl_kernel, bf16=bf16, pack=pack, alpha=hp.alpha,
            beta=hp.beta, lambda1=hp.lambda1, lambda2=hp.lambda2,
        ),
        grid_spec=grid_spec,
        out_shape=(shape, shape, shape),
        # update the state in place. Alias indices count ALL flattened
        # call operands INCLUDING the scalar-prefetch array: 0=win_off,
        # 1=slots, 2=d, 3=w, 4=n, 5=z -> outputs 0..2 (verified: a
        # {2: 0} mapping is rejected with d's shape in the error)
        input_output_aliases={3: 0, 4: 1, 5: 2},
    )(win_off, sorted_slots.reshape(1, n_occ), d_occ_t, w, n, z)


def scatter_ftrl_sorted(d_occ_t, sorted_slots, win_off, w, n, z, k: int, hp,
                        bf16=False, pack=1):
    """Windowed scatter-add of the occurrence cotangent + FTRL update in
    ONE table pass: returns (w', n', z'). `hp` carries
    (alpha, beta, lambda1, lambda2) — cfg.optim.ftrl. Semantically
    identical to `table_gather_sorted`'s VJP followed by
    optim/ftrl._update_one; the fusion removes the HBM-materialized
    gradient and the separate dense optimizer sweep (the CPU/XLA
    fallback composes exactly those pieces, so tests equate the two)."""
    if _on_tpu():
        return _scatter_ftrl_pallas(
            d_occ_t, sorted_slots, win_off, w, n, z, k, hp, bf16, pack
        )
    from xflow_tpu.optim.ftrl import _update_one

    num_slots = w.shape[0] * pack
    g = _scatter_xla(d_occ_t, sorted_slots, win_off, num_slots, k, pack)
    return _update_one(w, n, z, g, hp.alpha, hp.beta, hp.lambda1, hp.lambda2)


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


# ------------------------------------------------- row-sum kernel (fwd)

def _rowsum_kernel_factory(num_rows, ch, chunk):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(rows_ref, vals_ref, out_ref, vchunk, vt_ref, rchunk, sem_v, sem_r):
        n_chunks = vals_ref.shape[1] // chunk
        out_ref[:, :] = jnp.zeros((num_rows, ch), jnp.float32)

        def chunk_step(c, carry):
            o = c * chunk
            cp_r = pltpu.make_async_copy(rows_ref.at[:, pl.ds(o, chunk)], rchunk, sem_r)
            cp_r.start()
            cp_v = pltpu.make_async_copy(vals_ref.at[:, pl.ds(o, chunk)], vchunk, sem_v)
            cp_v.start()
            cp_r.wait()
            cp_v.wait()
            vt_ref[:, :] = vchunk[:, :].T  # [chunk, ch]: rows readable per i

            def inner(i, carry2):
                r = rchunk[0, i]
                out_ref[pl.ds(r, 1), :] += vt_ref[pl.ds(i, 1), :]
                return carry2

            jax.lax.fori_loop(0, chunk, inner, 0, unroll=chunk)
            return carry

        jax.lax.fori_loop(0, n_chunks, chunk_step, 0)

    return kernel


def _rowsum_pallas(vals_t, rows, num_rows):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ch, n = vals_t.shape
    assert n % CHUNK == 0, (n, CHUNK)
    assert ch % 8 == 0, ch
    return pl.pallas_call(
        _rowsum_kernel_factory(num_rows, ch, CHUNK),
        grid=(1,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((num_rows, ch), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((num_rows, ch), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((ch, CHUNK), jnp.float32),
            pltpu.VMEM((CHUNK, ch), jnp.float32),
            pltpu.SMEM((1, CHUNK), jnp.int32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
    )(rows.reshape(1, n), vals_t)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def row_sums_sorted(vals_t, rows, num_rows):
    """Σ over occurrences into rows: out[r, c] = Σ_{j: rows[j]=r} vals_t[c, j].

    The occurrence→row reduction is the FM sorted-path wall (docs/PERF.md):
    XLA's scatter runs ~24 ns/occurrence at bench shapes. On TPU this op is
    a Pallas kernel holding the [num_rows, ch] accumulator VMEM-resident
    and doing one dynamic-sublane read-modify-write per occurrence on the
    scalar core (~15 ns measured, 1.6×) — viable only while
    num_rows × 128 lanes × 4 B fits VMEM (num_rows ≤ ~64k), which is why
    MVM's [B·nf] segment space keeps the XLA segment-sum instead.
    Constraints: ch % 8 == 0, len(rows) % CHUNK == 0 (pad rows with 0 and
    vals with 0 — pads accumulate zero into row 0). Differentiable in
    `vals_t`; the VJP is the row gather d_out.T[:, rows]."""
    # VMEM guard: the accumulator occupies num_rows × 128 lanes × 4 B
    # regardless of ch (lane padding); 64k rows = 33.5 MB is measured to
    # fit on v5e, 2× that failed to compile (tools/rowsum_probe.py) —
    # larger batches fall back to the XLA segment-sum rather than dying
    # in Mosaic. num_rows % 8: a non-sublane-aligned accumulator block
    # (e.g. batch_size=50) would also fail deep in Mosaic (advisor r2).
    if _on_tpu() and num_rows <= 65536 and num_rows % 8 == 0:
        return _rowsum_pallas(vals_t, rows, num_rows)
    return jax.ops.segment_sum(vals_t.T, rows, num_segments=num_rows)


def _rowsum_fwd(vals_t, rows, num_rows):
    return row_sums_sorted(vals_t, rows, num_rows), rows


def _rowsum_bwd(num_rows, rows, d_out):
    return jnp.take(d_out.T, rows, axis=1), None


row_sums_sorted.defvjp(_rowsum_fwd, _rowsum_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def segment_sum_channels(vals_t, seg, num_segments):
    """Σ over occurrences into segments: out[s, c] = Σ_{j: seg[j]=s} vals_t[c, j].

    The SEGMENT-space counterpart of `row_sums_sorted` for row sides too
    large for the VMEM accumulator (MVM's and FFM's [B·nf] per-(row,
    field) spaces). Forward is XLA's per-channel scatter-add; the win is
    the BACKWARD: the plain VJP gathers ch-wide rows from the [S, ch]
    cotangent, whose (8, 128)-tiled HBM layout serves ch/128 useful
    lanes per line. Here the bwd gathers PACK-row groups from the free
    [S/PACK, PACK·ch] reshape — full 512 B lines — and sub-selects
    elementwise (`_sub_select`, never a matmul: gradients stay exact).
    Bench-level effect: MVM dupfields 651k → ~705k ex/s (the remaining
    wall is the forward scatter-add itself — docs/PERF.md 3a). Falls
    back to the plain gather when S % PACK != 0."""
    sums = jax.vmap(
        lambda r: jax.ops.segment_sum(r, seg, num_segments=num_segments)
    )(vals_t)
    return sums.T  # [S, ch]


def _ssc_fwd(vals_t, seg, num_segments):
    return segment_sum_channels(vals_t, seg, num_segments), seg


def _ssc_bwd(num_segments, seg, d_out):
    ch = d_out.shape[1]
    if num_segments % PACK:
        return jnp.take(d_out, seg, axis=0).T, None
    grouped = d_out.reshape(num_segments // PACK, PACK * ch)
    rows = jnp.take(grouped, seg // PACK, axis=0)  # [Np, PACK*ch]
    return _sub_select(rows, seg % PACK, PACK, ch).T, None


segment_sum_channels.defvjp(_ssc_fwd, _ssc_bwd)


# ------------------------------------------------------------ public op

@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def table_gather_sorted(table, sorted_slots, win_off, bf16=False, pack=1):
    """Per-occurrence table rows, transposed: [K8, Np] for slot-sorted
    occurrences. Differentiable in `table`; the VJP is the windowed
    scatter-add. Rows K..K8 are zero. Padded columns (positions past the
    batch's real occurrences) hold row `S-1`'s values, not zeros —
    multiply by `sorted_mask` before use. `bf16` (static — thread
    cfg.data.sorted_bf16 here) trades the f32-accurate 3-pass MXU
    contraction for one rounded pass (see `_dot_f32`). `pack` (static;
    callers derive it with `pack_of`) says the table is stored
    [S/pack, pack*K] (see `pack_table`); slot indices stay LOGICAL, the
    output is identical either way, and the VJP writes the gradient in
    the table's own layout."""
    if _on_tpu():
        return _gather_pallas(table, sorted_slots, win_off, bf16, pack)
    return _gather_xla(table, sorted_slots, win_off, pack)


def _gather_fwd(table, sorted_slots, win_off, bf16=False, pack=1):
    return table_gather_sorted(table, sorted_slots, win_off, bf16, pack), (
        sorted_slots,
        win_off,
        table.shape,
    )


def _gather_bwd(bf16, pack, res, d_occ_t):
    sorted_slots, win_off, (rows, width) = res
    num_slots, k = rows * pack, width // pack
    if _on_tpu():
        d_table = _scatter_pallas(
            d_occ_t, sorted_slots, win_off, num_slots, k, bf16, pack
        )
    else:
        d_table = _scatter_xla(d_occ_t, sorted_slots, win_off, num_slots, k, pack)
    return d_table, None, None


table_gather_sorted.defvjp(_gather_fwd, _gather_bwd)


# ------------------------------------------- multi-buffer op (fullshard)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def table_gather_sorted_multi(table, sorted_slots, loc_off, bf16=False, pack=1):
    """`table_gather_sorted` over a concatenated multi-buffer stream: the
    per-call input is `nbuf` fixed-capacity buffers, each slot-sorted
    over the SAME table (the fullshard engine's per-source-shard buffers
    over the local shard, pads at slot S_local-1 / mask 0; a single
    device's NS row-contiguous sub-batch plans over the whole table).
    Both directions are WINDOW-MAJOR — grid step j owns table window j
    and walks every buffer's matching span — so the table crosses
    HBM→VMEM exactly ONCE per call regardless of nbuf (the source-major
    order read it nbuf times; measured 2×+ on the MVM segment path at
    NS=4). The VJP accumulates every buffer's span into one [W, K]
    block write per window (`_scatter_kernel_multi`); in the fullshard
    engine the table-shard gradient never leaves the device.

    `loc_off` [nbuf, wpo+1]: buffer-local window offsets, last entry
    extended to `cap`. Capacity = sorted_slots.size // nbuf, a CHUNK
    multiple (host contract: parallel/sorted_fullshard.py buffers, or
    `plan_sorted_stacked` sub-batch plans via `sorted_gather_map`).
    `pack` as in `table_gather_sorted` (the table stored
    [S/pack, pack*K])."""
    if _on_tpu():
        cap = sorted_slots.shape[0] // loc_off.shape[0]
        return _gather_pallas_multi(table, sorted_slots, loc_off, cap, bf16, pack)
    return _gather_xla(table, sorted_slots, None, pack)


def _gather_multi_fwd(table, sorted_slots, loc_off, bf16=False, pack=1):
    return table_gather_sorted_multi(table, sorted_slots, loc_off, bf16, pack), (
        sorted_slots,
        loc_off,
        table.shape,
    )


def _gather_multi_bwd(bf16, pack, res, d_occ_t):
    sorted_slots, loc_off, (rows, width) = res
    num_slots, k = rows * pack, width // pack
    if _on_tpu():
        cap = sorted_slots.shape[0] // loc_off.shape[0]
        d_table = _scatter_pallas_multi(
            d_occ_t, sorted_slots, loc_off, num_slots, k, cap, bf16, pack
        )
    else:
        d_table = _scatter_xla(d_occ_t, sorted_slots, None, num_slots, k, pack)
    return d_table, None, None


table_gather_sorted_multi.defvjp(_gather_multi_fwd, _gather_multi_bwd)
