"""Headline benchmark: sparse-LR train-step throughput (examples/sec).

BASELINE.md: the reference publishes no numbers; the north star is
Criteo-1TB LR on v5e-64 at ≥50M examples/sec/pod ⇒ ~781k ex/s/chip.
`vs_baseline` reports this chip's throughput against that per-chip
share (value 1.0 = on track for the pod target).

Measurement: K train steps run inside ONE compiled program
(`lax.scan` over K pre-staged device batches) and completion is forced
by a host value read — per-dispatch host/tunnel overhead would
otherwise dominate (observed ~0.5 ms/dispatch on tunneled devices,
vs ~100 µs of real device work per step).

Prints ONE JSON line:
  {"metric": "lr_examples_per_sec", "value": N, "unit": "examples/sec",
   "vs_baseline": N}
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

PER_CHIP_TARGET = 50_000_000 / 64  # north-star pod target / chips


def stage_row_batches(rng, num_slots: int, num_fields: int, K: int, B: int,
                      F: int, with_slots: bool = True,
                      with_fields: bool = True) -> dict:
    """Host-staged [K, B, F] row-major batch arrays — the pre-staged
    device-bench harness shape, shared with tools/step_decompose.py so
    the two harnesses measure the same data distribution. The flags
    skip draws a caller replaces anyway (generating ~64 MB at the CLI
    shape only to throw it away): bench's main() takes slots
    per-distribution from `draw_slots` (zipf/uniform), and the MVM/FFM
    exclusive-fields shape uses one feature per field."""
    out = {}
    if with_slots:
        out["slots"] = rng.integers(0, num_slots, (K, B, F)).astype(np.int32)
    if with_fields:
        out["fields"] = rng.integers(0, num_fields, (K, B, F)).astype(np.int32)
    out.update({
        "mask": (rng.random((K, B, F)) < 0.6).astype(np.float32),
        "labels": (rng.random((K, B)) < 0.4).astype(np.float32),
        "row_mask": np.ones((K, B), np.float32),
    })
    return out


def measure_e2e(args, model: str, rows: int, use_cache: bool = False) -> float:
    """End-to-end trainer throughput: libffm file on disk → C++ parser →
    (sorted plan in the prefetch thread) → jitted device step. This is
    the number a user actually gets from `xflow train`, as opposed to
    the pre-staged device-only headline — the gap between them is the
    host data plane (docs/PERF.md "Host data plane"). Epoch 1 warms the
    compile caches; epoch 2 is timed. Returns examples/sec.

    `use_cache` packs the generated shard into the binary shard cache
    first (data/shardcache.py — hash at convert time, mmap zero-copy
    batches) and trains with data.cache=on: the parse/hash-free e2e
    figure, paired with the text number as the measured host gap."""
    import os
    import tempfile
    import time as _time

    from xflow_tpu.config import Config, override
    from xflow_tpu.data.synth import generate_shards_bulk
    from xflow_tpu.train.trainer import Trainer

    with tempfile.TemporaryDirectory() as td:
        prefix = os.path.join(td, "train")
        t0 = _time.perf_counter()
        generate_shards_bulk(prefix, 1, rows, num_fields=18,
                             ids_per_field=200_000, seed=0)
        gen_s = _time.perf_counter() - t0
        cfg = override(
            Config(),
            **{
                "model.name": model,
                "data.train_path": prefix,
                "data.log2_slots": args.log2_slots if not args.smoke else 16,
                # synth emits exactly one feature per field: size the padded
                # capacity to the data (a user would do the same) instead of
                # carrying 14 dead masked columns per row through the host
                # sort, the transfer, and the kernels
                "data.max_nnz": 18,
                "data.sorted_bf16": args.sorted_bf16,
                "data.batch_size": args.batch if not args.smoke else 2048,
                "data.sorted_sub_batches": args.sub_batches,
                "model.num_fields": 18,
                "train.epochs": 1,
                "train.pred_dump": False,
                "data.cache": "on" if use_cache else "off",
            },
        )
        if use_cache:
            from xflow_tpu.data.shardcache import build_cache

            t0 = _time.perf_counter()
            built = build_cache(prefix, cfg.data)
            print(
                f"# e2e[{model}]: cache build {built['rows']} rows "
                f"({built['bytes']} bytes) in "
                f"{_time.perf_counter() - t0:.1f}s",
                file=sys.stderr,
            )
        trainer = Trainer(cfg)
        res_warm = trainer.fit()  # epoch 1: compile + first pass
        t0 = _time.perf_counter()
        res = trainer.fit()  # timed epoch (fresh pass over the file)
        secs = _time.perf_counter() - t0
        rate = res.examples / secs
        print(
            f"# e2e[{model}]: rows={rows} gen={gen_s:.1f}s warm={res_warm.seconds:.1f}s "
            f"timed_epoch={secs:.2f}s steps={res.steps} sorted={trainer._sorted} "
            f"parser_threads=auto({os.cpu_count()} cores)",
            file=sys.stderr,
        )
        return rate


def bench_e2e(args) -> int:
    model = "fm" if args.model in ("all", "fm") else args.model
    rows = args.e2e_rows if not args.smoke else 20_000
    rate = measure_e2e(args, model, rows)
    rec = {
        "metric": f"e2e_{model}_examples_per_sec",
        "value": round(rate, 1),
        "unit": "examples/sec",
        "vs_baseline": round(rate / PER_CHIP_TARGET, 3),
        # wall clock for trajectory correlation only; every
        # duration above comes from time.perf_counter()
        "ts": round(time.time(), 3),
    }
    if args.e2e_cache:
        # the packed-shard-cache leg of the same workload: its
        # `_examples_per_sec` suffix makes it its own gated
        # perf_ledger group, and the speedup is the measured host gap
        cached = measure_e2e(args, model, rows, use_cache=True)
        rec[f"e2e_{model}_cached_examples_per_sec"] = round(cached, 1)
        rec["cache_speedup"] = round(cached / rate, 3) if rate > 0 else None
    print(json.dumps(rec))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--nnz", type=int, default=32)
    ap.add_argument("--log2-slots", type=int, default=22)
    ap.add_argument("--scan-steps", type=int, default=32, help="train steps per compiled program")
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--model", default="all",
                    help="lr|fm|mvm|ffm|all (all = one JSON line, LR headline)")
    ap.add_argument("--smoke", action="store_true", help="tiny shapes for CI")
    ap.add_argument("--no-sorted", action="store_true",
                    help="disable the sorted-window layout (FM and MVM; ops/sorted_table.py)")
    ap.add_argument("--dedup", action="store_true",
                    help="host-dedup the row-major batches (unique_slots + "
                         "inverse; measures docs/PERF.md lever 4 on the "
                         "GSPMD-path step)")
    ap.add_argument("--sub-batches", type=int, default=0,
                    help="sorted-layout sub-batches per step (0 = auto)")
    ap.add_argument("--no-zipf", action="store_true",
                    help="skip the skewed-slot (Zipf) companion runs")
    ap.add_argument("--sorted-bf16", action="store_true",
                    help="bf16 fast mode for the sorted kernels (cfg.data.sorted_bf16)")
    ap.add_argument("--e2e", action="store_true",
                    help="end-to-end pipeline bench (file -> C++ parser -> "
                         "sorted plan -> device) instead of pre-staged batches")
    ap.add_argument("--e2e-rows", type=int, default=1_000_000)
    ap.add_argument("--e2e-cache", action="store_true",
                    help="with --e2e: also measure the packed-shard-cache "
                         "leg of the same workload (data/shardcache.py) — "
                         "the record gains e2e_<model>_cached_examples_per_sec "
                         "+ cache_speedup")
    args = ap.parse_args()
    if args.smoke:
        args.batch, args.log2_slots, args.scan_steps, args.repeats = 2048, 16, 4, 2

    import os

    if os.environ.get("JAX_PLATFORMS"):
        # ambient site config may pin another platform; env takes priority
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import jax
    import jax.numpy as jnp

    from xflow_tpu.config import Config, override
    from xflow_tpu.models import get_model
    from xflow_tpu.optim import get_optimizer
    from xflow_tpu.train.state import init_state
    from xflow_tpu.train.step import make_train_step

    K, B, F = args.scan_steps, args.batch, args.nnz
    rng = np.random.default_rng(0)

    def draw_slots(num_slots: int, dist: str, shape=None) -> np.ndarray:
        """[K, B, F] slot ids. 'zipf' draws ranks from a bounded power law
        (alpha=1.05, Criteo-like head) and scrambles them with a
        multiplicative bijection mod 2^k so frequency skew survives but
        index locality (an artifact no hashed id stream has) does not."""
        shape = shape or (K, B, F)
        if dist == "uniform":
            return rng.integers(0, num_slots, shape).astype(np.int32)
        pmf = 1.0 / np.arange(1, num_slots + 1, dtype=np.float64) ** 1.05
        cdf = np.cumsum(pmf / pmf.sum())
        ranks = np.searchsorted(cdf, rng.random(shape))
        return ((ranks * 2654435761) % num_slots).astype(np.int32)

    if args.e2e:
        return bench_e2e(args)

    zipf_slots_cache = {}
    # compile accounting (telemetry.CompileRecorder): each model's
    # K-step program stamps its compile time and cost analysis; the
    # headline's lands in the JSON record so BENCH_r*/BENCH_SCALE
    # datapoints carry cost context, not just throughput. First bench
    # of a model wins (companion shapes — s24/bf16 — would overwrite
    # the CLI-shape cost the record describes).
    cost_by_model: dict = {}

    def bench_model(name: str, dists, dup_fields: bool = False,
                    log2_slots: int = 0, batch: int = 0, nnz: int = 0,
                    sorted_bf16: bool = None) -> dict:
        """Compile the model's K-step program ONCE, then time each slot
        distribution on it (shapes identical → no recompile).

        MVM benches its NATURAL data shape by default: one feature per
        field (fields 0..nnz-1 — what libffm FFM rows are), which the
        exclusive-fields product path (models/mvm.py) requires; per-row
        occurrence count matches the other models exactly.
        `dup_fields=True` instead draws random fields over num_fields=18
        (every row has duplicate fields), exercising the general
        segment-sum path — recorded as the `mvm_dupfields_*` companion.

        FFM benches at its practical shape — 18 one-feature-per-field
        fields, k=4 per opposing field (a [S, 73] fused row) — on the
        aligned-hybrid sorted engine at the full CLI batch (round 5;
        the round-4 16k cap was a segment-engine argument and the
        hybrid has no segment state).

        `log2_slots`/`batch`/`nnz` override the CLI shape (0 = CLI) —
        the 2^24 north-star companion runs use them.
        """
        log2_slots = log2_slots or args.log2_slots
        B_, F_ = batch or args.batch, nnz or args.nnz
        if sorted_bf16 is None:
            sorted_bf16 = args.sorted_bf16
        overrides = {
            "model.name": name,
            "data.log2_slots": log2_slots,
            "data.max_nnz": F_,
            "data.batch_size": B_,
            "data.sorted_sub_batches": args.sub_batches,
            "data.sorted_bf16": sorted_bf16,
        }
        if name == "mvm":
            if dup_fields:
                overrides["model.mvm_exclusive"] = "off"
            else:
                overrides["model.num_fields"] = F_
                overrides["model.mvm_exclusive"] = "on"
        if name == "ffm":
            overrides["model.num_fields"] = F_
            overrides["model.v_dim"] = 4
        cfg = override(Config(), **overrides)
        model, opt = get_model(name), get_optimizer("ftrl")
        step = make_train_step(model, opt, cfg, jit=False)
        # staging shared with tools/step_decompose.py (same harness,
        # same distribution); MVM/FFM's exclusive-fields shape uses one
        # feature per field instead of random fields, so that draw is
        # skipped too
        exclusive = name in ("mvm", "ffm") and not dup_fields
        staged = stage_row_batches(rng, cfg.num_slots, cfg.model.num_fields,
                                   K, B_, F_, with_slots=False,
                                   with_fields=not exclusive)
        mask_np = staged["mask"]
        if exclusive:
            fields_host = np.broadcast_to(
                np.arange(F_, dtype=np.int32), (K, B_, F_)
            ).copy()
        else:
            fields_host = staged["fields"]
        common = {
            "fields": jnp.asarray(fields_host),
            "mask": jnp.asarray(mask_np),
            "labels": jnp.asarray(staged["labels"]),
            "row_mask": jnp.asarray(staged["row_mask"]),
        }

        def make_batches(dist: str) -> dict:
            ck = (cfg.num_slots, B_, F_)
            if dist == "zipf" and ck not in zipf_slots_cache:
                zipf_slots_cache[ck] = draw_slots(cfg.num_slots, "zipf", (K, B_, F_))
            slots_np = (
                zipf_slots_cache[ck]
                if dist == "zipf"
                else draw_slots(cfg.num_slots, "uniform", (K, B_, F_))
            )
            batches = {**common, "slots": jnp.asarray(slots_np)}
            # only the row-major step consumes dedup arrays; attaching them
            # to a sorted-path run would measure dead transfers
            if args.dedup and (args.no_sorted or name == "lr"):
                # host dedup for the row-major step (data.dedup analog;
                # the skewed-data / cross-chip-volume lever): ships
                # (unique_slots, inverse) per scan step
                from xflow_tpu.ops.sorted_table import dedup_slots

                cap = int(B_ * F_ * 0.5)
                pairs = [dedup_slots(slots_np[i], cap) for i in range(K)]
                if all(p is not None for p in pairs):
                    batches["unique_slots"] = jnp.asarray(
                        np.stack([p[0] for p in pairs])
                    )
                    batches["inverse"] = jnp.asarray(np.stack([p[1] for p in pairs]))
                    print(f"# {name}: dedup on, cap={cap}", file=sys.stderr)
                else:
                    print(f"# {name}: dedup overflow (uniques > {cap}); direct",
                          file=sys.stderr)
            if name in ("fm", "mvm", "ffm") and not args.no_sorted:
                # sorted-window layout (ops/sorted_table.py): host-side
                # plan, sub-batched like the trainer (cache-resident rows).
                # FFM rides the ALIGNED HYBRID (round 5, models/ffm.py):
                # flat plan with fields + the host placement permutation
                from xflow_tpu.ops.sorted_table import (
                    plan_sorted_stacked,
                    resolve_sub_batches,
                )

                ns = 1 if name == "ffm" else resolve_sub_batches(cfg)
                # the MVM segment path and FFM consume per-occurrence
                # fields; the MVM product path routes on their absence
                use_fields = name == "ffm" or (name == "mvm" and dup_fields)
                plans = [
                    plan_sorted_stacked(
                        slots_np[i], mask_np[i], cfg.num_slots,
                        fields=fields_host[i] if use_fields else None,
                        num_sub=ns,
                    )
                    for i in range(K)
                ]
                path = (
                    f"sorted layout ({'segment' if use_fields else 'product'})"
                    if name == "mvm"
                    else "sorted layout (aligned hybrid)"
                    if name == "ffm"
                    else "sorted layout"
                )
                print(f"# {name}: {path}, sub_batches={ns}", file=sys.stderr)
                batches["sorted_slots"] = jnp.asarray(np.stack([p.sorted_slots for p in plans]))
                batches["sorted_row"] = jnp.asarray(np.stack([p.sorted_row for p in plans]))
                batches["sorted_mask"] = jnp.asarray(np.stack([p.sorted_mask for p in plans]))
                batches["win_off"] = jnp.asarray(np.stack([p.win_off for p in plans]))
                if use_fields:
                    batches["sorted_fields"] = jnp.asarray(
                        np.stack([p.sorted_fields for p in plans])
                    )
                if name == "ffm":
                    from xflow_tpu.models.ffm import ffm_invperm

                    batches["ffm_invperm"] = jnp.asarray(
                        np.stack(
                            [
                                ffm_invperm(
                                    p.sorted_row, p.sorted_fields,
                                    p.sorted_mask, B_, cfg.model.num_fields,
                                )
                                for p in plans
                            ]
                        )
                    )
            return batches

        from functools import partial

        # donate the state like every production engine does (train/
        # step.py and the three sharded builders): without it the K-step
        # scan keeps TWO copies of tables+optimizer state live in HBM
        # and benchmarks a memory profile the real step never has
        # (XF703, docs/STATIC_ANALYSIS.md)
        @partial(jax.jit, donate_argnums=(0,))
        def run_k_steps(state, batches):
            def body(st, batch):
                st, m = step(st, batch)
                return st, m["loss"]

            return jax.lax.scan(body, state, batches)

        from xflow_tpu.telemetry import CompileRecorder

        crec = CompileRecorder()
        run_k = crec.wrap(f"bench.{name}", run_k_steps)

        rates = {}
        for dist in dists:
            state = init_state(model, opt, cfg)
            batches = make_batches(dist)
            # warmup (compiles on the first dist; cache hit afterwards)
            state, losses = run_k(state, batches)
            _ = float(losses[-1])  # host read = hard sync
            times = []
            # companion runs (non-headline model or zipf) use fewer
            # repeats: the full 3-model x 2-dist sweep must fit a
            # single driver invocation comfortably
            reps = (
                args.repeats
                if (name == "lr" and dist == "uniform") or args.model != "all"
                else min(args.repeats, 3)
            )
            for _ in range(reps):
                t0 = time.perf_counter()
                state, losses = run_k(state, batches)
                _ = float(losses[-1])
                times.append(time.perf_counter() - t0)
            best = min(times)
            print(
                f"# {name}[{dist}]: device={jax.devices()[0]} scan_steps={K} batch={B_} "
                f"nnz={F_} slots=2^{log2_slots} best={best*1e3:.1f}ms/{K}steps "
                f"({best/K*1e6:.0f}µs/step) times_ms={[round(t*1e3,1) for t in times]}",
                file=sys.stderr,
            )
            rates[dist] = K * B_ / best
        info = crec.latest(f"bench.{name}")
        if info and info.get("flops"):
            cost_by_model.setdefault(name, {
                "compile_time_s": info["compile_time_s"],
                "flops": info["flops"],
                "bytes_accessed": info.get("bytes_accessed"),
                "examples_per_call": K * B_,  # one call = K steps x B_ rows
            })
        return rates

    kernel_parity = None
    if jax.default_backend() == "tpu" and not args.no_sorted:
        # on-device parity gate (VERDICT r2 item 6): the sorted-window
        # kernels are only lowered through Mosaic on a real chip, so the
        # silent-rounding class of bug is only visible here — fail the
        # bench loudly rather than record a fast-but-wrong number
        from xflow_tpu.tools.kernel_parity import check_kernel_parity

        par = check_kernel_parity()
        print(f"# kernel_parity: {par}", file=sys.stderr)
        if not par["ok"]:
            # fail loudly INSTEAD of recording a fast-but-wrong number:
            # no throughput line, nonzero exit
            print(json.dumps({"metric": "kernel_parity", "value": 0,
                              "unit": "bool", "vs_baseline": 0,
                              "error": f"kernel parity FAILED: {par['checks']}"}))
            return 1
        kernel_parity = "ok"

    models = ["lr", "fm", "mvm"] if args.model == "all" else [args.model]

    def model_shape(name: str) -> dict:
        # FFM benches at its practical shape — 18 one-feature-per-field
        # fields, k=4 — at 2x the CLI batch: wide-row models amortize
        # the per-step table-sized passes over more examples (measured
        # at 2^22: 64k -> 623k ex/s, 128k -> 742k, 192k OOM, 256k hits
        # the Mosaic compile-helper limit), and the aligned hybrid
        # carries no per-(row, field) segment state, so the round-4 16k
        # cap (a sorted-segment-engine argument) no longer applies.
        # 128k is also the recommended trainer batch for FFM (and the
        # cap here: a larger CLI batch would push the doubled FFM leg
        # into the measured OOM/compiler-limit territory).
        if name == "ffm":
            return {"batch": min(args.batch * 2, 131072), "nnz": 18}
        return {}
    # skewed-slot (Zipf alpha=1.05) runs ride along (round-1 verdict item
    # 9): real CTR id streams are heavy-tailed, and uniform slots are the
    # worst case for any dedup/caching lever — record both honestly
    dists = ("uniform",) if args.no_zipf else ("uniform", "zipf")
    rates = {name: bench_model(name, dists, **model_shape(name)) for name in models}
    headline = "lr" if "lr" in rates else models[0]
    record = {
        "metric": f"{headline}_examples_per_sec",
        "value": round(rates[headline]["uniform"], 1),
        "unit": "examples/sec",
        "vs_baseline": round(rates[headline]["uniform"] / PER_CHIP_TARGET, 3),
    }
    # secondary models ride along in the same single JSON line so FM/MVM
    # regressions are visible in BENCH_r*.json (round-1 verdict item 3)
    for name in models:
        if name != headline:
            record[f"{name}_examples_per_sec"] = round(rates[name]["uniform"], 1)
            record[f"{name}_vs_baseline"] = round(rates[name]["uniform"] / PER_CHIP_TARGET, 3)
    for name in models:
        if "zipf" in rates[name]:
            record[f"zipf_{name}_examples_per_sec"] = round(rates[name]["zipf"], 1)
    if "mvm" in models and not args.no_sorted:
        # general-path companion: random fields over 18 field groups =
        # every row has multi-valued fields, so the segment-sum path runs
        dup = bench_model("mvm", ("uniform",), dup_fields=True)
        record["mvm_dupfields_examples_per_sec"] = round(dup["uniform"], 1)
        record["mvm_dupfields_vs_baseline"] = round(
            dup["uniform"] / PER_CHIP_TARGET, 3
        )
        if args.log2_slots < 24 and not args.smoke and args.model == "all":
            # the segment path at the north-star table shape (round-4
            # verdict #3: recorded, not just the product path's s24)
            d24 = bench_model("mvm", ("uniform",), dup_fields=True,
                              log2_slots=24)
            record["mvm_dupfields_s24_examples_per_sec"] = round(
                d24["uniform"], 1
            )
            record["mvm_dupfields_s24_vs_baseline"] = round(
                d24["uniform"] / PER_CHIP_TARGET, 3
            )
    if args.model == "all":
        # FFM companion (BASELINE.json config 5) at its practical shape
        # (bench_model docstring): 18 one-feature-per-field fields, k=4
        # — a [S, 73] fused row on the aligned hybrid engine
        ffm = bench_model("ffm", ("uniform",), **model_shape("ffm"))
        record["ffm_examples_per_sec"] = round(ffm["uniform"], 1)
        record["ffm_vs_baseline"] = round(ffm["uniform"] / PER_CHIP_TARGET, 3)
        if args.log2_slots < 24 and not args.smoke:
            # north-star table shape (round-3 verdict #2): 2^24 slots/chip
            # = 1B features / 64 chips — the scale BASELINE.md's pod
            # target implies; recorded so BENCH_r*.json can't flatter by
            # benching only the smaller default shape
            for name in models:
                r24 = bench_model(name, ("uniform",), log2_slots=24)
                record[f"{name}_s24_examples_per_sec"] = round(r24["uniform"], 1)
                record[f"{name}_s24_vs_baseline"] = round(
                    r24["uniform"] / PER_CHIP_TARGET, 3
                )
            # FFM at 2^24 cannot run on one chip: the FTRL state is
            # 3 x [2^21, 584] f32 = 29.4 GB against ~15 GB of HBM (the
            # [S, 73] fused row is 6.6x FM's). At-scale FFM is the
            # fullshard mesh path (2^24 over 64 chips = 460 MB/chip);
            # recorded as a note so the absence is explicit, not silent
            record["ffm_s24_note"] = (
                "infeasible single-chip: FTRL state 3x9.8GB > 15GB HBM; "
                "at-scale FFM = fullshard mesh (dryrun leg covers it)"
            )
        if not args.smoke and not args.sorted_bf16:
            # bf16 fast-mode riders (cfg.data.sorted_bf16, docs/PERF.md
            # "Precision note"): the one-pass MXU read the exact default
            # deliberately forgoes — recorded so the trade stays visible
            b16 = bench_model("fm", ("uniform",), sorted_bf16=True)
            record["fm_bf16_examples_per_sec"] = round(b16["uniform"], 1)
            record["fm_bf16_vs_baseline"] = round(
                b16["uniform"] / PER_CHIP_TARGET, 3
            )
            f16 = bench_model("ffm", ("uniform",), sorted_bf16=True,
                              **model_shape("ffm"))
            record["ffm_bf16_examples_per_sec"] = round(f16["uniform"], 1)
            record["ffm_bf16_vs_baseline"] = round(
                f16["uniform"] / PER_CHIP_TARGET, 3
            )
        if not args.smoke:
            # end-to-end rider (round-3 verdict #5): disk → C++ parser →
            # plan → device, the number `xflow train` actually delivers;
            # the gap to the pre-staged headline is the host data plane
            e2e_rate = measure_e2e(args, "fm", min(args.e2e_rows, 1_000_000))
            record["e2e_fm_examples_per_sec"] = round(e2e_rate, 1)
            record["e2e_fm_vs_baseline"] = round(e2e_rate / PER_CHIP_TARGET, 3)
    if kernel_parity is not None:
        record["kernel_parity"] = kernel_parity
    # compile/cost context for the headline model (CompileRecorder):
    # per-example model FLOPs and bytes accessed are the roofline
    # numerators tools/perf_ledger.py converts the pod target with
    # (docs/PERF.md "Measured roofline")
    cost = cost_by_model.get(headline)
    if cost:
        ex = cost["examples_per_call"]
        record["compile_time_s"] = round(cost["compile_time_s"], 3)
        record["flops_per_example"] = round(cost["flops"] / ex, 2)
        if cost.get("bytes_accessed"):
            record["bytes_per_example"] = round(cost["bytes_accessed"] / ex, 2)
    # wall clock for trajectory correlation only; all durations above are
    # time.perf_counter() (monotonic — wall clock jumps under NTP slew)
    record["ts"] = round(time.time(), 3)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
