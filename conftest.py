"""Root conftest: force an 8-virtual-device CPU platform BEFORE any test
touches jax.

This is the framework's "fake cluster" (SURVEY.md §4): the analog of the
reference's single-machine multi-process emulation (`scripts/local.sh`)
is a single-process 8-device CPU mesh. TPU execution is exercised by
bench.py / __graft_entry__.py outside pytest.
"""

import os

# belt: env for subprocesses spawned by tests
os.environ["JAX_PLATFORMS"] = os.environ.get("XFLOW_TEST_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# suspenders: the ambient site config can override JAX_PLATFORMS (this
# image pins an 'axon' TPU plugin), so pin the jax config directly too
import jax

jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
try:
    # newer jax spells the device-count override as a config option; older
    # versions only honor the XLA_FLAGS form already exported above
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass
