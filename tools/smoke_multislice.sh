#!/usr/bin/env bash
# Multi-slice bounded-staleness CI gate (docs/DISTRIBUTED.md
# "Multi-slice bounded staleness", docs/ROBUSTNESS.md "Slice lost
# mid-sync"):
#
# 1. ONE-SLICE BASELINE: a plain 1-process run over slice 0's shard set
#    — the per-slice throughput yardstick the speedup gate divides by.
#
# 2. LOCKSTEP PARITY RUN (sync.mode=sync, K=0): 2 emulated slices over
#    disjoint shard sets, delta-synced every sync.every_steps. Both
#    slices must finish with IDENTICAL final AUC (K=0 merges to one
#    model) and the streams must pass metrics_report --check.
#
# 3. BOUNDED-STALENESS THROUGHPUT RUN (sync.mode=bounded, K=8,
#    proceed-on-stale): same data, no blocking waits. The 2-slice
#    AGGREGATE examples/sec over the baseline is the speedup the
#    acceptance gate requires >= 1.8x, and the final AUC must land
#    within the parity tolerance of the lockstep run's.
#
# 4. KILL-ONE-SLICE DRILL: slice 1 is SIGKILLed entering sync round 2
#    (XFLOW_FAULT_SLICE_KILL_ROUND); the survivor must continue
#    DEGRADED (membership shrinks — kind=sync records show left=[1]),
#    the supervisor relaunches slice 1, which resumes its own
#    checkpoint, catches up from the freshest published snapshot, and
#    rejoins. Exact example accounting on BOTH slices (every row
#    trained, none double-counted by the sync tier) and --check/--health
#    stay green across the membership churn.
#
# Emits MULTICHIP_r06.json (speedup + parity numbers; ok folds the
# >= 1.8x gate) and folds it through tools/perf_ledger.py --regress.
#
# Standalone:    bash tools/smoke_multislice.sh [workdir]
# From pytest:   tests/test_multislice.py::test_smoke_multislice_script
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
# perf record destination: the repo root ONLY standalone (the per-PR
# record); under pytest it stays in the workdir
MULTICHIP_OUT="$ROOT/MULTICHIP_r06.json"
if [ -z "$WORK" ]; then
    WORK="$(mktemp -d)"
    trap 'rm -rf "$WORK"' EXIT
else
    MULTICHIP_OUT="$WORK/MULTICHIP_r06.json"
fi

export JAX_PLATFORMS=cpu
# one CPU device per slice: the runtime emulates SLICES (each its own
# process + mesh), not an in-process device mesh (xargs trims; an empty
# result must UNSET the var — XLA treats a whitespace-only value as a
# flags FILE to open and aborts)
XLA_FLAGS="$(printf '%s\n' ${XLA_FLAGS:-} \
    | grep -v xla_force_host_platform_device_count | xargs || true)"
if [ -n "$XLA_FLAGS" ]; then export XLA_FLAGS; else unset XLA_FLAGS; fi

# disjoint per-slice shard sets (different row seeds = real data
# parallelism) over ONE planted concept (--truth-seed: slices must
# learn the same truth or cross-slice merging is meaningless, and the
# eval set must share it or AUC reads as chance). 6400 rows / batch 64
# = 100 steps per slice per epoch.
python -m xflow_tpu gen-data "$WORK/tr_s0" --shards 1 --rows 6400 \
    --fields 6 --ids-per-field 50 --seed 0 --truth-seed 0 >/dev/null
python -m xflow_tpu gen-data "$WORK/tr_s1" --shards 1 --rows 6400 \
    --fields 6 --ids-per-field 50 --seed 1 --truth-seed 0 >/dev/null
python -m xflow_tpu gen-data "$WORK/te" --shards 1 --rows 1600 \
    --fields 6 --ids-per-field 50 --seed 9 --truth-seed 0 >/dev/null

# sgd, not ftrl: summed deltas are exactly the large-batch gradient
# step, so cross-slice merging is the model the parity gate can hold
# to (ftrl's w=f(z) nonlinearity makes additive sync approximate)
TRAIN_ARGS=(
    --model lr --epochs 1 --optimizer sgd
    --batch-size 64 --log2-slots 12
    --test "$WORK/te"
    --set model.num_fields=6
    --set data.max_nnz=8
    --set train.pred_dump=false
    --set train.log_every=50
    --set train.heartbeat_every=10
    --set train.checkpoint_every=10
)
SYNC_ARGS=(
    --set sync.every_steps=10
    --set sync.snapshot_every=1
    --set sync.timeout_s=10
    --set sync.retries=1
)

# summary lines are JSON on stdout: harvest examples_per_sec / auc
rate_of() {  # rate_of <log> -> sum of examples_per_sec over summaries
    python - "$1" <<'EOF'
import json, sys
tot = 0.0
for line in open(sys.argv[1]):
    line = line.strip()
    if line.startswith("{"):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if "examples_per_sec" in rec:
            tot += rec["examples_per_sec"]
print(tot)
EOF
}
auc_of() {  # auc_of <log> -> first summary auc
    python - "$1" <<'EOF'
import json, sys
for line in open(sys.argv[1]):
    line = line.strip()
    if line.startswith("{"):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if "auc" in rec:
            print(rec["auc"]); break
EOF
}

# ---- 1. one-slice baseline -------------------------------------------------
python -m xflow_tpu launch-local --num-processes 1 \
    --run-dir "$WORK/run_base" -- \
    --train "$WORK/tr_s0" "${TRAIN_ARGS[@]}" \
    --checkpoint-dir "$WORK/ck_base" >"$WORK/base.log" 2>&1
BASE_RATE="$(rate_of "$WORK/base.log")"

# ---- 2. lockstep parity run (K=0) ------------------------------------------
python -m xflow_tpu launch-multislice --slices 2 \
    --run-dir "$WORK/run_sync" -- \
    --train "$WORK/tr_s{slice}" "${TRAIN_ARGS[@]}" \
    --checkpoint-dir "$WORK/run_sync/ck_s{slice}" \
    "${SYNC_ARGS[@]}" --set sync.mode=sync >"$WORK/sync.log" 2>&1
python tools/metrics_report.py "$WORK/run_sync" --check
AUC_SYNC="$(auc_of "$WORK/sync.log")"
# K=0 merges both slices to ONE model: their final AUCs are identical
python - "$WORK/sync.log" <<'EOF'
import json, sys
aucs = [json.loads(l)["auc"] for l in open(sys.argv[1])
        if l.strip().startswith("{") and "auc" in l]
assert len(aucs) == 2 and aucs[0] == aucs[1], \
    f"lockstep slices diverged: {aucs}"
print(f"smoke_multislice: lockstep OK (both slices auc {aucs[0]:.6f})")
EOF

# ---- 3. bounded-staleness throughput run (K=8, proceed) --------------------
python -m xflow_tpu launch-multislice --slices 2 \
    --run-dir "$WORK/run_bnd" -- \
    --train "$WORK/tr_s{slice}" "${TRAIN_ARGS[@]}" \
    --checkpoint-dir "$WORK/run_bnd/ck_s{slice}" \
    "${SYNC_ARGS[@]}" --set sync.mode=bounded --set sync.staleness_k=8 \
    --set sync.on_stale=proceed >"$WORK/bnd.log" 2>&1
python tools/metrics_report.py "$WORK/run_bnd" --check
AGG_RATE="$(rate_of "$WORK/bnd.log")"
AUC_BND="$(auc_of "$WORK/bnd.log")"

# ---- 4. kill-one-slice drill -----------------------------------------------
# slice 1 is SIGKILLed entering round 2 while slice 0 is paced as a
# 0.3s/round straggler (XFLOW_FAULT_SYNC_DELAY_SLICE aims the delay at
# the SURVIVOR): the pacing + the 2s restart backoff guarantee slice
# 0's trail spans the whole leave/degraded/rejoin sequence instead of
# racing past it, and in lockstep mode slice 0 then BLOCKS on the
# rejoined slice's catch-up — both injectors exercised in one drill
XFLOW_FAULT_SLICE=1 XFLOW_FAULT_SLICE_KILL_ROUND=2 \
XFLOW_FAULT_SYNC_DELAY_S=0.3 XFLOW_FAULT_SYNC_DELAY_SLICE=0 \
python -m xflow_tpu launch-multislice --slices 2 \
    --run-dir "$WORK/run_kill" --max-restarts 2 --restart-backoff 2 -- \
    --train "$WORK/tr_s{slice}" "${TRAIN_ARGS[@]}" --epochs 2 \
    --checkpoint-dir "$WORK/run_kill/ck_s{slice}" \
    "${SYNC_ARGS[@]}" --set sync.mode=sync >"$WORK/kill.log" 2>&1
grep -q "slice 1 left the sync group (exit rc=-9)" "$WORK/kill.log" || {
    echo "kill drill: slice 1 never left the group"; cat "$WORK/kill.log"; exit 1; }
grep -q "slice 1 rejoined the sync group (relaunch gen 1)" "$WORK/kill.log" || {
    echo "kill drill: slice 1 never rejoined"; cat "$WORK/kill.log"; exit 1; }
grep -q "caught up from snapshot round" "$WORK/kill.log" || {
    echo "kill drill: no snapshot catch-up logged"; cat "$WORK/kill.log"; exit 1; }
# the survivor recorded the membership churn in its kind=sync trail
python - "$WORK/run_kill/metrics_rank0.jsonl" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1])]
syncs = [r for r in recs if r.get("kind") == "sync"]
assert any(r["left"] == [1] for r in syncs), "survivor never saw slice 1 leave"
assert any(r["joined"] == [1] for r in syncs), "survivor never saw slice 1 rejoin"
assert any(r["live"] == [0] for r in syncs), "survivor never ran degraded"
print("smoke_multislice: membership trail OK "
      f"({len(syncs)} sync rounds on the survivor)")
EOF
# exact example accounting on BOTH slices: every row trained once —
# the killed slice's rows replay from its own checkpoint, never from
# the sync tier
python - "$WORK" <<'EOF'
import os, sys
from xflow_tpu.train.checkpoint import latest_step, read_data_state

work = sys.argv[1]
for s in (0, 1):
    ck = os.path.join(work, "run_kill", f"ck_s{s}")
    step = latest_step(ck)
    assert step == 200, f"slice {s}: final committed step {step} != 200"
    ds = read_data_state(ck, step)
    assert ds and ds["completed"], f"slice {s}: data_state not completed: {ds}"
    assert ds["examples"] == 12800, \
        f"slice {s}: examples {ds['examples']} != 12800 (replay or loss)"
print("smoke_multislice: kill drill accounting OK "
      "(both slices 200 steps over 2 epochs, 12800 examples each)")
EOF
python tools/metrics_report.py "$WORK/run_kill" --check
python tools/metrics_report.py "$WORK/run_kill" --health \
    | tee "$WORK/kill_health.txt" >/dev/null
grep -q "sync tier" "$WORK/kill_health.txt" || {
    echo "kill drill: --health lacks the sync-tier section"
    cat "$WORK/kill_health.txt"; exit 1; }

# ---- verdict + MULTICHIP_r06.json ------------------------------------------
# the speedup gate needs real parallel hardware: two slice processes
# time-sharing ONE core can never aggregate past 1x, so the gate is
# probe-gated on core count like every 2-proc drill in this repo
# (smoke_topology's world probe). The semantics drills above — parity,
# membership churn, kill/rejoin accounting — already ran and asserted
# unconditionally; only the throughput CLAIM is host-gated.
CORES="$(python -c 'import os; print(os.cpu_count() or 1)')"
python - "$BASE_RATE" "$AGG_RATE" "$AUC_SYNC" "$AUC_BND" "$CORES" \
    "$MULTICHIP_OUT" <<'EOF'
import json, sys

base, agg, auc_sync, auc_bnd = (float(v) for v in sys.argv[1:5])
cores = int(sys.argv[5])
speedup = agg / base if base > 0 else 0.0
auc_gap = abs(auc_sync - auc_bnd)
gate_speedup = cores >= 2
# parity: the bounded run must land where the lockstep run landed
# (docs/PARITY.md tolerance — the same one metrics_report --auc-tol
# defaults to)
parity_ok = auc_gap <= 0.01
ok = parity_ok and (speedup >= 1.8 if gate_speedup else True)
rec = {
    "n_devices": 2,
    "slices": 2,
    "rc": 0 if ok else 1,
    "ok": ok,
    "skipped": not gate_speedup,
    "cores": cores,
    "one_slice_examples_per_sec": round(base, 1),
    "agg_examples_per_sec": round(agg, 1),
    "speedup": round(speedup, 3),
    "k": 8,
    "auc_sync": auc_sync,
    "auc_bounded": auc_bnd,
    "auc_gap": round(auc_gap, 6),
    "tail": (
        f"multislice(2): bounded K=8 aggregate {agg:.0f} ex/s vs "
        f"one-slice {base:.0f} ex/s = {speedup:.2f}x"
        + ("" if gate_speedup else
           f" (speedup gate SKIPPED: {cores} core(s) — one core cannot "
           "aggregate past 1x)")
        + f"; auc sync {auc_sync:.6f} vs bounded {auc_bnd:.6f} "
        f"(gap {auc_gap:.6f}); kill-one-slice drill: survivor degraded, "
        "rejoin via snapshot catch-up, exact accounting"
    ),
}
with open(sys.argv[6], "w") as f:
    json.dump(rec, f, indent=2)
print(rec["tail"])
assert parity_ok, f"auc gap {auc_gap:.6f} > 0.01 parity tolerance"
if gate_speedup:
    assert speedup >= 1.8, f"aggregate speedup {speedup:.2f}x < 1.8x gate"
EOF

# fold the record through the ledger's regression gate (an ok -> failed
# flip on the multichip series fails the build); --metrics scopes the
# gate to the series THIS script measures — the repo-root bench
# datapoints are machine-local numbers from other rigs
python tools/perf_ledger.py "$MULTICHIP_OUT" --regress \
    --metrics '^(multichip_ok|multislice_)' --markdown /dev/null

# repo-root hygiene: running the tools from the root must leave no
# stray artifact dirs behind (tools/__pycache__ and friends)
rm -rf "$ROOT/tools/__pycache__" "$ROOT/__pycache__"

echo "smoke_multislice: OK"
