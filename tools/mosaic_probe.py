"""Probe which Pallas/Mosaic DMA slice shapes compile on this TPU.

Decides the sorted-table kernel data layout (ops/sorted_table.py):
Mosaic rejected a [512, 1] slice of an int32 [N, 1] array ("slice shape
along dimension 1 must be aligned to tiling (128)"). Candidates:
  A. in_spec BlockSpec (512, 11) over a [S, 11] f32 table
  B. manual DMA [11, 512] slice of a [11, N] f32 array (dyn col offset)
  C. manual DMA [1, 512] slice of a [1, N] int32 array (dyn col offset)
  D. manual DMA [512, 11] slice of an [N, 11] f32 array (dyn row offset)
Plus: cost of transposing [4M, 11] -> [11, 4M] (needed if only the
transposed layouts compile).
"""

import time

import numpy as np


def try_compile(name, fn, *args):
    import jax

    try:
        out = jax.jit(fn).lower(*args).compile()
        print(f"{name}: OK")
        return True
    except Exception as e:
        msg = str(e).split("\n")[0][:140]
        print(f"{name}: FAIL — {msg}")
        return False


def main():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    W, C, K = 512, 512, 11
    S, N = 1 << 14, 1 << 13

    table = jnp.zeros((S, K), jnp.float32)
    d_t = jnp.zeros((K, N), jnp.float32)
    sl_row = jnp.zeros((1, N), jnp.int32)
    d_rows = jnp.zeros((N, K), jnp.float32)
    off = jnp.zeros((S // W + 1,), jnp.int32)

    # A: BlockSpec windowed table input
    def kern_a(off_ref, tab_ref, out_ref):
        out_ref[:, :] = tab_ref[:, :] * 2.0

    def fa(off, table):
        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(S // W,),
            in_specs=[pl.BlockSpec((W, K), lambda t, o: (t, 0))],
            out_specs=pl.BlockSpec((W, K), lambda t, o: (t, 0)),
        )
        return pl.pallas_call(kern_a, grid_spec=gs,
                              out_shape=jax.ShapeDtypeStruct((S, K), jnp.float32))(off, table)

    try_compile("A block (512,11) f32", fa, off, table)

    # B: DMA [K, C] col-slice of [K, N] f32 at dynamic 128-aligned offset
    def kern_b(off_ref, d_ref, out_ref, scr, sem):
        t = pl.program_id(0)
        start = (off_ref[t] // C) * C
        cp = pltpu.make_async_copy(d_ref.at[:, pl.ds(start, C)], scr, sem)
        cp.start()
        cp.wait()
        out_ref[0, 0] = scr[0, 0]

    def fb(off, d):
        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(4,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
            scratch_shapes=[pltpu.VMEM((K, C), jnp.float32), pltpu.SemaphoreType.DMA(())],
        )
        return pl.pallas_call(kern_b, grid_spec=gs,
                              out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32))(off, d)

    try_compile("B dma [11,512] of [11,N] f32", fb, off, d_t)

    # C: DMA [1, C] col-slice of [1, N] int32
    def kern_c(off_ref, s_ref, out_ref, scr, sem):
        t = pl.program_id(0)
        start = (off_ref[t] // C) * C
        cp = pltpu.make_async_copy(s_ref.at[:, pl.ds(start, C)], scr, sem)
        cp.start()
        cp.wait()
        out_ref[0, 0] = scr[0, 0]

    def fc(off, s):
        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(4,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
            scratch_shapes=[pltpu.VMEM((1, C), jnp.int32), pltpu.SemaphoreType.DMA(())],
        )
        return pl.pallas_call(kern_c, grid_spec=gs,
                              out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32))(off, s)

    try_compile("C dma [1,512] of [1,N] i32", fc, off, sl_row)

    # D: DMA [C, K] row-slice of [N, K] f32 at dynamic unaligned row offset
    def kern_d(off_ref, d_ref, out_ref, scr, sem):
        t = pl.program_id(0)
        start = off_ref[t]
        cp = pltpu.make_async_copy(d_ref.at[pl.ds(start, C), :], scr, sem)
        cp.start()
        cp.wait()
        out_ref[0, 0] = scr[0, 0]

    def fd(off, d):
        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(4,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
            scratch_shapes=[pltpu.VMEM((C, K), jnp.float32), pltpu.SemaphoreType.DMA(())],
        )
        return pl.pallas_call(kern_d, grid_spec=gs,
                              out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32))(off, d)

    try_compile("D dma [512,11] of [N,11] f32 dyn-row", fd, off, d_rows)

    # E: transpose cost [4M, 11] <-> [11, 4M]
    big = jnp.zeros((1 << 22, K), jnp.float32) + 1.0

    @jax.jit
    def tr(x, s):
        y = (x + s).T
        return y, y[0, 0]

    y, v = tr(big, 0.0)
    _ = float(v)
    best = 1e9
    for i in range(4):
        t0 = time.perf_counter()
        y, v = tr(big, float(i))
        _ = float(v)
        best = min(best, time.perf_counter() - t0)
    print(f"E transpose [4M,11]->[11,4M]: {best*1e3:.1f} ms")


if __name__ == "__main__":
    main()
