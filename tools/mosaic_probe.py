"""Probe which Pallas/Mosaic DMA slice shapes compile on this TPU
(decides the sorted-table kernel data layout, ops/sorted_table.py).

Retired to a thin wrapper: the implementation lives in the unified
microbench lab (`xflow_tpu/tools/bench_lab.py --suite mosaic`). This
CLI keeps working:

    python tools/mosaic_probe.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xflow_tpu.tools.bench_lab import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--suite", "mosaic"] + sys.argv[1:]))
