"""TPU microbenchmarks for the sparse-table hot ops (docs/PERF.md).

Times each candidate primitive with the lax.scan + host-read-sync
pattern (block_until_ready does not reliably sync through the axon
tunnel). Run on the real chip:  python tools/microbench_tpu.py
"""

from __future__ import annotations

import time

import numpy as np


def timeit(fn, *args, iters=8, inner=4):
    import jax

    @jax.jit
    def run(*a):
        def body(c, _):
            out = fn(*a)
            # fold into carry so the loop can't be elided
            return c + out.ravel()[0].astype(np.float32), None

        c, _ = jax.lax.scan(body, np.float32(0.0), None, length=inner)
        return c

    r = run(*args)
    _ = float(r)
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        _ = float(run(*args))
        best = min(best, (time.perf_counter() - t0) / inner)
    return best


def main():
    import jax
    import jax.numpy as jnp

    S, N, K = 1 << 22, 1 << 21, 11  # table slots, occurrences, row width
    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, S, N), jnp.int32)
    idx_sorted = jnp.sort(idx)
    tab1 = jnp.zeros((S,), jnp.float32)
    tabk = jnp.zeros((S, K), jnp.float32)
    val1 = jnp.asarray(rng.normal(size=N).astype(np.float32))
    valk = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32))

    res = {}
    res["gather_scalar_2M"] = timeit(lambda t, i: t[i], tab1, idx)
    res["gather_rows_2M_x11"] = timeit(lambda t, i: t[i], tabk, idx)
    res["scatter_add_scalar_2M"] = timeit(lambda t, i, v: t.at[i].add(v), tab1, idx, val1)
    res["scatter_add_rows_2M_x11"] = timeit(lambda t, i, v: t.at[i].add(v), tabk, idx, valk)
    res["scatter_add_rows_sorted"] = timeit(lambda t, i, v: t.at[i].add(v), tabk, idx_sorted, valk)
    res["segment_sum_rows_to_table"] = timeit(
        lambda v, i: jax.ops.segment_sum(v, i, num_segments=S), valk, idx
    )
    res["segment_sum_sorted_hint"] = timeit(
        lambda v, i: jax.ops.segment_sum(v, i, num_segments=S, indices_are_sorted=True),
        valk,
        idx_sorted,
    )
    res["ftrl_elementwise_3xSxK"] = timeit(
        lambda w, g: w + g * g, tabk, tabk
    )
    # dedup shape: U unique rows + re-gather occurrences from the small array
    for U_log in (17, 19):
        U = 1 << U_log
        uniq = jnp.asarray(rng.integers(0, S, U), jnp.int32)
        inv = jnp.asarray(rng.integers(0, U, N), jnp.int32)
        res[f"dedup_gather_U{U>>10}k"] = timeit(
            lambda t, u, i: t[u][i], tabk, uniq, inv
        )
        res[f"dedup_scatter_U{U>>10}k"] = timeit(
            lambda t, u, i, v: t.at[u].add(
                jax.ops.segment_sum(v, i, num_segments=U)
            ),
            tabk,
            uniq,
            inv,
            valk,
        )

    dev = jax.devices()[0]
    print(f"# device={dev}")
    for k, v in res.items():
        print(f"{k:32s} {v*1e3:8.2f} ms")


if __name__ == "__main__":
    main()
