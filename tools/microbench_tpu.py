"""TPU microbenchmarks for the sparse-table hot ops (docs/PERF.md).

Retired to a thin wrapper: the implementation lives in the unified
microbench lab (`xflow_tpu/tools/bench_lab.py --suite micro`, same
lax.scan + host-read-sync harness). This CLI keeps working:

    python tools/microbench_tpu.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xflow_tpu.tools.bench_lab import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--suite", "micro"] + sys.argv[1:]))
