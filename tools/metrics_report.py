#!/usr/bin/env python3
"""Summarize xflow telemetry JSONL runs (docs/OBSERVABILITY.md).

Loads one or more metrics JSONL files (or run directories — every
`*.jsonl` inside), groups records by (run_id, rank), and prints a
throughput / loss / bad-step summary table. Reading is
truncation-tolerant (xflow_tpu.jsonl.read_jsonl_counted): a crash
mid-append leaves a partial last line, which is skipped with a warning,
never an exception.

    python tools/metrics_report.py runs/exp1/               # summary table
    python tools/metrics_report.py a.jsonl b.jsonl          # multiple files
    python tools/metrics_report.py runs/exp1 --check        # schema gate (CI)
    python tools/metrics_report.py runs/exp1 --bench-json - # BENCH-style JSON

`--check` validates the telemetry schema — every record stamped with
ts/rank/run_id, step numbers monotone per stream, window records
carrying the full decomposition key set — and exits nonzero on any
violation (tools/smoke_telemetry.sh gates on it).

`--bench-json` emits a BENCH-style perf-trajectory record (the shape
bench.py prints) computed from the run's own telemetry, so a training
run doubles as a benchmark sample without a separate bench invocation.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xflow_tpu.jsonl import read_jsonl_counted  # noqa: E402

# the step-decomposition keys every window record carries (telemetry
# .StepTimer.window_record); --check enforces all-or-none
WINDOW_KEYS = (
    "steps_per_s",
    "rows_per_s",
    "step_time_p50_ms",
    "step_time_p99_ms",
    "data_wait_ms",
    "dispatch_ms",
    "device_ms",
)
STAMP_KEYS = ("ts", "rank", "run_id")


def expand_paths(paths: list[str]) -> list[str]:
    """Files stay files; directories expand to their sorted *.jsonl."""
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(glob.glob(os.path.join(p, "*.jsonl")))
            if not found:
                raise FileNotFoundError(f"{p!r}: directory holds no *.jsonl files")
            out.extend(found)
        elif not os.path.exists(p):
            # caught in main(): a clean message + exit 2, not a traceback
            raise FileNotFoundError(f"{p!r}: no such file")
        else:
            out.append(p)
    return out


def load_streams(files: list[str]) -> tuple[dict, int]:
    """{(run_id, rank): [records in file order]} across all files, plus
    the total damaged-line count."""
    streams: dict = {}
    skipped_total = 0
    for path in files:
        records, skipped = read_jsonl_counted(path)
        skipped_total += skipped
        for rec in records:
            key = (str(rec.get("run_id", "?")), rec.get("rank", "?"))
            streams.setdefault(key, []).append(rec)
    return streams, skipped_total


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


def summarize_stream(records: list[dict]) -> dict:
    """One summary row for a (run_id, rank) stream."""
    steps_recs = [r for r in records if "step" in r and "loss" in r]
    windows = [r for r in records if "rows_per_s" in r]
    counters = [r["counters"] for r in records if isinstance(r.get("counters"), dict)]
    final = next((r for r in records if r.get("final")), None)

    steps = max(
        [r["step"] for r in steps_recs if _finite(r.get("step"))]
        + ([final["steps"]] if final and _finite(final.get("steps")) else [0])
        or [0]
    )
    examples = max(
        (r["examples"] for r in records if _finite(r.get("examples"))), default=0
    )
    elapsed = max(
        (r["elapsed_s"] for r in records if _finite(r.get("elapsed_s"))), default=0.0
    )
    losses = [r["loss"] for r in steps_recs if _finite(r.get("loss"))]
    p50s = [r["step_time_p50_ms"] for r in windows if _finite(r.get("step_time_p50_ms"))]
    p99s = [r["step_time_p99_ms"] for r in windows if _finite(r.get("step_time_p99_ms"))]
    waits = [r["data_wait_ms"] for r in windows if _finite(r.get("data_wait_ms"))]
    rates = [r["rows_per_s"] for r in windows if _finite(r.get("rows_per_s"))]
    evals = [r["eval_auc"] for r in records if _finite(r.get("eval_auc"))]
    bad_steps = max(
        (r["bad_steps"] for r in records if _finite(r.get("bad_steps"))), default=0
    )
    bad_rows = max((c.get("data.bad_rows", 0) for c in counters), default=0)

    med = lambda xs: sorted(xs)[len(xs) // 2] if xs else float("nan")
    return {
        "steps": int(steps),
        "examples": int(examples),
        "elapsed_s": float(elapsed),
        "examples_per_s": examples / elapsed if elapsed > 0 else float("nan"),
        "rows_per_s": med(rates),
        "p50_ms": med(p50s),
        "p99_ms": max(p99s) if p99s else float("nan"),
        "data_wait_ms": sum(waits) / len(waits) if waits else float("nan"),
        "last_loss": losses[-1] if losses else float("nan"),
        "bad_steps": int(bad_steps),
        "bad_rows": int(bad_rows),
        "eval_auc": evals[-1] if evals else float("nan"),
        "windows": len(windows),
    }


def check_streams(streams: dict, files: list[str]) -> list[str]:
    """Schema violations ([] = clean). The contract checked here is the
    one docs/OBSERVABILITY.md documents — keep the three in sync."""
    problems: list[str] = []
    if not streams:
        problems.append(f"no records in {', '.join(files)}")
    for (run_id, rank), records in sorted(streams.items(), key=str):
        tag = f"run {run_id} rank {rank}"
        last_step = -1
        step_recs = 0
        window_recs = 0
        for i, rec in enumerate(records, 1):
            for key in STAMP_KEYS:
                if key not in rec:
                    problems.append(f"{tag}: record {i} lacks {key!r}")
            if not _finite(rec.get("ts", 0.0)):
                problems.append(f"{tag}: record {i} has non-numeric ts")
            if "step" in rec:
                step_recs += 1
                if _finite(rec["step"]):
                    if rec["step"] < last_step:
                        problems.append(
                            f"{tag}: step went backwards "
                            f"({last_step} -> {rec['step']}) at record {i}"
                        )
                    last_step = max(last_step, rec["step"])
            present = [k for k in WINDOW_KEYS if k in rec]
            if present:
                window_recs += 1
                missing = [k for k in WINDOW_KEYS if k not in rec]
                if missing:
                    problems.append(
                        f"{tag}: record {i} has window keys {present} but "
                        f"lacks {missing}"
                    )
        if step_recs >= 2 and window_recs == 0:
            problems.append(
                f"{tag}: {step_recs} step records but no window record — "
                "StepTimer stats never landed"
            )
    return problems


def render_table(rows: list[tuple]) -> str:
    header = (
        "run_id", "rank", "steps", "examples", "elapsed_s", "ex/s",
        "rows/s", "p50_ms", "p99_ms", "wait_ms", "loss", "bad_steps",
        "bad_rows", "auc",
    )

    def fmt(v) -> str:
        if isinstance(v, float):
            if not math.isfinite(v):
                return "-"
            return f"{v:.4g}" if abs(v) < 1000 else f"{v:,.0f}"
        return str(v)

    cells = [header] + [tuple(fmt(c) for c in row) for row in rows]
    widths = [max(len(r[i]) for r in cells) for i in range(len(header))]
    lines = []
    for i, row in enumerate(cells):
        lines.append("  ".join(c.rjust(w) for c, w in zip(row, widths)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def bench_record(streams: dict) -> dict:
    """BENCH-style perf record over the newest run: summed per-rank
    examples over the longest rank elapsed — the honest cross-rank
    aggregate (ranks run the same global steps; examples counters are
    per-rank local rows)."""
    if not streams:
        return {}
    # newest run = the one whose records carry the largest ts
    def run_ts(run_id: str) -> float:
        return max(
            (r.get("ts", 0.0) for (rid, _), recs in streams.items() if rid == run_id
             for r in recs if _finite(r.get("ts"))),
            default=0.0,
        )

    run_ids = {rid for rid, _ in streams}
    newest = max(run_ids, key=run_ts)
    rows = {
        rank: summarize_stream(recs)
        for (rid, rank), recs in streams.items()
        if rid == newest
    }
    examples = sum(s["examples"] for s in rows.values())
    elapsed = max((s["elapsed_s"] for s in rows.values()), default=0.0)
    steps = max((s["steps"] for s in rows.values()), default=0)
    value = examples / elapsed if elapsed > 0 else 0.0
    return {
        "metric": "telemetry_examples_per_sec",
        "value": round(value, 1),
        "unit": "examples/sec",
        "run_id": newest,
        "ranks": len(rows),
        "steps": int(steps),
        "examples": int(examples),
        "elapsed_s": round(elapsed, 3),
        "bad_steps": int(sum(s["bad_steps"] for s in rows.values())),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize / schema-check xflow telemetry JSONL runs"
    )
    ap.add_argument("paths", nargs="+", help="JSONL file(s) and/or run dir(s)")
    ap.add_argument("--check", action="store_true",
                    help="schema-validate and exit nonzero on violation")
    ap.add_argument("--bench-json", default="",
                    help="write a BENCH-style perf JSON here ('-' = stdout)")
    args = ap.parse_args(argv)

    try:
        files = expand_paths(args.paths)
    except FileNotFoundError as e:
        print(f"metrics_report: {e}", file=sys.stderr)
        return 2
    streams, skipped = load_streams(files)

    if args.check:
        problems = check_streams(streams, files)
        if problems:
            for p in problems:
                print(f"metrics_report: FAIL: {p}", file=sys.stderr)
            return 2
        total = sum(len(v) for v in streams.values())
        print(
            f"metrics_report: OK: {len(files)} file(s), {len(streams)} "
            f"stream(s), {total} record(s), {skipped} damaged line(s) skipped"
        )
        return 0

    rows = []
    for (run_id, rank), records in sorted(streams.items(), key=str):
        s = summarize_stream(records)
        rows.append((
            run_id, rank, s["steps"], s["examples"], round(s["elapsed_s"], 1),
            s["examples_per_s"], s["rows_per_s"], s["p50_ms"], s["p99_ms"],
            s["data_wait_ms"], s["last_loss"], s["bad_steps"], s["bad_rows"],
            s["eval_auc"],
        ))
    if rows:
        print(render_table(rows))
    else:
        print("metrics_report: no records found", file=sys.stderr)
        return 1
    if skipped:
        print(f"# {skipped} damaged line(s) skipped (truncated append?)")

    if args.bench_json:
        rec = bench_record(streams)
        out = json.dumps(rec)
        if args.bench_json == "-":
            print(out)
        else:
            with open(args.bench_json, "w") as f:
                f.write(out + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
