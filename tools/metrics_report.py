#!/usr/bin/env python3
"""Summarize xflow telemetry JSONL runs (docs/OBSERVABILITY.md).

Loads one or more metrics JSONL files (or run directories — every
`*.jsonl` inside), groups records by (run_id, rank, kind) — `kind`
separates the metrics, heartbeat, and watchdog streams a run dir
holds — and prints a throughput / loss / bad-step summary table.
Reading is truncation-tolerant (xflow_tpu.jsonl.read_jsonl_counted):
a crash mid-append leaves a partial last line, which is skipped with
a warning, never an exception.

    python tools/metrics_report.py runs/exp1/               # summary table
    python tools/metrics_report.py a.jsonl b.jsonl          # multiple files
    python tools/metrics_report.py runs/exp1 --check        # schema gate (CI)
    python tools/metrics_report.py runs/exp1 --health       # health summary
    python tools/metrics_report.py runs/exp1 --bench-json - # BENCH-style JSON
    python tools/metrics_report.py runs/exp1 --regress BENCH_r05.json

`--check` validates the telemetry schema — every record stamped with
ts/rank/run_id, step numbers monotone per stream, window records
carrying the full decomposition key set, health fields all-or-none,
eval and heartbeat records complete, `world` stamps agreeing within
each generation (the rank SET may change ACROSS generations: a
degraded --allow-shrink relaunch is legitimate, not corruption) — and
exits nonzero on any violation (tools/smoke_telemetry.sh gates on it).

`--health` renders the model-health view: norm trends, loss EMA, the
AUC trajectory, occupancy/collision gauges, and a per-rank heartbeat
table (straggler/dead classification via launch/watchdog.py, with
"now" = the newest heartbeat seen, so a finished run reads as
finished, not dead; a rank the supervisor shrank away reads as
`retired@genK`, not dead).

`--bench-json` emits a BENCH-style perf-trajectory record (the shape
bench.py prints) computed from the run's own telemetry, so a training
run doubles as a benchmark sample without a separate bench invocation.

`--regress BASELINE.json` compares this run's bench record (and AUC,
when both sides have one) against a previously saved baseline and
exits 3 on regression beyond `--regress-tol` / `--auc-tol` — the CI
gate that keeps the bench trajectory honest.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xflow_tpu.jsonl import read_jsonl_counted  # noqa: E402
from xflow_tpu.tracing import (  # noqa: E402
    BATCH_SPAN_NAME,
    REQUEST_SPAN_NAMES,
)

# the step-decomposition keys every window record carries (telemetry
# .StepTimer.window_record); --check enforces all-or-none
WINDOW_KEYS = (
    "steps_per_s",
    "rows_per_s",
    "step_time_p50_ms",
    "step_time_p99_ms",
    "data_wait_ms",
    "dispatch_ms",
    "device_ms",
)
# the health keys a health-enabled window record carries (telemetry
# .HealthMonitor.window_record); --check enforces all-or-none too
HEALTH_KEYS = ("grad_norm", "update_norm", "param_norm", "loss_ema")
STAMP_KEYS = ("ts", "rank", "run_id")
# the key set every kind="compile" record carries (telemetry
# .CompileRecorder.record — docs/OBSERVABILITY.md "Compile accounting");
# --check enforces presence, a positive compile time, and the
# exactly-once rule: the same (program, sig) never compiles twice in
# one stream (a recompile means a jit cache is thrashing)
COMPILE_KEYS = ("program", "sig", "compile_time_s", "flops", "bytes_accessed")
# the key set every kind="serve" window record carries (serve/metrics
# .ServeMetrics.maybe_flush — SERVE_WINDOW_KEYS there is the writer's
# copy); --check enforces all-or-none plus monotone model generation
SERVE_KEYS = (
    "requests",
    "rows",
    "qps",
    "rows_per_s",
    "batches",
    "batch_fill",
    "queue_wait_p50_ms",
    "queue_wait_p99_ms",
    "device_p50_ms",
    "device_p99_ms",
    "total_p50_ms",
    "total_p99_ms",
    "window_s",
    "bad_requests",
    "shed_requests",
    "generation",
    "step",
    "data_freshness_s",
)
# serve window keys added AFTER runs were already archived: absence
# means a pre-upgrade writer (or a mid-upgrade fleet mixing binaries),
# not a schema violation — present they ride the all-or-none gate.
# data_freshness_s is doubly optional: it only exists while the served
# generation carries a publication sidecar (train.publish_every), so a
# window without it means "not measurable", never a violation
OPTIONAL_SERVE_KEYS = ("shed_requests", "data_freshness_s")
# the key set every kind="autotune" decision record carries (serve
# /autotune.py controller applied by server.ServeApp._autotune —
# docs/OBSERVABILITY.md "SLO autotuning"); --check enforces
# all-or-none, a known knob name, and monotone ts within a stream (one
# controller = one replica = one ordered decision trail; out-of-order
# ts means two controllers wrote one file)
AUTOTUNE_KEYS = (
    "knob",
    "old",
    "new",
    "reason",
    "slo_p99_ms",
    "total_p99_ms",
    "queue_wait_p99_ms",
    "device_p99_ms",
    "batch_fill",
)
# the only knobs the controller steers (autotune.AUTOTUNE_KNOBS is the
# writer's copy) — an unknown name means a forged or drifted record
AUTOTUNE_KNOB_NAMES = ("window_ms", "rung")
# the key set every kind="pipeline" window record carries (telemetry
# .PipelineProfiler.window_record + the trainer's step stamp —
# docs/OBSERVABILITY.md "Input-pipeline attribution"); --check enforces
# all-or-none, a positive wall, and the CONCURRENCY invariant: the
# producer (prefetch thread) and consumer (fit loop) stage groups each
# sum to at most the window wall — never the two groups combined, they
# overlap by design
PIPELINE_KEYS = (
    "wall_s",
    "read_s",
    "parse_s",
    "hash_s",
    "batch_s",
    "pad_s",
    "cache_read_s",
    "plan_s",
    "producer_wait_s",
    "queue_wait_s",
    "transfer_s",
    "dispatch_s",
    "device_s",
    "batches",
    "rows",
    "queue_depth",
    "queue_cap",
)
# pipeline keys added after runs were already archived (the round-12
# packed-shard-cache stage): absence means a pre-upgrade writer, not a
# schema violation — present they join the all-or-none gate and the
# producer sum below (the OPTIONAL_SERVE_KEYS convention)
OPTIONAL_PIPELINE_KEYS = ("cache_read_s",)
PIPELINE_PRODUCER_SUM = (
    "read_s", "parse_s", "hash_s", "batch_s", "pad_s", "cache_read_s",
    "plan_s", "producer_wait_s",
)
PIPELINE_CONSUMER_SUM = ("queue_wait_s", "transfer_s", "dispatch_s", "device_s")
# slack on the per-thread sum gate: stage accumulations batch on the
# producer side (a few hundred lines per flush), so a window boundary
# can carry a sliver of the previous window's time
PIPELINE_SUM_SLACK = 1.25
# the key set every kind="span" record carries (xflow_tpu/tracing.py —
# docs/OBSERVABILITY.md "Request tracing"); `parent` is optional (the
# root has none), everything else is the assembly contract
# tools/request_trace.py depends on
SPAN_KEYS = ("trace", "span", "name", "t0", "dur_ms")
# the key set every kind="ingest" record carries (data/pipeline
# .TailFollower.segments — docs/OBSERVABILITY.md "Freshness tracing"):
# one record per sealed streaming segment, `trace` is the ingest trace
# id the publish/reload/serve_first spans later link to; --check
# enforces all-or-none, non-negative finite rows/bytes/offset, and a
# strictly increasing seq per stream (the follower numbers segments
# 0, 1, 2, ... — a repeat or regression means two followers wrote one
# stream)
INGEST_KEYS = (
    "trace",
    "seq",
    "source",
    "offset",
    "rows",
    "bytes",
    "cache",
    "ingest_ts",
)
# the key set every kind="publish" record carries (train/trainer
# ._publish_checkpoint): one per in-run committed publication
# (train.publish_every), stamped with the newest contributing ingest
# trace; --check enforces all-or-none, monotone seq, and
# published_ts >= ingest_ts (a publication cannot predate the data it
# trained on). `step` rides the generic step-monotonicity gate.
PUBLISH_KEYS = ("step", "seq", "trace", "ingest_ts", "published_ts")
# the key set every kind="sync" record carries (parallel/multislice
# .SliceSyncer.sync — docs/OBSERVABILITY.md "Multi-slice sync
# records"); --check enforces all-or-none, a strictly increasing round
# per stream (each sync bumps by one; a rejoin generation is its own
# stream), the membership ledger (this round's live set must equal the
# previous round's minus `left` plus `joined` — a silent membership
# jump means a sync record was lost or forged), and the staleness
# arithmetic (stale = live peers lagging > k; lag_max = max lag)
SYNC_KEYS = (
    "round",
    "k",
    "mode",
    "live",
    "joined",
    "left",
    "bytes_out",
    "bytes_in",
    "applied",
    "stale",
    "timeouts",
    "lag_max",
    "lags",
    "dur_ms",
)
SYNC_MODES = ("sync", "bounded", "async")
# the key set every kind="ckpt" record carries (train/checkpoint
# .AsyncCheckpointWriter._record — docs/OBSERVABILITY.md "Checkpoint
# records"): one per async save outcome per tier (train.ckpt_async).
# --check enforces all-or-none, the tier/event vocabularies, finite
# non-negative timings, committed_ts >= queued_ts, a non-decreasing
# skip counter, and the at-most-one-in-flight contract: per stream and
# tier, a committed save's queued_ts must not precede the previous
# committed save's committed_ts (overlapping queued→committed intervals
# mean two writers raced one checkpoint dir)
CKPT_KEYS = (
    "step",
    "tier",
    "event",
    "queued_ts",
    "committed_ts",
    "queue_ms",
    "write_ms",
    "bytes",
    "skips",
    "degraded",
)
CKPT_TIERS = ("primary", "replica")
CKPT_EVENTS = ("committed", "skipped", "failed")
# request-path span names come from xflow_tpu.tracing (the source of
# truth): the cross-stream parenting gates below apply to those;
# operational spans — reload/checkpoint_save/… — are one-span traces
# and exempt


def expand_paths(paths: list[str]) -> list[str]:
    """Files stay files; directories expand to their sorted *.jsonl."""
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(glob.glob(os.path.join(p, "*.jsonl")))
            if not found:
                raise FileNotFoundError(f"{p!r}: directory holds no *.jsonl files")
            out.extend(found)
        elif not os.path.exists(p):
            # caught in main(): a clean message + exit 2, not a traceback
            raise FileNotFoundError(f"{p!r}: no such file")
        else:
            out.append(p)
    return out


def _gen_of(rec: dict) -> int:
    """Restart generation of a record (0 for pre-elastic streams and
    for damaged values — a string or NaN gen must group, not raise)."""
    g = rec.get("gen", 0)
    try:
        return int(g) if isinstance(g, (int, float)) else 0
    except (ValueError, OverflowError):  # NaN/inf floats
        return 0


def load_streams(files: list[str]) -> tuple[dict, int]:
    """{(run_id, rank, kind, gen): [records in file order]} across all
    files, plus the total damaged-line count. `kind` defaults to
    "metrics" for unstamped legacy streams; heartbeat/watchdog records
    stamp theirs. `gen` is the restart generation (elastic recovery):
    a supervised auto-restart relaunches the job under the SAME run_id
    with step counters back at 0, so every per-stream gate (step
    monotonicity above all) keys on the generation — one launch's
    restarts segment instead of reading as corruption."""
    streams: dict = {}
    skipped_total = 0
    for path in files:
        records, skipped = read_jsonl_counted(path)
        skipped_total += skipped
        for rec in records:
            key = (
                str(rec.get("run_id", "?")),
                rec.get("rank", "?"),
                str(rec.get("kind", "metrics")),
                _gen_of(rec),
            )
            streams.setdefault(key, []).append(rec)
    return streams, skipped_total


def metrics_streams(streams: dict) -> dict:
    """The (run_id, rank, gen) -> records subset holding trainer metrics."""
    return {
        (rid, rank, gen): recs
        for (rid, rank, kind, gen), recs in streams.items()
        if kind == "metrics"
    }


def serve_streams(streams: dict) -> dict:
    """The (run_id, rank, gen) -> records subset holding serving
    telemetry (kind="serve": QPS/latency windows + reload events)."""
    return {
        (rid, rank, gen): recs
        for (rid, rank, kind, gen), recs in streams.items()
        if kind == "serve"
    }


def compile_records(streams: dict, run_id: str = "") -> list[dict]:
    """Every kind="compile" record (optionally one run's), in file
    order — the CompileRecorder's per-program compile accounting."""
    out = []
    for (rid, _rank, kind, _gen), recs in sorted(streams.items(), key=str):
        if kind == "compile" and (not run_id or rid == run_id):
            out.extend(recs)
    return out


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


def summarize_stream(records: list[dict]) -> dict:
    """One summary row for a (run_id, rank) metrics stream."""
    steps_recs = [r for r in records if "step" in r and "loss" in r]
    windows = [r for r in records if "rows_per_s" in r]
    counters = [r["counters"] for r in records if isinstance(r.get("counters"), dict)]
    final = next((r for r in records if r.get("final")), None)

    steps = max(
        [r["step"] for r in steps_recs if _finite(r.get("step"))]
        + ([final["steps"]] if final and _finite(final.get("steps")) else [0])
        or [0]
    )
    examples = max(
        (r["examples"] for r in records if _finite(r.get("examples"))), default=0
    )
    elapsed = max(
        (r["elapsed_s"] for r in records if _finite(r.get("elapsed_s"))), default=0.0
    )
    losses = [r["loss"] for r in steps_recs if _finite(r.get("loss"))]
    p50s = [r["step_time_p50_ms"] for r in windows if _finite(r.get("step_time_p50_ms"))]
    p99s = [r["step_time_p99_ms"] for r in windows if _finite(r.get("step_time_p99_ms"))]
    waits = [r["data_wait_ms"] for r in windows if _finite(r.get("data_wait_ms"))]
    rates = [r["rows_per_s"] for r in windows if _finite(r.get("rows_per_s"))]
    evals = [r["eval_auc"] for r in records if _finite(r.get("eval_auc"))]
    bad_steps = max(
        (r["bad_steps"] for r in records if _finite(r.get("bad_steps"))), default=0
    )
    bad_rows = max((c.get("data.bad_rows", 0) for c in counters), default=0)

    def series(key):
        return [r[key] for r in records if _finite(r.get(key))]

    grads = series("grad_norm")
    grad_maxes = series("grad_norm_max")
    emas = series("loss_ema")
    occs = series("table_occupancy")
    colls = series("est_collision_rate")

    med = lambda xs: sorted(xs)[len(xs) // 2] if xs else float("nan")
    last = lambda xs: xs[-1] if xs else float("nan")
    return {
        "steps": int(steps),
        "examples": int(examples),
        "elapsed_s": float(elapsed),
        "examples_per_s": examples / elapsed if elapsed > 0 else float("nan"),
        "rows_per_s": med(rates),
        "p50_ms": med(p50s),
        "p99_ms": max(p99s) if p99s else float("nan"),
        "data_wait_ms": sum(waits) / len(waits) if waits else float("nan"),
        "last_loss": losses[-1] if losses else float("nan"),
        "bad_steps": int(bad_steps),
        "bad_rows": int(bad_rows),
        "eval_auc": last(evals),
        "windows": len(windows),
        # health trajectory (docs/OBSERVABILITY.md "Health metrics")
        "grad_norm_first": grads[0] if grads else float("nan"),
        "grad_norm_last": last(grads),
        "grad_norm_max": max(grad_maxes) if grad_maxes else float("nan"),
        "update_norm_last": last(series("update_norm")),
        "param_norm_last": last(series("param_norm")),
        "loss_ema_last": last(emas),
        "occupancy_last": last(occs),
        "est_collision_rate_last": last(colls),
        "auc_trajectory": evals,
    }


def summarize_serve_stream(records: list[dict]) -> dict:
    """One summary row for a (run_id, rank) kind="serve" stream:
    traffic totals over the window records, latency aggregated across
    windows (p50 = median of window p50s, p99 = max of window p99s —
    conservative for a tail), the reload-event count, and the
    generation trail."""
    windows = [r for r in records if "qps" in r]
    total_rows = sum(r.get("rows", 0) for r in windows if _finite(r.get("rows")))
    total_reqs = sum(
        r.get("requests", 0) for r in windows if _finite(r.get("requests"))
    )
    total_s = sum(
        r.get("window_s", 0.0) for r in windows if _finite(r.get("window_s"))
    )
    p50s = [r["total_p50_ms"] for r in windows if _finite(r.get("total_p50_ms"))]
    p99s = [r["total_p99_ms"] for r in windows if _finite(r.get("total_p99_ms"))]
    fills = [
        (r["batch_fill"], r["batches"])
        for r in windows
        if _finite(r.get("batch_fill")) and _finite(r.get("batches"))
    ]
    fill_w = sum(n for _, n in fills)
    gens = []
    for r in records:
        g = r.get("generation")
        if _finite(g) and (not gens or gens[-1] != g):
            gens.append(g)
    med = lambda xs: sorted(xs)[len(xs) // 2] if xs else float("nan")
    return {
        "windows": len(windows),
        "requests": int(total_reqs),
        "rows": int(total_rows),
        "window_seconds": float(total_s),
        "qps": total_reqs / total_s if total_s > 0 else float("nan"),
        "rows_per_s": total_rows / total_s if total_s > 0 else float("nan"),
        "p50_ms": med(p50s),
        "p99_ms": max(p99s) if p99s else float("nan"),
        "batch_fill": (
            sum(f * n for f, n in fills) / fill_w if fill_w else float("nan")
        ),
        "bad_requests": int(
            sum(r.get("bad_requests", 0) for r in windows
                if _finite(r.get("bad_requests")))
        ),
        "shed_requests": int(
            sum(r.get("shed_requests", 0) for r in windows
                if _finite(r.get("shed_requests")))
        ),
        "replica": next(
            (r["replica"] for r in records if _finite(r.get("replica"))),
            None,
        ),
        "reloads": sum(1 for r in records if r.get("event") == "reload"),
        "reload_failures": sum(
            1 for r in records if r.get("event") == "reload_failed"
        ),
        "generations": gens,
        "last_step": next(
            (r["step"] for r in reversed(records) if _finite(r.get("step"))),
            -1,
        ),
    }


def check_fleet_identity(streams: dict) -> list[str]:
    """Serving-fleet identity gates (docs/SERVING.md "Fleet"), active
    only where records carry a `replica` stamp (solo serving is
    untouched):

    - one stream = one replica: a (run_id, rank, gen) serve stream
      mixing two replica stamps means two processes appended to one
      file — exactly the interleaving the per-replica layout exists to
      prevent;
    - distinct replicas stay distinct: two streams sharing (run_id,
      rank) but stamping different replicas collide — the fleet failed
      to give them distinct rank identities and their metrics would
      merge in every per-rank view;
    - per-replica restart generations are monotone in time: replica
      k's `gen` stamps, ordered by ts, never go backwards (a
      regression means a stale pre-restart process kept writing after
      its supersessor came up — two live processes on one identity).
    """
    problems: list[str] = []
    # (run_id, rank) -> {replica stamps seen}, and per-(run_id, replica)
    # the (ts, gen) trail. Span and autotune streams ride the same
    # identity gates: "no span crosses replica stamps" is this
    # one-stream-one-replica rule applied to kind="span", and an
    # autotune decision trail mixing replicas means two controllers
    # steered one coalescer's record file.
    rank_replicas: dict = {}
    gen_trail: dict = {}
    for (run_id, rank, kind, gen), records in sorted(streams.items(), key=str):
        if kind not in ("serve", "span", "autotune"):
            continue
        reps = {
            r["replica"] for r in records
            if isinstance(r.get("replica"), int)
        }
        if not reps:
            continue
        if len(reps) > 1:
            problems.append(
                f"run {run_id} rank {rank} [{kind}] gen {gen}: one stream "
                f"mixes replica stamps {sorted(reps)}"
            )
        rank_replicas.setdefault((run_id, rank), set()).update(reps)
        for r in records:
            rep = r.get("replica")
            if isinstance(rep, int) and _finite(r.get("ts")):
                gen_trail.setdefault((run_id, rep), []).append(
                    (r["ts"], gen)
                )
    for (run_id, rank), reps in sorted(rank_replicas.items(), key=str):
        if len(reps) > 1:
            problems.append(
                f"run {run_id} rank {rank}: distinct replicas "
                f"{sorted(reps)} collide on one rank stamp — their serve "
                "streams would merge in every per-rank view"
            )
    for (run_id, rep), trail in sorted(gen_trail.items(), key=str):
        trail.sort(key=lambda tg: tg[0])
        last = -1
        for ts, g in trail:
            if g < last:
                problems.append(
                    f"run {run_id} replica {rep}: restart generation went "
                    f"backwards ({last} -> {g}) — a stale pre-restart "
                    "process is still writing"
                )
                break
            last = g
    return problems


def check_spans(streams: dict) -> list[str]:
    """Request-tracing gates (docs/OBSERVABILITY.md "Request tracing"),
    active only where kind="span" records exist (untraced runs are
    untouched). Cross-STREAM by design: one request's spans live in the
    router's file and 1-2 replicas' files, and the whole point of the
    trace id is that they join back up.

    - every sampled request parents to ONE root: a trace holding two
      parentless request-path spans is a split tree (two processes both
      thought they were the request's origin — id reuse or a broken
      parent header). A trace with NO parentless span is a partial
      capture (one hop force-emitted while the origin's verdict said
      drop) — tolerated, request_trace.py reports it as incomplete;
    - device-batch spans are referenced by >= 1 request span: an
      unreferenced batch span can never be reached from any request
      tree — the batch-membership link broke (the dedup emitted the
      batch but dropped every member's device span);
    - "no span crosses replica stamps" rides check_fleet_identity
      (span streams obey the same one-stream-one-replica rule).
    """
    problems: list[str] = []
    # run_id -> {trace: [parentless request spans]}, and the batch-link
    # reference sets
    roots: dict = {}
    batch_ids: dict = {}
    batch_refs: dict = {}
    for (run_id, _rank, kind, _gen), records in sorted(streams.items(), key=str):
        if kind != "span":
            continue
        for rec in records:
            name = rec.get("name")
            trace = rec.get("trace")
            if name == BATCH_SPAN_NAME and "span" in rec:
                batch_ids.setdefault(run_id, {})[rec["span"]] = trace
                continue
            if name not in REQUEST_SPAN_NAMES:
                continue  # operational spans: one-span traces, exempt
            if "batch" in rec:
                batch_refs.setdefault(run_id, set()).add(rec["batch"])
            if not rec.get("parent"):
                roots.setdefault(run_id, {}).setdefault(trace, []).append(rec)
    for run_id, traces in sorted(roots.items(), key=str):
        for trace, rs in sorted(traces.items(), key=str):
            if len(rs) > 1:
                problems.append(
                    f"run {run_id} trace {trace}: {len(rs)} parentless "
                    f"request spans ({[r.get('name') for r in rs]}) — a "
                    "sampled request's spans must parent to one root"
                )
    for run_id, ids in sorted(batch_ids.items(), key=str):
        refs = batch_refs.get(run_id, set())
        for bid, trace in sorted(ids.items(), key=str):
            if bid not in refs:
                problems.append(
                    f"run {run_id} trace {trace}: device_batch span {bid} "
                    "is referenced by no request span — the "
                    "batch-membership link broke"
                )
    return problems


def check_streams(streams: dict, files: list[str]) -> list[str]:
    """Schema violations ([] = clean). The contract checked here is the
    one docs/OBSERVABILITY.md documents — keep the three in sync.

    Topology elasticity: the rank SET may legitimately change across
    restart generations (--allow-shrink relaunches a degraded world
    under the same run_id), so nothing here requires generation k+1 to
    carry generation k's ranks. What IS enforced: within one (run_id,
    generation), every `world` stamp agrees, and no training rank's id
    is >= its generation's world size (the launcher's watchdog stream
    stamps rank -1 and is exempt)."""
    problems: list[str] = []
    if not streams:
        problems.append(f"no records in {', '.join(files)}")
    # (run_id, gen) -> set of world stamps seen (rank-set/world gate)
    worlds: dict = {}
    for (run_id, rank, kind, gen), records in sorted(streams.items(), key=str):
        rank_flagged = False  # one problem per stream, but keep
        # collecting its world stamps — the intra-generation
        # disagreement below is the more diagnostic signal
        for rec in records:
            w = rec.get("world")
            if isinstance(w, int) and w > 0:
                # multi-slice runs stamp `slice`: the rank is the
                # slice's id in the SYNC GROUP while `world` is the
                # slice's own (ICI) world size — two different
                # topologies, so the rank<world gate keys per slice
                sl = rec.get("slice")
                worlds.setdefault((run_id, gen, sl), set()).add(w)
                if (
                    not rank_flagged
                    and sl is None
                    and isinstance(rank, int)
                    and rank >= w
                ):
                    rank_flagged = True
                    problems.append(
                        f"run {run_id} rank {rank} [{kind}] gen {gen}: "
                        f"rank id >= its generation's world size {w}"
                    )
    for (run_id, gen, sl), seen in sorted(worlds.items(), key=str):
        if len(seen) > 1:
            where = f"gen {gen}" + (f" slice {sl}" if sl is not None else "")
            problems.append(
                f"run {run_id} {where}: world stamp disagrees across "
                f"streams ({sorted(seen)}) — ranks of one generation "
                "launched with different world sizes"
            )
    problems.extend(check_fleet_identity(streams))
    problems.extend(check_spans(streams))
    for (run_id, rank, kind, gen), records in sorted(streams.items(), key=str):
        tag = f"run {run_id} rank {rank} [{kind}]" + (
            f" gen {gen}" if gen else ""
        )
        last_step = -1
        step_recs = 0
        window_recs = 0
        last_model_gen = -1  # serve streams: the model generation a
        # record answered with must never regress (hot reload only
        # moves forward; a regression means a swap raced or went back)
        seen_programs: dict = {}  # compile streams: (program, sig) ->
        # record index — the exactly-once recompile gate
        last_round = 0  # sync streams: rounds count 1, 2, 3, ... within
        # a generation — a repeat or skip means a lost or forged record
        prev_live = None  # sync streams: membership ledger
        last_at_ts = float("-inf")  # autotune streams: decision trail
        # stays time-ordered (one controller per stream)
        last_ingest_seq = -1  # ingest streams: the follower's segment
        # counter only moves forward within a stream
        last_pub_seq = -1  # publish streams: publication counter ditto
        last_ckpt_end: dict = {}  # ckpt streams: tier -> committed_ts of
        # the last COMMITTED save — the at-most-one-in-flight gate
        last_ckpt_skips = -1  # ckpt streams: skip counter only grows
        for i, rec in enumerate(records, 1):
            for key in STAMP_KEYS:
                if key not in rec:
                    problems.append(f"{tag}: record {i} lacks {key!r}")
            if not _finite(rec.get("ts", 0.0)):
                problems.append(f"{tag}: record {i} has non-numeric ts")
            if "step" in rec and kind != "ckpt":
                # ckpt streams are exempt: the fit thread's skip
                # records interleave with the writer thread's commit
                # records (a step-10 skip can land before step 5's
                # replica commit), so their ordering contract is the
                # per-tier queued→committed interval gate below instead
                step_recs += 1
                if _finite(rec["step"]):
                    if rec["step"] < last_step:
                        problems.append(
                            f"{tag}: step went backwards "
                            f"({last_step} -> {rec['step']}) at record {i}"
                        )
                    last_step = max(last_step, rec["step"])
            # the StepTimer window contract is the TRAINER stream's
            # ("rows_per_s" also lives in serve windows, which have
            # their own key set below)
            present = [k for k in WINDOW_KEYS if k in rec] if kind == "metrics" else []
            if present:
                window_recs += 1
                missing = [k for k in WINDOW_KEYS if k not in rec]
                if missing:
                    problems.append(
                        f"{tag}: record {i} has window keys {present} but "
                        f"lacks {missing}"
                    )
            # health fields are all-or-none per record (null allowed for
            # a not-yet-available value, absence is the violation)
            h_present = [k for k in HEALTH_KEYS if k in rec]
            if h_present:
                h_missing = [k for k in HEALTH_KEYS if k not in rec]
                if h_missing:
                    problems.append(
                        f"{tag}: record {i} has health keys {h_present} "
                        f"but lacks {h_missing}"
                    )
            # an eval record carries BOTH quality numbers
            if ("eval_auc" in rec) != ("eval_logloss" in rec):
                problems.append(
                    f"{tag}: record {i} has one of eval_auc/eval_logloss "
                    "without the other"
                )
            if kind == "heartbeat" and "step" not in rec and "event" not in rec:
                problems.append(
                    f"{tag}: record {i} is neither a step heartbeat nor "
                    "an event"
                )
            if kind == "compile":
                c_missing = [k for k in COMPILE_KEYS if k not in rec]
                if c_missing:
                    problems.append(
                        f"{tag}: record {i} lacks compile keys {c_missing}"
                    )
                    continue
                if not _finite(rec["compile_time_s"]) or rec["compile_time_s"] <= 0:
                    problems.append(
                        f"{tag}: record {i} ({rec['program']!r}) has "
                        "non-positive compile_time_s"
                    )
                prog_key = (rec["program"], rec["sig"])
                if prog_key in seen_programs:
                    problems.append(
                        f"{tag}: program {rec['program']!r} sig "
                        f"{rec['sig']} compiled twice (records "
                        f"{seen_programs[prog_key]} and {i}) — each "
                        "program compiles exactly once per run"
                    )
                else:
                    seen_programs[prog_key] = i
            if kind == "pipeline":
                pl_missing = [
                    k for k in PIPELINE_KEYS
                    if k not in rec and k not in OPTIONAL_PIPELINE_KEYS
                ]
                if pl_missing:
                    problems.append(
                        f"{tag}: record {i} lacks pipeline keys {pl_missing}"
                    )
                elif not _finite(rec["wall_s"]) or rec["wall_s"] <= 0:
                    problems.append(
                        f"{tag}: record {i} has non-positive wall_s"
                    )
                else:
                    wall = rec["wall_s"]
                    for side, keys in (
                        ("producer", PIPELINE_PRODUCER_SUM),
                        ("consumer", PIPELINE_CONSUMER_SUM),
                    ):
                        vals = [rec[k] for k in keys if k in rec]
                        if not all(_finite(v) and v >= 0 for v in vals):
                            problems.append(
                                f"{tag}: record {i} has a non-numeric or "
                                f"negative {side} stage time"
                            )
                            continue
                        ssum = sum(vals)
                        if ssum > wall * PIPELINE_SUM_SLACK + 0.05:
                            problems.append(
                                f"{tag}: record {i} {side}-side stage times "
                                f"sum {ssum:.3f}s > window wall "
                                f"{wall:.3f}s — one thread cannot spend "
                                "more than the wall"
                            )
            if kind == "span":
                sp_missing = [k for k in SPAN_KEYS if k not in rec]
                if sp_missing:
                    problems.append(
                        f"{tag}: record {i} lacks span keys {sp_missing}"
                    )
                elif not (_finite(rec["t0"]) and _finite(rec["dur_ms"])
                          and rec["dur_ms"] >= 0):
                    problems.append(
                        f"{tag}: record {i} ({rec.get('name')!r}) has "
                        "non-numeric t0 or negative dur_ms"
                    )
            if kind == "serve":
                s_present = [k for k in SERVE_KEYS if k in rec]
                if "event" in rec:
                    if not isinstance(rec["event"], str):
                        problems.append(
                            f"{tag}: record {i} has a non-string event"
                        )
                elif s_present:
                    s_missing = [
                        k for k in SERVE_KEYS
                        if k not in rec and k not in OPTIONAL_SERVE_KEYS
                    ]
                    if s_missing:
                        problems.append(
                            f"{tag}: record {i} has serve keys "
                            f"{s_present[:3]}... but lacks {s_missing}"
                        )
                else:
                    problems.append(
                        f"{tag}: record {i} is neither a serve window "
                        "nor an event"
                    )
                mg = rec.get("generation")
                if _finite(mg):
                    if mg < last_model_gen:
                        problems.append(
                            f"{tag}: model generation went backwards "
                            f"({last_model_gen} -> {mg}) at record {i}"
                        )
                    last_model_gen = max(last_model_gen, mg)
                fresh = rec.get("data_freshness_s")
                if fresh is not None and (not _finite(fresh) or fresh < 0):
                    problems.append(
                        f"{tag}: record {i} has non-numeric or negative "
                        "data_freshness_s"
                    )
            if kind == "ingest":
                in_missing = [k for k in INGEST_KEYS if k not in rec]
                if in_missing:
                    problems.append(
                        f"{tag}: record {i} lacks ingest keys {in_missing}"
                    )
                    continue
                for key in ("offset", "rows", "bytes"):
                    if not _finite(rec[key]) or rec[key] < 0:
                        problems.append(
                            f"{tag}: record {i} has non-numeric or "
                            f"negative {key}"
                        )
                if not isinstance(rec["trace"], str) or not rec["trace"]:
                    problems.append(
                        f"{tag}: record {i} has an empty ingest trace id"
                    )
                if not _finite(rec["ingest_ts"]):
                    problems.append(
                        f"{tag}: record {i} has non-numeric ingest_ts"
                    )
                sq = rec["seq"]
                if not _finite(sq) or sq <= last_ingest_seq:
                    problems.append(
                        f"{tag}: ingest seq {last_ingest_seq} -> {sq} at "
                        f"record {i} — segment numbering must strictly "
                        "increase (two followers wrote one stream?)"
                    )
                if _finite(sq):
                    last_ingest_seq = max(last_ingest_seq, int(sq))
            if kind == "publish":
                pb_missing = [k for k in PUBLISH_KEYS if k not in rec]
                if pb_missing:
                    problems.append(
                        f"{tag}: record {i} lacks publish keys {pb_missing}"
                    )
                    continue
                if not isinstance(rec["trace"], str) or not rec["trace"]:
                    problems.append(
                        f"{tag}: record {i} has an empty publication "
                        "trace id"
                    )
                if not (_finite(rec["ingest_ts"]) and _finite(rec["published_ts"])):
                    problems.append(
                        f"{tag}: record {i} has non-numeric "
                        "ingest_ts/published_ts"
                    )
                elif rec["published_ts"] < rec["ingest_ts"]:
                    problems.append(
                        f"{tag}: record {i} published_ts "
                        f"{rec['published_ts']} < ingest_ts "
                        f"{rec['ingest_ts']} — a publication cannot "
                        "predate the data it trained on"
                    )
                sq = rec["seq"]
                if not _finite(sq) or sq <= last_pub_seq:
                    problems.append(
                        f"{tag}: publish seq {last_pub_seq} -> {sq} at "
                        f"record {i} — publication numbering must "
                        "strictly increase"
                    )
                if _finite(sq):
                    last_pub_seq = max(last_pub_seq, int(sq))
            if kind == "ckpt":
                ck_missing = [k for k in CKPT_KEYS if k not in rec]
                if ck_missing:
                    problems.append(
                        f"{tag}: record {i} lacks ckpt keys {ck_missing}"
                    )
                    continue
                if rec["tier"] not in CKPT_TIERS:
                    problems.append(
                        f"{tag}: record {i} has unknown ckpt tier "
                        f"{rec['tier']!r} (known: {', '.join(CKPT_TIERS)})"
                    )
                    continue
                if rec["event"] not in CKPT_EVENTS:
                    problems.append(
                        f"{tag}: record {i} has unknown ckpt event "
                        f"{rec['event']!r} (known: {', '.join(CKPT_EVENTS)})"
                    )
                    continue
                bad_num = [
                    k for k in ("queued_ts", "committed_ts", "queue_ms",
                                "write_ms", "bytes", "skips")
                    if not _finite(rec[k]) or rec[k] < 0
                ]
                if bad_num:
                    problems.append(
                        f"{tag}: record {i} has non-numeric or negative "
                        f"{bad_num}"
                    )
                    continue
                if not isinstance(rec["degraded"], bool):
                    problems.append(
                        f"{tag}: record {i} has a non-boolean degraded flag"
                    )
                if rec["committed_ts"] < rec["queued_ts"]:
                    problems.append(
                        f"{tag}: record {i} committed_ts "
                        f"{rec['committed_ts']} < queued_ts "
                        f"{rec['queued_ts']} — a save cannot commit "
                        "before it was queued"
                    )
                if rec["skips"] < last_ckpt_skips:
                    problems.append(
                        f"{tag}: skip counter went backwards "
                        f"({last_ckpt_skips} -> {rec['skips']}) at "
                        f"record {i}"
                    )
                last_ckpt_skips = max(last_ckpt_skips, int(rec["skips"]))
                if rec["event"] == "committed":
                    # at most one save in flight: this save's queued
                    # instant must not precede the previous committed
                    # save's commit instant on the same tier (the
                    # replica interval shares the job's queued_ts with
                    # its primary, so the gate keys per tier)
                    prev_end = last_ckpt_end.get(rec["tier"])
                    if prev_end is not None and rec["queued_ts"] < prev_end:
                        problems.append(
                            f"{tag}: record {i} ({rec['tier']} step "
                            f"{rec['step']}) queued at {rec['queued_ts']} "
                            f"before the previous save committed at "
                            f"{prev_end} — two saves in flight"
                        )
                    last_ckpt_end[rec["tier"]] = rec["committed_ts"]
            if kind == "autotune":
                a_present = [k for k in AUTOTUNE_KEYS if k in rec]
                a_missing = [k for k in AUTOTUNE_KEYS if k not in rec]
                if a_missing:
                    problems.append(
                        f"{tag}: record {i} has autotune keys "
                        f"{a_present[:3]}... but lacks {a_missing}"
                    )
                    continue
                if rec["knob"] not in AUTOTUNE_KNOB_NAMES:
                    problems.append(
                        f"{tag}: record {i} steers unknown knob "
                        f"{rec['knob']!r} (known: "
                        f"{', '.join(AUTOTUNE_KNOB_NAMES)})"
                    )
                if not (_finite(rec["old"]) and _finite(rec["new"])):
                    problems.append(
                        f"{tag}: record {i} has non-numeric old/new "
                        "knob values"
                    )
                ts = rec.get("ts")
                if _finite(ts):
                    if ts < last_at_ts:
                        problems.append(
                            f"{tag}: decision ts went backwards "
                            f"({last_at_ts} -> {ts}) at record {i} — "
                            "two controllers wrote one stream?"
                        )
                    last_at_ts = max(last_at_ts, ts)
            if kind == "sync":
                sy_missing = [k for k in SYNC_KEYS if k not in rec]
                if sy_missing:
                    problems.append(
                        f"{tag}: record {i} lacks sync keys {sy_missing}"
                    )
                    continue
                if rec["mode"] not in SYNC_MODES:
                    problems.append(
                        f"{tag}: record {i} has unknown sync mode "
                        f"{rec['mode']!r}"
                    )
                if rec["mode"] == "sync" and rec["k"] != 0:
                    problems.append(
                        f"{tag}: record {i} stamps mode=sync with "
                        f"k={rec['k']} — lockstep mode is k=0 by definition"
                    )
                rnd = rec["round"]
                # a stream's FIRST round may start anywhere >= 1: a
                # rejoined generation continues the slice's numbering
                # past its snapshot catch-up point. After that, +1 each
                # record — a repeat or skip means a lost or forged one.
                bad_first = last_round == 0 and (not _finite(rnd) or rnd < 1)
                bad_next = last_round > 0 and (
                    not _finite(rnd) or rnd != last_round + 1
                )
                if bad_first or bad_next:
                    problems.append(
                        f"{tag}: round {last_round} -> {rnd} at record "
                        f"{i} — rounds increment by one within a "
                        "generation (a repeat or skip means a lost or "
                        "forged sync record)"
                    )
                if _finite(rnd):
                    last_round = max(last_round, int(rnd))
                live, joined, left = rec["live"], rec["joined"], rec["left"]
                if not all(isinstance(v, list) for v in (live, joined, left)):
                    problems.append(
                        f"{tag}: record {i} live/joined/left are not lists"
                    )
                else:
                    if prev_live is not None and set(live) != (
                        (prev_live - set(left)) | set(joined)
                    ):
                        problems.append(
                            f"{tag}: record {i} membership ledger broken: "
                            f"live {sorted(prev_live)} - left {left} + "
                            f"joined {joined} != live {live}"
                        )
                    prev_live = set(live)
                lags = rec["lags"]
                if not isinstance(lags, dict) or not all(
                    _finite(v) and v >= 0 for v in lags.values()
                ):
                    problems.append(
                        f"{tag}: record {i} lags is not a dict of "
                        "non-negative rounds-behind counts"
                    )
                else:
                    want_max = max(lags.values(), default=0)
                    want_stale = sum(
                        1 for v in lags.values() if _finite(rec["k"]) and v > rec["k"]
                    )
                    if rec["lag_max"] != want_max:
                        problems.append(
                            f"{tag}: record {i} lag_max {rec['lag_max']} != "
                            f"max(lags) {want_max}"
                        )
                    if rec["stale"] != want_stale:
                        problems.append(
                            f"{tag}: record {i} stale {rec['stale']} != "
                            f"count of lags > k ({want_stale})"
                        )
                for key in ("bytes_out", "bytes_in", "applied", "timeouts",
                            "dur_ms"):
                    if not _finite(rec[key]) or rec[key] < 0:
                        problems.append(
                            f"{tag}: record {i} has non-numeric or "
                            f"negative {key}"
                        )
        if kind == "metrics" and step_recs >= 2 and window_recs == 0:
            problems.append(
                f"{tag}: {step_recs} step records but no window record — "
                "StepTimer stats never landed"
            )
    return problems


def render_table(rows: list[tuple]) -> str:
    header = (
        "run_id", "rank", "gen", "steps", "examples", "elapsed_s", "ex/s",
        "rows/s", "p50_ms", "p99_ms", "wait_ms", "loss", "bad_steps",
        "bad_rows", "auc",
    )

    def fmt(v) -> str:
        if isinstance(v, float):
            if not math.isfinite(v):
                return "-"
            return f"{v:.4g}" if abs(v) < 1000 else f"{v:,.0f}"
        return str(v)

    cells = [header] + [tuple(fmt(c) for c in row) for row in rows]
    widths = [max(len(r[i]) for r in cells) for i in range(len(header))]
    lines = []
    for i, row in enumerate(cells):
        lines.append("  ".join(c.rjust(w) for c, w in zip(row, widths)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def _newest_run(streams: dict) -> str:
    """run_id whose records carry the largest ts."""
    def run_ts(run_id: str) -> float:
        return max(
            (r.get("ts", 0.0) for (rid, _, _, _), recs in streams.items()
             if rid == run_id for r in recs if _finite(r.get("ts"))),
            default=0.0,
        )

    run_ids = {rid for rid, _, _, _ in streams}
    return max(run_ids, key=run_ts) if run_ids else "?"


def bench_record(streams: dict) -> dict:
    """BENCH-style perf record over the newest run: per GENERATION, the
    summed per-rank examples over the longest rank elapsed (the honest
    cross-rank aggregate — ranks run the same global steps; examples
    counters are per-rank local rows); across generations of one
    supervised run, examples/steps/elapsed SUM (each restart's fit
    restarts its clock and counters at the resumed stream position).
    Carries the last streaming-eval AUC when the run logged one, so
    --regress can gate quality too."""
    if not streams:
        return {}
    newest = _newest_run(streams)
    by_gen: dict = {}
    for (rid, rank, gen), recs in metrics_streams(streams).items():
        if rid == newest:
            by_gen.setdefault(gen, {})[rank] = summarize_stream(recs)
    if not by_gen:
        return {}
    examples = sum(s["examples"] for rows in by_gen.values() for s in rows.values())
    elapsed = sum(
        max((s["elapsed_s"] for s in rows.values()), default=0.0)
        for rows in by_gen.values()
    )
    steps = sum(
        max((s["steps"] for s in rows.values()), default=0)
        for rows in by_gen.values()
    )
    value = examples / elapsed if elapsed > 0 else 0.0
    rec = {
        "metric": "telemetry_examples_per_sec",
        "value": round(value, 1),
        "unit": "examples/sec",
        "run_id": newest,
        "ranks": len({rank for rows in by_gen.values() for rank in rows}),
        "steps": int(steps),
        "examples": int(examples),
        "elapsed_s": round(elapsed, 3),
        "bad_steps": int(
            sum(s["bad_steps"] for rows in by_gen.values() for s in rows.values())
        ),
    }
    if len(by_gen) > 1:
        rec["generations"] = len(by_gen)
    # quality comes from the NEWEST generation that logged an eval: the
    # final restart's model is what ships, and a superseded earlier
    # generation's (possibly better) AUC must not satisfy --regress.
    # Within one generation max-across-ranks is dedup, not choice — the
    # eval is collective, every rank logs the same value.
    for gen in sorted(by_gen, reverse=True):
        aucs = [
            s["eval_auc"] for s in by_gen[gen].values() if _finite(s["eval_auc"])
        ]
        if aucs:
            rec["auc"] = round(max(aucs), 6)
            break
    # compile context (telemetry.CompileRecorder): total compile
    # seconds and program count, so a BENCH datapoint carries the
    # cost-accounting trail alongside its throughput
    comps = compile_records(streams, run_id=newest)
    if comps:
        rec["compiled_programs"] = len(comps)
        rec["compile_time_s"] = round(
            sum(c["compile_time_s"] for c in comps
                if _finite(c.get("compile_time_s"))), 3
        )
    return rec


def serve_bench_record(streams: dict) -> dict:
    """BENCH-style SERVE perf record over the newest run (the shape
    tools/serve_bench.py emits, computed from the server's own
    telemetry instead of the client's) — the --bench-json fallback
    when a run dir holds serving streams but no trainer metrics, so a
    serving run feeds the BENCH_SERVE.json trajectory without a
    separate loadgen pass."""
    if not streams:
        return {}
    newest = _newest_run(streams)
    rows = {
        key: summarize_serve_stream(recs)
        for key, recs in serve_streams(streams).items()
        if key[0] == newest
    }
    rows = {k: s for k, s in rows.items() if s["windows"]}
    if not rows:
        return {}
    reqs = sum(s["requests"] for s in rows.values())
    total_rows = sum(s["rows"] for s in rows.values())
    # QPS: ranks serve CONCURRENTLY (their rates add); one rank's
    # restart generations run SEQUENTIALLY (they time-weight, never
    # add — summing would double a restarted server's trajectory)
    per_rank: dict = {}
    for (rid, rank, gen), s in rows.items():
        agg = per_rank.setdefault(rank, [0, 0.0])
        agg[0] += s["requests"]
        agg[1] += s["window_seconds"]
    qps = sum(r / t for r, t in per_rank.values() if t > 0)
    p50s = [s["p50_ms"] for s in rows.values() if _finite(s["p50_ms"])]
    p99s = [s["p99_ms"] for s in rows.values() if _finite(s["p99_ms"])]
    fills = [s["batch_fill"] for s in rows.values() if _finite(s["batch_fill"])]
    gens = sorted({g for s in rows.values() for g in s["generations"]})
    return {
        "metric": "serve_qps",
        "value": round(qps, 2),
        "unit": "requests/sec",
        "source": "serve_telemetry",
        "run_id": newest,
        "requests": int(reqs),
        "rows": int(total_rows),
        "p50_ms": round(sorted(p50s)[len(p50s) // 2], 3) if p50s else None,
        "p99_ms": round(max(p99s), 3) if p99s else None,
        "batch_fill": round(sum(fills) / len(fills), 4) if fills else None,
        "bad_requests": int(sum(s["bad_requests"] for s in rows.values())),
        "reloads": int(sum(s["reloads"] for s in rows.values())),
        "generations": gens,
    }


def render_compile_table(streams: dict) -> str:
    """The compile-accounting block: one row per kind="compile" record
    (program, compile seconds, model GFLOP and MB accessed per
    execution, temp bytes — docs/OBSERVABILITY.md "Compile
    accounting")."""
    recs = compile_records(streams)
    if not recs:
        return ""
    header = ("run_id", "rank", "program", "compile_s", "GFLOP", "MB_acc",
              "MB_temp", "n")

    def fmt(v):
        if v is None:
            return "-"
        if isinstance(v, float):
            return "-" if not math.isfinite(v) else f"{v:.4g}"
        return str(v)

    rows = []
    for r in recs:
        rows.append((
            r.get("run_id", "?"), r.get("rank", "?"),
            r.get("program", "?"), r.get("compile_time_s"),
            r["flops"] / 1e9 if _finite(r.get("flops")) else None,
            r["bytes_accessed"] / 1e6 if _finite(r.get("bytes_accessed")) else None,
            r["temp_bytes"] / 1e6 if _finite(r.get("temp_bytes")) else None,
            r.get("compiles", 1),
        ))
    cells = [header] + [tuple(fmt(c) for c in row) for row in rows]
    widths = [max(len(r[i]) for r in cells) for i in range(len(header))]
    lines = ["compiles (kind=compile):"]
    for i, row in enumerate(cells):
        lines.append("  ".join(c.rjust(w) for c, w in zip(row, widths)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def render_serve_table(streams: dict) -> str:
    """The serving summary block: one row per (run_id, rank, gen)
    serve stream."""
    header = (
        "run_id", "rank", "gen", "windows", "requests", "rows", "qps",
        "p50_ms", "p99_ms", "fill", "bad", "shed", "reloads", "step",
    )

    def fmt(v):
        if isinstance(v, float):
            return "-" if not math.isfinite(v) else f"{v:.4g}"
        return str(v)

    rows = []
    for (run_id, rank, gen), recs in sorted(serve_streams(streams).items(), key=str):
        s = summarize_serve_stream(recs)
        rows.append((
            run_id, rank, gen, s["windows"], s["requests"], s["rows"],
            s["qps"], s["p50_ms"], s["p99_ms"], s["batch_fill"],
            s["bad_requests"], s["shed_requests"], s["reloads"],
            s["last_step"],
        ))
    if not rows:
        return ""
    cells = [header] + [tuple(fmt(c) for c in row) for row in rows]
    widths = [max(len(r[i]) for r in cells) for i in range(len(header))]
    lines = ["serving (kind=serve):"]
    for i, row in enumerate(cells):
        lines.append("  ".join(c.rjust(w) for c, w in zip(row, widths)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


# ----------------------------------------------------------------- --health


def heartbeat_rows(streams: dict, run_id: str) -> list[dict]:
    """Straggler/dead classification over the run's heartbeat streams,
    via the same fold + classifier the live launcher watchdog uses —
    with "now" anchored to the newest heartbeat anywhere in the run
    (offline post-mortem: wall-clock now would read every finished run
    as dead).

    Topology elasticity: a rank the --allow-shrink supervisor dropped
    stops beating at its last generation and never writes a final
    event — wall-clock classification would call it dead forever. When
    the run's NEWEST generation stamps a smaller world, ranks outside
    that world whose beats stop at an older generation are relabeled
    ``retired@genK`` (K = the last generation they served in)."""
    from xflow_tpu.launch.watchdog import classify, fold_heartbeats

    beats: dict = {}
    latest_gen = 0
    world_by_gen: dict = {}
    for (rid, _rank, kind, gen), recs in streams.items():
        if rid != run_id:
            continue
        latest_gen = max(latest_gen, gen)
        if kind == "heartbeat":
            # generations fold together: the newest beat per rank wins,
            # so a rank that died in gen k and finished in gen k+1
            # correctly reads as finished
            fold_heartbeats(recs, beats)
        for r in recs:
            w = r.get("world")
            if isinstance(w, int) and w > 0:
                world_by_gen[gen] = max(world_by_gen.get(gen, 0), w)
    if not beats:
        return []
    now = max(b["ts"] for b in beats.values())
    rows = classify(beats, now)
    cur_world = world_by_gen.get(latest_gen, 0)
    for row in rows:
        beat_gen = beats.get(row["rank"], {}).get("gen", 0)
        if (
            cur_world
            and row["rank"] >= cur_world
            and beat_gen < latest_gen
            and row["status"] not in ("finished",)
        ):
            row["status"] = f"retired@gen{beat_gen}"
    return rows


def render_health(streams: dict) -> str:
    """The --health view for the newest run, one block per
    (rank, generation) — a supervised run's restarts segment here."""
    newest = _newest_run(streams)
    lines = [f"health report — run {newest}"]
    gens = sorted(
        {gen for (rid, _, gen) in metrics_streams(streams) if rid == newest}
    )
    if len(gens) > 1:
        lines.append(
            f"  restart generations: {len(gens)} "
            f"({len(gens) - 1} auto-restart(s); gen {gens[0]}..{gens[-1]})"
        )
    fmt = lambda v: f"{v:.4g}" if _finite(v) else "-"
    for (rid, rank, gen), recs in sorted(metrics_streams(streams).items(), key=str):
        if rid != newest:
            continue
        s = summarize_stream(recs)
        gen_tag = f" gen {gen}" if len(gens) > 1 else ""
        lines.append(
            f"  rank {rank}{gen_tag}: steps {s['steps']}  "
            f"loss {fmt(s['last_loss'])}  "
            f"loss_ema {fmt(s['loss_ema_last'])}"
        )
        lines.append(
            f"    norms: grad {fmt(s['grad_norm_first'])} -> "
            f"{fmt(s['grad_norm_last'])} (max {fmt(s['grad_norm_max'])})  "
            f"update {fmt(s['update_norm_last'])}  "
            f"param {fmt(s['param_norm_last'])}"
        )
        lines.append(
            f"    table: occupancy {fmt(s['occupancy_last'])}  "
            f"est_collision_rate {fmt(s['est_collision_rate_last'])}"
        )
        traj = s["auc_trajectory"]
        if traj:
            lines.append(
                f"    auc trajectory ({len(traj)} evals): "
                f"{fmt(traj[0])} -> {fmt(traj[-1])}"
                + ("  [declining]" if traj[-1] < traj[0] else "")
            )
        else:
            lines.append("    auc trajectory: none (train.eval_every off?)")
    hb = heartbeat_rows(streams, newest)
    if hb:
        lines.append("  heartbeats (lowest step first = the culprit ordering):")
        for row in hb:
            # retired@genK is a NEUTRAL state (the supervisor shrank
            # that rank away on purpose), not an alert like dead
            neutral = row["status"] in ("ok", "finished") or row[
                "status"
            ].startswith("retired")
            flag = "" if neutral else "  <-- " + row["status"].upper()
            lines.append(
                f"    rank {row['rank']}: step {row['step']}/{row['max_step']}"
                f"  last beat {row['age_s']:.1f}s before run end"
                f"  [{row['status']}]{flag}"
            )
    else:
        lines.append("  heartbeats: none (train.heartbeat_path off?)")
    pipe_lines = render_pipeline_verdict(streams, newest)
    if pipe_lines:
        lines.extend(pipe_lines)
    serve_lines = render_serve_latency_split(streams, newest)
    if serve_lines:
        lines.extend(serve_lines)
    at_lines = render_autotune_trajectory(streams, newest)
    if at_lines:
        lines.extend(at_lines)
    sync_lines = render_sync_staleness(streams, newest)
    if sync_lines:
        lines.extend(sync_lines)
    fresh_lines = render_freshness(streams, newest)
    if fresh_lines:
        lines.extend(fresh_lines)
    ckpt_lines = render_ckpt(streams, newest)
    if ckpt_lines:
        lines.extend(ckpt_lines)
    return "\n".join(lines)


def render_ckpt(streams: dict, run_id: str) -> list[str]:
    """The async-checkpoint section for the --health view
    (docs/ROBUSTNESS.md "Async tiered checkpointing"): last committed
    step per tier, committed/skip/failure counts, and whether the run
    ever degraded to replica-only saves — the first durability question
    an operator asks after an incident: what is the newest restorable
    step, and on which volume? Empty when the run carries no
    kind="ckpt" records (train.ckpt_async off)."""
    last_by_tier: dict = {}  # tier -> (ts, step)
    committed = 0
    failed = 0
    skips = 0
    degraded = False
    seen = False
    for (rid, _rank, kind, _gen), recs in sorted(streams.items(), key=str):
        if kind != "ckpt" or rid != run_id:
            continue
        for r in recs:
            seen = True
            skips = max(skips, r.get("skips", 0) or 0)
            if r.get("degraded") is True:
                degraded = True
            if r.get("event") == "failed":
                failed += 1
            if r.get("event") != "committed":
                continue
            committed += 1
            tier = r.get("tier", "?")
            cand = (r.get("committed_ts", 0.0), r.get("step"))
            if tier not in last_by_tier or cand > last_by_tier[tier]:
                last_by_tier[tier] = cand
    if not seen:
        return []
    out = ["  checkpoints (kind=ckpt, train.ckpt_async):"]
    for tier in CKPT_TIERS:
        if tier in last_by_tier:
            out.append(
                f"    {tier}: last committed step {last_by_tier[tier][1]}"
            )
        else:
            out.append(f"    {tier}: no committed saves")
    out.append(
        f"    committed {committed}  skipped {skips}  failed {failed}"
    )
    if degraded:
        out.append(
            "    DEGRADED: primary tier failed — saves land replica-only"
            "  <-- DEGRADED"
        )
    return out


def render_freshness(streams: dict, run_id: str) -> list[str]:
    """The data-freshness section for the --health view (docs/SERVING.md
    "Freshness"): publication cadence from the trainer's kind="publish"
    stream, then each serving replica's NEWEST data_freshness_s window
    gauge, and the stalest replica named — the first question a
    streaming run answers: how old is the data behind the predictions,
    and who is serving the oldest model? Empty when the run carries no
    publish records and no freshness-stamped serve windows
    (train.publish_every off, or a non-streaming run)."""
    pubs = 0
    last_pub = None  # (ts, step)
    for (rid, _rank, kind, _gen), recs in sorted(streams.items(), key=str):
        if kind != "publish" or rid != run_id:
            continue
        pubs += len(recs)
        for r in recs:
            if _finite(r.get("published_ts")):
                cand = (r["published_ts"], r.get("step"))
                if last_pub is None or cand > last_pub:
                    last_pub = cand
    # newest freshness-stamped window per serve stream; fold replicas
    # by rank (restart generations of one rank collapse, newest wins)
    by_rank: dict = {}  # rank -> (ts, freshness, model_gen)
    for (rid, rank, kind, _gen), recs in sorted(streams.items(), key=str):
        if kind != "serve" or rid != run_id:
            continue
        for r in recs:
            f = r.get("data_freshness_s")
            if not _finite(f):
                continue
            cand = (r.get("ts", 0.0), f, r.get("generation"))
            if rank not in by_rank or cand[0] > by_rank[rank][0]:
                by_rank[rank] = cand
    if not pubs and not by_rank:
        return []
    out = ["  freshness (kind=publish + serve data_freshness_s):"]
    if pubs:
        tail = ""
        if last_pub is not None:
            tail = f"  last at step {last_pub[1]}"
        out.append(f"    publications: {pubs}{tail}")
    else:
        out.append(
            "    publications: none in this run's streams "
            "(serving a checkpoint published elsewhere)"
        )
    stalest = None  # (freshness, rank)
    for rank, (_ts, f, mgen) in sorted(by_rank.items(), key=str):
        out.append(
            f"    replica rank {rank}: data_freshness_s {f:.3f} "
            f"(model generation {mgen})"
        )
        if stalest is None or f > stalest[0]:
            stalest = (f, rank)
    if stalest is not None:
        out.append(
            f"    stalest replica: rank {stalest[1]} "
            f"({stalest[0]:.3f}s behind the newest ingested row)"
        )
    elif pubs:
        out.append(
            "    no serving replica reported data_freshness_s "
            "(fleet not running, or windows predate the publication)"
        )
    return out


def render_sync_staleness(streams: dict, run_id: str) -> list[str]:
    """The multi-slice staleness-lag table for the --health view
    (docs/DISTRIBUTED.md "Multi-slice bounded staleness"): one line per
    slice's sync stream (newest generation wins — a rejoined slice
    reports its post-catch-up stream), then the most-stale peer across
    every slice's FINAL round, named. The first question a bounded-
    staleness run answers: who is holding the fleet back, and did
    anyone breach k? Empty when the run carries no sync records
    (sync.mode=off)."""
    by_rank: dict = {}  # rank -> (gen, records), newest gen wins
    for (rid, rank, kind, gen), recs in sorted(streams.items(), key=str):
        if kind != "sync" or rid != run_id or not recs:
            continue
        if rank not in by_rank or gen > by_rank[rank][0]:
            by_rank[rank] = (gen, recs)
    if not by_rank:
        return []
    last0 = next(iter(sorted(by_rank.items())))[1][1][-1]
    out = [
        f"  sync tier (kind=sync, mode={last0.get('mode')} "
        f"k={last0.get('k')}):"
    ]
    worst = None  # (lag, peer_slice, reporter_rank, reporter_round)
    for rank, (gen, recs) in sorted(by_rank.items(), key=str):
        last = recs[-1]
        stale_total = sum(r.get("stale", 0) for r in recs)
        timeout_total = sum(r.get("timeouts", 0) for r in recs)
        left_events = sum(len(r.get("left", ())) for r in recs)
        join_events = sum(len(r.get("joined", ())) for r in recs)
        out.append(
            f"    rank {rank}: rounds {last.get('round')}  "
            f"stale {stale_total}  timeouts {timeout_total}  "
            f"membership -{left_events}/+{join_events}  "
            f"last live {last.get('live')}"
        )
        lags = last.get("lags")
        if isinstance(lags, dict):
            for peer, lag in lags.items():
                if _finite(lag) and (worst is None or lag > worst[0]):
                    worst = (lag, peer, rank, last.get("round"))
    if worst and worst[0] > 0:
        out.append(
            f"    most-stale peer: slice {worst[1]} "
            f"({worst[0]} round(s) behind rank {worst[2]} at its final "
            f"round {worst[3]})"
        )
    elif worst is not None:
        out.append(
            "    most-stale peer: none (every peer caught up at the "
            "final round)"
        )
    return out


def render_pipeline_verdict(streams: dict, run_id: str) -> list[str]:
    """The input-pipeline bottleneck verdict for the --health view
    (docs/OBSERVABILITY.md "Input-pipeline attribution"), printed next
    to the queue-wait/device splits: aggregated kind="pipeline" stage
    seconds + the shared verdict line (telemetry.pipeline_verdict —
    the same one tools/pipeline_attrib.py prints). Empty when the run
    carries no pipeline records (train.pipeline_metrics off)."""
    from xflow_tpu.telemetry import PIPELINE_STAGES, pipeline_verdict

    stages = {s: 0.0 for s in PIPELINE_STAGES}
    wall = 0.0
    windows = 0
    for (rid, _rank, kind, _gen), recs in sorted(streams.items(), key=str):
        if kind != "pipeline" or rid != run_id:
            continue
        for r in recs:
            if not _finite(r.get("wall_s")):
                continue
            windows += 1
            wall += r["wall_s"]
            for s in stages:
                v = r.get(f"{s}_s")
                if _finite(v):
                    stages[s] += v
    if not windows:
        return []
    fmt = lambda s: f"{s} {100.0 * stages[s] / wall:.0f}%" if wall > 0 else s
    return [
        f"  input pipeline ({windows} window(s)): "
        + pipeline_verdict(stages, wall),
        "    stages: "
        + " | ".join(fmt(s) for s in ("parse", "cache_read", "plan",
                                      "producer_wait", "queue_wait",
                                      "dispatch", "device")),
    ]


def render_serve_latency_split(streams: dict, run_id: str) -> list[str]:
    """The per-replica queue-wait vs device p99 split (docs/SERVING.md
    "Telemetry + bench"): the first question request tracing answers in
    aggregate — is a replica's tail the COALESCER's backlog (queue-wait
    dominant: shrink the window, add replicas) or the DEVICE (device
    dominant: batch sizing, model cost)? One line per serve stream of
    the newest run, with the dominant side named."""
    fmt = lambda v: f"{v:.4g}" if _finite(v) else "-"
    out: list[str] = []
    for (rid, rank, gen), recs in sorted(serve_streams(streams).items(), key=str):
        if rid != run_id:
            continue
        windows = [r for r in recs if "qps" in r]
        q99s = [r["queue_wait_p99_ms"] for r in windows
                if _finite(r.get("queue_wait_p99_ms"))]
        d99s = [r["device_p99_ms"] for r in windows
                if _finite(r.get("device_p99_ms"))]
        if not q99s and not d99s:
            continue
        rep = next(
            (r["replica"] for r in recs if _finite(r.get("replica"))), None
        )
        q99 = max(q99s) if q99s else float("nan")
        d99 = max(d99s) if d99s else float("nan")
        dominant = (
            "queue-wait" if _finite(q99) and (not _finite(d99) or q99 >= d99)
            else "device"
        )
        label = f"replica {rep}" if rep is not None else f"rank {rank}"
        out.append(
            f"    {label} gen {gen}: queue_wait_p99 {fmt(q99)} ms | "
            f"device_p99 {fmt(d99)} ms  [{dominant}-bound]"
        )
    if out:
        out.insert(0, "  serving latency split (queue-wait vs device p99):")
    return out


def render_autotune_trajectory(streams: dict, run_id: str) -> list[str]:
    """The SLO-autotuner verdict for the --health view (docs/SERVING.md
    "Autotuning"): per controller stream, each knob's trajectory
    (start -> end over N decisions) plus a one-word verdict — did the
    closed loop CONVERGE (few direction reversals, settled), is it
    OSCILLATING (the damping failed to kill a flip-flop between the
    band edges), or is it PINNED AT FLOOR (the SLO is unattainable at
    this load and the controller gave up shrinking — raise the SLO or
    add replicas)? Empty when the run carries no autotune records
    (serve.autotune off)."""
    fmt = lambda v: f"{v:.4g}" if _finite(v) else "-"
    out: list[str] = []
    for (rid, rank, kind, gen), recs in sorted(streams.items(), key=str):
        if kind != "autotune" or rid != run_id:
            continue
        decisions = [r for r in recs if "knob" in r]
        if not decisions:
            continue
        rep = next(
            (r["replica"] for r in decisions if _finite(r.get("replica"))),
            None,
        )
        label = f"replica {rep}" if rep is not None else f"rank {rank}"
        parts = []
        verdict = "converged"
        for knob in AUTOTUNE_KNOB_NAMES:
            trail = [r for r in decisions if r.get("knob") == knob]
            if not trail:
                continue
            signs = [
                1 if r["new"] > r["old"] else -1
                for r in trail
                if _finite(r.get("old")) and _finite(r.get("new"))
                and r["new"] != r["old"]
            ]
            reversals = sum(
                1 for a, b in zip(signs, signs[1:]) if a != b
            )
            parts.append(
                f"{knob} {fmt(trail[0]['old'])} -> {fmt(trail[-1]['new'])} "
                f"({len(trail)} decision(s), {reversals} reversal(s))"
            )
            # oscillating: most moves undo the previous one — the
            # damping never settled the loop inside the band
            if len(signs) >= 4 and reversals > len(signs) // 2:
                verdict = "oscillating"
        if any(r.get("reason") == "floor_pinned" for r in decisions[-2:]):
            verdict = "pinned at floor (SLO unattainable at this load)"
        slo = decisions[-1].get("slo_p99_ms")
        out.append(
            f"    {label} gen {gen} (slo_p99_ms {fmt(slo)}): "
            + "  ".join(parts)
            + f"  [{verdict}]"
        )
    if out:
        out.insert(0, "  autotune trajectory (kind=autotune):")
    return out


# ---------------------------------------------------------------- --regress


def check_regression(
    current: dict, baseline: dict, tol: float, auc_tol: float
) -> list[str]:
    """Failures ([] = pass) comparing this run's bench record against a
    saved BENCH-style baseline. Throughput gates when both sides carry a
    value; AUC gates when both sides carry one."""
    problems = []
    base_v = baseline.get("value")
    cur_v = current.get("value")
    if _finite(base_v) and base_v > 0:
        if not _finite(cur_v):
            problems.append("current run has no throughput value")
        elif cur_v < (1.0 - tol) * base_v:
            problems.append(
                f"throughput regressed: {cur_v:.1f} < (1-{tol})*baseline "
                f"{base_v:.1f} {baseline.get('unit', '')}"
            )
    base_auc = baseline.get("auc")
    cur_auc = current.get("auc")
    if _finite(base_auc) and _finite(cur_auc) and cur_auc < base_auc - auc_tol:
        problems.append(
            f"AUC regressed: {cur_auc:.6f} < baseline {base_auc:.6f} - "
            f"{auc_tol}"
        )
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize / schema-check xflow telemetry JSONL runs"
    )
    ap.add_argument("paths", nargs="+", help="JSONL file(s) and/or run dir(s)")
    ap.add_argument("--check", action="store_true",
                    help="schema-validate and exit nonzero on violation")
    ap.add_argument("--health", action="store_true",
                    help="model-health summary: norm trends, AUC trajectory, "
                         "occupancy, heartbeat/straggler table")
    ap.add_argument("--bench-json", default="",
                    help="write a BENCH-style perf JSON here ('-' = stdout)")
    ap.add_argument("--regress", default="", metavar="BASELINE.json",
                    help="gate against a saved BENCH-style baseline; exit 3 "
                         "on throughput/AUC regression")
    ap.add_argument("--regress-tol", type=float, default=0.2,
                    help="allowed fractional throughput drop (default 0.2)")
    ap.add_argument("--auc-tol", type=float, default=0.01,
                    help="allowed absolute AUC drop (default 0.01)")
    args = ap.parse_args(argv)

    try:
        files = expand_paths(args.paths)
    except FileNotFoundError as e:
        print(f"metrics_report: {e}", file=sys.stderr)
        return 2
    streams, skipped = load_streams(files)

    if args.check:
        problems = check_streams(streams, files)
        if problems:
            for p in problems:
                print(f"metrics_report: FAIL: {p}", file=sys.stderr)
            return 2
        total = sum(len(v) for v in streams.values())
        print(
            f"metrics_report: OK: {len(files)} file(s), {len(streams)} "
            f"stream(s), {total} record(s), {skipped} damaged line(s) skipped"
        )
        return 0

    if not streams:
        # both views: an empty/wrong directory must not read as passing
        print("metrics_report: no records found", file=sys.stderr)
        return 1

    if args.health:
        # the health view replaces the summary table; --bench-json and
        # --regress below still run (a CI line can combine them)
        print(render_health(streams))
    else:
        rows = []
        for (run_id, rank, gen), records in sorted(
            metrics_streams(streams).items(), key=str
        ):
            s = summarize_stream(records)
            rows.append((
                run_id, rank, gen, s["steps"], s["examples"],
                round(s["elapsed_s"], 1),
                s["examples_per_s"], s["rows_per_s"], s["p50_ms"], s["p99_ms"],
                s["data_wait_ms"], s["last_loss"], s["bad_steps"], s["bad_rows"],
                s["eval_auc"],
            ))
        serve_table = render_serve_table(streams)
        compile_table = render_compile_table(streams)
        if rows:
            print(render_table(rows))
        if serve_table:
            print(serve_table)
        if compile_table:
            print(compile_table)
        if not rows and not serve_table and not compile_table:
            print("metrics_report: no records found", file=sys.stderr)
            return 1
    if skipped:
        print(f"# {skipped} damaged line(s) skipped (truncated append?)")

    if args.bench_json:
        # trainer record when the run trained; else the serving record
        # (a serve-only run dir feeds the BENCH_SERVE.json trajectory)
        rec = bench_record(streams) or serve_bench_record(streams)
        out = json.dumps(rec)
        if args.bench_json == "-":
            print(out)
        else:
            with open(args.bench_json, "w") as f:
                f.write(out + "\n")

    if args.regress:
        try:
            with open(args.regress) as f:
                baseline = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"metrics_report: cannot read baseline: {e}", file=sys.stderr)
            return 2
        problems = check_regression(
            bench_record(streams), baseline, args.regress_tol, args.auc_tol
        )
        if problems:
            for p in problems:
                print(f"metrics_report: REGRESSION: {p}", file=sys.stderr)
            return 3
        print(f"metrics_report: no regression vs {args.regress}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
