#!/usr/bin/env bash
# Streaming-freshness gate (docs/SERVING.md "Freshness", docs/DATA.md
# "Streaming source") — the whole stream -> train -> publish -> serve
# loop, live, under load:
#
# 1. Seed a libffm shard, then start a TAIL-MODE trainer
#    (data.stream=tail) that follows it: segments seal with ingest
#    trace ids, and every train.publish_every steps a committed
#    checkpoint publishes WITH its publication.json trace sidecar.
# 2. Wait for the first publication, then start a 2-replica
#    `xflow serve-fleet` on the SAME checkpoint dir (hot-reload poll +
#    span sink on), behind the health-checked router.
# 3. Drive tools/serve_bench.py closed-loop through the router while
#    APPENDING new rows to the watched shard mid-bench — the trainer
#    ingests them, publishes, and the replicas hot-swap the new
#    generations under live traffic. Gate: ZERO failed requests.
# 4. The trainer's idle timeout ends the stream; a last trickle of
#    requests closes the final publication's serve_first span. Gate:
#    the router /healthz carries the fleet freshness spread
#    (freshness_min_s / freshness_max_s / stalest_replica) and every
#    replica reports data_freshness_s.
# 5. tools/freshness_report.py reassembles the cross-process trace
#    (ingest -> publish -> reload -> serve_first), writes the
#    BENCH_FRESH.json ledger record, and GATES the end-to-end delta;
#    tools/metrics_report.py --check is green over the whole run dir
#    (ingest/publish/freshness schema gates included).
#
# Standalone:    bash tools/smoke_fresh.sh [workdir]
# From pytest:   tests/test_freshness.py::test_smoke_fresh_script
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
# bench datapoint destination: the repo root ONLY standalone (the
# per-PR record); under pytest it stays in the workdir
BENCH_OUT="$ROOT/BENCH_FRESH.json"
TRAIN_PID=""
FLEET_PID=""
cleanup() {
    if [ -n "$TRAIN_PID" ]; then kill -9 "$TRAIN_PID" 2>/dev/null || true; fi
    if [ -n "$FLEET_PID" ]; then kill -9 "$FLEET_PID" 2>/dev/null || true; fi
    # replicas are children of the fleet; sweep any orphans by this
    # run's unique workdir path
    pkill -9 -f "run_fresh" 2>/dev/null || true
    if [ -n "${TMP_WORK:-}" ]; then rm -rf "$TMP_WORK"; fi
}
trap cleanup EXIT
if [ -z "$WORK" ]; then
    TMP_WORK="$(mktemp -d)"
    WORK="$TMP_WORK"
else
    BENCH_OUT="$WORK/BENCH_FRESH.json"
fi

export JAX_PLATFORMS=cpu
# single CPU device (xargs trims; an empty result must UNSET the var —
# XLA treats a whitespace-only value as a flags FILE to open and aborts)
XLA_FLAGS="$(printf '%s\n' ${XLA_FLAGS:-} \
    | grep -v xla_force_host_platform_device_count | xargs || true)"
if [ -n "$XLA_FLAGS" ]; then export XLA_FLAGS; else unset XLA_FLAGS; fi

MODEL_ARGS=(--model lr --log2-slots 12
            --set model.num_fields=6 --set data.max_nnz=8)
RUN="$WORK/run_fresh"
mkdir -p "$RUN"

# ---- 1. seed the watched shard + start the tail-mode trainer --------------
python -m xflow_tpu gen-data "$WORK/stream" --shards 1 --rows 3200 \
    --fields 6 --ids-per-field 50 --seed 0 >/dev/null
# the mid-bench appends (1600 rows each = 25 more steps per append at
# batch 64, so each one crosses at least one publish_every=10 boundary)
python -m xflow_tpu gen-data "$WORK/more1" --shards 1 --rows 1600 \
    --fields 6 --ids-per-field 50 --seed 1 >/dev/null
python -m xflow_tpu gen-data "$WORK/more2" --shards 1 --rows 1600 \
    --fields 6 --ids-per-field 50 --seed 2 >/dev/null
python -m xflow_tpu gen-data "$WORK/reqs" --shards 1 --rows 512 \
    --fields 6 --ids-per-field 50 --seed 9 --truth-seed 0 >/dev/null

python -m xflow_tpu train --train "$WORK/stream" "${MODEL_ARGS[@]}" \
    --batch-size 64 --checkpoint-dir "$WORK/ck" \
    --set data.stream=tail --set data.stream_poll_s=0.2 \
    --set data.stream_idle_s=25 \
    --set train.publish_every=10 --set train.pred_dump=false \
    --set train.log_every=10 \
    --set train.metrics_path="$RUN/train_metrics.jsonl" \
    >/dev/null 2>"$WORK/train.log" &
TRAIN_PID=$!

for i in $(seq 1 240); do
    if ls "$WORK"/ck/step_*/publication.json >/dev/null 2>&1; then break; fi
    kill -0 "$TRAIN_PID" 2>/dev/null || {
        echo "smoke_fresh: trainer died before the first publication"
        cat "$WORK/train.log"; exit 1; }
    sleep 0.5
done
ls "$WORK"/ck/step_*/publication.json >/dev/null 2>&1 || {
    echo "smoke_fresh: no publication ever committed"
    cat "$WORK/train.log"; exit 1; }

# ---- 2. start the 2-replica fleet on the live checkpoint dir --------------
# trace_sample_rate > 0 binds the span sink (the publish->reload->
# serve_first links are operational spans, always emitted once bound;
# the low rate just keeps per-request span volume out of the smoke)
python -m xflow_tpu serve-fleet --checkpoint-dir "$WORK/ck" "${MODEL_ARGS[@]}" \
    --replicas 2 --port 0 --window-ms 3 --max-batch 64 --poll-s 0.3 \
    --reload-stagger-s 0.2 --retries 3 --deadline-ms 15000 \
    --health-poll-s 0.2 --run-dir "$RUN" \
    --no-mesh --set serve.metrics_every_s=1 \
    --set serve.trace_sample_rate=0.01 \
    >"$WORK/fleet_ready.json" 2>"$WORK/fleet.log" &
FLEET_PID=$!

for i in $(seq 1 360); do
    [ -s "$WORK/fleet_ready.json" ] && break
    kill -0 "$FLEET_PID" 2>/dev/null || {
        echo "smoke_fresh: fleet died during startup"
        cat "$WORK/fleet.log"; exit 1; }
    sleep 0.5
done
[ -s "$WORK/fleet_ready.json" ] || {
    echo "smoke_fresh: fleet never became ready"
    cat "$WORK/fleet.log"; exit 1; }
PORT=$(python - "$WORK/fleet_ready.json" <<'EOF'
import json, sys
ready = json.load(open(sys.argv[1]))
assert ready["fleet"] and len(ready["replicas"]) == 2, ready
print(ready["router_port"])
EOF
)

# ---- 3. bench through the router while the shard grows --------------------
python tools/serve_bench.py --url "http://127.0.0.1:$PORT" \
    --data "$WORK/reqs-00000" --duration 12 --concurrency 4 \
    --rows-per-request 4 --retries 3 --deadline-ms 20000 \
    >"$WORK/bench_report.json" 2>"$WORK/bench.log" &
BENCH_PID=$!
sleep 2
cat "$WORK/more1-00000" >>"$WORK/stream-00000"   # new rows land mid-load
sleep 3
cat "$WORK/more2-00000" >>"$WORK/stream-00000"
rc=0; wait "$BENCH_PID" || rc=$?
[ "$rc" -eq 0 ] || {
    echo "smoke_fresh: loadgen saw failed requests during live reloads"
    cat "$WORK/bench_report.json" "$WORK/fleet.log"; exit 1; }
python - "$WORK/bench_report.json" <<'EOF'
import json, sys
rec = json.load(open(sys.argv[1]))
assert rec["errors"] == 0, rec
assert rec["deadline_exceeded"] == 0, rec
assert len(rec["steps"]) >= 2, (
    f"appended rows never hot-reloaded mid-bench (served steps "
    f"{rec['steps']})")
print(f"smoke_fresh: load OK (qps {rec['value']}, served steps "
      f"{rec['steps']}, {rec['requests']} requests, 0 failed)")
EOF

# ---- 4. stream ends; close the final trace + check the fleet surface ------
for i in $(seq 1 480); do
    kill -0 "$TRAIN_PID" 2>/dev/null || break
    sleep 0.5
done
if kill -0 "$TRAIN_PID" 2>/dev/null; then
    echo "smoke_fresh: trainer never hit its idle timeout"
    cat "$WORK/train.log"; exit 1
fi
rc=0; wait "$TRAIN_PID" || rc=$?
TRAIN_PID=""
[ "$rc" -eq 0 ] || {
    echo "smoke_fresh: trainer exit $rc"; cat "$WORK/train.log"; exit 1; }

python - "$PORT" <<'EOF'
import http.client, json, sys, time

port = int(sys.argv[1])
# a trickle of requests across the final reload window closes the last
# publication's serve_first span
c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
for _ in range(8):
    c.request("POST", "/predict", json.dumps({"rows": ["0:a 1:b"]}),
              {"Content-Type": "application/json"})
    resp = c.getresponse()
    payload = json.loads(resp.read())
    assert resp.status == 200, payload
    time.sleep(0.3)
c.close()
# the fleet freshness spread: min/max + the stalest replica NAMED
deadline = time.monotonic() + 60
last = None
while time.monotonic() < deadline:
    try:
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        c.request("GET", "/healthz")
        last = json.loads(c.getresponse().read())
        c.close()
        if "freshness_min_s" in last and last.get("healthy") == 2:
            break
    except Exception:
        pass
    time.sleep(0.5)
assert last and last.get("healthy") == 2, f"fleet degraded: {last}"
assert "freshness_min_s" in last and "freshness_max_s" in last, last
assert "stalest_replica" in last, last
fresh = [r for r in last["replicas"] if "data_freshness_s" in r]
assert len(fresh) == 2, f"a replica never reported freshness: {last}"
assert all(r["data_freshness_s"] >= 0 for r in fresh), last
assert last["freshness_min_s"] <= last["freshness_max_s"], last
print(f"smoke_fresh: fleet freshness OK (min {last['freshness_min_s']}s, "
      f"max {last['freshness_max_s']}s, stalest replica "
      f"{last['stalest_replica']})")
EOF

# ---- 5. drain, assemble the Δ, gate everything ----------------------------
kill -TERM "$FLEET_PID"
rc=0; wait "$FLEET_PID" || rc=$?
FLEET_PID=""
[ "$rc" -eq 0 ] || {
    echo "smoke_fresh: fleet exit $rc"; cat "$WORK/fleet.log"; exit 1; }

# the ingest/publish records + the cross-process span links are all in
# ordinary JSONL — the trace id is the join key. 180s is the smoke's
# generosity bound for a loaded CI runner; the report prints the real
# decomposition for the ledger.
python tools/freshness_report.py "$RUN" --checkpoint-dir "$WORK/ck" \
    --bench-json "$BENCH_OUT" --max-delta-s 180

grep -q '"kind": "ingest"' "$RUN/train_metrics.jsonl" || {
    echo "smoke_fresh: no ingest records in the trainer stream"; exit 1; }
grep -q '"kind": "publish"' "$RUN/train_metrics.jsonl" || {
    echo "smoke_fresh: no publish records in the trainer stream"; exit 1; }
# direct grep, not `cat | grep -q`: under pipefail grep's early exit
# SIGPIPEs cat and fails the pipeline even when the record IS there
grep -q '"name": "serve_first"' "$RUN"/serve_replica*.jsonl || {
    echo "smoke_fresh: no serve_first span (the loop never closed)"; exit 1; }
grep -q '"data_freshness_s"' "$RUN"/serve_replica*.jsonl || {
    echo "smoke_fresh: no freshness-stamped serve window"; exit 1; }

python tools/metrics_report.py "$RUN" --check

# repo-root hygiene: running the tools from the root must leave no
# stray artifact dirs behind (tools/__pycache__ and friends)
rm -rf "$ROOT/tools/__pycache__" "$ROOT/__pycache__"

echo "smoke_fresh: OK"
