#!/usr/bin/env bash
# Perf-observability smoke gate (docs/OBSERVABILITY.md "Compile
# accounting", docs/PERF.md "Bench trajectory"): one instrumented
# 50-step synthetic CPU train proving the whole measurement layer end
# to end —
#   1. kind="compile" records for every compiled program, with nonzero
#      compile_time/flops/bytes and the op->scope map, gated by
#      metrics_report --check (schema + the exactly-once recompile rule);
#   2. roofline gauges (achieved_flops_per_s) in the window records;
#   3. tools/trace_attrib.py producing a per-scope device-time table
#      from the run's TraceWindow trace;
#   4. the round's BENCH_r09.json datapoint rendered through
#      tools/perf_ledger.py (markdown + JSON);
#   5. the ledger's regression mode exiting 3 on a controlled
#      regressed corpus (and 0 on a healthy one).
#
# Standalone:    bash tools/smoke_perf.sh [workdir]
# From pytest:   tests/test_perf_tools.py::test_smoke_perf_script
#
# With no workdir argument a temp dir is created and cleaned up.
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
# bench datapoint destination: the repo root ONLY standalone (the
# per-PR record); pytest runs keep it in the workdir so test runs
# never rewrite the committed BENCH_r09.json with machine-local numbers
BENCH_OUT="$ROOT/BENCH_r09.json"
if [ -z "$WORK" ]; then
    WORK="$(mktemp -d)"
    trap 'rm -rf "$WORK"' EXIT
else
    BENCH_OUT="$WORK/BENCH_r09.json"
fi

export JAX_PLATFORMS=cpu

# ---- 1. instrumented run: compile accounting + roofline + trace window
# 3200 rows / batch 64 = 50 steps; the trace window [10, 20) sits in
# the steady state, after the train program compiled
python -m xflow_tpu gen-data "$WORK/train" --shards 1 --rows 3200 \
    --fields 6 --ids-per-field 50 --seed 0 >/dev/null

python -m xflow_tpu train \
    --train "$WORK/train" --model lr --epochs 1 \
    --batch-size 64 --log2-slots 12 --no-mesh \
    --set model.num_fields=6 \
    --set data.max_nnz=8 \
    --set train.pred_dump=false \
    --set train.log_every=10 \
    --set "train.metrics_path=$WORK/run/metrics_rank0.jsonl" \
    --set "train.profile_dir=$WORK/prof" \
    --set train.trace_start_step=10 \
    --set train.trace_num_steps=10 \
    >/dev/null

# ---- 2. compile-record schema + exactly-once recompile gate ---------------
python tools/metrics_report.py "$WORK/run" --check
python - "$WORK/run/metrics_rank0.jsonl" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
comp = [r for r in recs if r.get("kind") == "compile"]
assert comp, "no kind=compile records in the run"
for c in comp:
    assert c["compile_time_s"] > 0, f"zero compile time: {c['program']}"
    assert c["flops"] and c["flops"] > 0, f"no flops: {c['program']}"
    assert c["bytes_accessed"] and c["bytes_accessed"] > 0, \
        f"no bytes: {c['program']}"
    assert c.get("op_scopes"), f"no op_scopes map: {c['program']}"
wins = [r for r in recs if "achieved_flops_per_s" in r]
assert wins, "no roofline gauges in any window record"
print(f"smoke_perf: {len(comp)} compile record(s), "
      f"roofline gauges in {len(wins)} window(s)")
EOF

# ---- 3. trace attribution from the run's own trace window -----------------
python tools/trace_attrib.py "$WORK/prof" --run-dir "$WORK/run" \
    --json "$WORK/attrib.json"
python - "$WORK/attrib.json" <<'EOF'
import json, sys
d = json.load(open(sys.argv[1]))
assert d["total_ms"] > 0, "trace attributed zero device time"
named = [s for s in d["scopes"] if s != "other"]
assert named, f"no named scope attributed any time: {d}"
print(f"smoke_perf: trace attributed ({d['total_ms']} ms device time, "
      f"named scopes: {named})")
EOF

# ---- 4. the round's bench datapoint through the ledger path ---------------
# emitted from a CLEAN (untraced) run: the instrumented run above
# carries profiler overhead, and the trajectory datapoint must be the
# steady state, not the measurement's own cost
python -m xflow_tpu train \
    --train "$WORK/train" --model lr --epochs 1 \
    --batch-size 64 --log2-slots 12 --no-mesh \
    --set model.num_fields=6 \
    --set data.max_nnz=8 \
    --set train.pred_dump=false \
    --set train.log_every=10 \
    --set "train.metrics_path=$WORK/run_clean/metrics_rank0.jsonl" \
    >/dev/null
python tools/metrics_report.py "$WORK/run_clean" --check
python tools/metrics_report.py "$WORK/run_clean" --bench-json "$BENCH_OUT"
python tools/perf_ledger.py "$BENCH_OUT" \
    --markdown "$WORK/ledger.md" --json "$WORK/ledger.json"
grep -q "Bench trajectory" "$WORK/ledger.md"
grep -q "telemetry_examples_per_sec" "$WORK/ledger.md"

# ---- 5. regression-gate mechanics on a controlled corpus ------------------
# (the real trajectory mixes machines — tolerance judgments there are
# the operator's; the MECHANICS are what CI pins: healthy -> 0,
# regressed -> 3)
mkdir -p "$WORK/series"
echo '{"metric": "smoke_examples_per_sec", "value": 1000.0, "unit": "examples/sec"}' \
    > "$WORK/series/BENCH_r01.json"
echo '{"metric": "smoke_examples_per_sec", "value": 990.0, "unit": "examples/sec"}' \
    > "$WORK/series/BENCH_r02.json"
python tools/perf_ledger.py --root "$WORK/series" --regress --markdown '' >/dev/null
echo '{"metric": "smoke_examples_per_sec", "value": 100.0, "unit": "examples/sec"}' \
    > "$WORK/series/BENCH_r03.json"
rc=0
python tools/perf_ledger.py --root "$WORK/series" --regress --markdown '' \
    >/dev/null 2>&1 || rc=$?
[ "$rc" -eq 3 ] || {
    echo "smoke_perf: ledger regression mode expected exit 3, got $rc"; exit 1; }

# repo-root hygiene: running the tools from the root must leave no
# stray artifact dirs behind (tools/__pycache__ and friends)
rm -rf "$ROOT/tools/__pycache__" "$ROOT/__pycache__"

echo "smoke_perf: OK"
