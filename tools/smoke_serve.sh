#!/usr/bin/env bash
# Serving smoke gate (docs/SERVING.md):
#
# 1. Train a small LR run with committed checkpoints every 10 steps
#    (10..50), and dump the FINAL state's evaluate() probabilities on a
#    held-out request set — the offline side of the parity pin.
# 2. Stage the step-20 checkpoint into a serving dir (atomic rename —
#    the shipping contract), start `xflow serve` on a free port with a
#    3 ms coalescing window, and wait for the ready line.
# 3. Drive tools/serve_bench.py closed-loop against it; MID-LOAD,
#    atomically commit the step-50 checkpoint into the serving dir.
#    The watcher must hot-reload it: the bench report must show a
#    generation flip (steps 20 -> 50) with ZERO failed requests — the
#    swap drops and blocks nothing. Emits BENCH_SERVE.json
#    (docs/PERF.md "Bench trajectory").
# 4. Parity: POST the held-out rows and compare the served pCTRs
#    against step 1's evaluate() dump (same rows, same checkpoint,
#    float tolerance) — online serving == offline eval, pinned.
# 5. tools/metrics_report.py --check green on the kind="serve" stream,
#    the reload event present, and a graceful SIGTERM shutdown.
#
# Standalone:    bash tools/smoke_serve.sh [workdir]
# From pytest:   tests/test_serve.py::test_smoke_serve_script
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
# bench datapoint destination: the repo root ONLY standalone (the
# per-PR record); under pytest it stays in the workdir
BENCH_OUT="$ROOT/BENCH_SERVE.json"
SERVE_PID=""
cleanup() {
    if [ -n "$SERVE_PID" ]; then kill -9 "$SERVE_PID" 2>/dev/null || true; fi
    if [ -n "${TMP_WORK:-}" ]; then rm -rf "$TMP_WORK"; fi
}
trap cleanup EXIT
if [ -z "$WORK" ]; then
    TMP_WORK="$(mktemp -d)"
    WORK="$TMP_WORK"
else
    BENCH_OUT="$WORK/BENCH_SERVE.json"
fi

export JAX_PLATFORMS=cpu
# single CPU device (xargs trims; an empty result must UNSET the var —
# XLA treats a whitespace-only value as a flags FILE to open and aborts)
XLA_FLAGS="$(printf '%s\n' ${XLA_FLAGS:-} \
    | grep -v xla_force_host_platform_device_count | xargs || true)"
if [ -n "$XLA_FLAGS" ]; then export XLA_FLAGS; else unset XLA_FLAGS; fi

MODEL_ARGS=(--model lr --log2-slots 12
            --set model.num_fields=6 --set data.max_nnz=8)

# ---- 1. train with a checkpoint trail + offline parity dump ---------------
python -m xflow_tpu gen-data "$WORK/train" --shards 1 --rows 3200 \
    --fields 6 --ids-per-field 50 --seed 0 >/dev/null
python -m xflow_tpu gen-data "$WORK/reqs" --shards 1 --rows 512 \
    --fields 6 --ids-per-field 50 --seed 9 --truth-seed 0 >/dev/null

python -m xflow_tpu train --train "$WORK/train" "${MODEL_ARGS[@]}" \
    --epochs 1 --batch-size 64 --checkpoint-dir "$WORK/ck" \
    --set train.checkpoint_every=10 --set train.pred_dump=false \
    --set train.log_every=10 >/dev/null 2>"$WORK/train.log"

# offline side of the parity pin: evaluate() probabilities from the
# FINAL (step-50) checkpoint on the request rows
(cd "$WORK" && python - "$WORK" <<'EOF'
import sys
from xflow_tpu.config import Config, override
from xflow_tpu.train.trainer import Trainer

work = sys.argv[1]
cfg = override(Config(), **{
    "model.name": "lr", "data.log2_slots": 12, "model.num_fields": 6,
    "data.max_nnz": 8, "data.batch_size": 64,
    "train.checkpoint_dir": f"{work}/ck",
})
t = Trainer(cfg)
assert t.maybe_restore(), "no checkpoint restored"
assert int(t.state.step) == 50, int(t.state.step)
t.evaluate(test_path=f"{work}/reqs-00000", dump=True, block=0)
EOF
)
[ -s "$WORK/pred_0_0.txt" ] || { echo "smoke_serve: no eval dump"; exit 1; }

# ---- 2. stage step-20 and start the server --------------------------------
stage() {  # atomic checkpoint shipping: payload under a temp name, one rename
    python - "$WORK/ck" "$WORK/serve_ck" "$1" <<'EOF'
import os, shutil, sys
src, dst, step = sys.argv[1], sys.argv[2], sys.argv[3]
os.makedirs(dst, exist_ok=True)
tmp = os.path.join(dst, f".staging_{step}")
if os.path.exists(tmp):
    shutil.rmtree(tmp)
shutil.copytree(os.path.join(src, f"step_{step}"), tmp)
os.replace(tmp, os.path.join(dst, f"step_{step}"))
EOF
}
stage 20

mkdir -p "$WORK/run_serve"
python -m xflow_tpu serve --checkpoint-dir "$WORK/serve_ck" "${MODEL_ARGS[@]}" \
    --port 0 --window-ms 3 --max-batch 64 --poll-s 0.3 --no-mesh \
    --metrics-path "$WORK/run_serve/serve_rank0.jsonl" \
    --set serve.metrics_every_s=1 \
    >"$WORK/serve_ready.json" 2>"$WORK/serve.log" &
SERVE_PID=$!

for i in $(seq 1 240); do
    [ -s "$WORK/serve_ready.json" ] && break
    kill -0 "$SERVE_PID" 2>/dev/null || {
        echo "smoke_serve: server died during startup"; cat "$WORK/serve.log"; exit 1; }
    sleep 0.5
done
[ -s "$WORK/serve_ready.json" ] || {
    echo "smoke_serve: server never became ready"; cat "$WORK/serve.log"; exit 1; }
PORT=$(python -c "import json,sys; print(json.load(open(sys.argv[1]))['port'])" \
    "$WORK/serve_ready.json")
grep -q '"step": 20' "$WORK/serve_ready.json" || {
    echo "smoke_serve: server did not start at step 20"; cat "$WORK/serve_ready.json"; exit 1; }

# ---- 3. loadgen + hot reload mid-load -------------------------------------
python tools/serve_bench.py --url "http://127.0.0.1:$PORT" \
    --data "$WORK/reqs-00000" --duration 8 --concurrency 4 \
    --rows-per-request 4 --bench-json "$BENCH_OUT" \
    >"$WORK/bench_report.json" 2>"$WORK/bench.log" &
BENCH_PID=$!
sleep 2.5
stage 50   # a NEWER checkpoint commits while requests are in flight
rc=0; wait "$BENCH_PID" || rc=$?
[ "$rc" -eq 0 ] || {
    echo "smoke_serve: loadgen saw failed requests"
    cat "$WORK/bench_report.json" "$WORK/serve.log"; exit 1; }

python - "$BENCH_OUT" <<'EOF'
import json, sys
rec = json.load(open(sys.argv[1]))
assert rec["errors"] == 0, rec
assert rec["gen_flips"] >= 1, f"no hot-reload generation flip: {rec}"
assert rec["steps"] == [20, 50], f"served steps {rec['steps']} != [20, 50]"
assert rec["value"] > 0 and rec["p99_ms"] > 0, rec
print("smoke_serve: hot reload OK "
      f"(qps {rec['value']}, p50 {rec['p50_ms']}ms, p99 {rec['p99_ms']}ms, "
      f"generations {rec['generations']}, {rec['requests']} requests, "
      "0 dropped)")
EOF

# ---- 4. online == offline parity ------------------------------------------
python - "$WORK" "$PORT" <<'EOF'
import http.client, json, sys

work, port = sys.argv[1], int(sys.argv[2])
rows = [l.split("\t", 1)[1].strip()
        for l in open(f"{work}/reqs-00000").read().splitlines() if l.strip()]
preds = [float(l.split("\t")[0])
         for l in open(f"{work}/pred_0_0.txt").read().splitlines()]
assert len(rows) == len(preds), (len(rows), len(preds))
conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
conn.request("GET", "/healthz")
h = json.loads(conn.getresponse().read())
assert h["step"] == 50, f"server not on step 50 after reload: {h}"
served = []
for lo in range(0, len(rows), 32):
    body = json.dumps({"rows": rows[lo:lo + 32]})
    conn.request("POST", "/predict", body, {"Content-Type": "application/json"})
    resp = conn.getresponse()
    payload = json.loads(resp.read())
    assert resp.status == 200, payload
    served.extend(payload["pctr"])
worst = max(abs(a - b) for a, b in zip(served, preds))
assert worst < 1e-5, f"serve/eval divergence {worst}"
print(f"smoke_serve: parity OK ({len(rows)} rows, max |serve-eval| {worst:.2e})")
EOF

# ---- 5. telemetry gate + graceful shutdown --------------------------------
kill -TERM "$SERVE_PID"
rc=0; wait "$SERVE_PID" || rc=$?
SERVE_PID=""
[ "$rc" -eq 0 ] || { echo "smoke_serve: server exit $rc"; cat "$WORK/serve.log"; exit 1; }

python tools/metrics_report.py "$WORK/run_serve" --check
grep -q '"event": "reload"' "$WORK/run_serve/serve_rank0.jsonl" || {
    echo "smoke_serve: no reload event in the serve stream"; exit 1; }
# the server-side bench record agrees the run served traffic
# (capture-then-grep: `| grep -q` + pipefail can SIGPIPE the producer)
python tools/metrics_report.py "$WORK/run_serve" --bench-json - \
    >"$WORK/serve_bench_record.json"
grep -q serve_qps "$WORK/serve_bench_record.json" \
    || { echo "smoke_serve: no serve bench record"; exit 1; }

# repo-root hygiene: running the tools from the root must leave no
# stray artifact dirs behind (tools/__pycache__ and friends)
rm -rf "$ROOT/tools/__pycache__" "$ROOT/__pycache__"

echo "smoke_serve: OK"
