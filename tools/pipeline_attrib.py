#!/usr/bin/env python3
"""Input-pipeline attribution report (docs/OBSERVABILITY.md
"Input-pipeline attribution").

Reads a run's `kind="pipeline"` window records (written by a trainer
with `train.pipeline_metrics=true`) plus its ordinary metrics stream,
and answers the question the ROADMAP's ~28x host-side gap raises:
WHERE does the end-to-end wall time go, stage by stage, and which side
of the prefetch queue is the bottleneck?

    python tools/pipeline_attrib.py runs/exp1               # table + verdict
    python tools/pipeline_attrib.py runs/exp1 --json a.json # machine-readable
    python tools/pipeline_attrib.py runs/exp1 --bench-json BENCH_PIPELINE.json

Two concurrent timelines are reported (the schema's per-thread
invariant, `metrics_report --check`):

- **consumer** (the fit loop): queue-wait -> transfer -> dispatch ->
  device. These stages tile the loop, so their sum over the windows is
  the attribution-coverage figure (the acceptance bar: >= 95% of
  windowed wall attributed to named stages).
- **producer** (the prefetch thread): read / parse / hash / batch /
  pad / plan working time, plus `producer_wait` (blocked in the
  bounded queue's put — the device-is-the-bottleneck signal).

The verdict line names the binding constraint ("host-bound in parse:
61% of wall" / "device-bound: producer blocked ...%"), shared with
`metrics_report --health` (telemetry.pipeline_verdict).

`--bench-json` emits a BENCH-shaped record quantifying the host gap so
the trajectory (tools/perf_ledger.py) gates it: e2e examples/sec, the
device-bound rate the run would reach with data-wait removed
(examples / (elapsed - data_wait_total)), and their ratio — the same
construction under which BENCH_SCALE.json's 62.5k ex/s e2e vs 1.75M
device-bound reads as a ~28x gap. This record is the BEFORE
denominator for the packed-shard-cache PR (ROADMAP "close the loop").
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xflow_tpu.jsonl import read_jsonl_counted  # noqa: E402
from xflow_tpu.telemetry import (  # noqa: E402
    PIPELINE_CONSUMER_STAGES,
    PIPELINE_PRODUCER_STAGES,
    pipeline_verdict,
)


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


def load_records(paths: list[str]) -> list[dict]:
    """All records from JSONL files / run dirs, in file order."""
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(glob.glob(os.path.join(p, "*.jsonl")))
            if not found:
                raise FileNotFoundError(f"{p!r}: directory holds no *.jsonl files")
            files.extend(found)
        elif not os.path.exists(p):
            raise FileNotFoundError(f"{p!r}: no such file")
        else:
            files.append(p)
    records: list[dict] = []
    for f in files:
        records.extend(read_jsonl_counted(f)[0])
    return records


def newest_run(records: list[dict]) -> str:
    """run_id with the largest ts (the run an operator just produced)."""
    best, best_ts = "?", -1.0
    seen: dict = {}
    for r in records:
        rid = str(r.get("run_id", "?"))
        ts = r.get("ts", 0.0)
        if _finite(ts):
            seen[rid] = max(seen.get(rid, -1.0), ts)
    for rid, ts in seen.items():
        if ts > best_ts:
            best, best_ts = rid, ts
    return best


def attribution(records: list[dict], run_id: str) -> dict:
    """Aggregate the run's pipeline windows + metrics stream into one
    attribution summary (empty dict when the run has no kind="pipeline"
    records — the profiler was off)."""
    pipe = [
        r for r in records
        if r.get("kind") == "pipeline" and str(r.get("run_id", "?")) == run_id
    ]
    if not pipe:
        return {}
    stages = {s: 0.0 for s in PIPELINE_PRODUCER_STAGES + PIPELINE_CONSUMER_STAGES}
    wall = 0.0
    batches = rows = 0
    for r in pipe:
        if _finite(r.get("wall_s")):
            wall += r["wall_s"]
        for s in stages:
            v = r.get(f"{s}_s")
            if _finite(v):
                stages[s] += v
        if _finite(r.get("batches")):
            batches += int(r["batches"])
        if _finite(r.get("rows")):
            rows += int(r["rows"])
    consumer = sum(stages[s] for s in PIPELINE_CONSUMER_STAGES)
    producer = sum(stages[s] for s in PIPELINE_PRODUCER_STAGES)
    # the run's own throughput/decomposition context (metrics stream):
    # cumulative examples, elapsed, and the data-wait run total the
    # StepTimer's registry counters carry in every counters snapshot
    mets = [
        r for r in records
        if str(r.get("run_id", "?")) == run_id
        and str(r.get("kind", "metrics")) == "metrics"
    ]
    examples = max(
        (r["examples"] for r in mets if _finite(r.get("examples"))), default=0
    )
    elapsed = max(
        (r["elapsed_s"] for r in mets if _finite(r.get("elapsed_s"))), default=0.0
    )
    data_wait = 0.0
    for r in mets:
        c = r.get("counters")
        if isinstance(c, dict) and _finite(c.get("step.data_wait.total_s")):
            data_wait = max(data_wait, c["step.data_wait.total_s"])
    out = {
        "run_id": run_id,
        "windows": len(pipe),
        "wall_s": round(wall, 6),
        "batches": batches,
        "rows": rows,
        "stages_s": {s: round(v, 6) for s, v in stages.items()},
        "consumer_s": round(consumer, 6),
        "producer_s": round(producer, 6),
        "attributed_pct": round(100.0 * consumer / wall, 2) if wall > 0 else 0.0,
        "queue_depth": pipe[-1].get("queue_depth"),
        "queue_cap": pipe[-1].get("queue_cap"),
        "verdict": pipeline_verdict(stages, wall),
        "examples": int(examples),
        "elapsed_s": round(float(elapsed), 3),
        "data_wait_s": round(float(data_wait), 6),
    }
    if elapsed > 0:
        e2e = examples / elapsed
        out["e2e_examples_per_sec"] = round(e2e, 1)
        busy = elapsed - min(data_wait, elapsed * 0.999)
        if examples and busy > 0:
            # the host gap: the rate this run would sustain with the
            # data-wait removed (everything else unchanged) vs what it
            # actually sustained — BENCH_SCALE's 62.5k-vs-1.75M ratio
            # computed from the run's own telemetry
            out["device_bound_examples_per_sec"] = round(examples / busy, 1)
            out["host_gap_ratio"] = round((examples / busy) / e2e, 3)
    return out


def compare_fields(rec: dict, other_path: str, label: str) -> dict:
    """Fold a previous `--bench-json` record (e.g. the text-path run of
    the same workload) into `rec` as a comparison: the other path's e2e
    rate under `<label>_e2e_examples_per_sec` (the `_examples_per_sec`
    suffix makes it its own gated ledger group — the text-vs-cache
    trajectory), its host-gap ratio, and `speedup_vs_<label>`. This is
    how the round-12 packed-shard-cache datapoint carries BOTH paths in
    one record (docs/PERF.md "Host data plane")."""
    with open(other_path) as f:
        other = json.load(f)
    base = other.get("value")
    if not _finite(base) or base <= 0:
        raise ValueError(
            f"{other_path!r}: comparison record has no positive e2e value"
        )
    rec[f"{label}_e2e_examples_per_sec"] = base
    for key in ("host_gap_ratio", "attributed_pct"):
        if _finite(other.get(key)):
            rec[f"{label}_{key}"] = other[key]
    rec[f"speedup_vs_{label}"] = round(rec["value"] / base, 3)
    return rec


def bench_record(att: dict, rnd=None) -> dict:
    """The BENCH-shaped host-gap record (`--bench-json`), consumed by
    tools/perf_ledger.py: the e2e headline plus the device-bound
    companion (its `_examples_per_sec` suffix makes it a gated group of
    its own) and the per-stage budget."""
    wall = att.get("wall_s") or 0.0
    rec = {
        "metric": "pipeline_e2e_examples_per_sec",
        "value": att.get("e2e_examples_per_sec", 0.0),
        "unit": "examples/sec",
        "run_id": att.get("run_id"),
        "examples": att.get("examples"),
        "elapsed_s": att.get("elapsed_s"),
        "data_wait_s": att.get("data_wait_s"),
        "attributed_pct": att.get("attributed_pct"),
        "bottleneck": att.get("verdict"),
        "stage_pct": {
            s: round(100.0 * v / wall, 2) if wall > 0 else 0.0
            for s, v in att.get("stages_s", {}).items()
        },
    }
    for key in ("device_bound_examples_per_sec", "host_gap_ratio"):
        if key in att:
            rec[key] = att[key]
    if rnd is not None:
        rec["round"] = int(rnd)
    return rec


def render(att: dict) -> str:
    wall = att["wall_s"] or 1e-9
    lines = [
        f"pipeline attribution — run {att['run_id']} "
        f"({att['windows']} window(s), {att['wall_s']:.3f} s wall, "
        f"{att['rows']} rows / {att['batches']} batches)",
        f"{'side':9s} {'stage':14s} {'seconds':>10s} {'% of wall':>10s}",
        f"{'-' * 9} {'-' * 14} {'-' * 10} {'-' * 10}",
    ]
    for side, group in (
        ("consumer", PIPELINE_CONSUMER_STAGES),
        ("producer", PIPELINE_PRODUCER_STAGES),
    ):
        for s in group:
            v = att["stages_s"].get(s, 0.0)
            lines.append(
                f"{side:9s} {s:14s} {v:10.3f} {100.0 * v / wall:9.1f}%"
            )
    lines.append(
        f"attributed (consumer side): {att['attributed_pct']:.1f}% of "
        "windowed wall"
    )
    if "e2e_examples_per_sec" in att:
        tail = ""
        if "device_bound_examples_per_sec" in att:
            tail = (
                f"  vs device-bound {att['device_bound_examples_per_sec']:,.0f}"
                f" (host gap {att.get('host_gap_ratio', 1.0):.2f}x)"
            )
        lines.append(
            f"e2e: {att['e2e_examples_per_sec']:,.0f} examples/sec{tail}"
        )
    lines.append(f"verdict: {att['verdict']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-stage input-pipeline attribution from a run's "
        'kind="pipeline" telemetry (train.pipeline_metrics=true)'
    )
    ap.add_argument("paths", nargs="+", help="JSONL file(s) and/or run dir(s)")
    ap.add_argument("--run-id", default="",
                    help="attribute this run (default: the newest by ts)")
    ap.add_argument("--json", default="", metavar="OUT",
                    help="write the attribution summary JSON ('-' = stdout)")
    ap.add_argument("--bench-json", default="", metavar="OUT",
                    help="write the BENCH-shaped host-gap record "
                         "('-' = stdout; feeds tools/perf_ledger.py)")
    ap.add_argument("--round", type=int, default=None,
                    help="trajectory round stamped into the bench record "
                         "(perf_ledger gates rounds)")
    ap.add_argument("--compare", default="", metavar="BENCH_JSON",
                    help="a previous --bench-json record of the SAME "
                         "workload on another input path (e.g. the "
                         "text-path run) to fold into this record as "
                         "<label>_e2e_examples_per_sec + speedup_vs_<label>")
    ap.add_argument("--compare-label", default="text",
                    help="label for the --compare record's keys "
                         "(default: text)")
    args = ap.parse_args(argv)

    try:
        records = load_records(args.paths)
    except FileNotFoundError as e:
        print(f"pipeline_attrib: {e}", file=sys.stderr)
        return 2
    run_id = args.run_id or newest_run(records)
    att = attribution(records, run_id)
    if not att:
        print(
            f"pipeline_attrib: run {run_id!r} has no kind=\"pipeline\" "
            "records — run with train.pipeline_metrics=true",
            file=sys.stderr,
        )
        return 1
    print(render(att))
    if args.json:
        payload = json.dumps(att, indent=1)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w") as f:
                f.write(payload + "\n")
    if args.bench_json:
        if "e2e_examples_per_sec" not in att:
            # never feed the trajectory a fabricated 0 ex/s datapoint
            # (a round-stamped zero would fail --regress against every
            # real previous round)
            print(
                "pipeline_attrib: run has no throughput context "
                "(metrics stream lacks examples/elapsed — "
                "train.log_every=0?); refusing to write a bench record",
                file=sys.stderr,
            )
            return 1
        rec = bench_record(att, rnd=args.round)
        if args.compare:
            try:
                rec = compare_fields(rec, args.compare, args.compare_label)
            except (OSError, ValueError, json.JSONDecodeError) as e:
                print(f"pipeline_attrib: --compare: {e}", file=sys.stderr)
                return 2
            lbl = args.compare_label
            print(
                f"vs {lbl}: {rec[f'speedup_vs_{lbl}']:.2f}x "
                f"({rec[f'{lbl}_e2e_examples_per_sec']:,.0f} -> "
                f"{rec['value']:,.0f} ex/s)"
            )
        payload = json.dumps(rec)
        if args.bench_json == "-":
            print(payload)
        else:
            with open(args.bench_json, "w") as f:
                f.write(payload + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
