#!/usr/bin/env python3
"""Attribute an xprof trace's device-op time to the step's named
scopes (docs/OBSERVABILITY.md "Trace attribution").

The TraceWindow (`train.trace_start_step`/`train.trace_num_steps`)
captures a steady-state trace nobody could read as op soup: hundreds of
fused HLO ops per step. The step builders already label the program
with `jax.named_scope`s (gather / loss / grad / optimizer /
scatter_optimizer / train_step), and the CompileRecorder stamps every
compile record with the {optimized-HLO op -> scope} map scraped from
the compiled module's metadata — this tool joins the two:

    python tools/trace_attrib.py /runs/exp1/prof --run-dir /runs/exp1
    python tools/trace_attrib.py trace.json.gz --run-dir /runs/exp1 --json -

and prints the per-scope device-time table ("the gather is 34% of the
step") that is the before/after evidence any kernel PR needs.

How the join works, per trace event (Chrome-trace `ph == "X"`):

1. the event's `args.hlo_op` (CPU backend) or name is looked up in the
   op->scope map from the `kind="compile"` records under --run-dir —
   keyed per `hlo_module` when both the record and the event carry the
   module name (HLO op names are only unique within one module, so a
   run that compiled train_step AND predict never cross-attributes),
   with a flat merged map (newest mapping wins) for events/records
   that lack it;
2. failing that, any path-shaped arg value (`tf_op` / `long_name` /
   `name`, the TPU backends' op metadata) is split on "/" and the last
   component matching a known scope label attributes the event;
3. with NO map available at all (no --run-dir), a last-resort keyword
   match on the op name itself runs (a `bitcast_gather_fusion` counts
   as "gather") — honest enough for a quick look, but it cannot tell a
   backward gather under `grad` from the forward's, so the compile-
   record join is the real path. Unmatched device ops bucket "other";
   host-side python events are excluded entirely.

Exit codes: 0 = table printed; 1 = no device-op events in the trace;
2 = no trace found / unreadable input.
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xflow_tpu.jsonl import read_jsonl  # noqa: E402
from xflow_tpu.telemetry import SCOPE_LABELS  # noqa: E402


def find_trace(path: str) -> str:
    """`path` itself when it is a trace file, else the newest
    *.trace.json(.gz) under it (TraceWindow writes
    <profile_dir>/plugins/profile/<ts>/<host>.trace.json.gz)."""
    if os.path.isfile(path):
        return path
    hits = glob.glob(os.path.join(path, "**", "*.trace.json.gz"), recursive=True)
    hits += glob.glob(os.path.join(path, "**", "*.trace.json"), recursive=True)
    if not hits:
        raise FileNotFoundError(f"no *.trace.json(.gz) under {path!r}")
    return max(hits, key=os.path.getmtime)


def load_trace(path: str) -> list:
    """The trace's event list, from gzip or plain chrome-trace JSON."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    if isinstance(data, dict):
        return data.get("traceEvents", [])
    return data if isinstance(data, list) else []


def load_op_scopes(run_dir: str) -> tuple[dict, dict]:
    """({hlo_module: {op -> scope}}, flat merged {op -> scope}) over
    every kind="compile" record in the run dir's JSONL files (newest
    mapping wins — a recompile's map supersedes). The per-module maps
    drive the join when the trace event names its module; the flat map
    is the fallback for records or events without one."""
    by_module: dict = {}
    flat: dict = {}
    for path in sorted(glob.glob(os.path.join(run_dir, "*.jsonl"))):
        for rec in read_jsonl(path, warn=False):
            if rec.get("kind") == "compile" and isinstance(
                rec.get("op_scopes"), dict
            ):
                flat.update(rec["op_scopes"])
                if rec.get("hlo_module"):
                    by_module.setdefault(rec["hlo_module"], {}).update(
                        rec["op_scopes"]
                    )
    return by_module, flat


def scope_of(
    name: str, args: dict, by_module: dict, op_scopes: dict, scopes: tuple,
    keyword_ok: bool
) -> str:
    """One event's scope bucket (see module docstring for the order)."""
    op = args.get("hlo_op") if isinstance(args, dict) else None
    mod_map = (
        by_module.get(args.get("hlo_module")) if isinstance(args, dict) else None
    )
    if mod_map is not None:
        # the event's own module is known: its map is authoritative —
        # never fall through to another program's identically-named op
        for key in (op, name):
            if key and key in mod_map:
                return mod_map[key]
    else:
        for key in (op, name):
            if key and key in op_scopes:
                return op_scopes[key]
    # path-shaped metadata (TPU op events): last scope component wins,
    # excluding the final component (the primitive name)
    candidates = [name] if "/" in name else []
    if isinstance(args, dict):
        for k in ("tf_op", "long_name", "name"):
            v = args.get(k)
            if isinstance(v, str) and "/" in v:
                candidates.append(v)
    for path in candidates:
        comps = path.split("/")
        for comp in reversed(comps[:-1]):
            if comp in scopes:
                return comp
    if keyword_ok:
        for scope in scopes:
            base = scope.split("_")[0]  # scatter_optimizer -> scatter
            if base and base in (op or name or ""):
                return scope
    return "other"


def attribute(
    events: list, by_module: dict, op_scopes: dict, scopes: tuple
) -> tuple[dict, dict, float]:
    """({scope: total_us}, {scope: event count}, total_us) over the
    trace's device-op events. Device-op = a complete event carrying an
    `hlo_op` arg (CPU backend) or living on a `/device:` process row
    (TPU/GPU backends) — minus the "XLA Modules"/"Steps" summary rows,
    whose spans aggregate the op rows over the same wall time."""
    device_pids = set()
    summary_tids = set()  # (pid, tid) rows whose spans AGGREGATE ops
    for e in events:
        if e.get("ph") != "M":
            continue
        args = e.get("args") or {}
        if e.get("name") == "process_name":
            pname = str(args.get("name", ""))
            if "/device:" in pname or pname.startswith("TPU"):
                device_pids.add(e.get("pid"))
        elif e.get("name") == "thread_name":
            # TPU xprof device rows: "XLA Ops" holds the per-op events;
            # "XLA Modules"/"Steps" rows span WHOLE program executions
            # over the same wall time — counting both double-counts
            # every op and halves every per-scope percentage
            tname = str(args.get("name", "")).lower()
            if "module" in tname or tname.startswith("step"):
                summary_tids.add((e.get("pid"), e.get("tid")))
    keyword_ok = not op_scopes
    totals: dict = {}
    counts: dict = {}
    total_us = 0.0
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        is_device = (isinstance(args, dict) and "hlo_op" in args) or (
            e.get("pid") in device_pids
        )
        if not is_device:
            continue
        if "hlo_op" not in args and (e.get("pid"), e.get("tid")) in summary_tids:
            continue  # an op event is never excluded, a summary span is
        dur = e.get("dur")
        if not isinstance(dur, (int, float)) or dur <= 0:
            continue
        scope = scope_of(str(e.get("name", "")), args, by_module, op_scopes,
                         scopes, keyword_ok)
        totals[scope] = totals.get(scope, 0.0) + float(dur)
        counts[scope] = counts.get(scope, 0) + 1
        total_us += float(dur)
    return totals, counts, total_us


def render(totals: dict, counts: dict, total_us: float) -> str:
    rows = sorted(totals.items(), key=lambda kv: -kv[1])
    lines = ["scope                 device_ms       %   events",
             "-----                 ---------       -   ------"]
    for scope, us in rows:
        lines.append(
            f"{scope:<20}  {us / 1e3:>9.3f}  {100.0 * us / total_us:>6.1f}"
            f"   {counts[scope]:>6}"
        )
    lines.append(
        f"{'total':<20}  {total_us / 1e3:>9.3f}   100.0   {sum(counts.values()):>6}"
    )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="bucket an xprof trace's device-op time by the step's "
        "named scopes"
    )
    ap.add_argument("trace", help="profile dir (train.profile_dir) or a "
                                  "*.trace.json(.gz) file")
    ap.add_argument("--run-dir", default="",
                    help="run dir holding metrics JSONL with kind=\"compile\" "
                         "records — their op_scopes maps drive the join")
    ap.add_argument("--scopes", default=",".join(SCOPE_LABELS),
                    help="comma-separated scope labels (default: the step "
                         "builders' named scopes)")
    ap.add_argument("--json", default="", metavar="OUT",
                    help="also write {scope: {ms, pct, events}} JSON "
                         "('-' = stdout)")
    args = ap.parse_args(argv)

    scopes = tuple(s for s in args.scopes.split(",") if s)
    try:
        trace_path = find_trace(args.trace)
        events = load_trace(trace_path)
    except (OSError, json.JSONDecodeError, FileNotFoundError) as e:
        print(f"trace_attrib: {e}", file=sys.stderr)
        return 2
    by_module, op_scopes = (
        load_op_scopes(args.run_dir) if args.run_dir else ({}, {})
    )
    if args.run_dir and not op_scopes:
        print(
            f"trace_attrib: warning: no kind=\"compile\" op_scopes under "
            f"{args.run_dir!r}; falling back to path/keyword matching",
            file=sys.stderr,
        )
    totals, counts, total_us = attribute(events, by_module, op_scopes, scopes)
    if total_us <= 0:
        print(
            f"trace_attrib: no device-op events in {trace_path!r} "
            "(trace captured before any step dispatched?)",
            file=sys.stderr,
        )
        return 1
    print(f"# trace: {trace_path}")
    if op_scopes:
        print(f"# op->scope map: {len(op_scopes)} ops from {args.run_dir!r}")
    print(render(totals, counts, total_us))
    if args.json:
        payload = {
            scope: {
                "ms": round(us / 1e3, 3),
                "pct": round(100.0 * us / total_us, 2),
                "events": counts[scope],
            }
            for scope, us in sorted(totals.items(), key=lambda kv: -kv[1])
        }
        out = json.dumps({"total_ms": round(total_us / 1e3, 3), "scopes": payload})
        if args.json == "-":
            print(out)
        else:
            with open(args.json, "w") as f:
                f.write(out + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
