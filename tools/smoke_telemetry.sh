#!/usr/bin/env bash
# Telemetry smoke gate (docs/OBSERVABILITY.md): a 50-step synthetic CPU
# train with the metrics JSONL on, then a schema validation of what it
# emitted via tools/metrics_report.py --check, then the human summary.
#
# Standalone:    bash tools/smoke_telemetry.sh [workdir]
# From pytest:   tests/test_telemetry.py::test_smoke_telemetry_script
#
# With no workdir argument a temp dir is created and cleaned up.
set -eu
cd "$(dirname "$0")/.."

WORK="${1:-}"
if [ -z "$WORK" ]; then
    WORK="$(mktemp -d)"
    trap 'rm -rf "$WORK"' EXIT
fi

export JAX_PLATFORMS=cpu

# 3200 rows / batch 64 = 50 steps
python -m xflow_tpu gen-data "$WORK/train" --shards 1 --rows 3200 \
    --fields 6 --ids-per-field 50 --seed 0 >/dev/null

python -m xflow_tpu train \
    --train "$WORK/train" --model lr --epochs 1 \
    --batch-size 64 --log2-slots 12 --no-mesh \
    --set model.num_fields=6 \
    --set data.max_nnz=8 \
    --set train.pred_dump=false \
    --set train.log_every=10 \
    --set "train.metrics_path=$WORK/run/metrics_rank0.jsonl" \
    >/dev/null

python tools/metrics_report.py "$WORK/run" --check
python tools/metrics_report.py "$WORK/run"
echo "smoke_telemetry: OK"
