#!/usr/bin/env bash
# Telemetry smoke gate (docs/OBSERVABILITY.md): a 50-step synthetic CPU
# train with the metrics JSONL, health metrics, heartbeats, and a
# streaming holdout eval on, then a schema validation of what it
# emitted via tools/metrics_report.py --check (extended schema: health
# fields all-or-none, eval records complete, heartbeat stream shape),
# the --health summary, the human summary table, a BENCH-style perf
# datapoint (BENCH_r06.json — the per-PR bench-trajectory convention,
# docs/PERF.md), and a --regress self-check against that fresh baseline.
#
# Standalone:    bash tools/smoke_telemetry.sh [workdir]
# From pytest:   tests/test_telemetry.py::test_smoke_telemetry_script
#
# With no workdir argument a temp dir is created and cleaned up.
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
# where the bench datapoint lands: the repo root ONLY on a standalone
# (argument-less) invocation — the per-PR record. With a workdir given
# (pytest runs), it stays in the workdir so test runs never rewrite
# the committed BENCH_r06.json with machine-local numbers.
BENCH_OUT="$ROOT/BENCH_r06.json"
if [ -z "$WORK" ]; then
    WORK="$(mktemp -d)"
    trap 'rm -rf "$WORK"' EXIT
else
    BENCH_OUT="$WORK/BENCH_r06.json"
fi

export JAX_PLATFORMS=cpu

# 3200 rows / batch 64 = 50 steps; the test split shares the planted
# truth (truth-seed) so the streaming AUC is meaningful
python -m xflow_tpu gen-data "$WORK/train" --shards 1 --rows 3200 \
    --fields 6 --ids-per-field 50 --seed 0 >/dev/null
python -m xflow_tpu gen-data "$WORK/test" --shards 1 --rows 640 \
    --fields 6 --ids-per-field 50 --seed 1 --truth-seed 0 >/dev/null

python -m xflow_tpu train \
    --train "$WORK/train" --test "$WORK/test" --model lr --epochs 1 \
    --batch-size 64 --log2-slots 12 --no-mesh \
    --set model.num_fields=6 \
    --set data.max_nnz=8 \
    --set train.pred_dump=false \
    --set train.log_every=10 \
    --set train.eval_every=1 \
    --set train.health_metrics=norms \
    --set train.heartbeat_every=10 \
    --set "train.metrics_path=$WORK/run/metrics_rank0.jsonl" \
    --set "train.heartbeat_path=$WORK/run/heartbeat_rank0.jsonl" \
    >/dev/null

python tools/metrics_report.py "$WORK/run" --check
python tools/metrics_report.py "$WORK/run" --health
python tools/metrics_report.py "$WORK/run"
# per-PR bench datapoint (docs/PERF.md "Bench trajectory"): the smoke
# run's own telemetry, in the BENCH_rNN.json series (repo root when
# standalone, workdir when driven by pytest — see BENCH_OUT above)
python tools/metrics_report.py "$WORK/run" --bench-json "$BENCH_OUT"
# regression gate self-check: a run can never regress against itself
python tools/metrics_report.py "$WORK/run" --regress "$BENCH_OUT" >/dev/null
# repo-root hygiene: running the tools from the root must leave no
# stray artifact dirs behind (tools/__pycache__ and friends)
rm -rf "$ROOT/tools/__pycache__" "$ROOT/__pycache__"

echo "smoke_telemetry: OK"
