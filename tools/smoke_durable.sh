#!/usr/bin/env bash
# Durability gate (docs/ROBUSTNESS.md "Async tiered checkpointing") —
# the async save pipeline + tier-2 replica, end to end:
#
# 1. Checkpoint-stall collapse: the SAME emulated-slow-disk save
#    (XFLOW_FAULT_CKPT_SLOW_S_PER_MB) is timed from the fit thread in
#    synchronous mode (round 1) and async mode (round 2); the p99 stall
#    lands in BENCH_CKPT.json and gates through perf_ledger --regress
#    (ckpt_stall_p99_ms is latency-shaped: the async round must not
#    regress upward). Hard gate here: async p99 < half the sync p99 and
#    within the same order as a plain train step.
# 2. Kill mid-async-save: a SIGKILL lands while the background writer
#    is mid-write (slow-paced). The torn step dir must be uncommitted
#    debris; the relaunch walks back, replays the stream, and the final
#    checkpoint accounts for every example exactly.
# 3. Replica serve drill: a trainer commits to primary+replica tiers; a
#    NEWER step ships with its primary copy digest-POISONED and only
#    the replica intact, while serve_bench drives closed-loop load. The
#    watcher must hot-reload the new step from the replica tier with
#    ZERO dropped requests.
# 4. tools/metrics_report.py --check green over the kind="ckpt" streams
#    (schema, tier/event vocab, at-most-one-in-flight intervals).
#
# Standalone:    bash tools/smoke_durable.sh [workdir]
# From pytest:   tests/test_durable_ckpt.py::test_smoke_durable_script
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
# bench datapoint destination: the repo root ONLY standalone (the
# per-PR record); under pytest it stays in the workdir
BENCH_OUT="$ROOT/BENCH_CKPT.json"
SERVE_PID=""
BENCH_PID=""
cleanup() {
    if [ -n "$SERVE_PID" ]; then kill -9 "$SERVE_PID" 2>/dev/null || true; fi
    if [ -n "$BENCH_PID" ]; then kill -9 "$BENCH_PID" 2>/dev/null || true; fi
    if [ -n "${TMP_WORK:-}" ]; then rm -rf "$TMP_WORK"; fi
}
trap cleanup EXIT
if [ -z "$WORK" ]; then
    TMP_WORK="$(mktemp -d)"
    WORK="$TMP_WORK"
else
    BENCH_OUT="$WORK/BENCH_CKPT.json"
fi

export JAX_PLATFORMS=cpu
# single CPU device (xargs trims; an empty result must UNSET the var —
# XLA treats a whitespace-only value as a flags FILE to open and aborts)
XLA_FLAGS="$(printf '%s\n' ${XLA_FLAGS:-} \
    | grep -v xla_force_host_platform_device_count | xargs || true)"
if [ -n "$XLA_FLAGS" ]; then export XLA_FLAGS; else unset XLA_FLAGS; fi

MODEL_ARGS=(--model lr --log2-slots 12
            --set model.num_fields=6 --set data.max_nnz=8)
RUN="$WORK/run_durable"
mkdir -p "$RUN"

python -m xflow_tpu gen-data "$WORK/train" --shards 1 --rows 3200 \
    --fields 6 --ids-per-field 50 --seed 0 >/dev/null
python -m xflow_tpu gen-data "$WORK/elastic" --shards 1 --rows 600 \
    --fields 6 --ids-per-field 50 --seed 3 >/dev/null
python -m xflow_tpu gen-data "$WORK/reqs" --shards 1 --rows 512 \
    --fields 6 --ids-per-field 50 --seed 9 --truth-seed 0 >/dev/null

# ---- 1. checkpoint-stall collapse (BENCH_CKPT.json rounds 1/2) ------------
# the emulated slow disk makes the write cost real on tmpfs; the fault
# paces on whichever thread does the writing, so sync mode stalls the
# fit thread and async mode does not — exactly the contract under test
python - "$WORK" "$BENCH_OUT" <<'EOF'
import json, os, sys, time

from xflow_tpu.config import Config, override
from xflow_tpu.train.trainer import Trainer

work, out = sys.argv[1], sys.argv[2]
os.environ["XFLOW_FAULT_CKPT_SLOW_S_PER_MB"] = "6"  # ~0.3s per staged file


def p99(ms):
    return sorted(ms)[max(int(len(ms) * 0.99) - 1, 0)]


def stall_round(tag, async_on):
    cfg = override(Config(), **{
        "model.name": "lr", "data.log2_slots": 12, "model.num_fields": 6,
        "data.max_nnz": 8, "data.batch_size": 64, "train.epochs": 1,
        "data.train_path": f"{work}/train",
        "train.pred_dump": False, "train.log_every": 0,
        "train.checkpoint_dir": f"{work}/bench_ck_{tag}",
        "train.ckpt_async": async_on,
        # NOT under run_durable/: both rounds run in THIS process, so
        # they share one run_id — merged they would trip the
        # compile-once gate; each file passes --check on its own
        "train.metrics_path": f"{work}/bench_{tag}.jsonl",
    })
    t = Trainer(cfg)
    res = t.fit()
    step_ms = res.seconds / max(res.steps, 1) * 1000.0
    stalls = []
    for _ in range(12):
        t0 = time.perf_counter()
        t.save_checkpoint()
        stalls.append((time.perf_counter() - t0) * 1000.0)
        if t._ckpt_writer is not None:
            t._ckpt_writer.drain()  # every submit must land (no skips)
    if t._ckpt_writer is not None:
        t._ckpt_writer.close()
        t._ckpt_writer = None
    t.metrics.close()
    return round(p99(stalls), 3), round(step_ms, 3)


sync_p99, step_ms = stall_round("sync", False)
async_p99, _ = stall_round("async", True)
recs = [
    {"metric": "ckpt_stall_p99_ms", "value": sync_p99, "unit": "ms",
     "round": 1, "mode": "sync", "train_step_ms": step_ms},
    {"metric": "ckpt_stall_p99_ms", "value": async_p99, "unit": "ms",
     "round": 2, "mode": "async", "train_step_ms": step_ms},
]
json.dump(recs, open(out, "w"), indent=1)
assert async_p99 < sync_p99 * 0.5, (
    f"async stall p99 {async_p99}ms did not collapse vs sync "
    f"{sync_p99}ms")
assert async_p99 < max(step_ms * 2.0, 50.0), (
    f"async stall p99 {async_p99}ms is not step-sized "
    f"(train step {step_ms}ms)")
print(f"smoke_durable: stall collapse OK (sync p99 {sync_p99}ms -> "
      f"async p99 {async_p99}ms; train step {step_ms}ms)")
EOF

# --root "$WORK": gate THIS series only — the repo-root trajectory has
# its own smoke (the explicit file folds in wherever BENCH_OUT lives)
python tools/perf_ledger.py --root "$WORK" "$BENCH_OUT" --regress >/dev/null || {
    echo "smoke_durable: perf_ledger --regress failed on BENCH_CKPT.json"
    exit 1; }

# ---- 2. kill mid-async-save, walk-back resume, exact accounting -----------
ELASTIC_ARGS=(--train "$WORK/elastic" --epochs 2 --batch-size 100
    --no-mesh --checkpoint-dir "$WORK/eck" "${MODEL_ARGS[@]}"
    --set train.pred_dump=false --set train.checkpoint_every=5
    --set train.resume=true
    --set train.metrics_path="$RUN/elastic_metrics.jsonl")

# phase A: sync saves, die after step 7 -> committed exactly [5]
XFLOW_FAULT_KILL_STEP=7 \
    python -m xflow_tpu train "${ELASTIC_ARGS[@]}" \
    >/dev/null 2>"$WORK/phaseA.log" && {
    echo "smoke_durable: phase A was supposed to be killed"; exit 1; }
[ -e "$WORK/eck/step_5/COMMITTED" ] || {
    echo "smoke_durable: phase A left no committed step 5"
    cat "$WORK/phaseA.log"; exit 1; }

# phase B: resume from 5, async on, the step-10 save paced to ~30s; the
# kill at local step 6 (global 11) lands mid-write
XFLOW_FAULT_KILL_STEP=6 XFLOW_FAULT_CKPT_SLOW_S_PER_MB=600 \
    XFLOW_FAULT_CKPT_TIER=primary \
    python -m xflow_tpu train "${ELASTIC_ARGS[@]}" --set train.ckpt_async=true \
    >/dev/null 2>"$WORK/phaseB.log" && {
    echo "smoke_durable: phase B was supposed to be killed"; exit 1; }
grep -q "resumed from step 5" "$WORK/phaseB.log" || {
    echo "smoke_durable: phase B did not resume from step 5"
    cat "$WORK/phaseB.log"; exit 1; }
[ -d "$WORK/eck/step_10" ] || {
    echo "smoke_durable: phase B left no torn step-10 debris"; exit 1; }
[ -e "$WORK/eck/step_10/COMMITTED" ] && {
    echo "smoke_durable: the mid-write kill still committed step 10"
    exit 1; }

# phase C: faults off — the walk-back resume sweeps the debris and
# finishes with exact accounting
python -m xflow_tpu train "${ELASTIC_ARGS[@]}" --set train.ckpt_async=true \
    >/dev/null 2>"$WORK/phaseC.log" || {
    echo "smoke_durable: phase C failed"; cat "$WORK/phaseC.log"; exit 1; }
python - "$WORK/eck" <<'EOF'
from xflow_tpu.train.checkpoint import committed_steps, read_data_state
import sys

ck = sys.argv[1]
steps = committed_steps(ck)
assert steps[0] == 12, f"final committed steps {steps}"
ds = read_data_state(ck, 12)
assert ds["completed"] and ds["examples"] == 1200, ds
print(f"smoke_durable: kill-mid-async-save OK (committed {steps}, "
      f"{ds['examples']} examples accounted)")
EOF

# ---- 3. serve hot reload from the replica tier under load -----------------
# commit steps 10..50 to BOTH tiers (sync mode mirrors inline — the
# replica machinery under test is mirror_step, shared with the writer)
python -m xflow_tpu train --train "$WORK/train" "${MODEL_ARGS[@]}" \
    --epochs 1 --batch-size 64 --checkpoint-dir "$WORK/ck" \
    --set train.ckpt_replica_dir="$WORK/ck_replica" \
    --set train.checkpoint_every=10 --set train.pred_dump=false \
    --set train.log_every=0 >/dev/null 2>"$WORK/serve_train.log"

stage() {  # atomic checkpoint shipping: payload under a temp name, one rename
    python - "$1" "$2" "$3" <<'EOF'
import os, shutil, sys
src, dst, step = sys.argv[1], sys.argv[2], sys.argv[3]
os.makedirs(dst, exist_ok=True)
tmp = os.path.join(dst, f".staging_{step}")
if os.path.exists(tmp):
    shutil.rmtree(tmp)
shutil.copytree(os.path.join(src, f"step_{step}"), tmp)
os.replace(tmp, os.path.join(dst, f"step_{step}"))
EOF
}
# the server starts on step 40, both tiers healthy
stage "$WORK/ck" "$WORK/serve_ck" 40
stage "$WORK/ck_replica" "$WORK/serve_replica" 40
# step 50 ships with a digest-POISONED primary copy; only the replica
# tier holds good bytes (staged before the primary so the watcher never
# sees the poisoned step without its fallback)
cp -r "$WORK/ck/step_50" "$WORK/poison_scratch"
mkdir -p "$WORK/poison"
mv "$WORK/poison_scratch" "$WORK/poison/step_50"
python tools/corrupt_ckpt.py --dir "$WORK/poison" --mode bitflip >/dev/null

python -m xflow_tpu serve --checkpoint-dir "$WORK/serve_ck" "${MODEL_ARGS[@]}" \
    --port 0 --window-ms 3 --max-batch 64 --poll-s 0.3 --no-mesh \
    --metrics-path "$RUN/serve_rank0.jsonl" \
    --set train.ckpt_replica_dir="$WORK/serve_replica" \
    --set serve.metrics_every_s=1 \
    >"$WORK/serve_ready.json" 2>"$WORK/serve.log" &
SERVE_PID=$!
for i in $(seq 1 240); do
    [ -s "$WORK/serve_ready.json" ] && break
    kill -0 "$SERVE_PID" 2>/dev/null || {
        echo "smoke_durable: server died during startup"
        cat "$WORK/serve.log"; exit 1; }
    sleep 0.5
done
[ -s "$WORK/serve_ready.json" ] || {
    echo "smoke_durable: server never became ready"
    cat "$WORK/serve.log"; exit 1; }
PORT=$(python -c "import json,sys; print(json.load(open(sys.argv[1]))['port'])" \
    "$WORK/serve_ready.json")
grep -q '"step": 40' "$WORK/serve_ready.json" || {
    echo "smoke_durable: server did not start at step 40"
    cat "$WORK/serve_ready.json"; exit 1; }

python tools/serve_bench.py --url "http://127.0.0.1:$PORT" \
    --data "$WORK/reqs-00000" --duration 8 --concurrency 4 \
    --rows-per-request 4 \
    >"$WORK/bench_report.json" 2>"$WORK/bench.log" &
BENCH_PID=$!
sleep 2.5
# ship step 50 mid-load: replica (good bytes) first, then the poisoned
# primary — the union watcher sees 50, the primary copy digest-fails,
# the replica loads, zero requests drop
stage "$WORK/ck_replica" "$WORK/serve_replica" 50
stage "$WORK/poison" "$WORK/serve_ck" 50
rc=0; wait "$BENCH_PID" || rc=$?
BENCH_PID=""
[ "$rc" -eq 0 ] || {
    echo "smoke_durable: loadgen saw failed requests during the replica reload"
    cat "$WORK/bench_report.json" "$WORK/serve.log"; exit 1; }
python - "$WORK/bench_report.json" <<'EOF'
import json, sys

rec = json.load(open(sys.argv[1]))
assert rec["errors"] == 0, rec
assert rec["steps"] == [40, 50], f"served steps {rec['steps']} != [40, 50]"
assert rec["gen_flips"] >= 1, f"no hot-reload generation flip: {rec}"
print(f"smoke_durable: replica hot reload OK (served steps {rec['steps']}, "
      f"{rec['requests']} requests, 0 dropped)")
EOF
grep -q "failed to load" "$WORK/serve.log" || {
    echo "smoke_durable: the poisoned primary never failed a load "
    cat "$WORK/serve.log"; exit 1; }
kill -9 "$SERVE_PID" 2>/dev/null || true
SERVE_PID=""

# ---- 4. telemetry gates over the ckpt streams -----------------------------
python tools/metrics_report.py "$RUN"/*.jsonl --check || {
    echo "smoke_durable: metrics_report --check failed"; exit 1; }
for f in "$WORK/bench_sync.jsonl" "$WORK/bench_async.jsonl"; do
    python tools/metrics_report.py "$f" --check || {
        echo "smoke_durable: metrics_report --check failed on $f"; exit 1; }
done
python tools/metrics_report.py "$WORK/bench_async.jsonl" --health \
    >"$WORK/health.txt"
grep -q "checkpoints (kind=ckpt" "$WORK/health.txt" || {
    echo "smoke_durable: --health has no checkpoint section"; exit 1; }

rm -rf "$ROOT/tools/__pycache__" "$ROOT/__pycache__"
echo "smoke_durable: OK"
