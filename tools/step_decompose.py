"""Decompose the FM/LR train step cost on the real chip.

Uses the bench.py harness (lax.scan over K pre-staged distinct batches,
host-read sync) with progressively larger slices of the step:
  fwd      — forward + loss only
  grad     — + backward (gradients materialized into the carry)
  step     — + optimizer update (the full train step)
The deltas attribute the step time to forward gather, backward scatter,
and dense optimizer update respectively.
"""

import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from xflow_tpu.config import Config, override
    from xflow_tpu.models import get_model
    from xflow_tpu.optim import get_optimizer
    from xflow_tpu.train.state import init_state
    from xflow_tpu.train.step import loss_fn, make_train_step

    K, B, F, LOG2 = 8, 65536, 32, 22
    for model_name in ("lr", "fm"):
        cfg = override(
            Config(),
            **{
                "model.name": model_name,
                "data.log2_slots": LOG2,
                "data.max_nnz": F,
                "data.batch_size": B,
            },
        )
        model, opt = get_model(model_name), get_optimizer("ftrl")
        state = init_state(model, opt, cfg)
        rng = np.random.default_rng(0)
        batches = {
            "slots": jnp.asarray(rng.integers(0, cfg.num_slots, (K, B, F)), jnp.int32),
            "fields": jnp.asarray(rng.integers(0, cfg.model.num_fields, (K, B, F)), jnp.int32),
            "mask": jnp.asarray((rng.random((K, B, F)) < 0.6).astype(np.float32)),
            "labels": jnp.asarray((rng.random((K, B)) < 0.4).astype(np.float32)),
            "row_mask": jnp.ones((K, B), jnp.float32),
        }

        def time_variant(fn, carry):
            @jax.jit
            def run(c, bs):
                return jax.lax.scan(fn, c, bs)

            c, out = run(carry, batches)
            _ = float(jax.tree.leaves(out)[0].ravel()[-1])
            best = float("inf")
            for _ in range(4):
                t0 = time.perf_counter()
                c, out = run(carry, batches)
                _ = float(jax.tree.leaves(out)[0].ravel()[-1])
                best = min(best, (time.perf_counter() - t0) / K)
            return best

        # fwd: tables fixed in carry, loss out
        def fwd(tables, batch):
            return tables, loss_fn(tables, batch, model, cfg)

        t_fwd = time_variant(fwd, state.tables)

        # grad: tables updated by -1e-9*grad so the scatter result is live
        def grad(tables, batch):
            loss, g = jax.value_and_grad(loss_fn)(tables, batch, model, cfg)
            new = jax.tree.map(lambda t, gg: t - 1e-9 * gg, tables, g)
            return new, loss

        t_grad = time_variant(grad, state.tables)

        step = make_train_step(model, opt, cfg, jit=False)

        def full(st, batch):
            st, m = step(st, batch)
            return st, m["loss"]

        t_full = time_variant(full, state)

        print(
            f"{model_name}: fwd={t_fwd*1e3:7.1f} ms  +bwd={t_grad*1e3:7.1f} ms "
            f"(bwd ~{(t_grad-t_fwd)*1e3:6.1f})  full={t_full*1e3:7.1f} ms "
            f"(opt ~{(t_full-t_grad)*1e3:6.1f})"
        )


if __name__ == "__main__":
    main()
