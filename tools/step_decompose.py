#!/usr/bin/env python3
"""Decompose the train-step cost on the real chip, machine-readably.

Uses the bench.py harness (lax.scan over K pre-staged distinct batches
— staging shared via `bench.stage_row_batches`, host-read sync) with
progressively larger slices of the step:

  fwd      — forward + loss only
  grad     — + backward (gradients materialized into the carry)
  step     — + optimizer update (the full train step)

The deltas attribute the step time to forward gather, backward scatter,
and dense optimizer update respectively. Output is one BENCH-shaped
JSON record per (model, slice) on stdout —

  {"metric": "decompose_fm_fwd_ms", "value": 52.2, "unit": "ms/step",
   "model": "fm", "slice": "fwd", ...}

— so a decomposition run lands in the same trajectory tooling as every
other datapoint (tools/perf_ledger.py folds explicit files in); the
human summary line per model goes to stderr. The full-step slice also
carries the CompileRecorder's compile time and cost analysis.

    python tools/step_decompose.py                     # lr + fm, bench shape
    python tools/step_decompose.py --models fm --smoke # tiny CPU shapes
    python tools/step_decompose.py --json out.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-slice (fwd/grad/step) train-step cost decomposition"
    )
    ap.add_argument("--models", default="lr,fm",
                    help="comma-separated model list (default lr,fm)")
    ap.add_argument("--scan-steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--nnz", type=int, default=32)
    ap.add_argument("--log2-slots", type=int, default=22)
    ap.add_argument("--repeats", type=int, default=4)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for CI (CPU-friendly)")
    ap.add_argument("--json", default="-", metavar="OUT",
                    help="where the JSON records go (default stdout)")
    args = ap.parse_args(argv)
    if args.smoke:
        args.batch, args.nnz, args.log2_slots = 1024, 8, 14
        args.scan_steps, args.repeats = 2, 2

    import jax
    import jax.numpy as jnp

    from bench import stage_row_batches
    from xflow_tpu.config import Config, override
    from xflow_tpu.models import get_model
    from xflow_tpu.optim import get_optimizer
    from xflow_tpu.telemetry import CompileRecorder
    from xflow_tpu.train.state import init_state
    from xflow_tpu.train.step import loss_fn, make_train_step

    K, B, F, LOG2 = args.scan_steps, args.batch, args.nnz, args.log2_slots
    out_f = sys.stdout if args.json == "-" else open(args.json, "w")
    records = []

    for model_name in [m for m in args.models.split(",") if m]:
        cfg = override(
            Config(),
            **{
                "model.name": model_name,
                "data.log2_slots": LOG2,
                "data.max_nnz": F,
                "data.batch_size": B,
            },
        )
        model, opt = get_model(model_name), get_optimizer("ftrl")
        state = init_state(model, opt, cfg)
        rng = np.random.default_rng(0)
        # the SAME staging the bench harness uses (bench.py) — one
        # distribution, one harness, no drift between the two tools
        batches = {
            k: jnp.asarray(v)
            for k, v in stage_row_batches(
                rng, cfg.num_slots, cfg.model.num_fields, K, B, F
            ).items()
        }
        crec = CompileRecorder()

        def time_variant(tag, fn, carry):
            run = crec.wrap(f"decompose.{model_name}.{tag}", jax.jit(
                lambda c, bs: jax.lax.scan(fn, c, bs)
            ))
            c, out = run(carry, batches)
            _ = float(jax.tree.leaves(out)[0].ravel()[-1])
            best = float("inf")
            for _ in range(args.repeats):
                t0 = time.perf_counter()
                c, out = run(carry, batches)
                _ = float(jax.tree.leaves(out)[0].ravel()[-1])
                best = min(best, (time.perf_counter() - t0) / K)
            return best

        # fwd: tables fixed in carry, loss out
        def fwd(tables, batch):
            return tables, loss_fn(tables, batch, model, cfg)

        t_fwd = time_variant("fwd", fwd, state.tables)

        # grad: tables updated by -1e-9*grad so the scatter result is live
        def grad(tables, batch):
            loss, g = jax.value_and_grad(loss_fn)(tables, batch, model, cfg)
            new = jax.tree.map(lambda t, gg: t - 1e-9 * gg, tables, g)
            return new, loss

        t_grad = time_variant("grad", grad, state.tables)

        step = make_train_step(model, opt, cfg, jit=False)

        def full(st, batch):
            st, m = step(st, batch)
            return st, m["loss"]

        t_full = time_variant("step", full, state)

        ts = round(time.time(), 3)
        shape = {"batch": B, "nnz": F, "log2_slots": LOG2, "scan_steps": K}
        for tag, best in (("fwd", t_fwd), ("grad", t_grad), ("step", t_full)):
            rec = {
                "metric": f"decompose_{model_name}_{tag}_ms",
                "value": round(best * 1e3, 3),
                "unit": "ms/step",
                "model": model_name,
                "slice": tag,
                **shape,
                "ts": ts,
            }
            if tag == "step":
                info = crec.latest(f"decompose.{model_name}.step")
                if info and info.get("flops"):
                    rec["compile_time_s"] = round(info["compile_time_s"], 3)
                    rec["flops_per_example"] = round(info["flops"] / (K * B), 2)
                    if info.get("bytes_accessed"):
                        rec["bytes_per_example"] = round(
                            info["bytes_accessed"] / (K * B), 2
                        )
            records.append(rec)
            print(json.dumps(rec), file=out_f)
        out_f.flush()
        print(
            f"{model_name}: fwd={t_fwd*1e3:7.1f} ms  +bwd={t_grad*1e3:7.1f} ms "
            f"(bwd ~{(t_grad-t_fwd)*1e3:6.1f})  full={t_full*1e3:7.1f} ms "
            f"(opt ~{(t_full-t_grad)*1e3:6.1f})",
            file=sys.stderr,
        )
    if out_f is not sys.stdout:
        out_f.close()
    return 0 if records else 1


if __name__ == "__main__":
    sys.exit(main())
