#!/usr/bin/env python3
"""The unified perf ledger: one trajectory report over every bench
series at the repo root (docs/PERF.md "Bench trajectory").

The trajectory grew organically into four hand-named JSON families —
`BENCH_rNN.json` (per-round bench/smoke datapoints, some wrapped in a
driver envelope with the record under "parsed"), `BENCH_SCALE.json`
(the 10M-row end-to-end scale run), `MULTICHIP_rNN.json` (the
multichip dryrun verdicts), and `BENCH_SERVE.json` (the serving
loadgen) — which nothing consolidated or gated. This tool is the one
reader:

    python tools/perf_ledger.py                      # markdown to stdout
    python tools/perf_ledger.py --json ledger.json   # machine-readable
    python tools/perf_ledger.py --regress            # gate: exit 3 on
                                                     # cross-round regression

- **Consolidation**: every file normalizes into ledger entries
  `{series, round, metric, value, unit, ...}`; the markdown report
  renders the bench trajectory per metric, the multichip verdict
  trail, the scale run, and the serving datapoint in one place.
- **Regression gating** (`--regress`): within each (series, metric)
  group the NEWEST round's value must not fall more than
  `--regress-tol` (default 0.2 — the same tolerance
  metrics_report --regress uses) below the best previous round
  (latency-shaped `*_ms` metrics gate in the opposite direction); a
  multichip round flipping ok -> failed is a regression outright.
  Exit 3 with one line per failure. Rounds measured on different
  machines (the CPU smoke datapoints) are gated within their OWN
  metric name (`telemetry_examples_per_sec`), never against
  chip-scale numbers — metric names partition the comparison.
- **Roofline extrapolation**: the newest device-bench record
  extrapolates ×64 chips against the SNIPPETS.md Criteo-1TB v5e-64
  target (>=50M examples/sec => ~781k ex/s/chip), and when the record
  carries the CompileRecorder's cost stamps (`bytes_per_example`,
  bench.py), the per-chip target converts into "% of HBM bandwidth"
  (docs/PERF.md "Measured roofline").
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

POD_TARGET = 50_000_000  # SNIPPETS.md Criteo-1TB v5e-64 examples/sec
POD_CHIPS = 64
PER_CHIP_TARGET = POD_TARGET / POD_CHIPS

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


def _load(path: str):
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        # JSONL (tools/step_decompose.py --json emits one record per
        # slice): a list of records, each normalized on its own
        return [json.loads(line) for line in text.splitlines() if line.strip()]


def _round_of(path: str):
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def _lower_is_better(metric, unit) -> bool:
    """Latency-shaped metrics (step_decompose's ms/step slices, serve
    p50/p99, the lab's ns/element cells) improve DOWNWARD — 'best' and
    the regression direction flip relative to throughput. `_ratio`
    metrics (the pipeline host-gap ratio) are gap-shaped: a round that
    climbs back toward text-path ratios is the regression the
    packed-shard-cache gate exists to catch. `fresh_*` metrics
    (BENCH_FRESH.json, tools/freshness_report.py) are delay-shaped —
    seconds from ingested row to served prediction — and gate downward
    too: a round where data gets STALER is the regression."""
    return (
        str(metric).endswith(("_ms", "_ns", "_ns_per_element", "_ratio"))
        or str(metric).startswith("fresh_")
        or str(unit).startswith(("ms", "ns"))
    )


def normalize_bench(path: str, data) -> list[dict]:
    """One BENCH_rNN.json -> ledger entries. Two on-disk shapes: the
    driver envelope ({"parsed": {record}}) and the bare record."""
    rec = data.get("parsed") if isinstance(data, dict) and "parsed" in data else data
    if not isinstance(rec, dict) or "metric" not in rec:
        return []
    rnd = _round_of(path)
    if rnd is None and _finite(rec.get("round")):
        # records without a round-numbered filename (BENCH_PIPELINE.json,
        # pipeline_attrib --round) may stamp the round themselves
        rnd = int(rec["round"])
    entry = {
        "series": "bench",
        "round": rnd,
        "path": os.path.basename(path),
        "metric": rec["metric"],
        "value": rec.get("value"),
        "unit": rec.get("unit", ""),
        "vs_baseline": rec.get("vs_baseline"),
        "headline": True,  # the record's own metric field
    }
    for key in (
        "auc", "steps", "examples", "elapsed_s", "compile_time_s",
        "flops_per_example", "bytes_per_example", "ranks",
    ):
        if _finite(rec.get(key)):
            entry[key] = rec[key]
    out = [entry]
    # companion metrics ride in the same record (fm_examples_per_sec,
    # zipf_*, *_s24_*, e2e_*..., and the pipeline record's text-path
    # comparison leg) — each becomes its own gated group
    for key, v in rec.items():
        if key.endswith("_examples_per_sec") and key != rec["metric"] and _finite(v):
            out.append({
                "series": "bench",
                "round": rnd,
                "path": os.path.basename(path),
                "metric": key,
                "value": v,
                "unit": "examples/sec",
                "vs_baseline": rec.get(key.replace("_examples_per_sec", "_vs_baseline")),
            })
    if str(rec["metric"]).startswith("pipeline_"):
        # the host-gap record's own companion groups (BENCH_PIPELINE*,
        # tools/pipeline_attrib.py): the gap ratio gates DOWNWARD (a
        # round regressing back toward text-path ratios exits 3 —
        # `_lower_is_better` keys on the `_ratio` suffix), and the
        # cache-vs-text speedup gates upward like any throughput group
        if _finite(rec.get("host_gap_ratio")):
            out.append({
                "series": "bench",
                "round": rnd,
                "path": os.path.basename(path),
                "metric": "pipeline_host_gap_ratio",
                "value": rec["host_gap_ratio"],
                "unit": "x",
            })
        for key, v in rec.items():
            if key.startswith("speedup_vs_") and _finite(v):
                out.append({
                    "series": "bench",
                    "round": rnd,
                    "path": os.path.basename(path),
                    "metric": f"pipeline_{key}",
                    "value": v,
                    "unit": "x",
                })
    return out


def normalize_multichip(path: str, data) -> list[dict]:
    out = [{
        "series": "multichip",
        "round": _round_of(path),
        "path": os.path.basename(path),
        "metric": "multichip_ok",
        "value": 1.0 if data.get("ok") else 0.0,
        "unit": "bool",
        "n_devices": data.get("n_devices"),
        "skipped": bool(data.get("skipped")),
    }] if isinstance(data, dict) else []
    # multi-slice records (MULTICHIP_r16+, tools/smoke_multislice.sh)
    # also carry the measured aggregate: the N-slice throughput and its
    # speedup over one slice. The `ok` flag above already folds the
    # >= 1.8x acceptance gate (the script computes it); these entries
    # ride the generic higher-is-better tolerance gate across rounds.
    if isinstance(data, dict) and not data.get("skipped"):
        for key, unit in (
            ("speedup", "x"),
            ("agg_examples_per_sec", "examples/sec"),
        ):
            if _finite(data.get(key)):
                out.append({
                    "series": "multichip",
                    "round": _round_of(path),
                    "path": os.path.basename(path),
                    "metric": f"multislice_{key}",
                    "value": float(data[key]),
                    "unit": unit,
                    "slices": data.get("slices"),
                    "skipped": False,
                })
    return out


def normalize_scale(path: str, data) -> list[dict]:
    if not isinstance(data, dict) or "models" not in data:
        return []
    out = []
    for model, rec in sorted(data["models"].items()):
        if not isinstance(rec, dict):
            continue
        entry = {
            "series": "scale",
            "round": None,
            "path": os.path.basename(path),
            "metric": f"e2e_{model}_examples_per_sec_scale",
            "value": rec.get("examples_per_sec_e2e"),
            "unit": "examples/sec",
        }
        for key in ("test_auc", "steps", "examples", "batch_size"):
            if _finite(rec.get(key)):
                entry[key] = rec[key]
        out.append(entry)
    return out


def normalize_lab(path: str, data) -> list[dict]:
    """One BENCH_LAB*.json (xflow_tpu/tools/bench_lab.py --suite core,
    docs/OBSERVABILITY.md "Sparse-primitive lab") -> ledger entries:
    the headline gather-latency cell plus one per-cell group
    (`lab_<op>_s<table_log2>_n<nnz_log2>_<dtype>`, ns/element — the
    latency direction, gated downward). The round comes from the
    record's own `round` stamp (operator-chosen) or the filename."""
    if not isinstance(data, dict) or not isinstance(data.get("cells"), list):
        return []
    rnd = data.get("round") if _finite(data.get("round")) else _round_of(path)
    rnd = int(rnd) if rnd is not None else None
    out: list[dict] = []
    if data.get("metric") and _finite(data.get("value")):
        entry = {
            "series": "lab",
            "round": rnd,
            "path": os.path.basename(path),
            "metric": data["metric"],
            "value": data["value"],
            "unit": data.get("unit", "ns/element"),
            "headline": True,
        }
        if isinstance(data.get("device"), str):
            entry["device"] = data["device"]
        if isinstance(data.get("headline_cell"), str):
            entry["cell"] = data["headline_cell"]
        out.append(entry)
    for c in data["cells"]:
        if not isinstance(c, dict) or not _finite(c.get("ns_per_element")):
            continue
        entry = {
            "series": "lab",
            "round": rnd,
            "path": os.path.basename(path),
            "metric": (
                f"lab_{c.get('op')}_s{c.get('table_log2')}"
                f"_n{c.get('nnz_log2')}_{c.get('dtype')}"
            ),
            "value": c["ns_per_element"],
            "unit": "ns/element",
        }
        if isinstance(data.get("device"), str):
            # cells inherit the record's device stamp: the roofline
            # citation's CPU-vs-chip preference needs it on every entry
            entry["device"] = data["device"]
        for key in ("time_ms", "flops", "bytes_accessed", "achieved_gbps",
                    "compile_time_s", "row_width"):
            if _finite(c.get(key)):
                entry[key] = c[key]
        out.append(entry)
    return out


def normalize_serve(path: str, data) -> list[dict]:
    if not isinstance(data, dict) or "metric" not in data:
        return []
    rnd = _round_of(path)
    if rnd is None and _finite(data.get("round")):
        # serve records without a round-numbered filename (the original
        # BENCH_SERVE.json) may stamp the round themselves (serve_bench
        # --round), joining the cross-round gate like any _rNN file
        rnd = int(data["round"])
    entry = {
        "series": "serve",
        "round": rnd,
        "path": os.path.basename(path),
        "metric": data["metric"],
        "value": data.get("value"),
        "unit": data.get("unit", ""),
    }
    for key in ("p50_ms", "p99_ms", "requests", "rows", "errors", "gen_flips",
                "trace_sample_rate", "trace_overhead_pct", "qps_untraced",
                "qps_traced", "slo_ms", "slo_attainment_pct"):
        if _finite(data.get(key)):
            entry[key] = data[key]
    if isinstance(data.get("traced"), bool):
        entry["traced"] = data["traced"]
    out = [entry]
    # the latency leg gates as its OWN group, downward (the _ms suffix
    # flips `_lower_is_better`): a round that doubles QPS by letting the
    # tail blow out is a regression, not a win — p99-at-SLO and QPS gate
    # together. Named off the record's metric so BENCH_SERVE /
    # BENCH_SERVE_FLEET / BENCH_TRACE rounds never cross-gate.
    if _finite(data.get("p99_ms")):
        out.append({
            "series": "serve",
            "round": rnd,
            "path": os.path.basename(path),
            "metric": f"{data['metric']}_p99_ms",
            "value": data["p99_ms"],
            "unit": "ms",
        })
    if _finite(data.get("slo_attainment_pct")):
        out.append({
            "series": "serve",
            "round": rnd,
            "path": os.path.basename(path),
            "metric": f"{data['metric']}_slo_attainment_pct",
            "value": data["slo_attainment_pct"],
            "unit": "%",
        })
    return out


def normalize_fresh(path: str, data) -> list[dict]:
    """One BENCH_FRESH*.json (tools/freshness_report.py --bench-json,
    docs/SERVING.md "Freshness") -> ledger entries: the headline
    end-to-end `fresh_delta_s` (ingested row -> first served
    prediction, fleet max) plus one group per Δ-decomposition leg
    (`fresh_<leg>_s`). Every group is delay-shaped: `_lower_is_better`
    keys on the `fresh_` prefix, so a round where data gets staler
    exits 3 under --regress."""
    if not isinstance(data, dict) or "metric" not in data:
        return []
    rnd = _round_of(path)
    if rnd is None and _finite(data.get("round")):
        rnd = int(data["round"])
    entry = {
        "series": "fresh",
        "round": rnd,
        "path": os.path.basename(path),
        "metric": data["metric"],
        "value": data.get("value"),
        "unit": data.get("unit", "s"),
        "headline": True,
    }
    for key in ("publications", "replicas", "traces", "segments"):
        if _finite(data.get(key)):
            entry[key] = data[key]
    out = [entry]
    for key, v in data.items():
        if key == data["metric"]:
            continue
        if key.startswith("fresh_") and key.endswith("_s") and _finite(v):
            out.append({
                "series": "fresh",
                "round": rnd,
                "path": os.path.basename(path),
                "metric": key,
                "value": v,
                "unit": "s",
            })
    return out


def collect(root: str, extra: list[str]) -> list[dict]:
    """Every ledger entry under `root` (+ explicit extra files), sorted
    by (series, metric, round)."""
    entries: list[dict] = []
    seen = set()

    def add(path: str):
        ap = os.path.abspath(path)
        if ap in seen or not os.path.exists(ap):
            return
        seen.add(ap)
        name = os.path.basename(path)
        try:
            data = _load(path)
        except (OSError, json.JSONDecodeError) as e:
            print(f"perf_ledger: warning: skipping {path!r}: {e}", file=sys.stderr)
            return
        if isinstance(data, list):
            for item in data:
                entries.extend(normalize_bench(path, item))
        elif name.startswith("MULTICHIP"):
            entries.extend(normalize_multichip(path, data))
        elif name == "BENCH_SCALE.json" or "SCALE" in name:
            entries.extend(normalize_scale(path, data))
        elif name.startswith("BENCH_LAB"):
            # the sparse-primitive lab matrix (bench_lab --suite core):
            # per-cell ns/element groups, gated downward
            entries.extend(normalize_lab(path, data))
        elif name.startswith("BENCH_FRESH"):
            # the streaming-freshness Δ record (freshness_report): the
            # end-to-end delta and its decomposition legs, gated downward
            entries.extend(normalize_fresh(path, data))
        elif name.startswith(("BENCH_SERVE", "BENCH_TRACE")):
            # BENCH_TRACE.json is the serve_bench record measured with
            # request tracing on (tools/smoke_trace.sh): same serve_qps
            # shape, plus the traced/trace_sample_rate/overhead stamps
            entries.extend(normalize_serve(path, data))
        else:
            entries.extend(normalize_bench(path, data))

    for pattern in ("BENCH_r*.json", "BENCH_SCALE*.json", "MULTICHIP_r*.json",
                    "BENCH_SERVE*.json", "BENCH_TRACE*.json",
                    "BENCH_LAB*.json", "BENCH_PIPELINE*.json",
                    "BENCH_FRESH*.json", "BENCH_CKPT*.json"):
        for path in sorted(glob.glob(os.path.join(root, pattern))):
            add(path)
    for path in extra:
        add(path)
    entries.sort(key=lambda e: (e["series"], str(e["metric"]),
                                e["round"] if e["round"] is not None else -1))
    return entries


def groups_of(entries: list[dict]) -> dict:
    """{(series, metric): [entries in round order]}."""
    out: dict = {}
    for e in entries:
        out.setdefault((e["series"], e["metric"]), []).append(e)
    return out


# ------------------------------------------------------------------ gating


def check_regressions(
    entries: list[dict], tol: float, metrics_re: str = ""
) -> list[str]:
    """Failures ([] = pass): within each (series, metric) group holding
    >= 2 rounds, the newest round's value must be >= (1 - tol) x the
    best previous round; a multichip ok -> failed flip (not skipped)
    fails outright. `metrics_re` scopes the gate to matching metric
    names (the CPU smoke datapoints are machine-local — an operator
    gates the series measured on ONE rig, not apples against oranges)."""
    problems: list[str] = []
    pat = re.compile(metrics_re) if metrics_re else None
    for (series, metric), group in sorted(groups_of(entries).items(), key=str):
        if pat is not None and not pat.search(str(metric)):
            continue
        rounds = [e for e in group if e["round"] is not None and _finite(e["value"])]
        if len(rounds) < 2:
            continue
        newest = rounds[-1]
        prev = rounds[:-1]
        if series == "multichip" and metric == "multichip_ok":
            if newest.get("skipped"):
                continue
            if newest["value"] < 1.0 and any(e["value"] >= 1.0 for e in prev):
                problems.append(
                    f"multichip round {newest['round']} failed "
                    f"({newest['path']}) after passing rounds "
                    f"{[e['round'] for e in prev if e['value'] >= 1.0]}"
                )
            continue
        if _lower_is_better(metric, newest.get("unit", "")):
            best_prev = min(e["value"] for e in prev)
            if best_prev > 0 and newest["value"] > (1.0 + tol) * best_prev:
                problems.append(
                    f"{metric}: round {newest['round']} = {newest['value']:.1f} "
                    f"> (1+{tol}) x best previous {best_prev:.1f} "
                    f"({newest['path']})"
                )
        else:
            best_prev = max(e["value"] for e in prev)
            if best_prev > 0 and newest["value"] < (1.0 - tol) * best_prev:
                problems.append(
                    f"{metric}: round {newest['round']} = {newest['value']:.1f} "
                    f"< (1-{tol}) x best previous {best_prev:.1f} "
                    f"({newest['path']})"
                )
    return problems


# ---------------------------------------------------------------- roofline


def roofline(entries: list[dict], hbm_gbps: float) -> dict:
    """The extrapolation block: newest device-bench headline x 64 chips
    vs the pod target, plus the HBM-bandwidth conversion when the
    record carries bytes_per_example (bench.py's CompileRecorder
    stamp)."""
    # device-bench headline records (the record's own metric field),
    # newest round; telemetry_* smoke datapoints are CPU numbers with
    # no roofline meaning and stay out — and so do the pipeline_*
    # host-gap records (BENCH_PIPELINE.json): their e2e rate is the
    # HOST-limited number, extrapolating it x64 chips would silently
    # replace the device headline with the gap it measures
    heads = [
        e for e in entries
        if e["series"] == "bench" and e["round"] is not None
        and e.get("headline") and _finite(e["value"])
        and str(e["metric"]).endswith("_examples_per_sec")
        and not str(e["metric"]).startswith(("telemetry", "pipeline"))
    ]
    if not heads:
        return {}
    newest = max(heads, key=lambda e: e["round"])
    out = {
        "metric": newest["metric"],
        "round": newest["round"],
        "per_chip_examples_per_sec": newest["value"],
        "pod_extrapolated_examples_per_sec": newest["value"] * POD_CHIPS,
        "pod_target_examples_per_sec": POD_TARGET,
        "pct_of_pod_target": round(
            100.0 * newest["value"] * POD_CHIPS / POD_TARGET, 1
        ),
        "per_chip_target_examples_per_sec": PER_CHIP_TARGET,
        "vs_per_chip_target": newest.get("vs_baseline"),
    }
    bpe = newest.get("bytes_per_example")
    if _finite(bpe) and hbm_gbps > 0:
        # the measured-roofline conversion (docs/PERF.md): examples/sec
        # x modeled bytes/example = HBM bytes/sec the program must move
        out["bytes_per_example"] = bpe
        out["hbm_gbps_assumed"] = hbm_gbps
        out["target_pct_of_hbm_bw"] = round(
            100.0 * PER_CHIP_TARGET * bpe / (hbm_gbps * 1e9), 1
        )
        out["achieved_pct_of_hbm_bw"] = round(
            100.0 * newest["value"] * bpe / (hbm_gbps * 1e9), 1
        )
    # the latency citation: the extrapolation's "why the gap" line now
    # cites the lab's MEASURED gather cell (BENCH_LAB.json) instead of
    # docs/PERF.md's hand-derived ~11 ns/element figure
    gathers = [
        e for e in entries
        if e["series"] == "lab" and _finite(e["value"])
        and "gather" in str(e["metric"])
        and str(e.get("unit", "")).startswith("ns")
    ]
    if gathers:
        # prefer a chip-measured cell over a CPU smoke datapoint: the
        # citation replaces docs/PERF.md's hand-derived TPU figure, and
        # a machine-local CPU number must never outrank a chip number
        # just because it stamped a round
        pick = max(
            gathers,
            key=lambda e: (
                "cpu" not in str(e.get("device", "")).lower(),
                bool(e.get("headline")),
                e["round"] if e["round"] is not None else -1,
            ),
        )
        out["measured_gather_ns_per_element"] = pick["value"]
        out["gather_cell"] = str(pick.get("cell") or pick["metric"])
        if isinstance(pick.get("device"), str):
            out["gather_device"] = pick["device"]
            out["gather_is_cpu"] = "cpu" in pick["device"].lower()
    return out


# ---------------------------------------------------------------- rendering


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if not math.isfinite(v):
            return "-"
        if abs(v) >= 10000:
            return f"{v:,.0f}"
        return f"{v:.4g}"
    return str(v)


def render_markdown(entries: list[dict], hbm_gbps: float) -> str:
    lines = ["# Perf ledger", ""]
    bench = groups_of([e for e in entries if e["series"] == "bench"])
    if bench:
        lines += ["## Bench trajectory (`BENCH_r*.json`)", "",
                  "| metric | rounds | first | best | newest | vs target/chip |",
                  "|---|---|---|---|---|---|"]
        for (_, metric), group in sorted(bench.items(), key=str):
            vals = [e for e in group if _finite(e["value"])]
            if not vals:
                continue
            rounds = [e["round"] for e in vals if e["round"] is not None]
            pick = min if _lower_is_better(metric, vals[-1].get("unit", "")) else max
            best = pick(vals, key=lambda e: e["value"])
            newest = vals[-1]
            lines.append(
                f"| {metric} | {_fmt(min(rounds)) if rounds else '-'}→"
                f"{_fmt(max(rounds)) if rounds else '-'} | {_fmt(vals[0]['value'])} "
                f"| {_fmt(best['value'])} (r{_fmt(best['round'])}) "
                f"| {_fmt(newest['value'])} | {_fmt(newest.get('vs_baseline'))} |"
            )
        lines.append("")
    multi = [e for e in entries if e["series"] == "multichip"
             and e["metric"] == "multichip_ok"]
    if multi:
        lines += ["## Multichip dryrun (`MULTICHIP_r*.json`)", "",
                  "| round | devices | verdict |", "|---|---|---|"]
        for e in sorted(multi, key=lambda e: e["round"] or -1):
            verdict = ("skipped" if e.get("skipped")
                       else "ok" if e["value"] else "FAILED")
            lines.append(f"| r{_fmt(e['round'])} | {_fmt(e.get('n_devices'))} "
                         f"| {verdict} |")
        lines.append("")
        # multi-slice rounds publish measured numbers too — print the
        # speedup trail under the verdict table
        speed = [e for e in entries if e["series"] == "multichip"
                 and e["metric"] == "multislice_speedup"
                 and _finite(e["value"])]
        for e in sorted(speed, key=lambda e: e["round"] or -1):
            agg = next(
                (a["value"] for a in entries
                 if a["series"] == "multichip"
                 and a["metric"] == "multislice_agg_examples_per_sec"
                 and a["round"] == e["round"] and _finite(a["value"])),
                None,
            )
            agg_txt = f", aggregate {agg:.0f} examples/sec" if agg else ""
            lines.append(
                f"multi-slice r{_fmt(e['round'])}: "
                f"{_fmt(e.get('slices'))} slice(s) at {e['value']:.2f}x "
                f"one slice{agg_txt}"
            )
        if speed:
            lines.append("")
    lab = groups_of([e for e in entries if e["series"] == "lab"])
    if lab:
        lines += ["## Sparse-primitive lab (`BENCH_LAB*.json`)", "",
                  "| cell | rounds | first | best | newest | GB/s |",
                  "|---|---|---|---|---|---|"]
        for (_, metric), group in sorted(lab.items(), key=str):
            vals = [e for e in group if _finite(e["value"])]
            if not vals:
                continue
            rounds = [e["round"] for e in vals if e["round"] is not None]
            best = min(vals, key=lambda e: e["value"])  # ns: lower is better
            newest = vals[-1]
            lines.append(
                f"| {metric} | {_fmt(min(rounds)) if rounds else '-'}→"
                f"{_fmt(max(rounds)) if rounds else '-'} "
                f"| {_fmt(vals[0]['value'])} "
                f"| {_fmt(best['value'])} (r{_fmt(best['round'])}) "
                f"| {_fmt(newest['value'])} "
                f"| {_fmt(newest.get('achieved_gbps'))} |"
            )
        lines.append("")
    pipe = groups_of([
        e for e in entries
        if e["series"] == "bench" and (
            str(e["metric"]).startswith("pipeline_")
            # the comparison legs pipeline_attrib --compare folds in,
            # whatever --compare-label named them (text_e2e_..., native_
            # e2e_..., the device-bound companion)
            or str(e["metric"]).endswith("_e2e_examples_per_sec")
            or str(e["metric"]) == "device_bound_examples_per_sec"
        )
    ])
    if pipe:
        # the host-gap trajectory in one place (BENCH_PIPELINE*,
        # docs/PERF.md "Host data plane"): e2e vs device-bound vs the
        # text-path comparison leg, ratio/speedup groups included —
        # the bench table above already gates these, this section is
        # the text-vs-cache story read top to bottom
        lines += ["## Input pipeline (`BENCH_PIPELINE*.json`, host gap)", "",
                  "| metric | rounds | first | newest |", "|---|---|---|---|"]
        for (_, metric), group in sorted(pipe.items(), key=str):
            vals = [e for e in group if _finite(e["value"])]
            if not vals:
                continue
            rounds = [e["round"] for e in vals if e["round"] is not None]
            lines.append(
                f"| {metric} | {_fmt(min(rounds)) if rounds else '-'}→"
                f"{_fmt(max(rounds)) if rounds else '-'} "
                f"| {_fmt(vals[0]['value'])} | {_fmt(vals[-1]['value'])} |"
            )
        lines.append("")
    scale = [e for e in entries if e["series"] == "scale"]
    if scale:
        lines += ["## Scale run (`BENCH_SCALE.json`, end-to-end)", "",
                  "| model | e2e ex/s | test AUC |", "|---|---|---|"]
        for e in scale:
            model = str(e["metric"]).replace("e2e_", "").replace(
                "_examples_per_sec_scale", "")
            lines.append(f"| {model} | {_fmt(e['value'])} "
                         f"| {_fmt(e.get('test_auc'))} |")
        lines.append("")
    serve = [e for e in entries if e["series"] == "serve"]
    if serve:
        # the source column keys the rows apart: BENCH_SERVE (solo),
        # BENCH_SERVE_FLEET (router), BENCH_TRACE (tracing on — its
        # overhead column is the request-tracing cost trajectory,
        # tools/smoke_trace.sh)
        lines += ["## Serving (`BENCH_SERVE*.json` / `BENCH_TRACE*.json`)", "",
                  "| source | metric | value | p50 ms | p99 ms | trace overhead |",
                  "|---|---|---|---|---|---|"]
        for e in serve:
            over = e.get("trace_overhead_pct")
            lines.append(f"| {e['path']} | {e['metric']} | {_fmt(e['value'])} "
                         f"| {_fmt(e.get('p50_ms'))} | {_fmt(e.get('p99_ms'))} "
                         f"| {_fmt(over) + '%' if over is not None else '-'} |")
        lines.append("")
    fresh = groups_of([e for e in entries if e["series"] == "fresh"])
    if fresh:
        # the Δ decomposition read top to bottom: the headline
        # end-to-end delta, then each leg of the stream -> train ->
        # publish -> serve loop. Lower is fresher; the bench gate above
        # already enforces the direction.
        lines += ["## Freshness (`BENCH_FRESH*.json`, ingested row → "
                  "served prediction)", "",
                  "| metric | rounds | first | best | newest |",
                  "|---|---|---|---|---|"]
        for (_, metric), group in sorted(fresh.items(), key=str):
            vals = [e for e in group if _finite(e["value"])]
            if not vals:
                continue
            rounds = [e["round"] for e in vals if e["round"] is not None]
            best = min(vals, key=lambda e: e["value"])  # s: lower = fresher
            lines.append(
                f"| {metric} | {_fmt(min(rounds)) if rounds else '-'}→"
                f"{_fmt(max(rounds)) if rounds else '-'} "
                f"| {_fmt(vals[0]['value'])} "
                f"| {_fmt(best['value'])} (r{_fmt(best['round'])}) "
                f"| {_fmt(vals[-1]['value'])} |"
            )
        lines.append("")
    roof = roofline(entries, hbm_gbps)
    if roof:
        lines += ["## Roofline extrapolation", ""]
        lines.append(
            f"- newest device headline: `{roof['metric']}` r{roof['round']} = "
            f"{_fmt(roof['per_chip_examples_per_sec'])} ex/s/chip "
            f"({_fmt(roof.get('vs_per_chip_target'))}x the "
            f"{_fmt(PER_CHIP_TARGET)} ex/s/chip pod share)"
        )
        lines.append(
            f"- x{POD_CHIPS} chips => "
            f"{_fmt(roof['pod_extrapolated_examples_per_sec'])} ex/s = "
            f"{roof['pct_of_pod_target']}% of the {_fmt(POD_TARGET)} ex/s "
            "pod target (assumes perfect scale-out; the multichip table "
            "above is the composition evidence, not this line)"
        )
        if "target_pct_of_hbm_bw" in roof:
            lines.append(
                f"- measured roofline: {_fmt(roof['bytes_per_example'])} "
                f"modeled bytes/example => the per-chip target is "
                f"{roof['target_pct_of_hbm_bw']}% of {_fmt(hbm_gbps)} GB/s "
                f"HBM; this chip achieves {roof['achieved_pct_of_hbm_bw']}%"
            )
        if "measured_gather_ns_per_element" in roof:
            # the trailing claim is honest about WHERE the cell was
            # measured: a CPU smoke cell tracks the lab's health, only
            # a chip cell is the latency wall the kernel arc must beat
            tail = (
                " — machine-local CPU datapoint; rerun the lab on a "
                "chip to refresh the latency wall"
                if roof.get("gather_is_cpu")
                else " — the latency wall the fused-kernel arc must beat"
            )
            lines.append(
                f"- measured gather random-access latency: "
                f"{_fmt(roof['measured_gather_ns_per_element'])} ns/element "
                f"(`{roof['gather_cell']}`, BENCH_LAB"
                + (f", {roof['gather_device']}" if "gather_device" in roof
                   else "")
                + ")" + tail
            )
        lines.append("")
    if len(lines) <= 2:
        lines.append("_no ledger entries found_")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="consolidate + gate the BENCH_*/MULTICHIP_*/BENCH_SERVE "
        "perf trajectory"
    )
    ap.add_argument("files", nargs="*", help="extra record files to fold in")
    ap.add_argument("--root", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), help="directory holding the series files "
        "(default: the repo root)")
    ap.add_argument("--json", default="", metavar="OUT",
                    help="write the normalized ledger JSON ('-' = stdout)")
    ap.add_argument("--markdown", default="-", metavar="OUT",
                    help="write the markdown report (default stdout; '' = off)")
    ap.add_argument("--regress", action="store_true",
                    help="gate: exit 3 when any metric's newest round "
                         "regressed beyond --regress-tol")
    ap.add_argument("--regress-tol", type=float, default=0.2,
                    help="allowed fractional drop vs the best previous round "
                         "(default 0.2, matching metrics_report --regress)")
    ap.add_argument("--metrics", default="", metavar="REGEX",
                    help="scope --regress to metric names matching this "
                         "regex (default: every group)")
    ap.add_argument("--hbm-gbps", type=float, default=819.0,
                    help="HBM bandwidth for the roofline conversion "
                         "(default 819 = v5e spec)")
    args = ap.parse_args(argv)

    entries = collect(args.root, args.files)
    if not entries:
        print("perf_ledger: no series files found", file=sys.stderr)
        return 2
    if args.markdown:
        md = render_markdown(entries, args.hbm_gbps)
        if args.markdown == "-":
            print(md)
        else:
            with open(args.markdown, "w") as f:
                f.write(md + "\n")
    if args.json:
        payload = json.dumps({
            "entries": entries,
            "roofline": roofline(entries, args.hbm_gbps),
        }, indent=1)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w") as f:
                f.write(payload + "\n")
    if args.regress:
        problems = check_regressions(entries, args.regress_tol, args.metrics)
        if problems:
            for p in problems:
                print(f"perf_ledger: REGRESSION: {p}", file=sys.stderr)
            return 3
        print(f"perf_ledger: no regression across "
              f"{len(groups_of(entries))} metric group(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
