#!/usr/bin/env bash
# Topology-elasticity + checkpoint-integrity smoke gate
# (docs/ROBUSTNESS.md "Host lost" / "Silent shard corruption"):
#
# 1. SILENT-CORRUPTION DIGEST DRILL (always runs): a 1-rank run is
#    SIGKILLed at step 30 (checkpoints committed at 10/20/30), the
#    step-30 checkpoint is bit-flipped INSIDE an array payload with the
#    container rewritten (corrupt_ckpt --mode bitflip — every zip-level
#    check still passes), and the resumed run must log a digest
#    mismatch, walk back to the committed step-20 checkpoint, resume
#    the stream at the stored offset, and finish with EXACT example
#    accounting (3200 — every row exactly once, steps 21-30 retrained
#    after the rollback). Emits the resumed segment's steady-state
#    datapoint as BENCH_r08.json (docs/PERF.md "Bench trajectory").
#
# 2. KILL-ONE-HOST SHRINK DRILL (probe-gated like every 2-proc drill):
#    a 2-rank supervised run with --allow-shrink; rank 1's "host" is
#    lost (a wedge via the stall injector — no heartbeat across the
#    grace window, the dead-HOST signature), the watchdog verdict tears
#    the job down, and the supervisor relaunches DEGRADED at 1 rank.
#    The survivor re-assigns BOTH data shards, resumes each at its
#    stored offset, and finishes with exact global accounting (3200);
#    metrics_report --check accepts the world change across
#    generations and --health labels rank 1 retired@gen0. When this
#    jax build cannot form a 2-process CPU world the drill is skipped
#    with a note (the in-process matrix in tests/test_topology.py
#    still covers the restore path).
#
# Standalone:    bash tools/smoke_topology.sh [workdir]
# From pytest:   tests/test_topology.py::test_smoke_topology_script
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
# bench datapoint destination: the repo root ONLY standalone (the
# per-PR record); under pytest it stays in the workdir
BENCH_OUT="$ROOT/BENCH_r08.json"
if [ -z "$WORK" ]; then
    WORK="$(mktemp -d)"
    trap 'rm -rf "$WORK"' EXIT
else
    BENCH_OUT="$WORK/BENCH_r08.json"
fi

export JAX_PLATFORMS=cpu
# one CPU device per rank: the multi-process drills below emulate
# hosts, not an in-process device mesh (xargs trims; an empty result
# must UNSET the var — XLA treats a whitespace-only value as a flags
# FILE to open and aborts)
XLA_FLAGS="$(printf '%s\n' ${XLA_FLAGS:-} \
    | grep -v xla_force_host_platform_device_count | xargs || true)"
if [ -n "$XLA_FLAGS" ]; then export XLA_FLAGS; else unset XLA_FLAGS; fi

# 3200 rows / batch 64 = 50 steps in one epoch (single-shard set)
python -m xflow_tpu gen-data "$WORK/train1" --shards 1 --rows 3200 \
    --fields 6 --ids-per-field 50 --seed 0 >/dev/null

# no --no-mesh: each rank has ONE CPU device (the flag strip above),
# so single-rank stages stay meshless naturally and the 2-rank drills
# form the real cross-process mesh
TRAIN_ARGS=(
    --model lr --epochs 1
    --batch-size 64 --log2-slots 12
    --set model.num_fields=6
    --set data.max_nnz=8
    --set train.pred_dump=false
    --set train.log_every=10
    --set train.heartbeat_every=5
    --set train.checkpoint_every=10
)

# ---- 1. silent-corruption digest drill -------------------------------------
# stage A: SIGKILL at step 30, right after its checkpoint committed
rc=0
XFLOW_FAULT_KILL_STEP=30 \
python -m xflow_tpu launch-local --num-processes 1 \
    --run-dir "$WORK/run_dig" -- \
    --train "$WORK/train1" "${TRAIN_ARGS[@]}" \
    --checkpoint-dir "$WORK/ck_dig" >/dev/null 2>"$WORK/dig_a.log" || rc=$?
[ "$rc" -ne 0 ] || { echo "digest drill: stage A unexpectedly exited 0"; exit 1; }

# flip bytes inside the newest (step-30) checkpoint's array payload,
# container rewritten: silent — only the meta.json digests can tell
python tools/corrupt_ckpt.py --dir "$WORK/ck_dig" --mode bitflip --count 16

# stage B: resume — must log the mismatch, walk back to step 20, and
# complete with every row trained exactly once
python -m xflow_tpu launch-local --num-processes 1 \
    --run-dir "$WORK/run_dig_b" -- \
    --train "$WORK/train1" "${TRAIN_ARGS[@]}" \
    --checkpoint-dir "$WORK/ck_dig" --set train.resume=true \
    >/dev/null 2>"$WORK/dig_b.log"
grep -q "digest mismatch" "$WORK/dig_b.log" || {
    echo "digest drill: no digest-mismatch log in stage B"; cat "$WORK/dig_b.log"; exit 1; }
grep -q "restored step 20" "$WORK/dig_b.log" || {
    echo "digest drill: walk-back to step 20 not logged"; cat "$WORK/dig_b.log"; exit 1; }

python - "$WORK" <<'EOF'
import os, sys
from xflow_tpu.train.checkpoint import latest_step, read_data_state

work = sys.argv[1]
step = latest_step(os.path.join(work, "ck_dig"))
assert step == 50, f"final committed step {step} != 50"
ds = read_data_state(os.path.join(work, "ck_dig"), step)
assert ds and ds["completed"], f"data_state not completed: {ds}"
assert ds["examples"] == 3200, f"examples {ds['examples']} != 3200 (replay or loss)"
print("smoke_topology: digest drill OK "
      f"(walk-back to 20, resumed to {step}, examples {ds['examples']})")
EOF

python tools/metrics_report.py "$WORK/run_dig_b" --check
python tools/metrics_report.py "$WORK/run_dig_b" --bench-json "$BENCH_OUT"

# ---- 2. kill-one-host shrink drill (probe-gated) ---------------------------
if python - >/dev/null 2>&1 <<'EOF'
import socket, subprocess, sys

s = socket.socket(); s.bind(("127.0.0.1", 0)); port = s.getsockname()[1]; s.close()
code = (
    "import sys, jax; jax.config.update('jax_platforms','cpu');"
    "jax.distributed.initialize(sys.argv[1], 2, int(sys.argv[2]));"
    "import numpy as np; from jax.sharding import Mesh, NamedSharding, PartitionSpec as P;"
    "mesh = Mesh(np.array(jax.devices()), ('d',));"
    "x = jax.device_put(np.zeros(4, np.float32), NamedSharding(mesh, P()));"
    "jax.block_until_ready(x)"
)
procs = [subprocess.Popen([sys.executable, "-c", code, f"127.0.0.1:{port}", str(r)])
         for r in range(2)]
ok = True
for p in procs:
    try:
        ok = ok and p.wait(timeout=120) == 0
    except subprocess.TimeoutExpired:
        p.kill(); ok = False
sys.exit(0 if ok else 1)
EOF
then
    # 2 shards x 1600 rows / batch 64 = 25 coordinated steps at 2 ranks
    python -m xflow_tpu gen-data "$WORK/train2" --shards 2 --rows 1600 \
        --fields 6 --ids-per-field 50 --seed 0 >/dev/null

    # rank 1 wedges at step 15 (stall injector — the host stops
    # answering without exiting); the watchdog's dead verdict after the
    # grace window is the dead-HOST signal --allow-shrink acts on
    XFLOW_FAULT_STALL_S=600 XFLOW_FAULT_STALL_STEP=15 XFLOW_FAULT_DELAY_RANK=1 \
    python -m xflow_tpu launch-local --num-processes 2 \
        --max-restarts 2 --restart-backoff 0.2 --allow-shrink \
        --dead-after-s 15 --watchdog-poll-s 0.5 \
        --run-dir "$WORK/run_shrink" -- \
        --train "$WORK/train2" "${TRAIN_ARGS[@]}" \
        --checkpoint-dir "$WORK/ck_shrink" >/dev/null 2>"$WORK/shrink.log"

    # the multi-generation, world-changing stream passes the schema gate
    python tools/metrics_report.py "$WORK/run_shrink" --check
    python tools/metrics_report.py "$WORK/run_shrink" --health \
        | tee "$WORK/shrink_health.txt" >/dev/null
    grep -q "retired@gen0" "$WORK/shrink_health.txt" || {
        echo "shrink drill: rank 1 not labeled retired@gen0"
        cat "$WORK/shrink_health.txt"; exit 1; }

    python - "$WORK" <<'EOF'
import os, sys
from xflow_tpu.train.checkpoint import latest_step, read_data_state

work = sys.argv[1]
step = latest_step(os.path.join(work, "ck_shrink"))
# gen 0 (2 ranks) committed step 10; the shrunk gen resumes there and
# trains each shard's remaining 15 batches: 10 + 30 = 40
assert step == 40, f"final committed step {step} != 40"
ds = read_data_state(os.path.join(work, "ck_shrink"), step)
assert ds and ds["completed"], f"data_state not completed: {ds}"
assert ds["examples"] == 3200, f"examples {ds['examples']} != 3200 (replay or loss)"
assert ds["world_size"] == 1 and ds["num_shards"] == 2, ds
print("smoke_topology: shrink drill OK "
      f"(2 ranks -> 1, step {step}, examples {ds['examples']})")
EOF
    # the shrink drill's steady-state datapoint supersedes stage B's
    python tools/metrics_report.py "$WORK/run_shrink" --bench-json "$BENCH_OUT"

    # ---- grow 1 -> 2: a 1-rank checkpoint resumes at 2 ranks ----------
    # stage A: 1 rank over the SAME 2-shard set (it owns shard 0 only,
    # the legacy contract), SIGKILLed at step 20 right after that
    # checkpoint committed
    rc=0
    XFLOW_FAULT_KILL_STEP=20 \
    python -m xflow_tpu launch-local --num-processes 1 \
        --run-dir "$WORK/run_grow_a" -- \
        --train "$WORK/train2" "${TRAIN_ARGS[@]}" \
        --checkpoint-dir "$WORK/ck_grow" >/dev/null 2>&1 || rc=$?
    [ "$rc" -ne 0 ] || { echo "grow drill: stage A unexpectedly exited 0"; exit 1; }

    # stage B: resume at TWO ranks — rank 0 continues shard 0 at its
    # stored offset, rank 1 picks up shard 1 (its own index) fresh
    python -m xflow_tpu launch-local --num-processes 2 \
        --run-dir "$WORK/run_grow_b" -- \
        --train "$WORK/train2" "${TRAIN_ARGS[@]}" \
        --checkpoint-dir "$WORK/ck_grow" --set train.resume=true \
        >/dev/null 2>"$WORK/grow_b.log"

    python - "$WORK" <<'EOF'
import os, sys
from xflow_tpu.train.checkpoint import latest_step, read_data_state

work = sys.argv[1]
step = latest_step(os.path.join(work, "ck_grow"))
# 20 (gen A) + 25 coordinated grown steps (rank 0: 5 real then pads,
# rank 1: 25) = 45
assert step == 45, f"final committed step {step} != 45"
ds = read_data_state(os.path.join(work, "ck_grow"), step)
assert ds and ds["completed"], f"data_state not completed: {ds}"
assert ds["examples"] == 3200, f"examples {ds['examples']} != 3200 (replay or loss)"
assert ds["world_size"] == 2 and ds["num_shards"] == 2, ds
print("smoke_topology: grow drill OK "
      f"(1 rank -> 2, step {step}, examples {ds['examples']})")
EOF
else
    echo "smoke_topology: shrink drill skipped (multi-process CPU unsupported by this jax build)"
fi

# repo-root hygiene: running the tools from the root must leave no
# stray artifact dirs behind (tools/__pycache__ and friends)
rm -rf "$ROOT/tools/__pycache__" "$ROOT/__pycache__"

echo "smoke_topology: OK"
