#!/usr/bin/env bash
# Static-analysis smoke gate (docs/STATIC_ANALYSIS.md):
#
# 1. Repo-wide xflowlint against the checked-in baseline must be GREEN
#    (zero unbaselined findings, zero stale baseline entries) —
#    includes the IR tier where jax is importable.
# 2. The fixture corpus must behave: every bad_* fixture fires exactly
#    its rule family (incl. the resurrected pre-PR 8 unlocked-appender
#    bug), every good_*/suppress_* fixture stays silent.
# 3. Baseline mechanics: a NEW finding exits 1; a baseline entry whose
#    finding was fixed exits 2 (the baseline-shrink check — fixing a
#    finding must also remove its entry); writing NEW entries without
#    --reason is refused (3) and a checked-in placeholder reason fails
#    the audit (3).
# 4. Seeded-violation drill: one violation of each rule class seeded
#    into a scratch copy of a REAL module is caught with the correct
#    rule id and file:line (4b: XF704 cross-engine drift via a
#    four-builder scratch tree with one trace scope renamed).
# 5. Engine-contract matrix: checked-in tools/engine_contracts.json is
#    current and byte-stable; un-regenerated builder edits exit 4
#    (distinct from finding growth). Builders-only scratch trees
#    compare the AST sections (the IR tier needs an importable tree).
# 6. IR tier (jaxpr rules + fusion worklist, docs/STATIC_ANALYSIS.md
#    "The IR tier"): the checked-in tools/fusion_worklist.json is
#    current and byte-stable, un-regenerated drift exits 4, and a
#    seeded violation of each XF801-XF804 rule in a FULL scratch tree
#    is caught at the exact file:line. SKIPPED with a notice where jax
#    is unimportable — AST-only linting keeps working.
# 7. ruff (the pinned generic-Python layer, pyproject.toml) runs clean
#    when installed; skipped with a notice where the container lacks it.
#
# Standalone:    bash tools/smoke_lint.sh [workdir]
# From pytest:   tests/test_xflowlint.py::test_smoke_lint_script
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
[ -n "$WORK" ] || WORK="$(mktemp -d /tmp/xflow_lint.XXXXXX)"
mkdir -p "$WORK"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export PYTHONPATH="$ROOT${PYTHONPATH:+:$PYTHONPATH}"

HAVE_IR=0
python -c "import jax" >/dev/null 2>&1 && HAVE_IR=1

echo "smoke_lint: workdir $WORK (IR tier available: $HAVE_IR)"

# ---- 1. repo-wide lint, baselined (full-tree runs include the IR
#         tier; --jobs 0 fans the per-module passes over a worker pool)
python tools/xflowlint.py --jobs 0
echo "smoke_lint: repo-wide lint green"

# ---- 2. fixture corpus ----------------------------------------------------
FIX="tests/fixtures/xflowlint"
expect_rules() { # expect_rules <fixture> <rule...>: exact rule-id set
    local fixture="$1"; shift
    local got want
    # xflowlint exits 1 on findings BY DESIGN — that's what we assert
    # on, so the substitution must not trip set -e/pipefail
    got=$({ python tools/xflowlint.py "$FIX/$fixture" --no-baseline \
        2>/dev/null || true; } | { grep -oE 'XF[0-9]+' || true; } \
        | sort -u | tr '\n' ' ')
    want=$(printf '%s\n' "$@" | sort -u | tr '\n' ' ')
    [ "$got" = "$want" ] || {
        echo "smoke_lint: $fixture: expected rules [$want] got [$got]"
        exit 1; }
}
expect_silent() {
    python tools/xflowlint.py "$FIX/$1" --no-baseline >/dev/null 2>&1 || {
        echo "smoke_lint: $1 must lint clean"; exit 1; }
}
expect_rules bad_jit_purity.py XF101
expect_rules bad_recompile.py XF201 XF202 XF203
expect_rules bad_lockset.py XF301     # the pre-PR 8 appender, forever
expect_rules bad_config.py XF401
expect_rules bad_schema.py XF501 XF502
expect_rules bad_shell.sh XF401 XF601
expect_rules bad_hostsync.py XF110 XF111
expect_rules bad_sharding_contract.py XF701 XF702 XF703
expect_silent good_lockset.py
expect_silent good_clean.py
expect_silent suppress_line.py
expect_silent suppress_file.py
echo "smoke_lint: fixture corpus behaves (8 bad fire, 4 good silent)"

# ---- 3. baseline growth + shrink mechanics --------------------------------
BL="$WORK/baseline.json"
rc=0; python tools/xflowlint.py "$FIX/bad_lockset.py" --no-baseline \
    >/dev/null 2>&1 || rc=$?
[ "$rc" -eq 1 ] || { echo "smoke_lint: new finding must exit 1, got $rc"; exit 1; }
# NEW entries need a justification: without --reason the write refuses
rc=0; python tools/xflowlint.py "$FIX/bad_lockset.py" --write-baseline \
    --baseline "$BL" >/dev/null 2>&1 || rc=$?
[ "$rc" -eq 3 ] || {
    echo "smoke_lint: reasonless --write-baseline must exit 3, got $rc"
    exit 1; }
python tools/xflowlint.py "$FIX/bad_lockset.py" --write-baseline \
    --baseline "$BL" --reason "smoke drill: fixture stays bad" >/dev/null
python tools/xflowlint.py "$FIX/bad_lockset.py" --baseline "$BL" >/dev/null \
    || { echo "smoke_lint: baselined lint must exit 0"; exit 1; }
# a checked-in placeholder reason fails the audit (the pre-fix
# --write-baseline default could land verbatim in the baseline)
sed 's/smoke drill: fixture stays bad/TODO: justify or fix/' "$BL" \
    > "$WORK/baseline_todo.json"
rc=0; python tools/xflowlint.py "$FIX/bad_lockset.py" \
    --baseline "$WORK/baseline_todo.json" >/dev/null 2>&1 || rc=$?
[ "$rc" -eq 3 ] || {
    echo "smoke_lint: placeholder baseline reason must fail the audit" \
         "(exit 3), got $rc"; exit 1; }
# "fix" the finding by linting the fixed fixture against the same
# baseline: every entry is now stale -> the gate demands the baseline
# shrink (exit 2)
rc=0; python tools/xflowlint.py "$FIX/good_lockset.py" --baseline "$BL" \
    >/dev/null 2>&1 || rc=$?
[ "$rc" -eq 2 ] || {
    echo "smoke_lint: stale baseline must exit 2 (shrink check), got $rc"
    exit 1; }
echo "smoke_lint: baseline growth/shrink/reason mechanics OK (1 / 3 / 0 / 3 / 2)"

# ---- 4. seeded violations in scratch copies of real modules ---------------
SCRATCH="$WORK/scratch"
seed() { # seed <rule> <module> <<< snippet-on-stdin
    local rule="$1" module="$2"
    local dst="$SCRATCH/$module"
    mkdir -p "$(dirname "$dst")"
    cp "$module" "$dst"
    cat >>"$dst"
    local line
    line=$(awk '/SEED$/{print NR; exit}' "$dst")
    local out
    out=$(python tools/xflowlint.py "$dst" --no-baseline 2>/dev/null || true)
    # herestrings, not `echo | grep -q`: pipefail + grep's early exit
    # can SIGPIPE the producer and fail a passing check
    grep -q "$rule" <<<"$out" || {
        echo "smoke_lint: seeded $rule in $module not caught"; echo "$out"
        exit 1; }
    grep -qE "${module##*/}:$line: $rule" <<<"$out" || {
        echo "smoke_lint: seeded $rule wanted ${module##*/}:$line"
        echo "$out"; exit 1; }
}
seed XF101 xflow_tpu/models/predict.py <<'EOF'


import time


@jax.jit
def _lint_seeded_purity(x):
    return x + time.perf_counter()  # SEED
EOF
seed XF201 xflow_tpu/models/predict.py <<'EOF'


def _lint_seeded_loop(xs):
    for _x in xs:
        jax.jit(lambda v: v)(_x)  # SEED
EOF
seed XF301 xflow_tpu/serve/metrics.py <<'EOF'


class _LintSeededRace:
    def __init__(self):
        self.n = 0
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        self.n += 1  # SEED

    def bump(self):
        self.n += 1
EOF
seed XF401 xflow_tpu/serve/metrics.py <<'EOF'


def _lint_seeded_key(cfg: "Config"):
    return cfg.serve.windw_ms  # SEED
EOF
seed XF501 xflow_tpu/serve/metrics.py <<'EOF'


def _lint_seeded_drift(app):
    app.append({"kind": "serve", "qqps": 1})  # SEED
EOF
seed XF110 xflow_tpu/train/trainer.py <<'EOF'


class _LintSeededSync:
    def _fit(self, batches):
        state = None
        for b in batches:
            state, m = self.train_step(state, b)
            print(float(m["loss"]))  # SEED
EOF
seed XF111 xflow_tpu/train/trainer.py <<'EOF'


class _LintSeededBranch:
    def _fit(self, batches):
        state = None
        for b in batches:
            state, m = self.train_step(state, b)
            if m["update_ok"]:  # SEED
                break
EOF
seed XF701 xflow_tpu/parallel/sorted_sharded.py <<'EOF'


def _lint_seeded_axis(mesh):
    return NamedSharding(mesh, P("tabel", None))  # SEED
EOF
seed XF702 xflow_tpu/train/step.py <<'EOF'


def _lint_seeded_donated(step_fn, state, batch):
    jitted = jax.jit(step_fn, donate_argnums=(0,))
    out = jitted(state, batch)
    return out, state  # SEED
EOF
seed XF703 xflow_tpu/parallel/train_step.py <<'EOF'


def _lint_seeded_nodonate():
    def train_step(state, batch):
        return state

    return jax.jit(train_step)  # SEED
EOF
echo "smoke_lint: seeded-violation drill OK (10 rule classes, exact file:line)"

# ---- 4b. XF704 cross-engine drift needs all four builders in one root ----
DRIFT="$WORK/drift_tree"
mkdir -p "$DRIFT/xflow_tpu/train" "$DRIFT/xflow_tpu/parallel"
cp xflow_tpu/train/step.py "$DRIFT/xflow_tpu/train/"
cp xflow_tpu/parallel/train_step.py xflow_tpu/parallel/sorted_sharded.py \
   xflow_tpu/parallel/sorted_fullshard.py xflow_tpu/parallel/mesh.py \
   "$DRIFT/xflow_tpu/parallel/"
python tools/xflowlint.py --root "$DRIFT" --no-baseline >/dev/null 2>&1 \
    || { echo "smoke_lint: faithful builder copies must lint clean"; exit 1; }
# rename one builder's "optimizer" scope: every OTHER builder covers it
sed -i 's/named_scope("optimizer")/named_scope("optimzer")/' \
    "$DRIFT/xflow_tpu/parallel/sorted_sharded.py"
line=$(grep -n 'jax.named_scope' "$DRIFT/xflow_tpu/parallel/sorted_sharded.py" \
    | head -1 | cut -d: -f1)
out=$(python tools/xflowlint.py --root "$DRIFT" --no-baseline 2>/dev/null || true)
grep -qE "sorted_sharded.py:$line: XF704" <<<"$out" || {
    echo "smoke_lint: seeded XF704 scope drift not caught at" \
         "sorted_sharded.py:$line"; echo "$out"; exit 1; }
echo "smoke_lint: XF704 cross-engine scope-drift drill OK"

# ---- 5. engine-contract matrix: checked in, byte-stable, drift-gated ------
# (docs/DISTRIBUTED.md "Engine contract matrix"; exit 4 is DISTINCT
# from finding growth so CI can tell "new bug" from "stale oracle")
python tools/xflowlint.py --check-contracts >/dev/null
CONTRACT="$WORK/contract_tree"
mkdir -p "$CONTRACT/xflow_tpu/train" "$CONTRACT/xflow_tpu/parallel" \
         "$CONTRACT/tools"
cp xflow_tpu/train/step.py "$CONTRACT/xflow_tpu/train/"
cp xflow_tpu/parallel/train_step.py xflow_tpu/parallel/sorted_sharded.py \
   xflow_tpu/parallel/sorted_fullshard.py xflow_tpu/parallel/mesh.py \
   "$CONTRACT/xflow_tpu/parallel/"
cp tools/engine_contracts.json "$CONTRACT/tools/"
python tools/xflowlint.py --root "$CONTRACT" --check-contracts >/dev/null \
    || { echo "smoke_lint: contract check must pass on faithful copies"; exit 1; }
# byte stability: two consecutive regenerations are identical, and both
# match the checked-in artifact
python tools/xflowlint.py --root "$CONTRACT" --write-contracts >/dev/null
cp "$CONTRACT/tools/engine_contracts.json" "$WORK/contracts_r1.json"
python tools/xflowlint.py --root "$CONTRACT" --write-contracts >/dev/null
cmp -s "$WORK/contracts_r1.json" "$CONTRACT/tools/engine_contracts.json" || {
    echo "smoke_lint: contract artifact not byte-stable across two runs"
    exit 1; }
cmp -s "$WORK/contracts_r1.json" tools/engine_contracts.json || {
    echo "smoke_lint: checked-in engine_contracts.json is stale —" \
         "regenerate with tools/xflowlint.py --write-contracts"
    exit 1; }
# drift gate: change a builder's contract (drop the donation) without
# regenerating -> exit 4, distinct from finding growth (1) / stale (2)
sed -i 's/donate_argnums=(0,),//' \
    "$CONTRACT/xflow_tpu/parallel/sorted_sharded.py"
rc=0; python tools/xflowlint.py --root "$CONTRACT" --check-contracts \
    >/dev/null 2>&1 || rc=$?
[ "$rc" -eq 4 ] || {
    echo "smoke_lint: contract drift must exit 4, got $rc"; exit 1; }
echo "smoke_lint: engine-contract matrix OK (stable, covered, drift=4)"

# ---- 6. IR tier: fusion worklist + XF801-XF804 seeded drills --------------
# (docs/STATIC_ANALYSIS.md "The IR tier"; mirrors the ruff pattern:
# jax unimportable => SKIP with a notice, AST-only linting keeps
# working — which section 1 already proved by running without it)
if [ "$HAVE_IR" -eq 1 ]; then
    # checked-in worklist is current (exit 4 on drift, like contracts)
    python tools/xflowlint.py --check-worklist >/dev/null
    # a FULL scratch tree (the IR tier imports and lowers it; the
    # import guard rejects partial trees, so builders-only copies
    # degrade to AST-only above)
    IRS="$WORK/ir_tree"
    mkdir -p "$IRS"
    cp -r xflow_tpu tools bench.py conftest.py "$IRS/"
    rm -rf "$IRS"/xflow_tpu/__pycache__
    # byte stability: two consecutive regenerations identical, both
    # matching the checked-in artifact
    python tools/xflowlint.py --root "$IRS" --write-worklist >/dev/null
    cp "$IRS/tools/fusion_worklist.json" "$WORK/worklist_r1.json"
    python tools/xflowlint.py --root "$IRS" --write-worklist >/dev/null
    cmp -s "$WORK/worklist_r1.json" "$IRS/tools/fusion_worklist.json" || {
        echo "smoke_lint: fusion worklist not byte-stable across two runs"
        exit 1; }
    cmp -s "$WORK/worklist_r1.json" tools/fusion_worklist.json || {
        echo "smoke_lint: checked-in fusion_worklist.json is stale —" \
             "regenerate with tools/xflowlint.py --write-worklist"
        exit 1; }
    # drift gate: a worklist that no longer matches the lowered
    # programs exits 4 (distinct from finding growth)
    sed -i 's/"gathers": 1/"gathers": 7/' "$IRS/tools/fusion_worklist.json"
    rc=0; python tools/xflowlint.py --root "$IRS" --check-worklist \
        >/dev/null 2>&1 || rc=$?
    [ "$rc" -eq 4 ] || {
        echo "smoke_lint: worklist drift must exit 4, got $rc"; exit 1; }
    # XF801: a chain missing from the worklist fires at the chain's
    # engine-module anchor (the LR two-pass chain anchors at the
    # loss_fn forward line in train/step.py)
    echo '{"entries": []}' > "$IRS/tools/fusion_worklist.json"
    line=$(grep -n 'logits = model.forward(tables, batch, cfg)' \
        "$IRS/xflow_tpu/train/step.py" | head -1 | cut -d: -f1)
    out=$(python tools/xflowlint.py --root "$IRS" --no-baseline \
        --rules XF801 2>/dev/null || true)
    grep -qE "step.py:$line: XF801" <<<"$out" || {
        echo "smoke_lint: seeded XF801 (empty worklist) not caught at" \
             "step.py:$line"; echo "$out"; exit 1; }
    cp tools/fusion_worklist.json "$IRS/tools/"
    # XF802: hidden bf16 -> f32 widening of the state tables
    sed -i 's|loss, grads = jax.value_and_grad(loss_fn)(state.tables, batch, model, cfg)|loss, grads = jax.value_and_grad(loss_fn)({k: v.astype(jnp.bfloat16).astype(jnp.float32) for k, v in state.tables.items()}, batch, model, cfg)  # IR-SEED-802|' \
        "$IRS/xflow_tpu/train/step.py"
    # XF803: a scan with a dead stacked output riding the step
    sed -i 's|^        metrics = {"loss": loss, "rows": batch\["row_mask"\].sum()}|        _c, _ys = jax.lax.scan(lambda c, _: (c, c * 2.0), loss, None, length=4)  # IR-SEED-803\n        metrics = {"loss": loss, "rows": batch["row_mask"].sum()}|' \
        "$IRS/xflow_tpu/train/step.py"
    # XF804: donation the AST tier cannot see (AST says undonated, the
    # lowered signature donates) — the contract matrix would rot
    sed -i 's|train_step = jax.jit(train_step, donate_argnums=(0,))|train_step = jax.jit(train_step, **{"donate_argnums": (0,)})  # IR-SEED-804|' \
        "$IRS/xflow_tpu/train/step.py"
    out=$(python tools/xflowlint.py --root "$IRS" --no-baseline \
        --rules XF802,XF803,XF804 2>/dev/null || true)
    for rule in XF802 XF803 XF804; do
        line=$(grep -n "IR-SEED-${rule#XF}" "$IRS/xflow_tpu/train/step.py" \
            | head -1 | cut -d: -f1)
        grep -qE "step.py:$line: $rule" <<<"$out" || {
            echo "smoke_lint: seeded $rule not caught at step.py:$line"
            echo "$out"; exit 1; }
    done
    echo "smoke_lint: IR tier OK (worklist stable+current, drift=4," \
         "XF801-XF804 seeded drills exact file:line)"
else
    echo "smoke_lint: jax not importable — IR tier drills SKIPPED" \
         "(AST-only linting verified above; the IR tier needs jax)"
fi

# ---- 7. ruff: the pinned generic-Python layer -----------------------------
if command -v ruff >/dev/null 2>&1; then
    ruff check .
    echo "smoke_lint: ruff layer green ($(ruff --version))"
else
    echo "smoke_lint: ruff not installed — generic layer SKIPPED" \
         "(pip install -e '.[lint]' to enable; pinned in pyproject.toml)"
fi

echo "smoke_lint: OK"
