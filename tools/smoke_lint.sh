#!/usr/bin/env bash
# Static-analysis smoke gate (docs/STATIC_ANALYSIS.md):
#
# 1. Repo-wide xflowlint against the checked-in baseline must be GREEN
#    (zero unbaselined findings, zero stale baseline entries).
# 2. The fixture corpus must behave: every bad_* fixture fires exactly
#    its rule family (incl. the resurrected pre-PR 8 unlocked-appender
#    bug), every good_*/suppress_* fixture stays silent.
# 3. Baseline mechanics: a NEW finding exits 1; a baseline entry whose
#    finding was fixed exits 2 (the baseline-shrink check — fixing a
#    finding must also remove its entry).
# 4. Seeded-violation drill: one violation of each rule class seeded
#    into a scratch copy of a REAL module is caught with the correct
#    rule id and file:line (4b: XF704 cross-engine drift via a
#    four-builder scratch tree with one trace scope renamed).
# 5. Engine-contract matrix: checked-in tools/engine_contracts.json is
#    current and byte-stable; un-regenerated builder edits exit 4
#    (distinct from finding growth).
# 6. ruff (the pinned generic-Python layer, pyproject.toml) runs clean
#    when installed; skipped with a notice where the container lacks it.
#
# Standalone:    bash tools/smoke_lint.sh [workdir]
# From pytest:   tests/test_xflowlint.py::test_smoke_lint_script
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
[ -n "$WORK" ] || WORK="$(mktemp -d /tmp/xflow_lint.XXXXXX)"
mkdir -p "$WORK"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export PYTHONPATH="$ROOT${PYTHONPATH:+:$PYTHONPATH}"

echo "smoke_lint: workdir $WORK"

# ---- 1. repo-wide lint, baselined ----------------------------------------
python tools/xflowlint.py
echo "smoke_lint: repo-wide lint green"

# ---- 2. fixture corpus ----------------------------------------------------
FIX="tests/fixtures/xflowlint"
expect_rules() { # expect_rules <fixture> <rule...>: exact rule-id set
    local fixture="$1"; shift
    local got want
    # xflowlint exits 1 on findings BY DESIGN — that's what we assert
    # on, so the substitution must not trip set -e/pipefail
    got=$({ python tools/xflowlint.py "$FIX/$fixture" --no-baseline \
        2>/dev/null || true; } | { grep -oE 'XF[0-9]+' || true; } \
        | sort -u | tr '\n' ' ')
    want=$(printf '%s\n' "$@" | sort -u | tr '\n' ' ')
    [ "$got" = "$want" ] || {
        echo "smoke_lint: $fixture: expected rules [$want] got [$got]"
        exit 1; }
}
expect_silent() {
    python tools/xflowlint.py "$FIX/$1" --no-baseline >/dev/null 2>&1 || {
        echo "smoke_lint: $1 must lint clean"; exit 1; }
}
expect_rules bad_jit_purity.py XF101
expect_rules bad_recompile.py XF201 XF202 XF203
expect_rules bad_lockset.py XF301     # the pre-PR 8 appender, forever
expect_rules bad_config.py XF401
expect_rules bad_schema.py XF501 XF502
expect_rules bad_shell.sh XF401 XF601
expect_rules bad_hostsync.py XF110 XF111
expect_rules bad_sharding_contract.py XF701 XF702 XF703
expect_silent good_lockset.py
expect_silent good_clean.py
expect_silent suppress_line.py
expect_silent suppress_file.py
echo "smoke_lint: fixture corpus behaves (8 bad fire, 4 good silent)"

# ---- 3. baseline growth + shrink mechanics --------------------------------
BL="$WORK/baseline.json"
rc=0; python tools/xflowlint.py "$FIX/bad_lockset.py" --no-baseline \
    >/dev/null 2>&1 || rc=$?
[ "$rc" -eq 1 ] || { echo "smoke_lint: new finding must exit 1, got $rc"; exit 1; }
python tools/xflowlint.py "$FIX/bad_lockset.py" --write-baseline \
    --baseline "$BL" >/dev/null
python tools/xflowlint.py "$FIX/bad_lockset.py" --baseline "$BL" >/dev/null \
    || { echo "smoke_lint: baselined lint must exit 0"; exit 1; }
# "fix" the finding by linting the fixed fixture against the same
# baseline: every entry is now stale -> the gate demands the baseline
# shrink (exit 2)
rc=0; python tools/xflowlint.py "$FIX/good_lockset.py" --baseline "$BL" \
    >/dev/null 2>&1 || rc=$?
[ "$rc" -eq 2 ] || {
    echo "smoke_lint: stale baseline must exit 2 (shrink check), got $rc"
    exit 1; }
echo "smoke_lint: baseline growth/shrink mechanics OK (1 / 0 / 2)"

# ---- 4. seeded violations in scratch copies of real modules ---------------
SCRATCH="$WORK/scratch"
seed() { # seed <rule> <module> <<< snippet-on-stdin
    local rule="$1" module="$2"
    local dst="$SCRATCH/$module"
    mkdir -p "$(dirname "$dst")"
    cp "$module" "$dst"
    cat >>"$dst"
    local line
    line=$(awk '/SEED$/{print NR; exit}' "$dst")
    local out
    out=$(python tools/xflowlint.py "$dst" --no-baseline 2>/dev/null || true)
    # herestrings, not `echo | grep -q`: pipefail + grep's early exit
    # can SIGPIPE the producer and fail a passing check
    grep -q "$rule" <<<"$out" || {
        echo "smoke_lint: seeded $rule in $module not caught"; echo "$out"
        exit 1; }
    grep -qE "${module##*/}:$line: $rule" <<<"$out" || {
        echo "smoke_lint: seeded $rule wanted ${module##*/}:$line"
        echo "$out"; exit 1; }
}
seed XF101 xflow_tpu/models/predict.py <<'EOF'


import time


@jax.jit
def _lint_seeded_purity(x):
    return x + time.perf_counter()  # SEED
EOF
seed XF201 xflow_tpu/models/predict.py <<'EOF'


def _lint_seeded_loop(xs):
    for _x in xs:
        jax.jit(lambda v: v)(_x)  # SEED
EOF
seed XF301 xflow_tpu/serve/metrics.py <<'EOF'


class _LintSeededRace:
    def __init__(self):
        self.n = 0
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        self.n += 1  # SEED

    def bump(self):
        self.n += 1
EOF
seed XF401 xflow_tpu/serve/metrics.py <<'EOF'


def _lint_seeded_key(cfg: "Config"):
    return cfg.serve.windw_ms  # SEED
EOF
seed XF501 xflow_tpu/serve/metrics.py <<'EOF'


def _lint_seeded_drift(app):
    app.append({"kind": "serve", "qqps": 1})  # SEED
EOF
seed XF110 xflow_tpu/train/trainer.py <<'EOF'


class _LintSeededSync:
    def _fit(self, batches):
        state = None
        for b in batches:
            state, m = self.train_step(state, b)
            print(float(m["loss"]))  # SEED
EOF
seed XF111 xflow_tpu/train/trainer.py <<'EOF'


class _LintSeededBranch:
    def _fit(self, batches):
        state = None
        for b in batches:
            state, m = self.train_step(state, b)
            if m["update_ok"]:  # SEED
                break
EOF
seed XF701 xflow_tpu/parallel/sorted_sharded.py <<'EOF'


def _lint_seeded_axis(mesh):
    return NamedSharding(mesh, P("tabel", None))  # SEED
EOF
seed XF702 xflow_tpu/train/step.py <<'EOF'


def _lint_seeded_donated(step_fn, state, batch):
    jitted = jax.jit(step_fn, donate_argnums=(0,))
    out = jitted(state, batch)
    return out, state  # SEED
EOF
seed XF703 xflow_tpu/parallel/train_step.py <<'EOF'


def _lint_seeded_nodonate():
    def train_step(state, batch):
        return state

    return jax.jit(train_step)  # SEED
EOF
echo "smoke_lint: seeded-violation drill OK (10 rule classes, exact file:line)"

# ---- 4b. XF704 cross-engine drift needs all four builders in one root ----
DRIFT="$WORK/drift_tree"
mkdir -p "$DRIFT/xflow_tpu/train" "$DRIFT/xflow_tpu/parallel"
cp xflow_tpu/train/step.py "$DRIFT/xflow_tpu/train/"
cp xflow_tpu/parallel/train_step.py xflow_tpu/parallel/sorted_sharded.py \
   xflow_tpu/parallel/sorted_fullshard.py xflow_tpu/parallel/mesh.py \
   "$DRIFT/xflow_tpu/parallel/"
python tools/xflowlint.py --root "$DRIFT" --no-baseline >/dev/null 2>&1 \
    || { echo "smoke_lint: faithful builder copies must lint clean"; exit 1; }
# rename one builder's "optimizer" scope: every OTHER builder covers it
sed -i 's/named_scope("optimizer")/named_scope("optimzer")/' \
    "$DRIFT/xflow_tpu/parallel/sorted_sharded.py"
line=$(grep -n 'jax.named_scope' "$DRIFT/xflow_tpu/parallel/sorted_sharded.py" \
    | head -1 | cut -d: -f1)
out=$(python tools/xflowlint.py --root "$DRIFT" --no-baseline 2>/dev/null || true)
grep -qE "sorted_sharded.py:$line: XF704" <<<"$out" || {
    echo "smoke_lint: seeded XF704 scope drift not caught at" \
         "sorted_sharded.py:$line"; echo "$out"; exit 1; }
echo "smoke_lint: XF704 cross-engine scope-drift drill OK"

# ---- 5. engine-contract matrix: checked in, byte-stable, drift-gated ------
# (docs/DISTRIBUTED.md "Engine contract matrix"; exit 4 is DISTINCT
# from finding growth so CI can tell "new bug" from "stale oracle")
python tools/xflowlint.py --check-contracts >/dev/null
CONTRACT="$WORK/contract_tree"
mkdir -p "$CONTRACT/xflow_tpu/train" "$CONTRACT/xflow_tpu/parallel" \
         "$CONTRACT/tools"
cp xflow_tpu/train/step.py "$CONTRACT/xflow_tpu/train/"
cp xflow_tpu/parallel/train_step.py xflow_tpu/parallel/sorted_sharded.py \
   xflow_tpu/parallel/sorted_fullshard.py xflow_tpu/parallel/mesh.py \
   "$CONTRACT/xflow_tpu/parallel/"
cp tools/engine_contracts.json "$CONTRACT/tools/"
python tools/xflowlint.py --root "$CONTRACT" --check-contracts >/dev/null \
    || { echo "smoke_lint: contract check must pass on faithful copies"; exit 1; }
# byte stability: two consecutive regenerations are identical, and both
# match the checked-in artifact
python tools/xflowlint.py --root "$CONTRACT" --write-contracts >/dev/null
cp "$CONTRACT/tools/engine_contracts.json" "$WORK/contracts_r1.json"
python tools/xflowlint.py --root "$CONTRACT" --write-contracts >/dev/null
cmp -s "$WORK/contracts_r1.json" "$CONTRACT/tools/engine_contracts.json" || {
    echo "smoke_lint: contract artifact not byte-stable across two runs"
    exit 1; }
cmp -s "$WORK/contracts_r1.json" tools/engine_contracts.json || {
    echo "smoke_lint: checked-in engine_contracts.json is stale —" \
         "regenerate with tools/xflowlint.py --write-contracts"
    exit 1; }
# drift gate: change a builder's contract (drop the donation) without
# regenerating -> exit 4, distinct from finding growth (1) / stale (2)
sed -i 's/donate_argnums=(0,),//' \
    "$CONTRACT/xflow_tpu/parallel/sorted_sharded.py"
rc=0; python tools/xflowlint.py --root "$CONTRACT" --check-contracts \
    >/dev/null 2>&1 || rc=$?
[ "$rc" -eq 4 ] || {
    echo "smoke_lint: contract drift must exit 4, got $rc"; exit 1; }
echo "smoke_lint: engine-contract matrix OK (stable, covered, drift=4)"

# ---- 6. ruff: the pinned generic-Python layer -----------------------------
if command -v ruff >/dev/null 2>&1; then
    ruff check .
    echo "smoke_lint: ruff layer green ($(ruff --version))"
else
    echo "smoke_lint: ruff not installed — generic layer SKIPPED" \
         "(pip install -e '.[lint]' to enable; pinned in pyproject.toml)"
fi

echo "smoke_lint: OK"
