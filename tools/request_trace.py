#!/usr/bin/env python3
"""Assemble per-request traces from a serving run's span streams
(docs/OBSERVABILITY.md "Request tracing").

The fleet writes `kind="span"` records into per-replica + router JSONL
streams (xflow_tpu/tracing.py); this tool is the reader that turns
them back into answers:

    python tools/request_trace.py runs/fleet/            # summary +
                                                         # critical-path table
    python tools/request_trace.py runs/fleet --slow 5    # slowest-5 exemplars
    python tools/request_trace.py runs/fleet --timeline  # reload/checkpoint
                                                         # overlay
    python tools/request_trace.py runs/fleet --chrome trace.json
                                                         # Perfetto-viewable
    python tools/request_trace.py runs/fleet --json -    # machine-readable
    python tools/request_trace.py runs/fleet --min-complete 0.99  # CI gate

- **Assembly**: spans group by trace id ACROSS streams (the router's
  rank=-1 stream + every replica's), parent ids knit them into one
  tree per request, and `device_batch` spans attach by the `batch=`
  link request `device` spans carry — the same cross-stream join
  philosophy as tools/trace_attrib.py, keyed on trace id instead of
  hlo_module. Orphans (a hedge leg whose losing-side spans outlived
  their parent's emission) are tolerated and counted, never fatal.

- **Critical path**: each 200-trace decomposes into retry (time burnt
  on legs before the winning attempt started), network (winning
  attempt minus the replica-observed server time), parse, queue
  (backlog wait inside a size-flushed batch), window (coalescing wait
  inside a deadline-flushed batch), device (the shared batch's device
  time), and server/router overhead. The printed table shows the
  aggregate per-hop percentages plus the p50 and p99 EXEMPLARS — real
  requests, with their trace ids, so "the p99 is queue-bound on
  replica 1" comes with a receipt you can pull up.

- **Per-replica blame**: the same decomposition grouped by the replica
  stamp the appender put on every span — a slow replica shows up as
  its own row with the guilty hop inflated (tools/smoke_trace.sh gates
  on exactly this).

- **Chrome export** (`--chrome`): trace-event JSON ("X" complete
  events, one pid per process stream, one tid per request) loadable in
  Perfetto / chrome://tracing.

Exit codes: 0 ok · 1 no span records · 2 bad paths ·
4 --min-complete unmet.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xflow_tpu.jsonl import read_jsonl_counted  # noqa: E402
from xflow_tpu.tracing import BATCH_SPAN_NAME, REQUEST_SPAN_NAMES  # noqa: E402

# the critical-path categories, in print order
CATEGORIES = (
    "retry", "network", "parse", "queue", "window", "device",
    "server_other", "router_other",
)


def expand_paths(paths: list) -> list:
    """Files stay files; directories expand to their sorted *.jsonl
    (rotated `.jsonl.1` siblings fold in via read_jsonl)."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(glob.glob(os.path.join(p, "*.jsonl")))
            if not found:
                raise FileNotFoundError(f"{p!r}: directory holds no *.jsonl files")
            out.extend(found)
        elif not os.path.exists(p):
            raise FileNotFoundError(f"{p!r}: no such file")
        else:
            out.append(p)
    return out


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


def load_spans(files: list) -> tuple[list, list, list]:
    """(request_spans, batch_spans, op_spans) across every file.
    Request spans are the per-hop names tracing.py emits; batch spans
    are the shared device_batch records; everything else kind="span"
    is operational (reload / checkpoint_save / ...)."""
    request, batch, ops = [], [], []
    for path in files:
        for rec in read_jsonl_counted(path, warn=False)[0]:
            if rec.get("kind") != "span":
                continue
            name = rec.get("name")
            if name == BATCH_SPAN_NAME:
                batch.append(rec)
            elif name in REQUEST_SPAN_NAMES:
                request.append(rec)
            else:
                ops.append(rec)
    return request, batch, ops


class TraceTree:
    """One request's assembled spans."""

    def __init__(self, trace: str, spans: list):
        self.trace = trace
        self.spans = spans
        self.by_id = {s["span"]: s for s in spans if "span" in s}
        self.children: dict = {}
        self.roots = []
        self.orphans = []
        for s in spans:
            parent = s.get("parent")
            if not parent:
                self.roots.append(s)
            elif parent in self.by_id:
                self.children.setdefault(parent, []).append(s)
            else:
                # the parent span never emitted (a dropped hop / a
                # losing hedge leg's abandoned router side) — the
                # subtree is kept, flagged, and excluded from
                # completeness
                self.orphans.append(s)

    @property
    def root(self):
        if not self.roots:
            return None
        # the router's "request" outranks a replica-local "server" root
        # (a direct-to-replica request has only the latter)
        for s in self.roots:
            if s.get("name") == "request":
                return s
        return self.roots[0]

    def kids(self, span: dict, name: str = "") -> list:
        out = self.children.get(span.get("span"), [])
        return [s for s in out if not name or s.get("name") == name] if name else out


def assemble(request_spans: list) -> dict:
    """{trace_id: TraceTree} over the request-path spans."""
    by_trace: dict = {}
    for s in request_spans:
        t = s.get("trace")
        if t:
            by_trace.setdefault(t, []).append(s)
    return {t: TraceTree(t, spans) for t, spans in by_trace.items()}


def critical_path(tree: TraceTree, batch_by_id: dict) -> dict:
    """The per-hop decomposition of one trace, in milliseconds.

    Returns {"total_ms", "status", "complete", "replica", categories...}.
    The math is deliberately first-order: wall-clock t0 anchors align
    processes on one host, durations are perf-counter-exact within a
    process, and every residual clamps at zero (clock skew must show up
    as a shrunken category, never a negative one)."""
    cats = {c: 0.0 for c in CATEGORIES}
    root = tree.root
    if root is None:
        return {"total_ms": 0.0, "status": None, "complete": False,
                "replica": None, **cats}
    total = float(root.get("dur_ms") or 0.0)
    status = root.get("status")
    server = None
    if root.get("name") == "request":
        attempts = sorted(
            tree.kids(root, "attempt"), key=lambda s: s.get("t0", 0.0)
        )
        # the winner is the 200 leg that FINISHED first (a losing
        # hedge/retry leg can also land a late 200 via the tracer's
        # late-span path — picking by start time would decompose the
        # request against the leg that lost the race)
        ok_legs = [a for a in attempts if a.get("status") == 200]
        winning = min(
            ok_legs,
            key=lambda a: a.get("t0", 0.0) + float(a.get("dur_ms") or 0.0) / 1e3,
            default=attempts[-1] if attempts else None,
        )
        if winning is not None:
            # everything before the winning leg started = retry cost
            # (failed legs, breaker consults, re-picks)
            cats["retry"] = max(
                (winning.get("t0", 0.0) - root.get("t0", 0.0)) * 1e3, 0.0
            )
            servers = tree.kids(winning, "server")
            server = servers[0] if servers else None
            a_dur = float(winning.get("dur_ms") or 0.0)
            if server is not None:
                cats["network"] = max(
                    a_dur - float(server.get("dur_ms") or 0.0), 0.0
                )
            else:
                # the replica side of this leg never emitted: the whole
                # leg is network/unobserved — honest, and exactly right
                # when the slowness WAS the network
                cats["network"] = a_dur
            cats["router_other"] = max(
                total - cats["retry"] - a_dur, 0.0
            )
    else:
        server = root
    complete = False
    if server is not None:
        s_dur = float(server.get("dur_ms") or 0.0)
        seen = 0.0
        for p in tree.kids(server, "parse"):
            cats["parse"] += float(p.get("dur_ms") or 0.0)
            seen += float(p.get("dur_ms") or 0.0)
        devices = tree.kids(server, "device")
        for q in tree.kids(server, "queue"):
            # queue wait splits by WHY the batch flushed: a deadline
            # flush means the request waited for the coalescing window
            # (latency floor), a size flush means it waited behind
            # backlog (overload)
            flush = None
            for d in devices:
                b = batch_by_id.get(d.get("batch"))
                if b is not None:
                    flush = b.get("flush")
                    break
            key = "window" if flush == "window" else "queue"
            cats[key] += float(q.get("dur_ms") or 0.0)
            seen += float(q.get("dur_ms") or 0.0)
        for d in devices:
            cats["device"] += float(d.get("dur_ms") or 0.0)
            seen += float(d.get("dur_ms") or 0.0)
            if d.get("batch") in batch_by_id:
                complete = True
        cats["server_other"] = max(s_dur - seen, 0.0)
    # a complete tree: one root, the winning chain reached a device
    # span whose batch link resolves, and nothing dangles mid-chain
    complete = complete and len(tree.roots) == 1
    return {
        "total_ms": total,
        "status": status,
        "complete": complete,
        "replica": (server or {}).get("replica", (server or {}).get("rank")),
        **cats,
    }


def decompose(trees: dict, batch_spans: list) -> list:
    batch_by_id = {b["span"]: b for b in batch_spans if "span" in b}
    rows = []
    for trace, tree in trees.items():
        row = critical_path(tree, batch_by_id)
        row["trace"] = trace
        rows.append(row)
    rows.sort(key=lambda r: r["total_ms"])
    return rows


def summarize(rows: list) -> dict:
    ok = [r for r in rows if r["status"] == 200]
    complete = [r for r in ok if r["complete"]]
    agg = {c: sum(r[c] for r in rows) for c in CATEGORIES}
    total = sum(r["total_ms"] for r in rows)
    per_replica: dict = {}
    for r in ok:
        rep = r["replica"]
        if rep is None:
            continue
        g = per_replica.setdefault(rep, {"requests": 0, "totals": [],
                                         **{c: 0.0 for c in CATEGORIES}})
        g["requests"] += 1
        g["totals"].append(r["total_ms"])
        for c in CATEGORIES:
            g[c] += r[c]
    for g in per_replica.values():
        ts = sorted(g.pop("totals"))
        g["p50_ms"] = round(ts[len(ts) // 2], 3) if ts else None
        g["p99_ms"] = round(ts[min(int(len(ts) * 0.99), len(ts) - 1)], 3) if ts else None
        for c in CATEGORIES:
            g[c] = round(g[c] / max(g["requests"], 1), 3)  # mean ms/request
    return {
        "traces": len(rows),
        "ok": len(ok),
        "complete": len(complete),
        "complete_frac": round(len(complete) / len(ok), 4) if ok else None,
        "total_ms_sum": round(total, 3),
        "per_hop_ms": {c: round(v, 3) for c, v in agg.items()},
        "per_hop_pct": {
            c: round(100.0 * v / total, 1) if total > 0 else 0.0
            for c, v in agg.items()
        },
        "per_replica": per_replica,
    }


def _exemplar(rows: list, q: float):
    ok = [r for r in rows if r["status"] == 200] or rows
    if not ok:
        return None
    return ok[min(int(len(ok) * q), len(ok) - 1)]


def render_report(rows: list, summary: dict, slow: int = 0) -> str:
    lines = [
        f"request_trace: {summary['traces']} trace(s), {summary['ok']} ok, "
        f"{summary['complete']} complete root->device-batch trees"
        + (f" ({summary['complete_frac'] * 100:.1f}% of ok)"
           if summary["complete_frac"] is not None else "")
    ]
    fmt = lambda v: f"{v:.3f}" if _finite(v) else "-"
    lines.append("")
    lines.append("critical path (aggregate + exemplars):")
    header = ("hop",) + tuple(
        f"{name}" for name in ("agg_ms", "agg_%", "p50_ms", "p99_ms")
    )
    p50 = _exemplar(rows, 0.50)
    p99 = _exemplar(rows, 0.99)
    table = [header]
    for c in CATEGORIES:
        table.append((
            c,
            fmt(summary["per_hop_ms"][c]),
            f"{summary['per_hop_pct'][c]:.1f}",
            fmt(p50[c]) if p50 else "-",
            fmt(p99[c]) if p99 else "-",
        ))
    table.append((
        "total",
        fmt(summary["total_ms_sum"]),
        "100.0",
        fmt(p50["total_ms"]) if p50 else "-",
        fmt(p99["total_ms"]) if p99 else "-",
    ))
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    for i, r in enumerate(table):
        lines.append("  " + "  ".join(c.rjust(w) for c, w in zip(r, widths)))
        if i == 0:
            lines.append("  " + "  ".join("-" * w for w in widths))
    if p50 is not None:
        lines.append(f"  p50 exemplar: trace {p50['trace']}")
    if p99 is not None:
        lines.append(f"  p99 exemplar: trace {p99['trace']}")
    if summary["per_replica"]:
        lines.append("")
        lines.append("per-replica (mean ms/request; the blame table):")
        rep_header = ("replica", "requests", "p50_ms", "p99_ms") + CATEGORIES
        rep_rows = [tuple(str(h) for h in rep_header)]
        for rep, g in sorted(summary["per_replica"].items(), key=str):
            rep_rows.append(tuple(
                fmt(x) if isinstance(x, float) else str(x)
                for x in (rep, g["requests"], g["p50_ms"], g["p99_ms"])
                + tuple(g[c] for c in CATEGORIES)
            ))
        widths = [max(len(r[i]) for r in rep_rows) for i in range(len(rep_header))]
        for i, r in enumerate(rep_rows):
            lines.append("  " + "  ".join(c.rjust(w) for c, w in zip(r, widths)))
            if i == 0:
                lines.append("  " + "  ".join("-" * w for w in widths))
    if slow > 0:
        lines.append("")
        lines.append(f"slowest {slow} exemplar(s):")
        for r in sorted(rows, key=lambda r: -r["total_ms"])[:slow]:
            hot = max(CATEGORIES, key=lambda c: r[c])
            lines.append(
                f"  {r['total_ms']:9.3f} ms  trace {r['trace']}  "
                f"status {r['status']}  replica {r['replica']}  "
                f"hot hop: {hot} ({r[hot]:.3f} ms)"
            )
    return "\n".join(lines)


# ----------------------------------------------------------------- timeline


def render_timeline(rows: list, op_spans: list) -> str:
    """Operational spans (reloads, checkpoint saves) overlaid against
    the request latency between them: each op line is followed by the
    request count / worst total in the interval up to the next op —
    the 'did the swap spike my p99' view."""
    ops = sorted(
        (o for o in op_spans if _finite(o.get("t0"))), key=lambda o: o["t0"]
    )
    reqs = sorted(
        (r for r in rows if _finite(r.get("t0_wall"))), key=lambda r: r["t0_wall"]
    )
    if not ops:
        return "timeline: no operational spans (reload/checkpoint) found"
    lines = ["timeline (ops overlaid on request latency):"]
    bounds = [o["t0"] for o in ops] + [float("inf")]
    t_base = min([ops[0]["t0"]] + ([reqs[0]["t0_wall"]] if reqs else []))

    def interval(lo, hi):
        window = [r for r in reqs if lo <= r["t0_wall"] < hi]
        if not window:
            return "no requests"
        worst = max(window, key=lambda r: r["total_ms"])
        return (f"{len(window)} request(s), worst {worst['total_ms']:.3f} ms "
                f"(trace {worst['trace']})")

    lines.append(f"  [+0.000s] ... {interval(-float('inf'), bounds[0])}")
    for i, o in enumerate(ops):
        who = o.get("replica", o.get("rank"))
        extra = " ".join(
            f"{k}={o[k]}" for k in ("step", "generation", "bytes") if k in o
        )
        lines.append(
            f"  [+{o['t0'] - t_base:.3f}s] {o.get('name')} "
            f"(replica/rank {who}, {o.get('dur_ms', 0):.1f} ms{', ' + extra if extra else ''})"
        )
        lines.append(f"      then: {interval(bounds[i], bounds[i + 1])}")
    return "\n".join(lines)


# ------------------------------------------------------------ chrome export


def chrome_events(trees: dict, batch_spans: list, op_spans: list) -> dict:
    """Trace-event JSON (Perfetto / chrome://tracing): one "X" complete
    event per span, pid = the emitting process stream (router / replica
    k / trainer rank), tid = the trace (so one request reads as one
    row). Timestamps are microseconds relative to the earliest span."""
    all_spans = [s for t in trees.values() for s in t.spans]
    all_spans += batch_spans + op_spans
    ts0 = min((s["t0"] for s in all_spans if _finite(s.get("t0"))),
              default=0.0)

    pids: dict = {}
    names: dict = {}

    def pid_of(s: dict) -> int:
        rep, rank = s.get("replica"), s.get("rank")
        label = (
            f"replica {rep}" if rep is not None
            else ("router" if rank == -1 else f"rank {rank}")
        )
        if label not in pids:
            pids[label] = len(pids) + 1
            names[pids[label]] = label
        return pids[label]

    tids: dict = {}

    def tid_of(trace) -> int:
        if trace not in tids:
            tids[trace] = len(tids) + 1
        return tids[trace]

    events = []
    for s in all_spans:
        if not (_finite(s.get("t0")) and _finite(s.get("dur_ms"))):
            continue
        args = {
            k: v for k, v in s.items()
            if k not in ("kind", "t0", "dur_ms", "ts") and _jsonable(v)
        }
        events.append({
            "name": s.get("name", "span"),
            "cat": "span",
            "ph": "X",
            "ts": round((s["t0"] - ts0) * 1e6, 1),
            "dur": round(s["dur_ms"] * 1e3, 1),
            "pid": pid_of(s),
            "tid": tid_of(s.get("trace", "?")),
            "args": args,
        })
    for pid, label in names.items():
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": label},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _jsonable(v) -> bool:
    return isinstance(v, (str, int, float, bool)) or v is None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="assemble request traces + critical paths from span JSONL"
    )
    ap.add_argument("paths", nargs="+", help="JSONL file(s) and/or run dir(s)")
    ap.add_argument("--json", default="", metavar="OUT",
                    help="machine-readable report ('-' = stdout)")
    ap.add_argument("--chrome", default="", metavar="OUT.json",
                    help="export Chrome trace-event JSON (Perfetto-viewable)")
    ap.add_argument("--timeline", action="store_true",
                    help="overlay reload/checkpoint spans on request latency")
    ap.add_argument("--slow", type=int, default=3,
                    help="print the N slowest exemplars (default 3; 0 = off)")
    ap.add_argument("--min-complete", type=float, default=0.0,
                    help="exit 4 unless >= this fraction of ok traces "
                         "assembled into complete root->device-batch trees "
                         "(the CI gate; e.g. 0.99)")
    args = ap.parse_args(argv)

    try:
        files = expand_paths(args.paths)
    except FileNotFoundError as e:
        print(f"request_trace: {e}", file=sys.stderr)
        return 2
    request_spans, batch_spans, op_spans = load_spans(files)
    if not request_spans and not op_spans:
        print(
            "request_trace: no kind=\"span\" records found (is "
            "serve.trace_sample_rate > 0?)", file=sys.stderr,
        )
        return 1

    trees = assemble(request_spans)
    rows = decompose(trees, batch_spans)
    # anchor each row's wall start for the timeline overlay
    for r in rows:
        root = trees[r["trace"]].root
        r["t0_wall"] = root.get("t0") if root else None
    summary = summarize(rows)

    print(render_report(rows, summary, slow=args.slow))
    if args.timeline:
        print()
        print(render_timeline(rows, op_spans))

    if args.chrome:
        out = chrome_events(trees, batch_spans, op_spans)
        with open(args.chrome, "w") as f:
            json.dump(out, f)
        print(f"request_trace: wrote {len(out['traceEvents'])} trace events "
              f"to {args.chrome}")

    if args.json:
        payload = json.dumps({
            **summary,
            "exemplars": {
                "p50": _exemplar(rows, 0.50),
                "p99": _exemplar(rows, 0.99),
            },
        })
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w") as f:
                f.write(payload + "\n")

    if args.min_complete > 0:
        frac = summary["complete_frac"]
        if frac is None or frac < args.min_complete:
            print(
                f"request_trace: FAIL: complete fraction "
                f"{frac if frac is not None else 'n/a'} < "
                f"{args.min_complete}", file=sys.stderr,
            )
            return 4
    return 0


if __name__ == "__main__":
    sys.exit(main())
