#!/usr/bin/env python3
"""Load generator for `xflow serve` (docs/SERVING.md).

Closed loop (default): `--concurrency` workers each keep exactly one
request in flight — the classic saturation probe; QPS is what the
server sustains. Open loop (`--rate R`): workers schedule arrivals at
a fixed aggregate rate regardless of completions — the tail-latency-
honest mode (a closed loop self-throttles when the server slows,
hiding queueing delay; the open loop keeps pushing like real traffic).

Rows come from a libffm file (`--data`; labels are stripped — serving
requests carry features only) or a synthesized pool. Every response's
`generation` is tracked, so a hot checkpoint reload mid-run shows up
as a generation flip in the report — tools/smoke_serve.sh gates on
exactly that (flip observed, zero errors, zero drops).

    python tools/serve_bench.py --url http://127.0.0.1:8000 --duration 10
    python tools/serve_bench.py --unix /tmp/serve.sock --rate 500 \
        --data /tmp/test-00000 --bench-json BENCH_SERVE.json

The `--bench-json` record is BENCH-shaped ({"metric": "serve_qps", ...}
with latency percentiles riding along) — the serving analog of
bench.py's training record, feeding the BENCH_SERVE.json trajectory.
Exit status: nonzero when any request errored (use in CI gates).
"""

from __future__ import annotations

import argparse
import http.client
import json
import socket
import sys
import threading
import time


class UnixHTTPConnection(http.client.HTTPConnection):
    """http.client over an AF_UNIX path (the colocated-client mode)."""

    def __init__(self, path: str, timeout: float = 30.0):
        super().__init__("localhost", timeout=timeout)
        self._path = path

    def connect(self):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(self.timeout)
        self.sock.connect(self._path)


def _connect(args):
    if args.unix:
        return UnixHTTPConnection(args.unix, timeout=args.timeout)
    host, _, port = args.url.rpartition("//")[2].partition(":")
    return http.client.HTTPConnection(
        host or "127.0.0.1", int(port or 80), timeout=args.timeout
    )


def load_rows(path: str, limit: int = 100000) -> list:
    """Feature rows from a libffm file: label stripped, features kept
    verbatim (the same tokens hash to the same slots server-side)."""
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            parts = line.split("\t", 1)
            if len(parts) == 1:
                parts = line.split(" ", 1)
            rows.append(parts[1] if len(parts) > 1 else parts[0])
            if len(rows) >= limit:
                break
    if not rows:
        raise SystemExit(f"serve_bench: no rows in {path!r}")
    return rows


def synth_rows(n: int = 1024, num_fields: int = 18) -> list:
    # deterministic pool: the bench must not depend on a data file
    return [
        " ".join(f"{f}:synth{(i * 31 + f * 7) % 997}" for f in range(num_fields))
        for i in range(n)
    ]


class Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.latencies: list = []
        self.requests = 0
        self.rows = 0
        self.errors = 0
        self.generations: list = []  # (t, gen) observations in order
        self.steps: set = set()

    def ok(self, t: float, lat_s: float, n_rows: int, gen: int, step: int):
        with self.lock:
            self.requests += 1
            self.rows += n_rows
            self.latencies.append(lat_s)
            if not self.generations or self.generations[-1][1] != gen:
                self.generations.append((t, gen))
            self.steps.add(step)

    def err(self):
        with self.lock:
            self.requests += 1
            self.errors += 1


def worker(args, rows, stats: Stats, deadline: float, interval_s: float, stop):
    conn = _connect(args)
    i = 0
    next_at = time.perf_counter()
    while not stop.is_set():
        now = time.perf_counter()
        if now >= deadline:
            break
        if interval_s > 0:  # open loop: hold the schedule
            if now < next_at:
                time.sleep(min(next_at - now, deadline - now))
                continue
            next_at += interval_s
        batch = [rows[(i * 13 + j) % len(rows)] for j in range(args.rows_per_request)]
        i += 1
        body = json.dumps({"rows": batch})
        t0 = time.perf_counter()
        try:
            conn.request(
                "POST", "/predict", body, {"Content-Type": "application/json"}
            )
            resp = conn.getresponse()
            payload = json.loads(resp.read())
            if resp.status != 200 or len(payload.get("pctr", [])) != len(batch):
                stats.err()
                continue
        except Exception:
            stats.err()
            try:
                conn.close()
            except Exception:
                pass
            conn = _connect(args)
            continue
        t1 = time.perf_counter()
        stats.ok(
            t1, t1 - t0, len(batch), payload.get("generation", 0),
            payload.get("step", -1),
        )
    try:
        conn.close()
    except Exception:
        pass


def percentile(xs: list, q: float) -> float:
    if not xs:
        return float("nan")
    xs = sorted(xs)
    idx = min(int(len(xs) * q / 100.0), len(xs) - 1)
    return xs[idx]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="loadgen for `xflow serve`")
    ap.add_argument("--url", default="http://127.0.0.1:8000")
    ap.add_argument("--unix", default="", help="AF_UNIX socket path (overrides --url)")
    ap.add_argument("--data", default="", help="libffm file to draw rows from "
                                               "(default: synthesized pool)")
    ap.add_argument("--duration", type=float, default=10.0, help="seconds")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--rate", type=float, default=0.0,
                    help="open-loop aggregate requests/s (0 = closed loop)")
    ap.add_argument("--rows-per-request", type=int, default=1)
    ap.add_argument("--num-fields", type=int, default=18,
                    help="fields in synthesized rows (ignored with --data)")
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--bench-json", default="",
                    help="write a BENCH-style serve perf JSON here ('-' = stdout)")
    args = ap.parse_args(argv)

    rows = load_rows(args.data) if args.data else synth_rows(num_fields=args.num_fields)
    stats = Stats()
    stop = threading.Event()
    # open loop: each worker holds rate/concurrency; closed loop: 0
    interval = args.concurrency / args.rate if args.rate > 0 else 0.0
    t0 = time.perf_counter()
    deadline = t0 + args.duration
    threads = [
        threading.Thread(
            target=worker, args=(args, rows, stats, deadline, interval, stop),
            daemon=True,
        )
        for _ in range(args.concurrency)
    ]
    for t in threads:
        t.start()
    try:
        for t in threads:
            t.join(timeout=args.duration + args.timeout + 10)
    except KeyboardInterrupt:
        stop.set()
    elapsed = time.perf_counter() - t0

    lat = stats.latencies
    gens = [g for _, g in stats.generations]
    rec = {
        "metric": "serve_qps",
        "value": round((stats.requests - stats.errors) / max(elapsed, 1e-9), 2),
        "unit": "requests/sec",
        "mode": f"open@{args.rate}/s" if args.rate > 0 else
                f"closed@{args.concurrency}",
        "requests": stats.requests,
        "errors": stats.errors,
        "rows": stats.rows,
        "rows_per_s": round(stats.rows / max(elapsed, 1e-9), 1),
        "p50_ms": round(percentile(lat, 50) * 1e3, 3),
        "p99_ms": round(percentile(lat, 99) * 1e3, 3),
        "duration_s": round(elapsed, 3),
        "rows_per_request": args.rows_per_request,
        # the hot-reload trail: distinct generations answered, in
        # arrival order; >1 entries = a reload flipped mid-run
        "generations": gens,
        "gen_flips": max(len(gens) - 1, 0),
        "steps": sorted(stats.steps),
    }
    out = json.dumps(rec)
    print(out)  # the one JSON line consumers parse
    if args.bench_json and args.bench_json != "-":  # '-' already printed
        with open(args.bench_json, "w") as f:
            f.write(out + "\n")
    return 1 if stats.errors else 0


if __name__ == "__main__":
    sys.exit(main())
