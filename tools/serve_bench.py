#!/usr/bin/env python3
"""Load generator for `xflow serve` (docs/SERVING.md).

Closed loop (default): `--concurrency` workers each keep exactly one
request in flight — the classic saturation probe; QPS is what the
server sustains. Open loop (`--rate R`): workers schedule arrivals at
a fixed aggregate rate regardless of completions — the tail-latency-
honest mode (a closed loop self-throttles when the server slows,
hiding queueing delay; the open loop keeps pushing like real traffic).

Rows come from a libffm file (`--data`; labels are stripped — serving
requests carry features only) or a synthesized pool. Every response's
`generation` is tracked, so a hot checkpoint reload mid-run shows up
as a generation flip in the report — tools/smoke_serve.sh gates on
exactly that (flip observed, zero errors, zero drops).

    python tools/serve_bench.py --url http://127.0.0.1:8000 --duration 10
    python tools/serve_bench.py --unix /tmp/serve.sock --rate 500 \
        --data /tmp/test-00000 --bench-json BENCH_SERVE.json

Client-side resilience knobs (the fleet chaos drill's measuring stick,
tools/smoke_serve_fleet.sh): `--retries N` resends after a connect
failure or 503 (the server's documented "retry later"), `--deadline-ms`
bounds one request's total budget, `--hedge-ms` duplicates a slow
request on a second connection (first answer wins). The record reports
`retried` / `retry_attempts` / `hedged` / `hedge_wins` /
`deadline_exceeded`.

The `--bench-json` record is BENCH-shaped ({"metric": "serve_qps", ...}
with latency percentiles riding along) — the serving analog of
bench.py's training record, feeding the BENCH_SERVE.json trajectory.
Exit status: nonzero when any request ULTIMATELY errored — a failure a
retry absorbed does not fail the run, an unabsorbed one does (use in
CI gates).
"""

from __future__ import annotations

import argparse
import http.client
import json
import socket
import sys
import threading
import time
import uuid


class UnixHTTPConnection(http.client.HTTPConnection):
    """http.client over an AF_UNIX path (the colocated-client mode)."""

    def __init__(self, path: str, timeout: float = 30.0):
        super().__init__("localhost", timeout=timeout)
        self._path = path

    def connect(self):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(self.timeout)
        self.sock.connect(self._path)


class _NoDelayHTTPConnection(http.client.HTTPConnection):
    """TCP_NODELAY on connect: a loadgen measuring tail latency must
    not let Nagle batch its own requests — without it, any send that
    straddles two segments waits on the server's delayed ACK (~40 ms
    on loopback), which would be charged to the server's p99."""

    def connect(self):
        super().connect()
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass


def _connect(args):
    if args.unix:
        return UnixHTTPConnection(args.unix, timeout=args.timeout)
    host, _, port = args.url.rpartition("//")[2].partition(":")
    return _NoDelayHTTPConnection(
        host or "127.0.0.1", int(port or 80), timeout=args.timeout
    )


def load_rows(path: str, limit: int = 100000) -> list:
    """Feature rows from a libffm file: label stripped, features kept
    verbatim (the same tokens hash to the same slots server-side)."""
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            parts = line.split("\t", 1)
            if len(parts) == 1:
                parts = line.split(" ", 1)
            rows.append(parts[1] if len(parts) > 1 else parts[0])
            if len(rows) >= limit:
                break
    if not rows:
        raise SystemExit(f"serve_bench: no rows in {path!r}")
    return rows


def synth_rows(n: int = 1024, num_fields: int = 18) -> list:
    # deterministic pool: the bench must not depend on a data file
    return [
        " ".join(f"{f}:synth{(i * 31 + f * 7) % 997}" for f in range(num_fields))
        for i in range(n)
    ]


class Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.latencies: list = []
        self.requests = 0
        self.rows = 0
        self.errors = 0
        self.retried = 0  # requests that succeeded only after >= 1 retry
        self.retry_attempts = 0  # extra sends the retries cost
        self.hedged = 0  # hedge legs launched
        self.hedge_wins = 0  # hedge legs that answered first
        self.deadline_exceeded = 0  # requests abandoned at --deadline-ms
        self.trace_echo_miss = 0  # --trace responses missing the id echo
        self.generations: list = []  # (t, gen) observations in order
        self.steps: set = set()

    def ok(self, t: float, lat_s: float, n_rows: int, gen: int, step: int,
           retries: int = 0):
        with self.lock:
            self.requests += 1
            self.rows += n_rows
            self.latencies.append(lat_s)
            if retries:
                self.retried += 1
                self.retry_attempts += retries
            if not self.generations or self.generations[-1][1] != gen:
                self.generations.append((t, gen))
            self.steps.add(step)

    def err(self, retries: int = 0, deadline: bool = False):
        with self.lock:
            self.requests += 1
            self.errors += 1
            self.retry_attempts += retries
            if deadline:
                self.deadline_exceeded += 1

    def hedge(self, won: bool):
        with self.lock:
            self.hedged += 1
            if won:
                self.hedge_wins += 1

    def echo_miss(self):
        with self.lock:
            self.trace_echo_miss += 1


class Client:
    """One worker's connection + the client-side resilience knobs:
    `--retries` (reconnect + resend on a connect failure or 503 — the
    server's documented 'retry later'), `--deadline-ms` (per-request
    budget the retries must fit in; exceeded = deadline_exceeded
    error), `--hedge-ms` (a request outstanding that long fires a
    duplicate on a second connection, first answer wins). A
    retry-ABSORBED failure is not an error — the nonzero-exit contract
    counts only requests that ultimately failed."""

    def __init__(self, args):
        self._args = args
        self._conn = _connect(args)
        self._hedge_conn = None

    def close(self):
        for c in (self._conn, self._hedge_conn):
            if c is not None:
                try:
                    c.close()
                except Exception:
                    pass

    def _reset_conns(self):
        """After a hedge, an abandoned leg may still own its
        connection's in-flight response — both conns restart clean so
        the next request never trips CannotSendRequest."""
        self.close()
        self._conn = _connect(self._args)
        self._hedge_conn = None

    def _send_once(self, conn, body: str, timeout_s: float = 0.0,
                   trace_id: str = ""):
        """(status, payload, echoed_trace_id) over one connection;
        raises on transport failure (caller reconnects). `timeout_s` >
        0 bounds the socket wait — the --deadline-ms budget reaches the
        transport, so a wedged replica costs the budget, not
        --timeout. `trace_id` rides the X-Trace-Id header; the echo is
        whatever the response header carried ("" = none)."""
        if timeout_s > 0:
            conn.timeout = timeout_s
            if conn.sock is not None:
                conn.sock.settimeout(timeout_s)
        headers = {"Content-Type": "application/json"}
        if trace_id:
            headers["X-Trace-Id"] = trace_id
        conn.request("POST", "/predict", body, headers)
        resp = conn.getresponse()
        echo = resp.getheader("X-Trace-Id") or ""
        return resp.status, json.loads(resp.read()), echo

    def _send_hedged(self, body: str, stats: Stats, timeout_s: float,
                     trace_id: str = ""):
        """Primary leg on the main connection; after --hedge-ms with no
        answer, a duplicate on the hedge connection — first answer
        wins. Transport failures surface as status 599 (retryable)."""
        import queue

        results: "queue.Queue" = queue.Queue()
        # timeout_s is the request's remaining --deadline-ms budget:
        # every wait below is bounded by this absolute point, so a
        # hedged request never overruns the deadline it measures
        t_end = time.perf_counter() + timeout_s

        def leg(conn, tag):
            # each leg is its OWN request to the server, so under
            # --trace the hedge leg carries its own fresh id — two
            # requests sharing one id would open two root spans and
            # assemble as a split tree (the metrics_report --check
            # gate). The echo is verified per leg and normalized to
            # the caller's id so send()'s round-trip check sees one
            # verdict whichever leg won.
            ltid = (uuid.uuid4().hex[:16]
                    if (trace_id and tag == "hedge") else trace_id)
            try:
                # the budget reaches BOTH legs' sockets — an abandoned
                # leg against a wedged replica unblocks at the deadline,
                # not at --timeout, so blocked threads/sockets don't
                # pile up under sustained wedge
                status, payload, echo = self._send_once(
                    conn, body, timeout_s, trace_id=ltid
                )
                if ltid and echo == ltid:
                    echo = trace_id  # round trip verified on this leg
                results.put((tag, (status, payload, echo)))
            except Exception as e:
                try:
                    conn.close()
                except Exception:
                    pass
                results.put((tag, (599, {"error": str(e)}, "")))

        t = threading.Thread(target=leg, args=(self._conn, "primary"),
                             daemon=True)
        t.start()
        try:
            tag, got = results.get(
                timeout=min(self._args.hedge_ms / 1e3, timeout_s)
            )
            return got, False
        except queue.Empty:
            pass
        if self._hedge_conn is None:
            self._hedge_conn = _connect(self._args)
        threading.Thread(
            target=leg, args=(self._hedge_conn, "hedge"), daemon=True
        ).start()
        first = None
        for _ in range(2):
            left = t_end - time.perf_counter()
            if left <= 0:
                break
            try:
                tag, got = results.get(timeout=left)
            except queue.Empty:
                break
            if got[0] == 200:
                stats.hedge(won=tag == "hedge")
                # a leg failed underneath a conn this Client reuses:
                # both conns get torn down lazily on their own errors
                return got, True
            if first is None:
                first = got
        if first is None:
            first = (599, {"error": "hedged request timed out"}, "")
        stats.hedge(won=False)
        return first, True

    def send(self, body: str, n_rows: int, stats: Stats):
        """One logical request through retries/deadline/hedging;
        records into `stats`. Returns True when it ultimately
        succeeded. Under --trace, every TRANSMIT gets a fresh
        X-Trace-Id (a client-level retry is a new request to the
        router — one trace id, one root span) and the final response's
        echo is verified against what was sent."""
        a = self._args
        if isinstance(body, str):
            # bytes bodies ride http.client's single-sendall path
            # (headers + body in one segment); a str body is sent as a
            # second send() and Nagle holds it for the delayed ACK
            body = body.encode("utf-8")
        t0 = time.perf_counter()
        budget = a.deadline_ms / 1e3 if a.deadline_ms > 0 else float("inf")
        retries_used = 0
        while True:
            left = budget - (time.perf_counter() - t0)
            if left <= 0:
                stats.err(retries=retries_used, deadline=True)
                return False
            tid = uuid.uuid4().hex[:16] if a.trace else ""
            echo = ""
            try:
                if a.hedge_ms > 0:
                    (status, payload, echo), hedged = self._send_hedged(
                        body, stats, min(left, a.timeout), trace_id=tid
                    )
                    if hedged:
                        self._reset_conns()
                else:
                    status, payload, echo = self._send_once(
                        self._conn, body, timeout_s=min(left, a.timeout),
                        trace_id=tid,
                    )
            except Exception:
                status, payload = 599, None
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = _connect(a)
            if status == 200 and len(payload.get("pctr", [])) == n_rows:
                if tid and echo != tid:
                    # the round-trip assert: a 200 that lost (or
                    # rewrote) its trace id means the id cannot join
                    # client-side latency to the server-side spans —
                    # counted, and it fails the run (nonzero exit)
                    stats.echo_miss()
                t1 = time.perf_counter()
                stats.ok(
                    t1, t1 - t0, n_rows, payload.get("generation", 0),
                    payload.get("step", -1), retries=retries_used,
                )
                return True
            if status in (503, 599) and retries_used < a.retries:
                # retryable (load shed / transport); the server asked
                # for "retry later" — honor it with a short pause (a
                # zero-delay retry loop would hammer a shedding server
                # with the exact stampede the 503 tried to stop)
                retries_used += 1
                time.sleep(min(a.retry_backoff_ms / 1e3,
                               max(budget - (time.perf_counter() - t0), 0)))
                continue
            stats.err(retries=retries_used)
            return False


def worker(args, rows, stats: Stats, deadline: float, interval_s: float, stop):
    client = Client(args)
    i = 0
    next_at = time.perf_counter()
    while not stop.is_set():
        now = time.perf_counter()
        if now >= deadline:
            break
        if interval_s > 0:  # open loop: hold the schedule
            if now < next_at:
                time.sleep(min(next_at - now, deadline - now))
                continue
            next_at += interval_s
        batch = [rows[(i * 13 + j) % len(rows)] for j in range(args.rows_per_request)]
        i += 1
        client.send(json.dumps({"rows": batch}), len(batch), stats)
    client.close()


def percentile(xs: list, q: float) -> float:
    if not xs:
        return float("nan")
    xs = sorted(xs)
    idx = min(int(len(xs) * q / 100.0), len(xs) - 1)
    return xs[idx]


def slo_attainment_pct(latencies_s: list, slo_ms: float) -> float:
    """Share (0..100) of successful requests answered within `slo_ms`.
    Empty = 0.0 — a run that answered nothing attained nothing (the
    --min-attainment gate must fail it, not divide by zero)."""
    if not latencies_s:
        return 0.0
    n = sum(1 for lat in latencies_s if lat * 1e3 <= slo_ms)
    return round(100.0 * n / len(latencies_s), 2)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="loadgen for `xflow serve`")
    ap.add_argument("--url", default="http://127.0.0.1:8000")
    ap.add_argument("--unix", default="", help="AF_UNIX socket path (overrides --url)")
    ap.add_argument("--data", default="", help="libffm file to draw rows from "
                                               "(default: synthesized pool)")
    ap.add_argument("--duration", type=float, default=10.0, help="seconds")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--rate", type=float, default=0.0,
                    help="open-loop aggregate requests/s (0 = closed loop)")
    ap.add_argument("--rows-per-request", type=int, default=1)
    ap.add_argument("--num-fields", type=int, default=18,
                    help="fields in synthesized rows (ignored with --data)")
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--retries", type=int, default=0,
                    help="resend a request up to N times after a connect "
                         "failure or 503 (the server's 'retry later'); an "
                         "absorbed retry is NOT an error — only requests "
                         "that ultimately fail trip the nonzero exit")
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="per-request budget the retries must fit in "
                         "(0 = none); exceeded = deadline_exceeded error")
    ap.add_argument("--retry-backoff-ms", type=float, default=50.0,
                    help="pause before each retry (default 50)")
    ap.add_argument("--hedge-ms", type=float, default=0.0,
                    help="fire a duplicate request on a second connection "
                         "after this long with no answer; first answer "
                         "wins (0 = off)")
    ap.add_argument("--trace", action="store_true",
                    help="send a fresh X-Trace-Id on every request and "
                         "assert the response echoes it (the tracing "
                         "round-trip gate, docs/OBSERVABILITY.md); an echo "
                         "miss fails the run")
    ap.add_argument("--trace-sample-rate", type=float, default=0.0,
                    help="the server-side serve.trace_sample_rate this run "
                         "drove (stamped into the bench record so the "
                         "BENCH_TRACE trajectory notes tracing overhead; "
                         "> 0 implies --trace)")
    ap.add_argument("--slo-ms", type=float, default=0.0,
                    help="the serving SLO this run is judged against: "
                         "stamp slo_attainment_pct (share of successful "
                         "requests answered within this many ms) into the "
                         "bench record (0 = no SLO accounting)")
    ap.add_argument("--min-attainment", type=float, default=0.0,
                    help="with --slo-ms: exit nonzero when "
                         "slo_attainment_pct lands below this percentage "
                         "(the CI attainment gate; 0 = report only)")
    ap.add_argument("--round", type=int, default=None,
                    help="perf-ledger round to stamp into the record "
                         "(tools/perf_ledger.py reads it when the filename "
                         "carries no _rNN suffix)")
    ap.add_argument("--bench-json", default="",
                    help="write a BENCH-style serve perf JSON here ('-' = stdout)")
    args = ap.parse_args(argv)
    if args.trace_sample_rate > 0:
        args.trace = True

    rows = load_rows(args.data) if args.data else synth_rows(num_fields=args.num_fields)
    stats = Stats()
    stop = threading.Event()
    # open loop: each worker holds rate/concurrency; closed loop: 0
    interval = args.concurrency / args.rate if args.rate > 0 else 0.0
    t0 = time.perf_counter()
    deadline = t0 + args.duration
    threads = [
        threading.Thread(
            target=worker, args=(args, rows, stats, deadline, interval, stop),
            daemon=True,
        )
        for _ in range(args.concurrency)
    ]
    for t in threads:
        t.start()
    try:
        for t in threads:
            t.join(timeout=args.duration + args.timeout + 10)
    except KeyboardInterrupt:
        stop.set()
    elapsed = time.perf_counter() - t0

    lat = stats.latencies
    gens = [g for _, g in stats.generations]
    rec = {
        "metric": "serve_qps",
        "value": round((stats.requests - stats.errors) / max(elapsed, 1e-9), 2),
        "unit": "requests/sec",
        "mode": f"open@{args.rate}/s" if args.rate > 0 else
                f"closed@{args.concurrency}",
        "requests": stats.requests,
        "errors": stats.errors,
        "rows": stats.rows,
        "rows_per_s": round(stats.rows / max(elapsed, 1e-9), 1),
        "p50_ms": round(percentile(lat, 50) * 1e3, 3),
        "p99_ms": round(percentile(lat, 99) * 1e3, 3),
        "duration_s": round(elapsed, 3),
        "rows_per_request": args.rows_per_request,
        # client-side resilience trail: failures the retries ABSORBED
        # (requests that still succeeded), the extra sends they cost,
        # hedging activity, and requests abandoned at --deadline-ms
        # (those DO count in errors — an unabsorbed failure)
        "retried": stats.retried,
        "retry_attempts": stats.retry_attempts,
        "hedged": stats.hedged,
        "hedge_wins": stats.hedge_wins,
        "deadline_exceeded": stats.deadline_exceeded,
        # the tracing trail (--trace): whether ids rode the requests,
        # the server-side sample rate this run drove (so BENCH_TRACE
        # datapoints note tracing overhead), and round-trip misses
        "traced": bool(args.trace),
        "trace_sample_rate": args.trace_sample_rate,
        "trace_echo_miss": stats.trace_echo_miss,
        # the hot-reload trail: distinct generations answered, in
        # arrival order; >1 entries = a reload flipped mid-run
        "generations": gens,
        "gen_flips": max(len(gens) - 1, 0),
        "steps": sorted(stats.steps),
    }
    attainment = None
    if args.slo_ms > 0:
        # the SLO trail (docs/SERVING.md "Autotuning"): which target the
        # run was judged against and what share of answers met it — the
        # per-request truth the p99-at-SLO ledger groups summarize
        attainment = slo_attainment_pct(lat, args.slo_ms)
        rec["slo_ms"] = args.slo_ms
        rec["slo_attainment_pct"] = attainment
    if args.round is not None:
        rec["round"] = args.round
    out = json.dumps(rec)
    print(out)  # the one JSON line consumers parse
    if args.bench_json and args.bench_json != "-":  # '-' already printed
        with open(args.bench_json, "w") as f:
            f.write(out + "\n")
    if (args.min_attainment > 0 and attainment is not None
            and attainment < args.min_attainment):
        print(
            f"serve_bench: SLO attainment {attainment}% < "
            f"--min-attainment {args.min_attainment}% "
            f"(slo {args.slo_ms} ms)",
            file=sys.stderr,
        )
        return 1
    # an echo miss is a FAILED round trip even when the predict
    # succeeded — the trace id is the join key the whole layer is for
    return 1 if (stats.errors or stats.trace_echo_miss) else 0


if __name__ == "__main__":
    sys.exit(main())
