#!/usr/bin/env bash
# Elastic-recovery smoke gate (docs/ROBUSTNESS.md "Elastic recovery"):
#
# 1. A clean 50-step supervised launch-local run (telemetry + heartbeats
#    + checkpoints on) — the steady-state path with the supervision
#    loop, generation stamping, and data_state writes all active. Gates
#    on `metrics_report.py --check`, emits the per-PR bench datapoint
#    (BENCH_r07.json, the docs/PERF.md "Bench trajectory" convention) so
#    the backoff/stamping machinery is shown to add no steady-state
#    throughput regression, and self-checks `--regress` against it.
# 2. The kill-and-recover drill: the same job with an injected SIGKILL
#    of the rank at step 30 (XFLOW_FAULT_KILL_STEP, on a checkpoint
#    boundary) under --max-restarts 2. The job must auto-restart without
#    operator action, restore the committed step-30 checkpoint, resume
#    the data stream at the stored offset, and finish with the exact
#    total example count (the final checkpoint's data_state records
#    cumulative examples across generations — 3200, every row exactly
#    once). Gates on exit code 0, `--check` accepting the
#    multi-generation stream, and the data_state accounting.
#
# Standalone:    bash tools/smoke_elastic.sh [workdir]
# From pytest:   tests/test_elastic.py::test_smoke_elastic_script
#
# With no workdir argument a temp dir is created and cleaned up.
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
# bench datapoint destination: the repo root ONLY standalone (the
# per-PR record); under pytest it stays in the workdir so test runs
# never rewrite the committed BENCH_r07.json with machine-local numbers
BENCH_OUT="$ROOT/BENCH_r07.json"
if [ -z "$WORK" ]; then
    WORK="$(mktemp -d)"
    trap 'rm -rf "$WORK"' EXIT
else
    BENCH_OUT="$WORK/BENCH_r07.json"
fi

export JAX_PLATFORMS=cpu

# 3200 rows / batch 64 = 50 steps in one epoch
python -m xflow_tpu gen-data "$WORK/train" --shards 1 --rows 3200 \
    --fields 6 --ids-per-field 50 --seed 0 >/dev/null

TRAIN_ARGS=(
    --train "$WORK/train" --model lr --epochs 1
    --batch-size 64 --log2-slots 12 --no-mesh
    --set model.num_fields=6
    --set data.max_nnz=8
    --set train.pred_dump=false
    --set train.log_every=10
    --set train.heartbeat_every=10
    --set train.checkpoint_every=10
)

# ---- 1. clean supervised run: steady-state throughput datapoint ------------
python -m xflow_tpu launch-local --num-processes 1 \
    --max-restarts 1 --restart-backoff 0.2 \
    --run-dir "$WORK/run_clean" -- \
    "${TRAIN_ARGS[@]}" --checkpoint-dir "$WORK/ck_clean" >/dev/null

python tools/metrics_report.py "$WORK/run_clean" --check
python tools/metrics_report.py "$WORK/run_clean" --bench-json "$BENCH_OUT"
# regression self-check: a run can never regress against itself
python tools/metrics_report.py "$WORK/run_clean" --regress "$BENCH_OUT" >/dev/null

# ---- 2. kill-and-recover drill ---------------------------------------------
# SIGKILL the rank the moment step 30 completes (right after its
# checkpoint committed); the supervisor must relaunch with resume and
# the job must still exit 0
XFLOW_FAULT_KILL_STEP=30 \
python -m xflow_tpu launch-local --num-processes 1 \
    --max-restarts 2 --restart-backoff 0.2 \
    --run-dir "$WORK/run_kill" -- \
    "${TRAIN_ARGS[@]}" --checkpoint-dir "$WORK/ck_kill" >/dev/null

# the multi-generation stream passes the schema gate
python tools/metrics_report.py "$WORK/run_kill" --check
python tools/metrics_report.py "$WORK/run_kill" --health >/dev/null

# exact accounting: the final checkpoint is step 50 with a completed
# data_state whose cumulative example count covers every row exactly
# once (no replay: the kill landed on the committed step-30 boundary),
# and the metrics streams really span two generations
python - "$WORK" <<'EOF'
import json, os, sys
from xflow_tpu.jsonl import read_jsonl
from xflow_tpu.train.checkpoint import latest_step, read_data_state

work = sys.argv[1]
step = latest_step(os.path.join(work, "ck_kill"))
assert step == 50, f"final committed step {step} != 50"
ds = read_data_state(os.path.join(work, "ck_kill"), step)
assert ds and ds["completed"], f"data_state not completed: {ds}"
assert ds["examples"] == 3200, f"examples {ds['examples']} != 3200 (replay or loss)"
recs = read_jsonl(os.path.join(work, "run_kill", "metrics_rank0.jsonl"))
gens = {r.get("gen", 0) for r in recs}
assert gens == {0, 1}, f"expected generations {{0, 1}}, got {gens}"
print("smoke_elastic: kill drill accounting OK "
      f"(step {step}, examples {ds['examples']}, generations {sorted(gens)})")
EOF
# repo-root hygiene: running the tools from the root must leave no
# stray artifact dirs behind (tools/__pycache__ and friends)
rm -rf "$ROOT/tools/__pycache__" "$ROOT/__pycache__"

echo "smoke_elastic: OK"
