"""Probe: can a Pallas scalar-core loop beat XLA's ~43 ms occurrence→row
scatter (docs/PERF.md "row-reduction kernel" lever)?

The op: accumulate vals [CH, Np] (slot-sorted order, random rows) into
out [B, CH] by row id. XLA's scatter does ~1 ns/element; the hope is a
VMEM-resident [B, CH] accumulator + per-occurrence dynamic-sublane
read-modify-write at a few cycles per occurrence.

Measures:
  A. compile + correctness of dynamic-sublane RMW (acc[r, :] += v)
  B. throughput vs the XLA segment-sum at bench shapes
"""

import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = 65536
    CH = 24  # padded channel count (21 used)
    C = 512  # chunk
    Np = 2098176  # padded_len(65536*32)
    K = 4  # batches in the scan

    rng = np.random.default_rng(0)
    rows = rng.integers(0, B, (K, Np)).astype(np.int32)
    vals = rng.normal(size=(K, CH, Np)).astype(np.float32)

    n_chunks = Np // C

    def kernel(rows_ref, vals_ref, out_ref, acc2, vchunk, vt_ref, rchunk, sem_v, sem_r):
        out_ref[:, :] = jnp.zeros((B, CH), jnp.float32)
        acc2[:, :] = jnp.zeros((B, CH), jnp.float32)

        def chunk_step(c, carry):
            o = c * C
            cp_r = pltpu.make_async_copy(rows_ref.at[:, pl.ds(o, C)], rchunk, sem_r)
            cp_r.start()
            cp_v = pltpu.make_async_copy(vals_ref.at[:, pl.ds(o, C)], vchunk, sem_v)
            cp_v.start()
            cp_r.wait()
            cp_v.wait()
            vt_ref[:, :] = vchunk[:, :].T  # [C, CH] staged for row reads

            def inner(i, carry2):
                r0 = rchunk[0, 2 * i]
                r1 = rchunk[0, 2 * i + 1]
                out_ref[pl.ds(r0, 1), :] += vt_ref[pl.ds(2 * i, 1), :]
                acc2[pl.ds(r1, 1), :] += vt_ref[pl.ds(2 * i + 1, 1), :]
                return carry2

            jax.lax.fori_loop(0, C // 2, inner, 0)
            return carry

        jax.lax.fori_loop(0, n_chunks, chunk_step, 0)
        out_ref[:, :] += acc2[:, :]

    def rowsum_pallas(rows1, vals1):
        return pl.pallas_call(
            kernel,
            grid=(1,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((B, CH), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((B, CH), jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((B, CH), jnp.float32),
                pltpu.VMEM((CH, C), jnp.float32),
                pltpu.VMEM((C, CH), jnp.float32),
                pltpu.SMEM((1, C), jnp.int32),
                pltpu.SemaphoreType.DMA(()),
                pltpu.SemaphoreType.DMA(()),
            ],
        )(rows1.reshape(1, Np), vals1)

    # correctness on a small case first (interpret on CPU would be slow;
    # run tiny on device)
    try:
        jit_rowsum = jax.jit(rowsum_pallas)
        small_out = jit_rowsum(jnp.asarray(rows[0]), jnp.asarray(vals[0]))
        got = np.asarray(small_out)
    except Exception as e:
        print(f"COMPILE/RUN FAIL: {str(e).splitlines()[0][:300]}")
        return 1
    want = np.zeros((B, CH), np.float32)
    np.add.at(want, rows[0], vals[0].T)
    err = np.abs(got - want).max()
    print(f"correctness: max abs err = {err:.2e}")

    @jax.jit
    def run_pallas(rows, vals):
        def body(c, b):
            out = rowsum_pallas(b[0], b[1])
            return c + out[::97, 0].sum() + out[::89, 5].sum(), None

        return jax.lax.scan(body, 0.0, (rows, vals))[0]

    @jax.jit
    def run_xla(rows, vals):
        def body(c, b):
            out = jax.ops.segment_sum(b[1].T, b[0], num_segments=B)
            return c + out[::97, 0].sum() + out[::89, 5].sum(), None

        return jax.lax.scan(body, 0.0, (rows, vals))[0]

    jrows, jvals = jnp.asarray(rows), jnp.asarray(vals)
    for name, fn in [("pallas scalar-RMW", run_pallas), ("xla segment_sum", run_xla)]:
        out = fn(jrows, jvals)
        _ = float(out)
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn(jrows, jvals)
            _ = float(out)
            best = min(best, (time.perf_counter() - t0) / K)
        print(f"{name}: {best*1e3:.1f} ms ({best/Np*1e9:.2f} ns/occurrence)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
