"""Probe: can a Pallas scalar-core loop beat XLA's occurrence→row
scatter (docs/PERF.md "row-reduction kernel" lever)?

Retired to a thin wrapper: the implementation lives in the unified
microbench lab (`xflow_tpu/tools/bench_lab.py --suite rowsum`). This
CLI keeps working:

    python tools/rowsum_probe.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xflow_tpu.tools.bench_lab import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--suite", "rowsum"] + sys.argv[1:]))
