#!/usr/bin/env python
"""xflowlint — project-native static analysis for xflow-tpu.

Runs the xflow_tpu/analysis passes (docs/STATIC_ANALYSIS.md) over the
repo (or explicit paths) and gates against the checked-in baseline.
Full-tree runs also run the IR tier (analysis/ir.py): the engine
builders' jitted programs are lowered to jaxprs in a pinned CPU
subprocess (trace-only, no execution) and checked semantically
(XF801–XF804); where jax (or an importable tree) is absent the IR tier
degrades to a notice and every AST-tier rule still runs.

    python tools/xflowlint.py                       # full repo, baselined
    python tools/xflowlint.py xflow_tpu/serve       # subset (AST tier only)
    python tools/xflowlint.py --rules XF301         # one rule family
    python tools/xflowlint.py --changed -j 8        # pre-commit fast path
    python tools/xflowlint.py --write-baseline --reason "..."
    python tools/xflowlint.py --check-contracts     # engine-contract gate
    python tools/xflowlint.py --check-worklist      # fusion-worklist gate
    python tools/xflowlint.py --list-rules

Exit codes (tools/smoke_lint.sh relies on these):
    0  clean — no unbaselined findings, no stale baseline entries
    1  NEW findings (not in the baseline)
    2  STALE baseline entries (a fixed finding must leave the baseline)
    3  usage / internal error (incl. a baseline entry still carrying
       the "TODO: justify or fix" placeholder reason)
    4  ARTIFACT drift — the extracted engine-contract matrix differs
       from tools/engine_contracts.json, or the extracted fusion
       worklist differs from tools/fusion_worklist.json (regenerate
       with --write-contracts / --write-worklist and review the diff)

The baseline (tools/xflowlint_baseline.json) makes the gate fail on
*growth*, not existence; inline `# xflowlint: disable=RULE` handles
intentional single sites (with a nearby comment saying why).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from xflow_tpu.analysis.core import (  # noqa: E402
    IR_RULES, PASS_REGISTRY, Baseline, Finding, Project, run_passes,
)

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "xflowlint_baseline.json")
REASON_PLACEHOLDER = "TODO: justify or fix"


def _changed_paths(root: str) -> list:
    """Files git considers changed (worktree vs HEAD, staged, and
    untracked), filtered to the default lintable set. The pre-commit
    fast path: lint what the commit touches, gate growth against the
    repo baseline."""
    import subprocess

    out: set = set()
    cmds = (
        ["git", "diff", "--name-only", "HEAD", "--"],
        ["git", "diff", "--name-only", "--cached", "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    )
    for cmd in cmds:
        try:
            r = subprocess.run(cmd, cwd=root, capture_output=True,
                               text=True, timeout=30)
        except Exception:
            continue
        if r.returncode != 0:
            continue
        out.update(ln.strip() for ln in r.stdout.splitlines() if ln.strip())
    keep = []
    for rel in sorted(out):
        norm = rel.replace(os.sep, "/")
        if "tests/fixtures" in norm:
            continue
        lintable = (
            (norm.startswith("xflow_tpu/") and norm.endswith(".py"))
            or (norm.startswith("tools/") and "/" not in norm[len("tools/"):]
                and norm.endswith((".py", ".sh")))
            or norm in ("bench.py", "conftest.py")
        )
        if lintable and os.path.exists(os.path.join(root, rel)):
            keep.append(os.path.join(root, rel))
    return keep


def _contract_artifact_path(root: str) -> str:
    return os.path.join(root, "tools", "engine_contracts.json")


def _worklist_artifact_path(root: str) -> str:
    from xflow_tpu.analysis.passes.ir_rules import WORKLIST_REL

    return os.path.join(root, *WORKLIST_REL.split("/"))


def _ir_facts_or_notice(root: str, no_ir: bool):
    """-> facts dict or None (with the skip notice already printed)."""
    if no_ir:
        print("xflowlint: IR tier disabled (--no-ir)", file=sys.stderr)
        return None
    from xflow_tpu.analysis.passes.ir_rules import ir_facts

    facts, reason = ir_facts(root)
    if facts is None:
        print(f"xflowlint: NOTICE — IR tier skipped ({reason}); "
              "AST-tier results only", file=sys.stderr)
    return facts


def _artifact_drift(kind: str, path: str, on_disk: str, rendered: str,
                    regen_flag: str) -> int:
    import difflib

    diff = difflib.unified_diff(
        on_disk.splitlines(), rendered.splitlines(),
        fromfile="checked-in", tofile="extracted", lineterm="", n=2)
    lines = list(diff)[:40]
    print(f"xflowlint: {kind} DRIFT — the extracted artifact differs "
          f"from {path}:", file=sys.stderr)
    for ln in lines:
        print(f"  {ln}", file=sys.stderr)
    print(f"xflowlint: if the change is intended, regenerate with "
          f"`python tools/xflowlint.py {regen_flag}` and review the "
          "diff (it is a machine-checked acceptance oracle)",
          file=sys.stderr)
    return 4


def _contracts_mode(args, write: bool) -> int:
    """--write-contracts / --check-contracts: the engine-contract
    matrix gate (docs/DISTRIBUTED.md "Engine contract matrix"). v2:
    the matrix carries a per-program jaxpr section (op histogram,
    gather/scatter counts, dtype census, flop/byte estimates) from the
    IR tier; where the IR tier is unavailable the section is preserved
    (write) or excluded from the comparison (check), with a notice."""
    from xflow_tpu.analysis.passes.ir_rules import ir_contract_section
    from xflow_tpu.analysis.passes.sharding_contract import (
        ENGINE_MODULES, MESH_MODULE, extract_contracts, render_artifact,
    )

    # only the builder sources (+ the mesh axis anchor) feed the AST
    # matrix — loading them alone keeps the pre-commit contract check
    # cheap (the IR tier imports the real modules in its own process)
    wanted = [os.path.join(args.root, *rel.split("/"))
              for rel in ENGINE_MODULES + (MESH_MODULE,)]
    project = Project.load(args.root,
                           [p for p in wanted if os.path.exists(p)] or None)
    contracts = extract_contracts(project)
    missing = [m for m in ENGINE_MODULES if m not in contracts["engines"]]
    if missing:
        print(
            "xflowlint: engine builders missing from the source tree: "
            + ", ".join(missing), file=sys.stderr)
        return 3
    facts = _ir_facts_or_notice(args.root, args.no_ir)
    ir_ok = facts is not None
    if ir_ok and facts.get("errors"):
        # a program that failed to lower would silently vanish from the
        # ir_programs section (write) or read as generic drift (check):
        # surface the real error instead, like the worklist gate does
        broken = ", ".join(e["program"] for e in facts["errors"])
        print(f"xflowlint: programs failed to lower: {broken}",
              file=sys.stderr)
        return 3
    if ir_ok:
        contracts["ir_programs"] = ir_contract_section(facts)
    path = _contract_artifact_path(args.root)
    on_disk = None
    try:
        with open(path) as f:
            on_disk = f.read()
    except OSError:
        pass
    if write:
        if not ir_ok and on_disk is not None:
            # keep the existing jaxpr section rather than silently
            # shrinking the artifact on a jax-less machine
            try:
                prev = json.loads(on_disk).get("ir_programs")
            except Exception:
                prev = None
            if prev is not None:
                contracts["ir_programs"] = prev
                print("xflowlint: NOTICE — ir_programs section "
                      "preserved from the checked-in artifact",
                      file=sys.stderr)
        rendered = render_artifact(contracts)
        with open(path, "w") as f:
            f.write(rendered)
        print(f"xflowlint: wrote engine-contract matrix for "
              f"{len(contracts['engines'])} builder(s) to {path}")
        return 0
    if on_disk is None:
        print(f"xflowlint: cannot read contract artifact: {path}",
              file=sys.stderr)
        return 4
    disk_doc = None
    try:
        disk_doc = json.loads(on_disk)
    except Exception:
        pass
    if not ir_ok and disk_doc is not None and "ir_programs" in disk_doc:
        # AST-only comparison: strip the section the IR tier would have
        # produced from both sides
        disk_doc = dict(disk_doc)
        disk_doc.pop("ir_programs")
        on_disk = render_artifact(disk_doc)
    rendered = render_artifact(contracts)
    if on_disk == rendered:
        scope = "" if ir_ok else " (AST sections only)"
        print(f"xflowlint: engine-contract matrix matches {path} "
              f"({len(contracts['engines'])} builders){scope}")
        return 0
    return _artifact_drift("CONTRACT", path, on_disk, rendered,
                           "--write-contracts")


def _worklist_mode(args, write: bool) -> int:
    """--write-worklist / --check-worklist: the fusion-worklist gate.
    tools/fusion_worklist.json is the Pallas kernel arc's target list
    (XF801's oracle); drift exits 4 like the contract matrix."""
    from xflow_tpu.analysis.passes.ir_rules import (
        build_worklist, render_worklist,
    )

    facts = _ir_facts_or_notice(args.root, args.no_ir)
    path = _worklist_artifact_path(args.root)
    if facts is None:
        if write:
            print("xflowlint: cannot regenerate the fusion worklist "
                  "without the IR tier", file=sys.stderr)
            return 3
        print("xflowlint: fusion-worklist check SKIPPED (IR tier "
              "unavailable)", file=sys.stderr)
        return 0
    if facts.get("errors"):
        broken = ", ".join(e["program"] for e in facts["errors"])
        print(f"xflowlint: programs failed to lower: {broken}",
              file=sys.stderr)
        return 3
    worklist = build_worklist(facts)
    rendered = render_worklist(worklist)
    n = len(worklist["entries"])
    if write:
        with open(path, "w") as f:
            f.write(rendered)
        print(f"xflowlint: wrote fusion worklist ({n} chains) to {path}")
        return 0
    try:
        with open(path) as f:
            on_disk = f.read()
    except OSError as e:
        print(f"xflowlint: cannot read worklist artifact: {e}",
              file=sys.stderr)
        return 4
    if on_disk == rendered:
        print(f"xflowlint: fusion worklist matches {path} ({n} chains)")
        return 0
    return _artifact_drift("WORKLIST", path, on_disk, rendered,
                           "--write-worklist")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="xflowlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the whole repo)")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="repo root (anchors config.py / OBSERVABILITY.md)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default {DEFAULT_BASELINE} on "
                         "full-repo runs; none on explicit paths)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline (report everything)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record current findings as the new baseline "
                         "(NEW entries require --reason)")
    ap.add_argument("--reason", default=None,
                    help="justification recorded on NEW baseline entries "
                         "written by --write-baseline (audited entries "
                         "keep their existing reasons)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (e.g. XF101,XF301)")
    ap.add_argument("--changed", action="store_true",
                    help="lint only git-changed files (worktree, staged, "
                         "untracked), growth-gated against the repo "
                         "baseline — the pre-commit fast path")
    ap.add_argument("--jobs", "-j", type=int, default=0,
                    help="fan per-module passes out over N processes "
                         "(default 0 = cpu count, capped at 8 — more "
                         "workers than file chunks just pay fork cost); "
                         "output is identical to -j 1")
    ap.add_argument("--ir", action="store_true",
                    help="force the IR tier (jaxpr rules XF801-XF804) on "
                         "this run; default: on for full-tree runs, off "
                         "for explicit paths / --changed")
    ap.add_argument("--no-ir", action="store_true",
                    help="skip the IR tier (AST rules only; artifact "
                         "checks compare their AST sections only)")
    ap.add_argument("--write-contracts", action="store_true",
                    help="regenerate tools/engine_contracts.json (the "
                         "engine sharding-contract matrix + jaxpr section)")
    ap.add_argument("--check-contracts", action="store_true",
                    help="fail with exit 4 if the extracted contract "
                         "matrix drifted from tools/engine_contracts.json")
    ap.add_argument("--write-worklist", action="store_true",
                    help="regenerate tools/fusion_worklist.json (the "
                         "kernel arc's fusion target list)")
    ap.add_argument("--check-worklist", action="store_true",
                    help="fail with exit 4 if the extracted fusion "
                         "worklist drifted from tools/fusion_worklist.json")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    import xflow_tpu.analysis.passes  # noqa: F401  (register)

    if args.list_rules:
        for name, (_fn, rules, _scope) in sorted(PASS_REGISTRY.items()):
            print(f"{name}: {', '.join(rules)}")
        return 0

    if args.ir and args.no_ir:
        print("xflowlint: --ir and --no-ir are mutually exclusive",
              file=sys.stderr)
        return 3

    artifact_modes = (args.write_contracts or args.check_contracts
                      or args.write_worklist or args.check_worklist)
    if artifact_modes:
        if args.paths or args.changed:
            print("xflowlint: the artifact modes operate on the whole "
                  "tree under --root; drop the explicit paths",
                  file=sys.stderr)
            return 3
        if args.write_contracts or args.check_contracts:
            rc = _contracts_mode(args, write=args.write_contracts)
            if rc != 0 or not (args.write_worklist or args.check_worklist):
                return rc
        return _worklist_mode(args, write=args.write_worklist)

    jobs = args.jobs
    if jobs == 0:
        jobs = min(os.cpu_count() or 1, 8)

    only = None
    if args.rules:
        only = {r.strip() for r in args.rules.split(",") if r.strip()}
        known = {r for _n, (_f, rs, _s) in PASS_REGISTRY.items() for r in rs}
        bad = only - known - {"XF001"}
        if bad:
            print(f"xflowlint: unknown rule(s): {', '.join(sorted(bad))}",
                  file=sys.stderr)
            return 3

    if args.ir and (args.paths or args.changed):
        print("xflowlint: --ir needs a full-tree run (the IR tier "
              "imports and lowers the whole engine)", file=sys.stderr)
        return 3

    paths = args.paths or None
    if args.changed:
        if args.paths:
            print("xflowlint: --changed selects its own path set; drop "
                  "the explicit paths", file=sys.stderr)
            return 3
        paths = _changed_paths(args.root)
        if not paths:
            print("xflowlint: --changed: no lintable changed files",
                  file=sys.stderr)
            return 0

    try:
        project = Project.load(args.root, paths)
    except OSError as e:
        print(f"xflowlint: {e}", file=sys.stderr)
        return 3

    # tier selection: full-tree runs get the IR tier by default (it is
    # the CI law); explicit-path and --changed scans stay AST-only for
    # speed unless --ir forces a full-tree semantic run
    use_ir = not args.no_ir and (args.ir or
                                 (project.full_tree and not args.changed))
    tiers = ("ast", "ir") if use_ir else ("ast",)
    findings = run_passes(project, only_rules=only, jobs=jobs, tiers=tiers)
    ir_ran = False
    if use_ir:
        from xflow_tpu.analysis.passes import ir_rules

        state, detail = ir_rules.LAST_STATUS
        # partial runs (a program failed to lower) don't count as "the
        # IR tier ran" for baseline purposes: a finding in the broken
        # program produced no verdict either way
        ir_ran = state == "ok" and not detail
        if state == "skipped":
            print(f"xflowlint: NOTICE — IR tier skipped ({detail}); "
                  "AST-tier results only", file=sys.stderr)
        elif detail:
            print(f"xflowlint: NOTICE — IR tier partial: {detail}",
                  file=sys.stderr)

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline \
            and (project.full_tree or args.changed):
        # --changed still gates GROWTH against the repo baseline (its
        # staleness check is scoped to the scanned files below)
        baseline_path = DEFAULT_BASELINE
    baseline = Baseline() if (args.no_baseline or not baseline_path) \
        else Baseline.load(baseline_path)

    if args.write_baseline:
        if not project.full_tree and args.baseline is None:
            print(
                "xflowlint: --write-baseline over an explicit path set "
                "would overwrite the repo-wide baseline with a PARTIAL "
                "scan (every entry outside the scanned paths would be "
                "dropped); pass an explicit --baseline file",
                file=sys.stderr,
            )
            return 3
        if only is not None:
            print(
                "xflowlint: --write-baseline with --rules would drop "
                "every other rule's baseline entries (a rule-scoped "
                "scan sees none of their findings); rerun without "
                "--rules",
                file=sys.stderr,
            )
            return 3
        target = baseline_path or DEFAULT_BASELINE
        out = Baseline()
        from xflow_tpu.analysis.core import BaselineEntry

        seen = set()
        # reasons carry over from the TARGET file (the baseline actually
        # being rewritten), so an audited reason survives regeneration;
        # NEW entries take --reason — without it they are refused, so
        # the placeholder can never land in a checked-in baseline again
        reasons = {(e.rule, e.path, e.message): e.reason
                   for e in Baseline.load(target).entries
                   if e.reason and e.reason != REASON_PLACEHOLDER}
        unreasoned = []
        for f in findings:
            fp = f.fingerprint()
            if fp in seen:
                continue
            seen.add(fp)
            reason = reasons.get(fp) or args.reason
            if not reason:
                unreasoned.append(f)
                continue
            out.entries.append(BaselineEntry(
                rule=f.rule, path=f.path, message=f.message,
                reason=reason))
        if not ir_ran:
            # IR-tier rules never ran this time (jax absent, --no-ir,
            # or a partial lowering): their existing entries cannot
            # have been fixed — carry them over instead of silently
            # dropping them from the rewritten baseline. Carried
            # entries still go through the reason policy: a placeholder
            # reason is replaced by --reason or refused, so the write
            # can never produce a baseline that fails its own audit
            for e in Baseline.load(target).entries:
                if e.rule not in IR_RULES \
                        or (e.rule, e.path, e.message) in seen:
                    continue
                seen.add((e.rule, e.path, e.message))
                if not e.reason or e.reason == REASON_PLACEHOLDER:
                    if not args.reason:
                        unreasoned.append(Finding(
                            rule=e.rule, path=e.path, line=1,
                            message=e.message))
                        continue
                    e.reason = args.reason
                out.entries.append(e)
        if unreasoned:
            print(
                "xflowlint: --write-baseline refused — "
                f"{len(unreasoned)} NEW entr"
                f"{'y' if len(unreasoned) == 1 else 'ies'} without a "
                "justification; pass --reason \"why this finding is "
                "accepted\" (prefer fixing the finding instead):",
                file=sys.stderr)
            for f in unreasoned[:10]:
                print(f"  {f.path}: {f.rule}: {f.message}",
                      file=sys.stderr)
            return 3
        out.save(target)
        print(f"xflowlint: wrote {len(out.entries)} baseline entr"
              f"{'y' if len(out.entries) == 1 else 'ies'} to {target}")
        return 0

    # baseline audit: the placeholder reason must never gate CI — it
    # means an entry was recorded without a human justification
    placeholders = [e for e in baseline.entries
                    if e.reason == REASON_PLACEHOLDER]
    if placeholders:
        print(
            f"xflowlint: baseline audit FAILED — {len(placeholders)} "
            f"entr{'y' if len(placeholders) == 1 else 'ies'} still "
            f"carry the {REASON_PLACEHOLDER!r} placeholder reason; "
            "justify (edit the reason) or fix the finding and remove "
            "the entry:", file=sys.stderr)
        for e in placeholders[:10]:
            print(f"  {e.path}: {e.rule}: {e.message}", file=sys.stderr)
        return 3

    scanned = None
    if args.changed:
        scanned = {m.relpath for m in project.modules} \
            | {s.relpath for s in project.shell_scripts}
    new, based, stale = baseline.split(findings, only_rules=only,
                                       only_paths=scanned)
    if not project.full_tree:
        # dead-key-style analyses never ran on this partial scan: their
        # entries cannot have been "fixed" by it
        from xflow_tpu.analysis.core import FULL_TREE_RULES

        stale = [e for e in stale if e.rule not in FULL_TREE_RULES]
    if not ir_ran:
        # IR-tier rules never ran (tier off, jax absent, or tree not
        # importable): their entries cannot have been fixed either
        stale = [e for e in stale if e.rule not in IR_RULES]

    if args.json:
        import dataclasses

        print(json.dumps({
            "new": [dataclasses.asdict(f) for f in new],
            "baselined": len(based),
            "stale_baseline": [
                {"rule": e.rule, "path": e.path, "message": e.message}
                for e in stale],
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        if based:
            print(f"xflowlint: {len(based)} finding(s) suppressed by "
                  f"baseline ({baseline_path})")
        for e in stale:
            print(f"xflowlint: STALE baseline entry (finding no longer "
                  f"fires — remove it): {e.path}: {e.rule}: {e.message}")
    n_files = len(project.modules) + len(project.shell_scripts)
    summary = (f"xflowlint: {n_files} files, {len(findings)} finding(s): "
               f"{len(new)} new, {len(based)} baselined, "
               f"{len(stale)} stale baseline entr"
               f"{'y' if len(stale) == 1 else 'ies'}")
    print(summary, file=sys.stderr)
    if new:
        return 1
    if stale:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
