#!/usr/bin/env python
"""xflowlint — project-native static analysis for xflow-tpu.

Runs the xflow_tpu/analysis passes (docs/STATIC_ANALYSIS.md) over the
repo (or explicit paths) and gates against the checked-in baseline:

    python tools/xflowlint.py                       # full repo, baselined
    python tools/xflowlint.py xflow_tpu/serve       # subset (no dead-key)
    python tools/xflowlint.py --rules XF301         # one rule family
    python tools/xflowlint.py --write-baseline      # re-record legacy set
    python tools/xflowlint.py --list-rules

Exit codes (tools/smoke_lint.sh relies on these):
    0  clean — no unbaselined findings, no stale baseline entries
    1  NEW findings (not in the baseline)
    2  STALE baseline entries (a fixed finding must leave the baseline)
    3  usage / internal error

The baseline (tools/xflowlint_baseline.json) makes the gate fail on
*growth*, not existence; inline `# xflowlint: disable=RULE` handles
intentional single sites (with a nearby comment saying why).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from xflow_tpu.analysis.core import (  # noqa: E402
    PASS_REGISTRY, Baseline, Project, run_passes,
)

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "xflowlint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="xflowlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the whole repo)")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="repo root (anchors config.py / OBSERVABILITY.md)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default {DEFAULT_BASELINE} on "
                         "full-repo runs; none on explicit paths)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline (report everything)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record current findings as the new baseline "
                         "(audit reasons by hand afterwards)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (e.g. XF101,XF301)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    import xflow_tpu.analysis.passes  # noqa: F401  (register)

    if args.list_rules:
        for name, (_fn, rules) in sorted(PASS_REGISTRY.items()):
            print(f"{name}: {', '.join(rules)}")
        return 0

    only = None
    if args.rules:
        only = {r.strip() for r in args.rules.split(",") if r.strip()}
        known = {r for _n, (_f, rs) in PASS_REGISTRY.items() for r in rs}
        bad = only - known - {"XF001"}
        if bad:
            print(f"xflowlint: unknown rule(s): {', '.join(sorted(bad))}",
                  file=sys.stderr)
            return 3

    try:
        project = Project.load(args.root, args.paths or None)
    except OSError as e:
        print(f"xflowlint: {e}", file=sys.stderr)
        return 3
    findings = run_passes(project, only_rules=only)

    baseline_path = args.baseline
    if baseline_path is None and project.full_tree and not args.no_baseline:
        baseline_path = DEFAULT_BASELINE
    baseline = Baseline() if (args.no_baseline or not baseline_path) \
        else Baseline.load(baseline_path)

    if args.write_baseline:
        if not project.full_tree and args.baseline is None:
            print(
                "xflowlint: --write-baseline over an explicit path set "
                "would overwrite the repo-wide baseline with a PARTIAL "
                "scan (every entry outside the scanned paths would be "
                "dropped); pass an explicit --baseline file",
                file=sys.stderr,
            )
            return 3
        if only is not None:
            print(
                "xflowlint: --write-baseline with --rules would drop "
                "every other rule's baseline entries (a rule-scoped "
                "scan sees none of their findings); rerun without "
                "--rules",
                file=sys.stderr,
            )
            return 3
        target = baseline_path or DEFAULT_BASELINE
        out = Baseline()
        from xflow_tpu.analysis.core import BaselineEntry

        seen = set()
        # reasons carry over from the TARGET file (the baseline actually
        # being rewritten), so an audited reason survives regeneration
        reasons = {(e.rule, e.path, e.message): e.reason
                   for e in Baseline.load(target).entries}
        for f in findings:
            fp = f.fingerprint()
            if fp in seen:
                continue
            seen.add(fp)
            out.entries.append(BaselineEntry(
                rule=f.rule, path=f.path, message=f.message,
                reason=reasons.get(fp, "TODO: justify or fix")))
        out.save(target)
        print(f"xflowlint: wrote {len(out.entries)} baseline entr"
              f"{'y' if len(out.entries) == 1 else 'ies'} to {target}")
        return 0

    new, based, stale = baseline.split(findings, only_rules=only)

    if args.json:
        import dataclasses

        print(json.dumps({
            "new": [dataclasses.asdict(f) for f in new],
            "baselined": len(based),
            "stale_baseline": [
                {"rule": e.rule, "path": e.path, "message": e.message}
                for e in stale],
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        if based:
            print(f"xflowlint: {len(based)} finding(s) suppressed by "
                  f"baseline ({baseline_path})")
        for e in stale:
            print(f"xflowlint: STALE baseline entry (finding no longer "
                  f"fires — remove it): {e.path}: {e.rule}: {e.message}")
    n_files = len(project.modules) + len(project.shell_scripts)
    summary = (f"xflowlint: {n_files} files, {len(findings)} finding(s): "
               f"{len(new)} new, {len(based)} baselined, "
               f"{len(stale)} stale baseline entr"
               f"{'y' if len(stale) == 1 else 'ies'}")
    print(summary, file=sys.stderr)
    if new:
        return 1
    if stale:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
