#!/usr/bin/env python
"""xflowlint — project-native static analysis for xflow-tpu.

Runs the xflow_tpu/analysis passes (docs/STATIC_ANALYSIS.md) over the
repo (or explicit paths) and gates against the checked-in baseline:

    python tools/xflowlint.py                       # full repo, baselined
    python tools/xflowlint.py xflow_tpu/serve       # subset (no dead-key)
    python tools/xflowlint.py --rules XF301         # one rule family
    python tools/xflowlint.py --changed -j 8        # pre-commit fast path
    python tools/xflowlint.py --write-baseline      # re-record legacy set
    python tools/xflowlint.py --check-contracts     # engine-contract gate
    python tools/xflowlint.py --list-rules

Exit codes (tools/smoke_lint.sh relies on these):
    0  clean — no unbaselined findings, no stale baseline entries
    1  NEW findings (not in the baseline)
    2  STALE baseline entries (a fixed finding must leave the baseline)
    3  usage / internal error
    4  CONTRACT drift — the extracted engine-contract matrix differs
       from the checked-in tools/engine_contracts.json (regenerate
       with --write-contracts and review the diff)

The baseline (tools/xflowlint_baseline.json) makes the gate fail on
*growth*, not existence; inline `# xflowlint: disable=RULE` handles
intentional single sites (with a nearby comment saying why).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from xflow_tpu.analysis.core import (  # noqa: E402
    PASS_REGISTRY, Baseline, Project, run_passes,
)

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "xflowlint_baseline.json")


def _changed_paths(root: str) -> list:
    """Files git considers changed (worktree vs HEAD, staged, and
    untracked), filtered to the default lintable set. The pre-commit
    fast path: lint what the commit touches, gate growth against the
    repo baseline."""
    import subprocess

    out: set = set()
    cmds = (
        ["git", "diff", "--name-only", "HEAD", "--"],
        ["git", "diff", "--name-only", "--cached", "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    )
    for cmd in cmds:
        try:
            r = subprocess.run(cmd, cwd=root, capture_output=True,
                               text=True, timeout=30)
        except Exception:
            continue
        if r.returncode != 0:
            continue
        out.update(ln.strip() for ln in r.stdout.splitlines() if ln.strip())
    keep = []
    for rel in sorted(out):
        norm = rel.replace(os.sep, "/")
        if "tests/fixtures" in norm:
            continue
        lintable = (
            (norm.startswith("xflow_tpu/") and norm.endswith(".py"))
            or (norm.startswith("tools/") and "/" not in norm[len("tools/"):]
                and norm.endswith((".py", ".sh")))
            or norm in ("bench.py", "conftest.py")
        )
        if lintable and os.path.exists(os.path.join(root, rel)):
            keep.append(os.path.join(root, rel))
    return keep


def _contract_artifact_path(root: str) -> str:
    return os.path.join(root, "tools", "engine_contracts.json")


def _contracts_mode(args, write: bool) -> int:
    """--write-contracts / --check-contracts: the engine-contract
    matrix gate (docs/DISTRIBUTED.md "Engine contract matrix")."""
    from xflow_tpu.analysis.passes.sharding_contract import (
        ENGINE_MODULES, MESH_MODULE, extract_contracts, render_artifact,
    )

    # only the builder sources (+ the mesh axis anchor) feed the matrix
    # — loading them alone keeps the pre-commit contract check cheap
    wanted = [os.path.join(args.root, *rel.split("/"))
              for rel in ENGINE_MODULES + (MESH_MODULE,)]
    project = Project.load(args.root,
                           [p for p in wanted if os.path.exists(p)] or None)
    contracts = extract_contracts(project)
    missing = [m for m in ENGINE_MODULES if m not in contracts["engines"]]
    if missing:
        print(
            "xflowlint: engine builders missing from the source tree: "
            + ", ".join(missing), file=sys.stderr)
        return 3
    rendered = render_artifact(contracts)
    path = _contract_artifact_path(args.root)
    if write:
        with open(path, "w") as f:
            f.write(rendered)
        print(f"xflowlint: wrote engine-contract matrix for "
              f"{len(contracts['engines'])} builder(s) to {path}")
        return 0
    try:
        with open(path) as f:
            on_disk = f.read()
    except OSError as e:
        print(f"xflowlint: cannot read contract artifact: {e}",
              file=sys.stderr)
        return 4
    if on_disk == rendered:
        print(f"xflowlint: engine-contract matrix matches {path} "
              f"({len(contracts['engines'])} builders)")
        return 0
    import difflib

    diff = difflib.unified_diff(
        on_disk.splitlines(), rendered.splitlines(),
        fromfile="checked-in", tofile="extracted", lineterm="", n=2)
    lines = list(diff)[:40]
    print("xflowlint: CONTRACT DRIFT — a builder's extracted sharding "
          "contract differs from tools/engine_contracts.json:",
          file=sys.stderr)
    for ln in lines:
        print(f"  {ln}", file=sys.stderr)
    print("xflowlint: if the change is intended, regenerate with "
          "`python tools/xflowlint.py --write-contracts` and review "
          "the diff (it is the unified-builder acceptance oracle)",
          file=sys.stderr)
    return 4


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="xflowlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the whole repo)")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="repo root (anchors config.py / OBSERVABILITY.md)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default {DEFAULT_BASELINE} on "
                         "full-repo runs; none on explicit paths)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline (report everything)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record current findings as the new baseline "
                         "(audit reasons by hand afterwards)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (e.g. XF101,XF301)")
    ap.add_argument("--changed", action="store_true",
                    help="lint only git-changed files (worktree, staged, "
                         "untracked), growth-gated against the repo "
                         "baseline — the pre-commit fast path")
    ap.add_argument("--jobs", "-j", type=int, default=1,
                    help="fan per-module passes out over N processes "
                         "(0 = cpu count, capped at 8 — more workers "
                         "than file chunks just pay fork cost); output "
                         "is identical to -j 1")
    ap.add_argument("--write-contracts", action="store_true",
                    help="regenerate tools/engine_contracts.json (the "
                         "engine sharding-contract matrix)")
    ap.add_argument("--check-contracts", action="store_true",
                    help="fail with exit 4 if the extracted contract "
                         "matrix drifted from tools/engine_contracts.json")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    import xflow_tpu.analysis.passes  # noqa: F401  (register)

    if args.list_rules:
        for name, (_fn, rules, _scope) in sorted(PASS_REGISTRY.items()):
            print(f"{name}: {', '.join(rules)}")
        return 0

    if args.write_contracts or args.check_contracts:
        if args.paths or args.changed:
            print("xflowlint: --write/check-contracts operates on the "
                  "whole tree under --root; drop the explicit paths",
                  file=sys.stderr)
            return 3
        return _contracts_mode(args, write=args.write_contracts)

    jobs = args.jobs
    if jobs == 0:
        jobs = min(os.cpu_count() or 1, 8)

    only = None
    if args.rules:
        only = {r.strip() for r in args.rules.split(",") if r.strip()}
        known = {r for _n, (_f, rs, _s) in PASS_REGISTRY.items() for r in rs}
        bad = only - known - {"XF001"}
        if bad:
            print(f"xflowlint: unknown rule(s): {', '.join(sorted(bad))}",
                  file=sys.stderr)
            return 3

    paths = args.paths or None
    if args.changed:
        if args.paths:
            print("xflowlint: --changed selects its own path set; drop "
                  "the explicit paths", file=sys.stderr)
            return 3
        paths = _changed_paths(args.root)
        if not paths:
            print("xflowlint: --changed: no lintable changed files",
                  file=sys.stderr)
            return 0

    try:
        project = Project.load(args.root, paths)
    except OSError as e:
        print(f"xflowlint: {e}", file=sys.stderr)
        return 3
    findings = run_passes(project, only_rules=only, jobs=jobs)

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline \
            and (project.full_tree or args.changed):
        # --changed still gates GROWTH against the repo baseline (its
        # staleness check is scoped to the scanned files below)
        baseline_path = DEFAULT_BASELINE
    baseline = Baseline() if (args.no_baseline or not baseline_path) \
        else Baseline.load(baseline_path)

    if args.write_baseline:
        if not project.full_tree and args.baseline is None:
            print(
                "xflowlint: --write-baseline over an explicit path set "
                "would overwrite the repo-wide baseline with a PARTIAL "
                "scan (every entry outside the scanned paths would be "
                "dropped); pass an explicit --baseline file",
                file=sys.stderr,
            )
            return 3
        if only is not None:
            print(
                "xflowlint: --write-baseline with --rules would drop "
                "every other rule's baseline entries (a rule-scoped "
                "scan sees none of their findings); rerun without "
                "--rules",
                file=sys.stderr,
            )
            return 3
        target = baseline_path or DEFAULT_BASELINE
        out = Baseline()
        from xflow_tpu.analysis.core import BaselineEntry

        seen = set()
        # reasons carry over from the TARGET file (the baseline actually
        # being rewritten), so an audited reason survives regeneration
        reasons = {(e.rule, e.path, e.message): e.reason
                   for e in Baseline.load(target).entries}
        for f in findings:
            fp = f.fingerprint()
            if fp in seen:
                continue
            seen.add(fp)
            out.entries.append(BaselineEntry(
                rule=f.rule, path=f.path, message=f.message,
                reason=reasons.get(fp, "TODO: justify or fix")))
        out.save(target)
        print(f"xflowlint: wrote {len(out.entries)} baseline entr"
              f"{'y' if len(out.entries) == 1 else 'ies'} to {target}")
        return 0

    scanned = None
    if args.changed:
        scanned = {m.relpath for m in project.modules} \
            | {s.relpath for s in project.shell_scripts}
    new, based, stale = baseline.split(findings, only_rules=only,
                                       only_paths=scanned)
    if not project.full_tree:
        # dead-key-style analyses never ran on this partial scan: their
        # entries cannot have been "fixed" by it
        from xflow_tpu.analysis.core import FULL_TREE_RULES

        stale = [e for e in stale if e.rule not in FULL_TREE_RULES]

    if args.json:
        import dataclasses

        print(json.dumps({
            "new": [dataclasses.asdict(f) for f in new],
            "baselined": len(based),
            "stale_baseline": [
                {"rule": e.rule, "path": e.path, "message": e.message}
                for e in stale],
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        if based:
            print(f"xflowlint: {len(based)} finding(s) suppressed by "
                  f"baseline ({baseline_path})")
        for e in stale:
            print(f"xflowlint: STALE baseline entry (finding no longer "
                  f"fires — remove it): {e.path}: {e.rule}: {e.message}")
    n_files = len(project.modules) + len(project.shell_scripts)
    summary = (f"xflowlint: {n_files} files, {len(findings)} finding(s): "
               f"{len(new)} new, {len(based)} baselined, "
               f"{len(stale)} stale baseline entr"
               f"{'y' if len(stale) == 1 else 'ies'}")
    print(summary, file=sys.stderr)
    if new:
        return 1
    if stale:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
