"""Host data-plane scaling harness (VERDICT r2 item 5).

The sorted engine's host side must outrun the device: at the round-3
device rates (FM 1.6M ex/s) one 64k x 18 batch is consumed every
~41 ms, so parse + plan must sustain >= 1.6M rows/s aggregate. This CI
image exposes ONE CPU core, so the absolute e2e number here is
host-bound by construction; this harness records the per-core rates and
the thread-scaling CURVE (1/2/4 worker caps) for both stages, so the
claim "a real multi-core TPU host clears the device rate" is backed by
measured per-core throughput x measured scaling efficiency instead of
assertion.

  python tools/hostplane_bench.py            # one JSON line

Stages measured:
- PARSE: the C MT parser pool (xf_mt_*) at 1/2/4 workers over a real
  libffm file (byte-identical reassembly either way).
- PLAN: the pair-encoded C radix planner (xf_plan_sorted) on
  concurrent sub-batch plans (ctypes releases the GIL) at 1/2/4
  workers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def bench_parse(path: str, caps, cfg) -> dict:
    from xflow_tpu.data.pipeline import batch_iterator
    from xflow_tpu.config import override

    out = {}
    for cap in caps:
        c = override(cfg, **{"data.parser_threads": cap})
        # warm (page cache + pool spin-up)
        for _ in batch_iterator(path, c.data):
            pass
        t0 = time.perf_counter()
        n = 0
        for b in batch_iterator(path, c.data):
            n += b.num_rows
        dt = time.perf_counter() - t0
        out[f"parse_rows_per_sec_{cap}w"] = round(n / dt, 1)
    return out


def bench_plan(caps, batch: int, nnz: int, log2_slots: int, num_sub: int) -> dict:
    from xflow_tpu.data.native import native_plan_sorted
    from xflow_tpu.ops.sorted_table import WINDOW, padded_len

    S = 1 << log2_slots
    rng = np.random.default_rng(0)
    bs = batch // num_sub
    subs = [
        np.ascontiguousarray(rng.integers(0, S, (bs, nnz)).astype(np.int32))
        for _ in range(num_sub)
    ]
    mask = np.ones((bs, nnz), np.float32)

    def one(i):
        return native_plan_sorted(subs[i], mask, None, S, WINDOW, padded_len(bs * nnz))

    out = {}
    for cap in caps:
        with ThreadPoolExecutor(max_workers=cap) as pool:
            list(pool.map(one, range(num_sub)))  # warm
            t0 = time.perf_counter()
            reps = 5
            for _ in range(reps):
                list(pool.map(one, range(num_sub)))
            dt = (time.perf_counter() - t0) / reps
        out[f"plan_rows_per_sec_{cap}w"] = round(batch / dt, 1)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=500_000)
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--nnz", type=int, default=18)
    ap.add_argument("--log2-slots", type=int, default=22)
    ap.add_argument("--num-sub", type=int, default=8,
                    help="concurrent sub-batch plans (the trainer's "
                         "parallelism unit)")
    ap.add_argument("--caps", default="1,2,4")
    args = ap.parse_args()

    from xflow_tpu.config import Config
    from xflow_tpu.data.synth import generate_shards_bulk

    caps = [int(c) for c in args.caps.split(",")]
    record = {"host_cores": os.cpu_count()}
    with tempfile.TemporaryDirectory() as td:
        prefix = os.path.join(td, "t")
        generate_shards_bulk(prefix, 1, args.rows, num_fields=args.nnz,
                             ids_per_field=200_000, seed=0)
        from xflow_tpu.config import override

        cfg = override(
            Config(),
            **{"data.batch_size": args.batch, "data.max_nnz": args.nnz,
               "data.log2_slots": args.log2_slots,
               "model.num_fields": args.nnz},
        )
        record.update(bench_parse(prefix + "-00000", caps, cfg))
    record.update(
        bench_plan(caps, args.batch, args.nnz, args.log2_slots, args.num_sub)
    )
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
