"""Host data-plane scaling harness (VERDICT r2 item 5): per-core
parse/plan rates and the 1/2/4-worker thread-scaling curve, printed as
one JSON line (docs/PERF.md "Host data plane").

Retired to a thin wrapper: the implementation lives in the unified
microbench lab (`xflow_tpu/tools/bench_lab.py --suite hostplane`). This
CLI keeps working, flags unchanged:

    python tools/hostplane_bench.py [--rows N --batch B --nnz F
                                     --log2-slots S --num-sub K --caps 1,2,4]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xflow_tpu.tools.bench_lab import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--suite", "hostplane"] + sys.argv[1:]))
