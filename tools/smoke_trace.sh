#!/usr/bin/env bash
# Request-tracing CI gate (docs/OBSERVABILITY.md "Request tracing"):
#
# 1. Train a small LR run with committed checkpoints (10..50), stage
#    step-20 into a serving dir.
# 2. Overhead A/B — SOLO `xflow serve` (2 processes total: server +
#    loadgen — a fleet would put 5 processes on a 2-core CI runner and
#    drown the signal in scheduler noise), three alternating pairs:
#    off, traced@0.01, ×3. The traced benches send a
#    fresh X-Trace-Id per request and assert the echo round-trip (an
#    echo miss fails the bench). Gates:
#      - the rate-0 run dirs hold ZERO kind="span" records (the rate-0
#        streams are the pre-tracing streams);
#      - best-of-pairs overhead = (best_off - best_traced)/best_off,
#        stamped into BENCH_TRACE.json (qps_untraced / qps_traced /
#        trace_overhead_pct — the acceptance budget is <2%; CI gates
#        loosely at <30%: best-of-pairs absorbs contention spikes, and
#        an accidental always-on hot-path cost still trips it).
# 3. The diagnosis drill — 2-replica fleet, sample_rate=1.0: replica 1 runs
#    with a fault-injected 60 ms per-batch delay
#    (XFLOW_FAULT_SERVE_DELAY_S — the slow-replica chaos injector);
#    the GOOD step-50 checkpoint commits mid-bench so a staggered hot
#    reload lands inside the traced window. Gates:
#      - tools/request_trace.py assembles >= 99% of ok traces into
#        complete root -> device-batch span trees (--min-complete 0.99);
#      - the per-replica critical-path table blames the slow replica's
#        added latency on the correct hops (queue/window/device — the
#        injected sleep sits inside the device window and backs up the
#        coalescer queue), with the fast replica as the control row;
#      - p50/p99 exemplar trace ids exist (the tail you page on comes
#        with a receipt);
#      - the Chrome trace-event export is well-formed
#        (Perfetto-loadable: "X" events + process_name metadata);
#      - reload spans are on disk and request_trace --timeline overlays
#        them against request latency;
#      - tools/metrics_report.py --check is green over the traced run
#        dir (span schema + one-root-per-trace + batch-link gates).
# 4. BENCH_TRACE.json flows through tools/perf_ledger.py (the serve
#    series notes tracing overhead alongside the BENCH_SERVE points).
#
# Standalone:    bash tools/smoke_trace.sh [workdir]
# From pytest:   tests/test_request_trace.py::test_smoke_trace_script
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
# bench datapoint destination: the repo root ONLY standalone (the
# per-PR record); under pytest it stays in the workdir
BENCH_OUT="$ROOT/BENCH_TRACE.json"
FLEET_PID=""
SOLO_PID=""
cleanup() {
    if [ -n "$FLEET_PID" ]; then kill -9 "$FLEET_PID" 2>/dev/null || true; fi
    if [ -n "$SOLO_PID" ]; then kill -9 "$SOLO_PID" 2>/dev/null || true; fi
    # replicas are children of the fleet; sweep any orphans by their
    # serving dir (unique to this run)
    pkill -9 -f "serve_ck_trace" 2>/dev/null || true
    if [ -n "${TMP_WORK:-}" ]; then rm -rf "$TMP_WORK"; fi
}
trap cleanup EXIT
if [ -z "$WORK" ]; then
    TMP_WORK="$(mktemp -d)"
    WORK="$TMP_WORK"
else
    BENCH_OUT="$WORK/BENCH_TRACE.json"
fi

export JAX_PLATFORMS=cpu
# single CPU device (xargs trims; an empty result must UNSET the var —
# XLA treats a whitespace-only value as a flags FILE to open and aborts)
XLA_FLAGS="$(printf '%s\n' ${XLA_FLAGS:-} \
    | grep -v xla_force_host_platform_device_count | xargs || true)"
if [ -n "$XLA_FLAGS" ]; then export XLA_FLAGS; else unset XLA_FLAGS; fi

MODEL_ARGS=(--model lr --log2-slots 12
            --set model.num_fields=6 --set data.max_nnz=8)
SERVE_CK="$WORK/serve_ck_trace"

# ---- 1. train with a checkpoint trail -------------------------------------
python -m xflow_tpu gen-data "$WORK/train" --shards 1 --rows 3200 \
    --fields 6 --ids-per-field 50 --seed 0 >/dev/null
python -m xflow_tpu gen-data "$WORK/reqs" --shards 1 --rows 512 \
    --fields 6 --ids-per-field 50 --seed 9 --truth-seed 0 >/dev/null

python -m xflow_tpu train --train "$WORK/train" "${MODEL_ARGS[@]}" \
    --epochs 1 --batch-size 64 --checkpoint-dir "$WORK/ck" \
    --set train.checkpoint_every=10 --set train.pred_dump=false \
    --set train.log_every=10 >/dev/null 2>"$WORK/train.log"

stage() {  # atomic checkpoint shipping: payload under a temp name, one rename
    python - "$WORK/ck" "$SERVE_CK" "$1" <<'EOF'
import os, shutil, sys
src, dst, step = sys.argv[1], sys.argv[2], sys.argv[3]
os.makedirs(dst, exist_ok=True)
tmp = os.path.join(dst, f".staging_{step}")
if os.path.exists(tmp):
    shutil.rmtree(tmp)
shutil.copytree(os.path.join(src, f"step_{step}"), tmp)
os.replace(tmp, os.path.join(dst, f"step_{step}"))
EOF
}
stage 20

# one fleet phase: run_fleet <run_dir> <ready_json> <extra --set args...>
run_fleet() {
    local rdir="$1" ready="$2"; shift 2
    mkdir -p "$rdir"
    python -m xflow_tpu serve-fleet --checkpoint-dir "$SERVE_CK" \
        "${MODEL_ARGS[@]}" \
        --replicas 2 --port 0 --window-ms 3 --max-batch 64 --poll-s 0.3 \
        --reload-stagger-s 0.3 --retries 2 --deadline-ms 20000 \
        --health-poll-s 0.2 --run-dir "$rdir" \
        --no-mesh --set serve.metrics_every_s=1 "$@" \
        >"$ready" 2>"$rdir/fleet.log" &
    FLEET_PID=$!
    for i in $(seq 1 360); do
        [ -s "$ready" ] && break
        kill -0 "$FLEET_PID" 2>/dev/null || {
            echo "smoke_trace: fleet died during startup"
            cat "$rdir/fleet.log"; exit 1; }
        sleep 0.5
    done
    [ -s "$ready" ] || {
        echo "smoke_trace: fleet never became ready"
        cat "$rdir/fleet.log"; exit 1; }
    PORT=$(python - "$ready" <<'EOF'
import json, sys
ready = json.load(open(sys.argv[1]))
assert ready["fleet"] and len(ready["replicas"]) == 2, ready
assert all(r["step"] == 20 for r in ready["replicas"]), ready
print(ready["router_port"])
EOF
)
}

drain_fleet() {
    kill -TERM "$FLEET_PID"
    local rc=0; wait "$FLEET_PID" || rc=$?
    FLEET_PID=""
    [ "$rc" -eq 0 ] || {
        echo "smoke_trace: fleet exit $rc"; cat "$1/fleet.log"; exit 1; }
}

# ---- 2. overhead A/B: solo serve, alternating off/traced pairs ------------
# one solo bench: solo_bench <label> <bench.json out> <trace sample rate|''>
solo_bench() {
    local label="$1" bjson="$2" rate="$3"
    # extras as ARRAYS, not word-split strings: quoted expansion stays
    # glob/space-safe under `set -euo pipefail` ('' rate = untraced)
    local serve_extra=() bench_extra=()
    if [ -n "$rate" ]; then
        serve_extra=(--set "serve.trace_sample_rate=$rate")
        bench_extra=(--trace-sample-rate "$rate")
    fi
    local sdir="$WORK/solo_$label"
    mkdir -p "$sdir"
    python -m xflow_tpu serve --checkpoint-dir "$SERVE_CK" "${MODEL_ARGS[@]}" \
        --port 0 --window-ms 3 --max-batch 64 --no-mesh \
        --metrics-path "$sdir/serve.jsonl" --set serve.metrics_every_s=5 \
        "${serve_extra[@]}" \
        >"$sdir/ready.json" 2>"$sdir/serve.log" &
    SOLO_PID=$!
    for i in $(seq 1 240); do
        [ -s "$sdir/ready.json" ] && break
        kill -0 "$SOLO_PID" 2>/dev/null || {
            echo "smoke_trace: solo serve ($label) died during startup"
            cat "$sdir/serve.log"; exit 1; }
        sleep 0.5
    done
    local port
    port=$(python -c "import json,sys; print(json.load(open(sys.argv[1]))['port'])" \
        "$sdir/ready.json")
    python tools/serve_bench.py --url "http://127.0.0.1:$port" \
        --data "$WORK/reqs-00000" --duration 4 --concurrency 2 \
        --rows-per-request 4 "${bench_extra[@]}" \
        --bench-json "$bjson" >"$sdir/report.json" 2>"$sdir/bench.log" || {
        echo "smoke_trace: solo bench ($label) failed"
        cat "$sdir/report.json" "$sdir/serve.log"; exit 1; }
    kill -TERM "$SOLO_PID"; wait "$SOLO_PID" || true
    SOLO_PID=""
}
solo_bench off1 "$WORK/bench_off1.json" ""
solo_bench traced1 "$WORK/bench_traced1.json" 0.01
solo_bench off2 "$WORK/bench_off2.json" ""
solo_bench traced2 "$WORK/bench_traced2.json" 0.01
solo_bench off3 "$WORK/bench_off3.json" ""
solo_bench traced3 "$WORK/bench_traced3.json" 0.01
if grep -q '"kind": "span"' "$WORK"/solo_off*/serve.jsonl; then
    echo "smoke_trace: rate-0 run emitted span records (must be byte-identical" \
         "to a pre-tracing stream)"; exit 1
fi

# ---- 3. slow-replica diagnosis drill at full sampling ---------------------
export XFLOW_FAULT_SERVE_DELAY_S=0.06
export XFLOW_FAULT_SERVE_REPLICA=1
run_fleet "$WORK/run_traced" "$WORK/ready_traced.json" \
    --set serve.trace_sample_rate=1.0
python tools/serve_bench.py --url "http://127.0.0.1:$PORT" \
    --data "$WORK/reqs-00000" --duration 9 --concurrency 4 \
    --rows-per-request 4 --retries 2 --deadline-ms 20000 \
    --trace-sample-rate 1.0 --bench-json "$WORK/bench_traced.json" \
    >"$WORK/bench_traced_report.json" 2>"$WORK/bench_traced.log" &
BENCH_PID=$!
sleep 4
stage 50   # a hot reload lands inside the traced window
rc=0; wait "$BENCH_PID" || rc=$?
unset XFLOW_FAULT_SERVE_DELAY_S XFLOW_FAULT_SERVE_REPLICA
[ "$rc" -eq 0 ] || {
    echo "smoke_trace: drill bench failed (errors or trace-id echo miss)"
    cat "$WORK/bench_traced_report.json" "$WORK/run_traced/fleet.log"; exit 1; }
# the mid-bench commit only has to be NOTICED under load; on a slow CI
# runner the staggered reload itself may land after the bench window —
# wait it out before draining (the gate below still requires the span)
for i in $(seq 1 120); do
    # grep the files directly: under pipefail, `cat | grep -q` turns a
    # successful early match into a failed pipeline (cat dies SIGPIPE)
    if grep -q '"name": "reload"' "$WORK/run_traced"/serve_replica*.jsonl \
            2>/dev/null; then break; fi
    sleep 0.5
done
drain_fleet "$WORK/run_traced"

# the assembled answer: critical paths, per-replica blame, exemplars,
# timeline overlay, Chrome export — and the >=99%-complete-trees gate
python tools/request_trace.py "$WORK/run_traced" \
    --min-complete 0.99 --timeline \
    --json "$WORK/trace_summary.json" \
    --chrome "$WORK/chrome_trace.json" >"$WORK/trace_report.txt" || {
    echo "smoke_trace: request_trace failed its completeness gate"
    cat "$WORK/trace_report.txt"; exit 1; }

python - "$WORK/trace_summary.json" "$WORK/chrome_trace.json" \
    "$WORK/trace_report.txt" <<'EOF'
import json, sys
s = json.load(open(sys.argv[1]))
assert s["ok"] > 0 and s["complete_frac"] >= 0.99, s
per = {str(k): v for k, v in s["per_replica"].items()}
assert "0" in per and "1" in per, f"blame table lacks a replica: {list(per)}"
fast, slow = per["0"], per["1"]
# the injected 60 ms/batch sleep sits between batch formation and the
# device call: it lands in the DEVICE span and backs the coalescer
# queue up behind it — the slow replica's queue+window+device mean must
# carry the fault, with the fast replica as the control
fast_hop = fast["queue"] + fast["window"] + fast["device"]
slow_hop = slow["queue"] + slow["window"] + slow["device"]
assert slow_hop >= fast_hop + 30.0, (
    f"slow replica not blamed on queue/window/device: "
    f"slow {slow_hop:.1f}ms vs fast {fast_hop:.1f}ms")
assert slow["p99_ms"] > fast["p99_ms"], (slow, fast)
# the tail exemplars come with receipts (trace ids)
for q in ("p50", "p99"):
    ex = s["exemplars"][q]
    assert ex and ex["trace"], f"no {q} exemplar"
assert s["exemplars"]["p99"]["total_ms"] >= 50.0, s["exemplars"]["p99"]
# Chrome export: Perfetto-loadable trace-event JSON
d = json.load(open(sys.argv[2]))
xs = [e for e in d["traceEvents"] if e["ph"] == "X"]
ms = [e for e in d["traceEvents"] if e["ph"] == "M"]
assert xs and ms, (len(xs), len(ms))
assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in xs)
names = {e["args"]["name"] for e in ms}
assert any(n.startswith("replica") for n in names) and "router" in names, names
report = open(sys.argv[3]).read()
assert "per-replica" in report and "p99 exemplar" in report, report[:400]
print("smoke_trace: drill OK "
      f"({s['ok']} ok traces, {s['complete_frac']*100:.1f}% complete, "
      f"slow-replica hop {slow_hop:.1f}ms vs control {fast_hop:.1f}ms, "
      f"{len(xs)} chrome events)")
EOF

# reload spans are on disk and the timeline overlays them
# direct grep, not `cat | grep -q`: under pipefail grep's early exit
# SIGPIPEs cat and fails the pipeline even when the span IS there
grep -q '"name": "reload"' "$WORK/run_traced"/serve_replica*.jsonl || {
    echo "smoke_trace: no reload span (hot swap never traced)"; exit 1; }
grep -q "reload" "$WORK/trace_report.txt" || {
    echo "smoke_trace: --timeline never overlaid the reload"; exit 1; }

# span schema + one-root-per-trace + batch-link + replica-identity gates
python tools/metrics_report.py "$WORK/run_traced" --check

# ---- 4. the overhead stamp + the perf ledger ------------------------------
python - "$BENCH_OUT" "$WORK"/bench_off?.json -- "$WORK"/bench_traced?.json <<'EOF'
import json, sys
sep = sys.argv.index("--")
offs = [json.load(open(p)) for p in sys.argv[2:sep]]
trcs = [json.load(open(p)) for p in sys.argv[sep + 1:]]
for off in offs:
    assert off["traced"] is False and off["errors"] == 0 and off["value"] > 0, off
for t in trcs:
    assert t["traced"] is True and t["trace_sample_rate"] == 0.01, t
    assert t["errors"] == 0 and t["trace_echo_miss"] == 0, t
# best-of-pairs: on a 2-core CI runner the QPS noise between identical
# runs dwarfs any real tracing cost; the max of each pair is the run
# the scheduler left alone, and THOSE are comparable
best_off = max(offs, key=lambda r: r["value"])
rec = max(trcs, key=lambda r: r["value"])
rec["qps_untraced"] = best_off["value"]
rec["qps_traced"] = rec["value"]
rec["trace_overhead_pct"] = round(
    100.0 * (best_off["value"] - rec["value"]) / best_off["value"], 2)
json.dump(rec, open(sys.argv[1], "w"))
# the acceptance budget is <2%; the CI gate is loose (<30%) so a noisy
# shared runner cannot flake it while a hot-path regression still trips
assert rec["trace_overhead_pct"] < 30.0, rec["trace_overhead_pct"]
print(f"smoke_trace: overhead OK (untraced {rec['qps_untraced']} qps, "
      f"traced@0.01 {rec['qps_traced']} qps, "
      f"overhead {rec['trace_overhead_pct']}%)")
EOF

# standalone, BENCH_OUT sits in the repo root (the per-PR record);
# under pytest, in the workdir — the ledger scans wherever it landed
# capture-then-grep (not `| grep -q`): pipefail + grep's early exit
# would SIGPIPE the ledger mid-print and fail a passing check
python tools/perf_ledger.py --root "$(dirname "$BENCH_OUT")" --markdown - \
    >"$WORK/ledger.md"
grep -q "BENCH_TRACE.json" "$WORK/ledger.md" || {
    echo "smoke_trace: BENCH_TRACE.json never reached the perf ledger"; exit 1; }

# repo-root hygiene: running the tools from the root must leave no
# stray artifact dirs behind (tools/__pycache__ and friends)
rm -rf "$ROOT/tools/__pycache__" "$ROOT/__pycache__"

echo "smoke_trace: OK"
