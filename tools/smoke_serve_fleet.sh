#!/usr/bin/env bash
# Serving-fleet chaos gate (docs/SERVING.md "Fleet"):
#
# 1. Train a small LR run with committed checkpoints every 10 steps
#    (10..50), stage step-20 into a serving dir.
# 2. Start `xflow serve-fleet`: 3 supervised replicas (fixed ports,
#    per-replica restart generations, staggered hot reload) behind the
#    health-checked failover router; wait for the ready line.
# 3. Drive tools/serve_bench.py closed-loop against the ROUTER while
#    the chaos runs:
#      - replica 1 SIGKILLs itself after 25 answered batches (the
#        testing/faults.py serve kill injector — a replica dying
#        MID-LOAD with responses in flight);
#      - a CORRUPT step-40 checkpoint is committed mid-load (payload
#        bitflip with rewritten zip CRCs: only the digest layer can
#        tell) — every replica's staggered reload must fail, log
#        reload_failed, and KEEP SERVING step 20;
#      - then the GOOD step-50 commits and hot-reloads through.
#    Gate: the client saw ZERO failed requests (router retries absorb
#    the kill, the walk-back absorbs the corruption) and served steps
#    flipped 20 -> 50. Emits a BENCH_SERVE-series datapoint
#    (BENCH_SERVE_FLEET.json).
# 4. Rejoin: the killed replica's supervised relaunch (restart
#    generation 1) comes back on its SAME port and the router's
#    half-open probe closes the circuit — /healthz reports 3/3 healthy;
#    circuit_open AND circuit_close events are in the router JSONL,
#    reload_failed in the replica streams, gen-1 records in replica
#    1's stream.
# 5. Ordered drain: SIGTERM -> router drains first, then replicas;
#    exit 0, a drain event in the router JSONL, and
#    tools/metrics_report.py --check green over the whole fleet run
#    dir (replica identity + generation gates included).
#
# Standalone:    bash tools/smoke_serve_fleet.sh [workdir]
# From pytest:   tests/test_serve_fleet.py::test_smoke_serve_fleet_script
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
# bench datapoint destination: the repo root ONLY standalone (the
# per-PR record); under pytest it stays in the workdir
BENCH_OUT="$ROOT/BENCH_SERVE_FLEET.json"
FLEET_PID=""
cleanup() {
    if [ -n "$FLEET_PID" ]; then kill -9 "$FLEET_PID" 2>/dev/null || true; fi
    # replicas are children of the fleet; sweep any orphans by their
    # serving dir (unique to this run)
    pkill -9 -f "serve_ck_fleet" 2>/dev/null || true
    if [ -n "${TMP_WORK:-}" ]; then rm -rf "$TMP_WORK"; fi
}
trap cleanup EXIT
if [ -z "$WORK" ]; then
    TMP_WORK="$(mktemp -d)"
    WORK="$TMP_WORK"
else
    BENCH_OUT="$WORK/BENCH_SERVE_FLEET.json"
fi

export JAX_PLATFORMS=cpu
# single CPU device (xargs trims; an empty result must UNSET the var —
# XLA treats a whitespace-only value as a flags FILE to open and aborts)
XLA_FLAGS="$(printf '%s\n' ${XLA_FLAGS:-} \
    | grep -v xla_force_host_platform_device_count | xargs || true)"
if [ -n "$XLA_FLAGS" ]; then export XLA_FLAGS; else unset XLA_FLAGS; fi

MODEL_ARGS=(--model lr --log2-slots 12
            --set model.num_fields=6 --set data.max_nnz=8)
SERVE_CK="$WORK/serve_ck_fleet"

# ---- 1. train with a checkpoint trail -------------------------------------
python -m xflow_tpu gen-data "$WORK/train" --shards 1 --rows 3200 \
    --fields 6 --ids-per-field 50 --seed 0 >/dev/null
python -m xflow_tpu gen-data "$WORK/reqs" --shards 1 --rows 512 \
    --fields 6 --ids-per-field 50 --seed 9 --truth-seed 0 >/dev/null

python -m xflow_tpu train --train "$WORK/train" "${MODEL_ARGS[@]}" \
    --epochs 1 --batch-size 64 --checkpoint-dir "$WORK/ck" \
    --set train.checkpoint_every=10 --set train.pred_dump=false \
    --set train.log_every=10 >/dev/null 2>"$WORK/train.log"

stage() {  # atomic checkpoint shipping: payload under a temp name, one
    # rename; $2 = "corrupt" applies a SILENT payload bitflip (zip CRCs
    # rewritten — only the per-array digests can catch it) BEFORE the
    # rename, so the fleet sees a committed-but-poisoned checkpoint
    python - "$WORK/ck" "$SERVE_CK" "$1" "${2:-}" <<'EOF'
import os, shutil, sys
src, dst, step, mode = sys.argv[1], sys.argv[2], sys.argv[3], sys.argv[4]
os.makedirs(dst, exist_ok=True)
tmp = os.path.join(dst, f".staging_{step}")
if os.path.exists(tmp):
    shutil.rmtree(tmp)
shutil.copytree(os.path.join(src, f"step_{step}"), tmp)
if mode == "corrupt":
    from xflow_tpu.testing.faults import bitflip_npz_array
    bitflip_npz_array(os.path.join(tmp, "state.npz"), count=8, seed=3)
os.replace(tmp, os.path.join(dst, f"step_{step}"))
EOF
}
stage 20

# ---- 2. start the 3-replica supervised fleet ------------------------------
mkdir -p "$WORK/run_fleet"
# chaos injector: replica 1 SIGKILLs itself after 25 answered batches,
# in restart generation 0 only (the relaunch must survive and rejoin)
export XFLOW_FAULT_SERVE_KILL_BATCHES=25
export XFLOW_FAULT_SERVE_REPLICA=1
export XFLOW_FAULT_SERVE_KILL_GEN=0

python -m xflow_tpu serve-fleet --checkpoint-dir "$SERVE_CK" "${MODEL_ARGS[@]}" \
    --replicas 3 --port 0 --window-ms 3 --max-batch 64 --poll-s 0.3 \
    --reload-stagger-s 0.5 --retries 3 --deadline-ms 15000 \
    --eject-failures 2 --circuit-open-s 1 --health-poll-s 0.2 \
    --run-dir "$WORK/run_fleet" --max-restarts 2 --restart-backoff 0.5 \
    --no-mesh --set serve.metrics_every_s=1 \
    >"$WORK/fleet_ready.json" 2>"$WORK/fleet.log" &
FLEET_PID=$!

for i in $(seq 1 360); do
    [ -s "$WORK/fleet_ready.json" ] && break
    kill -0 "$FLEET_PID" 2>/dev/null || {
        echo "smoke_serve_fleet: fleet died during startup"
        cat "$WORK/fleet.log"; exit 1; }
    sleep 0.5
done
[ -s "$WORK/fleet_ready.json" ] || {
    echo "smoke_serve_fleet: fleet never became ready"
    cat "$WORK/fleet.log"; exit 1; }
PORT=$(python - "$WORK/fleet_ready.json" <<'EOF'
import json, sys
ready = json.load(open(sys.argv[1]))
assert ready["fleet"] and len(ready["replicas"]) == 3, ready
assert all(r["step"] == 20 for r in ready["replicas"]), ready
print(ready["router_port"])
EOF
)

# ---- 3. closed-loop bench through the router + the chaos ------------------
# 16s window: the chaos sequence underneath needs ~11s on a fast run
# (kill + corrupt-40 walk-back + staggered step-50 reload across 3
# replicas) and the shared 1.5-core CI runner can stretch every load
# by seconds — 12s left the gen flip ~1s of margin and flaked
python tools/serve_bench.py --url "http://127.0.0.1:$PORT" \
    --data "$WORK/reqs-00000" --duration 16 --concurrency 4 \
    --rows-per-request 4 --retries 3 --deadline-ms 20000 \
    --bench-json "$BENCH_OUT" \
    >"$WORK/bench_report.json" 2>"$WORK/bench.log" &
BENCH_PID=$!
sleep 3
stage 40 corrupt   # a poisoned checkpoint commits while requests fly
sleep 3
stage 50           # then the good one
rc=0; wait "$BENCH_PID" || rc=$?
[ "$rc" -eq 0 ] || {
    echo "smoke_serve_fleet: loadgen saw unabsorbed failed requests"
    cat "$WORK/bench_report.json" "$WORK/fleet.log"; exit 1; }

python - "$BENCH_OUT" <<'EOF'
import json, sys
rec = json.load(open(sys.argv[1]))
assert rec["errors"] == 0, rec
assert rec["deadline_exceeded"] == 0, rec
steps = rec["steps"]
assert 20 in steps, f"never served the staged step 20: {rec}"
assert 50 in steps, f"the good step 50 never hot-reloaded mid-bench: {rec}"
assert rec["value"] > 0 and rec["p99_ms"] > 0, rec
print("smoke_serve_fleet: chaos OK "
      f"(qps {rec['value']}, p50 {rec['p50_ms']}ms, p99 {rec['p99_ms']}ms, "
      f"{rec['requests']} requests, 0 failed, steps {steps}, "
      f"client retried {rec['retried']})")
EOF

# ---- 4. the killed replica restarted and rejoined -------------------------
python - "$PORT" <<'EOF'
import http.client, json, sys, time

port = int(sys.argv[1])
deadline = time.monotonic() + 180
last = None
while time.monotonic() < deadline:
    try:
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        c.request("GET", "/healthz")
        last = json.loads(c.getresponse().read())
        c.close()
        if last["healthy"] == 3:
            break
    except Exception:
        pass
    time.sleep(0.5)
assert last and last["healthy"] == 3, f"killed replica never rejoined: {last}"
# and the rejoined fleet still answers, at the reloaded step
c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
steps = set()
for _ in range(6):
    c.request("POST", "/predict", json.dumps({"rows": ["0:a 1:b"]}),
              {"Content-Type": "application/json"})
    resp = c.getresponse()
    payload = json.loads(resp.read())
    assert resp.status == 200, payload
    steps.add(payload["step"])
c.close()
assert steps == {50}, f"post-rejoin fleet not uniformly on step 50: {steps}"
print("smoke_serve_fleet: rejoin OK (3/3 healthy, all replicas on step 50)")
EOF

grep -q '"event": "circuit_open"' "$WORK/run_fleet/serve_router.jsonl" || {
    echo "smoke_serve_fleet: no circuit_open event (kill never ejected)"; exit 1; }
grep -q '"event": "circuit_close"' "$WORK/run_fleet/serve_router.jsonl" || {
    echo "smoke_serve_fleet: no circuit_close event (rejoin never closed)"; exit 1; }
# direct grep, not `cat | grep -q`: under pipefail grep's early exit
# SIGPIPEs cat and fails the pipeline even when the event IS there
grep -q '"event": "reload_failed"' "$WORK/run_fleet"/serve_replica*.jsonl || {
    echo "smoke_serve_fleet: no reload_failed (corrupt commit went unnoticed)"; exit 1; }
grep -q '"gen": 1' "$WORK/run_fleet/serve_replica1.jsonl" || {
    echo "smoke_serve_fleet: replica 1 has no restart-generation-1 records"; exit 1; }

# ---- 5. ordered drain + telemetry gates -----------------------------------
kill -TERM "$FLEET_PID"
rc=0; wait "$FLEET_PID" || rc=$?
FLEET_PID=""
[ "$rc" -eq 0 ] || {
    echo "smoke_serve_fleet: fleet exit $rc"; cat "$WORK/fleet.log"; exit 1; }
grep -q '"event": "drain"' "$WORK/run_fleet/serve_router.jsonl" || {
    echo "smoke_serve_fleet: no drain event (router-first shutdown skipped)"; exit 1; }

python tools/metrics_report.py "$WORK/run_fleet" --check

# repo-root hygiene: running the tools from the root must leave no
# stray artifact dirs behind (tools/__pycache__ and friends)
rm -rf "$ROOT/tools/__pycache__" "$ROOT/__pycache__"

echo "smoke_serve_fleet: OK"
