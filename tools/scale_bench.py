"""Realistic-scale baseline run (BASELINE.md configs 2-3; VERDICT r2
missing #1): a ≥10M-row, ≥10^7-distinct-feature Zipf dataset on disk,
trained end-to-end (file → C++ parser → sorted plans → device) with the
full `Trainer`, and the result — e2e throughput, held-out AUC/logloss,
exact collision accounting — recorded as one JSON (BENCH_SCALE.json,
checked into the repo so later rounds regress against it).

No public CTR dataset can be downloaded in this environment (zero
egress), so the dataset is synthetic but *shaped* like Criteo-class
data: heavy-tailed feature frequencies (Zipf α≈1.1 per field), ~10.8M
distinct feature ids over 18 fields hashed into 2^24 slots (real
collision pressure), labels from a planted sparse linear truth with
noise (so held-out AUC measures genuine learning, with a cold tail the
model cannot see at train time — exactly real CTR's regime).

Run on the TPU host:  python tools/scale_bench.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def ensure_data(args) -> dict:
    """Generate train/test shards (reused across runs if present);
    returns generation stats + the exact collision accounting."""
    from xflow_tpu.data.synth import generate_shards_bulk
    from xflow_tpu.hashing import hash_int_tokens, slots_of

    os.makedirs(args.data_dir, exist_ok=True)
    train = os.path.join(args.data_dir, "train")
    test = os.path.join(args.data_dir, "test")
    meta_path = os.path.join(args.data_dir, "meta.json")
    if os.path.exists(meta_path) and not args.force_gen:
        with open(meta_path) as f:
            meta = json.load(f)
        if all(
            meta.get(key) == getattr(args, key)
            for key in ("rows", "test_rows", "fields", "ids_per_field",
                        "zipf_alpha", "log2_slots")
        ):
            print(f"# reusing dataset in {args.data_dir}", file=sys.stderr)
            return meta
    t0 = time.perf_counter()
    # same truth_seed ties train/test to one concept; different row seeds
    _, seen_tr = generate_shards_bulk(
        train, 1, args.rows, num_fields=args.fields,
        ids_per_field=args.ids_per_field, seed=1, truth_seed=7,
        zipf_alpha=args.zipf_alpha, track_seen=True,
    )
    _, seen_te = generate_shards_bulk(
        test, 1, args.test_rows, num_fields=args.fields,
        ids_per_field=args.ids_per_field, seed=2, truth_seed=7,
        zipf_alpha=args.zipf_alpha, track_seen=True,
    )
    gen_s = time.perf_counter() - t0
    # exact collision accounting from the emitted-id map — no 180M-token
    # file re-scan; hash_int_tokens is bit-identical to hashing str(gid)
    gids = np.flatnonzero(seen_tr | seen_te)
    hashes = hash_int_tokens(gids.astype(np.uint64))
    slots = slots_of(hashes, args.log2_slots)
    n_tok = int(gids.size)
    n_slot = int(np.unique(slots).size)
    meta = {
        "rows": args.rows,
        "test_rows": args.test_rows,
        "fields": args.fields,
        "ids_per_field": args.ids_per_field,
        "zipf_alpha": args.zipf_alpha,
        "gen_seconds": round(gen_s, 1),
        "gen_rows_per_sec": round((args.rows + args.test_rows) / gen_s, 1),
        "train_bytes": os.path.getsize(train + "-00000"),
        "distinct_features": n_tok,
        "distinct_hash64": int(np.unique(hashes).size),
        "distinct_slots": n_slot,
        "log2_slots": args.log2_slots,
        "collision_rate": round(1.0 - n_slot / n_tok, 6) if n_tok else 0.0,
        "table_occupancy_bound": round(n_slot / float(1 << args.log2_slots), 6),
    }
    with open(meta_path, "w") as f:
        json.dump(meta, f, indent=1)
    print(f"# generated: {json.dumps(meta)}", file=sys.stderr)
    return meta


def ensure_ffm_data(args) -> dict:
    """FFM-truth companion dataset (VERDICT r4 item 7): same 10M-row
    Zipf shape but labels from the planted field-PAIR interaction
    concept (`truth="ffm"`, data/synth.py) — the scale anchor for the
    model family the linear truth cannot exercise. 2^22 slots, not the
    main run's 2^24: FFM's fused [S, 1+nf·k] FTRL state at 2^24 is
    29 GB (bench.py ffm_s24_note), and the anchor's job is an AUC
    regression line, which collisions at 3.6M ids → 2^22 still leave
    meaningful."""
    from xflow_tpu.data.synth import generate_shards_bulk

    ddir = args.ffm_data_dir
    os.makedirs(ddir, exist_ok=True)
    meta_path = os.path.join(ddir, "meta.json")
    want = {
        "rows": args.rows,
        "test_rows": args.test_rows,
        "fields": args.fields,
        "ids_per_field": args.ffm_ids_per_field,
        "zipf_alpha": args.zipf_alpha,
        "truth": "ffm",
    }
    if os.path.exists(meta_path) and not args.force_gen:
        with open(meta_path) as f:
            meta = json.load(f)
        if all(meta.get(k) == v for k, v in want.items()):
            print(f"# reusing ffm dataset in {ddir}", file=sys.stderr)
            return meta
    t0 = time.perf_counter()
    generate_shards_bulk(
        os.path.join(ddir, "train"), 1, args.rows, num_fields=args.fields,
        ids_per_field=args.ffm_ids_per_field, seed=1, truth_seed=7,
        zipf_alpha=args.zipf_alpha, truth="ffm",
    )
    generate_shards_bulk(
        os.path.join(ddir, "test"), 1, args.test_rows, num_fields=args.fields,
        ids_per_field=args.ffm_ids_per_field, seed=2, truth_seed=7,
        zipf_alpha=args.zipf_alpha, truth="ffm",
    )
    meta = {**want, "gen_seconds": round(time.perf_counter() - t0, 1)}
    with open(meta_path, "w") as f:
        json.dump(meta, f, indent=1)
    print(f"# generated ffm dataset: {json.dumps(meta)}", file=sys.stderr)
    return meta


def run_model(name: str, args, data_dir=None, log2_slots=None,
              extra_cfg=None) -> dict:
    from xflow_tpu.config import Config, override
    from xflow_tpu.train.trainer import Trainer

    use_cache = bool(getattr(args, "cache", False))
    cfg = override(
        Config(),
        **{
            "model.name": name,
            "data.train_path": os.path.join(data_dir or args.data_dir, "train"),
            "data.test_path": os.path.join(data_dir or args.data_dir, "test"),
            "data.batch_size": args.batch,
            "data.max_nnz": args.fields,
            "data.log2_slots": log2_slots or args.log2_slots,
            # --cache: the parse/hash-free input path (data/shardcache.py);
            # "on" so a missing/stale cache fails loudly instead of
            # silently re-measuring the text path it claims to replace.
            # The baseline leg pins "off" — NOT auto — so leftover .xfc
            # files from a previous --cache run can never silently turn
            # the text trajectory into an unlabeled cached measurement
            "data.cache": "on" if use_cache else "off",
            "model.num_fields": args.fields,
            "train.epochs": args.epochs,
            "train.pred_dump": False,
            "train.log_every": 0,
            **(extra_cfg or {}),
            # plain-product MVM's exact gradients vanish multiplicatively
            # at 18 all-present fields with the 1e-2 reference init
            # (tests/test_mvm_product.py::test_plus_one_learns_...), so
            # the scale baseline records the bias-augmented factor form —
            # the one the reference's own hand gradient assumes
            **({"model.mvm_plus_one": args.mvm_plus_one} if name == "mvm" else {}),
        },
    )
    if use_cache:
        # build once per (data dir, hash config): build_cache skips
        # shards whose cache is already fresh, so the second model on
        # the same dataset pays ~nothing here
        from xflow_tpu.data.shardcache import build_cache

        t0 = time.perf_counter()
        built = {}
        for split in ("train", "test"):
            built[split] = build_cache(
                os.path.join(data_dir or args.data_dir, split), cfg.data
            )
        print(
            f"# {name}: shard cache "
            + json.dumps({k: v for k, v in built.items()})
            + f" ({time.perf_counter() - t0:.1f}s)",
            file=sys.stderr,
        )
    trainer = Trainer(cfg)
    res = trainer.fit()
    t0 = time.perf_counter()
    auc, logloss = trainer.evaluate(dump=False)
    eval_s = time.perf_counter() - t0
    rec = {
        "examples_per_sec_e2e": round(res.examples_per_sec, 1),
        "train_seconds": round(res.seconds, 1),
        "steps": res.steps,
        "epochs": res.epochs,
        "batch_size": args.batch,
        "examples": res.examples,
        "last_loss": round(res.last_loss, 6),
        "test_auc": round(auc, 6),
        "test_logloss": round(logloss, 6),
        "eval_seconds": round(eval_s, 1),
        "occupancy": {k: round(v, 6) for k, v in res.occupancy.items()},
    }
    if name == "mvm":
        rec["mvm_plus_one"] = args.mvm_plus_one
    if use_cache:
        # stamped so a merged BENCH_SCALE.json can never silently mix
        # cached and text-path rounds under one unlabeled number
        rec["cache"] = True
    print(f"# {name}: {json.dumps(rec)}", file=sys.stderr)
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_000_000)
    ap.add_argument("--test-rows", type=int, default=1_000_000)
    ap.add_argument("--fields", type=int, default=18)
    # default matches the committed BENCH_SCALE.json meta (ids_per_field
    # 1M -> 10.57M distinct features into 2^24 slots): a bare
    # `python tools/scale_bench.py` reuses/regenerates the SAME dataset
    # and regresses against the recorded numbers
    ap.add_argument("--ids-per-field", type=int, default=1_000_000)
    ap.add_argument("--zipf-alpha", type=float, default=1.1)
    ap.add_argument("--log2-slots", type=int, default=24)
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--models", default="lr,fm,mvm,ffm")
    ap.add_argument("--mvm-plus-one", action=argparse.BooleanOptionalAction,
                    default=True)
    ap.add_argument("--data-dir", default=os.path.join(REPO, "scale_data"))
    ap.add_argument("--ffm-data-dir",
                    default=os.path.join(REPO, "scale_data_ffm"))
    ap.add_argument("--ffm-ids-per-field", type=int, default=200_000)
    ap.add_argument("--ffm-log2-slots", type=int, default=22)
    ap.add_argument("--out", default=os.path.join(REPO, "BENCH_SCALE.json"))
    ap.add_argument("--force-gen", action="store_true")
    ap.add_argument("--cache", action="store_true",
                    help="pack the train/test shards into the binary shard "
                         "cache first (data/shardcache.py) and run every "
                         "model with data.cache=on — the parse/hash-free "
                         "e2e numbers; each model record stamps cache=true")
    args = ap.parse_args()

    models = args.models.split(",")
    # the linear-truth dataset feeds lr/fm/mvm only; an ffm-only run
    # must not spend minutes generating 12M rows it never reads
    meta = (
        ensure_data(args)
        if any(m != "ffm" for m in models)
        else {"note": "linear dataset not touched (ffm-only run)"}
    )
    import jax

    # epochs/batch live PER MODEL record: partial runs (--models subset)
    # merge into the committed file, and a top-level stamp would
    # misattribute the merged entries' provenance
    record = {
        "dataset": meta,
        "device": str(jax.devices()[0]),
        "host_cores": os.cpu_count(),
        "models": {},
    }
    if os.path.exists(args.out):
        # partial runs (--models subset) MERGE into the committed record
        # instead of silently dropping the other models' anchors
        try:
            with open(args.out) as f:
                prev = json.load(f)
            record["models"].update(prev.get("models", {}))
            if "ffm_dataset" in prev:
                record["ffm_dataset"] = prev["ffm_dataset"]
            if "note" in record["dataset"] and "dataset" in prev:
                # ffm-only run: keep the committed linear-dataset meta
                record["dataset"] = prev["dataset"]
        except (json.JSONDecodeError, OSError) as e:
            print(f"# ignoring unreadable {args.out}: {e}", file=sys.stderr)
    for name in models:
        if name == "ffm":
            continue  # its own dataset/truth below
        record["models"][name] = run_model(name, args)
    if "ffm" in models:
        # FFM anchors on its OWN dataset (planted field-pair truth) at
        # 2^22 slots, with an FM companion on the SAME data so the
        # "FFM beats a field-blind FM on this concept" gate
        # (tests/test_ffm.py) has a scale-sized counterpart
        ffm_meta = ensure_ffm_data(args)
        record["ffm_dataset"] = ffm_meta
        # SGD with a real v init, like the unit gate
        # (tests/test_ffm.py::test_ffm_beats_fm_...): under the
        # reference-default zero-init FTRL, interaction gradients
        # (∝ the opposing vectors = 0) never bootstrap and BOTH models
        # collapse to the identical pure-LR predictor — measured here
        # before this override existed: ffm and fm both landed at AUC
        # 0.541991 bitwise-equal.
        # lr and init are scale-tuned, NOT the unit gate's (256-row
        # batches, nf=4, lr 0.5, v_init 0.1): at nf=18 a constant
        # v_init of 0.1 puts the initial pairwise term at
        # ~0.5*nf^2*k*v^2 = +6.1 — every sigmoid saturated from step 0
        # (measured: loss climbs to ~1.0, AUC ~0.50 at both lr 0.5 and
        # 0.1). v = 0.02 keeps the initial term ~0.25.
        sgd = {"optim.name": "sgd", "optim.sgd.lr": 0.1,
               "optim.v_init_sgd": 0.02}
        record["models"]["ffm"] = run_model(
            "ffm", args, data_dir=args.ffm_data_dir,
            log2_slots=args.ffm_log2_slots,
            extra_cfg={"model.v_dim": 4, **sgd},
        )
        record["models"]["fm_on_ffm_truth"] = run_model(
            "fm", args, data_dir=args.ffm_data_dir,
            log2_slots=args.ffm_log2_slots,
            extra_cfg={"model.v_dim": 16, **sgd},
        )
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps({"metric": "scale_bench", "out": args.out,
                      **{f"{m}_auc": r["test_auc"]
                         for m, r in record["models"].items()}}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
