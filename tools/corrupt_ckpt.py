"""Deliberately corrupt a checkpoint — the operator's fire drill.

Shares its injectors with the tier-1 fault-injection tests
(xflow_tpu/testing/faults.py), so rehearsing recovery on a staging
checkpoint dir exercises EXACTLY the code paths the tests prove:
truncate or bit-flip the newest (or a chosen) checkpoint, then run the
normal resume and watch `restore_any` walk back to the previous
committed step (docs/ROBUSTNESS.md).

    # truncate the newest npz checkpoint to half its bytes
    python tools/corrupt_ckpt.py --dir ckpt

    # SILENT corruption drill (checkpoint digests, docs/ROBUSTNESS.md):
    # flip bytes inside the npz's array payload and rewrite the
    # container, so every zip-level check still passes and only the
    # meta.json per-array digests catch it on restore
    python tools/corrupt_ckpt.py --dir ckpt --mode bitflip

    # flip 8 random bits in a specific orbax step's data file (OCDBT
    # reads are not checksum-verified — also a digest-layer drill)
    python tools/corrupt_ckpt.py --dir ckpt --format orbax \\
        --step 1200 --mode bitflip --target largest

    # corrupt an arbitrary file (no checkpoint-layout assumptions;
    # raw byte flips, so an npz fails at the zip layer instead)
    python tools/corrupt_ckpt.py --file ckpt/step_10/state.npz --mode truncate

    # replica-tier drill (train.ckpt_replica_dir): poison the MIRROR
    # instead of the primary — restore_tiered must detect the divergence
    # and fall back to the primary copy of the same step
    python tools/corrupt_ckpt.py --dir ckpt --tier replica \\
        --replica-dir ckpt_replica --mode bitflip
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xflow_tpu.testing.faults import (  # noqa: E402
    bitflip_file,
    corrupt_npz_checkpoint,
    corrupt_orbax_checkpoint,
    truncate_file,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="deliberately corrupt a checkpoint (recovery drills)"
    )
    tgt = ap.add_mutually_exclusive_group(required=True)
    tgt.add_argument("--dir", help="checkpoint dir (train.checkpoint_dir)")
    tgt.add_argument("--file", help="corrupt this exact file instead")
    ap.add_argument("--format", default="npz", choices=("npz", "orbax"),
                    help="checkpoint format under --dir")
    ap.add_argument("--tier", default="primary",
                    choices=("primary", "replica"),
                    help="which checkpoint tier to poison: primary = "
                         "--dir itself; replica = the mirror under "
                         "--replica-dir (identical layout, so the same "
                         "injectors apply)")
    ap.add_argument("--replica-dir", default=None,
                    help="replica tier dir (train.ckpt_replica_dir); "
                         "required with --tier replica")
    ap.add_argument("--step", type=int, default=None,
                    help="step to corrupt (default: newest committed)")
    ap.add_argument("--mode", default="truncate", choices=("truncate", "bitflip"))
    ap.add_argument("--target", default=None,
                    help="which file of the checkpoint to corrupt: npz "
                         "state|data_state (default state), orbax "
                         "manifest|largest|data_state (default manifest). "
                         "data_state drills the exact-resume downgrade: the "
                         "model still restores, the stream restarts fresh "
                         "with a logged warning")
    ap.add_argument("--keep-frac", type=float, default=0.5,
                    help="truncate: fraction of bytes to keep")
    ap.add_argument("--offset", type=int, default=None,
                    help="bitflip: pin the first flipped byte")
    ap.add_argument("--count", type=int, default=8,
                    help="bitflip: number of bytes to flip")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    kw = dict(keep_frac=args.keep_frac, offset=args.offset,
              count=args.count, seed=args.seed)
    if args.tier == "replica" and not args.file:
        if not args.replica_dir:
            ap.error("--tier replica requires --replica-dir")
        # the mirror keeps the primary's exact layout, so the tier
        # switch is just a dir switch for the shared injectors
        args.dir = args.replica_dir
    if args.file:
        if args.mode == "truncate":
            truncate_file(args.file, keep_frac=args.keep_frac)
        else:
            bitflip_file(args.file, offset=args.offset, count=args.count,
                         seed=args.seed)
        path = args.file
    elif args.format == "orbax":
        path = corrupt_orbax_checkpoint(args.dir, step=args.step,
                                        mode=args.mode,
                                        target=args.target or "manifest", **kw)
    else:
        path = corrupt_npz_checkpoint(args.dir, step=args.step,
                                      mode=args.mode,
                                      target=args.target or "state", **kw)
    print(json.dumps({"corrupted": path, "mode": args.mode,
                      "tier": args.tier,
                      "size": os.path.getsize(path)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
