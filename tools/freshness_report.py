#!/usr/bin/env python3
"""Freshness Δ report: one trace id from ingested row to served
prediction (docs/SERVING.md "Freshness", docs/OBSERVABILITY.md
"Freshness tracing").

The streaming loop leaves four breadcrumbs in ordinary metrics JSONL,
all carrying the SAME ingest trace id:

    kind="ingest"              the tail follower sealed a segment
    kind="publish"             the trainer published a mid-run checkpoint
    span name="publish"        the same publication as a linked span
    span name="reload"/        the serve replica swapped the published
         "serve_load"          generation in (one per replica)
    span name="serve_first"    the first prediction served off it

This tool reassembles the loop across process boundaries — the trainer
and every replica write SEPARATE files; the trace id is the join key —
and decomposes the end-to-end delta:

    fresh_delta_s           serve_first.t0 - ingest_ts   (the headline)
    fresh_ingest_publish_s  published_ts  - ingest_ts    (train + save)
    fresh_publish_swap_s    reload end    - published_ts (detect + load)
    fresh_swap_serve_s      serve_first   - reload end   (first traffic)

Fleet semantics: per trace, each leg takes the WORST replica (max) —
freshness is an SLO, and the SLO is only as good as the stalest
replica. The headline is the max over fully-closed traces (a trace is
closed once at least one replica served off it).

    python tools/freshness_report.py RUNDIR [RUNDIR...]
    python tools/freshness_report.py RUNDIR --checkpoint-dir CKPT \
        --bench-json BENCH_FRESH.json --round 18 --max-delta-s 60

`--checkpoint-dir` folds in the publication.json sidecars'
`consumed_ts` (checkpoint.read_publication), splitting the first leg
into ingest->consume (queue/poll latency) and consume->publish
(train + save). `--bench-json` writes the perf-ledger record
(series "fresh", every leg gated DOWNWARD — tools/perf_ledger.py).
`--max-delta-s` gates: exit 3 when the headline exceeds it, or when no
trace closed at all (a loop that never closes is the worst staleness).
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xflow_tpu.jsonl import read_jsonl_counted  # noqa: E402

RELOAD_SPAN_NAMES = ("reload", "serve_load")


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


def expand_paths(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(glob.glob(os.path.join(p, "*.jsonl")))
            if not found:
                raise FileNotFoundError(
                    f"{p!r}: directory holds no *.jsonl files"
                )
            out.extend(found)
        elif not os.path.exists(p):
            raise FileNotFoundError(f"{p!r}: no such file")
        else:
            out.append(p)
    return out


def load_records(files: list[str]) -> list[dict]:
    recs: list[dict] = []
    for path in files:
        rows, _bad = read_jsonl_counted(path)
        recs.extend(r for r in rows if isinstance(r, dict))
    return recs


def assemble(records: list[dict], ckpt_dir: str = "",
             fmt: str = "npz") -> dict:
    """{trace: {ingest, publish, publish_span, reloads, firsts,
    sidecar}} — the cross-boundary join, keyed by the ingest trace id.
    Only traces a publication carried matter here: an ingest segment
    that never reached a publication is open by definition and reported
    in the totals, not the table."""
    ingests: dict = {}
    publishes: dict = {}
    publish_spans: dict = {}
    reloads: dict = {}
    firsts: dict = {}
    n_segments = 0
    for r in records:
        kind = r.get("kind")
        trace = r.get("trace")
        if not isinstance(trace, str) or not trace:
            continue
        if kind == "ingest":
            n_segments += 1
            ingests[trace] = r
        elif kind == "publish":
            publishes[trace] = r
        elif kind == "span":
            name = r.get("name")
            if name == "publish":
                publish_spans[trace] = r
            elif name in RELOAD_SPAN_NAMES:
                reloads.setdefault(trace, []).append(r)
            elif name == "serve_first":
                firsts.setdefault(trace, []).append(r)
    out: dict = {}
    for trace, pub in sorted(publishes.items(), key=lambda kv: (
            kv[1].get("seq", 0), kv[0])):
        entry = {
            "ingest": ingests.get(trace),
            "publish": pub,
            "publish_span": publish_spans.get(trace),
            "reloads": reloads.get(trace, []),
            "firsts": firsts.get(trace, []),
            "sidecar": None,
        }
        if ckpt_dir and _finite(pub.get("step")):
            from xflow_tpu.train import checkpoint as ckpt

            entry["sidecar"] = ckpt.read_publication(
                ckpt_dir, int(pub["step"]), fmt=fmt
            )
        out[trace] = entry
    out["_n_segments"] = n_segments
    return out


def _span_end(span: dict):
    if not (_finite(span.get("t0")) and _finite(span.get("dur_ms"))):
        return None
    return span["t0"] + span["dur_ms"] / 1e3


def decompose(entry: dict):
    """One publication's Δ legs, worst replica per leg; None when the
    loop has not closed (no replica served off this trace yet)."""
    pub = entry["publish"]
    if not (_finite(pub.get("ingest_ts")) and _finite(pub.get("published_ts"))):
        return None
    ingest_ts, published_ts = pub["ingest_ts"], pub["published_ts"]
    row = {
        "trace": pub["trace"],
        "step": pub.get("step"),
        "seq": pub.get("seq"),
        "ingest_ts": ingest_ts,
        "published_ts": published_ts,
        "fresh_ingest_publish_s": max(published_ts - ingest_ts, 0.0),
        "replicas": 0,
        "closed": False,
    }
    side = entry.get("sidecar")
    if isinstance(side, dict) and _finite(side.get("consumed_ts")):
        # the sidecar splits the first leg: poll/queue vs train+save
        row["fresh_ingest_consume_s"] = max(
            side["consumed_ts"] - ingest_ts, 0.0
        )
        row["fresh_consume_publish_s"] = max(
            published_ts - side["consumed_ts"], 0.0
        )
    # per replica: the swap that installed this publication, then the
    # first prediction served off it — join reload -> serve_first by
    # the serve_first's parent (the reload's span id) falling back to
    # rank stamps when the parent link is absent
    swaps = []
    for rel in entry["reloads"]:
        end = _span_end(rel)
        if end is None:
            continue
        first_t0 = None
        for sf in entry["firsts"]:
            if not _finite(sf.get("t0")):
                continue
            linked = sf.get("parent") == rel.get("span") or (
                "parent" not in sf and sf.get("rank") == rel.get("rank")
            )
            if linked and (first_t0 is None or sf["t0"] < first_t0):
                first_t0 = sf["t0"]
        swaps.append((end, first_t0))
    if swaps:
        row["replicas"] = len(swaps)
        row["fresh_publish_swap_s"] = max(
            max(end - published_ts, 0.0) for end, _ in swaps
        )
        closed = [(end, ft) for end, ft in swaps if ft is not None]
        if closed:
            row["closed"] = True
            row["fresh_swap_serve_s"] = max(
                max(ft - end, 0.0) for end, ft in closed
            )
            row["fresh_delta_s"] = max(
                max(ft - row["ingest_ts"], 0.0) for _end, ft in closed
            )
    return row


def report(traces: dict) -> dict:
    rows = []
    for trace, entry in traces.items():
        if trace == "_n_segments":
            continue
        row = decompose(entry)
        if row is not None:
            rows.append(row)
    closed = [r for r in rows if r["closed"]]
    out = {
        "rows": rows,
        "segments": traces.get("_n_segments", 0),
        "publications": len(rows),
        "closed": len(closed),
        "replicas": max((r["replicas"] for r in rows), default=0),
    }
    # the headline + legs: worst case over closed traces — the SLO view
    for leg in ("fresh_delta_s", "fresh_ingest_publish_s",
                "fresh_ingest_consume_s", "fresh_consume_publish_s",
                "fresh_publish_swap_s", "fresh_swap_serve_s"):
        vals = [r[leg] for r in closed if _finite(r.get(leg))]
        if vals:
            out[leg] = round(max(vals), 3)
    return out


def render(rep: dict) -> str:
    fmt = lambda v: f"{v:.3f}" if _finite(v) else "-"
    lines = [
        "freshness report — ingested row -> served prediction",
        f"  segments ingested: {rep['segments']}  publications: "
        f"{rep['publications']}  closed traces: {rep['closed']}  "
        f"replicas: {rep['replicas']}",
    ]
    for r in rep["rows"]:
        state = "closed" if r["closed"] else "OPEN (no serve_first yet)"
        lines.append(
            f"  trace {r['trace']} (step {r['step']}, seq {r['seq']}): "
            f"{state}"
        )
        lines.append(
            f"    ingest->publish {fmt(r.get('fresh_ingest_publish_s'))}s"
            + (
                f" (consume split: {fmt(r.get('fresh_ingest_consume_s'))}s"
                f" + {fmt(r.get('fresh_consume_publish_s'))}s)"
                if "fresh_ingest_consume_s" in r else ""
            )
            + f"  publish->swap {fmt(r.get('fresh_publish_swap_s'))}s"
            f"  swap->serve {fmt(r.get('fresh_swap_serve_s'))}s"
            f"  TOTAL {fmt(r.get('fresh_delta_s'))}s"
        )
    if _finite(rep.get("fresh_delta_s")):
        lines.append(
            f"  fleet freshness delta (worst closed trace, worst "
            f"replica): {rep['fresh_delta_s']:.3f}s"
        )
    else:
        lines.append(
            "  fleet freshness delta: unmeasurable — no trace closed "
            "(did any replica serve a published generation?)"
        )
    return "\n".join(lines)


def bench_record(rep: dict, rnd) -> dict:
    rec = {
        "metric": "fresh_delta_s",
        "value": rep.get("fresh_delta_s"),
        "unit": "s",
        "segments": rep["segments"],
        "publications": rep["publications"],
        "traces": rep["closed"],
        "replicas": rep["replicas"],
    }
    if rnd is not None:
        rec["round"] = int(rnd)
    for leg in ("fresh_ingest_publish_s", "fresh_ingest_consume_s",
                "fresh_consume_publish_s", "fresh_publish_swap_s",
                "fresh_swap_serve_s"):
        if _finite(rep.get(leg)):
            rec[leg] = rep[leg]
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="assemble + decompose the ingest->serve freshness Δ "
        "from metrics JSONL streams"
    )
    ap.add_argument("paths", nargs="+",
                    help="metrics .jsonl files or run directories "
                    "(trainer + every replica — the trace id joins them)")
    ap.add_argument("--checkpoint-dir", default="",
                    help="fold in publication.json sidecars (splits the "
                    "ingest->publish leg at consumed_ts)")
    ap.add_argument("--checkpoint-format", default="npz",
                    choices=("npz", "orbax"))
    ap.add_argument("--json", default="", metavar="OUT",
                    help="write the full report JSON ('-' = stdout)")
    ap.add_argument("--bench-json", default="", metavar="OUT",
                    help="write the perf-ledger record (BENCH_FRESH.json)")
    ap.add_argument("--round", default=None, type=int,
                    help="round stamp for the bench record")
    ap.add_argument("--max-delta-s", default=0.0, type=float,
                    help="gate: exit 3 when the headline delta exceeds "
                    "this (or no trace closed); 0 = report only")
    args = ap.parse_args(argv)

    try:
        files = expand_paths(args.paths)
    except FileNotFoundError as e:
        print(f"freshness_report: {e}", file=sys.stderr)
        return 2
    records = load_records(files)
    traces = assemble(records, args.checkpoint_dir, args.checkpoint_format)
    rep = report(traces)
    print(render(rep))
    if args.json:
        payload = json.dumps(rep, indent=1)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w") as f:
                f.write(payload + "\n")
    if args.bench_json:
        with open(args.bench_json, "w") as f:
            f.write(json.dumps(bench_record(rep, args.round), indent=1) + "\n")
    if args.max_delta_s > 0:
        delta = rep.get("fresh_delta_s")
        if not _finite(delta):
            print(
                "freshness_report: GATE: no closed trace — the loop "
                "never reached a served prediction",
                file=sys.stderr,
            )
            return 3
        if delta > args.max_delta_s:
            print(
                f"freshness_report: GATE: fresh_delta_s {delta:.3f}s > "
                f"--max-delta-s {args.max_delta_s:.3f}s",
                file=sys.stderr,
            )
            return 3
        print(
            f"freshness_report: gate ok ({delta:.3f}s <= "
            f"{args.max_delta_s:.3f}s)"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
