#!/usr/bin/env bash
# Closed-loop autotuning smoke gate (docs/SERVING.md "Autotuning"):
#
# 1. Train a small LR run with committed checkpoints and stage one into
#    a serving dir (the smoke_serve.sh recipe, minus the reload drill —
#    tools/smoke_serve.sh owns that).
# 2. Start `xflow serve` DELIBERATELY MIS-TUNED: a 50 ms coalescing
#    window against serve.slo_p99_ms=15, autotune on, a 16,64 ladder.
#    The ready path must report the precompiled rung count.
# 3. Drive a low-concurrency closed loop so the fat window dominates
#    queue wait; the controller must walk window_ms DOWN (kind=
#    "autotune" decision trail in the metrics stream: queue_dominated
#    shrinks first, the final window well under the mis-tuned start,
#    an `autotune` operational span per decision, live state in
#    /stats).
# 4. Headline bench on the CONVERGED server: tools/serve_bench.py
#    closed-loop at higher concurrency emits BENCH_SERVE_r17.json with
#    the SLO attainment gate on — >= 2x the round-9 baseline QPS at
#    equal-or-better p99 (docs/PERF.md "Bench trajectory").
# 5. tools/metrics_report.py --check green (autotune schema + serve +
#    exactly-once per-rung compile records), --health names the
#    trajectory without an oscillating verdict, and
#    tools/perf_ledger.py --regress stays green with r17 folded in.
#
# Standalone:    bash tools/smoke_autotune.sh [workdir]
# From pytest:   tests/test_serve_autotune.py::test_smoke_autotune_script
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
# bench datapoint destination: the repo root ONLY standalone (the
# per-PR record); under pytest it stays in the workdir
BENCH_OUT="$ROOT/BENCH_SERVE_r17.json"
SERVE_PID=""
cleanup() {
    if [ -n "$SERVE_PID" ]; then kill -9 "$SERVE_PID" 2>/dev/null || true; fi
    if [ -n "${TMP_WORK:-}" ]; then rm -rf "$TMP_WORK"; fi
}
trap cleanup EXIT
if [ -z "$WORK" ]; then
    TMP_WORK="$(mktemp -d)"
    WORK="$TMP_WORK"
else
    BENCH_OUT="$WORK/BENCH_SERVE_r17.json"
fi

export JAX_PLATFORMS=cpu
# single CPU device (xargs trims; an empty result must UNSET the var —
# XLA treats a whitespace-only value as a flags FILE to open and aborts)
XLA_FLAGS="$(printf '%s\n' ${XLA_FLAGS:-} \
    | grep -v xla_force_host_platform_device_count | xargs || true)"
if [ -n "$XLA_FLAGS" ]; then export XLA_FLAGS; else unset XLA_FLAGS; fi

MODEL_ARGS=(--model lr --log2-slots 12
            --set model.num_fields=6 --set data.max_nnz=8)

# ---- 1. train + stage a checkpoint ----------------------------------------
python -m xflow_tpu gen-data "$WORK/train" --shards 1 --rows 3200 \
    --fields 6 --ids-per-field 50 --seed 0 >/dev/null
python -m xflow_tpu gen-data "$WORK/reqs" --shards 1 --rows 512 \
    --fields 6 --ids-per-field 50 --seed 9 --truth-seed 0 >/dev/null

python -m xflow_tpu train --train "$WORK/train" "${MODEL_ARGS[@]}" \
    --epochs 1 --batch-size 64 --checkpoint-dir "$WORK/ck" \
    --set train.checkpoint_every=50 --set train.pred_dump=false \
    --set train.log_every=10 >/dev/null 2>"$WORK/train.log"

mkdir -p "$WORK/serve_ck"
cp -r "$WORK/ck/step_50" "$WORK/serve_ck/step_50.tmp"
mv "$WORK/serve_ck/step_50.tmp" "$WORK/serve_ck/step_50"

# ---- 2. serve mis-tuned with the controller on ----------------------------
mkdir -p "$WORK/run_serve"
python -m xflow_tpu serve --checkpoint-dir "$WORK/serve_ck" "${MODEL_ARGS[@]}" \
    --port 0 --window-ms 50 --max-batch 64 --poll-s 5 --no-mesh \
    --metrics-path "$WORK/run_serve/serve_rank0.jsonl" \
    --set serve.metrics_every_s=0.5 \
    --set serve.autotune=on --set serve.slo_p99_ms=15 \
    --set serve.ladder=16,64 \
    >"$WORK/serve_ready.json" 2>"$WORK/serve.log" &
SERVE_PID=$!

for i in $(seq 1 240); do
    [ -s "$WORK/serve_ready.json" ] && break
    kill -0 "$SERVE_PID" 2>/dev/null || {
        echo "smoke_autotune: server died during startup"; cat "$WORK/serve.log"; exit 1; }
    sleep 0.5
done
[ -s "$WORK/serve_ready.json" ] || {
    echo "smoke_autotune: server never became ready"; cat "$WORK/serve.log"; exit 1; }
PORT=$(python -c "import json,sys; print(json.load(open(sys.argv[1]))['port'])" \
    "$WORK/serve_ready.json")
grep -q 'precompiled 2 ladder rung' "$WORK/serve.log" || {
    echo "smoke_autotune: ladder was not precompiled at startup"
    cat "$WORK/serve.log"; exit 1; }

# ---- 3. converge under low-concurrency load -------------------------------
# 4 in-flight x 4 rows = 16 queued rows: never reaches the 64-row size
# flush, so the mis-tuned 50 ms deadline IS the latency — queue-wait
# dominated, exactly what the controller must steer out of
python tools/serve_bench.py --url "http://127.0.0.1:$PORT" \
    --data "$WORK/reqs-00000" --duration 12 --concurrency 4 \
    --rows-per-request 4 >"$WORK/bench_converge.json" 2>"$WORK/bench1.log" || {
    echo "smoke_autotune: convergence loadgen failed"
    cat "$WORK/bench1.log" "$WORK/serve.log"; exit 1; }

# live controller state while the server is still up
python - "$PORT" <<'EOF'
import http.client, json, sys
conn = http.client.HTTPConnection("127.0.0.1", int(sys.argv[1]), timeout=30)
conn.request("GET", "/stats")
s = json.loads(conn.getresponse().read())
at = s.get("autotune")
assert isinstance(at, dict), f"/stats has no autotune state: {list(s)}"
assert at["slo_p99_ms"] == 15.0 and at["rungs"] == [16, 64], at
assert at["windows_seen"] > 0, at
print(f"smoke_autotune: /stats live state OK (window_ms {at['window_ms']}, "
      f"rung {at['rung']}, {at['decisions']} decision(s))")
EOF

# the decision trail: queue_dominated shrinks first, the window ends
# well under the mis-tuned start, and every decision has its span
python - "$WORK/run_serve/serve_rank0.jsonl" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
dec = [r for r in recs if r.get("kind") == "autotune"]
assert len(dec) >= 2, f"only {len(dec)} autotune decision(s)"
win = [r for r in dec if r["knob"] == "window_ms"]
assert win, "no window_ms decisions"
assert win[0]["reason"] == "queue_dominated", win[0]
assert win[0]["old"] >= 40.0, f"first decision not from the mis-tuned start: {win[0]}"
final = win[-1]["new"]
assert final <= 15.0, f"window never converged under the SLO budget: {final} ms"
spans = [r for r in recs if r.get("kind") == "span" and r.get("name") == "autotune"]
assert len(spans) >= 1, "no autotune operational span"
print(f"smoke_autotune: converged OK ({len(dec)} decision(s), "
      f"window_ms {win[0]['old']} -> {final})")
EOF

# ---- 4. headline bench on the converged server ----------------------------
# SLO attainment doubles as the p99 gate: >= 99% of requests inside the
# round-9 baseline p99 pins "equal-or-better tail" client-side; the
# --retries are for transient transport blips only (absorbed retries
# are not errors — serve_bench's documented contract). 8 in-flight x 8
# rows = 64 queued rows = the top ladder rung: flushes trigger on SIZE,
# so the headline holds wherever inside the band the controller parked
# the window (12.5 or 6.25 ms both satisfy the hysteresis hold)
python - "$ROOT/BENCH_SERVE.json" >"$WORK/baseline.env" <<'EOF'
import json, sys
b = json.load(open(sys.argv[1]))
print(f"BASE_QPS={b['value']}")
print(f"BASE_P99={b['p99_ms']}")
EOF
. "$WORK/baseline.env"

python tools/serve_bench.py --url "http://127.0.0.1:$PORT" \
    --data "$WORK/reqs-00000" --duration 8 --concurrency 8 \
    --rows-per-request 8 --retries 2 --bench-json "$BENCH_OUT" --round 17 \
    --slo-ms "$BASE_P99" --min-attainment 99 \
    >"$WORK/bench_report.json" 2>"$WORK/bench2.log" || {
    echo "smoke_autotune: headline loadgen failed (errors or SLO attainment)"
    cat "$WORK/bench2.log" "$WORK/serve.log"; exit 1; }

python - "$BENCH_OUT" "$BASE_QPS" "$BASE_P99" <<'EOF'
import json, sys
rec = json.load(open(sys.argv[1]))
base_qps, base_p99 = float(sys.argv[2]), float(sys.argv[3])
assert rec["errors"] == 0, rec
assert rec["round"] == 17 and rec["slo_ms"] == base_p99, rec
assert rec["value"] >= 2.0 * base_qps, (
    f"headline QPS {rec['value']} < 2x round-9 baseline {base_qps}")
assert rec["p99_ms"] <= base_p99, (
    f"p99 {rec['p99_ms']} ms worse than round-9 baseline {base_p99} ms")
print(f"smoke_autotune: headline OK (qps {rec['value']} >= 2x {base_qps}, "
      f"p99 {rec['p99_ms']}ms <= {base_p99}ms, "
      f"attainment {rec['slo_attainment_pct']}%)")
EOF

# ---- 5. telemetry gates + graceful shutdown -------------------------------
kill -TERM "$SERVE_PID"
rc=0; wait "$SERVE_PID" || rc=$?
SERVE_PID=""
[ "$rc" -eq 0 ] || { echo "smoke_autotune: server exit $rc"; cat "$WORK/serve.log"; exit 1; }

python tools/metrics_report.py "$WORK/run_serve" --check
# the ladder's exactly-once compile records, one per rung
grep -q '"program": "predict.serve.b16"' "$WORK/run_serve/serve_rank0.jsonl" || {
    echo "smoke_autotune: no compile record for rung 16"; exit 1; }
grep -q '"program": "predict.serve.b64"' "$WORK/run_serve/serve_rank0.jsonl" || {
    echo "smoke_autotune: no compile record for rung 64"; exit 1; }
# --health renders the trajectory and the loop did not oscillate
# (capture-then-grep: `| grep -q` + pipefail can SIGPIPE the producer)
python tools/metrics_report.py "$WORK/run_serve" --health >"$WORK/health.txt"
grep -q 'autotune trajectory' "$WORK/health.txt" || {
    echo "smoke_autotune: --health has no autotune section"
    cat "$WORK/health.txt"; exit 1; }
if grep -q 'oscillating' "$WORK/health.txt"; then
    echo "smoke_autotune: controller oscillated"; cat "$WORK/health.txt"; exit 1
fi

# the serve trajectory stays green with r17 folded in (standalone the
# file is already at the root; under pytest it rides in as an extra
# file); --metrics scopes the gate to the series THIS script measures
# — the repo-root bench datapoints are machine-local numbers from
# other rigs (the smoke_multislice.sh convention). ^serve_qps also
# catches the p99/attainment companion legs perf_ledger derives.
python tools/perf_ledger.py "$BENCH_OUT" --regress \
    --metrics '^serve_qps' --markdown "" >/dev/null

# repo-root hygiene: running the tools from the root must leave no
# stray artifact dirs behind (tools/__pycache__ and friends)
rm -rf "$ROOT/tools/__pycache__" "$ROOT/__pycache__"

echo "smoke_autotune: OK"
