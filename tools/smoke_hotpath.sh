#!/usr/bin/env bash
# Hot-path attribution smoke gate (docs/OBSERVABILITY.md
# "Input-pipeline attribution" / "Sparse-primitive lab"): one profiled
# synthetic CPU train + one CPU-sized lab sweep proving the whole
# attribution layer end to end —
#   1. a train.pipeline_metrics=true run emits kind="pipeline" windows
#      that pass metrics_report --check (all-or-none keys, the
#      per-thread sum invariant) and surface in --health's verdict;
#   2. tools/pipeline_attrib.py attributes >= 95% of the windowed wall
#      to named stages, prints the bottleneck verdict, and emits the
#      BENCH-shaped host-gap record (BENCH_PIPELINE.json);
#   3. a profiler-OFF run carries ZERO pipeline records and no
#      pipeline.* counters (the zero-overhead-when-off contract);
#   4. a small bench_lab --suite core sweep emits BENCH_LAB.json with a
#      gather x {table size, nnz} matrix and CompileRecorder cost
#      stamps;
#   5. both records land in the tools/perf_ledger.py trajectory (lab
#      section rendered, measured gather latency cited in the roofline
#      block), and the ledger's regression mode exits 3 on a controlled
#      regressed lab corpus.
#
# Standalone:    bash tools/smoke_hotpath.sh [workdir]
# From pytest:   tests/test_hotpath.py::test_smoke_hotpath_script
#
# With no workdir argument a temp dir is created and cleaned up.
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
# datapoint destinations: the repo root ONLY standalone (the committed
# trajectory records); pytest runs keep them in the workdir so test
# runs never rewrite the committed files with machine-local numbers.
# ROUND stamps the records (this PR's round number, like smoke_perf's
# BENCH_r09 filename) — without it perf_ledger --regress would skip
# the lab/pipeline groups forever (gating needs >= 2 numbered rounds)
ROUND=11
PIPE_OUT="$ROOT/BENCH_PIPELINE.json"
LAB_OUT="$ROOT/BENCH_LAB.json"
if [ -z "$WORK" ]; then
    WORK="$(mktemp -d)"
    trap 'rm -rf "$WORK"' EXIT
else
    PIPE_OUT="$WORK/BENCH_PIPELINE.json"
    LAB_OUT="$WORK/BENCH_LAB.json"
fi

export JAX_PLATFORMS=cpu

# ---- 1. profiled run: pipeline windows + schema/health gates --------------
# 3200 rows / batch 64 = 50 steps, log_every=10 -> ~5 windows + tail
python -m xflow_tpu gen-data "$WORK/train" --shards 1 --rows 3200 \
    --fields 6 --ids-per-field 50 --seed 0 >/dev/null

python -m xflow_tpu train \
    --train "$WORK/train" --model lr --epochs 1 \
    --batch-size 64 --log2-slots 12 --no-mesh \
    --set model.num_fields=6 \
    --set data.max_nnz=8 \
    --set train.pred_dump=false \
    --set train.log_every=10 \
    --set train.pipeline_metrics=true \
    --set "train.metrics_path=$WORK/run/metrics_rank0.jsonl" \
    >/dev/null

python tools/metrics_report.py "$WORK/run" --check
# capture-then-grep: a `| grep -q` pipe would SIGPIPE the producer
# under pipefail the moment grep matches and exits
python tools/metrics_report.py "$WORK/run" --health > "$WORK/health.txt"
grep -q "input pipeline" "$WORK/health.txt"

# ---- 2. attribution report: coverage + verdict + host-gap record ----------
python tools/pipeline_attrib.py "$WORK/run" \
    --json "$WORK/attrib.json" --bench-json "$PIPE_OUT" --round "$ROUND"
python - "$WORK/attrib.json" <<'EOF'
import json, sys
a = json.load(open(sys.argv[1]))
assert a["windows"] >= 2, f"too few pipeline windows: {a['windows']}"
assert a["attributed_pct"] >= 95.0, \
    f"only {a['attributed_pct']}% of wall attributed to named stages"
assert a["verdict"], "no bottleneck verdict"
assert a.get("e2e_examples_per_sec", 0) > 0, "no e2e throughput"
assert a.get("host_gap_ratio", 0) > 0, "no host-gap ratio"
print(f"smoke_hotpath: {a['attributed_pct']}% attributed; "
      f"verdict: {a['verdict']}")
EOF

# ---- 3. zero-overhead-when-off: no pipeline records in an OFF run ---------
python -m xflow_tpu train \
    --train "$WORK/train" --model lr --epochs 1 \
    --batch-size 64 --log2-slots 12 --no-mesh \
    --set model.num_fields=6 \
    --set data.max_nnz=8 \
    --set train.pred_dump=false \
    --set train.log_every=10 \
    --set "train.metrics_path=$WORK/run_off/metrics_rank0.jsonl" \
    >/dev/null
python tools/metrics_report.py "$WORK/run_off" --check
python - "$WORK/run_off/metrics_rank0.jsonl" <<'EOF'
import json, sys
recs = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
pipe = [r for r in recs if r.get("kind") == "pipeline"]
assert not pipe, f"profiler-off run emitted {len(pipe)} pipeline record(s)"
leaked = [
    k for r in recs for k in (r.get("counters") or {})
    if k.startswith("pipeline.")
]
assert not leaked, f"profiler-off run leaked pipeline counters: {leaked}"
print("smoke_hotpath: profiler-off stream is pipeline-free")
EOF

# ---- 4. CPU-sized lab sweep: the gather x {table, nnz} baseline matrix ----
python -m xflow_tpu.tools.bench_lab --suite core \
    --table-log2 10,12 --nnz-log2 8,9 --row-width 4 \
    --iters 2 --inner 2 --round "$ROUND" --out "$LAB_OUT" 2>/dev/null
python - "$LAB_OUT" <<'EOF'
import json, sys
d = json.load(open(sys.argv[1]))
assert d["kind"] == "bench_lab" and d["unit"] == "ns/element"
gathers = {(c["table_log2"], c["nnz_log2"])
           for c in d["cells"] if c["op"] == "gather"}
assert len(gathers) >= 4, f"gather sweep too small: {gathers}"
assert all(c["ns_per_element"] > 0 for c in d["cells"])
assert any(c.get("bytes_accessed") for c in d["cells"]), \
    "no CompileRecorder cost stamps in any cell"
print(f"smoke_hotpath: lab swept {len(d['cells'])} cell(s), "
      f"headline {d['metric']}={d['value']} ns/element")
EOF

# ---- 5. both records through the ledger + regression mechanics ------------
python tools/perf_ledger.py "$PIPE_OUT" "$LAB_OUT" \
    --markdown "$WORK/ledger.md" --json "$WORK/ledger.json"
grep -q "Sparse-primitive lab" "$WORK/ledger.md"
grep -q "pipeline_e2e_examples_per_sec" "$WORK/ledger.md"
grep -q "measured gather random-access latency" "$WORK/ledger.md"

# regression mechanics: a second lab round whose gather cell got SLOWER
# must exit 3 (ns/element gates downward)
mkdir -p "$WORK/series"
python - "$LAB_OUT" "$WORK/series" <<'EOF'
import json, sys
d = json.load(open(sys.argv[1]))
d["round"] = 1
json.dump(d, open(sys.argv[2] + "/BENCH_LAB_r01.json", "w"))
d = json.loads(json.dumps(d))
d["round"] = 2
d["value"] = d["value"] * 10.0
for c in d["cells"]:
    c["ns_per_element"] = c["ns_per_element"] * 10.0
json.dump(d, open(sys.argv[2] + "/BENCH_LAB_r02.json", "w"))
EOF
rc=0
python tools/perf_ledger.py --root "$WORK/series" --regress --markdown '' \
    >/dev/null 2>&1 || rc=$?
[ "$rc" -eq 3 ] || {
    echo "smoke_hotpath: lab regression expected exit 3, got $rc"; exit 1; }

# repo-root hygiene: running the tools from the root must leave no
# stray artifact dirs behind (tools/__pycache__ and friends)
rm -rf "$ROOT/tools/__pycache__" "$ROOT/__pycache__"

echo "smoke_hotpath: OK"
