#!/usr/bin/env bash
# Pre-commit gate: xflowlint over the commit's changed files + the
# engine-contract drift check + ruff (when installed).
#
# Install:   ln -sf ../../tools/pre-commit.sh .git/hooks/pre-commit
# Run solo:  bash tools/pre-commit.sh
#
# Fast by construction: --changed lints only git-touched lintable
# files (worktree + staged + untracked), --jobs 0 fans the per-module
# passes over a worker pool (cpu count, capped at 8), and the contract
# check re-extracts four builder modules only.
#
# Caveat, stated plainly: like most lint hooks this checks WORKTREE
# content, not the staged index — `git add` then editing the violation
# away without re-adding commits the staged copy unchecked. CI's
# full-tree sweep (tools/smoke_lint.sh) remains the authority. A clean run is well under a second on a
# warm tree; the full-repo sweep stays in tools/smoke_lint.sh / CI.
set -euo pipefail
# $0 may be the .git/hooks/pre-commit SYMLINK — a plain dirname would
# land in .git/hooks; resolve the link to the real tools/ location
cd "$(dirname "$(readlink -f "$0")")/.."
export PYTHONPATH="$(pwd)${PYTHONPATH:+:$PYTHONPATH}"

rc=0
python tools/xflowlint.py --changed --jobs 0 || rc=$?
if [ "$rc" -eq 1 ]; then
    echo "pre-commit: xflowlint found NEW findings — fix them, or" \
         "suppress a deliberate single site with a reasoned" \
         "'# xflowlint: disable=RULE'" >&2
    exit "$rc"
elif [ "$rc" -eq 2 ]; then
    echo "pre-commit: STALE baseline entries — this commit fixes" \
         "baselined findings, so remove their entries from" \
         "tools/xflowlint_baseline.json (the baseline only shrinks)" >&2
    exit "$rc"
elif [ "$rc" -ne 0 ]; then
    echo "pre-commit: xflowlint failed (exit $rc)" >&2
    exit "$rc"
fi

# contract drift only matters when an engine builder (or the mesh)
# changed — cheap enough to just always check. --no-ir keeps the hook
# fast (AST sections only); the IR-tier sections (contracts v2 +
# fusion worklist) are CI's job: tools/smoke_lint.sh checks them with
# --check-contracts/--check-worklist on every run.
if ! python tools/xflowlint.py --check-contracts --no-ir; then
    echo "pre-commit: engine-contract matrix drifted — regenerate with" \
         "'python tools/xflowlint.py --write-contracts' and commit the" \
         "reviewed diff" >&2
    exit 4
fi

if command -v ruff >/dev/null 2>&1; then
    ruff check .
fi
echo "pre-commit: OK"
