"""Validate the sorted windowed-matmul scatter design (docs/PERF.md
lever): permute gradient rows into slot-sorted order, scan over
fixed-size chunks doing a one-hot matmul against a W-aligned table
window, and check numerical equality vs the XLA scatter.

Retired to a thin wrapper: the implementation (including the
`host_sort_plan` chunk planner) lives in the unified microbench lab
(`xflow_tpu/tools/bench_lab.py --suite scatter`). This CLI keeps
working:

    python tools/scatter_experiment.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xflow_tpu.tools.bench_lab import host_sort_plan, main  # noqa: E402,F401

if __name__ == "__main__":
    sys.exit(main(["--suite", "scatter"] + sys.argv[1:]))
