"""Validate the sorted windowed-matmul scatter design (docs/PERF.md lever).

The FM/MVM backward is dominated by the XLA scatter-add of [2M, 11]
gradient rows into the [4M, 11] table (~216 ms measured). Candidate
replacement: permute gradient rows into slot-sorted order (one gather),
then scan over fixed-size chunks doing a one-hot matmul against a
W-aligned table window and a dynamic_update_slice accumulate.

Measures: permute gather, the scan pipeline, end-to-end, and checks
numerical equality vs the XLA scatter.
"""

import time

import numpy as np

C = 1024  # occurrences per chunk
W = 2048  # table window (slot-grid aligned)


def host_sort_plan(slots_flat: np.ndarray, S: int):
    """(perm [M], sorted_slots [M], bases [M//C]) — chunks grid-aligned.

    perm maps sorted position -> occurrence index (N = dummy zero row).
    """
    N = slots_flat.shape[0]
    order = np.argsort(slots_flat, kind="stable")
    ss = slots_flat[order]
    win = ss // W
    # chunk boundaries: every C occurrences, or window change
    M_cap = N + (S // W + 1) * C
    perm = np.full(M_cap, N, np.int32)
    srt = np.zeros(M_cap, np.int32)
    bases = []
    pos = 0
    i = 0
    while i < N:
        w = win[i]
        j = min(N, i + C)
        # shrink to this window only
        j = i + int(np.searchsorted(win[i:j], w + 1))
        take = j - i
        perm[pos : pos + take] = order[i:j]
        srt[pos : pos + take] = ss[i:j]
        srt[pos + take : pos + C] = w * W  # dummies point in-window
        bases.append(w * W)
        pos += C
        i = j
    nchunks = len(bases)
    return (
        perm[: nchunks * C],
        srt[: nchunks * C],
        np.asarray(bases, np.int32),
    )


def main():
    import jax
    import jax.numpy as jnp

    S, N, K = 1 << 22, 1 << 21, 11
    rng = np.random.default_rng(0)
    slots = rng.integers(0, S, N).astype(np.int32)
    d_occ = rng.normal(size=(N, K)).astype(np.float32)

    t0 = time.perf_counter()
    perm, srt, bases = host_sort_plan(slots, S)
    t_host = time.perf_counter() - t0
    nchunks = len(bases)
    print(f"host plan: {t_host*1e3:.1f} ms, nchunks={nchunks} (pad {nchunks*C/N:.3f}x)")

    jperm = jnp.asarray(perm)
    jsrt = jnp.asarray(srt.reshape(nchunks, C))
    jbases = jnp.asarray(bases)
    jd = jnp.asarray(d_occ)
    jslots = jnp.asarray(slots)

    def timeit(f, *a, iters=5):
        out = f(*a)
        _ = float(jax.tree.leaves(out)[0].ravel()[0])
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            out = f(*a)
            _ = float(jax.tree.leaves(out)[0].ravel()[0])
            best = min(best, time.perf_counter() - t0)
        return best

    # 1. permute gather: [M,K] from compact [N+1,K]
    @jax.jit
    def permute(d, p):
        dpad = jnp.concatenate([d, jnp.zeros((1, K), d.dtype)], 0)
        return dpad[p]

    t = timeit(permute, jd, jperm)
    print(f"permute gather [{len(perm)},{K}]: {t*1e3:7.1f} ms")

    # 2. windowed matmul scatter via scan
    @jax.jit
    def windowed_scatter(d, p, srt2d, bases1d):
        dpad = jnp.concatenate([d, jnp.zeros((1, K), d.dtype)], 0)
        ds = dpad[p].reshape(nchunks, C, K)

        def body(tab, xs):
            dch, sch, base = xs
            onehot = (sch[:, None] == base + jax.lax.broadcasted_iota(jnp.int32, (C, W), 1)).astype(
                jnp.float32
            )
            upd = jax.lax.dot_general(
                onehot, dch, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )  # [W, K]
            win = jax.lax.dynamic_slice(tab, (base, 0), (W, K))
            return jax.lax.dynamic_update_slice(tab, win + upd, (base, 0)), None

        tab = jnp.zeros((S, K), jnp.float32)
        tab, _ = jax.lax.scan(body, tab, (ds, srt2d, bases1d))
        return tab

    t = timeit(windowed_scatter, jd, jperm, jsrt, jbases)
    print(f"windowed scatter e2e   : {t*1e3:7.1f} ms")

    # 3. XLA scatter baseline + equality
    @jax.jit
    def xla_scatter(d, s):
        return jnp.zeros((S, K), jnp.float32).at[s].add(d)

    t = timeit(xla_scatter, jd, jslots)
    print(f"xla scatter-add        : {t*1e3:7.1f} ms")

    a = np.asarray(windowed_scatter(jd, jperm, jsrt, jbases))
    b = np.asarray(xla_scatter(jd, jslots))
    err = np.max(np.abs(a - b))
    print(f"max |windowed - xla|   : {err:.3e}")


if __name__ == "__main__":
    main()
