"""Probe TPU layout/bandwidth for [S, k] vs flat state arrays, and the
true cost of the table gather/scatter ops (carry-threaded methodology:
each scan iteration depends on the previous one, so loop-invariant
hoisting and DCE cannot fire — docs/PERF.md "Measurement hygiene").

Retired to a thin wrapper: the implementation lives in the unified
microbench lab (`xflow_tpu/tools/bench_lab.py --suite layout`). This
CLI keeps working:

    python tools/layout_probe.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xflow_tpu.tools.bench_lab import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--suite", "layout"] + sys.argv[1:]))
