"""Probe TPU layout/bandwidth for [S, k] vs flat state arrays, and the
true cost of the table gather/scatter ops.

Methodology: thread the large array through the lax.scan CARRY so each
iteration depends on the previous one — loop-invariant hoisting and
dead-code elimination (which silently invalidated a naive `fn(const)`
-in-scan harness) cannot fire. Completion forced by a host scalar read
(block_until_ready does not sync reliably through the axon tunnel).
"""

import time

import numpy as np

INNER = 4


def timeit_carry(step, init, iters=6):
    """step: carry -> carry (same pytree structure). Returns s/iter."""
    import jax

    @jax.jit
    def run(c):
        return jax.lax.scan(lambda c, _: (step(c), None), c, None, length=INNER)[0]

    c = run(init)
    _ = float(jax.tree.leaves(c)[0].ravel()[0])
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        c = run(c)
        _ = float(jax.tree.leaves(c)[0].ravel()[0])
        best = min(best, (time.perf_counter() - t0) / INNER)
    return best


def main():
    import jax
    import jax.numpy as jnp

    S, K, N = 1 << 22, 11, 1 << 21
    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, S, N), jnp.int32)
    valk = jnp.asarray(rng.normal(size=(N, K)).astype(np.float32))

    a2d = jnp.full((S, K), 1.0, jnp.float32)
    aflat = jnp.full((S * K,), 1.0, jnp.float32)
    apack = jnp.full((S * K // 128, 128), 1.0, jnp.float32)

    r = {}
    mul = lambda x: x * 1.000001 + 1e-9
    r["elementwise [4M,11]"] = timeit_carry(mul, a2d)
    r["elementwise flat 44M"] = timeit_carry(mul, aflat)
    r["elementwise [344k,128]"] = timeit_carry(mul, apack)

    # gather rows: force each iteration to depend on the previous via a
    # scalar folded into the indices (cannot be constant-folded)
    def gather_step(c):
        t, s = c
        i = idx + jnp.where(s > 1e30, 1, 0).astype(jnp.int32)
        g = t[i]
        return t, s + g.sum()

    r["gather rows [S,11]"] = timeit_carry(gather_step, (a2d, jnp.float32(0)))

    def gather_flat_step(c):
        t, s = c
        i = idx + jnp.where(s > 1e30, 1, 0).astype(jnp.int32)
        g = t.reshape(S, K)[i]
        return t, s + g.sum()

    r["gather via reshape"] = timeit_carry(gather_flat_step, (aflat, jnp.float32(0)))

    # scatter-add rows: table is the carry — true sequential dependency
    r["scatter rows [S,11]"] = timeit_carry(lambda t: t.at[idx].add(valk), a2d)
    r["scatter via reshape"] = timeit_carry(
        lambda t: t.reshape(S, K).at[idx].add(valk).reshape(S * K), aflat
    )

    # FTRL-ish update: w,n,z carried, g fixed
    def ftrl_step(c):
        w, n, z = c
        g = valk.sum() * 0 + 1e-4  # scalar, negligible
        n2 = n + g * g
        z2 = z + g - (jnp.sqrt(n2) - jnp.sqrt(n)) * 20.0 * w
        w2 = jnp.where(jnp.abs(z2) <= 5e-5, 0.0, -z2 / ((1.0 + jnp.sqrt(n2)) * 20.0 + 10.0))
        return w2, n2, z2

    r["ftrl pass [4M,11]x3"] = timeit_carry(ftrl_step, (a2d, a2d * 0.5, a2d * 0.1))
    r["ftrl pass flat x3"] = timeit_carry(ftrl_step, (aflat, aflat * 0.5, aflat * 0.1))

    print(f"# device={jax.devices()[0]}  (s/iter, carry-threaded)")
    for k, v in r.items():
        print(f"{k:24s} {v*1e3:8.2f} ms")


if __name__ == "__main__":
    main()
