#!/usr/bin/env bash
# Packed-shard-cache smoke gate (docs/DATA.md, docs/PERF.md "Host data
# plane"): convert -> cached train -> parity + resume + bitflip drills
# -> pipeline_attrib -> ledger fold, end to end on one CPU —
#   1. gen synthetic libffm shards; `criteo_convert cache` packs them
#      into .xfc binary caches (pre-hashed, crc32-digested);
#   2. the TEXT-path run (data.cache=off, Python parser — see the
#      parser note below) and the CACHE-path run (data.cache=on), both
#      with train.pipeline_metrics=true: the cache run's windows carry
#      the cache_read stage, both pass metrics_report --check, and both
#      attribute >= 95% of windowed wall to named stages;
#   3. parity: cache-path batches are BITWISE-identical to text-path
#      batches over the whole shard (labels + all four arrays);
#   4. the measured win: cached e2e >= 5x text e2e on this workload,
#      stamped into the round-12 BENCH_PIPELINE record with the text
#      leg folded in (pipeline_attrib --compare), host_gap_ratio ~1;
#   5. elastic resume on cache shards: SIGKILL at step 6 (checkpoint
#      boundary) under the supervised launcher -> auto-restart ->
#      exact PR-4 example accounting (every row exactly once);
#   6. integrity: a bitflipped cache section is caught by its digest,
#      quarantined (one JSONL record naming the section), and the run
#      falls back to the text path with ZERO failures;
#   7. both bench records fold through tools/perf_ledger.py, and a
#      controlled host_gap_ratio regression (a round climbing back
#      toward text-path ratios) exits 3.
#
# Parser note: the text leg pins data.use_native_parser=false. The
# cache path replaces the read/parse/hash stages ENTIRELY, so the
# honest denominator is the parser a run would actually fall back to;
# on this 1-core CPU rig the native C parser outruns the CPU "device"
# step (docs/PERF.md), so a native-parser text leg is device-bound and
# the host gap is invisible at smoke scale — exactly the BENCH_SCALE
# situation in reverse. The chip-scale gap (62.5k vs 1.75M ex/s) is
# native-parser-bound; this smoke proves the mechanism, the committed
# BENCH_PIPELINE_r12.json records the rig-local magnitudes.
#
# Standalone:    bash tools/smoke_cache.sh [workdir]
# From pytest:   tests/test_shardcache.py::test_smoke_cache_script
#
# With no workdir argument a temp dir is created and cleaned up.
set -euo pipefail
cd "$(dirname "$0")/.."
ROOT="$(pwd)"

WORK="${1:-}"
# datapoint destination: the repo root ONLY standalone (the committed
# round-12 record); pytest runs keep it in the workdir so test runs
# never rewrite the committed file with machine-local numbers
ROUND=12
PIPE_OUT="$ROOT/BENCH_PIPELINE_r12.json"
if [ -z "$WORK" ]; then
    WORK="$(mktemp -d)"
    trap 'rm -rf "$WORK"' EXIT
else
    PIPE_OUT="$WORK/BENCH_PIPELINE_r12.json"
fi

export JAX_PLATFORMS=cpu

# 61440 rows / batch 4096 = 15 steps; 18 features/row at 2^20 slots is
# enough host work that the text leg is parse-bound, not dispatch-bound
ROWS=61440
python -m xflow_tpu gen-data "$WORK/train" --shards 1 --rows "$ROWS" \
    --fields 18 --ids-per-field 100000 --seed 0 >/dev/null

# ---- 1. pack the shard cache at convert time ------------------------------
python -m xflow_tpu.tools.criteo_convert cache "$WORK/train" \
    --log2-slots 20 --max-nnz 20 > "$WORK/cache_stats.json"
python - "$WORK/cache_stats.json" "$ROWS" <<'EOF'
import json, sys
s = json.load(open(sys.argv[1]))
assert s["shards"] == 1 and s["rows"] == int(sys.argv[2]), s
assert s["bytes"] > 0, s
print(f"smoke_cache: packed {s['rows']} rows into {s['bytes']} bytes")
EOF
TRAIN_ARGS=(
    --train "$WORK/train" --model lr --epochs 1
    --batch-size 4096 --log2-slots 20 --no-mesh
    --set model.num_fields=18
    --set data.max_nnz=20
    --set data.use_native_parser=false
    --set train.pred_dump=false
    --set train.log_every=2
    --set train.pipeline_metrics=true
)

# ---- 2. text leg vs cache leg, both profiled ------------------------------
python -m xflow_tpu train "${TRAIN_ARGS[@]}" \
    --set data.cache=off \
    --set "train.metrics_path=$WORK/run_text/metrics_rank0.jsonl" >/dev/null
python tools/metrics_report.py "$WORK/run_text" --check
python tools/pipeline_attrib.py "$WORK/run_text" \
    --json "$WORK/attrib_text.json" --bench-json "$WORK/BENCH_TEXT.json"

python -m xflow_tpu train "${TRAIN_ARGS[@]}" \
    --set data.cache=on \
    --set "train.metrics_path=$WORK/run_cache/metrics_rank0.jsonl" >/dev/null
python tools/metrics_report.py "$WORK/run_cache" --check
# the cache run's verdict rides the shared pipeline_verdict — a
# cache-bound producer is NAMEABLE (capture-then-grep: a `| grep -q`
# pipe would SIGPIPE the producer under pipefail)
python tools/metrics_report.py "$WORK/run_cache" --health > "$WORK/health.txt"
grep -q "input pipeline" "$WORK/health.txt"

# ---- 3. parity: cache batches bitwise-identical to text batches -----------
python - "$WORK/train-00000" <<'EOF'
import dataclasses, sys
import numpy as np
from xflow_tpu.config import Config, override
from xflow_tpu.data.pipeline import batch_iterator
cfg = override(Config(), **{
    "data.log2_slots": 20, "data.max_nnz": 20, "data.batch_size": 4096,
}).data
text = list(batch_iterator(sys.argv[1], dataclasses.replace(cfg, cache="off")))
cache = list(batch_iterator(sys.argv[1], dataclasses.replace(cfg, cache="on")))
assert len(text) == len(cache) and text, (len(text), len(cache))
for a, b in zip(text, cache):
    for name in ("slots", "fields", "mask", "labels", "row_mask"):
        x, y = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        assert x.dtype == y.dtype and np.array_equal(x, y), name
print(f"smoke_cache: {len(text)} batches bitwise-identical across paths")
EOF

# ---- 4. the measured win: >= 5x + the round-12 host-gap record ------------
python tools/pipeline_attrib.py "$WORK/run_cache" \
    --json "$WORK/attrib_cache.json" --bench-json "$PIPE_OUT" \
    --round "$ROUND" --compare "$WORK/BENCH_TEXT.json" --compare-label text
python - "$WORK/attrib_text.json" "$WORK/attrib_cache.json" "$PIPE_OUT" <<'EOF'
import json, sys
text = json.load(open(sys.argv[1]))
cache = json.load(open(sys.argv[2]))
rec = json.load(open(sys.argv[3]))
for name, a in (("text", text), ("cache", cache)):
    assert a["attributed_pct"] >= 95.0, \
        f"{name} leg: only {a['attributed_pct']}% of wall attributed"
speedup = rec["speedup_vs_text"]
assert speedup >= 5.0, \
    f"cache e2e only {speedup}x the text path (need >= 5x): " \
    f"{rec['text_e2e_examples_per_sec']} -> {rec['value']} ex/s"
assert rec["round"] == 12 and rec["host_gap_ratio"] >= 1.0
assert rec["stage_pct"].get("cache_read") is not None
assert rec["stage_pct"]["parse"] == 0.0, "cache run still parsed text"
print(f"smoke_cache: cache {rec['value']:,.0f} ex/s = {speedup}x text "
      f"{rec['text_e2e_examples_per_sec']:,.0f} ex/s "
      f"(host gap {rec['host_gap_ratio']}x, "
      f"{cache['attributed_pct']}%/{text['attributed_pct']}% attributed)")
EOF

# ---- 5. elastic resume on cache shards (PR-4 exact accounting) ------------
# SIGKILL the rank the moment step 6 completes (on its checkpoint
# boundary); the supervisor relaunches, the resumed stream fast-skips
# the cached shard to the stored offset, and the final data_state
# counts every row exactly once
XFLOW_FAULT_KILL_STEP=6 \
python -m xflow_tpu launch-local --num-processes 1 \
    --max-restarts 2 --restart-backoff 0.2 \
    --run-dir "$WORK/run_kill" -- \
    "${TRAIN_ARGS[@]}" --set data.cache=on \
    --set train.checkpoint_every=3 \
    --checkpoint-dir "$WORK/ck_kill" >/dev/null
python tools/metrics_report.py "$WORK/run_kill" --check
python - "$WORK" "$ROWS" <<'EOF'
import os, sys
from xflow_tpu.jsonl import read_jsonl
from xflow_tpu.train.checkpoint import latest_step, read_data_state
work, rows = sys.argv[1], int(sys.argv[2])
want = rows // 4096  # exact: ROWS divides the batch size
step = latest_step(os.path.join(work, "ck_kill"))
assert step == want, f"final committed step {step} != {want}"
ds = read_data_state(os.path.join(work, "ck_kill"), step)
assert ds and ds["completed"], f"data_state not completed: {ds}"
assert ds["examples"] == rows, \
    f"examples {ds['examples']} != {rows} (replay or loss)"
gens = {r.get("gen", 0) for r in
        read_jsonl(os.path.join(work, "run_kill", "metrics_rank0.jsonl"))}
assert gens == {0, 1}, f"expected generations {{0, 1}}, got {gens}"
print(f"smoke_cache: kill@6 resume accounting OK "
      f"(step {step}, examples {ds['examples']}, generations {sorted(gens)})")
EOF

# ---- 6. bitflip drill: digest catch -> quarantine -> text fallback --------
python - "$WORK/train-00000.xfc" <<'EOF'
import sys
# flip one payload byte INSIDE the slots section (past the 64-byte
# prologue padding) — only the digest layer can catch this
with open(sys.argv[1], "r+b") as f:
    f.seek(4096)
    b = f.read(1)
    f.seek(4096)
    f.write(bytes([b[0] ^ 0xFF]))
print("smoke_cache: flipped one cache byte at offset 4096")
EOF
# (native parser for the fallback leg: this drill proves integrity
# routing, not the host gap — a later --set wins over TRAIN_ARGS')
python -m xflow_tpu train "${TRAIN_ARGS[@]}" \
    --set data.cache=on \
    --set data.use_native_parser=true \
    --set "data.quarantine_path=$WORK/run_flip/quarantine.jsonl" \
    --set "train.metrics_path=$WORK/run_flip/metrics_rank0.jsonl" \
    > "$WORK/flip_stdout.txt" 2> "$WORK/flip_stderr.txt"
grep -q "failed integrity" "$WORK/flip_stderr.txt"
python tools/metrics_report.py "$WORK/run_flip" --check
python - "$WORK" "$ROWS" <<'EOF'
import json, os, sys
from xflow_tpu.jsonl import read_jsonl
work, rows = sys.argv[1], int(sys.argv[2])
q = read_jsonl(os.path.join(work, "run_flip", "quarantine.jsonl"))
hits = [r for r in q if r.get("reason") == "cache_digest_mismatch"]
assert hits, f"no cache quarantine record: {q}"
assert hits[0]["section"] in ("slots", "fields", "mask", "labels"), hits[0]
recs = read_jsonl(os.path.join(work, "run_flip", "metrics_rank0.jsonl"))
fin = [r for r in recs if r.get("final")]
assert fin and fin[0]["examples"] == rows, \
    f"fallback run trained {fin and fin[0].get('examples')} != {rows}"
counters = fin[0].get("counters") or {}
assert counters.get("data.cache_fallbacks") == 1, counters
print(f"smoke_cache: bitflip quarantined (section "
      f"{hits[0]['section']}), text fallback trained all {rows} rows")
EOF

# ---- 7. ledger fold + host_gap_ratio downward gating ----------------------
python tools/perf_ledger.py "$WORK/BENCH_TEXT.json" "$PIPE_OUT" \
    --markdown "$WORK/ledger.md" --json "$WORK/ledger.json"
grep -q "Input pipeline" "$WORK/ledger.md"
grep -q "pipeline_speedup_vs_text" "$WORK/ledger.md"
grep -q "text_e2e_examples_per_sec" "$WORK/ledger.md"

# regression mechanics: a later round whose host_gap_ratio climbed back
# toward text-path ratios must exit 3 (the ratio gates DOWNWARD)
mkdir -p "$WORK/series"
python - "$PIPE_OUT" "$WORK/series" <<'EOF'
import json, sys
d = json.load(open(sys.argv[1]))
d["round"] = 12
json.dump(d, open(sys.argv[2] + "/BENCH_PIPELINE_r12.json", "w"))
d = json.loads(json.dumps(d))
d["round"] = 13
d["host_gap_ratio"] = d["host_gap_ratio"] * 5.0  # back toward text-path
json.dump(d, open(sys.argv[2] + "/BENCH_PIPELINE_r13.json", "w"))
EOF
rc=0
python tools/perf_ledger.py --root "$WORK/series" --regress --markdown '' \
    --metrics 'host_gap_ratio' >/dev/null 2>&1 || rc=$?
[ "$rc" -eq 3 ] || {
    echo "smoke_cache: host_gap_ratio regression expected exit 3, got $rc"; exit 1; }

# repo-root hygiene: running the tools from the root must leave no
# stray artifact dirs behind (tools/__pycache__ and friends)
rm -rf "$ROOT/tools/__pycache__" "$ROOT/__pycache__"

echo "smoke_cache: OK"
