"""Topology-elastic, integrity-verified checkpoints (docs/ROBUSTNESS.md
"Host lost" / "Silent shard corruption"; docs/DISTRIBUTED.md "Canonical
checkpoint layout").

Two properties are pinned here:

1. **Elastic restore**: a checkpoint written at one mesh/world shape
   restores into any other — the npz stores the canonical LOGICAL
   layout, every leaf lands on the live sharding, and the data_state's
   per-SHARD offsets re-assign the record set to the new world with
   exact coverage (no record trained twice, none dropped). The mesh
   matrix (1<->2<->4 devices, GSPMD / sorted replicated / fullshard /
   single-device engines) runs in-process on the conftest's 8-CPU-device
   fake cluster; the true multi-PROCESS shrink drill is
   tools/smoke_topology.sh (probe-gated like every 2-proc drill).

2. **Integrity**: per-array digests written into meta.json at save are
   verified on restore; a digest mismatch is a logged walk-back to the
   previous committed step — drilled with the container-preserving
   payload bitflip (testing/faults.bitflip_npz_array) that every
   zip-level check survives, so ONLY the digest layer can catch it.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.data.pipeline import assign_shards, batch_iterator
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.parallel.mesh import make_mesh
from xflow_tpu.testing.faults import bitflip_npz_array, corrupt_npz_checkpoint
from xflow_tpu.train.checkpoint import (
    CheckpointDigestError,
    array_digest,
    committed_steps,
    normalize_data_state,
    read_data_state,
    restore_any,
    verify_digest,
)
from xflow_tpu.train.trainer import Trainer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_cfg(tmp_path, **kw):
    base = {
        "data.train_path": str(tmp_path / "train"),
        "data.log2_slots": 12,
        "data.batch_size": 100,
        "data.max_nnz": 8,
        "model.num_fields": 5,
        "train.epochs": 1,
        "train.pred_dump": False,
    }
    base.update(kw)
    return override(Config(), **base)


@pytest.fixture
def dataset(tmp_path):
    generate_shards(
        str(tmp_path / "train"), 1, 600, num_fields=5, ids_per_field=30, seed=0
    )
    return tmp_path


@pytest.fixture
def dataset2(tmp_path):
    """TWO shards — the record set of an (emulated) 2-rank run."""
    generate_shards(
        str(tmp_path / "train"), 2, 500, num_fields=5, ids_per_field=30, seed=0
    )
    return tmp_path


# ------------------------------------------------------- shard assignment
def test_assign_shards_legacy_and_elastic(tmp_path):
    p = str(tmp_path / "t")
    # fresh run (num_shards == world): rank k owns exactly shard k —
    # the legacy one-shard-per-rank contract, byte-identical paths
    assert assign_shards(p, 0, 1) == [(0, p + "-00000")]
    assert assign_shards(p, 1, 2) == [(1, p + "-00001")]
    # shrink 4 -> 1: the lone survivor covers the whole record set
    assert [i for i, _ in assign_shards(p, 0, 1, num_shards=4)] == [0, 1, 2, 3]
    # shrink 5 -> 2: round-robin, disjoint, complete
    r0 = [i for i, _ in assign_shards(p, 0, 2, num_shards=5)]
    r1 = [i for i, _ in assign_shards(p, 1, 2, num_shards=5)]
    assert r0 == [0, 2, 4] and r1 == [1, 3]
    # grow 2 -> 4: new ranks pick up their own (fresh) shard index
    assert assign_shards(p, 3, 4, num_shards=2) == [(3, p + "-00003")]


def test_normalize_data_state_versions():
    # v1 multi-process: per-rank examples fold to a global sum, the
    # coordinated offset fans out to every shard (lockstep invariant)
    v1 = {"version": 1, "epoch": 0, "batches": 7, "completed": False,
          "examples": 700, "examples_per_rank": [700, 650],
          "quarantined_rows": 0}
    got = normalize_data_state(v1)
    assert got["examples"] == 1350 and got["world_size"] == 2
    assert got["shard_batches"] == {0: 7, 1: 7} and got["num_shards"] == 2
    # v2 passes through with int-keyed offsets
    v2 = {"version": 2, "epoch": 1, "batches": 9, "completed": False,
          "examples": 2000, "shard_batches": {"0": 9, "2": 3},
          "num_shards": 3, "world_size": 3}
    got = normalize_data_state(v2)
    assert got["shard_batches"] == {0: 9, 2: 3} and got["num_shards"] == 3
    # malformed values raise (the caller downgrades to a fresh stream)
    with pytest.raises((TypeError, ValueError)):
        normalize_data_state({"epoch": "not-a-number"})


# ----------------------------------------------------------- integrity
def test_bitflip_npz_array_is_silent_to_the_container(tmp_path):
    """The drill primitive's contract: the rewritten npz passes every
    zip/numpy-level check (np.load succeeds, values differ) — only the
    digest layer can tell. A RAW flip on the same file trips the zip
    CRC instead (the loud mode restore_any always healed)."""
    p = str(tmp_path / "a.npz")
    a = np.arange(4096, dtype=np.float32)
    with open(p, "wb") as f:
        np.savez(f, x=a)
    before = array_digest(a)
    offs = bitflip_npz_array(p, count=8, seed=1)
    assert offs
    got = np.load(p)["x"]  # container-level read SUCCEEDS
    assert got.shape == a.shape and got.dtype == a.dtype
    assert array_digest(got) != before  # ... but the values changed
    with pytest.raises(CheckpointDigestError, match="digest mismatch"):
        verify_digest("x", got, {"x": before}, p)


def test_bitflipped_shard_walks_back_not_restores_garbage(dataset, tmp_path):
    """THE acceptance drill: a committed checkpoint bit-flipped through
    corrupt_ckpt's silent mode restores the PREVIOUS committed step
    with a logged digest mismatch — never the corrupted state."""
    ck = str(tmp_path / "ck")
    cfg = make_cfg(dataset, **{"train.epochs": 2,
                               "train.checkpoint_dir": ck,
                               "train.checkpoint_every": 5})
    t = Trainer(cfg)
    t.fit()
    good_w10 = None
    assert committed_steps(ck) == [12, 10, 5]
    good_w10 = np.load(os.path.join(ck, "step_10", "state.npz"))["tables/w"]
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "corrupt_ckpt.py"),
         "--dir", ck, "--mode", "bitflip", "--count", "16"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["corrupted"].endswith("step_12/state.npz")
    t2 = Trainer(cfg)
    assert t2.maybe_restore()
    assert int(t2.state.step) == 10  # walked back past the flipped step
    np.testing.assert_array_equal(np.asarray(t2.state.tables["w"]), good_w10)
    # the stream position came from the step that ACTUALLY restored
    # (600 rows / 100 = 6 batches per epoch; step 10 = epoch 1, batch 4)
    assert t2._resume_data_state["batches"] == 4


def test_checkpoint_verify_off_disables_the_digest_gate(dataset, tmp_path):
    """Negative control: with train.checkpoint_verify=off the flipped
    newest step restores (values and all) — proving the digest layer,
    not some container check, is what catches the silent flip."""
    ck = str(tmp_path / "ck")
    cfg = make_cfg(dataset, **{"train.epochs": 2,
                               "train.checkpoint_dir": ck,
                               "train.checkpoint_every": 5})
    Trainer(cfg).fit()
    corrupt_npz_checkpoint(ck, mode="bitflip", count=16, seed=2)
    t2 = Trainer(override(cfg, **{"train.checkpoint_verify": "off"}))
    assert t2.maybe_restore()
    assert int(t2.state.step) == 12  # restored the corrupted newest step


def test_orbax_digest_verification_fires_end_to_end(dataset, tmp_path):
    """The orbax verify path: OCDBT's own b-tree CRC catches inline
    small-array flips (tested in test_fault_injection), but LARGE
    chunked payload reads are not checksum-verified — the meta
    sibling's digests are the net. Simulated here by recording a
    digest that does not match the (intact) stored bytes: restore must
    fail that step with CheckpointDigestError and walk back."""
    pytest.importorskip("orbax.checkpoint")
    ck = str(tmp_path / "ck")
    cfg = make_cfg(dataset, **{"train.epochs": 2,
                               "train.checkpoint_dir": ck,
                               "train.checkpoint_every": 5,
                               "train.checkpoint_format": "orbax"})
    Trainer(cfg).fit()
    meta_p = os.path.join(ck, "orbax_step_12.meta.json")
    meta = json.load(open(meta_p))
    assert meta["version"] == 3 and meta["digests"]
    meta["digests"]["tables/w"] = "crc32:deadbeef"
    json.dump(meta, open(meta_p, "w"))
    t2 = Trainer(cfg)
    assert t2.maybe_restore()
    assert int(t2.state.step) == 10


# ------------------------------------------------- mesh resharding matrix
def mesh_of(cfg, n):
    return make_mesh(cfg, np.array(jax.devices()[:n]))


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 CPU devices")
def test_restore_reshards_gspmd_mesh_sizes(dataset, tmp_path):
    """LR on the GSPMD engine: save at a 2-device mesh, restore at 4
    devices and at a single device — identical logical tables."""
    cfg = make_cfg(dataset, **{"train.checkpoint_dir": str(tmp_path / "ck")})
    t = Trainer(cfg, mesh=mesh_of(cfg, 2))
    t.fit()
    w = np.asarray(jax.device_get(t.state.tables["w"]))
    for target in (4, 1, None):
        mesh = mesh_of(cfg, target) if target else None
        t2 = Trainer(cfg, mesh=mesh)
        assert t2.maybe_restore() and int(t2.state.step) == 6
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(t2.state.tables["w"])), w
        )
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(t2.state.opt_state["w"]["n"])),
            np.asarray(jax.device_get(t.state.opt_state["w"]["n"])),
        )


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 CPU devices")
def test_restore_reshards_across_sorted_engines(dataset, tmp_path):
    """Fused FM across ALL FOUR engines: a fullshard-engine checkpoint
    (2-device mesh) restores into the 4-device fullshard mesh, the
    sorted REPLICATED engine, and the single-device sorted step — the
    canonical logical npz layout makes the engine irrelevant."""
    base = {"train.checkpoint_dir": str(tmp_path / "ck"),
            "data.log2_slots": 14, "data.batch_size": 128,
            "model.name": "fm"}
    cfg = make_cfg(dataset, **base)
    t = Trainer(cfg, mesh=mesh_of(cfg, 2))
    assert t._mesh_engine == "fullshard"
    t.fit()
    wv = np.asarray(jax.device_get(t.state.tables["wv"]))
    step = int(t.state.step)

    # 4-device fullshard
    t4 = Trainer(cfg, mesh=mesh_of(cfg, 4))
    assert t4._mesh_engine == "fullshard"
    assert t4.maybe_restore() and int(t4.state.step) == step
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(t4.state.tables["wv"])), wv
    )
    # 2-device sorted REPLICATED engine
    cfg_r = make_cfg(dataset, **{**base, "data.sorted_layout": "on",
                                 "data.sorted_mesh": "replicated"})
    tr = Trainer(cfg_r, mesh=mesh_of(cfg_r, 2))
    assert tr._mesh_engine == "replicated"
    assert tr.maybe_restore() and int(tr.state.step) == step
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(tr.state.tables["wv"])), wv
    )
    # single-device sorted step
    t1 = Trainer(cfg)
    assert t1.maybe_restore() and int(t1.state.step) == step
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(t1.state.tables["wv"])), wv
    )


# --------------------------------------------- elastic data-stream resume
def record_consumed_labels(trainer, sink):
    """Wrap the trainer's batch stream to record every TRAINING batch's
    real (row-masked) labels — the record-set coverage probe."""
    orig = trainer._coordinated_batches

    def wrapped(path, *args, **kwargs):
        training = kwargs.get("enforce_bad_rows", True)
        for batch, arrays in orig(path, *args, **kwargs):
            if training:
                rm = np.asarray(batch.row_mask) > 0
                sink.append(np.asarray(batch.labels)[rm])
            yield batch, arrays

    trainer._coordinated_batches = wrapped


def test_shrunk_resume_covers_the_record_set_exactly(dataset2, tmp_path):
    """2 -> 1 data topology: a single rank resuming a 2-rank
    checkpoint's data_state (per-shard offsets {0: 2, 1: 2}) consumes
    EXACTLY each shard's untrained suffix — no record twice, none
    dropped — and the final checkpoint's global example accounting is
    exact: 400 restored + 600 consumed = 1000 = every row once."""
    ck = str(tmp_path / "ck")
    cfg = make_cfg(dataset2, **{"train.checkpoint_dir": ck})
    t = Trainer(cfg)
    # what a 2-rank gen-0 committed after 2 coordinated steps
    # (2 ranks x 2 batches x 100 rows = 400 examples)
    t._resume_data_state = {
        "version": 2, "epoch": 0, "batches": 2, "completed": False,
        "examples": 400, "examples_per_rank": [200, 200],
        "shard_batches": {"0": 2, "1": 2}, "num_shards": 2,
        "world_size": 2,
    }
    seen = []
    record_consumed_labels(t, seen)
    res = t.fit()
    # each 500-row shard holds 5 batches; offset 2 leaves 3 per shard
    assert res.steps == 6 and res.examples == 600
    expected = []
    for s in (0, 1):
        shard = str(dataset2 / "train") + "-%05d" % s
        for i, b in enumerate(batch_iterator(shard, cfg.data)):
            if i >= 2:
                rm = np.asarray(b.row_mask) > 0
                expected.append(np.asarray(b.labels)[rm])
    assert len(seen) == len(expected)
    for a, b in zip(seen, expected):
        np.testing.assert_array_equal(a, b)
    ds = read_data_state(ck, int(t.state.step))
    assert ds["completed"] and ds["examples"] == 1000
    assert ds["world_size"] == 1 and ds["num_shards"] == 2


def test_second_epoch_after_shrunk_resume_reads_all_shards(dataset2, tmp_path):
    """After the resumed epoch, later epochs read every owned shard
    from row 0 — the shrunk world keeps covering the whole record set,
    not just the resumed suffix."""
    cfg = make_cfg(dataset2, **{"train.epochs": 2})
    t = Trainer(cfg)
    t._resume_data_state = {
        "version": 2, "epoch": 0, "batches": 4, "completed": False,
        "examples": 800, "shard_batches": {"0": 4, "1": 4},
        "num_shards": 2, "world_size": 2,
    }
    res = t.fit()
    # epoch 0 remainder: (5-4)*2 shards = 2 steps; epoch 1: 10 steps
    assert res.steps == 12 and res.examples == 1200


# ------------------------------------------------ degraded-mode supervision
def test_dead_host_tracker_shrink_revive_floor():
    from xflow_tpu.launch.supervise import DeadHostTracker

    t = DeadHostTracker(allow_shrink=True)
    t.record("hostB")
    assert t.shrunk_world(3) == 2
    assert t.survivors(["a", "hostB", "c"]) == ["a", "c"]
    t.record("a")
    t.record("c")
    assert t.shrunk_world(3) == 1  # the last survivor keeps the run alive
    t.revive("a")  # the launch-dist probe found it reachable again
    assert t.survivors(["a", "hostB", "c"]) == ["a"]
    # off = same-shape supervision, untouched
    off = DeadHostTracker(allow_shrink=False)
    off.record("x")
    assert off.shrunk_world(3) == 3 and off.survivors(["x", "y"]) == ["x", "y"]


def test_launch_local_shrinks_after_dead_host_verdict(monkeypatch):
    """The wiring end to end (launcher level, fake attempts): gen 0's
    watchdog dead verdict shrinks gen 1 to the survivors — and only
    the FIRST verdict of the attempt counts (the culprit ordering puts
    the real loss first; its blocked SPMD peers are victims, not
    additional lost hosts)."""
    from xflow_tpu.launch import local as ll

    worlds = []

    def fake_once(n, args, on_dead_row=None, gen=0, **kw):
        worlds.append(n)
        if gen == 0:
            on_dead_row({"rank": 1, "status": "dead"})
            on_dead_row({"rank": 0, "status": "dead"})  # victim: ignored
            return 75  # EX_TEMPFAIL, the verdict-only failure code
        return 0

    monkeypatch.setattr(ll, "_launch_local_once", fake_once)
    rc = ll.launch_local(2, ["--train", "x"], max_restarts=2,
                         restart_backoff=0.0, allow_shrink=True)
    assert rc == 0 and worlds == [2, 1]
    # without --allow-shrink the relaunch stays same-shape
    worlds.clear()
    rc = ll.launch_local(2, ["--train", "x"], max_restarts=2,
                         restart_backoff=0.0)
    assert rc == 0 and worlds == [2, 2]


def test_orig_world_env_preserves_shard_coverage(dataset2, monkeypatch):
    """The shrink-before-first-checkpoint window: a relaunch that has
    no committed data_state cannot learn the shard set from a
    checkpoint — the supervisor's XFLOW_ORIG_WORLD export keeps the
    survivors covering every shard (here: a 1-rank world with original
    world 2 trains BOTH 500-row shards instead of silently dropping
    shard 1)."""
    monkeypatch.setenv("XFLOW_ORIG_WORLD", "2")
    res = Trainer(make_cfg(dataset2)).fit()
    assert res.steps == 10 and res.examples == 1000
    # control: without the env a fresh 1-rank run keeps the legacy
    # one-shard contract
    monkeypatch.delenv("XFLOW_ORIG_WORLD")
    res = Trainer(make_cfg(dataset2)).fit()
    assert res.steps == 5 and res.examples == 500


# ------------------------------------------------------------ world stamp
def test_world_stamp_in_every_jsonl_record(tmp_path, monkeypatch):
    from xflow_tpu.jsonl import JsonlAppender

    path = tmp_path / "m.jsonl"
    monkeypatch.setenv("XFLOW_NUM_PROCESSES", "3")
    ap = JsonlAppender(str(path), stamp={"rank": 0, "run_id": "r"})
    ap.append({"step": 1})
    ap.close()
    rec = json.loads(open(path).read())
    assert rec["world"] == 3


# --------------------------------------------------------- report tooling
def _rec(run_id, rank, gen, step, ts, world):
    return {"ts": ts, "rank": rank, "run_id": run_id, "gen": gen,
            "world": world, "step": step, "loss": 0.5,
            "examples": step * 10, "elapsed_s": float(step),
            "steps_per_s": 1.0, "rows_per_s": 10.0,
            "step_time_p50_ms": 1.0, "step_time_p99_ms": 2.0,
            "data_wait_ms": 0.1, "dispatch_ms": 0.1, "device_ms": 0.8}


def _load(tmp_path, name, recs):
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import metrics_report

    path = tmp_path / name
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    streams, _ = metrics_report.load_streams([str(path)])
    return metrics_report, streams, [str(path)]


def test_check_accepts_world_shrink_across_generations(tmp_path):
    """A shrunk relaunch changes the rank set between generations of
    one run_id — that must pass --check; an INTRA-generation world
    disagreement (or a rank outside its world) must not."""
    recs = [_rec("r", 0, 0, 5, 1.0, 2), _rec("r", 1, 0, 5, 1.1, 2),
            _rec("r", 0, 1, 2, 2.0, 1)]  # gen 1: rank 1 shrunk away
    mr, streams, files = _load(tmp_path, "ok.jsonl", recs)
    assert mr.check_streams(streams, files) == []

    bad = [_rec("r", 0, 0, 5, 1.0, 2), _rec("r", 1, 0, 5, 1.1, 3)]
    mr, streams, files = _load(tmp_path, "bad.jsonl", bad)
    assert any("world stamp disagrees" in p for p in mr.check_streams(streams, files))

    oob = [_rec("r", 2, 0, 5, 1.0, 2)]  # rank 2 of a 2-world
    mr, streams, files = _load(tmp_path, "oob.jsonl", oob)
    assert any("world size" in p for p in mr.check_streams(streams, files))


def test_health_labels_shrunk_ranks_retired(tmp_path):
    """--health heartbeat table: a rank the supervisor shrank away
    (beats stop at gen 0, newest generation's world excludes it) reads
    ``retired@gen0``, not DEAD; a genuinely dead rank still reads
    dead."""
    def hb(rank, gen, step, ts, world, event=None):
        r = {"ts": ts, "rank": rank, "run_id": "r", "kind": "heartbeat",
             "gen": gen, "world": world, "step": step}
        if event:
            r["event"] = event
        return r

    recs = [
        hb(0, 0, 10, 100.0, 2), hb(1, 0, 10, 100.0, 2),
        hb(0, 1, 20, 500.0, 1), hb(0, 1, 20, 501.0, 1, event="final"),
    ]
    mr, streams, _ = _load(tmp_path, "heartbeat_rank0.jsonl", recs)
    rows = {r["rank"]: r["status"] for r in mr.heartbeat_rows(streams, "r")}
    assert rows[0] == "finished"
    assert rows[1] == "retired@gen0"
    # the full health render stays consumable and shows the label
    out = mr.render_health(streams)
    assert "retired@gen0" in out and "<-- RETIRED" not in out


# ----------------------------------------------------------- CI smoke gate
def test_smoke_topology_script(tmp_path):
    """The topology CI gate end to end (tools/smoke_topology.sh): the
    silent-corruption digest drill always runs; the 2-process
    kill-one-host shrink drill runs when this jax build supports
    multi-process CPU (the script probes, like every 2-proc drill)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_topology.sh"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=570, env=env,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "smoke_topology: OK" in r.stdout
    assert "digest drill OK" in r.stdout
    assert ("shrink drill OK" in r.stdout
            or "shrink drill skipped" in r.stdout)
    bench = json.load(open(tmp_path / "BENCH_r08.json"))
    assert bench["metric"] == "telemetry_examples_per_sec"
    assert bench["value"] > 0
