"""MVM exclusive-fields product path (models/mvm.py).

When no row repeats a field (the natural libffm shape), the
per-(row, field) view sums are single v values and the field product
collapses to a log-space product over the row's occurrences — the same
cache-resident [B, ~24] row-sum shape as FM, replacing the [B·nf, k+1]
segment aggregate that was the measured MVM wall (docs/PERF.md 3a).

Covers: duplicate detection, routing (auto/on/off × process count),
logit equality vs the row-major oracle, the FTRL-critical exact-zero
reactivation gradient, multi-step training equality vs the segment
path, trainer plan routing, and fullshard-engine equality.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.models import get_model
from xflow_tpu.models.mvm import (
    has_field_duplicates,
    resolve_mvm_product,
)
from xflow_tpu.ops.sorted_table import plan_sorted_batch
from xflow_tpu.optim import get_optimizer
from xflow_tpu.train.state import init_state
from xflow_tpu.train.step import make_train_step

LOG2_SLOTS = 14
S = 1 << LOG2_SLOTS
B, F = 64, 8


def _cfg(**extra):
    return override(
        Config(),
        **{
            "model.name": "mvm",
            "model.num_fields": F,
            "data.log2_slots": LOG2_SLOTS,
            "data.batch_size": B,
            "data.max_nnz": F,
            **extra,
        },
    )


def _exclusive_batch(rng, b=B, f=F):
    """One feature per field per row (fields 0..f-1), random mask."""
    return {
        "slots": rng.integers(0, S, (b, f)).astype(np.int32),
        "fields": np.broadcast_to(np.arange(f, dtype=np.int32), (b, f)).copy(),
        "mask": (rng.random((b, f)) < 0.8).astype(np.float32),
        "labels": (rng.random(b) < 0.4).astype(np.float32),
        "row_mask": np.ones((b,), np.float32),
    }


def _sorted_arrays(batch, with_fields):
    plan = plan_sorted_batch(
        batch["slots"], batch["mask"], S,
        fields=batch["fields"] if with_fields else None,
    )
    out = {
        "sorted_slots": jnp.asarray(plan.sorted_slots),
        "sorted_row": jnp.asarray(plan.sorted_row),
        "sorted_mask": jnp.asarray(plan.sorted_mask),
        "win_off": jnp.asarray(plan.win_off),
        "labels": jnp.asarray(batch["labels"]),
        "row_mask": jnp.asarray(batch["row_mask"]),
    }
    if with_fields:
        out["sorted_fields"] = jnp.asarray(plan.sorted_fields)
    return out


# ------------------------------------------------------------- detection

def test_has_field_duplicates_bitmask_path():
    fields = np.array([[0, 1, 2], [3, 3, 4]], np.int32)
    mask = np.ones((2, 3), np.float32)
    assert has_field_duplicates(fields, mask)
    # the duplicate pair masked out -> no duplicates among MASKED occs
    mask[1, 0] = 0.0
    assert not has_field_duplicates(fields, mask)


def test_has_field_duplicates_wide_field_space():
    # field ids >= 64 exercise the sort-based path
    fields = np.array([[100, 200, 100], [1, 2, 3]], np.int64)
    mask = np.ones((2, 3), np.float32)
    assert has_field_duplicates(fields, mask)
    mask[0, 2] = 0.0
    assert not has_field_duplicates(fields, mask)


def test_has_field_duplicates_empty_and_single():
    assert not has_field_duplicates(np.zeros((0, 3), np.int32), np.zeros((0, 3)))
    assert not has_field_duplicates(np.zeros((4, 1), np.int32), np.ones((4, 1)))


# --------------------------------------------------------------- routing

def test_resolve_mvm_product_routing():
    assert resolve_mvm_product("auto", False, 1)
    assert resolve_mvm_product("auto", False, 4)
    assert not resolve_mvm_product("auto", True, 1)  # per-batch fallback
    assert not resolve_mvm_product("off", False, 1)
    assert resolve_mvm_product("on", False, 1)
    with pytest.raises(ValueError, match="mvm_exclusive=off"):
        resolve_mvm_product("on", True, 1)
    with pytest.raises(ValueError, match="collective"):
        resolve_mvm_product("auto", True, 2)  # multi-process cannot reroute
    with pytest.raises(ValueError, match="auto|on|off"):
        resolve_mvm_product("maybe", False, 1)


# ------------------------------------------------------- forward parity

def test_product_logits_match_rowmajor_oracle():
    cfg = _cfg()
    model = get_model("mvm")
    rng = np.random.default_rng(0)
    batch = _exclusive_batch(rng)
    # O(1)-scale v so products neither vanish nor explode
    v = jnp.asarray(rng.standard_normal((S, cfg.model.v_dim)).astype(np.float32))
    ref = np.asarray(
        model.forward({"v": v}, {k: jnp.asarray(a) for k, a in batch.items()}, cfg)
    )
    got = np.asarray(model.forward({"v": v}, _sorted_arrays(batch, False), cfg))
    # ln/exp round-trip noise ~ |sum of logs| * eps, plus sign-cancelled
    # sums across latent dims: compare with a scale-aware atol
    np.testing.assert_allclose(
        got, ref, rtol=1e-4, atol=np.abs(ref).max() * 1e-5 + 1e-10
    )


def test_product_matches_segment_path_on_exclusive_data():
    cfg = _cfg()
    model = get_model("mvm")
    rng = np.random.default_rng(1)
    batch = _exclusive_batch(rng)
    v = jnp.asarray(rng.standard_normal((S, cfg.model.v_dim)).astype(np.float32))
    seg = np.asarray(model.forward({"v": v}, _sorted_arrays(batch, True), cfg))
    prod = np.asarray(model.forward({"v": v}, _sorted_arrays(batch, False), cfg))
    np.testing.assert_allclose(
        prod, seg, rtol=1e-4, atol=np.abs(seg).max() * 1e-5 + 1e-10
    )


def test_zero_value_reactivation_gradient():
    """FTRL-proximal zeroes v entries as its sparsity mechanism; the
    product path must keep the oracle's NONZERO gradient at exact-zero
    v (dP/dv = product of the row's other factors), or sparsified
    weights would freeze forever. The Z channel + the exclusive-product
    custom VJP in make_row_products (models/mvm.py) provide this — the
    clamped ln cancels in S - L_j, so no epsilon perturbation exists
    anywhere."""
    cfg = _cfg()
    model = get_model("mvm")
    rng = np.random.default_rng(2)
    batch = _exclusive_batch(rng)
    v_np = rng.standard_normal((S, cfg.model.v_dim)).astype(np.float32)
    # zero latent dim 0 for each row's FIELD-0 occurrence only, so the
    # product of the row's OTHER factors (the reactivation gradient)
    # stays nonzero
    v_np[batch["slots"][:, 0], 0] = 0.0
    v = jnp.asarray(v_np)
    rowmajor = {k: jnp.asarray(a) for k, a in batch.items()}
    sorted_b = _sorted_arrays(batch, False)

    def loss(tbl, b):
        return model.forward(tbl, b, cfg).sum()

    g_ref = np.asarray(jax.grad(loss)({"v": v}, rowmajor)["v"])
    g_got = np.asarray(jax.grad(loss)({"v": v}, sorted_b)["v"])
    touched = np.zeros(S, bool)
    touched[batch["slots"].ravel()] = True
    # dim-0 gradients at the zeroed entries are the nonzero reactivation
    # gradients; they must match the oracle, not be zero
    assert np.abs(g_ref[touched, 0]).max() > 0
    np.testing.assert_allclose(
        g_got[touched], g_ref[touched],
        rtol=1e-3, atol=np.abs(g_ref).max() * 2e-5 + 1e-10,
    )


def test_training_equality_product_vs_segment():
    """A few FTRL steps through each path end at the same tables."""
    cfg = _cfg()
    model, opt = get_model("mvm"), get_optimizer("ftrl")
    rng = np.random.default_rng(3)
    batches = [_exclusive_batch(rng) for _ in range(3)]
    step = make_train_step(model, opt, cfg)

    states = {}
    for with_fields in (False, True):
        st = init_state(model, opt, cfg)
        for b in batches:
            st, _ = step(st, _sorted_arrays(b, with_fields))
        states[with_fields] = st
    np.testing.assert_allclose(
        np.asarray(states[False].tables["v"]),
        np.asarray(states[True].tables["v"]),
        rtol=2e-4, atol=1e-6,
    )


# ------------------------------------------------------ trainer routing

def test_trainer_routes_exclusive_to_product_path():
    from xflow_tpu.data.schema import SparseBatch
    from xflow_tpu.train.trainer import Trainer

    cfg = _cfg()
    rng = np.random.default_rng(4)
    b = _exclusive_batch(rng)
    sb = SparseBatch(
        slots=b["slots"], fields=b["fields"], mask=b["mask"],
        labels=b["labels"], row_mask=b["row_mask"],
    )
    tr = Trainer(cfg)
    assert tr._sorted
    arrays = tr._batch_arrays(sb)
    assert "sorted_fields" not in arrays  # product path
    # duplicate fields in one row -> auto falls back to the segment path
    dup = SparseBatch(
        slots=b["slots"], fields=np.zeros_like(b["fields"]), mask=b["mask"],
        labels=b["labels"], row_mask=b["row_mask"],
    )
    arrays = tr._batch_arrays(dup)
    assert "sorted_fields" in arrays
    # forcing exclusivity raises on the same batch
    tr_on = Trainer(_cfg(**{"model.mvm_exclusive": "on"}))
    with pytest.raises(ValueError, match="mvm_exclusive=off"):
        tr_on._batch_arrays(dup)


# ------------------------------------------------------ fullshard engine

@pytest.mark.parametrize("mesh_shape", [(2, 4), (4, 2)])
def test_fullshard_product_matches_single_device(mesh_shape):
    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.parallel.sorted_fullshard import (
        fullshard_batch_sharding,
        make_fullshard_train_step,
        plan_fullshard_batch,
    )
    from xflow_tpu.parallel.train_step import shard_state

    d, t = mesh_shape
    cfg = _cfg(**{"mesh.data": d, "mesh.table": t})
    model, opt = get_model("mvm"), get_optimizer("ftrl")
    rng = np.random.default_rng(5)
    batches = [_exclusive_batch(rng) for _ in range(3)]

    state1 = init_state(model, opt, cfg)
    step1 = make_train_step(model, opt, cfg)
    losses1 = []
    for b in batches:
        state1, m = step1(state1, {k: jnp.asarray(v) for k, v in b.items()})
        losses1.append(float(m["loss"]))

    mesh = make_mesh(cfg, devices=jax.devices()[: d * t])
    state2 = shard_state(init_state(model, opt, cfg), mesh)
    step2 = make_fullshard_train_step(opt, cfg, mesh)
    bsh = fullshard_batch_sharding(mesh, with_fields=False)
    losses2 = []
    for b in batches:
        arrays = plan_fullshard_batch(b["slots"], b["mask"], cfg, mesh)
        arrays["labels"] = b["labels"]
        arrays["row_mask"] = b["row_mask"]
        placed = {k: jax.device_put(jnp.asarray(v), bsh[k]) for k, v in arrays.items()}
        assert "fs_fields" not in placed  # product mode
        state2, m = step2(state2, placed)
        losses2.append(float(m["loss"]))

    np.testing.assert_allclose(losses1, losses2, rtol=2e-4)
    np.testing.assert_allclose(
        np.asarray(state1.tables["v"]),
        np.asarray(state2.tables["v"]),
        rtol=2e-4, atol=1e-6,
    )


def test_plus_one_form_all_paths_agree():
    """model.mvm_plus_one (the reference gradient's bias-augmented
    factor form, mvm_worker.cc:153-157): row-major, segment, and
    product paths compute the same logits."""
    cfg = _cfg(**{"model.mvm_plus_one": True})
    model = get_model("mvm")
    rng = np.random.default_rng(7)
    batch = _exclusive_batch(rng)
    v = jnp.asarray(
        (rng.standard_normal((S, cfg.model.v_dim)) * 0.1).astype(np.float32)
    )
    ref = np.asarray(
        model.forward({"v": v}, {k: jnp.asarray(a) for k, a in batch.items()}, cfg)
    )
    seg = np.asarray(model.forward({"v": v}, _sorted_arrays(batch, True), cfg))
    prod = np.asarray(model.forward({"v": v}, _sorted_arrays(batch, False), cfg))
    scale = np.abs(ref).max() * 1e-5 + 1e-10
    np.testing.assert_allclose(seg, ref, rtol=1e-4, atol=scale)
    np.testing.assert_allclose(prod, ref, rtol=1e-4, atol=scale)


def test_plus_one_learns_where_plain_product_cannot():
    """With 8+ fields and the reference's 1e-2 v init, the plain product
    model's gradients vanish multiplicatively (each is a product of the
    row's OTHER ~1e-2 factors); the plus-one form keeps factors near 1
    and learns. This is why mvm_plus_one exists."""
    from xflow_tpu.train.step import loss_fn

    model, opt = get_model("mvm"), get_optimizer("ftrl")
    rng = np.random.default_rng(8)
    batch = _exclusive_batch(rng)
    batch["mask"][:] = 1.0  # all 8 fields present: Π_others ~ (1e-2)^7
    last = {}
    for plus in (False, True):
        cfg = _cfg(**{"model.mvm_plus_one": plus})
        st = init_state(model, opt, cfg)
        g = jax.grad(loss_fn)(st.tables, _sorted_arrays(batch, False), model, cfg)
        last[plus] = float(np.abs(np.asarray(g["v"])).max())
    assert last[False] < 1e-9  # multiplicatively vanished
    assert last[True] > 1e-4  # alive
