"""On-TPU kernel parity gate (VERDICT r2 item 6).

Auto-skips off-TPU: the pytest conftest pins an 8-device CPU platform,
so in CI this file is a no-op; on a TPU host run

    XFLOW_TEST_PLATFORM=tpu python -m pytest tests/test_kernel_parity_tpu.py

`bench.py` also runs the same check on every benchmark invocation (the
driver always benches on real hardware), so `BENCH_r*.json` carries a
`kernel_parity` field — the silent-MXU-rounding class of bug
(docs/CHANGES_R2.md "Precision integrity") cannot regress unseen.
"""

import jax
import pytest

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="requires a real TPU chip"
)


def test_kernel_parity_on_device():
    from xflow_tpu.tools.kernel_parity import check_kernel_parity

    res = check_kernel_parity()
    assert res["ok"], res
