import jax
import jax.numpy as jnp
import numpy as np

from xflow_tpu.config import Config, override
from xflow_tpu.data.pipeline import examples_to_batches
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.data.libffm import iter_examples
from xflow_tpu.metrics import auc_logloss
from xflow_tpu.models import get_model
from xflow_tpu.optim import get_optimizer
from xflow_tpu.train import init_state, make_eval_step, make_train_step
from xflow_tpu.train.step import batch_to_arrays


def small_cfg(**kw):
    base = {
        "data.log2_slots": 14,
        "data.batch_size": 64,
        "data.max_nnz": 20,
        "model.num_fields": 6,
        "model.v_dim": 4,
    }
    base.update(kw)
    return override(Config(), **base)


def _device_batches(path, cfg):
    return [
        {k: jnp.asarray(v) for k, v in batch_to_arrays(b).items()}
        for b in examples_to_batches(
            iter_examples(path, cfg.data.log2_slots), cfg.data.batch_size, cfg.data.max_nnz
        )
    ]


def test_lr_gradient_is_scatter_of_residuals():
    # hand-check: grad wrt w[slot] == sum over occurrences (σ(wx)−y)/rows
    cfg = small_cfg()
    model = get_model("lr")
    from xflow_tpu.train.step import loss_fn

    w = jnp.zeros((cfg.num_slots,))
    batch = {
        "slots": jnp.asarray([[3, 5, 0], [3, 3, 0]], jnp.int32),
        "fields": jnp.zeros((2, 3), jnp.int32),
        "mask": jnp.asarray([[1, 1, 0], [1, 1, 0]], jnp.float32),
        "labels": jnp.asarray([1.0, 0.0]),
        "row_mask": jnp.ones((2,)),
    }
    g = jax.grad(loss_fn)(({"w": w}), batch, model, cfg)["w"]
    # logits 0 → σ=0.5; residuals: row0 = −0.5 on slots {3,5}, row1 = +0.5 twice on slot 3
    np.testing.assert_allclose(float(g[3]), (-0.5 + 0.5 + 0.5) / 2, rtol=1e-6)
    np.testing.assert_allclose(float(g[5]), -0.5 / 2, rtol=1e-6)
    assert float(g[0]) == 0.0  # masked padding contributes nothing


def test_training_learns_synthetic_lr(tmp_path):
    cfg = small_cfg()
    path = generate_shards(str(tmp_path / "s"), 1, 2000, num_fields=6, ids_per_field=50, seed=0, noise=0.3)[0]
    model, opt = get_model("lr"), get_optimizer("ftrl")
    state = init_state(model, opt, cfg)
    step = make_train_step(model, opt, cfg)
    eval_step = make_eval_step(model, cfg)
    batches = _device_batches(path, cfg)
    for epoch in range(8):
        for b in batches:
            state, m = step(state, b)
    pctrs, labels = [], []
    for b in batches:
        p = np.asarray(eval_step(state.tables, b))
        rm = np.asarray(b["row_mask"]) > 0
        pctrs.append(p[rm])
        labels.append(np.asarray(b["labels"])[rm])
    auc, ll = auc_logloss(np.concatenate(pctrs), np.concatenate(labels))
    assert auc > 0.85, f"LR failed to learn synthetic data: auc={auc}"


def test_training_learns_fm(tmp_path):
    path = generate_shards(str(tmp_path / "s"), 1, 1500, num_fields=6, ids_per_field=50, seed=1, noise=0.3)[0]
    cfg = override(small_cfg(), **{"model.name": "fm"})
    model, opt = get_model("fm"), get_optimizer("ftrl")
    state = init_state(model, opt, cfg)
    step = make_train_step(model, opt, cfg)
    eval_step = make_eval_step(model, cfg)
    batches = _device_batches(path, cfg)
    for epoch in range(10):
        for b in batches:
            state, m = step(state, b)
    pctrs, labels = [], []
    for b in batches:
        p = np.asarray(eval_step(state.tables, b))
        rm = np.asarray(b["row_mask"]) > 0
        pctrs.append(p[rm])
        labels.append(np.asarray(b["labels"])[rm])
    auc, _ = auc_logloss(np.concatenate(pctrs), np.concatenate(labels))
    assert auc > 0.8, f"fm failed to learn: auc={auc}"


def test_mvm_trains_loss_decreases(tmp_path):
    # MVM has no linear term: its logit is a product over field sums, so a
    # planted-LR task isn't representable near tiny init, and FTRL's soft
    # threshold zeroes the tiny latent weights outright (true of the
    # reference too). Assert steady SGD progress instead.
    path = generate_shards(str(tmp_path / "s"), 1, 512, num_fields=3, ids_per_field=20, seed=2, noise=0.3)[0]
    cfg = override(
        small_cfg(),
        **{
            "model.name": "mvm",
            "model.num_fields": 3,
            "optim.name": "sgd",
            "optim.sgd.lr": 1.0,
            "optim.v_init_sgd": 0.3,
        },
    )
    model, opt = get_model("mvm"), get_optimizer("sgd")
    state = init_state(model, opt, cfg)
    step = make_train_step(model, opt, cfg)
    batches = _device_batches(path, cfg)
    first = last = None
    for epoch in range(15):
        tot, n = 0.0, 0
        for b in batches:
            state, m = step(state, b)
            tot += float(m["loss"]); n += 1
        if first is None:
            first = tot / n
        last = tot / n
    assert last < first * 0.95, f"mvm loss did not decrease: {first} -> {last}"


def test_loss_decreases():
    cfg = small_cfg()
    rng = np.random.default_rng(0)
    model, opt = get_model("lr"), get_optimizer("sgd")
    cfg = override(cfg, **{"optim.name": "sgd", "optim.sgd.lr": 0.5})
    state = init_state(model, opt, cfg)
    step = make_train_step(model, opt, cfg)
    batch = {
        "slots": jnp.asarray(rng.integers(0, cfg.num_slots, (32, 8)), jnp.int32),
        "fields": jnp.zeros((32, 8), jnp.int32),
        "mask": jnp.ones((32, 8), jnp.float32),
        "labels": jnp.asarray((rng.random(32) < 0.5).astype(np.float32)),
        "row_mask": jnp.ones((32,)),
    }
    losses = []
    for _ in range(20):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.8
