import jax
import jax.numpy as jnp
import numpy as np

from xflow_tpu.config import Config, override
from xflow_tpu.data.pipeline import examples_to_batches
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.data.libffm import iter_examples
from xflow_tpu.metrics import auc_logloss
from xflow_tpu.models import get_model
from xflow_tpu.optim import get_optimizer
from xflow_tpu.train import init_state, make_eval_step, make_train_step
from xflow_tpu.train.step import batch_to_arrays


def small_cfg(**kw):
    base = {
        "data.log2_slots": 14,
        "data.batch_size": 64,
        "data.max_nnz": 20,
        "model.num_fields": 6,
        "model.v_dim": 4,
    }
    base.update(kw)
    return override(Config(), **base)


def _device_batches(path, cfg):
    return [
        {k: jnp.asarray(v) for k, v in batch_to_arrays(b).items()}
        for b in examples_to_batches(
            iter_examples(path, cfg.data.log2_slots), cfg.data.batch_size, cfg.data.max_nnz
        )
    ]


def test_lr_gradient_is_scatter_of_residuals():
    # hand-check: grad wrt w[slot] == sum over occurrences (σ(wx)−y)/rows
    cfg = small_cfg()
    model = get_model("lr")
    from xflow_tpu.train.step import loss_fn

    w = jnp.zeros((cfg.num_slots,))
    batch = {
        "slots": jnp.asarray([[3, 5, 0], [3, 3, 0]], jnp.int32),
        "fields": jnp.zeros((2, 3), jnp.int32),
        "mask": jnp.asarray([[1, 1, 0], [1, 1, 0]], jnp.float32),
        "labels": jnp.asarray([1.0, 0.0]),
        "row_mask": jnp.ones((2,)),
    }
    g = jax.grad(loss_fn)(({"w": w}), batch, model, cfg)["w"]
    # logits 0 → σ=0.5; residuals: row0 = −0.5 on slots {3,5}, row1 = +0.5 twice on slot 3
    np.testing.assert_allclose(float(g[3]), (-0.5 + 0.5 + 0.5) / 2, rtol=1e-6)
    np.testing.assert_allclose(float(g[5]), -0.5 / 2, rtol=1e-6)
    assert float(g[0]) == 0.0  # masked padding contributes nothing


def test_training_learns_synthetic_lr(tmp_path):
    cfg = small_cfg()
    path = generate_shards(str(tmp_path / "s"), 1, 2000, num_fields=6, ids_per_field=50, seed=0, noise=0.3)[0]
    model, opt = get_model("lr"), get_optimizer("ftrl")
    state = init_state(model, opt, cfg)
    step = make_train_step(model, opt, cfg)
    eval_step = make_eval_step(model, cfg)
    batches = _device_batches(path, cfg)
    for epoch in range(8):
        for b in batches:
            state, m = step(state, b)
    pctrs, labels = [], []
    for b in batches:
        p = np.asarray(eval_step(state.tables, b))
        rm = np.asarray(b["row_mask"]) > 0
        pctrs.append(p[rm])
        labels.append(np.asarray(b["labels"])[rm])
    auc, ll = auc_logloss(np.concatenate(pctrs), np.concatenate(labels))
    assert auc > 0.85, f"LR failed to learn synthetic data: auc={auc}"


def test_training_learns_fm(tmp_path):
    path = generate_shards(str(tmp_path / "s"), 1, 1500, num_fields=6, ids_per_field=50, seed=1, noise=0.3)[0]
    cfg = override(small_cfg(), **{"model.name": "fm"})
    model, opt = get_model("fm"), get_optimizer("ftrl")
    state = init_state(model, opt, cfg)
    step = make_train_step(model, opt, cfg)
    eval_step = make_eval_step(model, cfg)
    batches = _device_batches(path, cfg)
    for epoch in range(10):
        for b in batches:
            state, m = step(state, b)
    pctrs, labels = [], []
    for b in batches:
        p = np.asarray(eval_step(state.tables, b))
        rm = np.asarray(b["row_mask"]) > 0
        pctrs.append(p[rm])
        labels.append(np.asarray(b["labels"])[rm])
    auc, _ = auc_logloss(np.concatenate(pctrs), np.concatenate(labels))
    assert auc > 0.8, f"fm failed to learn: auc={auc}"


def test_mvm_trains_loss_decreases(tmp_path):
    # MVM has no linear term: its logit is a product over field sums, so a
    # planted-LR task isn't representable near tiny init, and FTRL's soft
    # threshold zeroes the tiny latent weights outright (true of the
    # reference too). Assert steady SGD progress instead.
    path = generate_shards(str(tmp_path / "s"), 1, 512, num_fields=3, ids_per_field=20, seed=2, noise=0.3)[0]
    cfg = override(
        small_cfg(),
        **{
            "model.name": "mvm",
            "model.num_fields": 3,
            "optim.name": "sgd",
            "optim.sgd.lr": 1.0,
            "optim.v_init_sgd": 0.3,
        },
    )
    model, opt = get_model("mvm"), get_optimizer("sgd")
    state = init_state(model, opt, cfg)
    step = make_train_step(model, opt, cfg)
    batches = _device_batches(path, cfg)
    first = last = None
    for epoch in range(15):
        tot, n = 0.0, 0
        for b in batches:
            state, m = step(state, b)
            tot += float(m["loss"]); n += 1
        if first is None:
            first = tot / n
        last = tot / n
    assert last < first * 0.95, f"mvm loss did not decrease: {first} -> {last}"


def test_loss_decreases():
    cfg = small_cfg()
    rng = np.random.default_rng(0)
    model, opt = get_model("lr"), get_optimizer("sgd")
    cfg = override(cfg, **{"optim.name": "sgd", "optim.sgd.lr": 0.5})
    state = init_state(model, opt, cfg)
    step = make_train_step(model, opt, cfg)
    batch = {
        "slots": jnp.asarray(rng.integers(0, cfg.num_slots, (32, 8)), jnp.int32),
        "fields": jnp.zeros((32, 8), jnp.int32),
        "mask": jnp.ones((32, 8), jnp.float32),
        "labels": jnp.asarray((rng.random(32) < 0.5).astype(np.float32)),
        "row_mask": jnp.ones((32,)),
    }
    losses = []
    for _ in range(20):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.8


def test_fused_scatter_ftrl_matches_two_pass():
    """optim.fused_scatter: the fused scatter+FTRL FM step (gradient
    applied inside the window write, ops/sorted_table.scatter_ftrl_sorted)
    must equal the value_and_grad + ftrl.apply two-pass form — same
    losses, same tables, same FTRL state, over several steps, packed
    and unpacked storage."""
    from xflow_tpu.ops.sorted_table import plan_sorted_batch

    for model_name, packed in (("fm", "auto"), ("fm", "off"), ("mvm", "auto")):
        # MVM fuses only under the explicit "on" (auto keeps it two-pass)
        base = {
            "model.name": model_name, "data.log2_slots": 13, "data.batch_size": 64,
            "data.max_nnz": 7, "model.num_fields": 5,
            "data.packed_tables": packed,
        }
        mode = "on" if model_name == "mvm" else "auto"
        cfg_f = override(Config(), **{**base, "optim.fused_scatter": mode})
        cfg_o = override(Config(), **{**base, "optim.fused_scatter": "off"})
        model, opt = get_model(model_name), get_optimizer("ftrl")
        tname = "v" if model_name == "mvm" else "wv"
        rng = np.random.default_rng(0)
        S = 1 << 13
        state_f = init_state(model, opt, cfg_f)
        state_o = init_state(model, opt, cfg_o)
        step_f = make_train_step(model, opt, cfg_f)
        step_o = make_train_step(model, opt, cfg_o)
        for i in range(3):
            slots = rng.integers(0, S, (64, 7)).astype(np.int32)
            mask = (rng.random((64, 7)) < 0.8).astype(np.float32)
            plan = plan_sorted_batch(slots, mask, S)
            batch = {
                "labels": jnp.asarray((rng.random(64) < 0.4).astype(np.float32)),
                "row_mask": jnp.ones(64, jnp.float32),
                "sorted_slots": jnp.asarray(plan.sorted_slots),
                "sorted_row": jnp.asarray(plan.sorted_row),
                "sorted_mask": jnp.asarray(plan.sorted_mask),
                "win_off": jnp.asarray(plan.win_off),
            }
            state_f, m_f = step_f(state_f, batch)
            state_o, m_o = step_o(state_o, batch)
            np.testing.assert_allclose(float(m_f["loss"]), float(m_o["loss"]), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(state_f.tables[tname]), np.asarray(state_o.tables[tname]),
            rtol=1e-6, atol=1e-8, err_msg=f"fused != two-pass ({model_name}, packed={packed})",
        )
        for key in ("n", "z"):
            np.testing.assert_allclose(
                np.asarray(state_f.opt_state[tname][key]),
                np.asarray(state_o.opt_state[tname][key]),
                rtol=1e-6, atol=1e-8,
            )


def test_fused_scatter_on_fails_loudly_when_ineligible():
    """optim.fused_scatter=on is a hard assertion, not a hint: config
    ineligibility (wrong optimizer/model, sharded builder) and
    non-flat-plan batches raise instead of silently running two-pass."""
    import pytest

    from xflow_tpu.train.step import _fused_scatter_eligible

    on = override(Config(), **{"optim.fused_scatter": "on"})
    assert _fused_scatter_eligible(override(on, **{"model.name": "fm"}), True)
    with pytest.raises(ValueError, match="fused_scatter=on"):
        _fused_scatter_eligible(override(on, **{"model.name": "lr"}), True)
    with pytest.raises(ValueError, match="single_device"):
        _fused_scatter_eligible(override(on, **{"model.name": "fm"}), False)
    with pytest.raises(ValueError, match="optim.name=ftrl"):
        _fused_scatter_eligible(override(on, **{"optim.name": "sgd"}), True)

    # a row-major batch under 'on' raises at trace time
    cfg = override(Config(), **{"optim.fused_scatter": "on", "model.name": "fm",
                                "data.log2_slots": 12, "data.batch_size": 16,
                                "data.max_nnz": 4, "model.num_fields": 3})
    model, opt = get_model("fm"), get_optimizer("ftrl")
    state = init_state(model, opt, cfg)
    step = make_train_step(model, opt, cfg)
    rng = np.random.default_rng(0)
    batch = {
        "slots": jnp.asarray(rng.integers(0, 1 << 12, (16, 4)).astype(np.int32)),
        "fields": jnp.zeros((16, 4), jnp.int32),
        "mask": jnp.ones((16, 4), jnp.float32),
        "labels": jnp.zeros(16, jnp.float32),
        "row_mask": jnp.ones(16, jnp.float32),
    }
    with pytest.raises(ValueError, match="no flat fields-free sorted plan"):
        step(state, batch)


def test_kernel_parity_runs_off_tpu():
    """The parity gate's contract: runnable on whatever backend is live
    (the fused scatter+FTRL check dispatches to the two-pass fallback
    off-TPU and passes trivially)."""
    from xflow_tpu.tools.kernel_parity import check_kernel_parity

    par = check_kernel_parity(log2_slots=13, n_occ=1 << 12, batch=256)
    assert par["ok"], par["checks"]


def test_fused_scatter_on_rejected_on_mesh_at_startup():
    """optim.fused_scatter=on on a mesh must fail at Trainer
    construction (the mesh engines run two-pass; a lazily-built
    overflow-fallback step raising mid-run would be far worse)."""
    import pytest

    from xflow_tpu.parallel.mesh import make_mesh
    from xflow_tpu.train.trainer import Trainer

    cfg = override(Config(), **{
        "model.name": "fm", "data.log2_slots": 14, "mesh.data": 4,
        "mesh.table": 2, "optim.fused_scatter": "on",
    })
    with pytest.raises(ValueError, match="single-device"):
        Trainer(cfg, mesh=make_mesh(cfg))
