"""Performance-attribution layer (round 9): CompileRecorder unit
coverage on fake lowered/compiled seams and real jax, the CPU-backend
memory_stats guard, StepTimer roofline gauges, tools/trace_attrib.py
on the checked-in minimal trace fixture, tools/perf_ledger.py
consolidation + regression-gate exit codes, the metrics_report
compile-schema / exactly-once-recompile gates, and the
tools/smoke_perf.sh CI gate end to end.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from types import SimpleNamespace

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.telemetry import (
    CompileRecorder,
    Registry,
    StepTimer,
    device_memory_stats,
    hbm_window_fields,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACE_FIXTURE = os.path.join(REPO_ROOT, "tests", "data", "minimal.trace.json.gz")


def tool(name: str) -> str:
    return os.path.join(REPO_ROOT, "tools", name)


def run_tool(args, **kw):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable] + args, capture_output=True, text=True, env=env, **kw
    )


# --------------------------------------------------- CompileRecorder (fakes)


HLO_TEXT = """\
HloModule jit_step
fusion.1 = f32[8]{0} fusion(x), kind=kLoop, metadata={op_name="jit(step)/jit(main)/grad/gather" source_file="x.py"}
add.2 = f32[] add(a, b), metadata={op_name="jit(step)/jit(main)/optimizer/add"}
noise.3 = f32[] add(a, b), metadata={op_name="jit(step)/jit(main)/mul"}
"""


class FakeCompiled:
    def __init__(self):
        self.calls = 0

    def cost_analysis(self):
        # the list-of-dicts shape jax 0.4.x returns
        return [{"flops": 10.0, "bytes accessed": 100.0}]

    def memory_analysis(self):
        return SimpleNamespace(
            argument_size_in_bytes=11,
            output_size_in_bytes=22,
            temp_size_in_bytes=33,
            generated_code_size_in_bytes=44,
        )

    def as_text(self):
        return HLO_TEXT

    def __call__(self, *args, **kwargs):
        self.calls += 1
        return "compiled-ran"


class FakeLowered:
    def __init__(self, compiled):
        self._compiled = compiled

    def compile(self):
        return self._compiled


class FakeJitted:
    """The .lower().compile() seam without jax."""

    def __init__(self, fail=False):
        self.compiled = FakeCompiled()
        self.lowers = 0
        self.direct_calls = 0
        self.fail = fail

    def lower(self, *args, **kwargs):
        self.lowers += 1
        if self.fail:
            raise RuntimeError("no AOT for you")
        return FakeLowered(self.compiled)

    def __call__(self, *args, **kwargs):
        self.direct_calls += 1
        return "jit-ran"


class ListSink:
    def __init__(self):
        self.records = []

    def append(self, rec):
        self.records.append(rec)


def test_compile_recorder_records_and_caches():
    sink = ListSink()
    rec = CompileRecorder(sink=sink, registry=Registry())
    fake = FakeJitted()
    fn = rec.wrap("train_step", fake)
    x = np.zeros((4, 2), np.float32)
    assert fn(x) == "compiled-ran"
    assert fn(x) == "compiled-ran"  # same signature: cache hit
    assert fake.lowers == 1 and fake.compiled.calls == 2
    assert len(sink.records) == 1
    r = sink.records[0]
    assert r["kind"] == "compile" and r["program"] == "train_step"
    assert r["compile_time_s"] >= 0 and r["compiles"] == 1
    assert r["flops"] == 10.0 and r["bytes_accessed"] == 100.0
    assert r["argument_bytes"] == 11 and r["temp_bytes"] == 33
    # op_scopes: the LAST scope component wins, the primitive (final
    # component) never matches, unscoped ops stay out
    assert r["op_scopes"] == {"fusion.1": "grad", "add.2": "optimizer"}
    assert r["hlo_module"] == "jit_step"  # the trace-join key
    assert rec.recompiles == 0


def test_compile_recorder_new_signature_is_not_a_recompile():
    sink = ListSink()
    rec = CompileRecorder(sink=sink, registry=Registry())
    fn = rec.wrap("train_step", FakeJitted())
    fn(np.zeros((4, 2), np.float32))
    fn(np.zeros((8, 2), np.float32))  # new shape: new program
    assert len(sink.records) == 2
    assert [r["compiles"] for r in sink.records] == [1, 2]
    assert sink.records[0]["sig"] != sink.records[1]["sig"]
    assert rec.recompiles == 0


def test_compile_recorder_recompile_counted():
    reg = Registry()
    rec = CompileRecorder(sink=ListSink(), registry=reg)
    fake = FakeJitted()
    x = np.zeros((2,), np.float32)
    rec.record("train_step", fake, x)
    rec.record("train_step", fake, x)  # same (program, sig) twice
    assert rec.recompiles == 1
    snap = reg.snapshot()
    assert snap["compile.recompiles"] == 1
    assert snap["compile.programs"] == 1


def test_compile_recorder_fallback_on_aot_failure(capsys):
    rec = CompileRecorder(sink=ListSink(), registry=Registry())
    fake = FakeJitted(fail=True)
    fn = rec.wrap("train_step", fake)
    x = np.zeros((2,), np.float32)
    assert fn(x) == "jit-ran"
    assert fn(x) == "jit-ran"
    # one lower attempt, then the plain jit path with no record
    assert fake.lowers == 1 and fake.direct_calls == 2
    assert rec.records == []
    assert "falling back" in capsys.readouterr().err


def test_compile_recorder_real_jax():
    import jax
    import jax.numpy as jnp

    sink = ListSink()
    rec = CompileRecorder(sink=sink, registry=Registry())
    fn = rec.wrap("train_step.real", jax.jit(lambda a, b: (a @ b).sum()))
    x = jnp.ones((16, 16))
    got = fn(x, x)
    assert float(got) == float((np.ones((16, 16)) @ np.ones((16, 16))).sum())
    assert fn(x, x) is not None  # cache hit, no second record
    assert len(sink.records) == 1
    r = sink.records[0]
    assert r["compile_time_s"] > 0
    assert r["flops"] and r["flops"] > 0
    assert r["bytes_accessed"] and r["bytes_accessed"] > 0
    assert rec.latest_cost("train_step") == {
        "flops": r["flops"],
        "bytes": r["bytes_accessed"],
    }


# ------------------------------------------------------------- HBM gauges


def test_device_memory_stats_cpu_guard():
    # the CPU allocator reports nothing: the guard yields {} (never a
    # raise), so window records simply omit the HBM fields
    assert device_memory_stats() == {}
    assert hbm_window_fields(Registry()) == {}


def test_device_memory_stats_fake_device():
    dev = SimpleNamespace(
        memory_stats=lambda: {
            "bytes_in_use": 1000,
            "peak_bytes_in_use": 2000,
            "bytes_limit": 4000,
            "irrelevant": "x",
        }
    )
    stats = device_memory_stats(dev)
    assert stats == {"bytes_in_use": 1000, "peak_bytes_in_use": 2000,
                     "bytes_limit": 4000}
    reg = Registry()
    fields = hbm_window_fields(reg, device=dev)
    assert fields["hbm_bytes_in_use"] == 1000
    assert fields["hbm_peak_bytes"] == 2000
    assert fields["hbm_bytes_limit"] == 4000
    snap = reg.snapshot()
    assert snap["hbm.bytes_in_use"] == 1000
    assert snap["hbm.peak_bytes"] == 2000


def test_device_memory_stats_erroring_device():
    def boom():
        raise RuntimeError("allocator exploded")

    assert device_memory_stats(SimpleNamespace(memory_stats=boom)) == {}


# --------------------------------------------------- StepTimer roofline


def test_steptimer_roofline_fields():
    st = StepTimer(Registry())
    for batch in st.batches([1, 2, 3]):
        st.dispatched(np.float32(0.5), rows=64)
    st.flush()
    rec = st.window_record(cost={"flops": 1000.0, "bytes": 500.0})
    assert rec["achieved_flops_per_s"] > 0
    assert rec["achieved_hbm_gbps"] > 0
    # flops/bytes ratio is pinned by the cost model: per unit device
    # time the two gauges differ by exactly bytes/flops * 1e-9
    ratio = rec["achieved_hbm_gbps"] * 1e9 / rec["achieved_flops_per_s"]
    assert ratio == pytest.approx(0.5, rel=0.05)


def test_steptimer_no_cost_no_roofline_fields():
    st = StepTimer(Registry())
    for batch in st.batches([1]):
        st.dispatched(np.float32(0.5), rows=64)
    st.flush()
    rec = st.window_record()
    assert "achieved_flops_per_s" not in rec
    assert "achieved_hbm_gbps" not in rec


# --------------------------------------- trainer integration (end to end)


def _train_tiny(tmp_path, **extra):
    from xflow_tpu.data.synth import generate_shards
    from xflow_tpu.train.trainer import Trainer

    data = str(tmp_path / "train")
    generate_shards(data, 1, 320, num_fields=6, ids_per_field=50, seed=0)
    cfg = override(Config(), **{
        "model.name": "lr",
        "data.train_path": data,
        "data.log2_slots": 12,
        "data.max_nnz": 8,
        "data.batch_size": 64,
        "model.num_fields": 6,
        "train.epochs": 1,
        "train.pred_dump": False,
        "train.log_every": 2,
        "train.metrics_path": str(tmp_path / "run" / "metrics_rank0.jsonl"),
        **extra,
    })
    trainer = Trainer(cfg)
    res = trainer.fit()
    from xflow_tpu.jsonl import read_jsonl

    return res, read_jsonl(str(tmp_path / "run" / "metrics_rank0.jsonl"))


def test_trainer_emits_compile_records(tmp_path):
    res, recs = _train_tiny(tmp_path)
    assert res.steps == 5
    comp = [r for r in recs if r.get("kind") == "compile"]
    assert len(comp) == 1  # one train program, compiled exactly once
    c = comp[0]
    assert c["program"] == "train_step"
    assert c["compile_time_s"] > 0 and c["flops"] > 0 and c["bytes_accessed"] > 0
    assert c["op_scopes"]  # the trace-attribution join map
    # roofline gauges land in the window records (cost known after the
    # first step's compile)
    wins = [r for r in recs if "achieved_flops_per_s" in r]
    assert wins
    # CPU: no HBM fields (the guard)
    assert not any("hbm_bytes_in_use" in r for r in recs)
    # the run passes the full --check gate including the compile rules
    r = run_tool([tool("metrics_report.py"), str(tmp_path / "run"), "--check"])
    assert r.returncode == 0, r.stderr
    # and the bench record carries the compile context
    r = run_tool([tool("metrics_report.py"), str(tmp_path / "run"),
                  "--bench-json", "-"])
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["compiled_programs"] == 1
    assert rec["compile_time_s"] > 0


def test_trainer_compile_metrics_off(tmp_path):
    res, recs = _train_tiny(tmp_path, **{"train.compile_metrics": False})
    assert res.steps == 5
    assert not any(r.get("kind") == "compile" for r in recs)


# ------------------------------------------------------------ trace_attrib


def _compile_jsonl(tmp_path) -> str:
    run_dir = tmp_path / "run"
    run_dir.mkdir(exist_ok=True)
    rec = {
        "ts": 1.0, "rank": 0, "run_id": "fix", "kind": "compile",
        "program": "train_step", "sig": "abc", "compile_time_s": 0.1,
        "flops": 1.0, "bytes_accessed": 2.0,
        "op_scopes": {
            "gather_fusion.1": "gather",
            "multiply_subtract_fusion": "optimizer",
            "while": "grad",
        },
    }
    path = run_dir / "metrics_rank0.jsonl"
    path.write_text(json.dumps(rec) + "\n")
    return str(run_dir)


def test_trace_attrib_fixture_with_map(tmp_path):
    run_dir = _compile_jsonl(tmp_path)
    out = tmp_path / "attrib.json"
    r = run_tool([tool("trace_attrib.py"), TRACE_FIXTURE,
                  "--run-dir", run_dir, "--json", str(out)])
    assert r.returncode == 0, r.stderr
    got = json.loads(out.read_text())
    scopes = got["scopes"]
    # map join: gather 100us, optimizer 50us (+10us from the TPU-style
    # path event), grad 300us, unknown op -> other; the host python
    # event (1000us) is excluded entirely
    assert scopes["gather"]["ms"] == pytest.approx(0.1)
    assert scopes["grad"]["ms"] == pytest.approx(0.3)
    assert scopes["optimizer"]["ms"] == pytest.approx(0.06)
    assert scopes["other"]["ms"] == pytest.approx(0.025)
    assert got["total_ms"] == pytest.approx(0.485)
    assert "grad" in r.stdout and "%" in r.stdout  # the table rendered


def test_trace_attrib_fixture_keyword_fallback(tmp_path):
    # no --run-dir: the keyword fallback attributes gather_fusion to
    # "gather"; the rest buckets other (honest: it cannot tell phases)
    r = run_tool([tool("trace_attrib.py"), TRACE_FIXTURE,
                  "--json", str(tmp_path / "a.json")])
    assert r.returncode == 0, r.stderr
    got = json.loads((tmp_path / "a.json").read_text())
    assert got["scopes"]["gather"]["ms"] == pytest.approx(0.1)
    # the TPU-style path event still attributes via its long_name
    assert got["scopes"]["optimizer"]["ms"] == pytest.approx(0.01)


def test_trace_attrib_module_keyed_join(tmp_path):
    # two programs reuse the HLO op name "fusion.1" (op names are only
    # module-unique): the event's hlo_module picks ITS program's map,
    # never the other's — and an op missing from its own module's map
    # buckets "other" instead of borrowing a colliding entry
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    recs = [
        {"kind": "compile", "program": "train_step", "sig": "a",
         "compile_time_s": 0.1, "flops": 1.0, "bytes_accessed": 1.0,
         "hlo_module": "jit_train_step", "op_scopes": {"fusion.1": "grad"}},
        {"kind": "compile", "program": "predict", "sig": "b",
         "compile_time_s": 0.1, "flops": 1.0, "bytes_accessed": 1.0,
         "hlo_module": "jit_predict", "op_scopes": {"fusion.1": "gather"}},
    ]
    (run_dir / "m.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in recs))
    trace = tmp_path / "t.trace.json"
    trace.write_text(json.dumps({"traceEvents": [
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 100.0,
         "name": "fusion.1",
         "args": {"hlo_op": "fusion.1", "hlo_module": "jit_train_step"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 200, "dur": 40.0,
         "name": "fusion.1",
         "args": {"hlo_op": "fusion.1", "hlo_module": "jit_predict"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 300, "dur": 7.0,
         "name": "unmapped.9",
         "args": {"hlo_op": "unmapped.9", "hlo_module": "jit_predict"}},
    ]}))
    out = tmp_path / "a.json"
    r = run_tool([tool("trace_attrib.py"), str(trace),
                  "--run-dir", str(run_dir), "--json", str(out)])
    assert r.returncode == 0, r.stderr
    scopes = json.loads(out.read_text())["scopes"]
    assert scopes["grad"]["ms"] == pytest.approx(0.1)
    assert scopes["gather"]["ms"] == pytest.approx(0.04)
    assert scopes["other"]["ms"] == pytest.approx(0.007)


def test_trace_attrib_excludes_device_summary_rows(tmp_path):
    # TPU xprof device pids carry an "XLA Modules" row whose one span
    # covers the same wall time as every op on the "XLA Ops" row —
    # counting both would double total_us and halve every percentage
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    (run_dir / "m.jsonl").write_text(json.dumps(
        {"kind": "compile", "program": "train_step", "sig": "a",
         "compile_time_s": 0.1, "flops": 1.0, "bytes_accessed": 1.0,
         "op_scopes": {"fusion.1": "grad"}}) + "\n")
    trace = tmp_path / "t.trace.json"
    trace.write_text(json.dumps({"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "thread_name", "pid": 7, "tid": 1,
         "args": {"name": "XLA Modules"}},
        {"ph": "M", "name": "thread_name", "pid": 7, "tid": 2,
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 0, "dur": 140.0,
         "name": "jit_train_step(1)"},
        {"ph": "X", "pid": 7, "tid": 2, "ts": 0, "dur": 100.0,
         "name": "fusion.1"},
    ]}))
    out = tmp_path / "a.json"
    r = run_tool([tool("trace_attrib.py"), str(trace),
                  "--run-dir", str(run_dir), "--json", str(out)])
    assert r.returncode == 0, r.stderr
    got = json.loads(out.read_text())
    assert got["total_ms"] == pytest.approx(0.1)  # the module span is out
    assert got["scopes"]["grad"]["pct"] == pytest.approx(100.0)


def test_trace_attrib_empty_trace_exits_1(tmp_path):
    empty = tmp_path / "empty.trace.json"
    empty.write_text(json.dumps({"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/host:CPU"}},
    ]}))
    r = run_tool([tool("trace_attrib.py"), str(empty)])
    assert r.returncode == 1
    assert "no device-op events" in r.stderr


def test_trace_attrib_missing_trace_exits_2(tmp_path):
    r = run_tool([tool("trace_attrib.py"), str(tmp_path)])
    assert r.returncode == 2


# ------------------------------------------------------------- perf_ledger


def _ledger_corpus(root):
    (root / "BENCH_r01.json").write_text(json.dumps({
        "n": 1, "cmd": "bench", "rc": 0,
        "parsed": {"metric": "lr_examples_per_sec", "value": 1000.0,
                   "unit": "examples/sec", "vs_baseline": 1.28,
                   "fm_examples_per_sec": 700.0, "fm_vs_baseline": 0.9},
    }))
    (root / "BENCH_r02.json").write_text(json.dumps({
        "metric": "lr_examples_per_sec", "value": 1200.0,
        "unit": "examples/sec", "vs_baseline": 1.54,
        "fm_examples_per_sec": 900.0,
        "bytes_per_example": 1500.0,
    }))
    (root / "BENCH_SCALE.json").write_text(json.dumps({
        "models": {"lr": {"examples_per_sec_e2e": 62534.0,
                          "test_auc": 0.674}},
    }))
    (root / "MULTICHIP_r01.json").write_text(json.dumps({
        "n_devices": 8, "ok": True, "skipped": False,
    }))
    (root / "BENCH_SERVE.json").write_text(json.dumps({
        "metric": "serve_qps", "value": 322.98, "unit": "requests/sec",
        "p50_ms": 10.9, "p99_ms": 27.7,
    }))


def test_perf_ledger_consolidates(tmp_path):
    _ledger_corpus(tmp_path)
    out = tmp_path / "ledger.json"
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path),
                  "--json", str(out)])
    assert r.returncode == 0, r.stderr
    md = r.stdout
    for section in ("Bench trajectory", "Multichip dryrun", "Scale run",
                    "Serving", "Roofline extrapolation"):
        assert section in md, f"missing section {section!r}:\n{md}"
    got = json.loads(out.read_text())
    series = {e["series"] for e in got["entries"]}
    assert series == {"bench", "multichip", "scale", "serve"}
    # both rounds of both bench metrics normalized
    lr = [e for e in got["entries"] if e["metric"] == "lr_examples_per_sec"]
    assert [e["round"] for e in lr] == [1, 2]
    roof = got["roofline"]
    assert roof["metric"] == "lr_examples_per_sec" and roof["round"] == 2
    assert roof["pct_of_pod_target"] == round(100.0 * 1200 * 64 / 50_000_000, 1)
    # the HBM conversion runs off the bytes_per_example stamp
    assert roof["target_pct_of_hbm_bw"] > 0


def test_perf_ledger_regress_gate(tmp_path):
    _ledger_corpus(tmp_path)
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path),
                  "--regress", "--markdown", ""])
    assert r.returncode == 0, r.stderr
    # a collapsed newest round trips the gate with exit 3
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({
        "metric": "lr_examples_per_sec", "value": 100.0,
        "unit": "examples/sec",
    }))
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path),
                  "--regress", "--markdown", ""])
    assert r.returncode == 3
    assert "REGRESSION" in r.stderr and "lr_examples_per_sec" in r.stderr
    # --metrics scopes the gate away from the regressed group
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path),
                  "--regress", "--markdown", "", "--metrics", "^fm_"])
    assert r.returncode == 0, r.stderr


def test_perf_ledger_multichip_flip_gates(tmp_path):
    _ledger_corpus(tmp_path)
    (tmp_path / "MULTICHIP_r02.json").write_text(json.dumps({
        "n_devices": 8, "ok": False, "skipped": False,
    }))
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path),
                  "--regress", "--markdown", ""])
    assert r.returncode == 3
    assert "multichip" in r.stderr
    # a SKIPPED round (no devices on this rig) never gates
    (tmp_path / "MULTICHIP_r02.json").write_text(json.dumps({
        "n_devices": 0, "ok": False, "skipped": True,
    }))
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path),
                  "--regress", "--markdown", ""])
    assert r.returncode == 0, r.stderr


def test_perf_ledger_folds_decompose_jsonl(tmp_path):
    # step_decompose --json writes JSONL (one record per slice): an
    # explicit file folds every line in as its own ledger entry
    _ledger_corpus(tmp_path)
    jsonl = tmp_path / "decomp.jsonl"
    jsonl.write_text(
        json.dumps({"metric": "decompose_lr_fwd_ms", "value": 0.3,
                    "unit": "ms/step", "model": "lr", "slice": "fwd"}) + "\n"
        + json.dumps({"metric": "decompose_lr_step_ms", "value": 1.1,
                      "unit": "ms/step", "model": "lr", "slice": "step",
                      "bytes_per_example": 1366.0}) + "\n")
    out = tmp_path / "ledger.json"
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path),
                  "--json", str(out), str(jsonl)])
    assert r.returncode == 0, r.stderr
    metrics = {e["metric"] for e in json.loads(out.read_text())["entries"]}
    assert {"decompose_lr_fwd_ms", "decompose_lr_step_ms"} <= metrics


def test_perf_ledger_ms_metrics_gate_downward(tmp_path):
    # latency-shaped *_ms metrics improve downward: a rising newest
    # round regresses, a falling one never trips the gate, and "best"
    # renders the LOWEST value
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "metric": "decompose_lr_step_ms", "value": 1.0, "unit": "ms/step"}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "metric": "decompose_lr_step_ms", "value": 5.0, "unit": "ms/step"}))
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path),
                  "--regress", "--markdown", ""])
    assert r.returncode == 3
    assert "decompose_lr_step_ms" in r.stderr
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "metric": "decompose_lr_step_ms", "value": 0.4, "unit": "ms/step"}))
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path),
                  "--regress", "--markdown", ""])
    assert r.returncode == 0, r.stderr
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path)])
    assert "0.4 (r2)" in r.stdout


def test_perf_ledger_empty_root_exits_2(tmp_path):
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path)])
    assert r.returncode == 2


# --------------------------------------- metrics_report compile gates


def _stamped(i, **kw):
    return {"ts": float(i), "rank": 0, "run_id": "r", "gen": 0, **kw}


def _compile_rec(i, program="train_step", sig="s1", **kw):
    return _stamped(i, kind="compile", program=program, sig=sig,
                    compile_time_s=0.5, flops=10.0, bytes_accessed=20.0, **kw)


def _write_jsonl(path, records):
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


def test_metrics_report_compile_gate_ok(tmp_path):
    _write_jsonl(tmp_path / "m.jsonl", [
        _compile_rec(1),
        _compile_rec(2, program="predict", sig="s2"),
    ])
    r = run_tool([tool("metrics_report.py"), str(tmp_path / "m.jsonl"),
                  "--check"])
    assert r.returncode == 0, r.stderr


def test_metrics_report_compile_gate_recompile(tmp_path):
    _write_jsonl(tmp_path / "m.jsonl", [
        _compile_rec(1),
        _compile_rec(2),  # same (program, sig): a recompile
    ])
    r = run_tool([tool("metrics_report.py"), str(tmp_path / "m.jsonl"),
                  "--check"])
    assert r.returncode == 2
    assert "compiled twice" in r.stderr


def test_metrics_report_compile_gate_schema(tmp_path):
    bad = _compile_rec(1)
    del bad["flops"]
    _write_jsonl(tmp_path / "m.jsonl", [bad])
    r = run_tool([tool("metrics_report.py"), str(tmp_path / "m.jsonl"),
                  "--check"])
    assert r.returncode == 2
    assert "compile keys" in r.stderr
    zero = _compile_rec(1)
    zero["compile_time_s"] = 0.0
    _write_jsonl(tmp_path / "m.jsonl", [zero])
    r = run_tool([tool("metrics_report.py"), str(tmp_path / "m.jsonl"),
                  "--check"])
    assert r.returncode == 2
    assert "non-positive compile_time_s" in r.stderr


def test_metrics_report_renders_compile_table(tmp_path):
    _write_jsonl(tmp_path / "m.jsonl", [_compile_rec(1)])
    r = run_tool([tool("metrics_report.py"), str(tmp_path / "m.jsonl")])
    assert r.returncode == 0, r.stderr
    assert "compiles (kind=compile):" in r.stdout
    assert "train_step" in r.stdout


# -------------------------------------------------------------- smoke gate


def test_smoke_perf_script(tmp_path):
    """The perf CI gate end to end (tools/smoke_perf.sh): instrumented
    run -> compile-record gates -> trace attribution -> BENCH_r09
    through the ledger -> regression-mode mechanics."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_perf.sh"),
         str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "smoke_perf: OK" in r.stdout
    # the datapoint stayed in the workdir (never the repo root from
    # a test run) and went through the ledger path
    assert (tmp_path / "BENCH_r09.json").exists()
    assert (tmp_path / "ledger.md").exists()
