import numpy as np
import pytest

from xflow_tpu.data.libffm import available_shards, iter_examples, parse_line, shard_path
from xflow_tpu.data.pipeline import examples_to_batches
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.hashing import fnv1a64, slot_of

LOG2 = 20


def test_parse_line_basic():
    ex = parse_line("1\t0:0:0.3651 2:1163:0.3651 17:2434:0.50000", LOG2)
    label, fields, slots = ex
    assert label == 1.0
    assert list(fields) == [0, 2, 17]
    assert slots[1] == slot_of(fnv1a64(b"1163"), LOG2)


def test_label_threshold_matches_reference():
    # load_data_from_disk.cc:131-134: y=1 iff atof(label) > 1e-7
    assert parse_line("0.5\t0:1:1.0", LOG2)[0] == 1.0
    assert parse_line("0\t0:1:1.0", LOG2)[0] == 0.0
    assert parse_line("-1\t0:1:1.0", LOG2)[0] == 0.0
    assert parse_line("0.0000000001\t0:1:1.0", LOG2)[0] == 0.0


def test_value_field_is_ignored():
    a = parse_line("1\t3:42:0.111", LOG2)
    b = parse_line("1\t3:42:99.9", LOG2)
    assert a[2][0] == b[2][0]


def test_feature_id_hashed_as_string():
    # "7" and "07" are distinct strings → distinct keys (reference hashes
    # the token string, not the parsed integer)
    a = parse_line("1\t0:7:1", LOG2)[2][0]
    b = parse_line("1\t0:07:1", LOG2)[2][0]
    assert a != b


def test_shard_path_convention():
    assert shard_path("/x/train", 0) == "/x/train-00000"
    assert shard_path("/x/train", 42) == "/x/train-00042"


def test_synth_roundtrip_and_batching(tmp_path):
    prefix = str(tmp_path / "synth")
    paths = generate_shards(prefix, num_shards=2, rows_per_shard=57, seed=3)
    assert paths == available_shards(prefix)
    examples = list(iter_examples(paths[0], LOG2))
    assert len(examples) == 57
    label, fields, slots = examples[0]
    assert fields.shape == slots.shape == (18,)
    batches = list(examples_to_batches(iter(examples), batch_size=16, max_nnz=32))
    assert len(batches) == 4  # 3 full + 1 padded partial
    assert batches[-1].num_rows == 57 - 48
    full = batches[0]
    assert full.slots.shape == (16, 32)
    assert full.mask[:, :18].all() and not full.mask[:, 18:].any()
    assert full.row_mask.all()


def test_drop_remainder():
    examples = [(1.0, np.array([0], np.int32), np.array([5], np.int32))] * 10
    batches = list(examples_to_batches(iter(examples), 4, 8, drop_remainder=True))
    assert len(batches) == 2


def test_synth_deterministic(tmp_path):
    p1 = generate_shards(str(tmp_path / "a"), 1, 20, seed=7)[0]
    p2 = generate_shards(str(tmp_path / "b"), 1, 20, seed=7)[0]
    assert open(p1).read() == open(p2).read()
