"""NumPy simulation of the reference's parameter-server training loop.

Reproduces the *semantics* of the reference's per-thread
Pull -> compute -> Push cycle against server-side FTRL state
(`/root/reference/src/model/lr/lr_worker.cc:145-177` +
`/root/reference/src/optimizer/ftrl.h:58-74,98-152`), in plain NumPy
with a deterministic (single-worker) schedule:

- per minibatch: collect per-occurrence keys, dedup (`lr_worker.cc:150-165`),
  pull w for unique keys (lazy server entries), compute the model forward,
  accumulate per-key gradients divided by the minibatch row count
  (`lr_worker.cc:116-118`), push; the server applies FTRL per key.
- v-table entries lazily init ~N(0,1)*1e-2 on first touch (`ftrl.h:113-120`).
- FM uses the reference's *coupled* second-order form and its hand-written
  gradients: the w-gradient is accumulated once per latent dim (so scaled
  by k, `fm_worker.cc:134-148`), v-gradient = loss*(v_sum - v_i)
  (`fm_worker.cc:140-142`).

This is the oracle for the async->sync semantic-shift gate
(BASELINE.md config 1): the framework's synchronous SPMD training must
reach the same AUC (within epsilon) as this faithful re-creation of the
reference's training loop.
"""

from __future__ import annotations

import numpy as np

ALPHA, BETA, L1, L2 = 5e-2, 1.0, 5e-5, 10.0  # ftrl.h:17-20


def _sigmoid_ref(x: float) -> float:
    # reference sigmoid with +-30 clamp (base.h:54-63)
    x = min(30.0, max(-30.0, x))
    return 1.0 / (1.0 + np.exp(-x))


class FTRLTable:
    """Server-side per-key FTRL state (ftrl.h): dict key -> (w, n, z)."""

    def __init__(self, dim: int = 0, rng: np.random.Generator | None = None,
                 init_scale: float = 1e-2):
        self.dim = dim  # 0 = scalar w-table; >0 = v-table rows
        self.rng = rng
        self.init_scale = init_scale
        self.store: dict[int, list[np.ndarray]] = {}

    def _entry(self, key: int):
        e = self.store.get(key)
        if e is None:
            if self.dim:
                # lazy random init on first touch (ftrl.h:113-120)
                w = self.rng.normal(0.0, 1.0, self.dim) * self.init_scale
            else:
                w = np.zeros(1)
            e = [w.astype(np.float64), np.zeros_like(w), np.zeros_like(w)]
            self.store[key] = e
        return e

    def pull(self, keys):
        return np.stack([self._entry(k)[0] for k in keys])

    def push(self, keys, grads):
        # ftrl.h:58-74 per element
        for k, g in zip(keys, grads):
            w, n, z = self._entry(k)
            g = np.atleast_1d(np.asarray(g, np.float64))
            n_new = n + g * g
            z += g - (np.sqrt(n_new) - np.sqrt(n)) / ALPHA * w
            n[:] = n_new
            w[:] = np.where(
                np.abs(z) <= L1,
                0.0,
                -(z - np.sign(z) * L1) / ((BETA + np.sqrt(n)) / ALPHA + L2),
            )


def sim_train_lr(batches, epochs: int) -> FTRLTable:
    """batches: list of (labels [B], rows: list of per-row key arrays)."""
    table = FTRLTable()
    for _ in range(epochs):
        for labels, rows in batches:
            B = len(labels)
            uniq = sorted({int(k) for r in rows for k in r})
            widx = {k: i for i, k in enumerate(uniq)}
            w = table.pull(uniq)[:, 0]
            g = np.zeros(len(uniq))
            for y, r in zip(labels, rows):
                wx = sum(w[widx[int(k)]] for k in r)
                loss = _sigmoid_ref(wx) - y
                for k in r:  # per occurrence (lr_worker.cc:106-115)
                    g[widx[int(k)]] += loss
            table.push(uniq, g / B)
    return table


def sim_predict_lr(table: FTRLTable, rows) -> np.ndarray:
    out = []
    for r in rows:
        uniq = sorted({int(k) for k in r})
        w = {k: table.pull([k])[0, 0] if k in table.store else 0.0 for k in uniq}
        # predict-time pull also lazily creates entries in the reference;
        # value is 0 for fresh w entries either way
        out.append(_sigmoid_ref(sum(w[int(k)] for k in r)))
    return np.asarray(out)


def sim_train_fm(batches, epochs: int, k: int = 10, seed: int = 0):
    """Reference-coupled FM (fm_worker.cc): scalar accumulator across
    (occurrence, latent) with hand-written gradients."""
    rng = np.random.default_rng(seed)
    wt = FTRLTable()
    vt = FTRLTable(dim=k, rng=rng)
    for _ in range(epochs):
        for labels, rows in batches:
            B = len(labels)
            uniq = sorted({int(key) for r in rows for key in r})
            idx = {key: i for i, key in enumerate(uniq)}
            w = wt.pull(uniq)[:, 0]
            v = vt.pull(uniq)  # [U, k]
            gw = np.zeros(len(uniq))
            gv = np.zeros((len(uniq), k))
            for y, r in zip(labels, rows):
                ids = [idx[int(key)] for key in r]
                wx = sum(w[i] for i in ids)
                vs = sum(v[i, kk] for i in ids for kk in range(k))  # coupled scalar
                vq = sum(v[i, kk] ** 2 for i in ids for kk in range(k))
                loss = _sigmoid_ref(wx + vs * vs - vq) - y
                for i in ids:
                    # w-grad accumulated once per latent dim (x k): the
                    # reference accident (fm_worker.cc:134-148)
                    gw[i] += loss * k
                    for kk in range(k):
                        gv[i, kk] += loss * (vs - v[i, kk])
            wt.push(uniq, gw / B)
            vt.push(uniq, gv / B)
    return wt, vt


def sim_predict_fm(wt: FTRLTable, vt: FTRLTable, rows, k: int = 10) -> np.ndarray:
    out = []
    for r in rows:
        keys = [int(key) for key in r]
        w = wt.pull(keys)[:, 0]
        v = vt.pull(keys)
        wx = float(w.sum())
        vs = float(v.sum())
        vq = float((v * v).sum())
        out.append(_sigmoid_ref(wx + vs * vs - vq))
    return np.asarray(out)
