"""Bucketed streaming eval vs the exact rank-sum path (verdict item 7)."""

import numpy as np

from xflow_tpu.config import Config, override
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.train.trainer import Trainer


def _cfg(tmp_path, **kw):
    return override(
        Config(),
        **{
            "data.train_path": str(tmp_path / "train"),
            "data.test_path": str(tmp_path / "test"),
            "data.log2_slots": 14,
            "data.batch_size": 256,
            "data.max_nnz": 8,
            "model.num_fields": 6,
            "train.epochs": 2,
            "train.pred_dump": False,
            **kw,
        },
    )


def test_bucketed_eval_matches_exact(tmp_path):
    generate_shards(str(tmp_path / "train"), 1, 2000, num_fields=6, ids_per_field=100, seed=0)
    generate_shards(
        str(tmp_path / "test"), 1, 3000, num_fields=6, ids_per_field=100, seed=5, truth_seed=0
    )
    t = Trainer(_cfg(tmp_path))
    t.fit()
    auc_exact, ll_exact = t.evaluate()

    t.cfg = _cfg(tmp_path, **{"train.eval_buckets": 65536})
    auc_b, ll_b = t.evaluate()
    assert abs(auc_b - auc_exact) < 1e-3, (auc_b, auc_exact)
    # coarser buckets: error grows with tie density but stays bounded
    t.cfg = _cfg(tmp_path, **{"train.eval_buckets": 8192})
    auc_c, _ = t.evaluate()
    assert abs(auc_c - auc_exact) < 5e-3, (auc_c, auc_exact)
    # logloss is exact in both paths (sum/count, no bucketing)
    assert abs(ll_b - ll_exact) < 1e-9, (ll_b, ll_exact)


def test_bucketed_eval_dumps_local_rows(tmp_path, monkeypatch):
    # eval_buckets + pred_dump: the bucketed path still writes the
    # reference-format per-rank pred file (path choice stays config-only
    # so collectives match across ranks)
    monkeypatch.chdir(tmp_path)
    generate_shards(str(tmp_path / "train"), 1, 300, num_fields=6, ids_per_field=50, seed=1)
    generate_shards(str(tmp_path / "test"), 1, 200, num_fields=6, ids_per_field=50, seed=2,
                    truth_seed=1)
    t = Trainer(_cfg(tmp_path, **{"train.eval_buckets": 4096, "train.pred_dump": True,
                                  "train.epochs": 1}))
    t.fit()
    auc, _ = t.evaluate()
    lines = (tmp_path / "pred_0_0.txt").read_text().splitlines()
    assert len(lines) == 200
    pctr, one_minus, label = lines[0].split("\t")
    assert 0.0 <= float(pctr) <= 1.0
    assert {one_minus, label} <= {"0", "1"} and int(one_minus) == 1 - int(label)


def test_sorted_layout_on_rejects_unsupported(tmp_path):
    import pytest

    (tmp_path / "train-00000").write_text("1\t0:1:1\n")
    for bad in ({"model.name": "lr"}, {"model.fm_fused": False}):
        cfg = _cfg(tmp_path, **{"data.sorted_layout": "on", "data.log2_slots": 12,
                                "model.name": "fm", **bad})
        with pytest.raises(ValueError, match="sorted_layout=on requires"):
            Trainer(cfg)


def test_bucketed_eval_single_class_nan(tmp_path):
    # all-positive labels: AUC undefined -> nan, like the exact path
    p = tmp_path / "test-00000"
    p.write_text("".join(f"1\t0:{i}:1\n" for i in range(50)))
    (tmp_path / "train-00000").write_text("1\t0:1:1\n0\t0:2:1\n")
    t = Trainer(_cfg(tmp_path, **{"train.eval_buckets": 1024, "train.epochs": 1}))
    t.fit()
    auc, ll = t.evaluate()
    assert np.isnan(auc)
    assert np.isfinite(ll)


def test_resolve_eval_buckets_auto():
    """-1 = auto: exact single-process, bucketed multi-process so the
    default pod-scale config has no per-batch eval collectives."""
    from xflow_tpu.train.trainer import resolve_eval_buckets

    assert resolve_eval_buckets(-1, multiproc=False) == 0
    assert resolve_eval_buckets(-1, multiproc=True) == 65536
    assert resolve_eval_buckets(0, multiproc=True) == 0  # explicit exact wins
    assert resolve_eval_buckets(1024, multiproc=False) == 1024
