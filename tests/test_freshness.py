"""Freshness observability: the stream -> train -> publish -> serve
loop (docs/SERVING.md "Freshness", docs/DATA.md "Streaming source").

Covers the tail follower's sealing discipline (deferred truncated
tails, rotation, convert-on-arrival), the publication sidecar
round-trip, the serve-side closure (Generation.freshness_s with fake
clocks, the data_freshness_s window key), the metrics_report
ingest/publish/freshness schema gates, the freshness_report Δ
assembly + gate, the perf-ledger `fresh` series direction, the
zero-overhead-when-off pin (data.stream=off / publish_every=0 leaves
every stream and checkpoint byte-identical to a pre-freshness build),
and — slow-marked — the live end-to-end drill (tools/smoke_fresh.sh)."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.data.pipeline import TailFollower, stream_dir_for
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.metrics import BucketAUC
from xflow_tpu.train import checkpoint as ckpt
from xflow_tpu.train.trainer import Trainer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LINE = "1 0:3:1.0 1:7:1.0 2:9:1.0 3:2:1.0 4:5:1.0 5:8:1.0\n"
LINE0 = "0 0:4:1.0 1:6:1.0 2:1:1.0 3:3:1.0 4:2:1.0 5:9:1.0\n"


class _App:
    """Duck-typed appender capturing what the follower stamps."""

    def __init__(self):
        self.recs = []

    def append(self, rec):
        self.recs.append(dict(rec))


def _data_cfg(tmp_path, **kw):
    base = {
        "data.cache": "off",
        "data.stream": "tail",
        "data.stream_poll_s": 0.01,
        "data.stream_idle_s": 0.2,
        "data.stream_dir": str(tmp_path / "spool"),
        "model.num_fields": 6,
        "data.max_nnz": 8,
    }
    base.update(kw)
    return override(Config(), **base).data


# ------------------------------------------------------------ tail follower


def test_tail_follower_defers_truncated_tail(tmp_path):
    src = tmp_path / "shard"
    src.write_text(LINE + LINE0[:-1])  # second row mid-append, no newline
    app = _App()
    f = TailFollower(str(src), _data_cfg(tmp_path), appender=app)
    segs = f.poll()
    # only the COMPLETED line seals; the torn tail is deferred (a
    # writer mid-append is normal), never quarantined
    assert len(segs) == 1 and segs[0].rows == 1
    assert segs[0].offset == 0 and segs[0].bytes == len(LINE)
    assert f.poll() == []  # still torn: nothing new
    with open(src, "a") as fh:
        fh.write("\n")  # the writer finishes the row
    segs2 = f.poll()
    assert len(segs2) == 1 and segs2[0].rows == 1
    assert segs2[0].offset == len(LINE)
    # segments are immutable spool files stamped with distinct traces
    assert segs[0].trace != segs2[0].trace
    assert segs2[0].seq == segs[0].seq + 1
    assert open(segs2[0].path).read() == LINE0
    # and each seal landed a kind="ingest" record with the full key set
    assert [r["kind"] for r in app.recs] == ["ingest", "ingest"]
    for r in app.recs:
        for key in ("trace", "seq", "source", "offset", "rows", "bytes",
                    "cache", "ingest_ts"):
            assert key in r


def test_tail_follower_rotation_restarts_from_top(tmp_path):
    src = tmp_path / "shard"
    src.write_text(LINE * 3)
    f = TailFollower(str(src), _data_cfg(tmp_path))
    assert f.poll()[0].rows == 3
    src.write_text(LINE0)  # rotated/recreated: SMALLER than the offset
    segs = f.poll()
    assert len(segs) == 1 and segs[0].offset == 0
    assert open(segs[0].path).read() == LINE0


def test_tail_follower_idle_timeout_bounds_the_stream(tmp_path):
    src = tmp_path / "shard"
    src.write_text(LINE)
    f = TailFollower(str(src), _data_cfg(tmp_path))
    t0 = time.monotonic()
    segs = list(f.segments())  # must END via stream_idle_s, not hang
    assert len(segs) == 1
    assert time.monotonic() - t0 < 10.0


def test_tail_follower_convert_on_arrival(tmp_path):
    src = tmp_path / "shard"
    src.write_text(LINE + LINE0)
    cfg = _data_cfg(tmp_path, **{"data.cache": "on",
                                 "data.cache_dir": str(tmp_path / "cc")})
    f = TailFollower(str(src), cfg)
    seg = f.poll()[0]
    # the sealed segment rides the packed device-rate path: its .xfc
    # sidecar exists and is stamped into the segment (and the record)
    assert seg.cache and os.path.exists(seg.cache)


def test_stream_dir_default_is_next_to_the_shards(tmp_path):
    cfg = _data_cfg(tmp_path, **{"data.stream_dir": ""})
    d = stream_dir_for(str(tmp_path / "sub" / "train"), cfg)
    assert d == str(tmp_path / "sub" / ".xfstream")


# -------------------------------------------------- publication round-trip


def test_publication_sidecar_roundtrip(tmp_path):
    pub = {"step": 10, "seq": 1, "trace": "ab" * 8, "span": "cd" * 8,
           "ingest_ts": 100.0, "consumed_ts": 101.0, "published_ts": 103.0}
    step_dir = tmp_path / "step_10"
    step_dir.mkdir()
    (step_dir / "publication.json").write_text(json.dumps(pub))
    assert ckpt.read_publication(str(tmp_path), 10) == pub
    # absence is the NORMAL case: silent None
    assert ckpt.read_publication(str(tmp_path), 20) is None
    # a damaged sidecar downgrades (logged) instead of gating the reload
    (step_dir / "publication.json").write_text("{torn")
    assert ckpt.read_publication(str(tmp_path), 10) is None


# ------------------------------------------------------- serve-side closure


def test_generation_freshness_with_fake_clock():
    from xflow_tpu.serve.runner import Generation

    gen = Generation(tables={}, step=10, gen=1,
                     publication={"ingest_ts": 100.0})
    assert gen.freshness_s(now=105.5) == pytest.approx(5.5)
    assert gen.freshness_s(now=99.0) == 0.0  # clock skew clamps, never <0
    # no publication (or a malformed one) = NOT MEASURABLE, never fake 0
    assert Generation(tables={}, step=1, gen=0).freshness_s(now=1.0) is None
    bad = Generation(tables={}, step=1, gen=0,
                     publication={"ingest_ts": float("nan")})
    assert bad.freshness_s(now=1.0) is None


def test_serve_window_freshness_key_optional(tmp_path):
    from xflow_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics(str(tmp_path / "s.jsonl"), every_s=0.05, batch_size=4)
    m.observe_batch(2, 4, [0.001], 0.002, [0.003])
    rec = m.maybe_flush(1, 10, force=True, freshness_s=2.5)
    assert rec["data_freshness_s"] == 2.5
    m.observe_batch(2, 4, [0.001], 0.002, [0.003])
    rec2 = m.maybe_flush(1, 10, force=True, freshness_s=None)
    # None (unpublished generation) leaves the record byte-identical to
    # a pre-freshness build — absent, not 0
    assert "data_freshness_s" not in rec2
    m.observe_batch(2, 4, [0.001], 0.002, [0.003])
    rec3 = m.maybe_flush(1, 10, force=True, freshness_s=-0.2)
    assert rec3["data_freshness_s"] == 0.0  # clock skew clamps
    m.close()


# -------------------------------------------------------- report gates


def _tools():
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import freshness_report
    import metrics_report
    import perf_ledger

    return metrics_report, freshness_report, perf_ledger


def _stamp(kind, ts=1.0, **kw):
    rec = {"ts": ts, "rank": 0, "run_id": "r1", "kind": kind}
    rec.update(kw)
    return rec


def _ingest(seq, ts=1.0, **kw):
    rec = _stamp("ingest", ts=ts, trace=f"t{seq:015d}", seq=seq,
                 source="s-00000", offset=0, rows=4, bytes=100, cache="",
                 ingest_ts=ts)
    rec.update(kw)
    return rec


def _publish(seq, step, ts=2.0, **kw):
    rec = _stamp("publish", ts=ts, step=step, seq=seq,
                 trace=f"t{seq:015d}", ingest_ts=ts - 1.0, published_ts=ts)
    rec.update(kw)
    return rec


def _write(tmp_path, name, recs):
    p = tmp_path / name
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(p)


def test_check_gates_ingest_stream(tmp_path):
    mr, _, _ = _tools()
    ok = _write(tmp_path, "ok.jsonl", [_ingest(0), _ingest(1, ts=2.0)])
    assert mr.main([ok, "--check"]) == 0
    partial = _ingest(0)
    del partial["offset"]
    assert mr.main([_write(tmp_path, "p.jsonl", [partial]), "--check"]) == 2
    # the follower's segment numbering only moves forward
    backwards = [_ingest(1), _ingest(1, ts=2.0)]
    assert mr.main([_write(tmp_path, "b.jsonl", backwards), "--check"]) == 2
    assert mr.main(
        [_write(tmp_path, "n.jsonl", [_ingest(0, rows=-1)]), "--check"]
    ) == 2


def test_check_gates_publish_stream(tmp_path):
    mr, _, _ = _tools()
    ok = _write(tmp_path, "ok.jsonl",
                [_publish(1, 10), _publish(2, 20, ts=3.0)])
    assert mr.main([ok, "--check"]) == 0
    # a publication cannot predate the data it trained on
    early = _publish(1, 10)
    early["published_ts"] = early["ingest_ts"] - 5.0
    assert mr.main([_write(tmp_path, "e.jsonl", [early]), "--check"]) == 2
    # publish seq repeats = two publishers in one stream
    rep = [_publish(1, 10), _publish(1, 20, ts=3.0)]
    assert mr.main([_write(tmp_path, "r.jsonl", rep), "--check"]) == 2
    partial = _publish(1, 10)
    del partial["trace"]
    assert mr.main([_write(tmp_path, "t.jsonl", [partial]), "--check"]) == 2


def test_check_gates_serve_freshness_key(tmp_path):
    from xflow_tpu.serve.metrics import SERVE_WINDOW_KEYS

    mr, _, _ = _tools()

    def window(**kw):
        rec = _stamp("serve", **{k: 1 for k in SERVE_WINDOW_KEYS})
        rec.update(generation=1, step=4)
        rec.update(kw)
        return rec

    # with the key, without the key: both legal (doubly optional —
    # absence means "not measurable", the OPTIONAL_SERVE_KEYS contract)
    ok = _write(tmp_path, "ok.jsonl",
                [window(), window(ts=2.0, data_freshness_s=3.25)])
    assert mr.main([ok, "--check"]) == 0
    bad = _write(tmp_path, "bad.jsonl", [window(data_freshness_s=-1.0)])
    assert mr.main([bad, "--check"]) == 2


def test_health_names_the_stalest_replica(tmp_path, capsys):
    from xflow_tpu.serve.metrics import SERVE_WINDOW_KEYS

    mr, _, _ = _tools()

    def window(rank, fresh, ts):
        rec = _stamp("serve", ts=ts, **{k: 1 for k in SERVE_WINDOW_KEYS})
        rec.update(rank=rank, generation=1, step=4,
                   data_freshness_s=fresh)
        return rec

    path = _write(tmp_path, "fleet.jsonl", [
        _publish(1, 10),
        window(0, 2.5, ts=3.0),
        window(1, 9.75, ts=3.0),
    ])
    assert mr.main([path, "--health"]) == 0
    out = capsys.readouterr().out
    assert "freshness" in out
    assert "publications: 1" in out
    assert "stalest replica: rank 1" in out and "9.75" in out


# --------------------------------------------------- freshness_report Δ


def _loop_records(trace="ab" * 8):
    pub_span, reload_span = "p" * 16, "r" * 16
    return [
        _stamp("ingest", ts=100.0, trace=trace, seq=0, source="s-00000",
               offset=0, rows=4, bytes=100, cache="", ingest_ts=100.0),
        _stamp("publish", ts=103.0, step=10, seq=1, trace=trace,
               ingest_ts=100.0, published_ts=103.0),
        _stamp("span", ts=103.1, trace=trace, span=pub_span, name="publish",
               t0=103.0, dur_ms=50.0, step=10, seq=1),
        _stamp("span", ts=104.5, trace=trace, span=reload_span,
               parent=pub_span, name="reload", t0=104.0, dur_ms=500.0,
               step=10, generation=2),
        _stamp("span", ts=105.0, trace=trace, span="f" * 16,
               parent=reload_span, name="serve_first", t0=105.0, dur_ms=0.0,
               step=10, generation=2),
    ]


def test_freshness_report_assembles_and_decomposes(tmp_path, capsys):
    _, fr, _ = _tools()
    path = _write(tmp_path, "run.jsonl", _loop_records())
    out = tmp_path / "BENCH_FRESH.json"
    rc = fr.main([path, "--bench-json", str(out), "--round", "18",
                  "--max-delta-s", "10"])
    assert rc == 0
    rec = json.load(open(out))
    assert rec["metric"] == "fresh_delta_s" and rec["round"] == 18
    assert rec["value"] == pytest.approx(5.0)  # serve_first - ingest_ts
    assert rec["fresh_ingest_publish_s"] == pytest.approx(3.0)
    assert rec["fresh_publish_swap_s"] == pytest.approx(1.5)  # reload END
    assert rec["fresh_swap_serve_s"] == pytest.approx(0.5)
    assert rec["traces"] == 1 and rec["publications"] == 1
    assert "closed" in capsys.readouterr().out


def test_freshness_report_gates_open_loop_and_threshold(tmp_path):
    _, fr, _ = _tools()
    # no serve_first anywhere: the loop never closed — gate fails
    open_recs = _loop_records()[:-1]
    p1 = _write(tmp_path, "open.jsonl", open_recs)
    assert fr.main([p1, "--max-delta-s", "10"]) == 3
    assert fr.main([p1]) == 0  # report-only mode still prints
    # closed but too stale for the threshold
    p2 = _write(tmp_path, "slow.jsonl", _loop_records())
    assert fr.main([p2, "--max-delta-s", "1"]) == 3


def test_perf_ledger_fresh_series_gates_downward(tmp_path):
    _, _, pl = _tools()
    assert pl._lower_is_better("fresh_delta_s", "s")
    assert pl._lower_is_better("fresh_publish_swap_s", "s")
    rec = {"metric": "fresh_delta_s", "value": 2.0, "unit": "s",
           "round": 2, "fresh_ingest_publish_s": 1.5, "publications": 3}
    (tmp_path / "BENCH_FRESH.json").write_text(json.dumps(rec))
    entries = pl.collect(str(tmp_path), [])
    by_metric = {e["metric"]: e for e in entries}
    assert by_metric["fresh_delta_s"]["series"] == "fresh"
    assert by_metric["fresh_ingest_publish_s"]["value"] == 1.5
    # staleness REGRESSING upward across rounds exits the gate
    older = dict(rec, value=0.5, round=1)
    problems = pl.check_regressions(
        pl.normalize_fresh("BENCH_FRESH_r1.json", older)
        + pl.normalize_fresh("BENCH_FRESH_r2.json", rec),
        tol=0.2,
    )
    assert any("fresh_delta_s" in p for p in problems)


# ------------------------------------------------------ eval window decay


def test_bucket_auc_decay():
    auc = BucketAUC(pos=np.array([4.0, 0.0, 2.0]),
                    neg=np.array([1.0, 3.0, 0.0]))
    dec = auc.decay(0.5)
    assert np.allclose(dec.pos, [2.0, 0.0, 1.0])
    assert np.allclose(dec.neg, [0.5, 1.5, 0.0])
    # the un-decayed histograms are untouched (decay returns a copy)
    assert np.allclose(auc.pos, [4.0, 0.0, 2.0])


# ---------------------------------------------- zero-overhead-when-off pin


def test_stream_off_is_byte_identical(tmp_path, monkeypatch):
    """data.stream=off + publish_every=0 (the defaults): no ingest or
    publish record, no linked span, no publication sidecar — the exact
    pre-freshness streams and checkpoint layout (PR 9 discipline)."""
    monkeypatch.chdir(tmp_path)
    generate_shards(str(tmp_path / "train"), 1, 256, num_fields=6,
                    ids_per_field=40, seed=0, noise=0.3)
    cfg = override(Config(), **{
        "data.train_path": str(tmp_path / "train"),
        "data.log2_slots": 12,
        "data.batch_size": 64,
        "data.max_nnz": 8,
        "model.num_fields": 6,
        "train.epochs": 1,
        "train.pred_dump": False,
        "train.checkpoint_dir": str(tmp_path / "ck"),
        "train.metrics_path": str(tmp_path / "m.jsonl"),
    })
    t = Trainer(cfg)
    res = t.fit()
    assert res.steps == 4
    recs = [json.loads(line) for line in open(tmp_path / "m.jsonl")]
    kinds = {r.get("kind") for r in recs}
    assert "ingest" not in kinds and "publish" not in kinds
    names = {r.get("name") for r in recs if r.get("kind") == "span"}
    assert not names & {"publish", "serve_first", "reload", "serve_load"}
    step = ckpt.latest_step(str(tmp_path / "ck"))
    assert step is not None
    assert ckpt.read_publication(str(tmp_path / "ck"), step) is None
    assert not list((tmp_path / "ck").rglob("publication.json"))


def test_fit_rejects_unknown_stream_mode(tmp_path):
    generate_shards(str(tmp_path / "train"), 1, 64, num_fields=6,
                    ids_per_field=40, seed=0, noise=0.3)
    cfg = override(Config(), **{
        "data.train_path": str(tmp_path / "train"),
        "data.stream": "firehose",
        "data.log2_slots": 12,
        "data.batch_size": 64,
        "data.max_nnz": 8,
        "model.num_fields": 6,
    })
    with pytest.raises(ValueError, match="data.stream"):
        Trainer(cfg).fit()


# ----------------------------------------------------- streaming mini-run


def test_fit_tail_publishes_with_sidecars(tmp_path, monkeypatch):
    """A bounded tail run over a pre-seeded shard: segments seal, the
    publish cadence commits checkpoints WITH publication sidecars, and
    the metrics stream carries the full breadcrumb trail (ingest +
    publish records, publish spans) — check-green."""
    monkeypatch.chdir(tmp_path)
    generate_shards(str(tmp_path / "stream"), 1, 256, num_fields=6,
                    ids_per_field=40, seed=0, noise=0.3)
    cfg = override(Config(), **{
        "data.train_path": str(tmp_path / "stream"),
        "data.log2_slots": 12,
        "data.batch_size": 64,
        "data.max_nnz": 8,
        "data.stream": "tail",
        "data.stream_poll_s": 0.02,
        "data.stream_idle_s": 0.5,
        "data.stream_dir": str(tmp_path / "spool"),
        "data.cache": "off",
        "model.num_fields": 6,
        "train.publish_every": 2,
        "train.pred_dump": False,
        "train.checkpoint_dir": str(tmp_path / "ck"),
        "train.metrics_path": str(tmp_path / "m.jsonl"),
    })
    res = Trainer(cfg).fit()
    assert res.steps == 4  # 256 rows / 64
    recs = [json.loads(line) for line in open(tmp_path / "m.jsonl")]
    ingests = [r for r in recs if r.get("kind") == "ingest"]
    pubs = [r for r in recs if r.get("kind") == "publish"]
    assert len(ingests) >= 1 and len(pubs) >= 1
    spans = [r for r in recs if r.get("kind") == "span"
             and r.get("name") == "publish"]
    # every publish record has its linked span, carrying the SAME
    # ingest trace id the segment sealed with
    assert {s["trace"] for s in spans} == {p["trace"] for p in pubs}
    assert {p["trace"] for p in pubs} <= {i["trace"] for i in ingests}
    # the newest committed step carries a complete publication sidecar
    step = ckpt.latest_step(str(tmp_path / "ck"))
    pub = ckpt.read_publication(str(tmp_path / "ck"), step)
    assert pub is not None and pub["step"] == step
    assert pub["published_ts"] >= pub["consumed_ts"] >= pub["ingest_ts"] > 0
    mr, _, _ = _tools()
    assert mr.main([str(tmp_path / "m.jsonl"), "--check"]) == 0


# ----------------------------------------------------------- CI live drill


@pytest.mark.slow
def test_smoke_fresh_script(tmp_path):
    """The live freshness drill end to end (tools/smoke_fresh.sh):
    tail-mode trainer following a growing shard -> in-run publications
    -> 2-replica fleet hot-swapping them under closed-loop load with
    rows appended mid-bench -> zero failed requests, fleet freshness
    surfaced on /healthz, freshness_report Δ gate + BENCH_FRESH.json,
    metrics_report --check green. Slow-marked like the other live
    drills: the stream's idle timeout alone is 25s of wall."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_fresh.sh"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=570, env=env,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "smoke_fresh: OK" in r.stdout
    assert "fleet freshness OK" in r.stdout
    bench = json.load(open(tmp_path / "BENCH_FRESH.json"))
    assert bench["metric"] == "fresh_delta_s" and bench["value"] > 0
    assert bench["traces"] >= 1
