"""Zipf-skew synthetic generator: heavy-tailed feature frequencies."""

import numpy as np

from xflow_tpu.data.libffm import read_examples
from xflow_tpu.data.synth import generate_shards


def test_zipf_mode_is_skewed_and_learnable(tmp_path):
    nf, ids = 6, 500
    upath = generate_shards(str(tmp_path / "u"), 1, 2000, num_fields=nf, ids_per_field=ids)[0]
    zpath = generate_shards(
        str(tmp_path / "z"), 1, 2000, num_fields=nf, ids_per_field=ids, zipf_alpha=1.1
    )[0]

    def dup_fraction(path):
        """Fraction of feature occurrences that repeat an earlier slot
        within a 256-row batch window — the dedup-win proxy."""
        ex = read_examples(path, 20)
        dups = total = 0
        for start in range(0, len(ex), 256):
            seen = set()
            for _, _, slots in ex[start : start + 256]:
                for s in slots.tolist():
                    total += 1
                    if s in seen:
                        dups += 1
                    seen.add(s)
        return dups / total

    fu, fz = dup_fraction(upath), dup_fraction(zpath)
    # uniform 500-id fields already repeat within 256 rows; zipf must be
    # decisively more repetitive (hot head features dominate)
    assert fz > fu + 0.1, (fu, fz)

    # labels still follow the planted concept on the skewed draw
    labels = [ex[0] for ex in read_examples(zpath, 20)]
    assert 0.15 < np.mean(labels) < 0.85


def test_zipf_deterministic(tmp_path):
    a = generate_shards(str(tmp_path / "a"), 1, 50, num_fields=3, ids_per_field=40,
                        zipf_alpha=1.2, seed=5)[0]
    b = generate_shards(str(tmp_path / "b"), 1, 50, num_fields=3, ids_per_field=40,
                        zipf_alpha=1.2, seed=5)[0]
    assert open(a).read() == open(b).read()
