"""Zipf-skew synthetic generator: heavy-tailed feature frequencies."""

import numpy as np

from xflow_tpu.data.libffm import read_examples
from xflow_tpu.data.synth import generate_shards


def test_zipf_mode_is_skewed_and_learnable(tmp_path):
    nf, ids = 6, 500
    upath = generate_shards(str(tmp_path / "u"), 1, 2000, num_fields=nf, ids_per_field=ids)[0]
    zpath = generate_shards(
        str(tmp_path / "z"), 1, 2000, num_fields=nf, ids_per_field=ids, zipf_alpha=1.1
    )[0]

    def dup_fraction(path):
        """Fraction of feature occurrences that repeat an earlier slot
        within a 256-row batch window — the dedup-win proxy."""
        ex = read_examples(path, 20)
        dups = total = 0
        for start in range(0, len(ex), 256):
            seen = set()
            for _, _, slots in ex[start : start + 256]:
                for s in slots.tolist():
                    total += 1
                    if s in seen:
                        dups += 1
                    seen.add(s)
        return dups / total

    fu, fz = dup_fraction(upath), dup_fraction(zpath)
    # uniform 500-id fields already repeat within 256 rows; zipf must be
    # decisively more repetitive (hot head features dominate)
    assert fz > fu + 0.1, (fu, fz)

    # labels still follow the planted concept on the skewed draw
    labels = [ex[0] for ex in read_examples(zpath, 20)]
    assert 0.15 < np.mean(labels) < 0.85


def test_zipf_deterministic(tmp_path):
    a = generate_shards(str(tmp_path / "a"), 1, 50, num_fields=3, ids_per_field=40,
                        zipf_alpha=1.2, seed=5)[0]
    b = generate_shards(str(tmp_path / "b"), 1, 50, num_fields=3, ids_per_field=40,
                        zipf_alpha=1.2, seed=5)[0]
    assert open(a).read() == open(b).read()


def test_bulk_writer_format_and_seen(tmp_path):
    """generate_shards_bulk emits parser-identical libffm lines and its
    `seen` map marks exactly the emitted feature ids."""
    from xflow_tpu.config import DataConfig
    from xflow_tpu.data.pipeline import batch_iterator
    from xflow_tpu.data.synth import generate_shards_bulk

    prefix = str(tmp_path / "bulk")
    paths, seen = generate_shards_bulk(
        prefix, 1, 500, num_fields=6, ids_per_field=40, seed=3,
        zipf_alpha=1.1, chunk_rows=128, track_seen=True,
    )
    lines = open(paths[0]).read().splitlines()
    assert len(lines) == 500
    import re

    pat = re.compile(r"^[01]\t(\d+:\d+:0\.\d{4})( \d+:\d+:0\.\d{4}){5}$")
    assert all(pat.match(ln) for ln in lines[:50])
    # parser agreement: every row parses to 6 in-range features
    cfg = DataConfig(max_nnz=8, batch_size=64, log2_slots=16)
    gids = set()
    labels = []
    for batch in batch_iterator(paths[0], cfg):
        rm = batch.row_mask > 0
        labels.extend(batch.labels[rm].tolist())
        assert (batch.mask.sum(axis=1)[rm] == 6).all()
        for row_f, row_m in zip(batch.fields[rm], batch.mask[rm]):
            assert set(row_f[row_m > 0].tolist()) == set(range(6))
    assert 0.1 < np.mean(labels) < 0.9  # planted truth gives both classes
    # seen map: re-read the raw ids from the text and compare exactly
    for ln in lines:
        for tok in ln.split("\t")[1].split(" "):
            gids.add(int(tok.split(":")[1]))
    assert set(np.flatnonzero(seen).tolist()) == gids
