"""C-ABI embedding test: compile a real C client, link the shim, and
drive the full lifecycle — train -> checkpoint -> XFLoadCheckpoint ->
XFPredict.

The reference's C API (C14) is disabled in its build and cannot compile
as shipped; this verifies ours actually embeds, trains, and serves
predictions from the committed checkpoint end-to-end (the serving
surface the reference never finished).
"""

import os
import shutil
import subprocess
import sys
import sysconfig

import pytest

from xflow_tpu.data.synth import generate_shards

pytestmark = pytest.mark.skipif(shutil.which("gcc") is None, reason="no gcc")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPI = os.path.join(REPO, "xflow_tpu", "c_api")

CLIENT = r"""
#include <stdio.h>
#include "xflow_c_api.h"

/* argv: train_prefix test_prefix checkpoint_dir
 * Full lifecycle: train -> checkpoint -> load -> predict. */
int main(int argc, char** argv) {
  void* h = 0;
  if (XFCreate(&h, argv[1], argv[2]) != 0) return 2;
  if (XFSetConfig(h, "train.epochs", "4") != 0) return 3;
  if (XFSetConfig(h, "data.batch_size", "64") != 0) return 3;
  if (XFSetConfig(h, "data.log2_slots", "12") != 0) return 3;
  if (XFSetConfig(h, "model.num_fields", "5") != 0) return 3;
  if (XFSetConfig(h, "train.pred_dump", "false") != 0) return 3;
  if (XFSetConfig(h, "train.checkpoint_dir", argv[3]) != 0) return 3;
  if (XFStartTrain(h) != 0) return 4;
  double auc = XFGetAUC(h);
  printf("AUC=%.4f\n", auc);
  if (auc <= 0.7) return 5;

  /* predicting before a load must fail cleanly, not crash */
  double pre[1];
  if (XFPredict(h, "0:a 1:b", pre, 1) != -1) return 6;

  if (XFLoadCheckpoint(h, argv[3]) != 0) return 7;
  double p[4];
  int n = XFPredict(h, "0:f0x 1:f1y 2:f2z\n1\t0:q 3:r\n4:s", p, 4);
  if (n != 3) { printf("XFPredict wrote %d rows, want 3\n", n); return 8; }
  for (int i = 0; i < 3; ++i) {
    printf("PCTR=%.6f\n", p[i]);
    if (!(p[i] > 0.0 && p[i] < 1.0)) return 9;
  }
  /* a malformed row errors (the quarantine philosophy), never crashes */
  if (XFPredict(h, "no-colon-tokens", p, 4) != -1) return 10;
  XFDestroy(h);
  return 0;
}
"""


def _python_flags():
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var("VERSION")
    return inc, libdir, f"python{ver}"


def test_c_client_trains(tmp_path):
    generate_shards(str(tmp_path / "train"), 1, 800, num_fields=5, ids_per_field=30, seed=0, noise=0.3)
    inc, libdir, pylib = _python_flags()
    src = tmp_path / "client.c"
    src.write_text(CLIENT)
    exe = tmp_path / "client"
    cmd = [
        "gcc", str(src), os.path.join(CAPI, "xflow_c_api.c"),
        f"-I{CAPI}", f"-I{inc}", f"-L{libdir}", f"-l{pylib}",
        f"-Wl,-rpath,{libdir}", "-o", str(exe),
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # evaluate on the train shard: the gate is that embedding works
    r = subprocess.run(
        [str(exe), str(tmp_path / "train"), str(tmp_path / "train"),
         str(tmp_path / "ckpt")],
        capture_output=True,
        text=True,
        env=env,
        cwd=tmp_path,
        timeout=600,
    )
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    assert r.stdout.startswith("AUC=")
    # the serving half: three predictions from the loaded checkpoint
    assert r.stdout.count("PCTR=") == 3, r.stdout
