import json
import os
import subprocess
import sys

import pytest

from xflow_tpu.data.synth import generate_shards

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(args, cwd):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-m", "xflow_tpu", *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        env=env,
        timeout=600,
    )


def test_gen_data_and_train_cli(tmp_path):
    r = run_cli(["gen-data", str(tmp_path / "train"), "--shards", "1", "--rows", "400",
                 "--fields", "5", "--ids-per-field", "30"], tmp_path)
    assert r.returncode == 0, r.stderr
    generate_shards(str(tmp_path / "test"), 1, 150, num_fields=5, ids_per_field=30, seed=9, truth_seed=0)
    r = run_cli(
        [
            "train",
            "--train", str(tmp_path / "train"),
            "--test", str(tmp_path / "test"),
            "--model", "lr",
            "--epochs", "4",
            "--batch-size", "64",
            "--log2-slots", "12",
            "--no-mesh",
            "--set", "model.num_fields=5",
        ],
        tmp_path,
    )
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["epochs"] == 4
    assert summary["auc"] > 0.75
    assert (tmp_path / "pred_0_0.txt").exists()


def test_reference_model_index_accepted(tmp_path):
    generate_shards(str(tmp_path / "train"), 1, 100, num_fields=4, ids_per_field=20)
    r = run_cli(
        ["train", "--train", str(tmp_path / "train"), "--model", "0", "--epochs", "1",
         "--batch-size", "32", "--log2-slots", "10", "--no-mesh",
         "--set", "model.num_fields=4"],
        tmp_path,
    )
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout.strip().splitlines()[-1])["steps"] == 4


def test_sigterm_checkpoints_and_resumes(tmp_path):
    """Preemption (SURVEY.md §5 A3): SIGTERM mid-train saves a checkpoint
    at the next step boundary, reports `interrupted`, and a rerun resumes
    from it. The reference loses all weights on any termination."""
    import signal
    import time

    generate_shards(str(tmp_path / "train"), 1, 2000, num_fields=5, ids_per_field=40, seed=3)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    args = [
        sys.executable, "-m", "xflow_tpu", "train",
        "--train", str(tmp_path / "train"),
        "--model", "lr",
        "--epochs", "100000",  # would run ~forever without the signal
        "--batch-size", "50",
        "--log2-slots", "12",
        "--no-mesh",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--set", "model.num_fields=5",
        "--set", "train.pred_dump=false",
    ]
    metrics = tmp_path / "metrics.jsonl"
    args += ["--set", f"train.metrics_path={metrics}", "--set", "train.log_every=1"]
    p = subprocess.Popen(args, cwd=tmp_path, env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True)
    # wait until training has demonstrably taken steps (per-step metrics)
    deadline = time.time() + 300
    while time.time() < deadline:
        if metrics.exists() and metrics.stat().st_size > 0:
            break
        assert p.poll() is None, (p.stdout.read(), p.stderr.read())
        time.sleep(0.2)
    assert metrics.exists() and metrics.stat().st_size > 0, "training never started"
    p.send_signal(signal.SIGTERM)
    out, err = p.communicate(timeout=120)
    assert p.returncode == 0, (out, err)
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["interrupted"] == int(signal.SIGTERM)
    assert summary["steps"] > 0
    assert "checkpointing at step" in err
    steps = sorted((tmp_path / "ckpt").glob("step_*"))
    assert steps, "no checkpoint written on signal"

    # rerun resumes from the signal checkpoint
    r = run_cli(
        ["train", "--train", str(tmp_path / "train"), "--model", "lr",
         "--epochs", "1", "--batch-size", "50", "--log2-slots", "12", "--no-mesh",
         "--checkpoint-dir", str(tmp_path / "ckpt"),
         "--set", "model.num_fields=5", "--set", "train.pred_dump=false"],
        tmp_path,
    )
    assert r.returncode == 0, r.stderr
    assert "resumed from step" in r.stderr
