import json
import os
import subprocess
import sys

import pytest

from xflow_tpu.data.synth import generate_shards

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(args, cwd):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-m", "xflow_tpu", *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        env=env,
        timeout=600,
    )


def test_gen_data_and_train_cli(tmp_path):
    r = run_cli(["gen-data", str(tmp_path / "train"), "--shards", "1", "--rows", "400",
                 "--fields", "5", "--ids-per-field", "30"], tmp_path)
    assert r.returncode == 0, r.stderr
    generate_shards(str(tmp_path / "test"), 1, 150, num_fields=5, ids_per_field=30, seed=9, truth_seed=0)
    r = run_cli(
        [
            "train",
            "--train", str(tmp_path / "train"),
            "--test", str(tmp_path / "test"),
            "--model", "lr",
            "--epochs", "4",
            "--batch-size", "64",
            "--log2-slots", "12",
            "--no-mesh",
            "--set", "model.num_fields=5",
        ],
        tmp_path,
    )
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["epochs"] == 4
    assert summary["auc"] > 0.75
    assert (tmp_path / "pred_0_0.txt").exists()


def test_reference_model_index_accepted(tmp_path):
    generate_shards(str(tmp_path / "train"), 1, 100, num_fields=4, ids_per_field=20)
    r = run_cli(
        ["train", "--train", str(tmp_path / "train"), "--model", "0", "--epochs", "1",
         "--batch-size", "32", "--log2-slots", "10", "--no-mesh",
         "--set", "model.num_fields=4"],
        tmp_path,
    )
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout.strip().splitlines()[-1])["steps"] == 4
