"""Model-health observability tests (train.health_metrics,
telemetry.HealthMonitor/HangWatchdog, launch/watchdog.py,
metrics_report --health/--regress): norm/EMA math against NumPy
oracles, single-device vs GSPMD parity of the fused health scalars,
streaming-AUC-vs-exact-eval parity, occupancy/collision gauges,
heartbeat classification, and the launch-local straggler drill.
"""

import io
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.jsonl import JsonlAppender, read_jsonl
from xflow_tpu.telemetry import (
    HangWatchdog,
    HealthMonitor,
    Registry,
    default_registry,
    estimate_collision_rate,
)
from xflow_tpu.train.trainer import Trainer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- norm oracles


def _hcfg(mode="norms", model="lr", **kw):
    base = {
        "train.health_metrics": mode,
        "model.name": model,
        "data.log2_slots": 12,
        "model.num_fields": 6,
    }
    base.update(kw)
    return override(Config(), **base)


def test_health_norms_numpy_oracle():
    """health_norms == the NumPy norms of grads / (new-old) / new."""
    import jax.numpy as jnp

    from xflow_tpu.train.step import health_norms

    rng = np.random.default_rng(0)
    old = {"w": rng.normal(size=(32,)).astype(np.float32),
           "v": rng.normal(size=(16, 4)).astype(np.float32)}
    new = {k: v + rng.normal(size=v.shape).astype(np.float32) * 0.01
           for k, v in old.items()}
    grads = {k: rng.normal(size=v.shape).astype(np.float32) for k, v in old.items()}
    cfg = _hcfg("norms")
    out = health_norms(
        cfg,
        {k: jnp.asarray(v) for k, v in old.items()},
        {k: jnp.asarray(v) for k, v in new.items()},
        grads={k: jnp.asarray(v) for k, v in grads.items()},
    )
    g_exp = np.sqrt(sum(float((g.astype(np.float64) ** 2).sum()) for g in grads.values()))
    u_exp = np.sqrt(sum(float(((new[k] - old[k]).astype(np.float64) ** 2).sum()) for k in old))
    p_exp = np.sqrt(sum(float((new[k].astype(np.float64) ** 2).sum()) for k in old))
    assert float(out["grad_norm"]) == pytest.approx(g_exp, rel=1e-5)
    assert float(out["update_norm"]) == pytest.approx(u_exp, rel=1e-5)
    assert float(out["param_norm"]) == pytest.approx(p_exp, rel=1e-5)
    assert "grad_norm.w" not in out  # norms mode: global only


def test_health_norms_full_mode_per_table():
    import jax.numpy as jnp

    from xflow_tpu.train.step import health_norms

    old = {"w": np.zeros((8,), np.float32)}
    new = {"w": np.full((8,), 3.0, np.float32)}
    grads = {"w": np.full((8,), 2.0, np.float32)}
    cfg = _hcfg("full")
    out = health_norms(
        cfg, {"w": jnp.asarray(old["w"])}, {"w": jnp.asarray(new["w"])},
        grads={"w": jnp.asarray(grads["w"])},
    )
    assert float(out["grad_norm.w"]) == pytest.approx(2.0 * np.sqrt(8), rel=1e-6)
    assert float(out["update_norm.w"]) == pytest.approx(3.0 * np.sqrt(8), rel=1e-6)
    assert float(out["param_norm.w"]) == float(out["param_norm"])


def test_health_mode_validation():
    from xflow_tpu.train.step import health_mode, metrics_keys

    with pytest.raises(ValueError):
        health_mode(_hcfg("bogus"))
    assert "grad_norm" not in metrics_keys(_hcfg("off"))
    keys = metrics_keys(_hcfg("full", model="lr"))
    assert "grad_norm" in keys and "grad_norm.w" in keys and "update_ok" in keys


def test_sharded_step_health_matches_single_device():
    """The GSPMD step's fused health scalars equal the single-device
    step's (replicated-reduction contract)."""
    import jax
    import jax.numpy as jnp

    from xflow_tpu.models import get_model
    from xflow_tpu.optim import get_optimizer
    from xflow_tpu.parallel.mesh import batch_sharding, make_mesh
    from xflow_tpu.parallel.train_step import make_sharded_train_step, shard_state
    from xflow_tpu.train.state import init_state
    from xflow_tpu.train.step import make_train_step

    cfg = _hcfg(
        "norms", model="lr",
        **{"mesh.data": 4, "mesh.table": 2, "data.batch_size": 64},
    )
    model, opt = get_model("lr"), get_optimizer("ftrl")
    rng = np.random.default_rng(3)
    batch = {
        "slots": rng.integers(0, 1 << 12, (64, 10)).astype(np.int32),
        "fields": rng.integers(0, 6, (64, 10)).astype(np.int32),
        "mask": (rng.random((64, 10)) < 0.8).astype(np.float32),
        "labels": (rng.random(64) < 0.4).astype(np.float32),
        "row_mask": np.ones((64,), np.float32),
    }
    state1 = init_state(model, opt, cfg)
    _, m1 = make_train_step(model, opt, cfg)(
        state1, {k: jnp.asarray(v) for k, v in batch.items()}
    )
    mesh = make_mesh(cfg)
    state2 = shard_state(init_state(model, opt, cfg), mesh)
    bsh = batch_sharding(mesh)
    placed = {k: jax.device_put(jnp.asarray(v), bsh[k]) for k, v in batch.items()}
    _, m2 = make_sharded_train_step(model, opt, cfg, mesh)(state2, placed)
    for key in ("grad_norm", "update_norm", "param_norm"):
        assert float(m2[key]) == pytest.approx(float(m1[key]), rel=2e-4), key


def test_sorted_mesh_engines_emit_identical_health():
    """The two mesh sorted engines (fullshard / replicated) fuse the
    SAME health scalars through their shard_map programs — norms agree
    with each other across layouts, and the guard flag still rides."""
    import jax

    from xflow_tpu.data.schema import SparseBatch
    from xflow_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the conftest 8-device CPU mesh")
    base = override(Config(), **{
        "data.log2_slots": 14,
        "data.batch_size": 64,
        "data.max_nnz": 8,
        "model.name": "fm",
        "model.num_fields": 5,
        "model.v_dim": 4,
        "mesh.data": 4,
        "mesh.table": 2,
        "data.sorted_layout": "on",
        "train.health_metrics": "norms",
    })
    mesh = make_mesh(base)
    rng = np.random.default_rng(0)
    B, F = 64, 8
    batch = SparseBatch(
        slots=rng.integers(0, 1 << 14, (B, F)).astype(np.int32),
        fields=rng.integers(0, 5, (B, F)).astype(np.int32),
        mask=(rng.random((B, F)) < 0.8).astype(np.float32),
        labels=(rng.random(B) < 0.4).astype(np.float32),
        row_mask=np.ones((B,), np.float32),
    )
    got = {}
    for engine in ("fullshard", "replicated"):
        cfg = override(base, **{"data.sorted_mesh": engine})
        t = Trainer(cfg, mesh=mesh)
        _, arrays = t._with_arrays(batch)
        arrays = t._shard_batch(arrays)
        t.state, m = t.train_step(t.state, arrays)
        assert "update_ok" in m  # guard flag still rides with health on
        got[engine] = {k: float(m[k]) for k in
                       ("grad_norm", "update_norm", "param_norm")}
        for v in got[engine].values():
            assert np.isfinite(v) and v > 0
    for key in got["fullshard"]:
        assert got["fullshard"][key] == pytest.approx(
            got["replicated"][key], rel=1e-4
        ), key


# --------------------------------------------------------------- EMA oracle


def test_health_monitor_ema_numpy_oracle():
    """staged/collect folds the EMA exactly like the NumPy recursion,
    one step behind, seeded by the first finite loss."""
    mon = HealthMonitor(mode="norms", ema_decay=0.9, registry=Registry())
    losses = [0.7, 0.6, float("nan"), 0.5, 0.4]
    ema = None
    for i, loss in enumerate(losses, 1):
        mon.staged({"loss": np.float32(loss), "grad_norm": np.float32(1.0),
                    "update_norm": np.float32(0.1), "param_norm": np.float32(2.0)})
        mon.collect()  # in the fit loop this collect belongs to step i+1
        if loss == loss:  # NaN (a guarded bad step) must not poison the EMA
            ema = loss if ema is None else 0.9 * ema + 0.1 * loss
        assert mon.loss_ema == pytest.approx(ema, rel=1e-6)
    rec = mon.window_record()
    assert rec["loss_ema"] == pytest.approx(ema, rel=1e-6)
    assert rec["grad_norm"] == pytest.approx(1.0)


def test_health_monitor_runs_one_behind():
    mon = HealthMonitor(mode="norms", registry=Registry())
    assert mon.window_record() == {}  # nothing collected yet
    mon.staged({"loss": np.float32(0.5)})
    assert mon.window_record() == {}  # step 1 staged but not collected
    mon.collect()
    assert mon.window_record()["loss_ema"] == pytest.approx(0.5)


def test_health_monitor_off_is_inert():
    mon = HealthMonitor(mode="off", registry=Registry(), num_slots=128)
    mon.staged({"loss": np.float32(0.5)})
    mon.collect()
    mon.observe_batch(np.zeros((2, 2), np.int32), np.ones((2, 2), np.float32))
    assert mon.window_record() == {}


# ----------------------------------------------------- occupancy / collisions


def test_estimate_collision_rate_bounds():
    assert estimate_collision_rate(0, 1 << 12) == 0.0
    assert estimate_collision_rate(1, 1 << 12) == pytest.approx(0.0, abs=1e-9)
    assert estimate_collision_rate(1 << 12, 1 << 12) == 1.0
    # sparse occupancy ⇒ near-zero estimate; heavy occupancy ⇒ substantial
    lo = estimate_collision_rate(10, 1 << 20)
    hi = estimate_collision_rate((1 << 12) - 10, 1 << 12)
    assert lo < 1e-4 < hi < 1.0
    # matches the closed form d = S(1-(1-1/S)^n) round-tripped
    S, n = 4096, 3000
    d = S * (1 - (1 - 1 / S) ** n)
    est = estimate_collision_rate(int(round(d)), S)
    assert est == pytest.approx(1 - d / n, abs=2e-3)


def test_occupancy_gauges():
    reg = Registry()
    mon = HealthMonitor(mode="norms", registry=reg, num_slots=256)
    slots = np.array([[1, 2], [3, 1]], np.int32)
    mask = np.array([[1, 1], [0, 1]], np.float32)  # slot 3 masked off
    mon.observe_batch(slots, mask)
    mon.staged({"loss": np.float32(0.5)})
    mon.collect()
    rec = mon.window_record()
    assert rec["slots_touched"] == 2  # {1, 2}
    assert rec["table_occupancy"] == pytest.approx(2 / 256, abs=1e-6)
    assert reg.gauge("health.table_occupancy").value == pytest.approx(2 / 256)


# ------------------------------------------------------------- trainer wiring


@pytest.fixture
def health_run(tmp_path, monkeypatch):
    """A small single-process run with health metrics, heartbeats, and a
    streaming eval all on; returns the run dir."""
    monkeypatch.chdir(tmp_path)
    generate_shards(str(tmp_path / "train"), 1, 640, num_fields=6,
                    ids_per_field=40, seed=0)
    generate_shards(str(tmp_path / "test"), 1, 256, num_fields=6,
                    ids_per_field=40, seed=1, truth_seed=0)
    run = tmp_path / "run"
    cfg = override(Config(), **{
        "data.train_path": str(tmp_path / "train"),
        "data.test_path": str(tmp_path / "test"),
        "data.log2_slots": 12,
        "data.batch_size": 64,
        "data.max_nnz": 8,
        "model.num_fields": 6,
        "train.epochs": 2,
        "train.log_every": 1,
        "train.eval_every": 1,
        "train.pred_dump": False,
        "train.health_metrics": "norms",
        "train.health_ema_decay": 0.9,
        "train.heartbeat_every": 5,
        "train.metrics_path": str(run / "metrics_rank0.jsonl"),
        "train.heartbeat_path": str(run / "heartbeat_rank0.jsonl"),
    })
    default_registry().reset()
    trainer = Trainer(cfg)
    res = trainer.fit()
    assert res.steps == 20
    return run, trainer


def test_trainer_health_fields_and_ema_oracle(health_run):
    """EVERY log record carries the full health key set, and the logged
    EMA replays exactly from the logged per-step losses, covering
    losses 1..i at the record for step i: since the XF110 fix the
    trainer stages each log-cadence record and writes it one step
    BEHIND (under the next step's device time), by which point the
    health collect for the record's own step has already run — so not
    even the first record is health-blind any more."""
    run, _ = health_run
    recs = read_jsonl(str(run / "metrics_rank0.jsonl"))
    steps = [r for r in recs if "step" in r and "loss" in r]
    health = [r for r in steps if "grad_norm" in r]
    assert len(health) == len(steps)  # one-behind write: all covered
    for r in health:
        for key in ("grad_norm", "update_norm", "param_norm", "loss_ema",
                    "grad_norm_max", "slots_touched", "table_occupancy",
                    "est_collision_rate"):
            assert key in r, key
        assert r["grad_norm"] > 0 and r["param_norm"] > 0
    losses = {r["step"]: r["loss"] for r in steps}
    ema = None
    for r in health:
        cur = losses[r["step"]]
        ema = cur if ema is None else 0.9 * ema + 0.1 * cur
        assert r["loss_ema"] == pytest.approx(ema, rel=1e-4), r["step"]
    # streaming evals landed mid-run, stamped with the step
    evals = [r for r in recs if "eval_auc" in r]
    assert len(evals) == 2
    assert all("eval_logloss" in r and "step" in r for r in evals)
    # occupancy only grows, and the touched count is honest (≤ slots)
    occs = [r["slots_touched"] for r in health]
    assert occs == sorted(occs) and occs[-1] <= 1 << 12
    # final record carries the tail health window too
    final = next(r for r in recs if r.get("final"))
    assert "grad_norm" in final and "loss_ema" in final


def test_trainer_health_full_per_table(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    generate_shards(str(tmp_path / "train"), 1, 256, num_fields=6,
                    ids_per_field=40, seed=0)
    mpath = tmp_path / "m.jsonl"
    cfg = override(Config(), **{
        "data.train_path": str(tmp_path / "train"),
        "data.log2_slots": 12,
        "data.batch_size": 64,
        "data.max_nnz": 8,
        "model.num_fields": 6,
        "model.name": "fm",
        "train.epochs": 1,
        "train.log_every": 2,
        "train.pred_dump": False,
        "train.health_metrics": "full",
        "train.metrics_path": str(mpath),
    })
    default_registry().reset()
    Trainer(cfg).fit()
    recs = [r for r in read_jsonl(str(mpath)) if "health_tables" in r]
    assert recs
    tables = recs[-1]["health_tables"]
    assert "wv" in tables  # fused FM single table
    assert set(tables["wv"]) == {"grad_norm", "update_norm", "param_norm"}


def test_sgd_update_norm_is_lr_times_grad_norm(tmp_path, monkeypatch):
    """NumPy-checkable invariant through the whole pipeline: under plain
    SGD the update is exactly −lr·grad, so update_norm == lr·grad_norm."""
    monkeypatch.chdir(tmp_path)
    generate_shards(str(tmp_path / "train"), 1, 128, num_fields=6,
                    ids_per_field=40, seed=0)
    mpath = tmp_path / "m.jsonl"
    cfg = override(Config(), **{
        "data.train_path": str(tmp_path / "train"),
        "data.log2_slots": 12,
        "data.batch_size": 64,
        "data.max_nnz": 8,
        "model.num_fields": 6,
        "optim.name": "sgd",
        "train.epochs": 1,
        "train.log_every": 1,
        "train.pred_dump": False,
        "train.health_metrics": "norms",
        "train.metrics_path": str(mpath),
    })
    default_registry().reset()
    Trainer(cfg).fit()
    recs = [r for r in read_jsonl(str(mpath)) if "grad_norm" in r and r.get("step")]
    assert recs
    for r in recs:
        # JSONL values are rounded to 6 decimals, hence the abs term
        assert r["update_norm"] == pytest.approx(
            cfg.optim.sgd.lr * r["grad_norm"], rel=1e-3, abs=2e-6
        )


def test_streaming_auc_matches_exact_eval(health_run):
    """The bucketed streaming eval the eval_every pass runs agrees with
    the exact rank-sum AUC to within bucket resolution, and the logloss
    exactly (same accumulation)."""
    _, trainer = health_run
    auc_exact, ll_exact = trainer.evaluate(dump=False)
    auc_stream, ll_stream = trainer.evaluate(dump=False, streaming=True)
    # bucketed error comes from same-bucket ties counted 1/2; with a
    # briefly-trained LR the scores cluster tightly, so allow a few
    # bucket-widths of slack rather than the ideal 1/buckets
    assert auc_stream == pytest.approx(auc_exact, abs=1e-3)
    assert ll_stream == pytest.approx(ll_exact, rel=1e-9)


def test_health_off_leaves_metrics_clean(tmp_path, monkeypatch):
    """Default (off): no health keys in the step metrics or the JSONL —
    the jitted step program is untouched."""
    monkeypatch.chdir(tmp_path)
    generate_shards(str(tmp_path / "train"), 1, 128, num_fields=6,
                    ids_per_field=40, seed=0)
    mpath = tmp_path / "m.jsonl"
    cfg = override(Config(), **{
        "data.train_path": str(tmp_path / "train"),
        "data.log2_slots": 12,
        "data.batch_size": 64,
        "data.max_nnz": 8,
        "model.num_fields": 6,
        "train.epochs": 1,
        "train.log_every": 1,
        "train.pred_dump": False,
        "train.metrics_path": str(mpath),
    })
    default_registry().reset()
    Trainer(cfg).fit()
    for r in read_jsonl(str(mpath)):
        assert "grad_norm" not in r and "loss_ema" not in r


# ------------------------------------------------------------ hang watchdog


def test_hang_watchdog_dumps_once_per_stall():
    out = io.StringIO()
    wd = HangWatchdog(0.15, out=out)
    try:
        time.sleep(0.6)  # stall: one dump, not one per poll
        assert wd.dumps == 1
        assert "hang watchdog" in out.getvalue()
        assert "Thread" in out.getvalue() or "thread" in out.getvalue()
        wd.tick()  # progress re-arms
        time.sleep(0.6)
        assert wd.dumps == 2
    finally:
        wd.close()


def test_hang_watchdog_disabled_at_zero():
    wd = HangWatchdog(0.0)
    assert wd._thread is None
    wd.close()


# ------------------------------------------------------- watchdog classifier


def test_watchdog_classify_statuses():
    from xflow_tpu.launch.watchdog import classify

    now = 1000.0
    beats = {
        0: {"step": 50, "ts": now - 1, "event": None},       # leader
        1: {"step": 10, "ts": now - 2, "event": None},       # straggler
        2: {"step": 48, "ts": now - 120, "event": None},     # dead
        3: {"step": 50, "ts": now - 300, "event": "final"},  # finished
    }
    beats[5] = {"step": 0, "ts": now - 500, "event": "start"}  # compiling
    rows = classify(beats, now, straggler_factor=2.0, dead_after_s=60.0,
                    expected_ranks=7)
    by_rank = {r["rank"]: r for r in rows}
    assert by_rank[0]["status"] == "ok"
    assert by_rank[1]["status"] == "straggler"
    assert by_rank[2]["status"] == "dead"
    assert by_rank[3]["status"] == "finished"
    # a rank still on its start beat is compiling, not dead/straggling —
    # TPU first-step compilation takes minutes
    assert by_rank[5]["status"] == "starting"
    assert by_rank[4]["status"] == "missing" and by_rank[6]["status"] == "missing"
    # culprit ordering: lowest step first (start-beat ranks excepted)
    assert rows[0]["rank"] in (1, 5)
    assert by_rank[1]["step"] == 10


def test_run_watchdog_flags_and_logs(tmp_path):
    from xflow_tpu.launch.watchdog import RunWatchdog

    run = tmp_path / "run"
    run.mkdir()
    now = time.time()
    for rank, step in ((0, 40), (1, 3)):
        a = JsonlAppender(str(run / f"heartbeat_rank{rank}.jsonl"),
                          stamp={"rank": rank, "run_id": "r1", "kind": "heartbeat"})
        a.append({"step": step})
        a.close()
    out = io.StringIO()
    wd = RunWatchdog(str(run), num_ranks=2, straggler_factor=2.0,
                     dead_after_s=600.0, run_id="r1", out=out)
    rows = wd.poll_once(now=now + 1)
    assert {r["rank"]: r["status"] for r in rows} == {0: "ok", 1: "straggler"}
    assert "rank 1 is a STRAGGLER" in out.getvalue()
    rows = wd.poll_once(now=now + 1)  # no re-report while unchanged
    assert out.getvalue().count("STRAGGLER") == 1
    wd.stop()
    events = read_jsonl(str(run / "watchdog.jsonl"))
    assert [e["event"] for e in events] == ["straggler"]
    # a reused run dir: the OLD run's beats must not leak into the new
    # run's live view (fold filters on the watchdog's run_id)
    from xflow_tpu.launch.watchdog import RunWatchdog as RW

    stale = JsonlAppender(str(run / "heartbeat_rank7.jsonl"),
                          stamp={"rank": 7, "run_id": "OLD", "kind": "heartbeat"})
    stale.append({"step": 999})
    stale.close()
    wd2 = RW(str(run), num_ranks=2, straggler_factor=2.0,
             dead_after_s=600.0, run_id="r1", out=io.StringIO())
    rows = wd2.poll_once(now=now + 1)
    assert 7 not in {r["rank"] for r in rows}
    assert max(r["max_step"] for r in rows) == 40  # old 999 ignored
    wd2.stop()
    assert events[0]["flagged_rank"] == 1 and events[0]["at_step"] == 3
    # stamped as the launcher's own stream, not any rank's
    assert events[0]["rank"] == -1 and events[0]["kind"] == "watchdog"


# ---------------------------------------------------- metrics_report wiring


def _report(args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "metrics_report.py"),
         *args],
        capture_output=True, text=True, timeout=120,
    )


def test_metrics_report_health_summary(health_run):
    run, _ = health_run
    r = _report([str(run), "--check"])
    assert r.returncode == 0, r.stderr
    r = _report([str(run), "--health"])
    assert r.returncode == 0, r.stderr
    assert "norms: grad" in r.stdout
    assert "auc trajectory (2 evals)" in r.stdout
    assert "occupancy" in r.stdout
    assert "[finished]" in r.stdout  # heartbeat table, clean finish


def test_metrics_report_check_flags_partial_health(tmp_path):
    bad = tmp_path / "bad.jsonl"
    with open(bad, "w") as f:
        f.write(json.dumps({"ts": 1.0, "rank": 0, "run_id": "r", "step": 1,
                            "loss": 0.5, "grad_norm": 1.0}) + "\n")
    r = _report([str(bad), "--check"])
    assert r.returncode != 0
    assert "health keys" in r.stderr


def test_metrics_report_check_flags_lone_eval_field(tmp_path):
    bad = tmp_path / "bad.jsonl"
    with open(bad, "w") as f:
        f.write(json.dumps({"ts": 1.0, "rank": 0, "run_id": "r",
                            "eval_auc": 0.7}) + "\n")
    r = _report([str(bad), "--check"])
    assert r.returncode != 0
    assert "eval_auc/eval_logloss" in r.stderr


def test_metrics_report_regress_gate(health_run, tmp_path):
    run, _ = health_run
    bench = tmp_path / "bench.json"
    r = _report([str(run), "--bench-json", str(bench)])
    assert r.returncode == 0, r.stderr
    rec = json.loads(bench.read_text())
    assert rec["value"] > 0 and "auc" in rec
    # self-comparison passes
    r = _report([str(run), "--regress", str(bench)])
    assert r.returncode == 0, r.stderr
    assert "no regression" in r.stdout
    # an inflated baseline fails on throughput
    fat = dict(rec, value=rec["value"] * 10)
    (tmp_path / "fat.json").write_text(json.dumps(fat))
    r = _report([str(run), "--regress", str(tmp_path / "fat.json")])
    assert r.returncode == 3
    assert "throughput regressed" in r.stderr
    # a better-AUC baseline fails on quality
    smart = dict(rec, auc=min(rec["auc"] + 0.05, 1.0))
    (tmp_path / "smart.json").write_text(json.dumps(smart))
    r = _report([str(run), "--regress", str(tmp_path / "smart.json")])
    assert r.returncode == 3
    assert "AUC regressed" in r.stderr


# -------------------------------------------------- launch-local drill


def test_launch_local_straggler_drill(tmp_path):
    """End-to-end watchdog drill: two launch-local ranks, rank 1 stalls
    mid-run (testing/faults.py env injector), the launcher watchdog
    flags it as a straggler while the run is live, and the run still
    completes cleanly once the stall ends."""
    from tests.test_launch_local import multiproc_cpu_supported, run_cli

    if not multiproc_cpu_supported():
        pytest.skip("this jax build cannot run multi-process CPU worlds")
    generate_shards(str(tmp_path / "train"), 2, 768, num_fields=6,
                    ids_per_field=40, seed=0)
    run = tmp_path / "run"
    r = run_cli(
        [
            "launch-local", "--num-processes", "2",
            "--run-dir", str(run),
            "--watchdog-poll-s", "0.2",
            "--straggler-factor", "1.01",
            "--dead-after-s", "300",
            "--",
            "--train", str(tmp_path / "train"), "--model", "lr",
            "--epochs", "1", "--batch-size", "32", "--log2-slots", "12",
            "--set", "model.num_fields=6",
            "--set", "data.max_nnz=8",
            "--set", "train.pred_dump=false",
            "--set", "train.heartbeat_every=1",
        ],
        cwd=str(tmp_path),
        extra_env={
            "XFLOW_FAULT_STALL_S": "6",
            "XFLOW_FAULT_STALL_STEP": "4",
            "XFLOW_FAULT_DELAY_RANK": "1",
        },
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "rank 1 is a STRAGGLER" in r.stderr, r.stderr
    events = read_jsonl(str(run / "watchdog.jsonl"), warn=False)
    assert any(
        e["event"] == "straggler" and e["flagged_rank"] == 1 for e in events
    )
    # every rank heartbeated and the post-mortem health view renders
    for rank in (0, 1):
        beats = read_jsonl(str(run / f"heartbeat_rank{rank}.jsonl"), warn=False)
        assert any(b.get("event") == "final" for b in beats)
    rep = _report([str(run), "--health"])
    assert rep.returncode == 0, rep.stderr
    assert "heartbeats" in rep.stdout
