"""Multi-machine launcher (`xflow launch-dist`, launch/dist.py — the
`run_ps_dist.sh` + `scripts/hosts` analog) and coordinated
multi-process preemption (train.signal_sync_every).

The two-"host" test drives the REAL launcher end to end with ssh
swapped for a local shim (`--ssh-cmd`), separate per-rank working
directories (`--workdir .../{rank}`), and the existing bit-match gate:
final tables equal a single-process run on the batch-composed data.
"""

import json
import os
import signal
import socket
import stat
import subprocess
import sys
import time

import numpy as np
import pytest

from xflow_tpu.data.synth import generate_shards

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _clean_env(extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XFLOW_NUM_CPU_DEVICES", None)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    if extra:
        env.update(extra)
    return env


def _fake_ssh(tmp_path) -> str:
    """An `ssh`-shaped shim: ignores the host argument and runs the
    remote command locally — two 'hosts' that are both this machine."""
    path = tmp_path / "fakessh"
    path.write_text('#!/bin/bash\nshift\nexec bash -c "$1"\n')
    path.chmod(path.stat().st_mode | stat.S_IEXEC)
    return str(path)


def test_dry_run_prints_env_contract(tmp_path):
    hosts = tmp_path / "hosts"
    hosts.write_text("# comment\nnode-a\nuser@node-b\n\n")
    r = subprocess.run(
        [sys.executable, "-m", "xflow_tpu", "launch-dist",
         "--hosts", str(hosts), "--port", "12345",
         "--workdir", "/w/{rank}", "--env", "FOO=bar r", "--dry-run",
         "--", "--train", "/data/t x", "--model", "fm"],
        capture_output=True, text=True, env=_clean_env(), timeout=120,
    )
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "# rank 0 on node-a:" in out and "# rank 1 on user@node-b:" in out
    # both ranks point at host 0 (user@ stripped from the address)
    assert out.count("XFLOW_COORDINATOR=node-a:12345") == 2
    assert "XFLOW_NUM_PROCESSES=2" in out
    assert "XFLOW_PROCESS_ID=0" in out and "XFLOW_PROCESS_ID=1" in out
    assert "/w/0" in out and "/w/1" in out
    # env values and forwarded args survive shell-quoted (the exact
    # escaping nests once more inside the ssh argument)
    assert "FOO=" in out and "bar r" in out
    assert "/data/t x" in out
    assert "ssh node-a" in out and "ssh user@node-b" in out


def test_launch_dist_two_hosts_bitmatch(tmp_path):
    """A 2-'host' run driven by launch-dist (separate workdirs, real
    rendezvous through the XFLOW_* contract) bit-matches a
    single-process run on the batch-composed data (round-2 verdict
    item 7's done criterion)."""
    from tests.test_launch_local import (
        TRAIN_ARGS, _interleave_shards, require_multiproc_cpu, run_cli,
    )

    require_multiproc_cpu()

    B, rows = 32, 96
    generate_shards(str(tmp_path / "train"), 2, rows, num_fields=4, ids_per_field=50)
    hosts = tmp_path / "hosts"
    hosts.write_text("127.0.0.1\n127.0.0.1\n")
    r2 = subprocess.run(
        [sys.executable, "-m", "xflow_tpu", "launch-dist",
         "--hosts", str(hosts), "--port", str(_free_port()),
         "--ssh-cmd", _fake_ssh(tmp_path),
         "--workdir", str(tmp_path / "rank{rank}"),
         "--python", sys.executable,
         "--env", "JAX_PLATFORMS=cpu",
         "--env", "PYTHONPATH=" + REPO_ROOT,
         "--", "--train", str(tmp_path / "train"),
         "--batch-size", str(B), "--checkpoint-dir", "ckpt",
         "--set", "train.eval_buckets=0",
         *TRAIN_ARGS],
        capture_output=True, text=True, env=_clean_env(), timeout=600,
    )
    assert r2.returncode == 0, (r2.stdout, r2.stderr)
    summaries = [json.loads(l) for l in r2.stdout.strip().splitlines()
                 if l.startswith("{")]
    assert len(summaries) == 1, r2.stdout  # rank 0 only
    s2 = summaries[0]
    assert s2["steps"] == 2 * (rows // B)
    # separate workdirs materialized; rank 0's checkpoint is the artifact
    assert (tmp_path / "rank0" / "ckpt").is_dir()
    assert (tmp_path / "rank1").is_dir()

    _interleave_shards(
        [tmp_path / "train-00000", tmp_path / "train-00001"], B,
        tmp_path / "comb-00000",
    )
    r1 = run_cli(
        ["train", "--train", str(tmp_path / "comb"), "--batch-size", str(2 * B),
         "--checkpoint-dir", str(tmp_path / "ckpt1p"), "--no-mesh", *TRAIN_ARGS],
        tmp_path,
    )
    assert r1.returncode == 0, r1.stderr
    s1 = json.loads(r1.stdout.strip().splitlines()[-1])
    d2 = np.load(tmp_path / "rank0" / "ckpt" / f"step_{s2['steps']}" / "state.npz")
    d1 = np.load(tmp_path / "ckpt1p" / f"step_{s1['steps']}" / "state.npz")
    assert s1["steps"] == s2["steps"]
    np.testing.assert_allclose(
        d2["tables/w"], d1["tables/w"], rtol=0, atol=1e-6,
        err_msg="launch-dist 2-host tables != single-process tables on composed data",
    )
    np.testing.assert_allclose(d2["opt/w/n"], d1["opt/w/n"], rtol=0, atol=1e-6)


def _pids_with_env(key: bytes) -> list:
    """All live pids whose environment contains `key` (via /proc)."""
    out = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/environ", "rb") as f:
                if key in f.read():
                    out.append(int(pid))
        except OSError:
            continue
    return out


def test_launch_dist_ranks_die_with_launcher(tmp_path):
    """The die-with-connection wrapper (rank_command): SIGKILL the
    launcher itself — no graceful teardown runs — and the rank
    processes must still exit, because the launcher's death closes the
    held-open ssh stdin pipes and the remote watcher TERMs each rank.
    Without the wrapper, ssh'd ranks blocked in collectives outlive the
    launcher and hold the coordinator port (ADVICE r3)."""
    from tests.test_launch_local import require_multiproc_cpu

    require_multiproc_cpu()
    generate_shards(str(tmp_path / "train"), 2, 4000, num_fields=4, ids_per_field=50)
    hosts = tmp_path / "hosts"
    hosts.write_text("127.0.0.1\n127.0.0.1\n")
    marker = f"XFLOW_DIEWITH_{os.getpid()}"
    p = subprocess.Popen(
        [sys.executable, "-m", "xflow_tpu", "launch-dist",
         "--hosts", str(hosts), "--port", str(_free_port()),
         "--ssh-cmd", _fake_ssh(tmp_path),
         "--workdir", str(tmp_path / "rank{rank}"),
         "--python", sys.executable,
         "--env", "JAX_PLATFORMS=cpu",
         "--env", "PYTHONPATH=" + REPO_ROOT,
         "--env", marker + "=1",
         "--", "--train", str(tmp_path / "train"),
         "--batch-size", "20", "--model", "lr", "--epochs", "100000",
         "--log2-slots", "10", "--set", "model.num_fields=4",
         "--set", "data.max_nnz=8", "--set", "train.pred_dump=false"],
        env=_clean_env(), stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.time() + 300
        while time.time() < deadline:
            ranks = _pids_with_env(marker.encode())
            if len(ranks) >= 2:
                break
            assert p.poll() is None, "launcher died before ranks started"
            time.sleep(0.3)
        assert len(ranks) >= 2, f"ranks never started: {ranks}"
        os.kill(p.pid, signal.SIGKILL)  # no teardown() runs
        p.wait()
        deadline = time.time() + 30  # watcher: TERM immediately, KILL +5s
        while time.time() < deadline:
            alive = [r for r in _pids_with_env(marker.encode()) if r != p.pid]
            if not alive:
                break
            time.sleep(0.5)
        assert not alive, f"rank pids outlived the launcher: {alive}"
    finally:
        for pid in _pids_with_env(marker.encode()):
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass


def _children_by_rank(parent_pid: int) -> dict:
    """rank -> pid of `xflow train` children, via /proc (Linux)."""
    out = {}
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/stat") as f:
                ppid = int(f.read().split()[3])
            if ppid != parent_pid:
                continue
            with open(f"/proc/{pid}/environ", "rb") as f:
                env = dict(
                    kv.split(b"=", 1) for kv in f.read().split(b"\0") if b"=" in kv
                )
            rank = env.get(b"XFLOW_PROCESS_ID")
            if rank is not None:
                out[int(rank)] = int(pid)
        except (OSError, ValueError, IndexError):
            continue
    return out


def test_coordinated_preemption_two_process(tmp_path):
    """SIGTERM delivered to rank 1 ONLY: the flag allgather
    (train.signal_sync_every) stops BOTH ranks at the same step, both
    checkpoint collectively, and rank 0's summary reports the adopted
    signal (round-2 weak #6)."""
    from tests.test_launch_local import require_multiproc_cpu

    require_multiproc_cpu()
    generate_shards(str(tmp_path / "train"), 2, 2000, num_fields=4, ids_per_field=50)
    metrics = tmp_path / "metrics.jsonl"
    p = subprocess.Popen(
        [sys.executable, "-m", "xflow_tpu", "launch-local", "--num-processes", "2",
         "--", "--train", str(tmp_path / "train"), "--model", "lr",
         "--epochs", "100000", "--batch-size", "20", "--log2-slots", "10",
         "--checkpoint-dir", str(tmp_path / "ckpt"),
         "--set", "model.num_fields=4", "--set", "data.max_nnz=8",
         "--set", "train.pred_dump=false", "--set", "train.log_every=1",
         "--set", "train.signal_sync_every=2",
         "--set", f"train.metrics_path={metrics}"],
        cwd=tmp_path, env=_clean_env(), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )
    deadline = time.time() + 300
    while time.time() < deadline:
        if metrics.exists() and metrics.stat().st_size > 0:
            break
        assert p.poll() is None, (p.stdout.read(), p.stderr.read())
        time.sleep(0.2)
    assert metrics.exists() and metrics.stat().st_size > 0, "training never started"
    kids = _children_by_rank(p.pid)
    assert 1 in kids, f"children found: {kids}"
    os.kill(kids[1], signal.SIGTERM)  # NOT rank 0 — coordination must spread it
    out, err = p.communicate(timeout=300)
    assert p.returncode == 0, (out, err)
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["interrupted"] == int(signal.SIGTERM)  # adopted by rank 0
    assert summary["steps"] > 0
    steps = sorted((tmp_path / "ckpt").glob("step_*"))
    assert steps, "no coordinated checkpoint written"
