"""Aux subsystems: profiling hook, collision tool, occupancy metric."""

import glob
import json
import os

import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.tools.collisions import measure
from xflow_tpu.train.trainer import Trainer


def test_profile_dir_produces_trace(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    generate_shards(str(tmp_path / "t"), 1, 200, num_fields=4, ids_per_field=20, seed=0)
    cfg = override(
        Config(),
        **{
            "data.train_path": str(tmp_path / "t"),
            "data.log2_slots": 10,
            "data.batch_size": 64,
            "data.max_nnz": 8,
            "model.num_fields": 4,
            "train.epochs": 1,
            "train.profile_dir": str(tmp_path / "prof"),
        },
    )
    Trainer(cfg).fit()
    traces = glob.glob(str(tmp_path / "prof" / "**" / "*"), recursive=True)
    assert traces, "no profiler output written"


def test_collision_tool(tmp_path):
    paths = generate_shards(str(tmp_path / "s"), 2, 300, num_fields=6, ids_per_field=50, seed=1)
    # tiny table: collisions guaranteed; big table: near-zero
    tight = measure(paths, log2_slots=6)
    roomy = measure(paths, log2_slots=22)
    assert tight["distinct_tokens"] == roomy["distinct_tokens"] > 0
    assert tight["collision_rate"] > 0.5
    assert roomy["collision_rate"] < 0.01
    assert 0 < roomy["table_occupancy"] < 1e-3


def test_occupancy_reported(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    generate_shards(str(tmp_path / "t"), 1, 400, num_fields=4, ids_per_field=20, seed=2)
    cfg = override(
        Config(),
        **{
            "data.train_path": str(tmp_path / "t"),
            "data.log2_slots": 12,
            "data.batch_size": 64,
            "data.max_nnz": 8,
            "model.num_fields": 4,
            "train.epochs": 3,
        },
    )
    res = Trainer(cfg).fit()
    assert "w" in res.occupancy
    # 80 distinct features in a 4096-slot table, FTRL leaves most touched
    # slots nonzero after enough steps
    assert 0 < res.occupancy["w"] < 0.1


def test_fullshard_overflow_sim():
    """The pod-scale overflow accounting (docs/DISTRIBUTED.md "Sizing
    fullshard_slack"): rates are monotone in slack, the default slack
    holds the single-host grid at Criteo-like skew, and the hot-key
    head share makes D*T=512 need ~p1*D*T (>> any sane default) — the
    quantified case for the coordinated fallback."""
    from xflow_tpu.tools.fullshard_overflow_sim import run

    res = run(quick=True)
    for key, row in res["rows"].items():
        rates = row["rates"]
        assert all(a >= b for a, b in zip(rates, rates[1:])), (key, rates)
    # default slack 2.0 holds D*T=8 at alpha<=1.1 (the docs claim)
    s_idx = res["slacks"].index(2.0)
    assert res["rows"]["a1.05_dt8"]["rates"][s_idx] == 0.0
    # at pod scale the needed slack is dominated by the head share:
    # far beyond any memory-free default
    assert res["rows"]["a1.05_dt512"]["needed_slack"] > 8
    assert (
        res["rows"]["a1.3_dt512"]["needed_slack"]
        > res["rows"]["a1.05_dt512"]["needed_slack"]
    )
