"""Hot-path attribution layer (round 11): PipelineProfiler unit
coverage, the prefetch queue counters + starvation detection under the
fault injectors' pacing, the zero-overhead-when-off contract (no
pipeline records/counters in an off run), trainer-integrated
kind="pipeline" windows through metrics_report --check/--health,
tools/pipeline_attrib.py's table/verdict/host-gap record, the
bench_lab core sweep + probe-wrapper CLIs, perf_ledger's BENCH_LAB /
BENCH_PIPELINE folding with the measured-gather roofline citation and
downward gating, and the tools/smoke_hotpath.sh CI gate end to end.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.telemetry import (
    PIPELINE_CONSUMER_STAGES,
    PIPELINE_PRODUCER_STAGES,
    PIPELINE_STAGES,
    PipelineProfiler,
    Registry,
    pipeline_verdict,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tool(name: str) -> str:
    return os.path.join(REPO_ROOT, "tools", name)


def run_tool(args, **kw):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable] + args, capture_output=True, text=True, env=env, **kw
    )


# ------------------------------------------------------- PipelineProfiler


def test_profiler_stages_and_window():
    prof = PipelineProfiler(registry=Registry())
    prof.start()
    prof.add("parse", 0.25)
    prof.add_many({"read": 0.05, "hash": 0.1})
    with prof.stage("plan"):
        time.sleep(0.01)
    prof.count_batch(64)
    prof.observe_queue(2, 2)
    rec = prof.window_record()
    for s in PIPELINE_STAGES:
        assert f"{s}_s" in rec
    assert rec["parse_s"] == pytest.approx(0.25)
    assert rec["read_s"] == pytest.approx(0.05)
    assert rec["plan_s"] > 0
    assert rec["batches"] == 1 and rec["rows"] == 64
    assert rec["queue_depth"] == 2 and rec["queue_cap"] == 2
    assert rec["wall_s"] > 0
    # the window reset: a second flush with no activity is empty
    assert prof.window_record() == {}
    # run totals survive the window reset
    totals, elapsed = prof.totals()
    assert totals["parse"] == pytest.approx(0.25)
    assert elapsed > 0


def test_profiler_registry_gauges():
    reg = Registry()
    prof = PipelineProfiler(registry=reg)
    prof.start()
    snap = reg.snapshot()
    # pre-registered at start() so profiled runs always carry them
    assert snap["pipeline.queue_depth"] == 0
    assert snap["pipeline.producer_blocked_s"] == 0.0
    prof.add("producer_wait", 1.5)
    prof.observe_queue(1, 4)
    snap = reg.snapshot()
    assert snap["pipeline.producer_blocked_s"] == pytest.approx(1.5)
    assert snap["pipeline.queue_depth"] == 1


def test_pipeline_verdict_directions():
    # consumer starved + parse dominant -> host-bound in parse
    v = pipeline_verdict({"queue_wait": 6.0, "parse": 6.1, "read": 0.5}, 10.0)
    assert v.startswith("host-bound in parse: 61%")
    # producer blocked -> device-bound
    v = pipeline_verdict({"producer_wait": 9.0, "dispatch": 8.0}, 10.0)
    assert v.startswith("device-bound")
    # neither -> balanced
    v = pipeline_verdict({"parse": 0.5, "device": 0.5}, 10.0)
    assert v.startswith("balanced")
    assert pipeline_verdict({}, 0.0) == "no pipeline windows"


# ------------------------------------------------- prefetch queue counters


def test_prefetch_counters_slow_consumer():
    """A slow consumer must show up as producer-blocked time and a full
    queue — the starvation signature the satellite asks for."""
    from xflow_tpu.data.pipeline import prefetch

    reg = Registry()
    prof = PipelineProfiler(registry=reg)
    prof.start()

    def gen():
        for i in range(8):
            yield i

    got = []
    for item in prefetch(gen(), depth=2, profiler=prof):
        time.sleep(0.02)  # artificially slow consumer
        got.append(item)
    assert got == list(range(8))
    totals, _ = prof.totals()
    # the producer spent most of its life blocked on the full queue
    assert totals["producer_wait"] > 0.05
    snap = reg.snapshot()
    assert snap["pipeline.producer_blocked_s"] == pytest.approx(
        totals["producer_wait"], abs=1e-5
    )
    assert "pipeline.queue_depth" in snap


def test_prefetch_without_profiler_unchanged():
    from xflow_tpu.data.pipeline import prefetch

    assert list(prefetch(iter(range(5)))) == list(range(5))


def test_parse_line_matches_profiled_halves():
    """parse_line keeps its fused single-pass hot loop; the profiled
    path goes through split_line + hash_ids. The two must agree on
    every token-rule corner or the profiled stream would differ from
    the stream it claims to attribute."""
    from xflow_tpu.data.libffm import hash_ids, parse_line, split_line

    lines = [
        "1\t0:abc:1 3:def:1",
        "0 2:xyz:1",  # space-separated label
        "junk\t5:q:1",  # strtod junk label -> 0
        "1\tgarbage novalue",  # all tokens malformed: zero features
        "",  # empty: not a row
        "1",  # label only: not a row
        "0.5\t1e2:tok:1 nan:other:1",  # strtod fgid corners
    ]
    for line in lines:
        full = parse_line(line, 12, salt=7)
        halves = split_line(line)
        if full is None:
            assert halves is None or not line.strip()
            if halves is None:
                continue
        label, fields, ids = halves
        assert full is not None
        assert full[0] == label
        np.testing.assert_array_equal(
            full[1], np.asarray(fields, dtype=np.int32)
        )
        np.testing.assert_array_equal(full[2], hash_ids(ids, 12, salt=7))


# ------------------------------------------------- trainer integration


def _train_tiny(tmp_path, run_name="run", rows=320, **extra):
    from xflow_tpu.data.synth import generate_shards
    from xflow_tpu.train.trainer import Trainer

    data = str(tmp_path / "train")
    if not os.path.exists(data + "-00000"):
        generate_shards(data, 1, rows, num_fields=6, ids_per_field=50, seed=0)
    cfg = override(Config(), **{
        "model.name": "lr",
        "data.train_path": data,
        "data.log2_slots": 12,
        "data.max_nnz": 8,
        "data.batch_size": 64,
        "model.num_fields": 6,
        "train.epochs": 1,
        "train.pred_dump": False,
        "train.log_every": 2,
        "train.metrics_path": str(tmp_path / run_name / "metrics_rank0.jsonl"),
        **extra,
    })
    trainer = Trainer(cfg)
    res = trainer.fit()
    from xflow_tpu.jsonl import read_jsonl

    return res, read_jsonl(str(tmp_path / run_name / "metrics_rank0.jsonl"))


def test_trainer_pipeline_records(tmp_path):
    res, recs = _train_tiny(
        tmp_path, **{"train.pipeline_metrics": True}
    )
    assert res.steps == 5
    pipe = [r for r in recs if r.get("kind") == "pipeline"]
    assert pipe, "no kind=pipeline records from a profiled run"
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    from metrics_report import PIPELINE_KEYS

    for r in pipe:
        for key in PIPELINE_KEYS:
            assert key in r, f"pipeline record lacks {key}"
        wall = r["wall_s"]
        assert wall > 0
        # the per-thread concurrency invariant (with the flush slack
        # the --check gate allows)
        prod = sum(r[f"{s}_s"] for s in PIPELINE_PRODUCER_STAGES)
        cons = sum(r[f"{s}_s"] for s in PIPELINE_CONSUMER_STAGES)
        assert prod <= wall * 1.25 + 0.05
        assert cons <= wall * 1.25 + 0.05
    # rows were counted (320 rows over the windows)
    assert sum(r["rows"] for r in pipe) == 320
    # profiled runs carry the prefetch gauges in their counters
    assert any(
        "pipeline.queue_depth" in (r.get("counters") or {}) for r in recs
    )
    # the full --check gate (pipeline schema included) passes
    r = run_tool([tool("metrics_report.py"),
                  str(tmp_path / "run"), "--check"])
    assert r.returncode == 0, r.stderr
    # --health prints the bottleneck verdict
    r = run_tool([tool("metrics_report.py"), str(tmp_path / "run"),
                  "--health"])
    assert r.returncode == 0, r.stderr
    assert "input pipeline" in r.stdout


def test_profiler_off_stream_is_pipeline_free(tmp_path):
    """The zero-overhead-when-off contract: an off run's stream holds
    no pipeline records and no pipeline.* counters — byte-identical in
    shape to a pre-profiler build."""
    from xflow_tpu.telemetry import default_registry

    default_registry().reset()  # a prior profiled test must not leak gauges
    res, recs = _train_tiny(tmp_path)
    assert res.steps == 5
    assert not any(r.get("kind") == "pipeline" for r in recs)
    for r in recs:
        for key in r.get("counters") or {}:
            assert not key.startswith("pipeline."), f"leaked counter {key}"


def test_profiled_then_off_run_no_gauge_leak(tmp_path):
    """The zero-overhead contract is per-RUN: a profiled fit followed
    by an off fit in the SAME process must leave no pipeline.* gauges
    in the off run's counters (fit() drops them at teardown) — no
    manual registry reset here on purpose."""
    _train_tiny(tmp_path, run_name="run_on",
                **{"train.pipeline_metrics": True})
    _, recs = _train_tiny(tmp_path, run_name="run_off2")
    assert not any(r.get("kind") == "pipeline" for r in recs)
    for r in recs:
        for key in r.get("counters") or {}:
            assert not key.startswith("pipeline."), f"leaked gauge {key}"


def test_starvation_detection_slow_consumer(tmp_path, monkeypatch):
    """Regression: an artificially slow consumer (the fault injectors'
    fit-loop pacing, testing/faults.fit_delays_from_env) must read as
    producer-blocked in the pipeline windows — the device-bound
    signature, never host-bound."""
    monkeypatch.setenv("XFLOW_FAULT_STEP_DELAY_S", "0.02")
    res, recs = _train_tiny(
        tmp_path, run_name="run_slow", **{"train.pipeline_metrics": True}
    )
    assert res.steps == 5
    pipe = [r for r in recs if r.get("kind") == "pipeline"]
    assert pipe
    wall = sum(r["wall_s"] for r in pipe)
    blocked = sum(r["producer_wait_s"] for r in pipe)
    host = sum(
        r[f"{s}_s"] for r in pipe
        for s in ("read", "parse", "hash", "batch", "pad", "plan")
    )
    # the producer spent most of the run blocked on the full queue,
    # dwarfing its actual host work
    assert blocked > 0.05
    assert blocked > host
    assert blocked / wall > 0.3
    # and the shared verdict names the right side
    stages = {
        s: sum(r[f"{s}_s"] for r in pipe) for s in PIPELINE_STAGES
    }
    assert pipeline_verdict(stages, wall).startswith("device-bound")


# ------------------------------------------------- metrics_report gates


def _stamped(i, **kw):
    return {"ts": float(i), "rank": 0, "run_id": "r", "gen": 0, **kw}


def _pipe_rec(i, step, **overrides):
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    from metrics_report import PIPELINE_KEYS

    rec = _stamped(i, kind="pipeline", step=step)
    for key in PIPELINE_KEYS:
        rec.setdefault(key, 0.001)
    rec["wall_s"] = 1.0
    rec["batches"] = 2
    rec["rows"] = 128
    rec["queue_depth"] = 1
    rec["queue_cap"] = 2
    rec.update(overrides)
    return rec


def _write_jsonl(path, records):
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


def test_metrics_report_pipeline_gate_ok(tmp_path):
    _write_jsonl(tmp_path / "m.jsonl", [_pipe_rec(1, 10), _pipe_rec(2, 20)])
    r = run_tool([tool("metrics_report.py"), str(tmp_path / "m.jsonl"),
                  "--check"])
    assert r.returncode == 0, r.stderr


def test_metrics_report_pipeline_gate_missing_key(tmp_path):
    bad = _pipe_rec(1, 10)
    del bad["queue_depth"]
    _write_jsonl(tmp_path / "m.jsonl", [bad])
    r = run_tool([tool("metrics_report.py"), str(tmp_path / "m.jsonl"),
                  "--check"])
    assert r.returncode == 2
    assert "pipeline keys" in r.stderr


def test_metrics_report_pipeline_gate_sum_exceeds_wall(tmp_path):
    # one thread claiming 3x the wall is impossible — the gate fires
    bad = _pipe_rec(1, 10, parse_s=3.0)
    _write_jsonl(tmp_path / "m.jsonl", [bad])
    r = run_tool([tool("metrics_report.py"), str(tmp_path / "m.jsonl"),
                  "--check"])
    assert r.returncode == 2
    assert "producer-side stage times sum" in r.stderr
    bad = _pipe_rec(1, 10, device_s=3.0)
    _write_jsonl(tmp_path / "m.jsonl", [bad])
    r = run_tool([tool("metrics_report.py"), str(tmp_path / "m.jsonl"),
                  "--check"])
    assert r.returncode == 2
    assert "consumer-side stage times sum" in r.stderr


def test_metrics_report_pipeline_gate_nonpositive_wall(tmp_path):
    _write_jsonl(tmp_path / "m.jsonl", [_pipe_rec(1, 10, wall_s=0.0)])
    r = run_tool([tool("metrics_report.py"), str(tmp_path / "m.jsonl"),
                  "--check"])
    assert r.returncode == 2
    assert "non-positive wall_s" in r.stderr


# ------------------------------------------------------- pipeline_attrib


def test_pipeline_attrib_report_and_bench(tmp_path):
    _, _ = _train_tiny(
        tmp_path, rows=640, **{"train.pipeline_metrics": True,
                               "train.log_every": 4}
    )
    out = tmp_path / "attrib.json"
    bench = tmp_path / "BENCH_PIPELINE.json"
    r = run_tool([tool("pipeline_attrib.py"), str(tmp_path / "run"),
                  "--json", str(out), "--bench-json", str(bench),
                  "--round", "11"])
    assert r.returncode == 0, r.stderr
    assert "verdict:" in r.stdout and "% of wall" in r.stdout
    att = json.loads(out.read_text())
    assert att["windows"] >= 2
    assert att["rows"] == 640
    # the consumer stages tile the fit loop: high coverage even on the
    # tiny CPU run (the smoke script pins the >= 95% acceptance bar on
    # a longer run; this bound just guards against gross regression)
    assert att["attributed_pct"] > 60.0
    rec = json.loads(bench.read_text())
    assert rec["metric"] == "pipeline_e2e_examples_per_sec"
    assert rec["value"] > 0
    assert rec["round"] == 11
    assert rec["host_gap_ratio"] >= 1.0
    assert rec["device_bound_examples_per_sec"] >= rec["value"]
    assert set(rec["stage_pct"]) == set(PIPELINE_STAGES)


def test_pipeline_attrib_unprofiled_run_exits_1(tmp_path):
    _write_jsonl(tmp_path / "m.jsonl", [_stamped(1, step=1, loss=0.5)])
    r = run_tool([tool("pipeline_attrib.py"), str(tmp_path / "m.jsonl")])
    assert r.returncode == 1
    assert "train.pipeline_metrics" in r.stderr


def test_pipeline_attrib_missing_path_exits_2(tmp_path):
    r = run_tool([tool("pipeline_attrib.py"), str(tmp_path / "nope")])
    assert r.returncode == 2


# ------------------------------------------------------------- bench_lab


def test_bench_lab_core_sweep_cpu(tmp_path):
    out = tmp_path / "BENCH_LAB.json"
    r = run_tool(["-m", "xflow_tpu.tools.bench_lab", "--suite", "core",
                  "--table-log2", "8,9", "--nnz-log2", "7",
                  "--row-width", "4", "--iters", "1", "--inner", "2",
                  "--round", "3", "--out", str(out)])
    assert r.returncode == 0, r.stderr
    d = json.loads(out.read_text())
    assert d["kind"] == "bench_lab"
    assert d["metric"] == "lab_gather_ns_per_element"
    assert d["unit"] == "ns/element" and d["value"] > 0
    assert d["round"] == 3
    # the full matrix: 3 ops x 2 table sizes x 1 nnz
    assert len(d["cells"]) == 6
    ops = {c["op"] for c in d["cells"]}
    assert ops == {"gather", "scatter_add", "segment_sum"}
    for c in d["cells"]:
        assert c["ns_per_element"] > 0 and c["time_ms"] > 0
    # CompileRecorder cost stamps ride along on CPU
    assert any(c.get("bytes_accessed") for c in d["cells"])
    assert any(c.get("achieved_gbps") for c in d["cells"])


def test_bench_lab_headline_is_largest_gather(tmp_path):
    out = tmp_path / "BENCH_LAB.json"
    r = run_tool(["-m", "xflow_tpu.tools.bench_lab", "--suite", "core",
                  "--table-log2", "7,9", "--nnz-log2", "6,7",
                  "--ops", "gather", "--row-width", "2",
                  "--iters", "1", "--inner", "2", "--out", str(out)])
    assert r.returncode == 0, r.stderr
    d = json.loads(out.read_text())
    assert d["headline_cell"] == "lab_gather_s9_n7_f32"


def test_bench_lab_unknown_suite_errors():
    r = run_tool(["-m", "xflow_tpu.tools.bench_lab", "--suite", "nope"])
    assert r.returncode == 2


def test_probe_wrappers_delegate_to_bench_lab():
    """The six retired probes keep their CLIs as thin wrappers over the
    lab (satellite: one entry point for the kernel arc). --help must
    resolve through the wrapper without importing jax-heavy paths."""
    for name in ("microbench_tpu.py", "layout_probe.py", "mosaic_probe.py",
                 "scatter_experiment.py", "rowsum_probe.py",
                 "hostplane_bench.py"):
        src = open(tool(name)).read()
        assert "bench_lab" in src, f"{name} does not delegate to bench_lab"
        r = run_tool([tool(name), "--help"])
        assert r.returncode == 0, f"{name} --help failed: {r.stderr}"
        assert "suite" in r.stdout


# ------------------------------------------------------------ perf_ledger


def _lab_record(value_scale=1.0, rnd=1):
    return {
        "kind": "bench_lab", "device": "cpu0", "host_cores": 1,
        "metric": "lab_gather_ns_per_element", "value": 100.0 * value_scale,
        "unit": "ns/element", "headline_cell": "lab_gather_s10_n8_f32",
        "row_width": 4, "iters": 1, "inner": 2, "seed": 0, "round": rnd,
        "cells": [
            {"op": "gather", "table_log2": 10, "nnz_log2": 8, "dtype": "f32",
             "row_width": 4, "time_ms": 0.1 * value_scale,
             "ns_per_element": 100.0 * value_scale,
             "flops": 10.0, "bytes_accessed": 2000.0, "achieved_gbps": 0.02,
             "compile_time_s": 0.05},
            {"op": "scatter_add", "table_log2": 10, "nnz_log2": 8,
             "dtype": "f32", "row_width": 4, "time_ms": 0.2 * value_scale,
             "ns_per_element": 200.0 * value_scale},
        ],
    }


def test_perf_ledger_folds_lab_and_pipeline(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "metric": "lr_examples_per_sec", "value": 1000.0,
        "unit": "examples/sec"}))
    (tmp_path / "BENCH_LAB.json").write_text(json.dumps(_lab_record()))
    (tmp_path / "BENCH_PIPELINE.json").write_text(json.dumps({
        "metric": "pipeline_e2e_examples_per_sec", "value": 5000.0,
        "unit": "examples/sec", "round": 1,
        "device_bound_examples_per_sec": 20000.0, "host_gap_ratio": 4.0}))
    out = tmp_path / "ledger.json"
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path),
                  "--json", str(out)])
    assert r.returncode == 0, r.stderr
    assert "Sparse-primitive lab" in r.stdout
    assert "measured gather random-access latency" in r.stdout
    got = json.loads(out.read_text())
    metrics = {e["metric"] for e in got["entries"]}
    assert {"lab_gather_ns_per_element", "lab_gather_s10_n8_f32",
            "lab_scatter_add_s10_n8_f32", "pipeline_e2e_examples_per_sec",
            "device_bound_examples_per_sec"} <= metrics
    labs = [e for e in got["entries"] if e["series"] == "lab"]
    assert all(e["round"] == 1 for e in labs)
    # the roofline block cites the MEASURED gather cell
    roof = got["roofline"]
    assert roof["measured_gather_ns_per_element"] == 100.0
    assert roof["gather_cell"] == "lab_gather_s10_n8_f32"


def test_perf_ledger_pipeline_never_roofline_headline(tmp_path):
    """A round-stamped host-gap record must NOT become the roofline's
    per-chip headline — its e2e rate is the host-limited number, not
    the device bench."""
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "metric": "lr_examples_per_sec", "value": 1000.0,
        "unit": "examples/sec"}))
    (tmp_path / "BENCH_PIPELINE.json").write_text(json.dumps({
        "metric": "pipeline_e2e_examples_per_sec", "value": 50.0,
        "unit": "examples/sec", "round": 99}))
    out = tmp_path / "ledger.json"
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path),
                  "--json", str(out), "--markdown", ""])
    assert r.returncode == 0, r.stderr
    roof = json.loads(out.read_text())["roofline"]
    assert roof["metric"] == "lr_examples_per_sec"


def test_bench_lab_rejects_unknown_dtype(tmp_path):
    r = run_tool(["-m", "xflow_tpu.tools.bench_lab", "--suite", "core",
                  "--table-log2", "7", "--nnz-log2", "6", "--dtypes", "f16",
                  "--row-width", "2", "--iters", "1", "--inner", "1",
                  "--out", str(tmp_path / "o.json")])
    assert r.returncode != 0
    assert "f16" in (r.stderr + r.stdout)


def test_perf_ledger_lab_gates_downward(tmp_path):
    (tmp_path / "BENCH_LAB_r01.json").write_text(
        json.dumps(_lab_record(1.0, rnd=1)))
    (tmp_path / "BENCH_LAB_r02.json").write_text(
        json.dumps(_lab_record(0.9, rnd=2)))  # faster: no regression
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path),
                  "--regress", "--markdown", ""])
    assert r.returncode == 0, r.stderr
    (tmp_path / "BENCH_LAB_r02.json").write_text(
        json.dumps(_lab_record(10.0, rnd=2)))  # 10x slower: regression
    r = run_tool([tool("perf_ledger.py"), "--root", str(tmp_path),
                  "--regress", "--markdown", ""])
    assert r.returncode == 3
    assert "lab_gather" in r.stderr


# -------------------------------------------------------------- smoke gate


def test_smoke_hotpath_script(tmp_path):
    """The hot-path CI gate end to end (tools/smoke_hotpath.sh):
    profiled run -> --check/--health -> pipeline_attrib coverage >= 95%
    -> zero-overhead-off -> lab sweep -> both records through the
    ledger -> lab regression mechanics."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_hotpath.sh"),
         str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "smoke_hotpath: OK" in r.stdout
    # the datapoints stayed in the workdir (never the repo root from a
    # test run) and went through the ledger path
    assert (tmp_path / "BENCH_PIPELINE.json").exists()
    assert (tmp_path / "BENCH_LAB.json").exists()
    assert (tmp_path / "ledger.md").exists()
