import numpy as np

from xflow_tpu.hashing import FNV_OFFSET, fnv1a64, hash_token, slot_of, slots_of


def test_fnv1a64_known_vectors():
    # canonical FNV-1a 64 test vectors (salt 0)
    assert fnv1a64(b"") == 0xCBF29CE484222325
    assert fnv1a64(b"a") == 0xAF63DC4C8601EC8C
    assert fnv1a64(b"foobar") == 0x85944171F73967E8


def test_salt_changes_hash():
    assert fnv1a64(b"1163", salt=0) != fnv1a64(b"1163", salt=1)


def test_hash_token_matches_bytes():
    assert hash_token("1163") == fnv1a64(b"1163")


def test_slot_range_and_determinism():
    for log2 in (4, 18, 22, 30, 33):
        s = slot_of(fnv1a64(b"9999"), log2)
        assert 0 <= s < (1 << log2)
        assert s == slot_of(fnv1a64(b"9999"), log2)


def test_slots_of_vectorized_matches_scalar():
    keys = np.array([fnv1a64(str(i).encode()) for i in range(1000)], dtype=np.uint64)
    vec = slots_of(keys, 18)
    for i in range(1000):
        assert vec[i] == slot_of(int(keys[i]), 18)


def test_slot_distribution_roughly_uniform():
    keys = np.array([fnv1a64(str(i).encode()) for i in range(20000)], dtype=np.uint64)
    s = slots_of(keys, 6)  # 64 buckets
    counts = np.bincount(s, minlength=64)
    assert counts.min() > 0.5 * counts.mean()
    assert counts.max() < 1.5 * counts.mean()


def test_hash_int_tokens_matches_scalar():
    from xflow_tpu.hashing import hash_int_tokens, hash_token

    vals = np.array(
        [0, 1, 9, 10, 99, 100, 999, 1000, 123456, 999999999, 10**9, 10**12,
         10**15, 10**15 + 1, 10**16, 10**19, 2**64 - 1],
        np.uint64,
    )
    for salt in (0, 12345):
        got = hash_int_tokens(vals, salt)
        want = np.array(
            [hash_token(str(int(v)), salt) for v in vals], np.uint64
        )
        np.testing.assert_array_equal(got, want)


def test_hash_int_tokens_random_parity():
    from xflow_tpu.hashing import hash_int_tokens, hash_token

    rng = np.random.default_rng(0)
    vals = rng.integers(0, 20_000_000, 2000).astype(np.uint64)
    got = hash_int_tokens(vals)
    want = np.array([hash_token(str(int(v))) for v in vals], np.uint64)
    np.testing.assert_array_equal(got, want)
