"""Request-path distributed tracing tests (xflow_tpu/tracing.py,
tools/request_trace.py, docs/OBSERVABILITY.md "Request tracing").

Layered like the serving tests: the tracer core on fake appenders
first (deterministic head sampling, tail-force verdicts, the
shared-batch-span dedup, bounded buffers), then JSONL rotation, the
span emission of a real ServeApp + Router against fake replicas (no
checkpoint or device anywhere near them), cross-stream assembly from
fixture spans (a retried request spanning two replicas, a hedged
request whose losing leg is orphaned), the critical-path math against
a hand-built oracle, the Chrome export shape, the metrics_report span
gates, serve_bench's trace-id round trip, the trainer's checkpoint
spans, and the CI smoke drill (tools/smoke_trace.sh)."""

import json
import os
import subprocess
import sys
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.jsonl import JsonlAppender, read_jsonl
from xflow_tpu.tracing import (
    FORCE_HEADER,
    PARENT_HEADER,
    TRACE_HEADER,
    Tracer,
    clean_id,
    emit_op_span,
    new_id,
    sampled,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))

import request_trace  # noqa: E402  (tools/request_trace.py)


class ListSink:
    """An appender double: records land in a list."""

    def __init__(self):
        self.records = []

    def append(self, rec):
        self.records.append(rec)


# ------------------------------------------------------------ tracer core


def test_sampled_is_deterministic_and_bounded():
    assert sampled("anything", 1.0)
    assert not sampled("anything", 0.0)
    ids = [new_id() for _ in range(4000)]
    frac = sum(sampled(i, 0.25) for i in ids) / len(ids)
    assert 0.19 < frac < 0.31, frac
    # the same id always decides the same way — the zero-coordination
    # property the router/replica agreement depends on
    for i in ids[:100]:
        assert sampled(i, 0.3) == sampled(i, 0.3)


def test_clean_id_rejects_junk():
    assert clean_id("  abc-DEF_1.2  ") == "abc-DEF_1.2"
    assert clean_id(None) == ""
    assert clean_id("") == ""
    assert clean_id("x" * 65) == ""
    assert clean_id('evil" {injection}') == ""


def test_tracer_head_sampled_trace_emits():
    sink = ListSink()
    tr = Tracer(sink, sample_rate=1.0)
    s = tr.span("t1", "server")
    tr.end(s, status=200)
    assert sink.records == []  # buffered until the verdict
    assert tr.finish("t1")
    assert [r["name"] for r in sink.records] == ["server"]
    rec = sink.records[0]
    assert rec["kind"] == "span" and rec["trace"] == "t1"
    assert rec["status"] == 200 and rec["dur_ms"] >= 0 and rec["t0"] > 0


def test_tracer_unsampled_trace_drops_unless_forced():
    # find an id the head sampler rejects at a tiny rate
    tid = next(i for i in (new_id() for _ in range(100))
               if not sampled(i, 1e-9))
    sink = ListSink()
    tr = Tracer(sink, sample_rate=1e-9)
    tr.end(tr.span(tid, "server"))
    assert not tr.finish(tid)
    assert sink.records == []
    # the same shape again, but the tail verdict forces it
    tr.end(tr.span(tid + "b", "server"))
    assert tr.finish(tid + "b", force=True)
    assert len(sink.records) == 1


def test_tracer_shared_batch_span_emits_exactly_once():
    sink = ListSink()
    tr = Tracer(sink, sample_rate=1.0)
    batch = {"kind": "span", "trace": "a", "span": "B", "name": "device_batch",
             "t0": 1.0, "dur_ms": 2.0}
    tr.add_shared(batch, ["a", "b"])
    tr.end(tr.span("a", "server"))
    tr.end(tr.span("b", "server"))
    tr.finish("a")
    tr.finish("b")
    assert sum(1 for r in sink.records if r["name"] == "device_batch") == 1
    # the emitted copy dropped the internal dedup marker
    emitted = next(r for r in sink.records if r["name"] == "device_batch")
    assert "_shared" not in emitted


def test_tracer_late_span_follows_recorded_verdict():
    """A hedge leg losing the race lands its span AFTER the request's
    verdict — an emitted trace keeps it, a dropped one drops it."""
    sink = ListSink()
    tr = Tracer(sink, sample_rate=1.0)
    tr.end(tr.span("t", "request"))
    tr.finish("t")
    tr.add("t", {"kind": "span", "trace": "t", "span": "x", "name": "attempt",
                 "t0": 1.0, "dur_ms": 5.0})
    assert sum(1 for r in sink.records if r["name"] == "attempt") == 1
    tid = next(i for i in (new_id() for _ in range(100))
               if not sampled(i, 1e-9))
    tr2 = Tracer(sink, sample_rate=1e-9)
    tr2.end(tr2.span(tid, "request"))
    tr2.finish(tid)
    n = len(sink.records)
    tr2.add(tid, {"kind": "span", "trace": tid, "span": "y",
                  "name": "attempt", "t0": 1.0, "dur_ms": 5.0})
    assert len(sink.records) == n  # dropped trace stays dropped


def test_tracer_pending_buffer_is_bounded():
    """A trace whose finish never comes (a leaked id) must not grow
    the process: oldest pending traces evict."""
    sink = ListSink()
    tr = Tracer(sink, sample_rate=1.0, max_pending=8)
    for k in range(100):
        tr.end(tr.span(f"leak{k}", "server"))
    assert tr.pending_traces() <= 8


def test_emit_op_span_is_unconditional():
    sink = ListSink()
    rec = emit_op_span(sink, "checkpoint_save", 123.0, 0.5, step=10,
                       bytes=2048)
    assert sink.records == [rec]
    assert rec["name"] == "checkpoint_save" and rec["dur_ms"] == 500.0
    assert rec["step"] == 10 and rec["bytes"] == 2048
    assert rec["trace"] and rec["span"]


# ---------------------------------------------------------- JSONL rotation


def test_rotation_rolls_and_reader_folds_in_order(tmp_path):
    path = str(tmp_path / "rot.jsonl")
    app = JsonlAppender(path, stamp={"rank": 0, "run_id": "r"}, max_bytes=500)
    for k in range(12):
        app.append({"kind": "x", "k": k})
    app.close()
    assert os.path.exists(path + ".1")
    # both files individually under ~the cap, and the fold reads OLD
    # records first so file order (and every order-sensitive report
    # gate) survives the roll
    recs = read_jsonl(path)
    ks = [r["k"] for r in recs]
    assert ks == sorted(ks) and ks[-1] == 11
    assert len(read_jsonl(path + ".1", warn=False)) + len(
        read_jsonl(path, fold_rotated=False)
    ) == len(recs)


def test_rotation_keeps_locked_append_contract(tmp_path):
    """Concurrent appenders through one rolling sink: every line in
    the live + rolled files parses (no interleaved/torn lines)."""
    path = str(tmp_path / "conc.jsonl")
    app = JsonlAppender(path, stamp={"rank": 0, "run_id": "r"},
                        max_bytes=4096)
    def worker(tag):
        for k in range(50):
            app.append({"kind": "x", "tag": tag, "k": k})
    threads = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    app.close()
    for f in (path + ".1", path):
        if os.path.exists(f):
            for line in open(f):
                json.loads(line)  # raises on a torn line


def test_unrotated_reads_are_untouched(tmp_path):
    path = str(tmp_path / "plain.jsonl")
    app = JsonlAppender(path, stamp={"rank": 0, "run_id": "r"})
    app.append({"kind": "x"})
    app.close()
    assert len(read_jsonl(path)) == 1


# -------------------------------------------------- server-side span wiring


class FakeGen:
    gen = 1
    step = 20


class FakeRunner:
    generation = FakeGen()
    compile_recorder = None
    span_sink = None

    def predict(self, arrays):
        n = arrays["row_mask"].shape[0]
        return np.full((n,), 0.5, np.float32), self.generation


def _app_cfg(tmp_path, **extra):
    base = {
        "data.log2_slots": 12, "data.max_nnz": 8, "model.num_fields": 5,
        "serve.window_ms": 1.0, "serve.max_batch": 8,
        "serve.metrics_path": str(tmp_path / "serve.jsonl"),
        "serve.metrics_every_s": 0.2,
        "serve.trace_sample_rate": 1.0,
    }
    base.update(extra)
    return override(Config(), **base)


BODY = json.dumps({"rows": ["0:a 1:b", "2:c"]}).encode()


def test_server_emits_linked_span_tree(tmp_path):
    from xflow_tpu.serve.server import ServeApp

    app = ServeApp(_app_cfg(tmp_path), FakeRunner())
    app.start()
    try:
        tid = new_id()
        status, _ = app.handle_predict(BODY, trace_id=tid)
        assert status == 200
    finally:
        app.close()
    spans = [r for r in read_jsonl(str(tmp_path / "serve.jsonl"))
             if r.get("kind") == "span"]
    names = sorted(s["name"] for s in spans)
    assert names == ["device", "device_batch", "parse", "queue", "server"]
    root = next(s for s in spans if s["name"] == "server")
    assert "parent" not in root and root["trace"] == tid
    by_name = {s["name"]: s for s in spans}
    # parse/queue/device all parent to the server span; device links
    # the shared batch span by id (the batch-membership join)
    for child in ("parse", "queue", "device"):
        assert by_name[child]["parent"] == root["span"]
    assert by_name["device"]["batch"] == by_name["device_batch"]["span"]
    assert by_name["device_batch"]["flush"] in ("window", "size")
    assert by_name["device_batch"]["rows"] == 2


def test_server_rate_zero_is_byte_identical(tmp_path):
    """The acceptance pin: trace_sample_rate=0 leaves the serve JSONL
    exactly as a pre-tracing build wrote it — no span records, no new
    keys — even when the client sends a trace id."""
    from xflow_tpu.serve.server import ServeApp

    app = ServeApp(
        _app_cfg(tmp_path, **{"serve.trace_sample_rate": 0.0}), FakeRunner()
    )
    app.start()
    try:
        status, _ = app.handle_predict(BODY, trace_id=new_id())
        assert status == 200
    finally:
        app.close()
    recs = read_jsonl(str(tmp_path / "serve.jsonl"))
    assert recs, "serve windows should still flush"
    assert not [r for r in recs if r.get("kind") == "span"]
    assert not [r for r in recs if "trace" in r]


def test_server_tail_captures_errors_despite_head_drop(tmp_path):
    """A 400 at a near-zero sample rate still lands on disk — the
    tail-capture contract."""
    from xflow_tpu.serve.server import ServeApp

    tid = next(i for i in (new_id() for _ in range(200))
               if not sampled(i, 1e-9))
    app = ServeApp(
        _app_cfg(tmp_path, **{"serve.trace_sample_rate": 1e-9}), FakeRunner()
    )
    app.start()
    try:
        status, _ = app.handle_predict(b"not json", trace_id=tid)
        assert status == 400
        # a 200 under the same rate drops (head sampling holds)
        ok_tid = next(i for i in (new_id() for _ in range(200))
                      if not sampled(i, 1e-9))
        status, _ = app.handle_predict(BODY, trace_id=ok_tid)
        assert status == 200
    finally:
        app.close()
    spans = [r for r in read_jsonl(str(tmp_path / "serve.jsonl"))
             if r.get("kind") == "span"]
    assert [s["trace"] for s in spans] == [tid]
    assert spans[0]["status"] == 400


# -------------------------------------------------- router-side span wiring


class EchoReplica:
    """A header-recording fake replica: answers /predict 200 (or a
    scripted failure budget) and records the tracing headers each
    forward carried."""

    def __init__(self, fail_first: int = 0):
        self.fail_first = fail_first
        self.seen_headers = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, status, payload):
                data = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                tid = self.headers.get(TRACE_HEADER)
                if tid:
                    self.send_header(TRACE_HEADER, tid)
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                outer.seen_headers.append({
                    k: self.headers.get(k)
                    for k in (TRACE_HEADER, PARENT_HEADER, FORCE_HEADER)
                })
                n = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(n)) if n else {}
                if outer.fail_first > 0:
                    outer.fail_first -= 1
                    self._reply(503, {"error": "scripted shed"})
                    return
                self._reply(200, {
                    "pctr": [0.5] * len(body.get("rows", [])),
                    "generation": 1, "step": 20,
                })

            def do_GET(self):
                self._reply(200, {"ok": True})

            def log_message(self, fmt, *args):
                pass

        class Server(ThreadingHTTPServer):
            daemon_threads = True

        self.srv = Server(("127.0.0.1", 0), Handler)
        self.port = self.srv.server_address[1]
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()

    def close(self):
        self.srv.shutdown()
        self.srv.server_close()


def _traced_router(replicas, tmp_path, rate=1.0, **kw):
    from xflow_tpu.serve.router import Backend, Router

    app = JsonlAppender(str(tmp_path / "router.jsonl"),
                        stamp={"rank": -1, "run_id": "trace-test"})
    kw.setdefault("health_poll_s", 30.0)
    return Router(
        [Backend(i, "127.0.0.1", r.port) for i, r in enumerate(replicas)],
        appender=app,
        tracer=Tracer(app, sample_rate=rate, slow_ms=kw.pop("slow_ms", 250.0)),
        **kw,
    )


def test_router_retry_spans_and_force_propagation(tmp_path):
    """A retried request: root + one attempt per leg, the retry leg
    carrying X-Trace-Force to the replica (the replica cannot know the
    router's verdict), and the whole trace emitted even at a
    never-sample rate — retries are tail exemplars."""
    shedding, ok = EchoReplica(fail_first=10), EchoReplica()
    # backend order matters: pick() round-robins starting at index 1,
    # so the shedding replica sits there to take the primary leg
    router = _traced_router([ok, shedding], tmp_path, rate=1e-9,
                            deadline_ms=5000, retries=2)
    try:
        tid = next(i for i in (new_id() for _ in range(200))
                   if not sampled(i, 1e-9))
        status, _ = router.handle_predict(BODY, headers={TRACE_HEADER: tid})
        assert status == 200
    finally:
        router.close()
        shedding.close()
        ok.close()
    spans = [r for r in read_jsonl(str(tmp_path / "router.jsonl"), warn=False)
             if r.get("kind") == "span"]
    roots = [s for s in spans if s["name"] == "request"]
    attempts = sorted(
        (s for s in spans if s["name"] == "attempt"),
        key=lambda s: s["t0"],
    )
    assert len(roots) == 1 and roots[0]["trace"] == tid
    assert len(attempts) == 2
    assert attempts[0]["status"] == 503 and attempts[0]["leg"] == "primary"
    assert attempts[1]["status"] == 200 and attempts[1]["leg"] == "retry"
    assert all(a["parent"] == roots[0]["span"] for a in attempts)
    # header propagation: every forward carried the id + its attempt
    # span as parent; only the retry leg was forced
    seen = shedding.seen_headers + ok.seen_headers
    assert all(h[TRACE_HEADER] == tid for h in seen)
    parents = {a["span"] for a in attempts}
    assert {h[PARENT_HEADER] for h in seen} <= parents
    assert ok.seen_headers[-1][FORCE_HEADER] == "1"
    assert shedding.seen_headers[0][FORCE_HEADER] is None


def test_router_untraced_request_forwards_bare(tmp_path):
    """No X-Trace-Id in, tracing effectively off for the request: no
    spans, no tracing headers on the forward."""
    ok = EchoReplica()
    router = _traced_router([ok], tmp_path, rate=1.0, deadline_ms=2000)
    try:
        status, _ = router.handle_predict(BODY, headers={})
        assert status == 200
    finally:
        router.close()
        ok.close()
    # no spans at all: the lazy appender never even created the file
    assert not os.path.exists(tmp_path / "router.jsonl")
    assert ok.seen_headers[0][TRACE_HEADER] is None


def test_router_http_front_end_mints_and_echoes_id(tmp_path):
    """A client without an id gets one minted at the router and echoed
    in the response header — the fleet's id birthplace."""
    import http.client

    from xflow_tpu.serve.router import make_router_http_server

    ok = EchoReplica()
    router = _traced_router([ok], tmp_path, rate=1.0, deadline_ms=2000)
    srv = make_router_http_server(router, "127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        c = http.client.HTTPConnection("127.0.0.1", srv.server_address[1],
                                       timeout=10)
        c.request("POST", "/predict", BODY,
                  {"Content-Type": "application/json"})
        resp = c.getresponse()
        minted = resp.getheader(TRACE_HEADER)
        resp.read()
        assert resp.status == 200 and minted
        # a client-sent id wins and echoes back verbatim
        sent = new_id()
        c.request("POST", "/predict", BODY,
                  {"Content-Type": "application/json", TRACE_HEADER: sent})
        resp = c.getresponse()
        assert resp.getheader(TRACE_HEADER) == sent
        resp.read()
        c.close()
    finally:
        srv.shutdown()
        srv.server_close()
        router.close()
        ok.close()
    spans = [r for r in read_jsonl(str(tmp_path / "router.jsonl"), warn=False)
             if r.get("kind") == "span"]
    assert {s["trace"] for s in spans if s["name"] == "request"} == {minted, sent}


# ------------------------------------------------- assembly + critical path


def _span(trace, span, name, t0, dur_ms, parent=None, **attrs):
    rec = {"kind": "span", "trace": trace, "span": span, "name": name,
           "t0": t0, "dur_ms": dur_ms, **attrs}
    if parent:
        rec["parent"] = parent
    return rec


def _oracle_trace(trace="t-oracle"):
    """A hand-built retried request spanning two replicas, with exact
    durations the critical-path math must reproduce."""
    return [
        _span(trace, "R", "request", 100.000, 100.0, status=200),
        _span(trace, "A1", "attempt", 100.001, 20.0, parent="R",
              status=503, leg="primary", backend=0),
        # the losing replica's side: a real server span on replica 0
        _span(trace, "S1", "server", 100.002, 18.0, parent="A1",
              status=503, replica=0, rank=0),
        _span(trace, "A2", "attempt", 100.030, 65.0, parent="R",
              status=200, leg="retry", backend=1),
        _span(trace, "S2", "server", 100.032, 60.0, parent="A2",
              status=200, replica=1, rank=1),
        _span(trace, "P", "parse", 100.033, 5.0, parent="S2", replica=1),
        _span(trace, "Q", "queue", 100.038, 20.0, parent="S2", replica=1),
        _span(trace, "D", "device", 100.058, 30.0, parent="S2",
              batch="B", replica=1),
    ], [
        _span(trace, "B", "device_batch", 100.058, 30.0, flush="size",
              requests=3, rows=6, batch_fill=0.75, replica=1),
    ]


def test_critical_path_matches_oracle():
    req, batch = _oracle_trace()
    trees = request_trace.assemble(req)
    rows = request_trace.decompose(trees, batch)
    assert len(rows) == 1
    r = rows[0]
    assert r["complete"] and r["status"] == 200 and r["replica"] == 1
    assert r["total_ms"] == pytest.approx(100.0)
    assert r["retry"] == pytest.approx(30.0, abs=1e-6)     # winner t0 - root t0
    assert r["network"] == pytest.approx(5.0)              # attempt - server
    assert r["parse"] == pytest.approx(5.0)
    assert r["queue"] == pytest.approx(20.0)               # size flush
    assert r["window"] == pytest.approx(0.0)
    assert r["device"] == pytest.approx(30.0)
    assert r["server_other"] == pytest.approx(5.0)         # 60 - 55
    assert r["router_other"] == pytest.approx(5.0)         # 100 - 30 - 65
    summary = request_trace.summarize(rows)
    assert summary["complete_frac"] == 1.0
    assert summary["per_replica"][1]["requests"] == 1


def test_window_flush_attributes_to_window_category():
    req, batch = _oracle_trace()
    batch[0]["flush"] = "window"
    rows = request_trace.decompose(request_trace.assemble(req), batch)
    assert rows[0]["window"] == pytest.approx(20.0)
    assert rows[0]["queue"] == pytest.approx(0.0)


def test_hedged_losing_leg_orphan_is_tolerated():
    """The losing hedge leg's replica-side spans whose router attempt
    never emitted: orphaned, counted, and the winner's path still
    assembles complete."""
    trace = "t-hedge"
    req = [
        _span(trace, "R", "request", 10.0, 50.0, status=200),
        _span(trace, "A1", "attempt", 10.001, 48.0, parent="R",
              status=200, leg="primary", backend=0),
        _span(trace, "S1", "server", 10.002, 40.0, parent="A1",
              status=200, replica=0),
        _span(trace, "P1", "parse", 10.003, 1.0, parent="S1", replica=0),
        _span(trace, "Q1", "queue", 10.004, 2.0, parent="S1", replica=0),
        _span(trace, "D1", "device", 10.006, 30.0, parent="S1",
              batch="B1", replica=0),
        # the losing leg: its parent attempt span was never emitted
        _span(trace, "S2", "server", 10.020, 35.0, parent="A-GONE",
              status=200, replica=1),
    ]
    batch = [_span(trace, "B1", "device_batch", 10.006, 30.0,
                   flush="window", replica=0)]
    trees = request_trace.assemble(req)
    tree = trees[trace]
    assert [s["span"] for s in tree.orphans] == ["S2"]
    assert len(tree.roots) == 1
    rows = request_trace.decompose(trees, batch)
    assert rows[0]["complete"]
    assert rows[0]["replica"] == 0  # the WINNING replica gets the blame row


def test_assembly_from_files_cross_stream(tmp_path):
    """The CLI path: spans scattered over router + two replica files
    (as a fleet writes them) assemble back into complete trees and the
    gate/--json/--chrome surfaces all work."""
    req, batch = _oracle_trace()
    by_file = {"serve_router.jsonl": [], "serve_replica0.jsonl": [],
               "serve_replica1.jsonl": []}
    for s in req + batch:
        rep = s.get("replica")
        f = ("serve_router.jsonl" if rep is None
             else f"serve_replica{rep}.jsonl")
        by_file[f].append(s)
    for f, recs in by_file.items():
        with open(tmp_path / f, "w") as fh:
            for r in recs:
                fh.write(json.dumps(
                    {"ts": r["t0"], "rank": r.get("rank", -1),
                     "run_id": "fix", **r}
                ) + "\n")
    out = tmp_path / "report.json"
    chrome = tmp_path / "chrome.json"
    rc = request_trace.main([
        str(tmp_path), "--json", str(out), "--chrome", str(chrome),
        "--min-complete", "0.99",
    ])
    assert rc == 0
    rep = json.loads(out.read_text())
    assert rep["complete"] == 1 and rep["complete_frac"] == 1.0
    assert rep["exemplars"]["p99"]["trace"] == "t-oracle"
    events = json.loads(chrome.read_text())["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    assert len(xs) == len(req) + len(batch)
    assert all(isinstance(e["pid"], int) and e["ts"] >= 0 for e in xs)
    names = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert {"replica 0", "replica 1"} <= names


def test_timeline_overlays_ops_on_requests():
    req, batch = _oracle_trace()
    trees = request_trace.assemble(req)
    rows = request_trace.decompose(trees, batch)
    for r in rows:
        r["t0_wall"] = trees[r["trace"]].root["t0"]
    ops = [_span("op1", "O1", "reload", 100.050, 80.0, step=50,
                 generation=2, bytes=4096, replica=1)]
    text = request_trace.render_timeline(rows, ops)
    assert "reload" in text and "step=50" in text
    assert "worst" in text


# --------------------------------------------------- metrics_report gates


def _report(tmp_path, records, name="stream.jsonl"):
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import metrics_report

    path = tmp_path / name
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    files = [str(path)]
    streams, _ = metrics_report.load_streams(files)
    return metrics_report.check_streams(streams, files)


def _stamped(rec, rank=0, replica=None):
    out = {"ts": rec.get("t0", 1.0), "rank": rank, "run_id": "r", **rec}
    if replica is not None:
        out["replica"] = replica
    return out


def test_check_passes_valid_span_streams(tmp_path):
    """A fleet-shaped layout — router spans in a rank=-1 stream, each
    replica's spans in its own replica-stamped stream — passes every
    span gate."""
    import metrics_report

    req, batch = _oracle_trace()
    files = []
    by_file: dict = {}
    for s in req + batch:
        rep = s.get("replica")
        rank = -1 if rep is None else rep
        rec = {"ts": s["t0"], "rank": rank, "run_id": "r", **s}
        by_file.setdefault(f"f{rank}.jsonl", []).append(rec)
    for fname, recs in by_file.items():
        path = tmp_path / fname
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        files.append(str(path))
    streams, _ = metrics_report.load_streams(files)
    problems = metrics_report.check_streams(streams, files)
    assert problems == [], problems


def test_check_flags_missing_span_keys(tmp_path):
    bad = _stamped({"kind": "span", "trace": "t", "name": "server"})
    problems = _report(tmp_path, [bad])
    assert any("span keys" in p for p in problems)


def test_check_flags_two_roots_in_one_trace(tmp_path):
    recs = [
        _stamped(_span("t", "R1", "request", 1.0, 5.0, status=200)),
        _stamped(_span("t", "S1", "server", 1.0, 4.0, status=200)),
    ]
    problems = _report(tmp_path, recs)
    assert any("parent to one root" in p for p in problems)


def test_check_flags_unreferenced_batch_span(tmp_path):
    recs = [
        _stamped(_span("t", "R", "server", 1.0, 5.0, status=200)),
        _stamped(_span("t", "B", "device_batch", 1.0, 2.0, flush="size")),
    ]
    problems = _report(tmp_path, recs)
    assert any("batch-membership" in p for p in problems)


def test_check_flags_span_stream_mixing_replicas(tmp_path):
    recs = [
        _stamped(_span("t1", "S1", "server", 1.0, 5.0, status=200),
                 replica=0),
        _stamped(_span("t2", "S2", "server", 2.0, 5.0, status=200),
                 replica=1),
    ]
    problems = _report(tmp_path, recs)
    assert any("mixes replica stamps" in p for p in problems)


def test_health_renders_queue_vs_device_split(tmp_path):
    import metrics_report

    window = {
        "ts": 1.0, "rank": 0, "run_id": "r", "kind": "serve",
        "requests": 10, "rows": 10, "qps": 5.0, "rows_per_s": 5.0,
        "batches": 2, "batch_fill": 0.5,
        "queue_wait_p50_ms": 1.0, "queue_wait_p99_ms": 9.0,
        "device_p50_ms": 1.0, "device_p99_ms": 2.0,
        "total_p50_ms": 2.0, "total_p99_ms": 11.0, "window_s": 2.0,
        "bad_requests": 0, "shed_requests": 0, "generation": 1, "step": 20,
        "replica": 1,
    }
    path = tmp_path / "serve.jsonl"
    path.write_text(json.dumps(window) + "\n")
    streams, _ = metrics_report.load_streams([str(path)])
    text = metrics_report.render_health(streams)
    assert "queue-wait vs device p99" in text
    assert "queue-wait-bound" in text  # 9.0 > 2.0


# -------------------------------------------------- serve_bench round trip


def test_serve_bench_trace_round_trip(tmp_path):
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import serve_bench

    rep = EchoReplica()
    out = tmp_path / "bench.json"
    try:
        rc = serve_bench.main([
            "--url", f"http://127.0.0.1:{rep.port}", "--duration", "1.2",
            "--concurrency", "2", "--trace", "--trace-sample-rate", "0.01",
            "--bench-json", str(out),
        ])
    finally:
        rep.close()
    rec = json.loads(out.read_text())
    assert rc == 0, rec
    assert rec["traced"] is True
    assert rec["trace_sample_rate"] == 0.01
    assert rec["trace_echo_miss"] == 0
    assert rec["requests"] > 0 and rec["errors"] == 0
    # every forward carried an id (fresh per request)
    ids = [h[TRACE_HEADER] for h in rep.seen_headers]
    assert all(ids) and len(set(ids)) == len(ids)


def test_serve_bench_flags_missing_echo(tmp_path):
    """A server that answers 200 but drops the id: the round-trip
    gate fails the run."""

    class NoEcho(EchoReplica):
        pass

    rep = NoEcho()
    # strip the echo by monkey-patching the handler class's _reply
    handler_cls = rep.srv.RequestHandlerClass

    def _reply(self, status, payload):
        data = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    handler_cls._reply = _reply
    import serve_bench

    try:
        rc = serve_bench.main([
            "--url", f"http://127.0.0.1:{rep.port}", "--duration", "0.8",
            "--concurrency", "1", "--trace",
        ])
    finally:
        rep.close()
    assert rc == 1


# ---------------------------------------------------- trainer ckpt spans


def test_trainer_checkpoint_spans(tmp_path):
    from xflow_tpu.data.synth import generate_shards
    from xflow_tpu.train.trainer import Trainer

    generate_shards(str(tmp_path / "train"), 1, 64, num_fields=5,
                    ids_per_field=20, seed=0)
    cfg = override(Config(), **{
        "data.train_path": str(tmp_path / "train"),
        "data.batch_size": 32, "data.log2_slots": 10, "data.max_nnz": 8,
        "model.num_fields": 5, "train.pred_dump": False,
        "train.checkpoint_dir": str(tmp_path / "ck"),
        "train.metrics_path": str(tmp_path / "metrics.jsonl"),
    })
    t = Trainer(cfg)
    t.save_checkpoint()
    t2 = Trainer(cfg)
    assert t2.maybe_restore()
    t.metrics.close()
    t2.metrics.close()
    spans = [r for r in read_jsonl(str(tmp_path / "metrics.jsonl"))
             if r.get("kind") == "span"]
    names = [s["name"] for s in spans]
    assert "checkpoint_save" in names and "checkpoint_restore" in names
    for s in spans:
        assert s["bytes"] > 0 and s["dur_ms"] >= 0 and "step" in s

    # off = byte-identical metrics stream (no span records)
    cfg_off = override(cfg, **{
        "train.ckpt_spans": False,
        "train.metrics_path": str(tmp_path / "metrics_off.jsonl"),
        "train.checkpoint_dir": str(tmp_path / "ck_off"),
    })
    t3 = Trainer(cfg_off)
    t3.save_checkpoint()
    t3.metrics.close()
    recs = read_jsonl(str(tmp_path / "metrics_off.jsonl"), warn=False) \
        if os.path.exists(tmp_path / "metrics_off.jsonl") else []
    assert not [r for r in recs if r.get("kind") == "span"]


# ------------------------------------------------------------ CI smoke gate


def test_smoke_trace_script(tmp_path):
    """The tracing CI drill end to end (tools/smoke_trace.sh): train ->
    2-replica fleet with a fault-injected slow replica -> traced bench
    through the router -> request_trace reconstructs >=99% complete
    trees and blames the slow replica's hop -> metrics_report --check
    green -> BENCH_TRACE.json through perf_ledger."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_trace.sh"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=570, env=env,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "smoke_trace: OK" in r.stdout
    bench = json.load(open(tmp_path / "BENCH_TRACE.json"))
    assert bench["metric"] == "serve_qps" and bench["value"] > 0
    assert bench["traced"] is True and bench["trace_echo_miss"] == 0
    assert "qps_untraced" in bench and "trace_overhead_pct" in bench
