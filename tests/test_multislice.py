"""Multi-slice bounded-staleness sync suite (docs/DISTRIBUTED.md
"Multi-slice bounded staleness", docs/ROBUSTNESS.md "Slice lost
mid-sync"): the delta model's convergence algebra, the staleness
policies (wait vs proceed, both bounded), membership-driven wait
release, the rejoin catch-up paths (snapshot adoption + the
no-snapshot fast-forward), and the K=0 bitwise guarantee — sync.mode
off and sync must produce the identical model for a single slice.

The end-to-end acceptance drill — 2 emulated slices, kill one at a
sync round, survivor continues degraded, relaunch rejoins via snapshot
catch-up with exact example accounting — runs in
tools/smoke_multislice.sh (wired below); the parity sweep over
K in {1, 8, 64} is the slow-marked launch matrix at the bottom.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.data.synth import generate_shards
from xflow_tpu.models import get_model
from xflow_tpu.optim import get_optimizer
from xflow_tpu.parallel.multislice import (
    SliceSyncer,
    read_membership,
    slice_forward_args,
    write_membership,
)
from xflow_tpu.testing.faults import sync_faults_from_env
from xflow_tpu.train import init_state
from xflow_tpu.train.trainer import Trainer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def sync_cfg(tmp_path, **kw):
    base = {
        "sync.mode": "bounded",
        "sync.dir": str(tmp_path / "sync"),
        "sync.staleness_k": 1,
        "sync.on_stale": "proceed",
        "sync.timeout_s": 0.2,
        "sync.retries": 0,
        "sync.backoff_s": 0.0,
        "sync.snapshot_every": 1000,  # off unless a test asks
    }
    base.update(kw)
    return override(Config(), **base).sync


def tiny_state(seed=0):
    cfg = override(Config(), **{"data.log2_slots": 6})
    return cfg, init_state(get_model("lr"), get_optimizer("sgd"), cfg, seed=seed)


def bump(state, delta):
    """A fake local training block: every table leaf moves by `delta`."""
    return state._replace(
        tables={k: v + delta for k, v in state.tables.items()}
    )


# ------------------------------------------------------------- membership
def test_membership_defensive_read(tmp_path):
    # missing file: everyone is live (never fail-stop on bookkeeping)
    assert read_membership(str(tmp_path), 3) == {0, 1, 2}
    write_membership(str(tmp_path), {0, 2}, run_id="r", note="t")
    assert read_membership(str(tmp_path), 3) == {0, 2}
    # out-of-range ids are filtered, an empty result falls back to all
    write_membership(str(tmp_path), {7}, run_id="r", note="t")
    assert read_membership(str(tmp_path), 3) == {0, 1, 2}
    # corrupt json: everyone is live
    with open(os.path.join(str(tmp_path), "membership.json"), "w") as f:
        f.write("{nope")
    assert read_membership(str(tmp_path), 3) == {0, 1, 2}


def test_sync_fault_env_parsing(monkeypatch):
    for var in ("XFLOW_FAULT_SLICE_KILL_ROUND", "XFLOW_FAULT_SYNC_DELAY_S",
                "XFLOW_FAULT_SLICE", "XFLOW_FAULT_SLICE_KILL_GEN",
                "XFLOW_SLICE", "XFLOW_RESTART_GEN"):
        monkeypatch.delenv(var, raising=False)
    assert sync_faults_from_env() == (0, 0.0)
    monkeypatch.setenv("XFLOW_FAULT_SLICE_KILL_ROUND", "3")
    monkeypatch.setenv("XFLOW_FAULT_SYNC_DELAY_S", "0.25")
    assert sync_faults_from_env() == (3, 0.25)
    # targeted at another slice: both injectors disarm
    monkeypatch.setenv("XFLOW_FAULT_SLICE", "1")
    monkeypatch.setenv("XFLOW_SLICE", "0")
    assert sync_faults_from_env() == (0, 0.0)
    monkeypatch.setenv("XFLOW_SLICE", "1")
    assert sync_faults_from_env() == (3, 0.25)
    # the kill is generation-gated: the relaunch must rejoin, not re-die
    monkeypatch.setenv("XFLOW_RESTART_GEN", "1")
    kill, delay = sync_faults_from_env()
    assert kill == 0 and delay == 0.25


def test_slice_forward_args_substitution():
    out = slice_forward_args(
        ["--train", "/d/tr_s{slice}", "--epochs", "2"], 1
    )
    assert out == ["--train", "/d/tr_s1", "--epochs", "2"]


# ------------------------------------------------------- the delta algebra
def test_single_slice_passthrough_is_the_same_object(tmp_path):
    """No peers -> no merge -> the state OBJECT passes through: the
    strongest possible form of the K=0 bitwise guarantee (a float
    round-trip base + (local - base) would already break it)."""
    _, st = tiny_state()
    s = SliceSyncer(sync_cfg(tmp_path, **{"sync.mode": "sync"}), 0, 1)
    s.attach(st)
    st1 = bump(st, 1.0)
    st2, rec = s.sync(st1)
    assert st2 is st1
    assert rec["round"] == 1 and rec["k"] == 0 and rec["applied"] == 0
    st3, rec = s.sync(st2)
    assert st3 is st2 and rec["round"] == 2


def test_two_slices_converge_to_the_delta_sum(tmp_path):
    """Local-SGD algebra: both slices end at init + sum(all deltas),
    independent of apply order — exactly the large-batch semantics that
    make additive sync EXACT for sgd."""
    _, stA = tiny_state(seed=0)
    _, stB = tiny_state(seed=0)  # identical seeded init, the contract
    cfg = sync_cfg(tmp_path)
    sA, sB = SliceSyncer(cfg, 0, 2), SliceSyncer(cfg, 1, 2)
    sA.attach(stA)
    sB.attach(stB)
    stA1, recA = sA.sync(bump(stA, 1.0))   # publishes +1, sees nothing
    stB1, recB = sB.sync(bump(stB, 2.0))   # publishes +2, applies +1
    assert recA["applied"] == 0 and recB["applied"] == 1
    # A's round 2 adds nothing locally but folds in B's +2
    stA2, recA2 = sA.sync(stA1)
    assert recA2["applied"] == 1
    want = np.asarray(stA.tables["w"]) + 3.0
    np.testing.assert_allclose(np.asarray(stA2.tables["w"]), want, rtol=0)
    np.testing.assert_allclose(np.asarray(stB1.tables["w"]), want, rtol=0)


def test_sync_requires_attach(tmp_path):
    _, st = tiny_state()
    s = SliceSyncer(sync_cfg(tmp_path), 0, 1)
    with pytest.raises(RuntimeError):
        s.sync(st)


# ------------------------------------------------------ staleness policies
def test_proceed_on_stale_counts_and_continues(tmp_path):
    """k=0 bounded + proceed: a silent peer makes the round STALE
    (counted, lag reported) but never blocks."""
    _, st = tiny_state()
    s = SliceSyncer(
        sync_cfg(tmp_path, **{"sync.staleness_k": 0}), 0, 2
    )
    s.attach(st)
    _, rec = s.sync(bump(st, 1.0))
    assert rec["stale"] == 1 and rec["lags"] == {"1": 1}
    assert rec["timeouts"] == 0  # proceed never waits


def test_wait_on_stale_is_bounded_and_counted(tmp_path):
    _, st = tiny_state()
    s = SliceSyncer(
        sync_cfg(tmp_path, **{
            "sync.staleness_k": 0,
            "sync.on_stale": "wait",
            "sync.timeout_s": 0.05,
            "sync.retries": 1,
        }), 0, 2,
    )
    s.attach(st)
    _, rec = s.sync(bump(st, 1.0))  # returns despite the dead peer
    assert rec["timeouts"] >= 1 and rec["stale"] == 1


def test_membership_releases_the_wait(tmp_path):
    """A peer the launcher declared dead stops being waited on: the
    wait loop re-reads membership every poll. timeout_s is set long so
    a pass proves membership (not the timeout) released it."""
    _, st = tiny_state()
    cfg = sync_cfg(tmp_path, **{
        "sync.mode": "sync", "sync.timeout_s": 60.0, "sync.retries": 0,
    })
    s = SliceSyncer(cfg, 0, 2)
    s.attach(st)
    write_membership(cfg.dir, {0}, run_id="r", note="slice 1 dead")
    _, rec = s.sync(bump(st, 1.0))
    assert rec["live"] == [0] and rec["left"] == [1]
    assert rec["stale"] == 0  # staleness is judged against LIVE peers


def test_dead_peer_committed_deltas_still_apply(tmp_path):
    """Zero-lost-examples: rounds a slice PUBLISHED before dying are
    trained examples — survivors fold them in even after the member
    leaves the group."""
    _, stA = tiny_state(seed=0)
    _, stB = tiny_state(seed=0)
    cfg = sync_cfg(tmp_path)
    sB = SliceSyncer(cfg, 1, 2)
    sB.attach(stB)
    sB.sync(bump(stB, 2.0))  # B publishes round 1, then "dies"
    write_membership(cfg.dir, {0}, run_id="r", note="slice 1 dead")
    sA = SliceSyncer(cfg, 0, 2)
    sA.attach(stA)
    stA1, rec = sA.sync(bump(stA, 1.0))
    assert rec["applied"] == 1 and rec["live"] == [0]
    np.testing.assert_allclose(
        np.asarray(stA1.tables["w"]), np.asarray(stA.tables["w"]) + 3.0,
        rtol=0,
    )


# ------------------------------------------------------------ rejoin paths
def test_adopt_latest_snapshot(tmp_path):
    _, stA = tiny_state(seed=0)
    cfg = sync_cfg(tmp_path, **{"sync.snapshot_every": 1})
    sA = SliceSyncer(cfg, 0, 2)
    sA.attach(stA)
    stA1, _ = sA.sync(bump(stA, 1.0))  # publishes delta + snapshot r1
    _, stB = tiny_state(seed=0)
    sB = SliceSyncer(cfg, 1, 2)
    stB2, adopted = sB.adopt_latest_snapshot(stB)
    assert adopted == (1, 0)
    assert sB._applied[0] == 1 and sB.round == 1  # r1 must not re-apply
    np.testing.assert_allclose(
        np.asarray(stB2.tables["w"]), np.asarray(stA1.tables["w"]), rtol=0
    )
    # the adopted state keeps ITS OWN step counter (example accounting)
    assert int(stB2.step) == int(stB.step)


def test_attach_fast_forwards_without_snapshot(tmp_path, monkeypatch):
    """Death before the first snapshot: the restored checkpoint already
    folded in an unknown prefix of peer deltas, so a gen>0 attach with
    nothing to adopt skips everything already published rather than
    double-applying it."""
    _, stA = tiny_state(seed=0)
    cfg = sync_cfg(tmp_path)  # snapshots off
    sA = SliceSyncer(cfg, 0, 2)
    sA.attach(stA)
    st = bump(stA, 1.0)
    for _ in range(2):
        st, _ = sA.sync(st)
    monkeypatch.setenv("XFLOW_RESTART_GEN", "1")
    _, stB = tiny_state(seed=0)
    sB = SliceSyncer(cfg, 1, 2)
    stB2, adopted = sB.adopt_latest_snapshot(stB)
    assert adopted is None
    sB.attach(stB2)
    assert sB._applied[0] == 2
    stB3, rec = sB.sync(bump(stB2, 5.0))
    assert rec["applied"] == 0  # old rounds skipped, not double-counted


# -------------------------------------------------- K=0 bitwise, end to end
@pytest.fixture
def dataset(tmp_path):
    generate_shards(
        str(tmp_path / "train"), 1, 600, num_fields=5, ids_per_field=30,
        seed=0,
    )
    generate_shards(
        str(tmp_path / "test"), 1, 200, num_fields=5, ids_per_field=30,
        seed=1, truth_seed=0,
    )
    return tmp_path


def _fit_cfg(tmp_path, **kw):
    base = {
        "data.train_path": str(tmp_path / "train"),
        "data.log2_slots": 12,
        "data.batch_size": 100,
        "data.max_nnz": 8,
        "model.num_fields": 5,
        "model.name": "lr",
        "optim.name": "sgd",
        "train.epochs": 1,
        "train.pred_dump": False,
    }
    base.update(kw)
    return override(Config(), **base)


def test_mode_off_and_single_slice_sync_are_bitwise_identical(
    dataset, tmp_path
):
    """The pre-PR semantics gate: sync.mode=off and a single-slice
    sync.mode=sync run (rounds every 2 steps + the final round) produce
    byte-identical final tables — the sync boundary is a no-op when no
    peer delta applies."""
    t_off = Trainer(_fit_cfg(dataset))
    t_off.fit()
    t_sync = Trainer(_fit_cfg(dataset, **{
        "sync.mode": "sync",
        "sync.dir": str(tmp_path / "sync_solo"),
        "sync.every_steps": 2,
    }))
    t_sync.fit()
    for name in t_off.state.tables:
        a = np.asarray(t_off.state.tables[name])
        b = np.asarray(t_sync.state.tables[name])
        assert a.tobytes() == b.tobytes(), f"table {name} diverged"


# ----------------------------------------------------------- CI smoke gate
def test_smoke_multislice_script(tmp_path):
    """The multi-slice CI gate end to end: one-slice baseline, lockstep
    parity run, bounded-staleness throughput run, kill-one-slice drill
    with rejoin + exact accounting, --check/--health green, and the
    MULTICHIP_r06.json record folded through perf_ledger --regress
    (tools/smoke_multislice.sh; the acceptance criterion's drill)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_multislice.sh"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=570, env=env,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "smoke_multislice: OK" in r.stdout
    rec = json.load(open(tmp_path / "MULTICHIP_r06.json"))
    assert rec["ok"] and rec["slices"] == 2
    assert rec["auc_gap"] <= 0.01


# ------------------------------------------------- parity sweep (K matrix)
@pytest.mark.slow
@pytest.mark.parametrize("k", [1, 8, 64])
def test_parity_k_sweep(tmp_path, k):
    """2-slice AUC at K in {1, 8, 64} (bounded, proceed-on-stale) lands
    within the parity tolerance of the K=0 lockstep run — staleness
    trades synchrony for throughput, not model quality
    (docs/DISTRIBUTED.md sweep table)."""
    for s, seed in (("0", 0), ("1", 1)):
        generate_shards(
            str(tmp_path / f"tr_s{s}"), 1, 3200, num_fields=5,
            ids_per_field=30, seed=seed, truth_seed=0,
        )
    generate_shards(
        str(tmp_path / "te"), 1, 800, num_fields=5, ids_per_field=30,
        seed=9, truth_seed=0,
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"

    def launch(tag, *sync_sets):
        r = subprocess.run(
            [sys.executable, "-m", "xflow_tpu", "launch-multislice",
             "--slices", "2", "--run-dir", str(tmp_path / f"run_{tag}"),
             "--",
             "--train", str(tmp_path / "tr_s{slice}"),
             "--test", str(tmp_path / "te"),
             "--model", "lr", "--optimizer", "sgd",
             "--epochs", "1", "--batch-size", "64", "--log2-slots", "12",
             "--set", "model.num_fields=5", "--set", "data.max_nnz=8",
             "--set", "train.pred_dump=false",
             "--set", "sync.every_steps=10",
             "--set", f"sync.dir={tmp_path / f'run_{tag}' / 'sync'}",
             *sync_sets],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert r.returncode == 0, f"{tag}: {r.stdout}\n{r.stderr}"
        aucs = [json.loads(l)["auc"] for l in r.stdout.splitlines()
                if l.strip().startswith("{") and "auc" in l]
        assert len(aucs) == 2, f"{tag}: missing slice summaries"
        return aucs

    base = launch("k0", "--set", "sync.mode=sync")
    assert base[0] == base[1], "K=0 slices must merge to one model"
    aucs = launch(
        f"k{k}", "--set", "sync.mode=bounded",
        "--set", f"sync.staleness_k={k}", "--set", "sync.on_stale=proceed",
    )
    for auc in aucs:
        assert abs(auc - base[0]) <= 0.01, (
            f"K={k} auc {auc} vs lockstep {base[0]}"
        )
