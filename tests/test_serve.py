"""Serving subsystem tests (xflow_tpu/serve, docs/SERVING.md).

Socket-free core first — the coalescer's flush rules, padding, the
hot-reload swap under concurrent requests, malformed-request rejection
— then the HTTP layer on a real loopback socket, serve/eval prediction
parity (the no-drift pin for models/predict.py), the kind="serve"
telemetry schema through metrics_report, and the CI smoke gate
(tools/smoke_serve.sh: loadgen + hot reload mid-flight).
"""

import json
import os
import shutil
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from xflow_tpu.config import Config, override
from xflow_tpu.serve.coalescer import (
    MicroBatcher,
    PendingRequest,
    RejectedRequest,
    assemble_batch,
)
from xflow_tpu.serve.runner import BadRequest, ServeRunner, parse_rows

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- coalescer
class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _rows(n, nnz=3):
    fields = [np.arange(nnz, dtype=np.int32) for _ in range(n)]
    slots = [np.full(nnz, 7, dtype=np.int32) for _ in range(n)]
    return fields, slots


def test_coalescer_size_flush_before_window():
    clock = FakeClock()
    mb = MicroBatcher(max_rows=4, window_s=100.0, clock=clock)
    futs = [mb.submit(*_rows(2)) for _ in range(2)]
    # 4 rows queued = max_rows: take returns NOW despite the huge window
    group = mb.take(timeout=0.0)
    assert group is not None and sum(r.num_rows for r in group) == 4
    assert all(not f.done() for f in futs)  # resolution is the worker's job


def test_coalescer_deadline_flush():
    clock = FakeClock()
    mb = MicroBatcher(max_rows=100, window_s=5.0, clock=clock)
    mb.submit(*_rows(1))
    assert mb.take(timeout=0.0) is None  # window not expired, no flush
    clock.t = 5.1
    group = mb.take(timeout=0.0)
    assert group is not None and len(group) == 1


def test_coalescer_whole_request_boundary():
    clock = FakeClock()
    mb = MicroBatcher(max_rows=4, window_s=0.0, clock=clock)
    mb.submit(*_rows(3))
    mb.submit(*_rows(3))
    g1 = mb.take(timeout=0.0)
    # 3 + 3 > 4: the second request must NOT split across batches
    assert [r.num_rows for r in g1] == [3]
    g2 = mb.take(timeout=0.0)
    assert [r.num_rows for r in g2] == [3]


def test_coalescer_rejects_oversized_and_backlog():
    mb = MicroBatcher(max_rows=4, window_s=0.0, max_queue_rows=6)
    with pytest.raises(RejectedRequest, match="max_batch"):
        mb.submit(*_rows(5))
    with pytest.raises(RejectedRequest, match="no rows"):
        mb.submit([], [])
    mb.submit(*_rows(4))
    mb.submit(*_rows(2))
    with pytest.raises(RejectedRequest, match="queue full"):
        mb.submit(*_rows(1))


def test_coalescer_close_drains_then_none():
    clock = FakeClock()
    mb = MicroBatcher(max_rows=8, window_s=100.0, clock=clock)
    mb.submit(*_rows(2))
    mb.close()
    with pytest.raises(RejectedRequest):
        mb.submit(*_rows(1))
    assert len(mb.take(timeout=0.0)) == 1  # backlog drains on close
    assert mb.take(timeout=0.0) is None  # then the worker's exit signal


def test_assemble_batch_padding_and_truncation():
    r1 = PendingRequest(
        fields=[np.asarray([1, 2], np.int32)], slots=[np.asarray([10, 20], np.int32)]
    )
    long = np.arange(9, dtype=np.int32)
    r2 = PendingRequest(fields=[long], slots=[long + 100])
    arrays, spans = assemble_batch([r1, r2], batch_size=4, max_nnz=4)
    assert arrays["slots"].shape == (4, 4)
    np.testing.assert_array_equal(arrays["slots"][0], [10, 20, 0, 0])
    np.testing.assert_array_equal(arrays["mask"][0], [1, 1, 0, 0])
    # truncation: a 9-feature row keeps its deterministic 4-prefix
    np.testing.assert_array_equal(arrays["slots"][1], [100, 101, 102, 103])
    np.testing.assert_array_equal(arrays["row_mask"], [1, 1, 0, 0])
    assert arrays["mask"][2:].sum() == 0  # ragged tail fully masked
    assert [(lo, hi) for _, lo, hi in spans] == [(0, 1), (1, 2)]


# ------------------------------------------------------------ row parsing
def test_parse_rows_label_optional_and_hash_parity():
    from xflow_tpu.data.libffm import parse_line

    cfg = Config()
    fr, sr = parse_rows(["0:tok1 1:tok2", "1\t0:tok1 1:tok2"], cfg.data)
    # a features-only row and a labeled libffm line parse identically
    np.testing.assert_array_equal(sr[0], sr[1])
    # and land in the training parser's slots exactly
    _, _, train_slots = parse_line(
        "1\t0:tok1 1:tok2", cfg.data.log2_slots, cfg.data.hash_salt
    )
    np.testing.assert_array_equal(sr[0], train_slots)


def test_parse_rows_rejects_malformed():
    cfg = Config()
    with pytest.raises(BadRequest, match="no parseable"):
        parse_rows(["nothing here"], cfg.data)
    with pytest.raises(BadRequest, match="expected a string"):
        parse_rows([42], cfg.data)
    with pytest.raises(BadRequest):
        parse_rows([""], cfg.data)


# ------------------------------------------------------------- fixtures
def _serve_cfg(ckpt_dir, **extra):
    base = {
        "data.batch_size": 64,
        "data.log2_slots": 12,
        "data.max_nnz": 8,
        "model.num_fields": 5,
        "model.name": "lr",
        "train.pred_dump": False,
        "train.checkpoint_dir": str(ckpt_dir),
        "serve.window_ms": 1.0,
        "serve.max_batch": 32,
        "serve.metrics_every_s": 0.2,
    }
    base.update(extra)
    return override(Config(), **base)


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    """One trained run with committed checkpoints at steps 4..16 and an
    eval pred dump from the final state — shared by the parity, reload,
    and HTTP tests (training it once keeps the module fast)."""
    from xflow_tpu.data.synth import generate_shards
    from xflow_tpu.train.trainer import Trainer

    work = tmp_path_factory.mktemp("serve_fixture")
    generate_shards(
        str(work / "train"), 1, 512, num_fields=5, ids_per_field=30, seed=0
    )
    cfg = _serve_cfg(
        work / "ck",
        **{"data.train_path": str(work / "train"), "train.epochs": 2,
           "train.checkpoint_every": 4},
    )
    t = Trainer(cfg)
    res = t.fit()
    assert res.steps == 16
    cwd = os.getcwd()
    os.chdir(work)
    try:
        t.evaluate(test_path=str(work / "train-00000"), dump=True, block=0)
    finally:
        os.chdir(cwd)
    rows = [
        line.split("\t", 1)[1].strip()
        for line in open(work / "train-00000").read().splitlines()[:96]
    ]
    preds = [
        float(line.split("\t")[0])
        for line in open(work / "pred_0_0.txt").read().splitlines()[:96]
    ]
    return {"work": work, "rows": rows, "preds": preds}


# ------------------------------------------------- parity (the drift pin)
def test_serve_matches_evaluate_probabilities(trained):
    """The satellite pin: online serve output == offline evaluate()
    probabilities on the same rows (models/predict.py is the ONE
    forward both compile)."""
    cfg = _serve_cfg(trained["work"] / "ck")
    r = ServeRunner(cfg)
    gen = r.load()
    assert gen.step == 16
    p, _ = r.predict_rows(trained["rows"])
    np.testing.assert_allclose(
        p, np.asarray(trained["preds"], np.float32), atol=1e-5
    )


def test_mesh_serving_reshards_and_matches(trained):
    """Reshard-on-load for serving: the 1-process training checkpoint
    loads onto a multi-device serving mesh (tables pjit-sharded over
    all devices) and predicts the same probabilities."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("single-device jax build")
    from xflow_tpu.parallel.mesh import make_mesh

    cfg = _serve_cfg(trained["work"] / "ck")
    mesh = make_mesh(cfg)
    r = ServeRunner(cfg, mesh=mesh)
    r.load()
    # the serving tables really are sharded over the whole mesh
    sh = r.generation.tables["w"].sharding
    assert not sh.is_fully_replicated
    p, _ = r.predict_rows(trained["rows"])
    np.testing.assert_allclose(
        p, np.asarray(trained["preds"], np.float32), atol=1e-5
    )


# --------------------------------------------------------------- reload
def _stage_ckpt(src_ck, dst_ck, step):
    """Copy one committed step dir into the serving dir ATOMICALLY
    (payload lands under a temp name, one rename publishes it) — the
    contract a checkpoint-shipping pipeline must follow."""
    os.makedirs(dst_ck, exist_ok=True)
    tmp = os.path.join(dst_ck, f".staging_step_{step}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    shutil.copytree(os.path.join(src_ck, f"step_{step}"), tmp)
    os.replace(tmp, os.path.join(dst_ck, f"step_{step}"))


def test_hot_reload_swaps_without_dropping_requests(trained, tmp_path):
    """The tentpole invariant: a reload mid-traffic drops and blocks
    NOTHING; responses carry a monotone generation that flips to the
    new checkpoint step."""
    from xflow_tpu.serve.server import ServeApp

    src = trained["work"] / "ck"
    dst = tmp_path / "serving_ck"
    _stage_ckpt(src, dst, 4)
    cfg = _serve_cfg(dst)
    runner = ServeRunner(cfg)
    assert runner.load().step == 4
    app = ServeApp(cfg, runner)
    app.start()
    results = []
    errors = []
    stop = threading.Event()

    def client(i):
        body = json.dumps({"rows": [trained["rows"][i % 64]]}).encode()
        while not stop.is_set():
            status, payload = app.handle_predict(body)
            if status != 200:
                errors.append((status, payload))
                return
            results.append((time.perf_counter(), payload["generation"], payload["step"]))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 10.0
        while not any(g == 2 for _, g, _ in results):
            if time.monotonic() > deadline:
                break
            if runner.step == 4:
                _stage_ckpt(src, dst, 16)
                runner.maybe_reload()
            time.sleep(0.05)
        time.sleep(0.2)  # traffic on BOTH sides of the swap
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        app.close()
    assert not errors, errors[:3]
    gens = [g for _, g, _ in sorted(results)]
    assert set(gens) == {1, 2}, f"saw generations {set(gens)}"
    # monotone: once a client sees generation 2 nothing answers at 1
    flip = gens.index(2)
    assert all(g == 2 for g in gens[flip:])
    steps = {g: s for _, g, s in results}
    assert steps == {1: 4, 2: 16}


def test_bad_checkpoint_mid_reload_keeps_serving_old_generation(trained, tmp_path):
    """Failure-matrix row: a corrupt checkpoint committed mid-reload
    must keep the old generation serving (restore_any walks back; the
    runner refuses to regress to the step it already serves)."""
    src = trained["work"] / "ck"
    dst = tmp_path / "serving_ck"
    _stage_ckpt(src, dst, 16)
    cfg = _serve_cfg(dst)
    r = ServeRunner(cfg)
    assert r.load().step == 16
    # a torn/corrupt NEWER checkpoint, committed: garbage npz + marker
    bad = dst / "step_99"
    bad.mkdir()
    (bad / "state.npz").write_bytes(b"this is not an npz file")
    (bad / "COMMITTED").write_text("ok\n")
    assert r.maybe_reload() is None  # walk-back lands on step 16 = serving
    assert r.step == 16 and r.generation.gen == 1
    p, gen = r.predict_rows(trained["rows"][:4])
    assert gen.gen == 1 and p.shape == (4,)


def test_watcher_does_not_retry_a_permanently_bad_step(trained, tmp_path):
    """A corrupt newest step must fail ONCE per committed step, not
    once per poll — no disk-thrash loop, no reload_failed spam."""
    from xflow_tpu.serve.runner import CheckpointWatcher

    src = trained["work"] / "ck"
    dst = tmp_path / "serving_ck"
    _stage_ckpt(src, dst, 8)
    cfg = _serve_cfg(dst)
    r = ServeRunner(cfg)
    r.load()
    bad = dst / "step_99"
    bad.mkdir()
    (bad / "state.npz").write_bytes(b"garbage")
    (bad / "COMMITTED").write_text("ok\n")
    w = CheckpointWatcher(r, poll_s=0.02)
    w.start()
    try:
        time.sleep(0.6)  # ~30 polls
    finally:
        w.close()
    assert w.failures == 1, w.failures
    assert r.step == 8 and r.generation.gen == 1  # still serving


def test_watcher_reloads_on_newer_commit(trained, tmp_path):
    from xflow_tpu.serve.runner import CheckpointWatcher

    src = trained["work"] / "ck"
    dst = tmp_path / "serving_ck"
    _stage_ckpt(src, dst, 8)
    cfg = _serve_cfg(dst)
    r = ServeRunner(cfg)
    r.load()
    seen = []
    w = CheckpointWatcher(r, poll_s=0.05, on_reload=lambda g: seen.append(g.step))
    w.start()
    try:
        _stage_ckpt(src, dst, 12)
        deadline = time.monotonic() + 10
        while r.step != 12 and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        w.close()
    assert r.step == 12 and seen == [12] and w.reloads == 1


# ----------------------------------------------------------- HTTP layer
@pytest.fixture()
def http_app(trained):
    from xflow_tpu.serve.server import ServeApp, make_http_server

    cfg = _serve_cfg(trained["work"] / "ck")
    runner = ServeRunner(cfg)
    runner.load()
    app = ServeApp(cfg, runner)
    app.start()
    srv = make_http_server(app, "127.0.0.1", 0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield app, srv.server_address[1]
    srv.shutdown()
    srv.server_close()
    app.close()


def _post(port, body, path="/predict"):
    import http.client

    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    c.request("POST", path, body, {"Content-Type": "application/json"})
    resp = c.getresponse()
    payload = json.loads(resp.read())
    c.close()
    return resp.status, payload


def test_http_malformed_requests_400_server_survives(trained, http_app):
    app, port = http_app
    # each malformed shape -> 400 with a reason, never a crash
    assert _post(port, b"not json")[0] == 400
    assert _post(port, json.dumps({"rows": []}))[0] == 400
    assert _post(port, json.dumps({"nope": 1}))[0] == 400
    assert _post(port, json.dumps({"rows": ["tokens without any colon"]}))[0] == 400
    assert _post(port, json.dumps({"rows": [123]}))[0] == 400
    # oversized request: client error, not load shedding
    too_big = json.dumps({"rows": ["0:a"] * 33})
    assert _post(port, too_big)[0] == 400
    # the server is still serving after all of that
    status, payload = _post(port, json.dumps({"rows": trained["rows"][:2]}))
    assert status == 200
    assert len(payload["pctr"]) == 2 and payload["generation"] == 1
    np.testing.assert_allclose(
        payload["pctr"], trained["preds"][:2], atol=1e-5
    )
    # and counted the rejects in the serve telemetry
    from xflow_tpu.telemetry import default_registry

    assert default_registry().counter("serve.bad_requests").value >= 6


def test_http_healthz_and_stats(http_app):
    import http.client

    _, port = http_app
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    c.request("GET", "/healthz")
    h = json.loads(c.getresponse().read())
    assert h["ok"] and h["step"] == 16 and h["generation"] == 1
    c.request("GET", "/stats")
    s = json.loads(c.getresponse().read())
    assert "registry" in s
    c.request("GET", "/nope")
    assert c.getresponse().status == 404
    c.close()


def test_concurrent_http_requests_coalesce(trained, http_app):
    """N concurrent 1-row requests answer from FEWER device batches
    than requests — the microbatching win, visible in batch_fill."""
    app, port = http_app
    from xflow_tpu.telemetry import default_registry

    reg = default_registry()
    req0 = reg.counter("serve.requests").value
    bat0 = reg.counter("serve.batches").value
    import concurrent.futures as cf

    body = json.dumps({"rows": trained["rows"][:1]})
    with cf.ThreadPoolExecutor(16) as ex:
        statuses = list(ex.map(lambda _: _post(port, body)[0], range(48)))
    assert statuses == [200] * 48
    requests = reg.counter("serve.requests").value - req0
    batches = reg.counter("serve.batches").value - bat0
    assert requests == 48
    assert batches < requests, (batches, requests)


# ------------------------------------------------------- serve telemetry
def test_serve_metrics_window_schema(tmp_path):
    from xflow_tpu.serve.metrics import SERVE_WINDOW_KEYS, ServeMetrics

    path = tmp_path / "serve.jsonl"
    m = ServeMetrics(str(path), every_s=60.0, batch_size=32)
    m.event("start", generation=1, step=4)
    m.observe_batch(2, 3, [0.001, 0.002], 0.004, [0.005, 0.006])
    m.observe_bad_request()
    rec = m.maybe_flush(1, 4, force=True)
    for k in SERVE_WINDOW_KEYS:
        assert k in rec, k
    assert rec["batch_fill"] == pytest.approx(3 / 32, abs=1e-4)
    m.event("reload", generation=2, step=8)
    m.close(2, 8)
    # the file passes the report tool's schema gate
    mr = _metrics_report()
    assert mr.main([str(path), "--check"]) == 0


def _metrics_report():
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import metrics_report as mr

    return mr


def _serve_rec(run_id="r1", rank=0, gen=0, ts=1.0, **kw):
    base = {"ts": ts, "rank": rank, "run_id": run_id, "gen": gen,
            "kind": "serve"}
    base.update(kw)
    return base


def _window(generation, step, ts=1.0, **kw):
    from xflow_tpu.serve.metrics import SERVE_WINDOW_KEYS

    rec = {k: 1 for k in SERVE_WINDOW_KEYS}
    rec.update(generation=generation, step=step)
    rec.update(kw)
    return _serve_rec(ts=ts, **rec)


def _write(tmp_path, name, recs):
    p = tmp_path / name
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(p)


def test_check_rejects_generation_regression(tmp_path):
    mr = _metrics_report()
    ok = _write(tmp_path, "ok.jsonl", [_window(1, 4, ts=1.0), _window(2, 8, ts=2.0)])
    assert mr.main([ok, "--check"]) == 0
    bad = _write(
        tmp_path, "bad.jsonl", [_window(2, 8, ts=1.0), _window(1, 8, ts=2.0)]
    )
    assert mr.main([bad, "--check"]) == 2


def test_check_rejects_partial_serve_window(tmp_path):
    mr = _metrics_report()
    rec = _window(1, 4)
    del rec["batch_fill"]
    assert mr.main([_write(tmp_path, "p.jsonl", [rec]), "--check"]) == 2
    # a record that is neither window nor event fails too
    stray = _serve_rec(other=1)
    assert mr.main([_write(tmp_path, "s.jsonl", [stray]), "--check"]) == 2


def test_serve_bench_record_and_table(tmp_path, capsys):
    mr = _metrics_report()
    path = _write(
        tmp_path,
        "serve.jsonl",
        [
            _serve_rec(event="start", generation=1, step=4),
            _window(1, 4, ts=1.0, requests=10, rows=20, qps=100.0,
                    window_s=0.1, total_p50_ms=2.0, total_p99_ms=9.0),
            _serve_rec(event="reload", generation=2, step=16, ts=1.5),
            _window(2, 16, ts=2.0, requests=30, rows=60, qps=300.0,
                    window_s=0.1, total_p50_ms=3.0, total_p99_ms=7.0),
        ],
    )
    assert mr.main([path]) == 0
    out = capsys.readouterr().out
    assert "serving (kind=serve):" in out
    streams, _ = mr.load_streams([path])
    rec = mr.serve_bench_record(streams)
    assert rec["metric"] == "serve_qps"
    assert rec["requests"] == 40 and rec["rows"] == 80
    # 40 requests over 0.2s of windows — computed from totals, not the
    # records' own qps fields
    assert rec["value"] == pytest.approx(200.0, rel=0.01)
    assert rec["reloads"] == 1 and rec["generations"] == [1, 2]
    assert rec["p99_ms"] == 9.0
    # --bench-json falls back to the serve record for serve-only dirs
    out_json = tmp_path / "B.json"
    assert mr.main([path, "--bench-json", str(out_json)]) == 0
    assert json.load(open(out_json))["metric"] == "serve_qps"


def test_serve_bench_record_time_weights_sequential_generations(tmp_path):
    """A restarted server's generations run SEQUENTIALLY: 100 qps in
    gen 0 then 100 qps in gen 1 is 100 qps, not 200 (concurrent RANKS
    still add)."""
    mr = _metrics_report()
    recs = [
        _window(1, 4, ts=1.0, gen=0, requests=10, window_s=0.1),
        _window(1, 4, ts=2.0, gen=1, requests=10, window_s=0.1),
        _window(1, 4, ts=1.0, gen=0, rank=1, requests=10, window_s=0.1),
    ]
    streams, _ = mr.load_streams([_write(tmp_path, "g.jsonl", recs)])
    rec = mr.serve_bench_record(streams)
    # rank 0: 20 reqs over 0.2s = 100 qps; rank 1 (concurrent): +100
    assert rec["value"] == pytest.approx(200.0, rel=0.01)
    assert rec["requests"] == 30


def test_summarize_serve_stream_aggregates():
    mr = _metrics_report()
    recs = [
        _window(1, 4, requests=10, rows=20, qps=100.0, window_s=0.1,
                batches=5, batch_fill=0.5, bad_requests=1),
        _serve_rec(event="reload_failed"),
        _window(1, 4, requests=10, rows=40, qps=100.0, window_s=0.1,
                batches=5, batch_fill=1.0, bad_requests=0),
    ]
    s = mr.summarize_serve_stream(recs)
    assert s["requests"] == 20 and s["rows"] == 60 and s["windows"] == 2
    assert s["qps"] == pytest.approx(100.0)
    assert s["batch_fill"] == pytest.approx(0.75)
    assert s["bad_requests"] == 1 and s["reload_failures"] == 1


# -------------------------------------------------------------------- CLI
def test_cli_serve_requires_and_validates_checkpoint(tmp_path):
    from xflow_tpu.launch.cli import main as cli_main

    # no checkpoints under the dir: clean failure, not a traceback
    rc = cli_main(["serve", "--checkpoint-dir", str(tmp_path / "empty")])
    assert rc == 1


# ----------------------------------------------------------- CI smoke gate
def test_smoke_serve_script(tmp_path):
    """The serving CI gate end to end (tools/smoke_serve.sh): train ->
    serve -> loadgen -> hot reload mid-load (generation flip, zero
    failed requests) -> serve/eval parity -> metrics_report --check ->
    BENCH_SERVE.json."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "smoke_serve.sh"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=570, env=env,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "smoke_serve: OK" in r.stdout
    assert "hot reload OK" in r.stdout
    assert "parity OK" in r.stdout
    bench = json.load(open(tmp_path / "BENCH_SERVE.json"))
    assert bench["metric"] == "serve_qps" and bench["value"] > 0
    assert bench["errors"] == 0 and bench["gen_flips"] >= 1
